"""Query parser for the document index (tantivy query-language subset).

Reference: contrib/tantivy-search's QueryParser, reached through
src/document/document_index.h SearchWithQuery. Supported syntax:

    hello world              bare terms (OR by default)
    +must -not               required / excluded terms
    "exact phrase"           phrase (consecutive positions)
    title:hello              term restricted to one text field
    price:[10 TO 20]         inclusive numeric/bytes range
    price:{10 TO 20}         exclusive range ([ / { mix freely per end)
    price:[10 TO *]          open-ended range
    flag:true                bool column equality
    AND                      switch default conjunction to AND

Produces a ParsedQuery of text terms, phrases, and typed ColumnPredicates
that DocumentIndex.search_query evaluates.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, List, Optional, Tuple

from dingo_tpu.document.index import tokenize


class QueryParseError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class ColumnPredicate:
    """Typed column constraint. op: eq | range (lo/hi, each optional);
    negate inverts the match (the parser's -field:... form)."""

    field: str
    op: str
    value: Any = None
    lo: Any = None
    hi: Any = None
    incl_lo: bool = True
    incl_hi: bool = True
    negate: bool = False

    def matches(self, doc: dict) -> bool:
        hit = self._matches_positive(doc)
        return not hit if self.negate else hit

    def _matches_positive(self, doc: dict) -> bool:
        v = doc.get(self.field)
        if v is None:
            return False
        try:
            if self.op == "eq":
                return v == self.value
            if self.lo is not None:
                if v < self.lo or (not self.incl_lo and v == self.lo):
                    return False
            if self.hi is not None:
                if v > self.hi or (not self.incl_hi and v == self.hi):
                    return False
            return True
        except TypeError:
            return False


@dataclasses.dataclass
class ParsedQuery:
    terms: List[str] = dataclasses.field(default_factory=list)
    required: List[str] = dataclasses.field(default_factory=list)
    excluded: List[str] = dataclasses.field(default_factory=list)
    phrases: List[List[str]] = dataclasses.field(default_factory=list)
    #: -"..." phrases: docs containing them are dropped
    neg_phrases: List[List[str]] = dataclasses.field(default_factory=list)
    #: (field, term) pairs — term must appear in that text field
    field_terms: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)
    predicates: List[ColumnPredicate] = dataclasses.field(
        default_factory=list)
    mode: str = "or"


_TOKEN_SPLIT = re.compile(
    r'[+-]?"[^"]*"'                 # quoted phrase (optionally signed)
    r"|[+-]?\w+:[\[{][^\]}]*[\]}]"  # field:[lo TO hi] (spans spaces)
    r"|\S+"                         # everything else
)
_RANGE = re.compile(
    r"^(?P<open>[\[{])\s*(?P<lo>[^ ]+)\s+TO\s+(?P<hi>[^ ]+)\s*"
    r"(?P<close>[\]}])$"
)


def _coerce(raw: str, schema_type: Optional[str]) -> Any:
    """Typed literal per the column's schema (i64/f64/bytes/bool/text)."""
    if raw == "*":
        return None
    if schema_type == "i64":
        try:
            return int(raw)
        except ValueError as e:
            raise QueryParseError(f"bad i64 literal {raw!r}") from e
    if schema_type == "f64":
        try:
            return float(raw)
        except ValueError as e:
            raise QueryParseError(f"bad f64 literal {raw!r}") from e
    if schema_type == "bool":
        if raw.lower() in ("true", "1"):
            return True
        if raw.lower() in ("false", "0"):
            return False
        raise QueryParseError(f"bad bool literal {raw!r}")
    if schema_type == "bytes":
        return raw.encode()
    # untyped / text: best-effort numeric, else string
    try:
        return int(raw)
    except ValueError:
        try:
            return float(raw)
        except ValueError:
            return raw


def parse_query(query: str, schema: Optional[dict] = None) -> ParsedQuery:
    """schema: column name -> type string ("text"/"i64"/"f64"/"bytes"/
    "bool"); None = schemaless (numeric literals coerced best-effort)."""
    out = ParsedQuery()
    schema = schema or {}
    for m in _TOKEN_SPLIT.finditer(query):
        tok = m.group(0)
        if tok == "AND":
            out.mode = "and"
            continue
        if tok == "OR":
            out.mode = "or"
            continue
        sign = ""
        if tok[:1] in "+-" and len(tok) > 1:
            sign, tok = tok[0], tok[1:]
        if tok.startswith('"') and tok.endswith('"') and len(tok) >= 2:
            words = tokenize(tok[1:-1])
            if words:
                if sign == "-":
                    out.neg_phrases.append(words)
                else:
                    out.phrases.append(words)
                    out.terms.extend(words)
            continue
        if ":" in tok:
            field, _, rest = tok.partition(":")
            ftype = schema.get(field)
            rm = _RANGE.match(rest)
            if rm:
                lo = _coerce(rm.group("lo"), ftype)
                hi = _coerce(rm.group("hi"), ftype)
                out.predicates.append(ColumnPredicate(
                    field=field, op="range", lo=lo, hi=hi,
                    incl_lo=rm.group("open") == "[",
                    incl_hi=rm.group("close") == "]",
                    negate=sign == "-",
                ))
                continue
            if ftype in ("i64", "f64", "bytes", "bool"):
                out.predicates.append(ColumnPredicate(
                    field=field, op="eq", value=_coerce(rest, ftype),
                    negate=sign == "-"))
                continue
            # text field restriction
            for w in tokenize(rest):
                out.field_terms.append((field, w))
                out.terms.append(w)
            continue
        for w in tokenize(tok):
            if sign == "+":
                out.required.append(w)
            elif sign == "-":
                out.excluded.append(w)
                continue
            out.terms.append(w)
    # required terms also score
    for w in out.required:
        if w not in out.terms:
            out.terms.append(w)
    return out
