"""Cache key derivation: fingerprints binding a query row to exactly the
device state and resolved parameters that would answer it.

A result-cache entry is correct to serve iff a fresh dispatch would
return byte-identical rows. Three things determine that reply on the
plain search path:

- the raw query bytes (the kernel input),
- the resolved search parameters (topn, nprobe/ef, metric-relevant
  kwargs — the same canonicalized scalar items the coalescer keys on),
- the device state, summarized losslessly for this purpose by
  ``SlotStore.mutation_version`` (index/slot_store.py): every put /
  remove / growth bumps it, and every [capacity]-shaped cached artifact
  in the repo already keys on it (HNSW filter masks, the adjacency
  mirror). FilterSpec-bearing searches additionally fold the filter
  fingerprint — the plain path serves filter-free, so the empty
  fingerprint is the common case.

Fingerprints ride the PR 11 ``ops/digest.py`` row-fingerprint primitive
(odd-coefficient byte projection xor splitmix64), the same machinery the
state-integrity plane trusts for corruption detection — collisions are
the 2^-64 class of risk already accepted there.

The semantic tier quantizes the query with the PR 4 sq8 codec first
(per-region params trained lazily on observed queries), so near-identical
queries that round to the same uint8 codes share a fingerprint. Exact and
semantic namespaces are disjoint by tag.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from dingo_tpu.ops.digest import row_fingerprints, splitmix64, tag_seed
from dingo_tpu.ops.sq import SqParams, sq_encode, sq_train

#: rows of observed queries the lazy per-region semantic codec trains on
SEMANTIC_TRAIN_ROWS = 256


def params_seed(topn: int, kw_items: Tuple, filter_fp: bytes = b"") -> np.uint64:
    """One uint64 summarizing the resolved search parameters + filter.

    `kw_items` is the coalescer key's canonical scalar-kwarg tuple
    (sorted (name, value) pairs) — parameter-identical searches, and only
    those, share a seed. The filter fingerprint (FilterSpec.fingerprint,
    blake2b-16) folds in as hex; the plain path passes b""."""
    return tag_seed(
        f"cache.params|{int(topn)}|{kw_items!r}|{filter_fp.hex()}"
    )


def query_fingerprints(queries: np.ndarray, seed: np.uint64) -> np.ndarray:
    """[n] uint64 fingerprints over raw query-row bytes under `seed`.

    Rows digest over their canonical C-order float32 bytes — the exact
    bytes the kernel would scan — so the same VALUES always fingerprint
    identically regardless of upstream array layout."""
    q = np.ascontiguousarray(np.asarray(queries, np.float32))
    if q.ndim != 2:
        raise ValueError(f"query_fingerprints needs [n, d], got {q.shape}")
    fps = row_fingerprints(
        "cache.query", np.zeros(len(q), np.int64), q
    )
    return splitmix64(fps ^ np.uint64(seed))


def semantic_fingerprints(codes: np.ndarray, seed: np.uint64) -> np.ndarray:
    """[n] uint64 fingerprints over sq8 code rows — a distinct namespace
    from the exact tier (different tag), same seed binding."""
    c = np.ascontiguousarray(np.asarray(codes, np.uint8))
    fps = row_fingerprints(
        "cache.semantic", np.zeros(len(c), np.int64), c
    )
    return splitmix64(fps ^ np.uint64(seed))


class SemanticCodec:
    """Per-region sq8 quantizer for query rows, trained lazily.

    The first SEMANTIC_TRAIN_ROWS observed query rows accumulate on the
    host; once enough arrive, sq_train fits the per-dim affine codec and
    encode() starts answering. Until trained (or after reset) encode()
    returns None and the semantic tier simply doesn't serve — no
    approximate hit is ever minted from an unfitted codec."""

    def __init__(self):
        self._lock = threading.Lock()
        self._params: Dict[int, SqParams] = {}
        self._pending: Dict[int, list] = {}

    def observe(self, region_id: int, queries: np.ndarray) -> None:
        """Accumulate training rows until the codec fits."""
        with self._lock:
            if region_id in self._params:
                return
            buf = self._pending.setdefault(region_id, [])
            buf.append(np.array(queries, np.float32, copy=True))
            rows = sum(len(b) for b in buf)
            if rows < SEMANTIC_TRAIN_ROWS:
                return
            sample = np.concatenate(buf, axis=0)[:SEMANTIC_TRAIN_ROWS]
            self._params[region_id] = sq_train(sample)
            del self._pending[region_id]

    def encode(self, region_id: int,
               queries: np.ndarray) -> Optional[np.ndarray]:
        """uint8 codes [n, d], or None while the codec is untrained or
        the query dimension moved (region recreated at a new dim)."""
        with self._lock:
            params = self._params.get(region_id)
        if params is None:
            return None
        q = np.asarray(queries, np.float32)
        if q.ndim != 2 or q.shape[1] != params.dim:
            return None
        return sq_encode(q, params)

    def trained(self, region_id: int) -> bool:
        with self._lock:
            return region_id in self._params

    def forget_region(self, region_id: int) -> None:
        with self._lock:
            self._params.pop(region_id, None)
            self._pending.pop(region_id, None)

    def reset(self) -> None:
        with self._lock:
            self._params.clear()
            self._pending.clear()
