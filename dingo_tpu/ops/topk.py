"""k-selection kernels.

Replaces faiss's heap-based k-selection (per-query CPU heaps in
IndexFlat::search and the reference's brute-force merge of per-batch top-k
heaps, src/vector/vector_reader.cc:1873+) with lax.top_k over score rows,
plus a streaming/shard merge used both for scan-batched brute force and for
cross-device top-k reduction (per-device topk -> all-gather -> merge).

Masking contract: invalid slots (tombstones, filter-rejected ids, padding)
carry score -inf and id -1; merge and topk preserve that, so a fully-masked
row yields (distance=+inf-equivalent, id=-1) entries the host layer drops —
matching the reference's behavior of returning fewer than topN results when
the region has fewer candidates.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


def topk_scores(
    scores: jax.Array,
    k: int,
    valid: Optional[jax.Array] = None,
    ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k per row of a 'larger is better' score matrix.

    scores: [b, n]; valid: [n] or [b, n] bool mask; ids: [n] external ids.
    Returns (scores[b,k] desc, ids[b,k]) with -1 ids on masked-out picks.
    """
    b, n = scores.shape
    if valid is not None:
        scores = jnp.where(valid, scores, NEG_INF)
    if k > n:
        pad = jnp.full((b, k - n), NEG_INF, scores.dtype)
        scores = jnp.concatenate([scores, pad], axis=1)
        if ids is not None:
            ids = jnp.concatenate([ids, jnp.full((k - n,), -1, ids.dtype)])
        n = k
    vals, idx = jax.lax.top_k(scores, k)
    out_ids = idx if ids is None else jnp.take(ids, idx, axis=0)
    out_ids = jnp.where(jnp.isneginf(vals), -1, out_ids)
    return vals, out_ids


def begin_host_fetch(*arrays):
    """Start ONE D2H copy group for a reply's whole fetch tuple.

    The one-sync epilogue contract (serving pipeline): everything a
    resolve() needs on the host — distances, slots, prune stats,
    diagnostic counters — joins a single ``copy_to_host_async`` group
    here, and resolve performs exactly one ``jax.device_get`` on the
    returned tuple. None entries are dropped (optional members like the
    prune-stats block just don't join), so the caller indexes the
    result positionally over its non-None arguments. Host-side values
    (numpy fallbacks) pass through untouched."""
    out = []
    for a in arrays:
        if a is None:
            continue
        start = getattr(a, "copy_to_host_async", None)
        if start is not None:
            start()
        out.append(a)
    return tuple(out)


def merge_topk(
    scores_a: jax.Array,
    ids_a: jax.Array,
    scores_b: jax.Array,
    ids_b: jax.Array,
    k: int,
) -> Tuple[jax.Array, jax.Array]:
    """Merge two per-row top-k result sets into one (streaming scan batches,
    reference vector_reader.cc:1873 'merge per-query topk heaps'; also the
    cross-shard reduce step in parallel/)."""
    scores = jnp.concatenate([scores_a, scores_b], axis=1)
    ids = jnp.concatenate([ids_a, ids_b], axis=1)
    vals, idx = jax.lax.top_k(scores, k)
    out_ids = jnp.take_along_axis(ids, idx, axis=1)
    out_ids = jnp.where(jnp.isneginf(vals), -1, out_ids)
    return vals, out_ids


def merge_sharded_topk(
    shard_scores: jax.Array, shard_ids: jax.Array, k: int
) -> Tuple[jax.Array, jax.Array]:
    """[s, b, k'] per-shard results -> [b, k] global results.

    Used after an all_gather of per-device top-k blocks (the TPU analog of the
    reference's client-side scatter-gather across regions, SURVEY.md §5
    'long-context' note)."""
    s, b, kk = shard_scores.shape
    flat_scores = jnp.transpose(shard_scores, (1, 0, 2)).reshape(b, s * kk)
    flat_ids = jnp.transpose(shard_ids, (1, 0, 2)).reshape(b, s * kk)
    vals, idx = jax.lax.top_k(flat_scores, k)
    out_ids = jnp.take_along_axis(flat_ids, idx, axis=1)
    out_ids = jnp.where(jnp.isneginf(vals), -1, out_ids)
    return vals, out_ids
