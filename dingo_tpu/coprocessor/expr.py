"""Expression VM for pushdown predicates (coprocessor v2).

Reference: src/coprocessor/coprocessor_v2.{h,cc} runs rel-expression
bytecode from the dingo-libexpr submodule (rel::RelRunner,
coprocessor_v2.cc:209-216). This is an original expression evaluator over
the same role: a wire-encodable expression tree evaluated against a row's
field map, with comparison, boolean, arithmetic, membership, mathematical/
string function, cast, and conditional operators.

Wire form: nested lists (JSON friendly) —
    ["and", ["ge", ["field", "age"], ["const", 21]],
            ["in", ["field", "color"], ["const", ["red", "blue"]]]]
    ["mul", ["field", "price"], ["cast", "DOUBLE", ["field", "qty"]]]
    ["if", ["is_null", ["field", "name"]], ["const", "?"],
           ["upper", ["field", "name"]]]
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Sequence

_BINOPS = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "mod": lambda a, b: a % b,
    # math.pow, not **: always a double (SQL POWER), and negative-base
    # fractional exponents raise ValueError (-> unknown) instead of the **
    # operator's complex fallback, which would escape the NULL machinery
    # and huge int exponents can't allocate billion-digit integers
    "pow": lambda a, b: math.pow(_num(a), _num(b)),
    "in": lambda a, b: a in b,
    "concat": lambda a, b: _str(a) + _str(b),
}


def _str(v) -> str:
    if not isinstance(v, str):
        raise TypeError(f"expected string, got {type(v).__name__}")
    return v


def _num(v):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise TypeError(f"expected number, got {type(v).__name__}")
    return v


# Unary function library (libexpr op set: mathematical/string functions run
# inside rel-expression bytecode, src/coprocessor/coprocessor_v2.cc:209-216).
_UNOPS = {
    "neg": lambda a: -_num(a),
    "abs": lambda a: abs(_num(a)),
    "floor": lambda a: math.floor(_num(a)),
    "ceil": lambda a: math.ceil(_num(a)),
    "sqrt": lambda a: math.sqrt(_num(a)),
    "exp": lambda a: math.exp(_num(a)),
    "ln": lambda a: math.log(_num(a)),
    "lower": lambda a: _str(a).lower(),
    "upper": lambda a: _str(a).upper(),
    "length": lambda a: len(_str(a)),
}

def _cast_bool(v):
    # SQL CAST semantics for strings ('false' is false), not Python
    # truthiness (where any non-empty string would be true)
    if isinstance(v, str):
        s = v.strip().lower()
        if s in ("true", "1"):
            return True
        if s in ("false", "0"):
            return False
        raise TypeError(f"cannot cast {v!r} to BOOL")
    return bool(v)


# Cast targets mirror the serial/SQL type names used by SchemaColumn.
# bytes -> VARCHAR decodes utf-8 (UnicodeDecodeError is a ValueError ->
# unknown), never Python repr.
_CASTS = {
    "BIGINT": lambda v: int(v),
    "DOUBLE": lambda v: float(v),
    "VARCHAR": lambda v: v if isinstance(v, str) else v.decode("utf-8")
    if isinstance(v, bytes) else str(v),
    "BOOL": _cast_bool,
}


class ExprError(ValueError):
    pass


class Expr:
    """Compiled expression (validates shape once; eval per row)."""

    def __init__(self, tree: Sequence):
        self._tree = self._validate(tree)

    @classmethod
    def _validate(cls, node) -> List:
        if not isinstance(node, (list, tuple)) or not node:
            raise ExprError(f"bad expr node {node!r}")
        op = node[0]
        if op == "const":
            if len(node) != 2:
                raise ExprError("const takes 1 arg")
            return ["const", node[1]]
        if op == "field":
            if len(node) != 2 or not isinstance(node[1], str):
                raise ExprError("field takes a name")
            return ["field", node[1]]
        if op == "not":
            if len(node) != 2:
                raise ExprError("not takes 1 arg")
            return ["not", cls._validate(node[1])]
        if op in ("and", "or"):
            if len(node) < 3:
                raise ExprError(f"{op} takes >=2 args")
            return [op] + [cls._validate(a) for a in node[1:]]
        if op == "is_null":
            if len(node) != 2:
                raise ExprError("is_null takes 1 arg")
            return ["is_null", cls._validate(node[1])]
        if op == "if":
            if len(node) != 4:
                raise ExprError("if takes cond/then/else")
            return ["if"] + [cls._validate(a) for a in node[1:]]
        if op == "cast":
            if len(node) != 3 or node[1] not in _CASTS:
                raise ExprError(
                    f"cast takes a type in {sorted(_CASTS)} and 1 arg"
                )
            return ["cast", node[1], cls._validate(node[2])]
        if op == "substr":
            # ["substr", s, start, len] — 0-based start, clamped like SQL
            if len(node) != 4:
                raise ExprError("substr takes string/start/len")
            return ["substr"] + [cls._validate(a) for a in node[1:]]
        if op in _UNOPS:
            if len(node) != 2:
                raise ExprError(f"{op} takes 1 arg")
            return [op, cls._validate(node[1])]
        if op in _BINOPS:
            if len(node) != 3:
                raise ExprError(f"{op} takes 2 args")
            return [op, cls._validate(node[1]), cls._validate(node[2])]
        raise ExprError(f"unknown op {op!r}")

    def eval(self, row: Dict[str, Any]) -> Any:
        return self._eval(self._tree, row)

    def matches(self, row: Dict[str, Any]) -> bool:
        try:
            return bool(self.eval(row))
        except _UNKNOWN:
            return False   # SQL unknown (type/domain error) filters the row

    def eval_or_null(self, row: Dict[str, Any]) -> Any:
        """Projection semantics: an unknown-valued expression yields NULL."""
        try:
            return self.eval(row)
        except _UNKNOWN:
            return None

    @classmethod
    def _eval(cls, node: List, row: Dict[str, Any]) -> Any:
        op = node[0]
        if op == "const":
            return node[1]
        if op == "field":
            return row.get(node[1])
        if op == "not":
            v = cls._bool3(node[1], row)
            if v is None:
                raise TypeError("unknown operand")
            return not v
        if op == "and":
            # Kleene three-valued AND: false dominates unknown
            unknown = False
            for a in node[1:]:
                v = cls._bool3(a, row)
                if v is None:
                    unknown = True
                elif not v:
                    return False
            if unknown:
                raise TypeError("unknown operand")
            return True
        if op == "or":
            # Kleene three-valued OR: true dominates unknown
            unknown = False
            for a in node[1:]:
                v = cls._bool3(a, row)
                if v is None:
                    unknown = True
                elif v:
                    return True
            if unknown:
                raise TypeError("unknown operand")
            return False
        if op == "is_null":
            return cls._eval(node[1], row) is None
        if op == "if":
            # SQL CASE: an unknown condition (NULL operand, type mismatch,
            # domain error inside the predicate) selects the ELSE branch
            try:
                cond = cls._eval(node[1], row)
            except _UNKNOWN:
                cond = None
            return cls._eval(node[2] if cond else node[3], row)
        if op == "cast":
            v = cls._eval(node[2], row)
            if v is None:
                raise TypeError("null operand")
            return _CASTS[node[1]](v)
        if op == "substr":
            s = _str(cls._require(node[1], row))
            start = _num(cls._require(node[2], row))
            ln = _num(cls._require(node[3], row))
            if isinstance(start, float) or isinstance(ln, float):
                raise TypeError("substr bounds must be integers")
            start, ln = max(0, start), max(0, ln)
            return s[start:start + ln]
        if op in _UNOPS:
            return _UNOPS[op](cls._require(node[1], row))
        a = cls._require(node[1], row)
        b = cls._require(node[2], row)
        return _BINOPS[op](a, b)

    @classmethod
    def _require(cls, node: List, row: Dict[str, Any]) -> Any:
        v = cls._eval(node, row)
        if v is None:
            raise TypeError("null operand")
        return v

    @classmethod
    def _bool3(cls, node: List, row: Dict[str, Any]):
        """Three-valued truth of a subexpression: True/False, or None when
        the value is NULL or its evaluation errored (SQL unknown)."""
        try:
            v = cls._eval(node, row)
        except _UNKNOWN:
            return None
        return None if v is None else bool(v)


# Errors that make an expression's value "unknown" in SQL terms: type
# mismatches, division by zero, math domain errors (sqrt(-1), ln(0)),
# overflow (exp(1e6)), and bad casts (int("x")).
_UNKNOWN = (TypeError, ArithmeticError, ValueError)


class ExprFilter:
    """ScalarFilter-compatible adapter so the VectorReader's TABLE filter
    mode and scans can take full expressions."""

    def __init__(self, tree: Sequence):
        self.expr = Expr(tree)

    def matches(self, scalar: Dict[str, Any]) -> bool:
        return self.expr.matches(scalar)

    def is_empty(self) -> bool:
        return False
