"""Quality observability plane + SLO tuner (ISSUE 9).

Covers: estimator correctness vs brute force on seeded data; the
sampling-off path dispatching ZERO shadow scans while leaving served
results byte-identical (spy on the shadow kernel); CI width shrinking
with evidence; tuner monotone stepping / ladder bounds / stale-metrics
no-op via fake control; the heartbeat pb round-trip of the quality
fields; and the recompile-sentinel invariant across tuner steps.
"""

import json
import time

import numpy as np
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.index import IndexParameter, IndexType, new_index
from dingo_tpu.obs.quality import (
    QUALITY,
    WindowedEstimator,
    rank_biased_overlap,
    recall_hits,
    score_gap,
    wilson_interval,
)
from dingo_tpu.obs.tuner import (
    RERANK_LADDER,
    SloTuner,
    ladder_step,
    ladder_values,
)


@pytest.fixture(autouse=True)
def _quality_env():
    """Sampling off by default; every test that turns it on gets a clean
    plane and restored flags afterwards."""
    old_rate = FLAGS.get("quality_sample_rate")
    old_win = FLAGS.get("quality_window_s")
    FLAGS.set("quality_window_s", 3600.0)
    yield
    FLAGS.set("quality_sample_rate", old_rate)
    FLAGS.set("quality_window_s", old_win)
    QUALITY.clear()


def _corpus(n=2000, d=32, seed=3, noise=2.0, nq=8):
    rng = np.random.default_rng(seed)
    ncl = 16
    centers = rng.standard_normal((ncl, d), dtype=np.float32)
    x = centers[rng.integers(0, ncl, n)] + noise * rng.standard_normal(
        (n, d)
    ).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    queries = x[rng.choice(n, nq, replace=False)] + 0.3 * (
        rng.standard_normal((nq, d)).astype(np.float32)
    )
    return ids, x, queries


def _exact_gt(x, ids, queries, k):
    dmat = (
        (queries ** 2).sum(1)[:, None] - 2.0 * queries @ x.T
        + (x ** 2).sum(1)[None, :]
    )
    return ids[np.argsort(dmat, axis=1)[:, :k]]


def _recall(res, gt, k):
    return float(np.mean(
        [len(set(r.ids) & set(g)) / k for r, g in zip(res, gt)]
    ))


def _ivf(region_id, d=32, nlist=16, nprobe=2, precision=""):
    return new_index(region_id, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=d, ncentroids=nlist,
        default_nprobe=nprobe, precision=precision,
    ))


def _fake_estimate(recall, half=0.01, queries=100, trials=1000, age_s=0.0):
    return {
        "recall": recall,
        "ci_low": max(0.0, recall - half),
        "ci_high": min(1.0, recall + half),
        "queries": queries,
        "trials": trials,
        "newest_ts": time.time() - age_s,
        "oldest_ts": time.time() - age_s - 1.0,
    }


# ---------------------------------------------------------------------------
# scoring math units
# ---------------------------------------------------------------------------

def test_recall_hits_ignores_padding():
    served = np.asarray([1, 2, 3, -1, -1])
    gt = np.asarray([2, 3, 9, -1, -1])
    assert recall_hits(served, gt) == (2, 3)
    assert recall_hits(np.asarray([-1]), np.asarray([-1])) == (0, 0)


def test_rbo_order_sensitivity():
    a = np.arange(10)
    assert rank_biased_overlap(a, a) == pytest.approx(1.0)
    assert rank_biased_overlap(a, a + 100) == pytest.approx(0.0)
    # same SET, reversed order: overlap penalized but nonzero
    r = rank_biased_overlap(a, a[::-1])
    assert 0.0 < r < 1.0
    # a prefix-correct list beats a suffix-correct one (top-weighted)
    half_front = np.concatenate([a[:5], a[:5] + 100])
    half_back = np.concatenate([a[5:] + 100, a[5:]])
    assert rank_biased_overlap(half_front, a) > rank_biased_overlap(
        half_back, a)


def test_score_gap_relative_regret():
    gt = np.asarray([0.5, 0.8, 1.0], np.float32)
    served = np.asarray([0.5, 0.9, 1.2], np.float32)
    assert score_gap(served, gt, ascending=True) == pytest.approx(0.2)
    assert score_gap(gt, gt, ascending=True) == 0.0
    # descending (IP): a SMALLER served k-th score is the regret
    assert score_gap(
        np.asarray([0.9], np.float32), np.asarray([1.0], np.float32),
        ascending=False,
    ) == pytest.approx(0.1)


def test_wilson_ci_width_shrinks_with_samples():
    lo1, hi1 = wilson_interval(95, 100)
    lo2, hi2 = wilson_interval(950, 1000)
    assert hi1 - lo1 > hi2 - lo2
    assert lo1 < 0.95 < hi1 and lo2 < 0.95 < hi2
    # p = 1.0 keeps a nonzero-width interval (the SLO regime)
    lo, hi = wilson_interval(100, 100)
    assert hi == 1.0 and 0.9 < lo < 1.0


def test_estimator_windowing_and_reset():
    est = WindowedEstimator()
    est.add(8, 70, 80, 7.5, [0.1, 0.2])
    st = est.stats()
    assert st["recall"] == pytest.approx(70 / 80)
    assert st["queries"] == 8 and st["trials"] == 80
    assert st["ci_low"] < st["recall"] < st["ci_high"]
    est.reset()
    assert est.stats() is None
    # aged-out entries leave the window (read-time pruning)
    FLAGS.set("quality_window_s", 0.05)
    est.add(4, 40, 40, 4.0, [])
    time.sleep(0.12)
    assert est.stats() is None


# ---------------------------------------------------------------------------
# live estimator vs brute force
# ---------------------------------------------------------------------------

def test_live_estimate_matches_brute_force():
    ids, x, queries = _corpus()
    k = 10
    gt = _exact_gt(x, ids, queries, k)
    idx = _ivf(9301)
    idx.store.reserve(len(ids))
    idx.upsert(ids, x)
    idx.train()
    FLAGS.set("quality_sample_rate", 1.0)
    res = idx.search(queries, k)
    assert QUALITY.flush()
    est = QUALITY.region_estimate(9301)
    offline = _recall(res, gt, k)
    assert est is not None and est["queries"] == len(queries)
    # the shadow oracle reads the same fp32 rows numpy scanned: the live
    # estimate IS the brute-force recall of the served result
    assert est["recall"] == pytest.approx(offline, abs=1e-6)
    assert est["ci_low"] <= est["recall"] <= est["ci_high"]
    # curated gauges published for the region rollup
    assert METRICS.gauge("quality.recall", 9301).get() == pytest.approx(
        offline, abs=1e-6)


def test_sampling_off_is_inert(monkeypatch):
    """quality.sample_rate = 0: zero shadow kernels dispatched, zero
    estimator state, and served results identical to a sampled run."""
    import dingo_tpu.ops.shadow as shadow_mod

    calls = {"n": 0}
    real = shadow_mod.shadow_exact_topk

    def spy(*args, **kw):
        calls["n"] += 1
        return real(*args, **kw)

    monkeypatch.setattr(shadow_mod, "shadow_exact_topk", spy)
    ids, x, queries = _corpus(n=1500)
    k = 10
    idx = _ivf(9302)
    idx.store.reserve(len(ids))
    idx.upsert(ids, x)
    idx.train()
    scans0 = METRICS.counter("quality.shadow_scans", 9302).get()
    res_off = idx.search(queries, k)
    res_off2 = idx.search(queries, k)
    QUALITY.flush()
    assert calls["n"] == 0
    assert METRICS.counter("quality.shadow_scans", 9302).get() == scans0
    assert QUALITY.region_estimate(9302) is None
    # sampling ON must not perturb the served results either
    FLAGS.set("quality_sample_rate", 1.0)
    res_on = idx.search(queries, k)
    QUALITY.flush()
    assert calls["n"] >= 1
    for a, b, c in zip(res_off, res_off2, res_on):
        np.testing.assert_array_equal(a.ids, b.ids)
        np.testing.assert_array_equal(a.ids, c.ids)
        np.testing.assert_allclose(a.distances, c.distances)


def test_ci_width_shrinks_with_sample_count():
    ids, x, queries = _corpus()
    idx = _ivf(9303)
    idx.store.reserve(len(ids))
    idx.upsert(ids, x)
    idx.train()
    FLAGS.set("quality_sample_rate", 1.0)
    idx.search(queries, 10)
    QUALITY.flush()
    one = QUALITY.region_estimate(9303)
    for _ in range(9):
        idx.search(queries, 10)
    QUALITY.flush()
    many = QUALITY.region_estimate(9303)
    assert many["queries"] > one["queries"]
    assert (many["ci_high"] - many["ci_low"]) < (
        one["ci_high"] - one["ci_low"])


def test_quantized_mirror_keeps_original_rows():
    """sq8 tier: the oracle's ground truth is the ORIGINAL fp32 rows fed
    at write time — not the decoded surrogate — so the live estimate sees
    quantization loss; deletes leave the mirror too."""
    FLAGS.set("quality_sample_rate", 1.0)
    rng = np.random.default_rng(11)
    d = 16
    x = rng.standard_normal((64, d)).astype(np.float32)
    ids = np.arange(64, dtype=np.int64)
    idx = new_index(9304, IndexParameter(
        index_type=IndexType.FLAT, dimension=d, precision="sq8",
    ))
    idx.upsert(ids, x)
    oracle = QUALITY._oracle_for(idx)
    assert oracle.mode == "mirror"
    snap = oracle._mirror.to_host()
    order = np.argsort(snap["ids"])
    np.testing.assert_array_equal(snap["ids"][order], ids)
    # bit-exact originals, NOT sq8-decoded values
    np.testing.assert_array_equal(snap["vectors"][order], x)
    idx.delete(ids[:8])
    answer = oracle.exact_topk(x[:2], k=4)
    assert answer is not None
    gt_ids, _ = answer
    assert not (set(gt_ids.ravel().tolist()) & set(range(8)))


def test_filtered_search_scored_against_filtered_truth():
    """A filtered search's ground truth is restricted to the SAME
    candidate set (review finding): low-selectivity filters must not
    read as recall collapses and stampede the tuner."""
    from dingo_tpu.index.base import FilterSpec

    ids, x, queries = _corpus(n=2000)
    k = 10
    idx = _ivf(9309, nlist=16, nprobe=16)     # full probe: exact
    idx.store.reserve(len(ids))
    idx.upsert(ids, x)
    idx.train()
    # 1/8 selectivity whitelist
    keep = ids[ids % 8 == 3]
    spec = FilterSpec(include_ids=keep)
    FLAGS.set("quality_sample_rate", 1.0)
    res = idx.search(queries, k, spec)
    assert QUALITY.flush()
    est = QUALITY.region_estimate(9309)
    # full-probe IVF over the filtered set IS exact: against filtered
    # truth the estimate reads ~1.0 (vs ~0.125 against unfiltered truth)
    assert est is not None and est["recall"] > 0.95
    # sanity: the served sets really were filtered
    assert all(set(r.ids) <= set(keep.tolist()) for r in res)
    # and the oracle agrees with a numpy brute force over the subset
    mask = np.isin(ids, keep)
    gt_f = _exact_gt(x[mask], ids[mask], queries, k)
    assert est["recall"] == pytest.approx(
        _recall(res, gt_f, k), abs=1e-6)


def test_mirror_survives_sample_rate_toggle():
    """An attached mirror keeps syncing while sampling is momentarily
    off: rate 1 -> 0 -> 1 around a write burst must not leave deleted
    rows in the ground truth or miss fresh ones (review finding)."""
    FLAGS.set("quality_sample_rate", 1.0)
    rng = np.random.default_rng(21)
    d = 16
    x = rng.standard_normal((64, d)).astype(np.float32)
    ids = np.arange(64, dtype=np.int64)
    idx = new_index(9306, IndexParameter(
        index_type=IndexType.FLAT, dimension=d, precision="sq8",
    ))
    idx.upsert(ids[:32], x[:32])
    oracle = QUALITY._oracle_for(idx)
    # incident: operator flips sampling off; writes keep flowing
    FLAGS.set("quality_sample_rate", 0.0)
    idx.delete(ids[:8])
    idx.upsert(ids[32:], x[32:])
    FLAGS.set("quality_sample_rate", 1.0)
    answer = oracle.exact_topk(x[40:42], k=4)
    gt_ids, _ = answer
    found = set(gt_ids.ravel().tolist())
    assert not (found & set(range(8)))          # deletes left the mirror
    snap = oracle._mirror.to_host()
    assert set(snap["ids"]) == set(ids[8:].tolist())   # fresh rows landed


def test_tuner_skips_rerank_knob_without_cache():
    """bf16/sq8 regions with no rerank cache must not burn ticks on a
    disconnected rerank_factor dial (review finding): the first tighten
    goes straight to nprobe."""
    FLAGS.set("rerank_cache_rows", 0)
    idx = _ivf(9307, nlist=16, nprobe=1, precision="bf16")
    assert idx._rerank_cache is None
    tuner = SloTuner(slo_recall=0.95, latency_budget_ms=0.0,
                     quality_plane=_PlaneRecorder())
    op = tuner.step_index(idx, _fake_estimate(0.5))
    assert op.knob == "nprobe"


def test_precision_advisory_fires_once_per_episode():
    """The unapplied precision advisory is rate-limited to one per
    stuck-at-ceiling episode, re-armed by leaving the regime (review
    finding: it used to re-fire every tick forever)."""
    FLAGS.set("rerank_cache_rows", 0)
    idx = _ivf(9308, nlist=16, nprobe=16, precision="sq8")
    tuner = SloTuner(slo_recall=0.99, latency_budget_ms=0.0,
                     quality_plane=_PlaneRecorder())
    op = tuner.step_index(idx, _fake_estimate(0.5))
    assert op is not None and op.knob == "precision" and not op.applied
    for _ in range(3):
        assert tuner.step_index(idx, _fake_estimate(0.5)) is None
    # recovery (in band) re-arms the advisory for the next episode
    assert tuner.step_index(idx, _fake_estimate(0.99, half=0.02)) is None
    op = tuner.step_index(idx, _fake_estimate(0.5))
    assert op is not None and op.knob == "precision"


def test_install_reference_and_score_direct():
    """The mesh-bench rider mechanism: a standalone fp32 reference +
    synchronous scoring through the same estimator plumbing."""
    rng = np.random.default_rng(5)
    x = rng.standard_normal((256, 16)).astype(np.float32)
    ids = np.arange(256, dtype=np.int64)
    queries = x[:4]
    k = 5
    gt = _exact_gt(x, ids, queries, k)
    QUALITY.install_reference(9305, ids, x)
    perfect = QUALITY.score_direct(9305, queries, gt, k, kind="mesh")
    assert perfect["recall"] == pytest.approx(1.0)
    wrong = gt.copy()
    wrong[:, 0] = -1            # drop the top hit of every query
    partial = QUALITY.score_direct(9305, queries, wrong, k, kind="mesh")
    assert partial["recall"] == pytest.approx((k - 1) / k)
    est = QUALITY.region_estimate(9305)
    assert est is not None and est["queries"] == 8


# ---------------------------------------------------------------------------
# tuner
# ---------------------------------------------------------------------------

def test_ladder_helpers():
    vals = ladder_values(64)
    assert vals == (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 48, 64)
    assert ladder_step(vals, 1, up=True) == 2
    assert ladder_step(vals, 8, up=True) == 12
    assert ladder_step(vals, 80, up=True) is None     # past the cap
    assert ladder_step(vals, 64, up=True) is None     # ceiling
    assert ladder_step(vals, 1, up=False) is None     # floor
    assert ladder_step(vals, 12, up=False) == 8
    # off-ladder current value (operator-configured): snaps to neighbors
    assert ladder_step(vals, 10, up=True) == 12
    assert ladder_step(vals, 10, up=False) == 8


class _PlaneRecorder:
    def __init__(self):
        self.resets = []

    def reset_region(self, region_id):
        self.resets.append(region_id)


def test_tuner_monotone_tighten_to_ladder_ceiling():
    idx = _ivf(9310, nlist=16, nprobe=1)
    plane = _PlaneRecorder()
    tuner = SloTuner(slo_recall=0.95, latency_budget_ms=0.0,
                     quality_plane=plane)
    seen = []
    for _ in range(12):
        op = tuner.step_index(idx, _fake_estimate(0.5))
        if op is None or op.knob == "precision":
            break
        seen.append(op.new)
    assert seen == [2, 3, 4, 6, 8, 12, 16]     # strictly ladder-monotone
    assert idx.tuning["nprobe"] == 16
    # ceiling reached on a fp32 index: nothing further to tighten
    assert tuner.step_index(idx, _fake_estimate(0.5)) is None
    assert plane.resets == [9310] * 7          # window reset per step


def test_tuner_relax_floors_at_ladder_bottom():
    idx = _ivf(9311, nlist=16, nprobe=4)
    plane = _PlaneRecorder()
    tuner = SloTuner(slo_recall=0.90, latency_budget_ms=0.0,
                     quality_plane=plane)
    comfortably_above = _fake_estimate(0.999, half=0.001)
    steps = []
    for _ in range(6):
        op = tuner.step_index(idx, comfortably_above)
        if op is None:
            break
        steps.append(op.new)
    assert steps == [3, 2, 1]
    assert tuner.step_index(idx, comfortably_above) is None   # floor


def test_tuner_stale_or_thin_evidence_is_noop():
    idx = _ivf(9312, nlist=16, nprobe=4)
    tuner = SloTuner(slo_recall=0.95, latency_budget_ms=0.0,
                     quality_plane=_PlaneRecorder(), min_queries=32)
    assert tuner.step_index(idx, None) is None
    assert tuner.step_index(
        idx, _fake_estimate(0.5, queries=8)) is None        # too thin
    assert tuner.step_index(
        idx, _fake_estimate(0.5, age_s=3600 * 5)) is None   # stale
    assert "nprobe" not in idx.tuning


def test_tuner_in_band_holds_and_budget_blocks():
    idx = _ivf(9313, nlist=16, nprobe=4)
    tuner = SloTuner(slo_recall=0.95, latency_budget_ms=5.0,
                     quality_plane=_PlaneRecorder())
    # CI straddles the SLO: no confident violation, no comfortable excess
    assert tuner.step_index(idx, _fake_estimate(0.95, half=0.02)) is None
    # confident violation but the latency budget is blown: hold + count
    blocked0 = METRICS.counter("quality.tuner_blocked", 9313).get()
    assert tuner.step_index(
        idx, _fake_estimate(0.5), p99_ms=50.0) is None
    assert METRICS.counter(
        "quality.tuner_blocked", 9313).get() == blocked0 + 1
    # over budget AND above SLO: relax toward faster settings
    op = tuner.step_index(
        idx, _fake_estimate(0.999, half=0.0005), p99_ms=50.0)
    assert op is not None and op.direction == "relax"


def test_tuner_quantized_knob_order_and_precision_advisory():
    """Quantized IVF: rerank_factor is the cheap knob (walked first);
    when every live knob tops out, the remaining move is an ADVISORY
    precision upgrade (never auto-applied)."""
    FLAGS.set("rerank_cache_rows", 64)
    try:
        idx = _ivf(9314, nlist=16, nprobe=16, precision="sq8")
        idx.tuning["rerank_factor"] = RERANK_LADDER[-1] - 1
        tuner = SloTuner(slo_recall=0.99, latency_budget_ms=0.0,
                         quality_plane=_PlaneRecorder())
        op = tuner.step_index(idx, _fake_estimate(0.5))
        assert op.knob == "rerank_factor" and op.new == RERANK_LADDER[-1]
        # rerank + nprobe both at ceiling -> advisory tier upgrade
        op = tuner.step_index(idx, _fake_estimate(0.5))
        assert op.knob == "precision" and op.new == "bf16"
        assert not op.applied
        assert getattr(idx, "_precision") == "sq8"   # NOT flipped live
    finally:
        FLAGS.set("rerank_cache_rows", 0)


def test_tuner_knobs_for_hnsw():
    idx = new_index(9315, IndexParameter(
        index_type=IndexType.HNSW, dimension=8, nlinks=4,
        efconstruction=32,
    ))
    tuner = SloTuner(slo_recall=0.95, latency_budget_ms=0.0,
                     quality_plane=_PlaneRecorder())
    op = tuner.step_index(idx, _fake_estimate(0.5))
    assert op.knob == "ef" and op.new > idx.ef_search_default
    assert idx.tuning["ef"] == op.new


def test_tuner_override_reaches_the_search_path():
    """The applied override changes what the region actually serves: a
    tightened nprobe must measurably raise recall on a hard corpus."""
    ids, x, queries = _corpus(n=3000, noise=2.0)
    k = 10
    gt = _exact_gt(x, ids, queries, k)
    idx = _ivf(9316, nlist=16, nprobe=1)
    idx.store.reserve(len(ids))
    idx.upsert(ids, x)
    idx.train()
    before = _recall(idx.search(queries, k), gt, k)
    idx.tuning["nprobe"] = 16                      # ladder ceiling
    after = _recall(idx.search(queries, k), gt, k)
    assert after >= before
    assert after == pytest.approx(
        _recall(idx.search(queries, k, nprobe=16), gt, k))
    # a request-pinned nprobe overrides the tuner's default
    pinned = _recall(idx.search(queries, k, nprobe=1), gt, k)
    assert pinned == pytest.approx(before, abs=1e-6)


def test_recompile_sentinel_invariant_across_tuner_steps():
    """Tuner steps only ever pick shape-ladder values, so a warmed region
    serves the WHOLE walk with zero jit-cache misses — the PR 5 sentinel
    makes it checkable."""
    ids, x, queries = _corpus(n=2000, noise=2.0)
    k = 10
    idx = _ivf(9317, nlist=16, nprobe=1)
    idx.store.reserve(len(ids))
    idx.upsert(ids, x)
    idx.train()
    for np_ in ladder_values(16):
        idx.warmup(batches=(len(queries),), topk=k, nprobe=np_)
    FLAGS.set("quality_sample_rate", 1.0)
    idx.search(queries, k)             # warm the shadow kernel's shapes
    assert QUALITY.flush()
    rc = METRICS.counter("xla.recompiles")
    rc0 = rc.get()
    tuner = SloTuner(slo_recall=0.99, latency_budget_ms=0.0,
                     min_queries=4)
    for _ in range(8):
        idx.search(queries, k)
        assert QUALITY.flush()
        tuner.step_index(idx, QUALITY.region_estimate(9317))
    assert idx.tuning.get("nprobe", 1) > 1         # the walk happened
    assert rc.get() - rc0 == 0


# ---------------------------------------------------------------------------
# heartbeat / surfacing
# ---------------------------------------------------------------------------

def test_quality_fields_ride_heartbeat_pb_roundtrip():
    from dingo_tpu.metrics.snapshot import (
        RegionMetricsSnapshot,
        StoreMetricsSnapshot,
    )
    from dingo_tpu.server import convert

    rm = RegionMetricsSnapshot(
        region_id=7, vector_count=100, is_leader=True, search_qps=12.5,
        quality_recall=0.971, quality_recall_ci_low=0.95,
        quality_recall_ci_high=0.988, quality_samples=64,
    )
    snap = StoreMetricsSnapshot(store_id="s1", regions=[rm])
    msg = convert.store_metrics_to_pb(snap)
    wire = type(msg).FromString(msg.SerializeToString())
    back = convert.store_metrics_from_pb(wire)
    got = back.region(7)
    assert got.quality_recall == pytest.approx(0.971)
    assert got.quality_recall_ci_low == pytest.approx(0.95)
    assert got.quality_recall_ci_high == pytest.approx(0.988)
    assert got.quality_samples == 64
    # persist round-trip (the replicated coordinator's raft leg)
    from dingo_tpu.common import persist

    again = persist.loads(persist.dumps(snap))
    assert again.region(7).quality_recall == pytest.approx(0.971)


def test_cluster_top_renders_recall_column():
    from dingo_tpu.client.cli import format_cluster_top
    from dingo_tpu.server import pb

    resp = pb.GetStoreMetricsResponse()
    entry = resp.stores.add()
    entry.store_id = "s1"
    entry.metrics.store_id = "s1"
    r1 = entry.metrics.regions.add()
    r1.region_id = 1
    r1.is_leader = True
    r1.quality_recall = 0.973
    r1.quality_samples = 80
    r2 = entry.metrics.regions.add()
    r2.region_id = 2          # no evidence: renders '-'
    out = format_cluster_top(resp)
    assert "RECALL" in out
    assert "0.973" in out
    # region 2 has no evidence: its RECALL cell is '-'
    line2 = next(ln for ln in out.splitlines() if ln.startswith("2 "))
    cells = line2.split()
    # RECALL sits before the QDEPTH/PRESS/SHED pressure columns, the
    # CACHE column, and FLAGS
    assert cells[-6] == "-"


def test_flight_bundle_captures_quality_state(tmp_path):
    from dingo_tpu.obs.flight import FLIGHT

    ids, x, queries = _corpus(n=1500)
    idx = _ivf(9320)
    idx.store.reserve(len(ids))
    idx.upsert(ids, x)
    idx.train()
    FLAGS.set("quality_sample_rate", 1.0)
    idx.search(queries, 10)
    assert QUALITY.flush()
    bid = FLIGHT.trigger("slow_query", name="test.quality")
    assert bid
    bundle = FLIGHT.get_json(bid)
    assert any(k.startswith("quality.recall") for k in bundle["quality"])
    # the report tool renders a per-region quality table from it
    import importlib

    report = importlib.import_module("tools.flight_report")
    text = report.render(bundle)
    assert "quality / slo-tuner state" in text
    assert "RECALL" in text
    # and parse_bundle round-trips the payload file form
    p = tmp_path / "bundle.json"
    p.write_text(json.dumps(bundle))
    assert report.parse_bundle(str(p))["id"] == bundle["id"]
