"""TpuFlat: exact brute-force index (reference VectorIndexFlat,
src/vector/vector_index_flat.{h,cc} — faiss::IndexFlatL2/IP inside
IndexIDMap2) and TpuBinaryFlat (faiss::IndexBinaryFlat equivalent).

One jit'd program does the whole search: [b, capacity] score matrix on the
MXU + masked top-k. Query batches are padded to power-of-two buckets and
capacity grows by doubling, so the compile cache stays small and steady-state
searches hit cached executables.
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    IndexType,
    InvalidParameter,
    NotSupported,
    SearchResult,
    VectorIndex,
    resolve_precision,
    strip_invalid,
)
from dingo_tpu.index.rerank_cache import DeviceRerankCache
from dingo_tpu.index.slot_store import SlotStore, SqSlotStore, _next_pow2
from dingo_tpu.ops.distance import (
    Metric,
    device_wait_span,
    np_normalize,
    score_matrix,
    scores_to_distances,
)
from dingo_tpu.ops.topk import begin_host_fetch, topk_scores
from dingo_tpu.obs.quality import QUALITY
from dingo_tpu.obs.sentinel import sentinel_jit


@sentinel_jit("index.flat.search", static_argnames=("k", "metric", "nbits"))
def _flat_search_kernel(vecs, sqnorm, mask, queries, k, metric, nbits):
    """Whole-index scan + masked top-k; returns distances and SLOT indices
    (host translates slots -> 64-bit external ids, see slot_store.py)."""
    scores = score_matrix(
        queries,
        vecs,
        metric,
        x_sqnorm=sqnorm,
        x_is_normalized=(metric is Metric.COSINE),
        nbits=nbits,
    )
    vals, slots = topk_scores(scores, k, valid=mask)
    return scores_to_distances(vals, metric), slots


@sentinel_jit("index.flat.search_sq", static_argnames=("k", "metric"))
def _sq_flat_search_kernel(codes, vmin, scale, sqnorm, mask, queries, k,
                           metric):
    """SQ8 whole-index scan: decode-on-the-fly bf16 compute over uint8
    codes, fp32 accumulate (ops/sq.py), then the same masked top-k."""
    from dingo_tpu.ops.sq import sq_score_matrix

    scores = sq_score_matrix(
        queries, codes, vmin, scale, metric, x_sqnorm=sqnorm
    )
    vals, slots = topk_scores(scores, k, valid=mask)
    return scores_to_distances(vals, metric), slots


def _new_tier_store(precision: str, dim: int, parameter: IndexParameter,
                    capacity: int = 0):
    """SlotStore for a precision tier: fp32/bf16 are dtype choices on the
    float store; sq8 swaps in the quantizing store."""
    kw = {"capacity": capacity} if capacity else {}
    if precision == "sq8":
        return SqSlotStore(dim, **kw)
    dtype = jnp.bfloat16 if precision == "bf16" \
        else jnp.dtype(parameter.dtype)
    return SlotStore(dim, dtype, **kw)


def integrity_mutation(fn):
    """Bracket an index write path for the state-integrity plane: bumps
    the ledger's pending/mutation counters BEFORE any device state can
    mutate and releases the pending bracket when the method exits (even
    on error). While the bracket is open a concurrent scrub classifies
    as raced (device may be ahead of the ledger) and the heartbeat
    withholds the digest vector (the applied-index tag may be pending).
    No-op while the index is untracked."""
    import functools

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        self._integrity_begin()
        try:
            return fn(self, *args, **kwargs)
        finally:
            self._integrity_end()
    return wrapped


def _resolve_train_cap(derived: int) -> int:
    """Effective train-sample row cap: the shared conf cap
    (train.sample_rows) meets the caller's derived cap (e.g.
    max_points_per_centroid * nlist). 0 from conf = full corpus — an
    explicit opt-in that lifts the derived cap too (ISSUE 18b: chunked
    device Lloyd makes full-corpus training one compiled scan, the Faiss
    derive-from-corpus stance instead of a fixed host-sample ceiling).
    Returns 0 for uncapped."""
    from dingo_tpu.common.config import train_sample_rows

    conf = train_sample_rows()
    if conf == 0:
        return 0
    if derived <= 0:
        return conf
    return min(conf, derived)


def _pad_batch(q: np.ndarray) -> np.ndarray:
    b = q.shape[0]
    bb = _next_pow2(max(1, b))
    if bb != b:
        q = np.concatenate([q, np.zeros((bb - b,) + q.shape[1:], q.dtype)])
    return q


class _SlotStoreIndex(VectorIndex):
    """Shared machinery for indexes whose whole search is one flat-scan
    kernel over a SlotStore (float flat + binary flat)."""

    store: SlotStore
    _kernel_metric: Metric
    _kernel_nbits: int
    #: precision tier ("fp32"/"bf16"/"sq8"); binary indexes stay "fp32"
    _precision: str = "fp32"
    #: bounded device row cache for exact rerank of quantized shortlists
    _rerank_cache = None

    # -- precision tier / rerank plumbing ---------------------------------
    def _init_precision(self, parameter: IndexParameter,
                        tier: Optional[str] = None) -> None:
        """Resolve the tier and (for quantized tiers) attach the rerank
        cache. Call AFTER self.store exists — the cache shares its lock.
        Pass `tier` to pin an already-resolved tier (reload paths must not
        re-consult the mutable conf default mid-life)."""
        from dingo_tpu.common.config import FLAGS

        self._precision = tier or resolve_precision(parameter)
        self._rerank_cache = None
        if self._precision in ("bf16", "sq8"):
            rows = int(FLAGS.get("rerank_cache_rows"))
            if rows > 0:
                self._rerank_cache = DeviceRerankCache(
                    self.dimension,
                    rows,
                    dtype=jnp.dtype(str(FLAGS.get("rerank_cache_dtype"))),
                    device_lock=self.store.device_lock,
                )

    def _offer_rerank(self, slots, vectors) -> None:
        if self._rerank_cache is not None:
            self._rerank_cache.offer(slots, vectors)

    def _invalidate_rerank(self, slots) -> None:
        if self._rerank_cache is not None:
            self._rerank_cache.invalidate(slots[slots >= 0])

    def _rerank_shortlist(self, topk: int):
        """k' to over-fetch for the rerank stage, or None when the stage
        is off (fp32 tier, no cache, empty cache, or factor <= 1). The
        SLO tuner can override the conf factor per region (obs/tuner.py),
        riding the same ladder values."""
        cache = self._rerank_cache
        if cache is None or not len(cache):
            return None
        from dingo_tpu.common.config import FLAGS

        factor = self.tuned(
            "rerank_factor", int(FLAGS.get("quantized_rerank_factor"))
        )
        if factor <= 1:
            return None
        return topk * factor

    def _dispatch_rerank(self, qpad, dists, slots, topk: int):
        """Exact rerank of the quantized shortlist against the device row
        cache; caller holds store.device_lock (cache arrays are donated by
        its write programs under the same lock)."""
        from dingo_tpu.ops.rerank import cached_rerank_device

        cache = self._rerank_cache
        return cached_rerank_device(
            cache.vecs,
            cache.sqnorm,
            cache.device_map(self.store.capacity),
            dists,
            slots,
            qpad,
            k=topk,
            metric=self.metric,
        )

    # -- train sampling (device-resident, ISSUE 18b) -----------------------
    def _train_rows_device(self, derived_cap: int = 0):
        """Live stored rows for implicit training, as a DEVICE f32 array:
        samples slot INDICES host-side (cheap ints, seeded by index id so
        retrains are reproducible) and gathers the rows on device via
        store.rows_device — the corpus never materializes on the host the
        way the old to_host() path did. `derived_cap` is the caller's own
        ceiling (0 = none); conf train.sample_rows=0 lifts both."""
        live = np.flatnonzero(self.store.ids_by_slot >= 0)
        cap = _resolve_train_cap(derived_cap)
        if cap and len(live) > cap:
            sel = np.random.default_rng(self.id).choice(
                len(live), cap, replace=False
            )
            live = np.sort(live[sel])   # ascending gather, stable order
        return self.store.rows_device(live)

    # -- state-integrity ledger hooks (obs/integrity.py) -------------------
    def _integrity_begin(self) -> None:
        """Called BEFORE any device state mutates in a write path (the
        integrity_mutation decorator): bumps the ledger's pending +
        mutation counters so a scrub overlapping the device-written-but-
        not-yet-folded window classifies as raced instead of phantom
        corruption. No-op while untracked."""
        from dingo_tpu.obs.integrity import INTEGRITY

        INTEGRITY.note_mutation_begin(self)

    def _integrity_end(self) -> None:
        from dingo_tpu.obs.integrity import INTEGRITY

        INTEGRITY.note_mutation_end(self)

    def _integrity_write(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        """Fold a write batch into the region's incremental state digests:
        'rows' always (canonical stored bytes — codes for sq8), 'blocked'
        when the store maintains the dimension-blocked mirror. O(batch)
        host hashing; zero device work; no-op while the index is
        untracked (integrity.enabled off AND no ledger — an existing
        ledger keeps folding through a flag toggle)."""
        from dingo_tpu.obs.integrity import INTEGRITY

        if len(ids) == 0 or not INTEGRITY.tracking(self):
            return
        stored = self.store.canonical_rows(vectors)
        ids = np.asarray(ids, np.int64)
        INTEGRITY.note_write(self, "rows", ids, stored)
        if getattr(self.store, "vecs_blk", None) is not None:
            # the blocked mirror holds the same values per slot (the
            # transform is a per-row reshape), digested under its own tag
            # so the scrub can tell WHICH copy rotted
            INTEGRITY.note_write(self, "blocked", ids, stored)

    def _integrity_delete(self, ids: np.ndarray) -> None:
        from dingo_tpu.obs.integrity import INTEGRITY

        INTEGRITY.note_delete(self, np.asarray(ids, np.int64))

    def _integrity_on_restore(self, meta: dict) -> None:
        """Recompute digests from the restored state and verify them
        against the snapshot's persisted vector (raises
        SnapshotCorruption; the manager falls back to an engine rebuild).

        A precision-tier flip across the snapshot (fp32 <-> bf16 share
        the f32-on-disk row format and legitimately load across tiers,
        incl. legacy pre-tier snapshots with no precision key) re-casts
        every stored byte, so digest comparison is undefined — the
        ledger still rebuilds from the restored state, verification is
        skipped, and the next scrub covers it from there."""
        from dingo_tpu.obs.integrity import INTEGRITY

        integ = meta.get("integrity")
        if meta.get("precision") != self._precision:
            integ = None
        INTEGRITY.verify_restore(self, integ)

    def _count_search(self) -> None:
        from dingo_tpu.common.metrics import METRICS

        METRICS.counter(
            "vector.search_by_precision",
            region_id=self.id,
            labels={"precision": self._precision},
        ).add(1)

    def _note_prune_stats(self, stats_h) -> None:
        """Fold a pruned-scan stats block ([b, 4] host array: scanned
        pairs, total pairs, full scans, candidates — see
        ops/pallas_ivf._ivf_pruned_kernel) into the metrics plane. Called
        from resolve() so the hot path never synchronizes for it."""
        from dingo_tpu.common.metrics import METRICS

        sums = np.asarray(stats_h, np.float64).sum(axis=0)
        scanned, total, full, cand = (float(x) for x in sums[:4])
        if total > 0:
            METRICS.gauge(
                "ivf.pruned_dim_fraction", region_id=self.id
            ).set(max(0.0, 1.0 - scanned / total))
        METRICS.counter("ivf.pruned_candidates", region_id=self.id).add(
            int(max(0.0, cand - full))
        )

    # subclasses set these
    def _prep_vectors(self, vectors: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _prep_queries(self, queries: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # -- mutation ----------------------------------------------------------
    def add(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        uniq, counts = np.unique(ids, return_counts=True)
        if (counts > 1).any():
            raise InvalidParameter(
                f"duplicate ids within batch: {uniq[counts > 1][:5].tolist()}"
            )
        dup = [int(i) for i in ids if int(i) in self.store]
        if dup:
            raise InvalidParameter(f"duplicate ids {dup[:5]} (use upsert)")
        self.upsert(ids, vectors)

    @integrity_mutation
    def upsert(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        vectors = self._prep_vectors(vectors)
        if len(ids) != len(vectors):
            raise InvalidParameter("ids/vectors length mismatch")
        slots = self.store.put(np.asarray(ids, np.int64), vectors)
        self._offer_rerank(slots, vectors)
        # quality plane: quantized tiers keep an fp32 ground-truth mirror
        # fed the PRE-quantization rows (no-op while sampling is off)
        QUALITY.observe_write(self, np.asarray(ids, np.int64), vectors)
        self._integrity_write(ids, vectors)
        self.write_count_since_save += len(ids)

    @integrity_mutation
    def delete(self, ids: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        slots = self.store.remove_slots(ids)
        removed = int((slots >= 0).sum())
        self._invalidate_rerank(slots)
        QUALITY.observe_delete(self, ids)
        self._integrity_delete(ids)
        self.write_count_since_save += removed

    # -- search ------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
    ) -> List[SearchResult]:
        return self.search_async(queries, topk, filter_spec)()

    def search_async(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        staged=None,
    ) -> Callable[[], List[SearchResult]]:
        """Dispatch the search and return a thunk materializing results.

        The device->host hop dominates wall time on the axon tunnel
        (~60-80 ms vs ~4 ms kernel); callers with concurrent requests
        (service layer, bench) dispatch many searches and resolve later,
        pipelining the device. Slots freed while a search is in flight park
        in limbo (slot_store.py) so resolve never misattributes results.

        ``staged`` (common/pipeline.StagedBatch) carries a pre-padded
        device upload from the serving pipeline's staging ring; it is
        claimed only when its identity check proves it was built from
        THESE queries (``_prep_queries`` rebinding — binary bit-unpack,
        dtype cast — makes the claim fail and the local pad run instead).

        One-sync contract: resolve() performs exactly ONE
        ``jax.device_get`` on the whole fetch tuple (dists, slots, and
        the prune-stats block when present) — dingolint's resolve-sync
        checker enforces this across index families."""
        queries = self._prep_queries(queries)
        b = queries.shape[0]
        qpad = staged.take(queries) if staged is not None else None
        if qpad is None:
            qpad = jnp.asarray(_pad_batch(queries))
        store = self.store
        # lease BEFORE dispatch: kernel-produced slots must stay limbo-
        # parked (not reassigned) until resolve translates them
        lease = store.begin_search()
        self._count_search()
        try:
            with store.device_lock:
                # mask capture AND dispatch under the device lock: a
                # concurrent donated write or growth would invalidate the
                # vecs reference / change the capacity mid-dispatch
                if filter_spec is None or filter_spec.is_empty():
                    mask = store.device_mask()
                else:
                    mask = jnp.asarray(
                        filter_spec.slot_mask(store.ids_by_slot)
                    )
                kprime = self._rerank_shortlist(int(topk))
                dists, slots, stats = self._run_search_kernel(
                    qpad, mask, kprime or int(topk)
                )
                if kprime is not None:
                    # exact rerank of the quantized shortlist, still under
                    # the lock (cache arrays share it) and still async
                    dists, slots = self._dispatch_rerank(
                        qpad, dists, slots, int(topk)
                    )
        except Exception:
            lease.release()
            raise
        if kprime is not None:
            # sampled traces get a true ops.rerank kernel-time span
            # (outside the lock; no-op when the request isn't sampled)
            device_wait_span("rerank", (dists, slots))
        # Start the D2H copy as soon as the kernel finishes — ONE group
        # covering the whole reply (stats included): the tunnel's fetch
        # RTT then overlaps across in-flight searches instead of
        # serializing at resolve time.
        fetch = begin_host_fetch(dists, slots, stats)
        # trace hook OUTSIDE the device lock: a sampled request blocks for
        # a true kernel-time span without stalling concurrent searches
        device_wait_span("flat_scan", (dists, slots))
        from dingo_tpu.obs.heat import HEAT, heat_enabled

        heat_on = heat_enabled()
        if heat_on:
            HEAT.register_layout(self.id, "slot", self._heat_layout)
        def resolve() -> List[SearchResult]:
            try:
                fetched = jax.device_get(fetch)
                dists_h, slots_h = fetched[0], fetched[1]
                if stats is not None:
                    self._note_prune_stats(fetched[2][:b])
                if heat_on:
                    # result slots -> slot-block heat units, from the
                    # array this resolve ALREADY fetched (no new sync;
                    # -1 padding filtered on the heat worker)
                    HEAT.observe(self.id, "slot", slots_h[:b])
                ids = store.ids_of_slots(slots_h[:b])
                dists_h = self._convert_distances(dists_h)
                # head-sampled shadow scoring (async lane; noop at rate 0);
                # filtered searches carry their spec so the ground truth
                # is restricted to the same candidate set
                QUALITY.observe_search(
                    self, queries, topk, ids, dists_h[:b], bucket="flat",
                    filter_spec=filter_spec,
                )
                return [strip_invalid(i, d) for i, d in zip(ids, dists_h[:b])]
            finally:
                lease.release()

        return resolve

    def _convert_distances(self, dists: np.ndarray) -> np.ndarray:
        """Kernel-score -> wire-distance hook (identity for float metrics;
        binary hamming converts from the cached-pm1 IP score)."""
        return dists

    def _heat_layout(self) -> dict:
        """Heat-plane layout provider: FLAT heat units are fixed
        SLOT_BLOCK slot ranges, priced at this tier's bytes/row (heat
        worker thread)."""
        from dingo_tpu.obs.heat import SLOT_BLOCK, TIER_BYTES

        tier = getattr(self, "_precision", "fp32")
        return {
            "rows_per_unit": SLOT_BLOCK,
            "row_bytes": self.dimension * TIER_BYTES.get(tier, 4.0),
            "tier": tier,
            "dim": self.dimension,
        }

    def _run_search_kernel(self, qpad, mask, k):
        """Kernel crossover for the whole-store scan; returns (dists,
        slots, prune_stats_or_None). Three arms per tier:

          * pruned Pallas streaming kernel — fused crossover fired AND the
            store maintains the dimension-blocked mirror (vecs_blk):
            partial distances per dim block, early candidate pruning, no
            [b, capacity] HBM score matrix;
          * plain fused Pallas kernel — crossover fired, no blocked mirror;
          * XLA scan + masked top-k otherwise.
        """
        from dingo_tpu.common.config import pallas_fused_enabled
        from dingo_tpu.ops.distance import metric_ascending

        store = self.store
        fused_on = (
            pallas_fused_enabled(store.capacity)
            and self._kernel_metric in (Metric.L2, Metric.INNER_PRODUCT)
        )
        pruned_on = fused_on and store.vecs_blk is not None
        if pruned_on:
            from dingo_tpu.common.config import prune_scan_enabled

            pruned_on = prune_scan_enabled()
        if self._precision == "sq8":
            if store.sq_params is None:
                # empty untrained store: nothing valid to scan; identity
                # codec keeps the kernel well-defined WITHOUT installing
                # params (the first real write must still train them)
                vmin = jnp.zeros((self.dimension,), jnp.float32)
                scale = jnp.ones((self.dimension,), jnp.float32)
            elif pruned_on:
                from dingo_tpu.ops.pallas_topk import pruned_fused_search

                vals, slots, stats = pruned_fused_search(
                    qpad, store.vecs_blk, store.bsq_blk, store.sqnorm,
                    mask, k,
                    ascending=metric_ascending(self._kernel_metric),
                    sq_vmin=store.sq_vmin_d, sq_scale=store.sq_scale_d,
                )
                return (
                    scores_to_distances(vals, self._kernel_metric),
                    slots, stats,
                )
            else:
                vmin = store.sq_vmin_d
                scale = store.sq_scale_d
            dists, slots = _sq_flat_search_kernel(
                store.vecs,
                vmin,
                scale,
                store.sqnorm,
                mask,
                qpad,
                k=k,
                metric=self._kernel_metric,
            )
            return dists, slots, None
        # float stores only (f32/bf16 — the kernels promote in VMEM):
        # TpuBinaryFlat reaches here with an int8 ±1 store and mixed
        # int dot under Mosaic is unvalidated; keep it on XLA.
        if fused_on and store.vecs.dtype in (jnp.float32, jnp.bfloat16):
            if pruned_on:
                from dingo_tpu.ops.pallas_topk import pruned_fused_search

                vals, slots, stats = pruned_fused_search(
                    qpad, store.vecs_blk, store.bsq_blk, store.sqnorm,
                    mask, k,
                    ascending=metric_ascending(self._kernel_metric),
                )
                return (
                    scores_to_distances(vals, self._kernel_metric),
                    slots, stats,
                )
            from dingo_tpu.ops.pallas_topk import fused_search

            vals, slots = fused_search(
                qpad, store.vecs, store.sqnorm,
                mask, k, ascending=metric_ascending(self._kernel_metric),
            )
            return (
                scores_to_distances(vals, self._kernel_metric), slots, None
            )
        dists, slots = _flat_search_kernel(
            store.vecs,
            store.sqnorm,
            mask,
            qpad,
            k=k,
            metric=self._kernel_metric,
            nbits=self._kernel_nbits,
        )
        return dists, slots, None

    # -- lifecycle ---------------------------------------------------------
    def get_count(self) -> int:
        return len(self.store)

    def get_memory_size(self) -> int:
        return self.store.memory_size()

    def _save_meta(self) -> dict:
        from dingo_tpu.obs.integrity import INTEGRITY

        meta = {
            "index_type": self.index_type.value,
            "dimension": self.dimension,
            "metric": self.metric.value,
            "apply_log_id": self.apply_log_id,
            "count": self.get_count(),
            "precision": self._precision,
            # scan-layout metadata: informational (rows persist FLAT; the
            # blocked mirror is a runtime arrangement rebuilt at load time
            # from conf vector.blocked_layout), recorded so operators can
            # tell which layout produced a snapshot's bench numbers
            "blocked_layout": bool(
                getattr(self.store, "vecs_blk", None) is not None
            ),
            "dim_block": int(getattr(self.store, "dim_block", 0) or 0),
        }
        # state-integrity digest vector (obs/integrity.py): restore
        # recomputes from the loaded state and refuses to serve a
        # mismatch. Only persistable artifacts ride (the blocked mirror
        # is rebuilt from conf at load; the live scrub covers it)
        integ = INTEGRITY.snapshot_artifacts(self)
        if integ:
            meta["integrity"] = integ
        return meta

    def _check_meta(self, meta: dict) -> None:
        if meta["dimension"] != self.dimension:
            raise InvalidParameter(
                f"snapshot dimension {meta['dimension']} != {self.dimension}"
            )
        if meta["metric"] != self.metric.value:
            raise InvalidParameter(
                f"snapshot metric {meta['metric']} != {self.metric.value}"
            )
        snap_p = meta.get("precision")
        if snap_p is not None and snap_p != self._precision:
            # fp32<->bf16 snapshots share the f32-on-disk row format, so a
            # tier flip (conf default change) loads fine — rows re-cast
            # into the new store. sq8 is a different CONTAINER (codes +
            # codec params), so crossing it is a hard error. Pre-tier
            # snapshots have no key and load under any tier.
            if "sq8" in (snap_p, self._precision):
                raise InvalidParameter(
                    f"snapshot precision {snap_p} != {self._precision}"
                )

    def need_to_save(self, last_save_log_behind: int) -> bool:
        """Reference wrapper policy (vector_index.h:497-500): save when the
        accumulated write count or raft-log lag crosses thresholds."""
        return (
            self.write_count_since_save >= 10000
            or last_save_log_behind >= 10000000
        )


class TpuFlat(_SlotStoreIndex):
    """Exact search; also used internally as IVF_PQ's pre-train stage
    (reference hybrid contract vector_index_ivf_pq.h:113-115) and as the
    brute-force engine behind VectorReader's scan path."""

    def __init__(self, index_id: int, parameter: IndexParameter):
        super().__init__(index_id, parameter)
        if parameter.dimension <= 0:
            raise InvalidParameter(f"dimension {parameter.dimension}")
        precision = resolve_precision(parameter)
        if precision == "sq8" and parameter.metric is Metric.HAMMING:
            raise InvalidParameter("sq8 tier needs a float metric")
        self.store = _new_tier_store(
            precision, parameter.dimension, parameter
        )
        self._init_precision(parameter)
        self._kernel_metric = parameter.metric
        self._kernel_nbits = 0

    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        """FLAT needs no geometric training, but the sq8 tier can install
        its per-dim min/max codec from an explicit train set BEFORE ingest
        (otherwise the first write batch trains it — faiss's
        train-once-clip-later convention). need_train() stays False so the
        manager never blocks on this."""
        if self._precision == "sq8" and vectors is not None:
            self.store.maybe_train(self._prep_vectors(vectors))

    def _prep_vectors(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dimension:
            raise InvalidParameter(
                f"vector dim {vectors.shape} != {self.dimension}"
            )
        if self.metric is Metric.COSINE:
            # Store normalized; search then runs plain IP on the MXU
            # (reference normalizes for cosine, vector_index_utils.h:183).
            # Host-side normalize: the jnp round-trip here synchronized
            # the device on every write batch (dingolint host-sync).
            vectors = np_normalize(vectors)
        return vectors

    def _prep_queries(self, queries: np.ndarray) -> np.ndarray:
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.shape[1] != self.dimension:
            raise InvalidParameter(
                f"query dim {queries.shape[1]} != {self.dimension}"
            )
        return queries

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        if self._precision == "sq8" and self.store.sq_params is not None:
            # codes + codec params persist verbatim (1 byte/dim on disk,
            # bit-exact restore — the SQ analog of PQ codebooks riding
            # ivf_pq.npz); a decoded save would re-encode on load and
            # silently double the quantization error
            snap = self.store.codes_to_host()
            np.savez(
                os.path.join(path, "flat.npz"),
                ids=snap["ids"],
                codes=snap["codes"],
                sq_vmin=self.store.sq_params.vmin,
                sq_scale=self.store.sq_params.scale,
            )
        else:
            snap = self.store.to_host()
            np.savez(
                os.path.join(path, "flat.npz"),
                ids=snap["ids"],
                # f32 on disk: numpy's savez can't serialize ml_dtypes
                # bfloat16, and widening loses nothing
                vectors=np.asarray(snap["vectors"], np.float32),
            )
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(self._save_meta(), f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._check_meta(meta)
        data = np.load(os.path.join(path, "flat.npz"))
        self.store = _new_tier_store(
            self._precision, self.dimension, self.parameter,
            capacity=max(len(data["ids"]), 1),
        )
        # fresh rerank cache sharing the NEW store's lock; rows refill as
        # post-restore writes arrive
        self._init_precision(self.parameter, tier=self._precision)
        if "codes" in data.files:
            from dingo_tpu.ops.sq import SqParams

            self.store.set_params(SqParams(
                np.asarray(data["sq_vmin"], np.float32),
                np.asarray(data["sq_scale"], np.float32),
            ))
            if len(data["ids"]):
                self.store.put_codes(
                    np.asarray(data["ids"], np.int64),
                    np.asarray(data["codes"], np.uint8),
                )
        elif len(data["ids"]):
            self.store.put(np.asarray(data["ids"], np.int64),
                           data["vectors"])
        self.apply_log_id = meta["apply_log_id"]
        self.write_count_since_save = 0
        self._integrity_on_restore(meta)


class BinaryPm1Mixin:
    """Shared bit-packed <-> ±1 codec for binary indexes (TpuBinaryFlat,
    TpuBinaryIvfFlat). dimension is in BITS; wire rows are dimension//8
    uint8. Unpacking happens ONCE at write time into a ±1 int8 store so
    every search is an int8 MXU matmul —
    hamming(a, b) = (nbits - <pm(a), pm(b)>) / 2."""

    dimension: int
    nbytes: int

    def _unpack_pm1(self, packed: np.ndarray) -> np.ndarray:
        bits = np.unpackbits(packed, axis=1, bitorder="little")
        bits = bits[:, : self.dimension]
        return (bits.astype(np.int8) * 2 - 1)

    def _repack(self, pm1: np.ndarray) -> np.ndarray:
        return np.packbits(pm1 > 0, axis=1, bitorder="little")

    def _convert_distances(self, dists: np.ndarray) -> np.ndarray:
        # kernel returned IP of ±1 vectors (descending); hamming ascending
        return (self.dimension - dists) * 0.5

    def _prep_vectors(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, np.uint8)
        if vectors.ndim != 2 or vectors.shape[1] != self.nbytes:
            raise InvalidParameter(f"binary vector shape {vectors.shape}")
        return self._unpack_pm1(vectors)

    def _prep_queries(self, queries: np.ndarray) -> np.ndarray:
        queries = np.asarray(queries, np.uint8)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.shape[1] != self.nbytes:
            raise InvalidParameter(f"binary query shape {queries.shape}")
        return self._unpack_pm1(queries).astype(np.float32)


class TpuBinaryFlat(BinaryPm1Mixin, _SlotStoreIndex):
    """Binary (uint8 bit-packed) exact hamming search — the reference's
    faiss::IndexBinaryFlat variant (vector_index_flat.h binary template
    arm); codec shared via BinaryPm1Mixin."""

    def __init__(self, index_id: int, parameter: IndexParameter):
        super().__init__(index_id, parameter)
        if parameter.dimension <= 0 or parameter.dimension % 8:
            raise InvalidParameter("binary dimension must be multiple of 8")
        self.nbytes = parameter.dimension // 8
        self.store = SlotStore(parameter.dimension, jnp.int8)
        self._kernel_metric = Metric.INNER_PRODUCT
        self._kernel_nbits = 0

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        snap = self.store.to_host()
        np.savez(
            os.path.join(path, "binary_flat.npz"),
            ids=snap["ids"],
            vectors=self._repack(snap["vectors"]),
        )
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(self._save_meta(), f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._check_meta(meta)
        data = np.load(os.path.join(path, "binary_flat.npz"))
        self.store = SlotStore(self.dimension, jnp.int8)
        if len(data["ids"]):
            self.store.put(
                np.asarray(data["ids"], np.int64),
                self._unpack_pm1(np.asarray(data["vectors"], np.uint8)),
            )
        self.apply_log_id = meta["apply_log_id"]
        self.write_count_since_save = 0
        self._integrity_on_restore(meta)


class TpuBruteforce(VectorIndex):
    """Reference VectorIndexBruteforce (vector_index_bruteforce.cc:111):
    holds no data; Search returns EVECTOR_NOT_SUPPORT so VectorReader takes
    the scan+temp-flat path. Kept for index-type parity."""

    def __init__(self, index_id: int, parameter: IndexParameter):
        super().__init__(index_id, parameter)

    def add(self, ids, vectors):  # noqa: D102
        pass

    def upsert(self, ids, vectors):  # noqa: D102
        pass

    def delete(self, ids):  # noqa: D102
        pass

    def search(self, queries, topk, filter_spec=None):
        raise NotSupported("BRUTEFORCE index has no in-memory search")

    def save(self, path):
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({"index_type": self.index_type.value}, f)

    def load(self, path):
        pass

    def get_count(self) -> int:
        return 0

    def get_memory_size(self) -> int:
        return 0
