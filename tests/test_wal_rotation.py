"""WalEngine durability: auto checkpoint rotation bounds restart replay
(round-1 VERDICT weak #10: unbounded WAL replay into dicts)."""

import os

import numpy as np
import pytest

from dingo_tpu.engine.raw_engine import CF_DEFAULT, WalEngine, WriteBatch


def put(engine, key: bytes, value: bytes):
    engine.write(WriteBatch().put(CF_DEFAULT, key, value))


def test_wal_rotates_at_threshold(tmp_path):
    eng = WalEngine(str(tmp_path), checkpoint_threshold_bytes=4096)
    payload = b"x" * 512
    for i in range(64):
        put(eng, f"k{i:04d}".encode(), payload)
    # rotation happened at least once: WAL is far below total written bytes
    assert os.path.getsize(tmp_path / "wal.log") < 8 * 1024
    assert os.path.exists(tmp_path / "checkpoint" / "mem.ckpt")
    eng.close()

    # restart: checkpoint + short WAL tail reproduce every row
    eng2 = WalEngine(str(tmp_path), checkpoint_threshold_bytes=4096)
    for i in range(64):
        assert eng2.get(CF_DEFAULT, f"k{i:04d}".encode()) == payload
    eng2.close()


def test_torn_wal_tail_recovers_prefix(tmp_path):
    eng = WalEngine(str(tmp_path), checkpoint_threshold_bytes=1 << 30)
    for i in range(10):
        put(eng, f"k{i}".encode(), b"v")
    eng.close()
    # simulate a crash mid-append: chop bytes off the tail
    wal = tmp_path / "wal.log"
    data = wal.read_bytes()
    wal.write_bytes(data[:-7])
    eng2 = WalEngine(str(tmp_path))
    assert eng2.get(CF_DEFAULT, b"k8") == b"v"
    assert eng2.get(CF_DEFAULT, b"k9") is None  # torn record dropped
    # engine stays writable after recovery
    put(eng2, b"k9", b"v2")
    assert eng2.get(CF_DEFAULT, b"k9") == b"v2"
    eng2.close()


def test_checkpoint_is_atomic(tmp_path):
    """A crash mid-checkpoint must not destroy the previous checkpoint."""
    eng = WalEngine(str(tmp_path), checkpoint_threshold_bytes=1 << 30)
    put(eng, b"a", b"1")
    eng.checkpoint()
    # leftover temp file from a crashed later checkpoint is ignored
    with open(tmp_path / "checkpoint" / "mem.ckpt.tmp", "wb") as f:
        f.write(b"garbage")
    eng.close()
    eng2 = WalEngine(str(tmp_path))
    assert eng2.get(CF_DEFAULT, b"a") == b"1"
    eng2.close()


def test_store_node_full_restart_recovery(tmp_path):
    """StoreNode.recover(): region meta + raft member + index rebuild from
    a durable engine after restart (main.cc:1074-1076 recovery ordering)."""
    import time

    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.index import codec as vcodec
    from dingo_tpu.index.base import IndexParameter, IndexType
    from dingo_tpu.raft.transport import LocalTransport
    from dingo_tpu.store.node import StoreNode
    from dingo_tpu.store.region import RegionType

    control = CoordinatorControl(MemEngine(), replication=1)
    raw = WalEngine(str(tmp_path), checkpoint_threshold_bytes=16384)
    node = StoreNode("s0", LocalTransport(), control, raw_engine=raw,
                     raft_kw={"seed": 0})
    node.start_heartbeat(0.1)
    d = control.create_region(
        vcodec.encode_vector_key(1, 0), vcodec.encode_vector_key(1, 1 << 30),
        partition_id=1, region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=IndexType.FLAT,
                                       dimension=16),
    )
    time.sleep(1.0)
    region = node.get_region(d.region_id)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((300, 16)).astype(np.float32)
    node.storage.vector_add(region, np.arange(300, dtype=np.int64), x)
    node.stop()
    raw.close()

    raw2 = WalEngine(str(tmp_path), checkpoint_threshold_bytes=16384)
    node2 = StoreNode("s0", LocalTransport(), None, raw_engine=raw2,
                      raft_kw={"seed": 0})
    assert node2.recover() == 1
    time.sleep(0.6)  # single-member raft re-elects
    region2 = node2.get_region(d.region_id)
    res = node2.storage.vector_batch_search(region2, x[:2], 3)
    assert res[0][0].id == 0 and res[1][0].id == 1
    # region is writable again after recovery
    node2.storage.vector_add(region2, np.asarray([900], np.int64), x[:1])
    node2.stop()
    raw2.close()


def test_torn_tail_then_append_survives_second_restart(tmp_path):
    """Review repro: recovery must truncate the torn tail BEFORE appending,
    or post-recovery writes land after garbage and vanish on restart #2."""
    eng = WalEngine(str(tmp_path), checkpoint_threshold_bytes=1 << 30)
    for i in range(5):
        put(eng, f"k{i}".encode(), b"v")
    eng.close()
    wal = tmp_path / "wal.log"
    wal.write_bytes(wal.read_bytes()[:-3])  # torn tail
    eng2 = WalEngine(str(tmp_path))
    put(eng2, b"new", b"acked")             # written after recovery
    eng2.close()
    eng3 = WalEngine(str(tmp_path))         # restart #2
    assert eng3.get(CF_DEFAULT, b"new") == b"acked"
    assert eng3.get(CF_DEFAULT, b"k3") == b"v"
    eng3.close()


def test_raft_log_torn_tail_then_append(tmp_path):
    from dingo_tpu.raft.log import RaftLog

    log = RaftLog(str(tmp_path / "r.log"))
    for i in range(5):
        log.append(1, f"p{i}".encode())
    log.close()
    p = tmp_path / "r.log"
    p.write_bytes(p.read_bytes()[:-3])
    log2 = RaftLog(str(p))
    assert log2.last_index() == 4           # torn record 5 dropped
    log2.append(1, b"after")                # acked post-recovery
    log2.close()
    log3 = RaftLog(str(p))
    assert log3.last_index() == 5
    assert log3.entry_at(5)[1] == b"after"
    log3.close()
