"""RaftStoreEngine: raft-replicated engine.

Reference: src/engine/raft_store_engine.{h,cc} — one RaftNode per region
(raft_node_manager_, raft_store_engine.cc:67,232); Write = propose + wait
(:417-444); reads go straight to the RawEngine (:466+) since committed state
is applied locally. The state machine callback dispatches committed payloads
through the same apply handlers the mono engine uses
(StoreStateMachine::on_apply -> RaftApplyHandlerFactory, §3.2).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from dingo_tpu.engine.apply import apply_write
from dingo_tpu.engine.apply_results import ApplyResultBuffer
from dingo_tpu.engine.raw_engine import ALL_CFS, CF_META, RawEngine, WriteBatch
from dingo_tpu.engine.write_data import WriteData, decode_write, encode_write
from dingo_tpu.raft import wire
from dingo_tpu.index import codec as vcodec
from dingo_tpu.mvcc.codec import Codec
from dingo_tpu.index.vector_reader import ReaderContext, VectorReader
from dingo_tpu.mvcc.codec import MAX_TS
from dingo_tpu.raft.core import RaftNode
from dingo_tpu.raft.transport import Transport
from dingo_tpu.store.region import Region


def _region_bounds(region: Region):
    """Encoded key range of a region in the mvcc-encoded CFs. An empty
    end_key (unbounded region) maps to None — encoding b"" would produce
    the MINIMUM key and make the range empty."""
    start = Codec.encode_bytes(region.definition.start_key)
    end_key = region.definition.end_key
    end = Codec.encode_bytes(end_key) if end_key else None
    return start, end


def region_snapshot(raw: RawEngine, region: Region) -> dict:
    """{cf: [(k, v)]} for this region's range only (meta CF excluded —
    store-local, never replicated)."""
    start, end = _region_bounds(region)
    out = {}
    for cf in ALL_CFS:
        if cf == CF_META:
            continue
        pairs = raw.scan(cf, start, end)
        if pairs:
            out[cf] = pairs
    return out


def region_install(raw: RawEngine, region: Region, state: dict) -> None:
    start, end = _region_bounds(region)
    batch = WriteBatch()
    for cf in ALL_CFS:
        if cf == CF_META:
            continue
        batch.delete_range(cf, start, end)
    for cf, pairs in state.items():
        for k, v in pairs:
            batch.put(cf, k, v)
    raw.write(batch)


class RaftStoreEngine:
    """Holds this store's raw engine + the raft node per hosted region."""

    def __init__(self, raw_engine: RawEngine, store_id: str,
                 transport: Transport, context=None):
        self.raw = raw_engine
        self.store_id = store_id
        self.transport = transport
        #: hosting StoreNode (split handler + topology callbacks)
        self.context = context
        self._lock = threading.Lock()
        self._nodes: Dict[int, RaftNode] = {}   # RaftNodeManager
        self._regions: Dict[int, Region] = {}
        # propose() blocks until the local apply ran, so a proposer can
        # collect its applied outcome (e.g. delete_range counts) right
        # after write() returns; see ApplyResultBuffer for the waiter
        # gating that spares followers/replay the computation
        self._apply_results = ApplyResultBuffer()

    # -- node management (RaftNodeManager / AddNode) -------------------------
    def node_address(self, region_id: int) -> str:
        return f"{self.store_id}/r{region_id}"

    def add_node(self, region: Region, peer_store_ids, log=None,
                 **raft_kw) -> RaftNode:
        """AddNode (raft_store_engine.cc:232): start this region's raft
        member on this store."""
        region_id = region.id

        def apply_fn(index: int, payload: bytes) -> None:
            data = decode_write(payload)
            result = apply_write(
                self.raw, region, data, index, context=self.context,
                want_result=self._apply_results.wanted(region_id, data),
            )
            if result is not None:
                self._apply_results.record(region_id, index, result)

        def snapshot_save() -> bytes:
            # REGION-scoped checkpoint (the reference streams per-region
            # RocksDB SSTs through DingoFileSystemAdaptor): only this
            # region's key range, across all CFs — a store hosts many
            # regions on one raw engine and must not ship the others.
            return wire.encode(region_snapshot(self.raw, region))

        def snapshot_install(blob: bytes) -> None:
            region_install(self.raw, region, wire.decode(blob))
            # in-memory index must be rebuilt after a state install
            wrapper = region.vector_index_wrapper
            if wrapper is not None:
                wrapper.ready = False

        node = RaftNode(
            self.node_address(region_id),
            [f"{sid}/r{region_id}" for sid in peer_store_ids],
            self.transport,
            log=log,
            apply_fn=apply_fn,
            snapshot_save_fn=snapshot_save,
            snapshot_install_fn=snapshot_install,
            **raft_kw,
        )
        with self._lock:
            self._nodes[region_id] = node
            self._regions[region_id] = region
        node.start()
        return node

    def get_node(self, region_id: int) -> Optional[RaftNode]:
        with self._lock:
            return self._nodes.get(region_id)

    def stop_node(self, region_id: int) -> None:
        with self._lock:
            node = self._nodes.pop(region_id, None)
            self._regions.pop(region_id, None)
        if node:
            node.stop()

    def stop(self) -> None:
        with self._lock:
            nodes = list(self._nodes.values())
            self._nodes.clear()
        for n in nodes:
            n.stop()

    # -- Engine::Writer (Write = propose + wait, raft_store_engine.cc:417) ---
    def write(self, region: Region, data: WriteData, timeout: float = 5.0) -> int:
        node = self.get_node(region.id)
        if node is None:
            raise RuntimeError(f"no raft node for region {region.id}")
        payload = encode_write(data)
        waiter = self._apply_results.register_waiter(region.id, data)
        try:
            return node.propose(payload, timeout=timeout)
        finally:
            self._apply_results.unregister_waiter(waiter)

    def take_apply_result(self, region_id: int, log_id: int):
        """Result recorded by this region's apply handler for log_id (None
        if the handler produced none)."""
        return self._apply_results.take(region_id, log_id)

    # -- Engine::VectorReader -------------------------------------------------
    def new_vector_reader(self, region: Region, read_ts: int = MAX_TS) -> VectorReader:
        ctx = ReaderContext(
            region_id=region.id,
            partition_id=region.definition.partition_id,
            start_key=region.definition.start_key,
            end_key=region.definition.end_key,
            index_wrapper=region.vector_index_wrapper,
            engine=self.raw,
            read_ts=read_ts,
            parameter=region.definition.index_parameter,
        )
        return VectorReader(ctx)
