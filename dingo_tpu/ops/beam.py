"""Batched lockstep beam search over a device-resident graph index.

TPU-native HNSW serving (ROADMAP item 5 / ISSUE 8 tentpole): the host
C++ graph (native/hnsw) walks pointers one query at a time; this kernel
walks hundreds of queries in lockstep over the FLATTENED level-0
adjacency — a dense ``[capacity, deg]`` int32 array in slot space
(SlotStore.adj) — so every step is regular gather + matmul + masked
top-k work the MXU/VPU are built for:

  frontier gather    one ``jnp.take`` on the adjacency: [b, beam] beam
                     slots -> [b, beam*deg] candidate slots
  candidate scores   one ``[b, beam*deg] x d`` einsum against the
                     SlotStore rows (bf16 pairs down for the bf16 tier,
                     sq8 decodes on the fly — the PR 4 precision tiers)
  visited set        a per-query PACKED bitmask over capacity
                     ([b, capacity/32] uint32, 1 bit per slot). Marking
                     uses scatter-ADD, which is a correct bitwise OR
                     here: a slot passes the not-yet-visited mask at
                     most once over the whole walk and in-batch
                     duplicates are removed first, so no bit is ever
                     added twice
  dedup              candidates sort by slot id per iteration; repeats
                     (two beam entries sharing an unvisited neighbor)
                     mask to -1 so they cannot burn beam width
  beam update        masked ``lax.top_k`` over old beam + candidates

Termination: a fixed iteration cap (``hnsw.max_iters``) plus an
early-exit-by-convergence flag — a query goes inactive once an
expansion round admits no new candidate into its beam, and the
``lax.while_loop`` stops when every query is inactive. Inactive queries
ride along (lockstep has no partial shapes) but cannot change state.

Filter pushdown (the PR 3 filter-mask cache, applied device-side): the
kernel keeps TWO candidate lists. The ROUTING beam admits any
store-valid node — a filtered-out node must still conduct the walk or
low-selectivity filters would disconnect the graph — while the RESULT
list only ever admits mask-eligible candidates, so masked candidates
never enter the beam the caller reranks and no host post-filter pass
exists. Unfiltered searches pass the validity mask for both and the two
lists coincide.

Returned slots are UNORDERED evidence: the caller reranks them with the
exact device rerank (ops/rerank.py) so final ordering is byte-identical
with the host graph path whenever the candidate sets agree.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from dingo_tpu.obs.sentinel import sentinel_jit


def _candidate_scores(vecs, sqnorm, qd, slots, metric, sq, vmin, scale):
    """'Larger is better' scores [b, C] for candidate slots [b, C] (-1 =
    hole, scored -inf). One gather + one einsum through the SAME metric
    math as the rerank kernels (ops/rerank._scores_from_rows) — the
    byte-identical host/device ordering guarantee depends on it; bf16
    tiers pair the query down, sq8 decodes to the bf16 surrogate, f32
    accumulation everywhere."""
    from dingo_tpu.ops.rerank import _scores_from_rows

    safe = jnp.where(slots >= 0, slots, 0)
    rows = jnp.take(vecs, safe, axis=0)                  # [b, C, d]
    if sq:
        from dingo_tpu.ops.sq import sq_decode_device

        rows = sq_decode_device(rows, vmin, scale)       # bf16 surrogate
    csq = jnp.take(sqnorm, safe)
    scores = _scores_from_rows(rows, csq, qd, metric)
    return jnp.where(slots >= 0, scores, -jnp.inf)


@sentinel_jit("ops.beam.search",
              static_argnames=("beam", "max_iters", "metric", "sq"))
def beam_search(adj, vecs, sqnorm, valid, fmask, queries, entry, vmin,
                scale, beam, max_iters, metric, sq):
    """Lockstep graph walk; see module docstring for the design.

    adj     [cap, deg] int32 slot-space adjacency (-1 padded)
    vecs    [cap, d] rows (f32 / bf16 / uint8 sq codes when sq=True)
    sqnorm  [cap] f32 stored/decoded row norms (SlotStore convention)
    valid   [cap] bool — store validity: gates ROUTING and results
    fmask   [cap] bool — filter pushdown: gates RESULTS only (pass
            `valid` again when unfiltered)
    queries [b, d] f32 (pre-normalized for cosine), entry [] int32
            slot of the graph entry point (-1 = empty graph)
    vmin/scale [d] f32 sq8 codec params (ignored when sq=False)

    Returns (res_slots [b, beam] int32 candidate set (-1 padded,
    unordered — rerank it), hops [b] int32 expansion rounds per query,
    visited [b] int32 marked-slot count, occupancy [b] int32 live
    result entries).
    """
    b, _ = queries.shape
    cap, deg = adj.shape
    nwords = (cap + 31) // 32
    qd = queries.astype(jnp.float32)
    res_ok = valid & fmask
    rowix = jnp.arange(b)[:, None]

    def score(slots):
        return _candidate_scores(
            vecs, sqnorm, qd, slots, metric, sq, vmin, scale
        )

    entry = entry.astype(jnp.int32)
    entry_ok = entry >= 0
    e_safe = jnp.maximum(entry, 0)
    visited = jnp.zeros((b, nwords), jnp.uint32)
    ebit = jnp.where(
        entry_ok,
        jnp.uint32(1) << (e_safe.astype(jnp.uint32) & 31),
        jnp.uint32(0),
    )
    visited = visited.at[
        jnp.arange(b), jnp.broadcast_to(e_safe >> 5, (b,))
    ].add(jnp.broadcast_to(ebit, (b,)))

    # seed: the entry always anchors the ROUTING beam (even when it is
    # tombstoned or filtered out — its neighbors must still be reachable;
    # a -inf score drops it at the first merge, after expansion), and
    # joins the RESULT list only when eligible.
    bslots = jnp.full((b, beam), -1, jnp.int32).at[:, 0].set(
        jnp.where(entry_ok, entry, -1)
    )
    es = score(jnp.broadcast_to(entry, (b, 1)))[:, 0]
    e_elig = entry_ok & jnp.take(res_ok, e_safe)
    bscores = jnp.full((b, beam), -jnp.inf, jnp.float32).at[:, 0].set(
        jnp.where(entry_ok & jnp.take(valid, e_safe), es, -jnp.inf)
    )
    rslots = jnp.full((b, beam), -1, jnp.int32).at[:, 0].set(
        jnp.where(e_elig, entry, -1)
    )
    rscores = jnp.full((b, beam), -jnp.inf, jnp.float32).at[:, 0].set(
        jnp.where(e_elig, es, -jnp.inf)
    )
    active = jnp.broadcast_to(entry_ok, (b,))
    hops = jnp.zeros((b,), jnp.int32)

    def cond(st):
        it, active = st[0], st[6]
        return (it < max_iters) & jnp.any(active)

    def body(st):
        it, bslots, bscores, rslots, rscores, visited, active, hops = st
        hops = hops + active.astype(jnp.int32)
        # 1) frontier gather: every beam entry expands one hop
        safe_b = jnp.where(bslots >= 0, bslots, 0)
        neigh = jnp.take(adj, safe_b, axis=0)            # [b, beam, deg]
        neigh = jnp.where((bslots >= 0)[:, :, None], neigh, -1)
        neigh = neigh.reshape(b, beam * deg)
        # 2) drop holes, already-visited and store-invalid candidates
        ok = neigh >= 0
        safe_n = jnp.where(ok, neigh, 0)
        words = safe_n >> 5
        bits = (safe_n & 31).astype(jnp.uint32)
        seen = (jnp.take_along_axis(visited, words, axis=1) >> bits) & 1
        new = ok & (seen == 0) & jnp.take(valid, safe_n)
        # 3) in-batch dedup: sort by slot (cap sorts holes last), mask
        #    runs — duplicates of one slot carry identical scores, so
        #    keeping the first survivor is exact
        cs = jnp.where(new, safe_n, cap).astype(jnp.int32)
        cs = jnp.sort(cs, axis=1)
        dup = jnp.concatenate(
            [jnp.zeros((b, 1), bool), cs[:, 1:] == cs[:, :-1]], axis=1
        )
        cand = jnp.where((cs < cap) & ~dup, cs, -1)
        # 4) one einsum scores the whole candidate wave
        cscores = score(cand)
        # 5) mark survivors visited (scatter-add == OR: each slot
        #    survives the not-visited mask at most once per walk, and
        #    step 3 removed in-batch repeats)
        csafe = jnp.where(cand >= 0, cand, 0)
        addv = jnp.where(
            cand >= 0,
            jnp.uint32(1) << (csafe.astype(jnp.uint32) & 31),
            jnp.uint32(0),
        )
        visited = visited.at[rowix, csafe >> 5].add(addv)
        # 6) routing-beam merge: any store-valid candidate competes
        mv, mi = lax.top_k(
            jnp.concatenate([bscores, cscores], axis=1), beam
        )
        mslots = jnp.take_along_axis(
            jnp.concatenate([bslots, cand], axis=1), mi, axis=1
        )
        mslots = jnp.where(jnp.isneginf(mv), -1, mslots)
        entered = jnp.any((mi >= beam) & ~jnp.isneginf(mv), axis=1)
        # 7) result merge: masked candidates never enter this beam
        relig = (cand >= 0) & jnp.take(res_ok, csafe)
        rv, ri = lax.top_k(
            jnp.concatenate(
                [rscores, jnp.where(relig, cscores, -jnp.inf)], axis=1
            ),
            beam,
        )
        nrslots = jnp.take_along_axis(
            jnp.concatenate([rslots, cand], axis=1), ri, axis=1
        )
        nrslots = jnp.where(jnp.isneginf(rv), -1, nrslots)
        # 8) convergence: a query with no beam admission is done — every
        #    reachable unvisited node is now worse than its whole beam
        active = active & entered
        return (it + 1, mslots, mv, nrslots, rv, visited, active, hops)

    st = (jnp.int32(0), bslots, bscores, rslots, rscores, visited, active,
          hops)
    st = lax.while_loop(cond, body, st)
    rslots, visited, hops = st[3], st[5], st[7]
    vcount = jnp.sum(
        lax.population_count(visited), axis=1
    ).astype(jnp.int32)
    occ = jnp.sum((rslots >= 0).astype(jnp.int32), axis=1)
    return rslots, hops, vcount, occ
