"""TpuIvfFlat: inverted-file index with TPU k-means training and
bucketed list-scan search.

Reference: VectorIndexIvfFlat (src/vector/vector_index_ivf_flat.{h,cc} —
faiss::IndexIVFFlat with a separately-held quantizer, vector_index_ivf_flat.h:
137; train-data bookkeeping :144-145; untrained search returns
EVECTOR_NOT_SUPPORT so VectorReader falls back to brute force,
vector_reader.cc:1814-1833).

TPU-first design:
  train  — on-device Lloyd k-means (ops/kmeans.py) over a sampled subset
           (max_points_per_centroid * nlist, faiss ClusteringParameters
           convention), deterministic farthest-first init.
  layout — ground truth lives in a flat SlotStore (same arrays as TpuFlat);
           a *bucketed view* [B, cap_list, d] of fixed-width spill buckets
           (ivf_layout.py) is maintained INCREMENTALLY: upserts append
           into free rows of the assigned list's tail bucket via small
           donated scatters, deletes flip the row invalid, and a deferred
           compaction (crontab / threshold-driven, see IvfViewMaintenance)
           restores the dense layout off the hot path. The full rebuild
           survives only as the compaction/restore fallback — a write
           between two searches no longer costs an O(N) host gather.
           cap_list tracks the MEAN list size; long lists spill into extra
           buckets, so HBM is bounded by ~n*d + nlist*cap_list*d
           regardless of assignment skew.
  search — [b, nlist] centroid scores -> top-nprobe coarse lists ->
           on-device expansion to virtual bucket probes -> lax.scan over
           probe ranks: gather one bucket per query per rank
           ([b, cap_list, d] dynamic gather), distance einsum, running
           top-k merge. HBM traffic per query ~ nprobe/nlist of the index
           (vs full scan) — the win IVF exists for. (A Pallas kernel that
           DMAs list tiles and skips unprobed lists is the planned upgrade.)

Semantics parity: untrained index raises NotTrained (reader brute-force
fallback contract); deletes tombstone; adds are accepted before training
(vectors buffer in the SlotStore; assignment happens at train time —
the reference buffers train data similarly).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import jax
import jax.numpy as jnp

from dingo_tpu.obs.sentinel import sentinel_jit
import numpy as np
from jax import lax

from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    InvalidParameter,
    NotTrained,
    SearchResult,
    VectorIndex,
    resolve_precision,
    strip_invalid,
)
from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.index.flat import (
    BinaryPm1Mixin,
    _SlotStoreIndex,
    _pad_batch,
    _resolve_train_cap,
    integrity_mutation,
)
from dingo_tpu.index.ivf_layout import (
    MutableIvfView,
    expand_probes,
    shape_bucket,
)
from dingo_tpu.index.slot_store import SlotStore, _next_pow2
from dingo_tpu.trace import TRACER
from dingo_tpu.ops.distance import (
    Metric,
    np_normalize,
    score_matrix,
    scores_to_distances,
    squared_norms,
)
from dingo_tpu.ops.kmeans import (
    MAX_POINTS_PER_CENTROID,
    kmeans_assign,
    train_kmeans,
)
from dingo_tpu.ops.topk import begin_host_fetch, merge_topk, topk_scores


def coarse_probes(queries, centroids, c_sqnorm, nprobe):
    """Top-nprobe coarse lists per query: [b, nprobe] int32. Plain function
    (shard_map-safe); `_probe_lists` is the jitted wrapper."""
    # Coarse quantizer is always L2 (faiss uses the metric's quantizer, but
    # L2 on normalized data == cosine ordering; IP uses L2 quantizer too in
    # the reference's faiss config).
    d = (
        squared_norms(queries)[:, None]
        - 2.0
        * jnp.einsum(
            "bd,nd->bn",
            queries,
            centroids,
            precision=jax.lax.Precision.HIGHEST,
        )
        + c_sqnorm[None, :]
    )
    _, idx = jax.lax.top_k(-d, nprobe)
    return idx.astype(jnp.int32)


_probe_lists = sentinel_jit("index.ivf.probe_lists", coarse_probes,
                            static_argnames=("nprobe",))


def ivf_scan_scores(
    buckets, bucket_sqnorm, bucket_valid, bucket_slot, probes, queries, k,
    metric, sq_vmin=None, sq_scale=None,
):
    """Scan nprobe bucket ranks per query with a running top-k.

    buckets:     [nlist, cap_list, d]
    bucket_*:    [nlist, cap_list] (sqnorm f32 / valid bool / slot int32)
    probes:      [b, nprobe] int32
    queries:     [b, d]
    sq_*:        [d] SQ8 codec params when buckets hold uint8 codes —
                 gathered buckets decode on the fly (ops/sq.py) with fp32
                 accumulation; bucket_sqnorm then caches DECODED norms
    Returns raw SCORES (descending-better) + slots — shard_map-safe (no
    jit, no distance conversion) so the mesh-sharded IVF can merge scores
    across shards before converting; `_ivf_scan_kernel` is the single-
    device jitted wrapper.
    """
    b = queries.shape[0]
    nprobe = probes.shape[1]
    neg_inf = jnp.float32(-jnp.inf)

    def body(carry, r):
        best_vals, best_slots = carry
        lists_r = jnp.take(probes, r, axis=1)        # [b] (-1 = padded rank)
        rank_ok = lists_r >= 0
        lists_c = jnp.where(rank_ok, lists_r, 0)
        data = jnp.take(buckets, lists_c, axis=0)
        if sq_vmin is None and not jnp.issubdtype(data.dtype, jnp.floating):
            # int8 stores (binary ivf): promote after the gather; float
            # stores (incl. bf16) keep their dtype — the einsum accumulates
            # in f32 via preferred_element_type either way
            data = data.astype(jnp.float32)
        sq = jnp.take(bucket_sqnorm, lists_c, axis=0)
        val = jnp.take(bucket_valid, lists_c, axis=0) & rank_ok[:, None]
        slot = jnp.take(bucket_slot, lists_c, axis=0)
        # per-query distance to its own bucket: einsum over d
        if sq_vmin is not None:
            from dingo_tpu.ops.sq import sq_bucket_scores

            scores = sq_bucket_scores(
                queries, data, sq, sq_vmin, sq_scale, metric
            )
        elif metric is Metric.L2:
            dots = jnp.einsum(
                "bd,bcd->bc", queries, data,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            scores = -(squared_norms(queries)[:, None] - 2.0 * dots + sq)
        else:  # IP / cosine (queries pre-normalized for cosine)
            scores = jnp.einsum(
                "bd,bcd->bc", queries, data,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        scores = jnp.where(val, scores, neg_inf)
        vals_r, idx_r = jax.lax.top_k(scores, min(k, scores.shape[1]))
        slots_r = jnp.take_along_axis(slot, idx_r, axis=1)
        slots_r = jnp.where(jnp.isneginf(vals_r), -1, slots_r)
        best_vals, best_slots = merge_topk(
            best_vals, best_slots, vals_r, slots_r, k
        )
        return (best_vals, best_slots), None

    init = (
        jnp.full((b, k), neg_inf, jnp.float32),
        jnp.full((b, k), -1, jnp.int32),
    )
    (vals, slots), _ = jax.lax.scan(body, init, jnp.arange(nprobe))
    return vals, slots


@sentinel_jit("index.ivf.scan", static_argnames=("k", "metric"))
def _ivf_scan_kernel(
    buckets, bucket_sqnorm, bucket_valid, bucket_slot, probes, queries, k, metric
):
    vals, slots = ivf_scan_scores(
        buckets, bucket_sqnorm, bucket_valid, bucket_slot, probes, queries,
        k, metric,
    )
    return scores_to_distances(vals, metric), slots


@sentinel_jit("index.ivf.scan_sq", static_argnames=("k", "metric"))
def _ivf_scan_kernel_sq(
    buckets, bucket_sqnorm, bucket_valid, bucket_slot, sq_vmin, sq_scale,
    probes, queries, k, metric
):
    """SQ8 variant: buckets hold uint8 codes, decoded on the fly."""
    vals, slots = ivf_scan_scores(
        buckets, bucket_sqnorm, bucket_valid, bucket_slot, probes, queries,
        k, metric, sq_vmin=sq_vmin, sq_scale=sq_scale,
    )
    return scores_to_distances(vals, metric), slots


@sentinel_jit("index.ivf.filter_mask")
def _filter_bucket_mask(slot_mask, bucket_slot):
    """Expand a [capacity] slot mask to [B, cap_list] ON DEVICE. The
    filtered path used to build (and upload) the full bucket-shaped mask
    in numpy per request; uploading the slot-level delta and expanding it
    against the resident bucket_slot map keeps the per-request H2D at
    [capacity] bools."""
    safe = jnp.where(bucket_slot >= 0, bucket_slot, 0)
    return jnp.take(slot_mask, safe, axis=0) & (bucket_slot >= 0)


#: filter-mask cache entries kept per index (distinct live filter shapes
#: per region are few: the region's base id-window plus ad-hoc id sets)
FILTER_CACHE_SIZE = 16


class IvfViewMaintenance:
    """Incremental bucketed-view lifecycle shared by TpuIvfFlat and
    TpuIvfPq: append-in-place upserts, tombstone deletes, deferred
    compaction, the filter-mask cache, and (batch, k, nprobe) shape
    bucketing. Subclasses own the bucket-shaped DATA arrays and implement
    the two hooks `_materialize_view_data` / `_scatter_view_data`.

    Counters/spans (tools/check_metrics_names.py naming contract):
      ivf.inplace_appends / ivf.tombstones / ivf.full_rebuild /
      ivf.compactions counters, ivf.tombstone_ratio gauge; spans
      ivf.append_inplace / ivf.compact / ivf.full_rebuild.
    """

    _view: Optional[MutableIvfView]
    _view_dirty: bool

    # -- hooks (owning index's data arrays) --------------------------------
    def _materialize_view_data(self, view: MutableIvfView) -> None:
        raise NotImplementedError

    def _scatter_view_data(self, upd, rows) -> None:
        raise NotImplementedError

    def _warmup_queries(self, b: int) -> np.ndarray:
        return np.ones((b, self.dimension), np.float32)

    # -- view lifecycle ----------------------------------------------------
    def _ensure_view(self) -> None:
        """Hot-path entry: only (re)builds when there is no usable view —
        steady-state searches find a fresh view and do nothing here."""
        if self._view is None or self._view_dirty:
            self._rebuild_view("search")

    def _rebuild_view(self, reason: str = "search") -> None:
        """Full dense rebuild (build_layout + gather). On the hot path
        this survives only as the restore fallback (first search after
        train/load, or a write batch too large to point-scatter); the
        compaction path runs it deliberately, off the serving path."""
        compacting = reason == "compact"
        name = "ivf.compact" if compacting else "ivf.full_rebuild"
        with TRACER.start_span(name) as span:
            with self.store.device_lock:
                # the WHOLE rebuild under one hold: the host snapshot
                # (assign/valid), the data gather, and the view swap. A
                # write landing mid-rebuild would otherwise be captured by
                # neither the snapshot nor the (orphaned) old view — and
                # nothing would mark the fresh view dirty.
                view = MutableIvfView.build(
                    self._assign_h, self.store.valid_h, self.nlist,
                    self.store.capacity,
                )
                self._materialize_view_data(view)
                self._view = view
                self._view_dirty = False
                self._filter_cache.clear()
            if span.sampled:
                span.set_attr("region_id", self.id)
                span.set_attr("buckets", view.nbuckets)
                span.set_attr("rows", view.live_rows)
        METRICS.counter(
            "ivf.compactions" if compacting else "ivf.full_rebuild",
            region_id=self.id,
        ).add(1)
        self._update_view_gauges()

    def _invalidate_view(self) -> None:
        with self.store.device_lock:
            # lock pairs with the filtered-search path, which iterates
            # _filter_cache under the same lock (an unlocked clear() could
            # land mid-iteration and crash the search)
            self._view_dirty = True
            self._filter_cache.clear()

    def _update_view_gauges(self) -> None:
        v = self._view
        if v is not None:
            METRICS.gauge("ivf.tombstone_ratio", region_id=self.id).set(
                v.tombstone_ratio()
            )

    # -- incremental write path --------------------------------------------
    def _view_apply_upsert(self, slots, assign, rows) -> None:
        from dingo_tpu.ops.scatter import MAX_SCATTER_BATCH

        if len(slots) > MAX_SCATTER_BATCH:
            # batch big enough to amortize a dense rebuild — defer it
            self._invalidate_view()
            return
        with TRACER.start_span("ivf.append_inplace") as span:
            # stage (host bookkeeping) + apply (donated scatters) under
            # ONE device_lock hold: a search dispatching concurrently must
            # never observe staged host state (max_spill, probe chains)
            # ahead of the device arrays it describes. self._view re-read
            # inside the hold: a concurrent compaction may have swapped it.
            with self.store.device_lock:
                view = self._view
                if view is None or self._view_dirty:
                    self._view_dirty = True   # raced with invalidation
                    return
                view.ensure_slot_capacity(self.store.capacity)
                upd = view.stage_upsert(slots, np.asarray(assign))
                if upd is None:               # no-op batch
                    return
                view.apply_device(upd)
                self._scatter_view_data(upd, rows)
            if span.sampled:
                span.set_attr("region_id", self.id)
                span.set_attr("rows", int(len(slots)))
        METRICS.counter("ivf.inplace_appends", region_id=self.id).add(
            len(upd.appended)
        )
        self._update_view_gauges()

    def _view_apply_delete(self, slots) -> None:
        with self.store.device_lock:
            view = self._view
            if view is None or self._view_dirty:
                self._view_dirty = True
                return
            upd = view.stage_delete(slots)
            if upd is None:
                return
            view.apply_device(upd)
        METRICS.counter("ivf.tombstones", region_id=self.id).add(
            len(upd.touched)
        )
        self._update_view_gauges()

    # -- compaction --------------------------------------------------------
    def need_compact(self) -> bool:
        """True when the view accumulated enough garbage (tombstones /
        spill buckets) for the dense rebuild to pay for itself, or a
        deferred full rebuild is pending that the compaction crontab can
        absorb off the hot path."""
        v = self._view
        if v is None:
            return False
        if self._view_dirty:
            return True
        return (
            v.tombstone_ratio() >= FLAGS.get("ivf_compact_tombstone_ratio")
            or v.spill_ratio() >= FLAGS.get("ivf_compact_spill_ratio")
        )

    def compact(self) -> None:
        """Rebuild the dense layout now (O(N); callers keep this OFF the
        serving path — crontab / scrub / tests)."""
        self._rebuild_view("compact")

    def maybe_compact(self) -> bool:
        if self.need_compact():
            self.compact()
            return True
        return False

    def view_stats(self) -> dict:
        out = {"built": self._view is not None, "dirty": self._view_dirty}
        if self._view is not None:
            out.update(self._view.stats())
        return out

    def _heat_layout(self) -> Optional[dict]:
        """Heat-plane layout provider: rows per IVF bucket from the host
        assignment array, priced at this tier's bytes/row. Invoked on
        the heat plane's WORKER thread (<= once per layout TTL), so the
        bincount never rides a serving thread."""
        assign = self._assign_h
        if assign is None:
            return None
        from dingo_tpu.obs.heat import TIER_BYTES

        rows = np.bincount(assign[assign >= 0].astype(np.int64),
                           minlength=self.nlist)
        return {
            "unit_rows": rows,
            "row_bytes": self.dimension * TIER_BYTES.get(
                self._precision, 4.0),
            "tier": self._precision,
            "dim": self.dimension,
        }

    # -- state-integrity: bucket-assignment artifact -----------------------
    def _integrity_assign(self, ids: np.ndarray, assign: np.ndarray) -> None:
        """Fold a write batch's coarse-list assignments into the
        'ivf_buckets' digest (the ledger tracks the assignment TRUTH; the
        scrub reads the device view's arrangement back and compares)."""
        from dingo_tpu.obs.integrity import INTEGRITY

        if len(ids) == 0 or not INTEGRITY.tracking(self):
            return
        ids = np.asarray(ids, np.int64)
        assign = np.asarray(assign, np.int32)
        placed = assign >= 0
        if placed.any():
            INTEGRITY.note_write(self, "ivf_buckets", ids[placed],
                                 assign[placed])

    def _integrity_reset_assign(self) -> None:
        """Rebuild the assignment digest from _assign_h (train/load paths
        reassign every stored row at once)."""
        from dingo_tpu.obs.integrity import INTEGRITY

        if not INTEGRITY.tracking(self):
            return
        INTEGRITY.reset_artifact(self, "ivf_buckets")
        live = np.flatnonzero(self.store.ids_by_slot >= 0)
        if len(live):
            assign = self._assign_h[live].astype(np.int32)
            self._integrity_assign(self.store.ids_by_slot[live], assign)

    # -- filter-mask cache -------------------------------------------------
    def _prep_filter_mask(self, filter_spec: Optional[FilterSpec]):
        """Host-side filter work done OUTSIDE the device lock: fingerprint
        hashing and the O(capacity) numpy slot-mask build can cost
        milliseconds on big include sets, and must not serialize every
        concurrent search/write behind the lock. Returns (fp, version,
        mask_or_None); the in-lock consumer revalidates against the live
        view version and rebuilds in the (rare) raced case."""
        if filter_spec is None or filter_spec.is_empty():
            return None
        view = self._view
        fp = filter_spec.fingerprint()
        ver = view.version if view is not None else -1
        hit = self._filter_cache.get(fp)
        if hit is not None and hit[0] == ver:
            return (fp, ver, None)       # expected cache hit; skip the build
        return (fp, ver, filter_spec.slot_mask(self.store.ids_by_slot))

    def _bucket_valid_for_filter(
        self, filter_spec: Optional[FilterSpec], prep=None
    ):
        """Device validity mask for the scan kernel. Unfiltered searches
        reuse the resident bucket_valid (zero per-request H2D); filtered
        searches hit a (filter-fingerprint, view-version) cache, and a
        miss uploads only the [capacity] slot mask, expanding it on
        device (_filter_bucket_mask). Callers hold store.device_lock;
        pass `prep` from _prep_filter_mask to keep the host work outside
        the hold."""
        view = self._view
        if filter_spec is None or filter_spec.is_empty():
            return view.bucket_valid
        fp, ver, mask = prep if prep is not None else (
            filter_spec.fingerprint(), view.version, None
        )
        hit = self._filter_cache.get(fp)
        if hit is not None and hit[0] == view.version:
            METRICS.counter("ivf.filter_mask_hits", region_id=self.id).add(1)
            return hit[1]
        if mask is None or ver != view.version:
            # raced with a write since prep (or the expected hit was
            # evicted): rebuild against the live host state
            mask = filter_spec.slot_mask(self.store.ids_by_slot)
        bmask = _filter_bucket_mask(jnp.asarray(mask), view.bucket_slot)
        if len(self._filter_cache) >= FILTER_CACHE_SIZE:
            stale = [k for k, (v, _) in self._filter_cache.items()
                     if v != view.version]
            for k in stale:
                del self._filter_cache[k]
            while len(self._filter_cache) >= FILTER_CACHE_SIZE:
                self._filter_cache.pop(next(iter(self._filter_cache)))
        self._filter_cache[fp] = (view.version, bmask)
        METRICS.counter("ivf.filter_mask_misses", region_id=self.id).add(1)
        return bmask

    # -- shape bucketing + warmup ------------------------------------------
    def _shape_buckets(self, topk: int, nprobe: int):
        """(k_eff, nprobe_eff) on the {1, 1.5}x-pow2 ladder so steady-state
        serving reuses a handful of compiled programs. k_eff >= topk
        (resolve slices back); a larger nprobe only adds recall."""
        if not FLAGS.get("ivf_shape_bucketing"):
            return topk, nprobe
        return shape_bucket(topk), min(shape_bucket(nprobe), self.nlist)

    def warmup(self, batches=(1, 8, 64), topk: int = 10,
               nprobe: Optional[int] = None) -> int:
        """Pre-compile the steady-state search programs (one per
        shape-bucketed (batch, k, nprobe) triple) so first real traffic
        never pays an XLA compile. Returns the number of probe searches
        issued."""
        if not self.is_trained():
            return 0
        n = 0
        with TRACER.start_span("ivf.warmup") as span:
            self._ensure_view()
            for bsz in batches:
                self.search(self._warmup_queries(int(bsz)), topk,
                            nprobe=nprobe)
                n += 1
            if span.sampled:
                span.set_attr("searches", n)
        return n


class TpuIvfFlat(IvfViewMaintenance, _SlotStoreIndex):
    #: metric the bucketed scan kernel runs with (the binary subclass scans
    #: with INNER_PRODUCT over ±1 vectors and converts to hamming after)
    _scan_metric: Metric

    def __init__(self, index_id: int, parameter: IndexParameter):
        VectorIndex.__init__(self, index_id, parameter)
        if parameter.dimension <= 0:
            raise InvalidParameter(f"dimension {parameter.dimension}")
        if parameter.ncentroids <= 0:
            raise InvalidParameter(f"ncentroids {parameter.ncentroids}")
        if parameter.metric is Metric.HAMMING and type(self) is TpuIvfFlat:
            raise InvalidParameter("use BINARY_IVF_FLAT for hamming")
        self._scan_metric = parameter.metric
        from dingo_tpu.index.flat import _new_tier_store

        self.store = _new_tier_store(
            resolve_precision(parameter), parameter.dimension, parameter
        )
        self._init_precision(parameter)
        self.nlist = parameter.ncentroids
        self.centroids: Optional[jax.Array] = None       # [nlist, d]
        self._c_sqnorm: Optional[jax.Array] = None
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self._view: Optional[MutableIvfView] = None
        self._buckets = None          # [alloc, cap_list, d]
        self._bucket_sqnorm = None
        self._bucket_bsq = None       # [alloc, nblk, cap_list] prune norms
        self._view_dirty = True
        self._filter_cache: dict = {}

    def _prep_vectors(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dimension:
            raise InvalidParameter(
                f"vector dim {vectors.shape} != {self.dimension}"
            )
        if self.metric is Metric.COSINE:
            vectors = np_normalize(vectors)
        return vectors

    def _prep_queries(self, queries: np.ndarray) -> np.ndarray:
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.shape[1] != self.dimension:
            raise InvalidParameter(
                f"query dim {queries.shape[1]} != {self.dimension}"
            )
        if self.metric is Metric.COSINE:
            queries = np_normalize(queries)
        return queries

    # -- mutation: track assignments ---------------------------------------
    @integrity_mutation
    def upsert(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        vectors = self._prep_vectors(vectors)
        if len(ids) != len(vectors):
            raise InvalidParameter("ids/vectors length mismatch")
        slots = self.store.put(np.asarray(ids, np.int64), vectors)
        self._offer_rerank(slots, vectors)
        from dingo_tpu.obs.quality import QUALITY

        # quality plane: quantized tiers mirror the pre-quantization rows
        # for shadow ground truth (no-op while sampling is off)
        QUALITY.observe_write(self, np.asarray(ids, np.int64), vectors)
        self._integrity_write(ids, vectors)
        if self._assign_h.shape[0] < self.store.capacity:
            grown = np.full((self.store.capacity,), -1, np.int32)
            grown[: self._assign_h.shape[0]] = self._assign_h
            self._assign_h = grown
        if self.is_trained():
            assign = np.asarray(kmeans_assign(jnp.asarray(vectors), self.centroids))
            self._assign_h[slots] = assign
            self._integrity_assign(ids, assign)
            if self._view is not None and not self._view_dirty:
                # incremental append-in-place; the next search reuses the
                # maintained view instead of rebuilding from scratch
                self._view_apply_upsert(slots, assign, vectors)
            else:
                self._invalidate_view()
        else:
            self._view_dirty = True
        self.write_count_since_save += len(ids)

    @integrity_mutation
    def delete(self, ids: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        slots = self.store.remove_slots(ids)
        removed = int((slots >= 0).sum())
        self._invalidate_rerank(slots)
        from dingo_tpu.obs.quality import QUALITY

        QUALITY.observe_delete(self, ids)
        self._integrity_delete(ids)
        if removed:
            if self._view is not None and not self._view_dirty:
                self._view_apply_delete(slots[slots >= 0])
            else:
                self._invalidate_view()
        self.write_count_since_save += removed

    # -- training ----------------------------------------------------------
    def need_train(self) -> bool:
        return True

    def is_trained(self) -> bool:
        return self.centroids is not None

    @integrity_mutation
    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        """Train the coarse quantizer. With no explicit train set, samples
        the stored vectors (VectorIndexManager::TrainForBuild samples the
        region, vector_index_manager.cc:1365)."""
        if vectors is None:
            # implicit path (ISSUE 18b): sample slot indices host-side,
            # gather + decode + normalize on DEVICE — only centroids ever
            # come back to the host. Conf train.sample_rows caps the
            # sample (0 = full corpus, lifting the derived cap too).
            dv = self._train_rows_device(
                MAX_POINTS_PER_CENTROID * self.nlist
            )
            if int(dv.shape[0]) < self.nlist:
                raise NotTrained(
                    f"need >= {self.nlist} train vectors, "
                    f"have {int(dv.shape[0])}"
                )
            if self.metric is Metric.COSINE:
                # stored rows are prep-normalized; quantized tiers decode
                # with drift, so renormalize (the old host path did too)
                dv = dv * jax.lax.rsqrt(jnp.maximum(
                    jnp.sum(dv * dv, axis=1, keepdims=True), 1e-30
                ))
            self.centroids, _ = train_kmeans(
                dv, k=self.nlist, iters=10, seed=self.id
            )
        else:
            if self._precision == "sq8":
                # an explicit train set reaches the codec BEFORE any
                # encode happened — per-dim min/max from the true
                # distribution beats first-batch lazy training
                self.store.maybe_train(self._prep_vectors(vectors))
            vectors = np.asarray(vectors, np.float32)
            if len(vectors) < self.nlist:
                raise NotTrained(
                    f"need >= {self.nlist} train vectors, "
                    f"have {len(vectors)}"
                )
            if self.metric is Metric.COSINE:
                vectors = np_normalize(vectors)
            cap = _resolve_train_cap(MAX_POINTS_PER_CENTROID * self.nlist)
            if cap and len(vectors) > cap:
                sel = np.random.default_rng(self.id).choice(
                    len(vectors), cap, replace=False
                )
                vectors = vectors[sel]
            self.centroids, _ = train_kmeans(
                jnp.asarray(vectors), k=self.nlist, iters=10, seed=self.id
            )
        self._c_sqnorm = squared_norms(self.centroids)
        # (re)assign everything currently stored — device gather, one
        # assign kernel, host copy of the int32 labels only
        live = np.flatnonzero(self.store.ids_by_slot >= 0)
        if len(live):
            vecs = self.store.rows_device(live)
            assign = np.asarray(kmeans_assign(vecs, self.centroids))
            self._assign_h[live] = assign
        self._integrity_reset_assign()
        self._invalidate_view()
        # retrain moves centroids + reassignments: the same query bytes now
        # produce different results with no row having been written, so
        # serving-state version consumers (the serving-edge result cache
        # keys on mutation_version) must see a new version
        self.store.mutation_version += 1

    # -- bucketed view (IvfViewMaintenance data hooks) -----------------------
    def _prune_dim_block(self):
        """Dimension-block width the pruned scan kernel would use for this
        index, or None when pruning cannot apply (flag off, binary ±1
        store, sq8+cosine — the XLA arm divides by the decoded norm, the
        kernel doesn't — or a dimension that doesn't block)."""
        from dingo_tpu.common.config import (
            pallas_ivf_enabled,
            prune_scan_enabled,
        )
        from dingo_tpu.ops.blocked import resolve_dim_block

        # metadata is only worth building where the Pallas route will
        # read it (a flag flip takes effect at the next view rebuild)
        if not pallas_ivf_enabled(self.dimension):
            return None
        if not prune_scan_enabled():
            return None
        if self._scan_metric not in (
            Metric.L2, Metric.INNER_PRODUCT, Metric.COSINE
        ):
            return None
        if self.store.vecs.dtype == jnp.int8:
            return None                       # binary ±1 family stays XLA
        if self._precision == "sq8" and self.metric is Metric.COSINE:
            return None
        return resolve_dim_block(self.dimension)

    def _materialize_view_data(self, view: MutableIvfView) -> None:
        """Dense gather of the whole store into the bucket coordinates —
        the O(N) path, reached only via rebuild/compaction. Caller holds
        device_lock (gather reads store.vecs, which is donatable)."""
        self._buckets = view.gather_rows(self.store.vecs)
        if self._bf16_widen_view():
            # CPU arm of the bf16 tier: rows are already bf16-quantized in
            # the store; widening the SCAN copy once per rebuild dodges
            # XLA CPU's scalar bf16 convert on every probe gather
            self._buckets = self._buckets.astype(jnp.float32)
        self._bucket_sqnorm = view.gather_rows(self.store.sqnorm)
        # pruning metadata: per-dimension-block squared norms of what the
        # scan kernel accumulates (decoded values for sq8 code buckets)
        self._bucket_bsq = None
        dblk = self._prune_dim_block()
        if dblk:
            from dingo_tpu.ops.blocked import bucket_block_sqnorms

            data = self._buckets
            if self._precision == "sq8":
                from dingo_tpu.ops.sq import sq_decode_device

                data = sq_decode_device(
                    data, self.store.sq_vmin_d, self.store.sq_scale_d,
                    jnp.float32,
                )
            self._bucket_bsq = bucket_block_sqnorms(data, dblk)

    def _bf16_widen_view(self) -> bool:
        from dingo_tpu.common.config import bf16_compute_native

        return self._precision == "bf16" and not bf16_compute_native()

    def _scatter_view_data(self, upd, rows) -> None:
        """Apply a staged append batch to the data arrays (caller holds
        device_lock; arrays are donated to the scatter programs)."""
        from dingo_tpu.ops.scatter import pad_buckets, scatter_bucket_update

        if upd.grew_alloc is not None:
            self._buckets = pad_buckets(self._buckets, upd.grew_alloc)
            self._bucket_sqnorm = pad_buckets(
                self._bucket_sqnorm, upd.grew_alloc
            )
            if self._bucket_bsq is not None:
                self._bucket_bsq = pad_buckets(
                    self._bucket_bsq, upd.grew_alloc
                )
        if not upd.appended:
            return
        cap = self._view.cap_list
        pos = np.asarray([p for p, _ in upd.appended], np.int64)
        src = np.asarray([i for _, i in upd.appended], np.int64)
        b_idx = (pos // cap).astype(np.int32)
        r_idx = (pos % cap).astype(np.int32)
        sel = np.asarray(rows)[src]
        if self._precision == "sq8":
            # bucket view mirrors the store: scatter CODES, cache DECODED
            # norms (same codec → bit-identical to the store rows)
            sel = self.store.encode(sel)
            deq = self.store.decode(sel)
            sq = (deq ** 2).sum(axis=1).astype(np.float32)
            norm_rows = deq
        else:
            norm_rows = sel.astype(np.float32)
            if self._precision == "bf16":
                # norms describe the bf16-quantized rows the scan reads
                # (same stored-row convention as slot_store._write_run)
                norm_rows = sel.astype(jnp.bfloat16).astype(np.float32)
                if self._bf16_widen_view():
                    # widened-view arm: quantize through bf16 first so the
                    # f32 scan copy matches the store rows bit-for-bit
                    sel = norm_rows
            sq = (norm_rows ** 2).sum(axis=1)
        self._buckets = scatter_bucket_update(
            self._buckets, b_idx, r_idx, sel
        )
        self._bucket_sqnorm = scatter_bucket_update(
            self._bucket_sqnorm, b_idx, r_idx, sq
        )
        if self._bucket_bsq is not None:
            from dingo_tpu.ops.blocked import block_sqnorms
            from dingo_tpu.ops.scatter import scatter_bucket_dim_update

            dblk = self.dimension // self._bucket_bsq.shape[1]
            bsq_rows = np.asarray(
                block_sqnorms(np.asarray(norm_rows, np.float32), dblk)
            ).T                                            # [n, nblk]
            self._bucket_bsq = scatter_bucket_dim_update(
                self._bucket_bsq, b_idx, r_idx, bsq_rows
            )

    # -- search -------------------------------------------------------------
    def search(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        nprobe: Optional[int] = None,
    ) -> List[SearchResult]:
        return self.search_async(queries, topk, filter_spec, nprobe)()

    def search_async(
        self,
        queries: np.ndarray,
        topk: int,
        filter_spec: Optional[FilterSpec] = None,
        nprobe: Optional[int] = None,
        staged=None,
    ):
        if not self.is_trained():
            raise NotTrained("IVF_FLAT not trained")  # reader falls back
        queries = self._prep_queries(queries)
        self._ensure_view()
        self._count_search()
        b = queries.shape[0]
        topk = int(topk)
        # request-pinned nprobe wins; else the SLO tuner's override; else
        # the configured default (obs/tuner.py walks ladder values only)
        nprobe = min(
            nprobe or self.tuned("nprobe", self.parameter.default_nprobe),
            self.nlist,
        )
        kprime = self._rerank_shortlist(topk)
        k_eff, nprobe = self._shape_buckets(max(topk, kprime or 0), nprobe)
        # staging-ring upload (serving pipeline): claimed only when the
        # identity check proves it was built from THESE queries
        qpad = staged.take(queries) if staged is not None else None
        if qpad is None:
            qpad = jnp.asarray(_pad_batch(queries))
        # lease BEFORE dispatch: kernel slots must stay limbo-parked until
        # resolve translates them (delete+reinsert would misattribute)
        lease = self.store.begin_search()
        try:
            probes = _probe_lists(qpad, self.centroids, self._c_sqnorm, nprobe)
            fprep = self._prep_filter_mask(filter_spec)
            from dingo_tpu.common.config import pallas_ivf_enabled

            # view snapshot + dispatch under the device lock: the
            # incremental write path DONATES bucket arrays to its scatter
            # programs, so a concurrent write must not invalidate a
            # captured reference between here and dispatch (same contract
            # as slot_store.put); reading self._view inside the same hold
            # keeps view metadata and self._buckets consistent
            stats = None
            with self.store.device_lock:
                view = self._view
                vprobes = expand_probes(
                    probes, view.probe_table, nprobe, view.max_spill
                )
                valid = self._bucket_valid_for_filter(filter_spec, fprep)
                # kernel keeps top-k in a 128-lane output block; larger
                # k (and its unrolled select rounds) stays on XLA
                pallas_ok = (
                    pallas_ivf_enabled(self.dimension)
                    and self.metric in (
                        Metric.L2, Metric.INNER_PRODUCT, Metric.COSINE
                    )
                    and k_eff <= 64
                )
                float_store = self.store.vecs.dtype in (
                    jnp.float32, jnp.bfloat16
                )
                if pallas_ok and self._bucket_bsq is not None and (
                    float_store or self._precision == "sq8"
                ):
                    # dimension-blocked early-pruning scan: partial
                    # distances per block, candidates that cannot beat
                    # the running k-th best stop scanning
                    from dingo_tpu.ops.distance import metric_ascending
                    from dingo_tpu.ops.pallas_ivf import ivf_pruned_search

                    sq = self._precision == "sq8"
                    dblk = self.dimension // self._bucket_bsq.shape[1]
                    vals, slots, stats = ivf_pruned_search(
                        vprobes, qpad, self._buckets, self._bucket_bsq,
                        self._bucket_sqnorm, valid, view.bucket_slot,
                        k=k_eff, dim_block=dblk,
                        ascending=metric_ascending(self._scan_metric),
                        sq_vmin=self.store.sq_vmin_d if sq else None,
                        sq_scale=self.store.sq_scale_d if sq else None,
                    )
                    dists = scores_to_distances(vals, self._scan_metric)
                elif pallas_ok and float_store:
                    from dingo_tpu.ops.distance import metric_ascending
                    from dingo_tpu.ops.pallas_ivf import ivf_list_search

                    vals, slots = ivf_list_search(
                        vprobes, qpad, self._buckets, self._bucket_sqnorm,
                        valid, view.bucket_slot, k=k_eff,
                        ascending=metric_ascending(self._scan_metric),
                    )
                    dists = scores_to_distances(vals, self._scan_metric)
                elif self._precision == "sq8":
                    dists, slots = _ivf_scan_kernel_sq(
                        self._buckets,
                        self._bucket_sqnorm,
                        valid,
                        view.bucket_slot,
                        self.store.sq_vmin_d,
                        self.store.sq_scale_d,
                        vprobes,
                        qpad,
                        k=k_eff,
                        metric=self._scan_metric,
                    )
                else:
                    dists, slots = _ivf_scan_kernel(
                        self._buckets,
                        self._bucket_sqnorm,
                        valid,
                        view.bucket_slot,
                        vprobes,
                        qpad,
                        k=k_eff,
                        metric=self._scan_metric,
                    )
                if kprime is not None:
                    # exact rerank of the quantized shortlist against the
                    # device row cache, dispatched under the same lock
                    # (cache arrays share it); still fully async
                    dists, slots = self._dispatch_rerank(
                        qpad, dists, slots, topk
                    )
        except Exception:
            lease.release()
            raise
        if kprime is not None:
            from dingo_tpu.ops.distance import device_wait_span

            # sampled traces time the scan+rerank chain as ops.rerank
            # (outside the lock; no-op for unsampled requests)
            device_wait_span("rerank", (dists, slots))
        store = self.store
        # one-sync epilogue: the whole reply (prune stats included) joins
        # a single D2H copy group; resolve device_gets it exactly once.
        # The heat plane's probed-bucket ids ride the SAME group — the
        # access sketch costs zero extra syncs (resolve-sync contract)
        from dingo_tpu.obs.heat import HEAT, heat_enabled

        heat_on = heat_enabled()
        if heat_on:
            HEAT.register_layout(self.id, "ivf", self._heat_layout)
        fetch = begin_host_fetch(dists, slots, stats,
                                 probes if heat_on else None)
        def resolve() -> List[SearchResult]:
            try:
                fetched = jax.device_get(fetch)
                dists_h, slots_h = fetched[0], fetched[1]
                if stats is not None:
                    # pruned-fraction observability rides the result
                    # fetch — no extra sync on the dispatch path
                    self._note_prune_stats(fetched[2][:b])
                if heat_on:
                    # probed bucket ids = which partitions this batch
                    # actually read (bounded enqueue; folds async)
                    HEAT.observe(self.id, "ivf", fetched[-1][:b])
                # shape bucketing may have run a larger k; slice back
                ids = store.ids_of_slots(slots_h[:b, :topk])
                dists_h = self._convert_distances(dists_h[:b, :topk])
                # head-sampled shadow scoring, attributed to the nprobe
                # bucket actually scanned (async lane; noop at rate 0)
                from dingo_tpu.obs.quality import QUALITY

                QUALITY.observe_search(
                    self, queries, topk, ids, dists_h,
                    bucket=f"nprobe={nprobe}", filter_spec=filter_spec,
                )
                return [strip_invalid(i, d) for i, d in zip(ids, dists_h)]
            finally:
                lease.release()

        return resolve

    # -- lifecycle -----------------------------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        if self._precision == "sq8" and self.store.sq_params is not None:
            snap = self.store.codes_to_host()
            # codes + codec params ride the snapshot exactly like PQ
            # codebooks: bit-exact restore, 1 byte/dim on disk
            snap["sq_vmin"] = self.store.sq_params.vmin
            snap["sq_scale"] = self.store.sq_params.scale
        else:
            snap = self.store.to_host()
            snap["vectors"] = np.asarray(snap["vectors"], np.float32)
        extras = {}
        if self.is_trained():
            extras["centroids"] = np.asarray(self.centroids)
            live = self.store.ids_by_slot >= 0
            extras["assign"] = self._assign_h[np.flatnonzero(live)]
        np.savez(os.path.join(path, "ivf_flat.npz"), **snap, **extras)
        meta = self._save_meta()
        meta["nlist"] = self.nlist
        meta["trained"] = self.is_trained()
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load(self, path: str) -> None:
        from dingo_tpu.index.flat import _new_tier_store

        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._check_meta(meta)
        if meta["nlist"] != self.nlist:
            raise InvalidParameter(
                f"snapshot nlist {meta['nlist']} != {self.nlist}"
            )
        data = np.load(os.path.join(path, "ivf_flat.npz"))
        self.store = _new_tier_store(
            self._precision, self.dimension, self.parameter,
            capacity=max(len(data["ids"]), 1),
        )
        self._init_precision(self.parameter, tier=self._precision)
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self.centroids = None
        self._c_sqnorm = None
        if "codes" in data.files:
            from dingo_tpu.ops.sq import SqParams

            self.store.set_params(SqParams(
                np.asarray(data["sq_vmin"], np.float32),
                np.asarray(data["sq_scale"], np.float32),
            ))
            slots = self.store.put_codes(
                np.asarray(data["ids"], np.int64),
                np.asarray(data["codes"], np.uint8),
            ) if len(data["ids"]) else np.empty(0, np.int64)
        elif len(data["ids"]):
            # bypass upsert's assignment (we restore it directly). Rows on
            # disk came from the store, so cosine rows are ALREADY
            # normalized — re-normalizing drifts low-order bits and would
            # break the snapshot's bit-exact restore-digest verification
            slots = self.store.put(np.asarray(data["ids"], np.int64),
                                   data["vectors"])
        else:
            slots = np.empty(0, np.int64)
        if self._assign_h.shape[0] < self.store.capacity:
            grown = np.full((self.store.capacity,), -1, np.int32)
            grown[: self._assign_h.shape[0]] = self._assign_h
            self._assign_h = grown
        if meta.get("trained"):
            self.centroids = jnp.asarray(data["centroids"])
            self._c_sqnorm = squared_norms(self.centroids)
            self._assign_h[slots] = data["assign"]
        self.apply_log_id = meta["apply_log_id"]
        self._view = None
        self._view_dirty = True
        self._filter_cache.clear()
        self.write_count_since_save = 0
        self._integrity_on_restore(meta)


class TpuBinaryIvfFlat(BinaryPm1Mixin, TpuIvfFlat):
    """Binary (bit-packed) IVF with hamming list scan.

    Reference: faiss::IndexBinaryIVF behind the NewBinaryIVFFlat factory arm
    (vector_index_factory.h:37-68; vector_index_ivf_flat.cc:60-62).
    dimension is in BITS; the wire format is [n, dimension//8] uint8 rows.

    TPU-first: vectors unpack once at write time into a ±1 int8 store (same
    trick as TpuBinaryFlat), so the coarse quantizer is plain float k-means
    over ±1 space and the list scan is an int8 MXU matmul —
    hamming(a, b) = (nbits - <pm(a), pm(b)>) / 2. Centroids stay float
    (fractional centroids order candidate lists strictly better than
    re-binarized ones; faiss quantizes them because CPU hamming is its only
    fast kernel, a constraint the MXU does not have).
    """

    def __init__(self, index_id: int, parameter: IndexParameter):
        if parameter.dimension <= 0 or parameter.dimension % 8:
            raise InvalidParameter("binary dimension must be multiple of 8")
        super().__init__(index_id, parameter)
        self.nbytes = parameter.dimension // 8
        self.store = SlotStore(parameter.dimension, jnp.int8)
        # the ±1 int8 store IS the binary family's quantized form; the
        # float precision tiers don't apply on top of it
        self._precision = "fp32"
        self._rerank_cache = None
        self._scan_metric = Metric.INNER_PRODUCT
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)

    # packed <-> ±1 codec + distance conversion come from BinaryPm1Mixin

    def _warmup_queries(self, b: int) -> np.ndarray:
        return np.ones((b, self.nbytes), np.uint8)   # wire format is packed

    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        """Float k-means over ±1 space. An explicit train set arrives
        bit-packed (the wire format); the implicit path samples the already-
        unpacked store."""
        if vectors is not None:
            vectors = self._prep_vectors(vectors)
        super().train(vectors)

    # -- lifecycle (packed on disk) -----------------------------------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        snap = self.store.to_host()
        extras = {}
        if self.is_trained():
            extras["centroids"] = np.asarray(self.centroids)
            live = self.store.ids_by_slot >= 0
            extras["assign"] = self._assign_h[np.flatnonzero(live)]
        np.savez(
            os.path.join(path, "binary_ivf_flat.npz"),
            ids=snap["ids"],
            vectors=self._repack(snap["vectors"]),
            **extras,
        )
        meta = self._save_meta()
        meta["nlist"] = self.nlist
        meta["trained"] = self.is_trained()
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        self._check_meta(meta)
        if meta["nlist"] != self.nlist:
            raise InvalidParameter(
                f"snapshot nlist {meta['nlist']} != {self.nlist}"
            )
        data = np.load(os.path.join(path, "binary_ivf_flat.npz"))
        self.store = SlotStore(self.dimension, jnp.int8,
                               max(len(data["ids"]), 1))
        self._assign_h = np.full((self.store.capacity,), -1, np.int32)
        self.centroids = None
        self._c_sqnorm = None
        if len(data["ids"]):
            slots = self.store.put(
                np.asarray(data["ids"], np.int64),
                self._unpack_pm1(np.asarray(data["vectors"], np.uint8)),
            )
        else:
            slots = np.empty(0, np.int64)
        if self._assign_h.shape[0] < self.store.capacity:
            grown = np.full((self.store.capacity,), -1, np.int32)
            grown[: self._assign_h.shape[0]] = self._assign_h
            self._assign_h = grown
        if meta.get("trained"):
            self.centroids = jnp.asarray(data["centroids"])
            self._c_sqnorm = squared_norms(self.centroids)
            self._assign_h[slots] = data["assign"]
        self.apply_log_id = meta["apply_log_id"]
        self._view = None
        self._view_dirty = True
        self._filter_cache.clear()
        self.write_count_since_save = 0
        self._integrity_on_restore(meta)
