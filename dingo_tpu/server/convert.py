"""protobuf <-> internal object conversions."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from dingo_tpu.coprocessor.scalar_filter import CmpOp, ScalarFilter, ScalarPredicate
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.index.vector_reader import VectorFilterMode, VectorFilterType
from dingo_tpu.ops.distance import Metric
from dingo_tpu.server import pb
from dingo_tpu.store.region import RegionDefinition, RegionEpoch, RegionType
from dingo_tpu.raft import wire

_METRIC_TO_PB = {
    Metric.L2: pb.METRIC_TYPE_L2,
    Metric.INNER_PRODUCT: pb.METRIC_TYPE_INNER_PRODUCT,
    Metric.COSINE: pb.METRIC_TYPE_COSINE,
    Metric.HAMMING: pb.METRIC_TYPE_HAMMING,
}
_PB_TO_METRIC = {v: k for k, v in _METRIC_TO_PB.items()}

_ITYPE_TO_PB = {
    IndexType.FLAT: pb.VECTOR_INDEX_TYPE_FLAT,
    IndexType.IVF_FLAT: pb.VECTOR_INDEX_TYPE_IVF_FLAT,
    IndexType.IVF_PQ: pb.VECTOR_INDEX_TYPE_IVF_PQ,
    IndexType.HNSW: pb.VECTOR_INDEX_TYPE_HNSW,
    IndexType.DISKANN: pb.VECTOR_INDEX_TYPE_DISKANN,
    IndexType.BRUTEFORCE: pb.VECTOR_INDEX_TYPE_BRUTEFORCE,
    IndexType.BINARY_FLAT: pb.VECTOR_INDEX_TYPE_BINARY_FLAT,
    IndexType.BINARY_IVF_FLAT: pb.VECTOR_INDEX_TYPE_BINARY_IVF_FLAT,
}
_PB_TO_ITYPE = {v: k for k, v in _ITYPE_TO_PB.items()}

_FILTER_TO_MODE = {
    pb.VECTOR_FILTER_NONE: VectorFilterMode.NONE,
    pb.SCALAR_FILTER: VectorFilterMode.SCALAR,
    pb.TABLE_FILTER: VectorFilterMode.TABLE,
    pb.VECTOR_ID_FILTER: VectorFilterMode.VECTOR_ID,
}


def index_parameter_to_pb(p: Optional[IndexParameter]) -> pb.VectorIndexParameter:
    out = pb.VectorIndexParameter()
    if p is None:
        return out
    out.index_type = _ITYPE_TO_PB[p.index_type]
    out.dimension = p.dimension
    out.metric_type = _METRIC_TO_PB[p.metric]
    out.ncentroids = p.ncentroids
    out.nsubvector = p.nsubvector
    out.nbits_per_idx = p.nbits_per_idx
    out.default_nprobe = p.default_nprobe
    out.efconstruction = p.efconstruction
    out.nlinks = p.nlinks
    out.host_vectors = p.host_vectors
    out.scalar_speedup_keys.extend(p.scalar_speedup_keys)
    out.precision = p.precision
    return out


def index_parameter_from_pb(m: pb.VectorIndexParameter) -> Optional[IndexParameter]:
    if m.index_type == pb.VECTOR_INDEX_TYPE_NONE:
        return None
    return IndexParameter(
        index_type=_PB_TO_ITYPE[m.index_type],
        dimension=m.dimension,
        metric=_PB_TO_METRIC.get(m.metric_type, Metric.L2),
        ncentroids=m.ncentroids or 2048,
        nsubvector=m.nsubvector or 64,
        nbits_per_idx=m.nbits_per_idx or 8,
        default_nprobe=m.default_nprobe or 80,
        efconstruction=m.efconstruction or 200,
        nlinks=m.nlinks or 32,
        host_vectors=m.host_vectors,
        scalar_speedup_keys=tuple(m.scalar_speedup_keys),
        precision=m.precision,
    )


def region_def_to_pb(d: RegionDefinition) -> pb.RegionDefinition:
    out = pb.RegionDefinition()
    out.region_id = d.region_id
    out.epoch.conf_version = d.epoch.conf_version
    out.epoch.version = d.epoch.version
    out.range.start_key = d.start_key
    out.range.end_key = d.end_key
    out.partition_id = d.partition_id
    out.peers.extend(d.peers)
    out.region_type = {"store": 0, "index": 1, "document": 2}[d.region_type.value]
    out.index_parameter.CopyFrom(index_parameter_to_pb(d.index_parameter))
    for name, ftype in (d.document_schema or {}).items():
        col = out.document_schema.add()
        col.name = name
        col.sql_type = ftype
    return out


def region_def_from_pb(m: pb.RegionDefinition) -> RegionDefinition:
    return RegionDefinition(
        region_id=m.region_id,
        start_key=m.range.start_key,
        end_key=m.range.end_key,
        partition_id=m.partition_id,
        peers=list(m.peers),
        epoch=RegionEpoch(m.epoch.conf_version or 1, m.epoch.version or 1),
        region_type=[RegionType.STORE, RegionType.INDEX,
                     RegionType.DOCUMENT][m.region_type],
        index_parameter=index_parameter_from_pb(m.index_parameter),
        document_schema=(
            {c.name: c.sql_type for c in m.document_schema}
            if m.document_schema else None
        ),
    )


def scalar_to_pb(entries, scalar: Optional[Dict[str, Any]]) -> None:
    for k, v in (scalar or {}).items():
        e = entries.add()
        e.key = k
        e.value = wire.encode_obj(v)


def scalar_from_pb(entries) -> Dict[str, Any]:
    return {e.key: wire.decode_obj(e.value) for e in entries}


def predicates_from_pb(preds) -> Optional[ScalarFilter]:
    if not preds:
        return None
    return ScalarFilter([
        ScalarPredicate(p.field, CmpOp(p.op), wire.decode_obj(p.value))
        for p in preds
    ])


def search_kwargs_from_pb(param: pb.VectorSearchParameter) -> dict:
    kw: dict = {
        "filter_mode": _FILTER_TO_MODE.get(param.filter, VectorFilterMode.NONE),
        "filter_type": (
            VectorFilterType.QUERY_PRE
            if param.filter_type == pb.QUERY_PRE
            else VectorFilterType.QUERY_POST
        ),
        "with_vector_data": param.with_vector_data,
        "with_scalar_data": param.with_scalar_data,
    }
    if param.vector_ids:
        kw["vector_ids"] = list(param.vector_ids)
    sf = predicates_from_pb(param.predicates)
    if sf is not None:
        kw["scalar_filter"] = sf
    cop = coprocessor_from_pb(param.coprocessor)
    if cop is not None:
        kw["coprocessor"] = cop
    return kw


def region_cmd_from_pb(c):
    """pb.RegionCmd -> coordinator RegionCmd (single source of truth for
    the three command-delivery paths: push, requeue, remote heartbeat)."""
    from dingo_tpu.coordinator.control import RegionCmd, RegionCmdType

    return RegionCmd(
        cmd_id=c.cmd_id,
        region_id=c.region_id,
        cmd_type=RegionCmdType(c.cmd_type),
        definition=(region_def_from_pb(c.definition)
                    if c.definition.region_id else None),
        split_key=c.split_key,
        child_region_id=c.child_region_id,
        target_store_id=c.target_store_id,
    )


def fill_vector_pb(vector_pb, row: np.ndarray) -> None:
    """Emit a stored row into a Vector message: packed uint8 rows go to
    binary_values, float rows to values."""
    if row.dtype == np.uint8:
        vector_pb.binary_values = row.tobytes()
    else:
        vector_pb.values.extend(row.tolist())


def queries_from_pb(vectors, binary: bool = False) -> np.ndarray:
    if binary:
        return np.stack([
            np.frombuffer(v.binary_values, np.uint8) for v in vectors
        ])
    return np.asarray([list(v.values) for v in vectors], np.float32)


def is_binary_parameter(param) -> bool:
    from dingo_tpu.index.vector_reader import is_binary_dim_param

    return is_binary_dim_param(param)


def coprocessor_from_pb(m) -> "object | None":
    """pb.Coprocessor -> CoprocessorV2 (None when the field is unset)."""
    if not m.original_schema:
        return None
    from dingo_tpu.coprocessor.coprocessor_v2 import (
        AggOpV2,
        AggregationSpec,
        CoprocessorDef,
        CoprocessorV2,
        SchemaColumn,
    )

    if m.projections:
        selection = []
        for p in m.projections:
            if p.expr:
                tree = wire.decode(p.expr)
                if not isinstance(tree, (list, tuple)):
                    # a scalar here would be silently taken as a column
                    # index by CoprocessorDef — reject the malformed expr
                    raise ValueError(f"projection expr is not a tree: {tree!r}")
                selection.append(tree)
            else:
                selection.append(p.column_index)
    else:
        selection = list(m.selection)
    defn = CoprocessorDef(
        original_schema=[
            SchemaColumn(c.name, c.sql_type or "VARCHAR", c.index)
            for c in m.original_schema
        ],
        selection=selection,
        filter_expr=wire.decode(m.filter_expr) if m.filter_expr else None,
        group_by=list(m.group_by),
        aggregations=[
            AggregationSpec(
                AggOpV2(a.op), a.column_index,
                expr=wire.decode(a.expr) if a.expr else None,
            )
            for a in m.aggregations
        ],
    )
    return CoprocessorV2(defn)


# ---------------- store metrics (heartbeat payload) ----------------

_REGION_METRIC_FIELDS = (
    "region_id", "key_count", "approximate_bytes", "vector_count",
    "vector_memory_bytes", "device_memory_bytes", "index_ready",
    "index_building", "index_build_error", "index_apply_log_id",
    "index_snapshot_log_id", "apply_lag", "is_leader", "search_qps",
    "document_count", "device_peak_bytes",
    # quality plane (obs/quality.py): windowed live recall + Wilson CI;
    # quality_samples == 0 means the figures carry no evidence
    "quality_recall", "quality_recall_ci_low", "quality_recall_ci_high",
    "quality_samples",
    # serving-pressure plane (obs/pressure.py): queue depth / recent
    # queue-wait watermark / cumulative shed+expired / degrade level
    "qos_queue_depth", "qos_queue_wait_ms", "qos_shed_total",
    "qos_degrade_level",
    # state-integrity plane (obs/integrity.py): applied-index-tagged
    # per-artifact digest vector + store-local scrub verdict
    "integrity_applied_index", "integrity_digests", "integrity_mismatch",
    "device_degraded",
    # serving-edge cache (dingo_tpu/cache/): hit/miss rollup + entries
    "cache_hits", "cache_misses", "cache_entries",
    # workload-heat plane (obs/heat.py): traffic concentration + the
    # {50,90,99}% working-set bytes at the region's own tier; touches
    # == 0 means no evidence. Feeds the coordinator's capacity rollups
    "heat_hot_fraction", "heat_gini", "heat_working_set_p50",
    "heat_working_set_p90", "heat_working_set_p99", "heat_touches",
    # per-shape cost model (obs/cost.py): EWMA per-row dispatch cost µs
    "cost_row_us",
    # memory-tier ladder (index/tiering.py): serving rung name
    "serving_tier",
    # control-plane flight recorder (obs/events.py): live-overrides JSON
    "live_knobs",
)

_STORE_METRIC_FIELDS = (
    "store_id", "collected_at_ms", "device_bytes_in_use",
    "device_bytes_limit", "device_peak_bytes", "engine_key_count",
)

# control-plane decision events (obs/events.Event <-> pb.ControlEvent);
# same field names on both sides, all scalars
_CONTROL_EVENT_FIELDS = (
    "actor", "region_id", "knob", "old", "new", "trigger", "evidence",
    "ts_ms", "actor_seq", "node_id", "trace_id", "flight_bundle_id",
)


def control_event_to_pb(ev, out: Optional[pb.ControlEvent] = None
                        ) -> pb.ControlEvent:
    out = out if out is not None else pb.ControlEvent()
    for f in _CONTROL_EVENT_FIELDS:
        v = getattr(ev, f)
        # old/new are free-typed on the ledger Event (ints, floats, rung
        # names, None); the wire carries strings
        if f in ("old", "new"):
            v = "" if v is None else str(v)
        setattr(out, f, v)
    return out


def control_event_from_pb(m: pb.ControlEvent):
    from dingo_tpu.obs.events import Event

    return Event(**{f: getattr(m, f) for f in _CONTROL_EVENT_FIELDS})


def region_metrics_to_pb(rm, out: Optional[pb.RegionMetrics] = None
                         ) -> pb.RegionMetrics:
    out = out if out is not None else pb.RegionMetrics()
    for f in _REGION_METRIC_FIELDS:
        setattr(out, f, getattr(rm, f))
    return out


def region_metrics_from_pb(m: pb.RegionMetrics):
    from dingo_tpu.metrics.snapshot import RegionMetricsSnapshot

    return RegionMetricsSnapshot(
        **{f: getattr(m, f) for f in _REGION_METRIC_FIELDS}
    )


def store_metrics_to_pb(snap, out: Optional[pb.StoreMetrics] = None
                        ) -> pb.StoreMetrics:
    out = out if out is not None else pb.StoreMetrics()
    for f in _STORE_METRIC_FIELDS:
        setattr(out, f, getattr(snap, f))
    for rm in snap.regions:
        region_metrics_to_pb(rm, out.regions.add())
    for ev in getattr(snap, "events", ()):
        control_event_to_pb(ev, out.events.add())
    return out


def store_metrics_from_pb(m: pb.StoreMetrics):
    from dingo_tpu.metrics.snapshot import StoreMetricsSnapshot

    snap = StoreMetricsSnapshot(
        **{f: getattr(m, f) for f in _STORE_METRIC_FIELDS}
    )
    snap.regions = [region_metrics_from_pb(r) for r in m.regions]
    snap.events = [control_event_from_pb(e) for e in m.events]
    return snap
