"""CoprocessorV2: typed-schema pushdown over serial-encoded table rows.

Reference: src/coprocessor/coprocessor_v2.{h,cc} — holds original/result
serial schemas + selection column indexes (coprocessor_v2.h:102-111), runs
rel-expression bytecode (rel::RelRunner from dingo-libexpr,
coprocessor_v2.cc:209-216) against each decoded row during a scan, then
projects (selection) and optionally aggregates (AggregationManager,
aggregation.h). This module plays the same role over dingo_tpu's pieces:
`common/serial.py` typed rows, the `coprocessor/expr.py` VM as the
expression engine, and a grouped aggregation manager.

Row wire format: a row VALUE is the concatenation of `serial.encode_value`
for each column in schema order (order-preserving typed encoding, so rows
are also memcomparable per column).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from dingo_tpu.common import serial
from dingo_tpu.coprocessor.expr import Expr


class CoprocessorError(ValueError):
    pass


@dataclasses.dataclass(frozen=True)
class SchemaColumn:
    name: str
    sql_type: str = "VARCHAR"    # BIGINT/DOUBLE/VARCHAR/BOOL/BYTES
    index: int = 0


class AggOpV2(enum.Enum):
    """AggregationManager operator set (aggregation.h)."""

    SUM = 1
    COUNT = 2
    COUNT_WITH_NULL = 3
    MAX = 4
    MIN = 5
    SUM0 = 6     # like SUM but 0 (not NULL) over an empty group


@dataclasses.dataclass
class AggregationSpec:
    op: AggOpV2
    column_index: int            # original-schema column; -1 for COUNT(*)


@dataclasses.dataclass
class CoprocessorDef:
    """pb::store::Coprocessor analog."""

    original_schema: List[SchemaColumn]
    selection: List[int] = dataclasses.field(default_factory=list)
    filter_expr: Optional[list] = None          # expr.py wire tree
    group_by: List[int] = dataclasses.field(default_factory=list)
    aggregations: List[AggregationSpec] = dataclasses.field(
        default_factory=list
    )


def encode_row(values: Sequence[Any]) -> bytes:
    """Row value bytes: concatenated typed encodings in schema order."""
    return b"".join(serial.encode_value(v) for v in values)


def decode_row(blob: bytes, ncols: int) -> List[Any]:
    out, offset = [], 0
    for _ in range(ncols):
        v, offset = serial.decode_value(blob, offset)
        out.append(v)
    return out


class _Group:
    __slots__ = ("accs", "counts")

    def __init__(self, n: int):
        self.accs: List[Any] = [None] * n
        self.counts = [0] * n


class CoprocessorV2:
    """Filter -> project | group+aggregate over decoded rows."""

    def __init__(self, defn: CoprocessorDef):
        self.defn = defn
        ncols = len(defn.original_schema)
        for idx in defn.selection + defn.group_by:
            if not 0 <= idx < ncols:
                raise CoprocessorError(f"column index {idx} out of range")
        for a in defn.aggregations:
            if a.column_index >= ncols or a.column_index < -1:
                # -1 is the COUNT(*) sentinel; anything else negative is a
                # caller bug that would silently aggregate the literal 1
                raise CoprocessorError(
                    f"aggregation column {a.column_index} out of range"
                )
        self._names = [c.name for c in defn.original_schema]
        self._expr = (
            Expr(defn.filter_expr) if defn.filter_expr is not None else None
        )

    # -- row-at-a-time (RawCoprocessor::Filter contract) ---------------------
    def decode(self, value: bytes) -> List[Any]:
        return decode_row(value, len(self.defn.original_schema))

    def filter_row(self, row: List[Any]) -> bool:
        if self._expr is None:
            return True
        fields = dict(zip(self._names, row))
        try:
            return bool(self._expr.eval(fields))
        except TypeError:
            # SQL WHERE semantics: a NULL operand makes the predicate
            # unknown, and unknown rows are not selected
            return False

    def project(self, row: List[Any]) -> List[Any]:
        if not self.defn.selection:
            return row
        return [row[i] for i in self.defn.selection]

    # -- scan execution (CoprocessorV2::Execute contract) --------------------
    def execute(
        self, kvs: Iterable[Tuple[bytes, bytes]], limit: int = 0
    ) -> List[Tuple[bytes, bytes]]:
        """Run over scan output. Without aggregations: (key, projected-row)
        for rows passing the filter, stopping at `limit` matches (0 =
        unlimited). With aggregations: one row per group (limit applies to
        the grouped output), key = encoded group-by values (b"" for the
        global group)."""
        if not self.defn.aggregations:
            out = []
            for k, v in kvs:
                row = self.decode(v)
                if self.filter_row(row):
                    out.append((k, encode_row(self.project(row))))
                    if limit and len(out) >= limit:
                        break
            return out

        groups: Dict[bytes, _Group] = {}
        nagg = len(self.defn.aggregations)
        for _k, v in kvs:
            row = self.decode(v)
            if not self.filter_row(row):
                continue
            gkey = encode_row([row[i] for i in self.defn.group_by])
            g = groups.get(gkey)
            if g is None:
                g = groups[gkey] = _Group(nagg)
            for i, spec in enumerate(self.defn.aggregations):
                val = row[spec.column_index] if spec.column_index >= 0 else 1
                op = spec.op
                if op is AggOpV2.COUNT_WITH_NULL:
                    g.counts[i] += 1
                    continue
                if val is None:
                    continue
                g.counts[i] += 1
                acc = g.accs[i]
                if op in (AggOpV2.SUM, AggOpV2.SUM0):
                    g.accs[i] = val if acc is None else acc + val
                elif op is AggOpV2.COUNT:
                    pass  # counts[i] carries it
                elif op is AggOpV2.MAX:
                    g.accs[i] = val if acc is None else max(acc, val)
                elif op is AggOpV2.MIN:
                    g.accs[i] = val if acc is None else min(acc, val)
        out = []
        for gkey in sorted(groups):
            g = groups[gkey]
            row_out: List[Any] = []
            for i, spec in enumerate(self.defn.aggregations):
                if spec.op in (AggOpV2.COUNT, AggOpV2.COUNT_WITH_NULL):
                    row_out.append(g.counts[i])
                elif spec.op is AggOpV2.SUM0:
                    row_out.append(0 if g.accs[i] is None else g.accs[i])
                else:
                    row_out.append(g.accs[i])
            out.append((gkey, encode_row(row_out)))
        return out[:limit] if limit else out
