"""Raft-replicated coordinator (raft_meta.py) — VERDICT r3 Next #3.

Covers: replicated mutations on all replicas, NotLeader routing, TSO
monotonicity across failover, leader-crash-mid-split completion by a
survivor, exactly-once replay after restart, and snapshot-install catch-up.
Reference semantics: coordinator_control.h:218 SubmitMetaIncrementSync +
src/raft/meta_state_machine.h.
"""

import time

import pytest

from dingo_tpu.coordinator.raft_meta import RaftMetaCoordinator
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.raft.core import NotLeader
from dingo_tpu.raft.log import RaftLog
from dingo_tpu.raft.transport import LocalTransport
from dingo_tpu.store.region import RegionType

FAST = dict(election_timeout=(0.05, 0.12), heartbeat_interval=0.02)


def make_cluster(n=3, transport=None, engines=None, logs=None, **raft_kw):
    transport = transport or LocalTransport()
    ids = [f"coor{i}" for i in range(n)]
    coords = []
    for i in range(n):
        coords.append(RaftMetaCoordinator(
            ids[i], ids, transport,
            engines[i] if engines else MemEngine(),
            log=logs[i] if logs else None,
            **{**FAST, **raft_kw},
        ))
    for c in coords:
        c.start()
    return transport, coords


def wait_leader(coords, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [c for c in coords if c.is_leader()]
        if leaders:
            return leaders[0]
        time.sleep(0.01)
    raise AssertionError("no coordinator leader elected")


def wait_converged(coords, fn, expect, timeout=5.0):
    """Wait until fn(coordinator) == expect on every live replica."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(fn(c) == expect for c in coords):
            return
        time.sleep(0.01)
    got = [fn(c) for c in coords]
    raise AssertionError(f"replicas did not converge: {got} != {expect}")


def stop_all(coords):
    for c in coords:
        try:
            c.stop()
        except Exception:
            pass


def test_replicated_create_region_visible_on_all_replicas():
    _, coords = make_cluster()
    try:
        leader = wait_leader(coords)
        for sid in ("s1", "s2", "s3"):
            leader.control.register_store(sid, f"addr-{sid}")
        definition = leader.control.create_region(b"a", b"m")
        rid = definition.region_id
        wait_converged(coords, lambda c: rid in c.sm.control.regions, True)
        # identical placement + queued CREATE cmds everywhere
        for c in coords:
            assert c.sm.control.regions[rid].peers == definition.peers
            queued = [cmd.cmd_id for q in c.sm.control.store_ops.values()
                      for cmd in q if cmd.region_id == rid]
            assert len(queued) == 3
    finally:
        stop_all(coords)


def test_follower_mutation_raises_not_leader_with_hint():
    _, coords = make_cluster()
    try:
        leader = wait_leader(coords)
        follower = next(c for c in coords if c is not leader)
        # follower must know who leads before the hint is useful
        deadline = time.monotonic() + 3
        while follower.leader_hint() is None and time.monotonic() < deadline:
            time.sleep(0.01)
        with pytest.raises(NotLeader) as exc:
            follower.control.register_store("s9")
        assert exc.value.leader_hint == leader.node.id
    finally:
        stop_all(coords)


def test_tso_never_regresses_across_failover():
    _, coords = make_cluster()
    try:
        leader = wait_leader(coords)
        issued = []
        for _ in range(5):
            first, count = leader.tso.gen_ts(100)
            issued.append(first + count - 1)
        assert issued == sorted(issued)
        leader.stop()
        survivors = [c for c in coords if c is not leader]
        new_leader = wait_leader(survivors)
        first, count = new_leader.tso.gen_ts(1)
        assert first > issued[-1], (
            f"TSO regressed after failover: {first} <= {issued[-1]}"
        )
    finally:
        stop_all(coords)


def test_leader_crash_mid_split_survivor_completes():
    """The VERDICT gate: kill the coordinator leader mid-split; a survivor
    must deliver the SPLIT cmd (even though the dead leader marked it
    'sent') and absorb the split-done report."""
    _, coords = make_cluster()
    try:
        leader = wait_leader(coords)
        for sid in ("s1", "s2", "s3"):
            leader.control.register_store(sid)
        definition = leader.control.create_region(b"a", b"z")
        rid = definition.region_id
        # drain the CREATE cmds so only the SPLIT remains pending
        for sid in ("s1", "s2", "s3"):
            leader.control.store_heartbeat(sid, region_ids=[rid])
        leader.control.store_heartbeat("s1", region_ids=[rid],
                                       leader_region_ids=[rid])
        child_id = leader.control.split_region(rid, b"m")
        # the dead-leader-marked-'sent' window: deliver once, don't execute
        sent = leader.control.store_heartbeat("s1", region_ids=[rid],
                                              leader_region_ids=[rid])
        assert any(c.cmd_type.value == "split" for c in sent)
        leader.stop()

        survivors = [c for c in coords if c is not leader]
        new_leader = wait_leader(survivors)
        # survivor re-arms 'sent' cmds on election and re-delivers
        deadline = time.monotonic() + 5
        redelivered = []
        while time.monotonic() < deadline and not redelivered:
            redelivered = [
                c for c in new_leader.control.store_heartbeat(
                    "s1", region_ids=[rid], leader_region_ids=[rid])
                if c.cmd_type.value == "split"
            ]
            time.sleep(0.02)
        assert redelivered, "survivor never re-delivered the split cmd"
        split = redelivered[0]
        assert split.child_region_id == child_id

        # store executes the split and reports done to the NEW leader
        import dataclasses
        child_def = dataclasses.replace(
            definition, region_id=child_id, start_key=b"m", end_key=b"z",
        )
        new_leader.control.on_region_split_done(rid, child_def)
        wait_converged(
            survivors, lambda c: child_id in c.sm.control.regions, True
        )
        assert new_leader.sm.control.regions[rid].end_key == b"m"
    finally:
        stop_all(coords)


def test_restart_replays_exactly_once(tmp_path):
    """Re-applying a create_region on restart would allocate fresh ids and
    fork the replica — the applied-index marker must prevent it."""
    transport = LocalTransport()
    engine = MemEngine()
    log = RaftLog(str(tmp_path / "meta.log"))
    c = RaftMetaCoordinator("coor0", ["coor0"], transport, engine,
                            log=log, **FAST)
    c.start()
    try:
        leader = wait_leader([c])
        leader.control.register_store("s1")
        r1 = leader.control.create_region(b"a", b"b", replication=1)
        r2 = leader.control.create_region(b"b", b"c", replication=1)
        next_id = leader.sm.control._next_region_id
    finally:
        c.stop()

    # restart over the same engine + log: entries replay, marker skips them
    c2 = RaftMetaCoordinator("coor0", ["coor0"], transport, engine,
                             log=RaftLog(str(tmp_path / "meta.log")), **FAST)
    c2.start()
    try:
        leader = wait_leader([c2])
        assert set(leader.sm.control.regions) == {r1.region_id, r2.region_id}
        assert leader.sm.control._next_region_id == next_id
        r3 = leader.control.create_region(b"c", b"d", replication=1)
        assert r3.region_id == next_id
    finally:
        c2.stop()


def test_lagging_follower_catches_up_via_snapshot_install():
    transport = LocalTransport()
    _, coords = make_cluster(transport=transport, snapshot_threshold=10)
    try:
        leader = wait_leader(coords)
        lagger = next(c for c in coords if c is not leader)
        for other in coords:
            if other is not lagger:
                transport.partition(other.node.id, lagger.node.id)
        leader.control.register_store("s1")
        for i in range(25):    # > snapshot_threshold: log compacts
            leader.auto_incr.generate(7, 10)
        transport.heal()
        wait_converged(coords, lambda c: c.sm.auto_incr.get(7), 251,
                       timeout=8.0)
    finally:
        stop_all(coords)


def test_meta_and_kv_replicate():
    _, coords = make_cluster()
    try:
        leader = wait_leader(coords)
        leader.kv.kv_put(b"cfg/a", b"1")
        rev = leader.kv.kv_put(b"cfg/a", b"2")
        leader.meta.create_schema("analytics")
        wait_converged(
            coords, lambda c: c.sm.kv.kv_range(b"cfg/a")[0][0].value, b"2"
        )
        wait_converged(
            coords, lambda c: "analytics" in c.sm.meta.get_schemas(), True
        )
        assert rev >= 2
    finally:
        stop_all(coords)


def test_nack_rearms_failed_cmds_without_touching_leader_state():
    """Round-4 advisor: stores mutate COPIES of queue cmds; failures are
    re-delivered through the explicit nack channel (failed_cmd_ids) with a
    coordinator-owned retry budget."""
    import time as _t

    _, coords = make_cluster()
    try:
        leader = wait_leader(coords)
        leader.control.register_store("s1")
        d = leader.control.create_region(b"a", b"z", replication=1)

        # beat 1: deliver the CREATE; nothing mutates the SM's objects
        cmds = leader.control.store_heartbeat("s1")
        assert [c.cmd_id for c in cmds]
        cmd_id = cmds[0].cmd_id
        sm_cmd = next(c for c in leader.sm.control.store_ops["s1"]
                      if c.cmd_id == cmd_id)
        # the "store" fails execution: it only reports the nack — no
        # direct status write on the delivered object reaches the SM
        assert sm_cmd.status == "sent"
        # a STALLED report (election churn) re-arms without charging the
        # retry budget
        leader.control.store_heartbeat("s1", stalled_cmd_ids=[cmd_id])
        sm_cmd = next(c for c in leader.sm.control.store_ops["s1"]
                      if c.cmd_id == cmd_id)
        assert sm_cmd.retries == 0
        leader.control.store_heartbeat("s1", failed_cmd_ids=[cmd_id])
        # re-armed and re-delivered (same beat pops it back to sent)
        sm_cmd = next(c for c in leader.sm.control.store_ops["s1"]
                      if c.cmd_id == cmd_id)
        assert sm_cmd.retries == 1
        # keep failing: budget exhausted -> cmd dropped, job errored
        for _ in range(5):
            leader.control.store_heartbeat("s1", failed_cmd_ids=[cmd_id])
        assert all(c.cmd_id != cmd_id
                   for c in leader.sm.control.store_ops["s1"])
        job = next(j for j in leader.sm.control.jobs
                   if j.cmd_id == cmd_id)
        assert job.status.startswith("error")
        # every replica agrees (the nack rode the raft log)
        _t.sleep(0.5)
        for c in coords:
            j = next(j for j in c.sm.control.jobs if j.cmd_id == cmd_id)
            assert j.status.startswith("error"), c.node.id
    finally:
        stop_all(coords)
