"""Test configuration: force an 8-device virtual CPU platform.

Mirrors the reference's single-process multi-peer raft tests
(test/unit_test/test_raft_node.cc:125-199): all "distributed" behavior is
exercised in one process. Here the device mesh itself is virtualized so
sharding/collective code paths compile and run without TPU hardware.

Must run before jax is imported anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The image's sitecustomize (/root/.axon_site) imports jax at interpreter
# startup with JAX_PLATFORMS=axon already baked in, so the env var alone is
# too late — override through the config API before any backend initializes.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Make the repo root importable regardless of pytest rootdir.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
