"""Supervisor for tools/tpu_watcher.py (round-4 VERDICT Next #1).

Round 4's watcher died silently and stayed down for most of the round.
This supervisor keeps it alive for the whole round: it respawns the
watcher whenever it exits, logs every spawn/exit with the exit status,
and backs off briefly between respawns so a crash loop can't spin.

    setsid nohup python tools/tpu_supervisor.py >/dev/null 2>&1 &

It exits on its own at the round deadline, or when the watcher reports
its queue complete (state file has every queue step done).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
from tpu_watcher import ROUND_DEADLINE_S as DEADLINE_S  # noqa: E402 — one
# constant governs both processes (deadline drift caused a respawn/state-
# reset loop in review)
from tpu_watcher import (  # noqa: E402 — shared runtime dir + state path
    RUNTIME_DIR,
    STATE_PATH,
    append_log,
)

LOG_PATH = os.path.join(RUNTIME_DIR, "tpu_supervisor.log")
PID_PATH = os.path.join(RUNTIME_DIR, "tpu_supervisor.pid")
RESPAWN_BACKOFF_S = 20
QUEUE_STEPS = {"smoke", "bench_row2", "row1_flat", "row4_hnsw", "row3_ivfpq"}


def log(msg: str) -> None:
    append_log(LOG_PATH, f"[{time.strftime('%H:%M:%S')}] {msg}")


def queue_complete() -> bool:
    try:
        with open(STATE_PATH) as f:
            st = json.load(f)
    except (OSError, ValueError):
        return False
    return QUEUE_STEPS <= set(st.get("done", {}))


def _other_supervisor_alive() -> bool:
    try:
        with open(PID_PATH) as f:
            pid = int(f.read().strip())
        if pid != os.getpid():
            os.kill(pid, 0)   # raises if dead
            return True
    except (OSError, ValueError):
        pass
    return False


def main() -> None:
    if _other_supervisor_alive():
        # two supervisors would race two watchers on the state file and
        # contend for the single axon lease (which wedges under contention)
        log(f"another supervisor is alive ({PID_PATH}); refusing to start")
        return
    with open(PID_PATH, "w") as f:
        f.write(str(os.getpid()))
    try:
        with open(STATE_PATH) as f:
            start = json.load(f).get("started", time.time())
    except (OSError, ValueError):
        start = time.time()
    log(f"supervisor up pid={os.getpid()}")
    while time.time() - start < DEADLINE_S:
        if queue_complete():
            log("watcher queue complete; supervisor exiting")
            return
        p = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "tpu_watcher.py")],
            cwd=REPO,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.STDOUT,
        )
        log(f"spawned watcher pid={p.pid}")
        while p.poll() is None and time.time() - start < DEADLINE_S:
            time.sleep(30)
        if p.poll() is None:
            log("round deadline; leaving watcher to its own deadline exit")
            return
        log(f"watcher pid={p.pid} exited rc={p.returncode}; "
            f"respawn in {RESPAWN_BACKOFF_S}s")
        time.sleep(RESPAWN_BACKOFF_S)
    log("supervisor deadline reached")


if __name__ == "__main__":
    main()
