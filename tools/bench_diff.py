"""Diff two bench.py JSON summaries and flag performance regressions.

The BENCH_r0*.json trajectory has been eyeball-only since round 1; this
makes it machine-checkable:

    python tools/bench_diff.py OLD.json NEW.json \
        [--qps-drop 0.15] [--recall-drop 0.02] [--bytes-grow 0.25] [--json]

Both files are flattened to dotted numeric paths; a metric is compared
only when BOTH summaries carry it (new scenarios / removed scenarios are
reported as coverage changes, never as regressions). Classification is by
key name, so the tool keeps working as bench grows scenarios:

  qps        — any key named/suffixed `qps` or a top-level `value` whose
               sibling `unit` is qps: regression when it drops by more
               than --qps-drop (relative).
  recall     — keys containing `recall` (excluding deltas/booleans):
               regression when it drops by more than --recall-drop
               (absolute — recall is already a fraction).
  bytes      — `hbm`/`bytes` keys: regression when they GROW by more
               than --bytes-grow (relative).
  recompiles — `recompiles` keys: regression when a steady-state counter
               that was meeting the invariant (0) becomes nonzero, or
               grows at all.
  recovery   — chaos-scenario `recovery_ms` keys: regression when the
               figure more than doubles AND crosses 1s absolute (coarse
               on purpose — recovery is bounded, not benchmarked).
               Chaos `goodput` keys ride the qps rule.

build_throughput (ISSUE 18) names its per-arm rates `host_rows_qps` /
`device_rows_qps` deliberately: build rows/s ride the qps rule, its
recall_*_built keys the recall rule, and steady_state_recompiles the
recompiles rule — no bespoke classifier needed.

memory_pressure (ISSUE 19) rides the same rules per curve point
(p50_qps / recall_at_10 / steady_recompiles), while its curve AXES —
`budget_frac` and `resident_fraction` — are excluded: they describe the
synthetic pressure schedule and the tier placement it forces, which are
scenario design, not code under test.

Exit status: 0 = no regressions, 1 = regressions found (CI-gateable),
2 = usage/file errors. All human output goes to stdout; --json emits the
machine-readable comparison instead.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional


def flatten(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves only, dotted paths; bools excluded (gates, not
    magnitudes); list elements index into the path."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(flatten(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.update(flatten(v, f"{prefix}[{i}]"))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out[prefix] = float(obj)
    return out


def classify(path: str, summary: Optional[dict] = None) -> Optional[str]:
    """Metric kind for a flattened path, or None (not perf-compared)."""
    if "trajectory" in path.lower():
        # recall_slo's per-tick convergence trail: it INTENTIONALLY
        # starts mistuned (~0.4 recall at tick 1) and mid-walk estimates
        # vary run to run — diagnostics, never a regression signal
        return None
    leaf = path.rsplit(".", 1)[-1]
    low = leaf.lower()
    if "baseline" in low:
        # the CPU reference measurement drifts with the host, not with
        # the code under test — never a regression signal
        return None
    if "qos_off" in path.lower():
        # the overload scenario's UNSHAPED arm exists to demonstrate the
        # collapse — its goodput is intentionally terrible and noisy
        # (whatever survived before the backlog crossed the deadline);
        # only the shaped arm and the on/off ratio are the signal
        return None
    if "goodput" in low:
        # goodput (replies within deadline) regresses like a QPS figure:
        # covers goodput_ratio_* and any future non-_qps-suffixed key
        return "qps"
    if low in ("shed", "expired", "offered", "served", "dispatched_rows",
               "deadline_ms"):
        # overload-scenario load accounting: magnitudes track the offered
        # rate (2x measured capacity), not code quality — the goodput and
        # gate keys carry the regression signal
        return None
    if low in ("events_emitted", "tuner_events", "tier_events"):
        # flight-recorder decision counts (ISSUE 20): how often the
        # controllers chose to act under a scenario's traffic — cadence
        # accounting, not a perf signal; the *overhead_pct keys carry
        # the ledger's cost gate
        return None
    if low == "value" and summary is not None and (
        summary.get("unit") == "qps"
    ):
        return "qps"
    if low == "qps" or low.endswith("_qps") or low.startswith("qps_"):
        return "qps"
    if low.endswith("overhead_pct"):
        # instrumentation-overhead ratios (e.g. integrity_scrub's mixed
        # p99 with the digest ledger + scrub on vs off): already a
        # percentage, so the threshold is absolute points, not relative
        return "overhead"
    if "recall" in low:
        # deltas/differences around recall are signed diagnostics, not
        # magnitudes to threshold
        if "delta" in low or "vs" in low:
            return None
        return "recall"
    if "recompile" in low:
        return "recompiles"
    if low.endswith("recovery_ms"):
        # chaos-scenario recovery times (kill/restart, failover, remat):
        # wall-clock on a shared CI host, so the gate is coarse — only a
        # large relative blow-up signals a real recovery-path regression
        return "recovery"
    if "working_set" in low:
        # heat_skew's working-set estimate measures the PLANTED traffic
        # pattern (bytes the skewed stream needed resident), not code
        # quality — the bytes-suffix rule below would false-flag it
        return None
    if "resident_fraction" in low or low == "budget_frac":
        # memory_pressure's curve axes: the synthetic budget step and
        # the device-resident share it forces are scenario DESIGN, not
        # code quality — the per-point p50_qps / recall_at_10 /
        # steady_recompiles keys carry the regression signal
        return None
    if "hbm" in low or low.endswith("bytes") or low.endswith(
            "bytes_per_vector"):
        return "bytes"
    return None


def compare(old: dict, new: dict, qps_drop: float = 0.15,
            recall_drop: float = 0.02, bytes_grow: float = 0.25
            ) -> Dict[str, Any]:
    """Full comparison record: per-metric rows + regression list +
    coverage changes."""
    fo, fn = flatten(old), flatten(new)
    rows: List[Dict[str, Any]] = []
    regressions: List[Dict[str, Any]] = []
    for path in sorted(set(fo) & set(fn)):
        kind = classify(path, new if "." not in path else None)
        if kind is None:
            continue
        ov, nv = fo[path], fn[path]
        row = {"path": path, "kind": kind, "old": ov, "new": nv}
        bad = False
        if kind == "qps":
            change = (nv - ov) / ov if ov else 0.0
            row["change"] = round(change, 4)
            bad = ov > 0 and change < -qps_drop
        elif kind == "recall":
            row["change"] = round(nv - ov, 4)
            bad = (ov - nv) > recall_drop
        elif kind == "bytes":
            change = (nv - ov) / ov if ov else 0.0
            row["change"] = round(change, 4)
            bad = ov > 0 and change > bytes_grow
        elif kind == "recompiles":
            row["change"] = round(nv - ov, 4)
            # the steady-state invariant: any growth is a regression
            bad = nv > ov
        elif kind == "recovery":
            # recovery is bounded, not benchmarked: flag only when a
            # recovery that used to be fast blows past double its old
            # figure AND crosses a 1s absolute floor (sub-second jitter
            # on shared hosts is machine weather, not a regression)
            change = (nv - ov) / ov if ov else 0.0
            row["change"] = round(change, 4)
            bad = ov > 0 and change > 1.0 and nv > 1000.0
        elif kind == "overhead":
            # overhead percentages regress when they grow by more than
            # 5 points (the integrity_scrub acceptance bound); shrinking
            # or noise inside the band is fine
            row["change"] = round(nv - ov, 4)
            bad = (nv - ov) > 5.0
        row["regression"] = bad
        rows.append(row)
        if bad:
            regressions.append(row)
    return {
        "compared": len(rows),
        "rows": rows,
        "regressions": regressions,
        "only_old": sorted(p for p in set(fo) - set(fn) if classify(p)),
        "only_new": sorted(p for p in set(fn) - set(fo) if classify(p)),
    }


def _fmt(v: float) -> str:
    return f"{v:g}"


def render(result: Dict[str, Any]) -> str:
    out: List[str] = []
    regs = result["regressions"]
    out.append(
        f"compared {result['compared']} metrics: "
        f"{len(regs)} regression(s)"
    )
    if regs:
        w = max(len(r["path"]) for r in regs)
        for r in regs:
            out.append(
                f"  REGRESSION {r['path'].ljust(w)}  {r['kind']:<10} "
                f"{_fmt(r['old'])} -> {_fmt(r['new'])} "
                f"(change {r['change']:+g})"
            )
    for key, label in (("only_old", "dropped from new"),
                       ("only_new", "new coverage")):
        if result[key]:
            out.append(f"  {label}: {len(result[key])} metric path(s)")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("old", help="baseline bench JSON summary")
    ap.add_argument("new", help="candidate bench JSON summary")
    ap.add_argument("--qps-drop", type=float, default=0.15,
                    help="max tolerated relative QPS drop (default 0.15)")
    ap.add_argument("--recall-drop", type=float, default=0.02,
                    help="max tolerated absolute recall drop "
                         "(default 0.02)")
    ap.add_argument("--bytes-grow", type=float, default=0.25,
                    help="max tolerated relative HBM/bytes growth "
                         "(default 0.25)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable comparison")
    args = ap.parse_args(argv)
    try:
        with open(args.old) as f:
            old = json.load(f)
        with open(args.new) as f:
            new = json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2
    result = compare(old, new, qps_drop=args.qps_drop,
                     recall_drop=args.recall_drop,
                     bytes_grow=args.bytes_grow)
    if args.json:
        json.dump(result, sys.stdout, indent=1, sort_keys=True)
        print()
    else:
        print(render(result))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
