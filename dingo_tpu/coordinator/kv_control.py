"""KvControl: etcd-compatible revisioned KV with leases and one-time watches.

Reference: src/coordinator/kv_control.{h,cc} + _fsm/_kv/_lease/_watch.cc
(~6K LoC) — KvRange/KvPut/KvDeleteRange/KvCompaction (kv_control.h:252-291),
revision model (main revision per raft term + sub revision), LeaseGrant/
LeaseRevoke (:221-225) with TTL-attached keys, and one-time watches with a
KvWatchNode closure queue (:47-113).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from dingo_tpu.common import persist
from dingo_tpu.engine.raw_engine import CF_META, RawEngine

_PREFIX_KV = b"VKV_"
_PREFIX_LEASE = b"VLEASE_"
_KEY_REVISION = b"VKVREV__"  # NOT under VKV_: user keys cannot collide


@persist.register
@dataclasses.dataclass
class KvItem:
    key: bytes
    value: bytes
    create_revision: int
    mod_revision: int
    version: int
    lease_id: int = 0


@persist.register
@dataclasses.dataclass
class Lease:
    lease_id: int
    ttl_s: int
    granted_ms: int
    keys: List[bytes] = dataclasses.field(default_factory=list)

    def expired(self, now_ms: Optional[int] = None) -> bool:
        now_ms = now_ms or int(time.time() * 1000)
        return now_ms > self.granted_ms + self.ttl_s * 1000


class KvControl:
    def __init__(self, engine: RawEngine):
        self.engine = engine
        self._lock = threading.RLock()
        self._revision = 1
        self._kv: Dict[bytes, KvItem] = {}
        self._leases: Dict[int, Lease] = {}
        self._next_lease = 1
        #: one-time watches: key -> [(watch_revision, callback)]
        self._watches: Dict[bytes, List[Tuple[int, Callable]]] = {}
        self._recover()

    # ---------------- persistence -------------------------------------------
    def _recover(self) -> None:
        blob = self.engine.get(CF_META, _KEY_REVISION)
        if blob:
            self._revision = persist.loads(blob)
        for k, v in self.engine.scan(CF_META, _PREFIX_KV, _PREFIX_KV + b"\xff"):
            if k == _KEY_REVISION:
                continue
            item: KvItem = persist.loads(v)
            self._kv[item.key] = item
            self._revision = max(self._revision, item.mod_revision)
        for k, v in self.engine.scan(CF_META, _PREFIX_LEASE,
                                     _PREFIX_LEASE + b"\xff"):
            lease: Lease = persist.loads(v)
            self._leases[lease.lease_id] = lease
            self._next_lease = max(self._next_lease, lease.lease_id + 1)

    def _bump_revision(self) -> int:
        """Monotonic across restarts: deletes advance it too, so issued
        revisions are never reused (etcd contract)."""
        self._revision += 1
        self.engine.put(CF_META, _KEY_REVISION, persist.dumps(self._revision))
        return self._revision

    def _persist_kv(self, item: KvItem) -> None:
        self.engine.put(CF_META, _PREFIX_KV + item.key, persist.dumps(item))

    def _persist_lease(self, lease: Lease) -> None:
        self.engine.put(
            CF_META, _PREFIX_LEASE + str(lease.lease_id).encode(),
            persist.dumps(lease),
        )

    # ---------------- KV ------------------------------------------------------
    def kv_put(self, key: bytes, value: bytes, lease_id: int = 0) -> int:
        """Returns the new revision (KvPut, kv_control.h:263)."""
        with self._lock:
            if lease_id:
                lease = self._leases.get(lease_id)
                if lease is None or lease.expired():
                    raise KeyError(f"lease {lease_id} not found/expired")
                if key not in lease.keys:
                    lease.keys.append(key)
                    self._persist_lease(lease)
            self._bump_revision()
            old = self._kv.get(key)
            item = KvItem(
                key=key,
                value=value,
                create_revision=old.create_revision if old else self._revision,
                mod_revision=self._revision,
                version=(old.version + 1) if old else 1,
                lease_id=lease_id,
            )
            self._kv[key] = item
            self._persist_kv(item)
            self._fire_watches(key, "put", item)
            return self._revision

    def kv_range(self, start: bytes, end: Optional[bytes] = None,
                 limit: int = 0) -> Tuple[List[KvItem], int]:
        """KvRange: [start, end) or exact key when end is None."""
        with self._lock:
            self._expire_leases()
            if end is None:
                item = self._kv.get(start)
                return ([item] if item else [], self._revision)
            out = [
                item for k, item in sorted(self._kv.items())
                if start <= k < end
            ]
            if limit:
                out = out[:limit]
            return out, self._revision

    def kv_delete_range(self, start: bytes, end: Optional[bytes] = None) -> int:
        """Returns number deleted."""
        with self._lock:
            doomed = (
                [start] if end is None
                else [k for k in list(self._kv) if start <= k < end]
            )
            n = 0
            for k in doomed:
                item = self._kv.pop(k, None)
                if item is None:
                    continue
                self._bump_revision()
                n += 1
                self.engine.delete(CF_META, _PREFIX_KV + k)
                self._fire_watches(k, "delete", item)
            return n

    def kv_compaction(self, revision: int) -> int:
        """KvCompaction (kv_control.h:291): our store keeps only the latest
        version per key, so compaction just reports the floor."""
        with self._lock:
            return self._revision

    # ---------------- leases --------------------------------------------------
    def lease_grant(self, ttl_s: int, lease_id: int = 0) -> Lease:
        with self._lock:
            lid = lease_id or self._next_lease
            self._next_lease = max(self._next_lease, lid + 1)
            lease = Lease(lease_id=lid, ttl_s=ttl_s,
                          granted_ms=int(time.time() * 1000))
            self._leases[lid] = lease
            self._persist_lease(lease)
            return lease

    def lease_renew(self, lease_id: int) -> Lease:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease.expired():
                raise KeyError(f"lease {lease_id} not found/expired")
            lease.granted_ms = int(time.time() * 1000)
            self._persist_lease(lease)
            return lease

    def lease_revoke(self, lease_id: int) -> int:
        """Revoke + delete attached keys; returns deleted count."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return 0
            self.engine.delete(CF_META, _PREFIX_LEASE + str(lease_id).encode())
            n = 0
            for key in lease.keys:
                n += self.kv_delete_range(key)
            return n

    def _expire_leases(self) -> None:
        for lid, lease in list(self._leases.items()):
            if lease.expired():
                self.lease_revoke(lid)

    def lease_gc(self) -> None:
        """Crontab entry point (lease expiry sweep)."""
        with self._lock:
            self._expire_leases()

    # ---------------- watches -------------------------------------------------
    def watch(self, key: bytes, start_revision: int,
              callback: Callable[[str, KvItem], None]) -> None:
        """One-time watch (kv_control.h:47-113): callback fires once on the
        next event for `key` at/after start_revision, then unregisters."""
        with self._lock:
            item = self._kv.get(key)
            if item is not None and item.mod_revision >= start_revision:
                callback("put", item)   # immediate catch-up fire
                return
            self._watches.setdefault(key, []).append((start_revision, callback))

    def _fire_watches(self, key: bytes, event: str, item: KvItem) -> None:
        keep = []
        for rev, cb in self._watches.pop(key, []):
            if item.mod_revision < rev:
                keep.append((rev, cb))   # event predates the watch window
                continue
            try:
                cb(event, item)
            except Exception:
                pass
        if keep:
            self._watches[key] = keep
