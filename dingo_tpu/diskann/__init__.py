from dingo_tpu.diskann.core import CoreState, DiskAnnCore
from dingo_tpu.diskann.item import DiskAnnItemManager

__all__ = ["CoreState", "DiskAnnCore", "DiskAnnItemManager"]
