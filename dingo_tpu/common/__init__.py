"""Common runtime: config, crontab, failpoints, request tracking, metrics,
stream paging, worker pools. Mirrors reference src/common/, src/config/,
src/crontab/, src/metrics/."""
