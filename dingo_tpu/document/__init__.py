"""Document subsystem: per-region full-text index.

Mirrors reference src/document/ (DocumentIndex over the vendored Rust
tantivy-search, document_index.h; DocumentIndexManager; DocumentReader).
No Rust exists in this image, so the index is an original BM25 inverted
index (documents are also persisted in the engine; the index is an
apply-log-tracked materialized view exactly like the vector index).
"""

from dingo_tpu.document.index import DocumentIndex  # noqa: F401
