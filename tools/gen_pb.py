"""Regenerate dingo_tpu/server/dingo_pb2.py without protoc.

The image ships neither protoc nor grpcio-tools, so schema evolution works
by descriptor surgery: load the serialized FileDescriptorProto embedded in
the current dingo_pb2.py, apply the declarative ADDITIONS below (new
messages + new fields on existing messages), and re-emit the module in the
standard `_builder` generated-code shape. protobuf wire compatibility is
preserved because existing field numbers are never touched — only appended.

proto/dingo.proto stays the human-readable source of truth: edit it AND
mirror the change here, then run

    python tools/gen_pb.py

The tool is idempotent — messages/fields that already exist are skipped —
so it can re-run safely after partial edits.
"""

from __future__ import annotations

import os
import sys

from google.protobuf import descriptor_pb2

T = descriptor_pb2.FieldDescriptorProto

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PB2_PATH = os.path.join(REPO, "dingo_tpu", "server", "dingo_pb2.py")

# ---------------------------------------------------------------------------
# Declarative schema additions. Field spec:
#   (name, number, type, type_name_or_None, repeated)
# type_name is the fully qualified message type (".dingo_tpu.X") for
# TYPE_MESSAGE / TYPE_ENUM fields.
# ---------------------------------------------------------------------------

#: new messages appended to the file (store-metrics plane, PR 2)
NEW_MESSAGES = {
    # per-region snapshot collected by StoreMetricsCollector
    "RegionMetrics": [
        ("region_id", 1, T.TYPE_INT64, None, False),
        ("key_count", 2, T.TYPE_INT64, None, False),
        ("approximate_bytes", 3, T.TYPE_INT64, None, False),
        ("vector_count", 4, T.TYPE_INT64, None, False),
        ("vector_memory_bytes", 5, T.TYPE_INT64, None, False),
        ("device_memory_bytes", 6, T.TYPE_INT64, None, False),
        ("index_ready", 7, T.TYPE_BOOL, None, False),
        ("index_building", 8, T.TYPE_BOOL, None, False),
        ("index_build_error", 9, T.TYPE_BOOL, None, False),
        ("index_apply_log_id", 10, T.TYPE_INT64, None, False),
        ("index_snapshot_log_id", 11, T.TYPE_INT64, None, False),
        ("apply_lag", 12, T.TYPE_INT64, None, False),
        ("is_leader", 13, T.TYPE_BOOL, None, False),
        ("search_qps", 14, T.TYPE_DOUBLE, None, False),
        ("document_count", 15, T.TYPE_INT64, None, False),
        # HBM high-watermark for the region total (obs hbm ledger, PR 5)
        ("device_peak_bytes", 16, T.TYPE_INT64, None, False),
        # quality plane (obs/quality.py, PR 9): windowed live recall@k
        # estimate with its Wilson CI; quality_samples = scored queries
        # in the window (0 = no evidence, renderers show '-')
        ("quality_recall", 17, T.TYPE_DOUBLE, None, False),
        ("quality_recall_ci_low", 18, T.TYPE_DOUBLE, None, False),
        ("quality_recall_ci_high", 19, T.TYPE_DOUBLE, None, False),
        ("quality_samples", 20, T.TYPE_INT64, None, False),
        # serving-pressure plane (obs/pressure.py, PR 10): coalescer
        # queue depth (rows), recent queue-wait watermark (ms),
        # cumulative shed+expired requests, shed-ladder degrade level
        ("qos_queue_depth", 21, T.TYPE_INT64, None, False),
        ("qos_queue_wait_ms", 22, T.TYPE_DOUBLE, None, False),
        ("qos_shed_total", 23, T.TYPE_INT64, None, False),
        ("qos_degrade_level", 24, T.TYPE_INT64, None, False),
        # state-integrity plane (obs/integrity.py, PR 11): the raft
        # applied index the digest vector corresponds to, the compact
        # JSON {artifact: digest} vector, and the store-local scrub
        # verdict (a full-state recompute disagreed with the ledger)
        ("integrity_applied_index", 25, T.TYPE_INT64, None, False),
        ("integrity_digests", 26, T.TYPE_STRING, None, False),
        ("integrity_mismatch", 27, T.TYPE_BOOL, None, False),
        # fault-domain hardening (index/recovery.py): region's device
        # index OOMed past the recovery ladder — served by the host
        # exact path until the background re-materialization completes
        ("device_degraded", 28, T.TYPE_BOOL, None, False),
        # serving-edge cache (dingo_tpu/cache/): cumulative hit/miss
        # counts and live cached entries — the cluster top CACHE column
        # renders hit rate ('-' while hits+misses == 0)
        ("cache_hits", 29, T.TYPE_INT64, None, False),
        ("cache_misses", 30, T.TYPE_INT64, None, False),
        ("cache_entries", 31, T.TYPE_INT64, None, False),
        # workload-heat plane (obs/heat.py): traffic concentration
        # (hot_fraction / gini over heat units) and bytes to serve
        # {50,90,99}% of traffic at the region's own precision tier;
        # heat_touches = cumulative sketch touches (0 = no evidence).
        # The coordinator's capacity plane rolls these against the HBM
        # ledger for advisory tier/split recommendations
        ("heat_hot_fraction", 32, T.TYPE_DOUBLE, None, False),
        ("heat_gini", 33, T.TYPE_DOUBLE, None, False),
        ("heat_working_set_p50", 34, T.TYPE_INT64, None, False),
        ("heat_working_set_p90", 35, T.TYPE_INT64, None, False),
        ("heat_working_set_p99", 36, T.TYPE_INT64, None, False),
        ("heat_touches", 37, T.TYPE_INT64, None, False),
        # per-shape cost model (obs/cost.py): EWMA per-row dispatch µs
        ("cost_row_us", 38, T.TYPE_DOUBLE, None, False),
        # memory-tier ladder (index/tiering.py): serving rung name
        ("serving_tier", 39, T.TYPE_STRING, None, False),
        # control-plane flight recorder (obs/events.py): compact JSON of
        # the live overrides in force on this region at collect time —
        # {"tuning": {...}, "advisory_precision": ..., "tier": ...,
        #  "tier_base": ...}. `cluster explain` reconciles these against
        # the event ledger (a live knob with no event = orphan)
        ("live_knobs", 40, T.TYPE_STRING, None, False),
    ],
    # control-plane decision event (obs/events.py): one controller
    # actuation with the metric evidence read at decision time. Rides
    # heartbeats (StoreMetrics.events) to the coordinator's merged
    # cluster timeline
    "ControlEvent": [
        ("actor", 1, T.TYPE_STRING, None, False),
        ("region_id", 2, T.TYPE_INT64, None, False),
        ("knob", 3, T.TYPE_STRING, None, False),
        ("old", 4, T.TYPE_STRING, None, False),
        ("new", 5, T.TYPE_STRING, None, False),
        ("trigger", 6, T.TYPE_STRING, None, False),
        ("evidence", 7, T.TYPE_STRING, None, False),  # compact JSON
        ("ts_ms", 8, T.TYPE_INT64, None, False),
        ("actor_seq", 9, T.TYPE_INT64, None, False),
        ("node_id", 10, T.TYPE_STRING, None, False),
        ("trace_id", 11, T.TYPE_STRING, None, False),
        ("flight_bundle_id", 12, T.TYPE_STRING, None, False),
    ],
    # whole-store snapshot (process device gauges + per-region list)
    "StoreMetrics": [
        ("store_id", 1, T.TYPE_STRING, None, False),
        ("collected_at_ms", 2, T.TYPE_INT64, None, False),
        ("device_bytes_in_use", 3, T.TYPE_INT64, None, False),
        ("device_bytes_limit", 4, T.TYPE_INT64, None, False),
        ("device_peak_bytes", 5, T.TYPE_INT64, None, False),
        ("engine_key_count", 6, T.TYPE_INT64, None, False),
        ("regions", 7, T.TYPE_MESSAGE, ".dingo_tpu.RegionMetrics", True),
        # control-plane events harvested since the last beat (bounded by
        # events.heartbeat_batch; each event ships exactly once)
        ("events", 8, T.TYPE_MESSAGE, ".dingo_tpu.ControlEvent", True),
    ],
    "GetStoreMetricsRequest": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.RequestInfo", False),
        ("store_id", 2, T.TYPE_STRING, None, False),  # empty = every store
    ],
    "StoreMetricsEntry": [
        ("store_id", 1, T.TYPE_STRING, None, False),
        ("last_update_ms", 2, T.TYPE_INT64, None, False),
        ("stale", 3, T.TYPE_BOOL, None, False),
        ("metrics", 4, T.TYPE_MESSAGE, ".dingo_tpu.StoreMetrics", False),
    ],
    "GetStoreMetricsResponse": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.ResponseInfo", False),
        ("error", 2, T.TYPE_MESSAGE, ".dingo_tpu.Error", False),
        ("stores", 3, T.TYPE_MESSAGE, ".dingo_tpu.StoreMetricsEntry", True),
        # regions the coordinator's replica-digest comparison currently
        # flags as DIVERGED (state-integrity plane; cluster top renders)
        ("diverged_region_ids", 4, T.TYPE_INT64, None, True),
    ],
    "GetRegionMetricsRequest": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.RequestInfo", False),
        ("region_id", 2, T.TYPE_INT64, None, False),  # 0 = every region
    ],
    "RegionMetricsEntry": [
        ("store_id", 1, T.TYPE_STRING, None, False),
        ("stale", 2, T.TYPE_BOOL, None, False),
        ("metrics", 3, T.TYPE_MESSAGE, ".dingo_tpu.RegionMetrics", False),
    ],
    "GetRegionMetricsResponse": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.ResponseInfo", False),
        ("error", 2, T.TYPE_MESSAGE, ".dingo_tpu.Error", False),
        ("regions", 3, T.TYPE_MESSAGE, ".dingo_tpu.RegionMetricsEntry", True),
        ("diverged_region_ids", 4, T.TYPE_INT64, None, True),
    ],
    # flight-recorder bundle export (device-runtime observability, PR 5)
    "FlightBundleMeta": [
        ("id", 1, T.TYPE_STRING, None, False),
        ("reason", 2, T.TYPE_STRING, None, False),
        ("name", 3, T.TYPE_STRING, None, False),
        ("trace_id", 4, T.TYPE_STRING, None, False),
        ("region_id", 5, T.TYPE_INT64, None, False),
        ("created_ms", 6, T.TYPE_INT64, None, False),
        ("payload_bytes", 7, T.TYPE_INT64, None, False),
    ],
    "FlightDumpRequest": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.RequestInfo", False),
        ("bundle_id", 2, T.TYPE_STRING, None, False),  # "" = newest
        ("include_payload", 3, T.TYPE_BOOL, None, False),
    ],
    "FlightDumpResponse": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.ResponseInfo", False),
        ("error", 2, T.TYPE_MESSAGE, ".dingo_tpu.Error", False),
        ("bundles", 3, T.TYPE_MESSAGE, ".dingo_tpu.FlightBundleMeta", True),
        ("payload", 4, T.TYPE_BYTES, None, False),  # zlib(JSON) bundle
        ("payload_bundle_id", 5, T.TYPE_STRING, None, False),
    ],
    # event-ledger dump (DebugService on stores: process-local ring;
    # ClusterStatService on the coordinator: merged cluster timeline)
    "EventDumpRequest": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.RequestInfo", False),
        ("region_id", 2, T.TYPE_INT64, None, False),  # 0 = every region
        ("actor", 3, T.TYPE_STRING, None, False),     # "" = every actor
        ("limit", 4, T.TYPE_INT64, None, False),      # 0 = default bound
    ],
    "EventDumpResponse": [
        ("info", 1, T.TYPE_MESSAGE, ".dingo_tpu.ResponseInfo", False),
        ("error", 2, T.TYPE_MESSAGE, ".dingo_tpu.Error", False),
        ("events", 3, T.TYPE_MESSAGE, ".dingo_tpu.ControlEvent", True),
        ("dropped", 4, T.TYPE_INT64, None, False),
    ],
}

#: fields appended to existing messages
NEW_FIELDS = {
    # precision tier for float FLAT/IVF_FLAT storage+compute (ISSUE 4):
    # "" (conf default) / "fp32" / "bf16" / "sq8"
    "VectorIndexParameter": [
        ("precision", 13, T.TYPE_STRING, None, False),
    ],
    # heartbeat transport for the metrics payload
    "StoreHeartbeatRequest": [
        ("metrics", 11, T.TYPE_MESSAGE, ".dingo_tpu.StoreMetrics", False),
    ],
    # cluster-stat rollups (aggregated from the freshest store snapshots)
    "StoreStat": [
        ("key_count", 6, T.TYPE_INT64, None, False),
        ("vector_count", 7, T.TYPE_INT64, None, False),
        ("memory_bytes", 8, T.TYPE_INT64, None, False),
        ("device_memory_bytes", 9, T.TYPE_INT64, None, False),
        ("metrics_stale", 10, T.TYPE_BOOL, None, False),
        ("leader_qps", 11, T.TYPE_DOUBLE, None, False),
    ],
    "GetClusterStatResponse": [
        ("total_key_count", 8, T.TYPE_INT64, None, False),
        ("total_vector_count", 9, T.TYPE_INT64, None, False),
        ("total_memory_bytes", 10, T.TYPE_INT64, None, False),
        ("total_device_memory_bytes", 11, T.TYPE_INT64, None, False),
    ],
    # exposition selector: "" / "json" (default) or "prometheus"
    "MetricsDumpRequest": [
        ("format", 2, T.TYPE_STRING, None, False),
    ],
}

_HEADER = '''# -*- coding: utf-8 -*-
# Generated by tools/gen_pb.py (descriptor surgery; protoc is not in the
# image).  DO NOT EDIT BY HAND — edit proto/dingo.proto + tools/gen_pb.py
# and re-run `python tools/gen_pb.py`.
# source: dingo.proto
"""Generated protocol buffer code."""
from google.protobuf.internal import builder as _builder
from google.protobuf import descriptor as _descriptor
from google.protobuf import descriptor_pool as _descriptor_pool
from google.protobuf import symbol_database as _symbol_database
# @@protoc_insertion_point(imports)

_sym_db = _symbol_database.Default()


DESCRIPTOR = _descriptor_pool.Default().AddSerializedFile({blob!r})

_builder.BuildMessageAndEnumDescriptors(DESCRIPTOR, globals())
_builder.BuildTopDescriptorsAndMessages(DESCRIPTOR, 'dingo_pb2', globals())
# @@protoc_insertion_point(module_scope)
'''


def _load_current_fdp() -> descriptor_pb2.FileDescriptorProto:
    """Extract the serialized FileDescriptorProto from the current module
    WITHOUT importing it (importing would register the old schema in this
    interpreter's default descriptor pool and block re-registration)."""
    import ast

    with open(PB2_PATH) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and getattr(node.func, "attr", "") == "AddSerializedFile"
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            fdp = descriptor_pb2.FileDescriptorProto()
            fdp.ParseFromString(node.args[0].value)
            return fdp
    raise SystemExit(f"no AddSerializedFile(<bytes>) literal in {PB2_PATH}")


def _add_field(msg, spec) -> bool:
    name, number, ftype, type_name, repeated = spec
    if any(f.name == name for f in msg.field):
        return False
    taken = {f.number for f in msg.field}
    if number in taken:
        raise SystemExit(
            f"{msg.name}.{name}: field number {number} already in use"
        )
    f = msg.field.add()
    f.name = name
    f.number = number
    f.type = ftype
    f.label = T.LABEL_REPEATED if repeated else T.LABEL_OPTIONAL
    if type_name:
        f.type_name = type_name
    return True


def extend(fdp: descriptor_pb2.FileDescriptorProto) -> int:
    changed = 0
    have = {m.name: m for m in fdp.message_type}
    for name, fields in NEW_MESSAGES.items():
        msg = have.get(name)
        if msg is None:
            msg = fdp.message_type.add()
            msg.name = name
            have[name] = msg
            changed += 1
        for spec in fields:
            changed += _add_field(msg, spec)
    for name, fields in NEW_FIELDS.items():
        msg = have.get(name)
        if msg is None:
            raise SystemExit(f"NEW_FIELDS target {name} not in schema")
        for spec in fields:
            changed += _add_field(msg, spec)
    return changed


def verify(blob: bytes) -> None:
    """Round-trip the new schema in an isolated pool before writing."""
    from google.protobuf import descriptor_pool, message_factory

    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.ParseFromString(blob)
    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    hb = message_factory.GetMessageClass(
        pool.FindMessageTypeByName("dingo_tpu.StoreHeartbeatRequest")
    )()
    rm = hb.metrics.regions.add()
    rm.region_id = 7
    rm.device_memory_bytes = 123
    again = type(hb).FromString(hb.SerializeToString())
    assert again.metrics.regions[0].device_memory_bytes == 123


def main() -> int:
    fdp = _load_current_fdp()
    changed = extend(fdp)
    blob = fdp.SerializeToString()
    verify(blob)
    with open(PB2_PATH, "w") as f:
        f.write(_HEADER.format(blob=blob))
    print(f"{PB2_PATH}: {changed} schema additions, "
          f"{len(fdp.message_type)} messages, {len(blob)} bytes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
