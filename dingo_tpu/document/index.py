"""DocumentIndex: BM25 inverted index with typed fields.

Reference: src/document/document_index.h wraps tantivy (tokenized text
fields + i64/f64/bytes columns; queries are boolean text matches with
optional column filters). This is an original implementation covering that
surface: tokenization, positional postings with term frequencies, BM25
ranking, AND/OR boolean modes, PHRASE queries (consecutive positions),
column (scalar) filters, delete/upsert, save/load.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from collections import defaultdict

from dingo_tpu.common import persist
from typing import Any, Dict, List, Optional, Sequence, Tuple

_TOKEN_RE = re.compile(r"[a-z0-9]+")

FIELD_POSITION_GAP = 1_000_000
BM25_K1 = 1.2
BM25_B = 0.75


def tokenize(text: str) -> List[str]:
    return _TOKEN_RE.findall(text.lower())


class DocumentIndex:
    def __init__(self, index_id: int, text_fields: Sequence[str] = ("text",)):
        self.id = index_id
        self.text_fields = list(text_fields)
        self._lock = threading.RLock()
        #: term -> {doc_id: [positions]} (tf == len(positions))
        self._postings: Dict[str, Dict[int, List[int]]] = defaultdict(dict)
        #: doc_id -> (doc dict, token_count)
        self._docs: Dict[int, Tuple[Dict[str, Any], int]] = {}
        self._total_tokens = 0
        self.apply_log_id = 0

    # ---------------- mutation ----------------
    def add(self, doc_id: int, doc: Dict[str, Any]) -> None:
        with self._lock:
            if doc_id in self._docs:
                self._remove_unlocked(doc_id)
            ntok = 0
            pos = 0
            for field in self.text_fields:
                value = doc.get(field)
                if not isinstance(value, str):
                    continue
                for tok in tokenize(value):
                    self._postings[tok].setdefault(doc_id, []).append(pos)
                    pos += 1
                    ntok += 1
                # position gap between fields so a phrase cannot match
                # across a field boundary (tantivy parity)
                pos += FIELD_POSITION_GAP
            self._docs[doc_id] = (dict(doc), ntok)
            self._total_tokens += ntok

    upsert = add

    def delete(self, doc_ids: Sequence[int]) -> int:
        with self._lock:
            n = 0
            for did in doc_ids:
                if did in self._docs:
                    self._remove_unlocked(int(did))
                    n += 1
            return n

    def _remove_unlocked(self, doc_id: int) -> None:
        doc, ntok = self._docs.pop(doc_id)
        self._total_tokens -= ntok
        for field in self.text_fields:
            value = doc.get(field)
            if isinstance(value, str):
                for tok in set(tokenize(value)):
                    entry = self._postings.get(tok)
                    if entry is not None:
                        entry.pop(doc_id, None)
                        if not entry:
                            del self._postings[tok]

    # ---------------- search ----------------
    def search(
        self,
        query: str,
        topk: int = 10,
        mode: str = "or",
        column_filter: Optional[Dict[str, Any]] = None,
    ) -> List[Tuple[int, float]]:
        """BM25-ranked (doc_id, score), best first.
        mode: 'or' | 'and' | 'phrase' (terms at consecutive positions)."""
        terms = tokenize(query)
        if not terms:
            return []
        with self._lock:
            n_docs = len(self._docs)
            if n_docs == 0:
                return []
            avg_len = self._total_tokens / n_docs
            scores: Dict[int, float] = defaultdict(float)
            for term in terms:
                postings = self._postings.get(term)
                if not postings:
                    continue
                idf = math.log(1 + (n_docs - len(postings) + 0.5)
                               / (len(postings) + 0.5))
                for did, positions in postings.items():
                    tf = len(positions)
                    dlen = self._docs[did][1] or 1
                    denom = tf + BM25_K1 * (
                        1 - BM25_B + BM25_B * dlen / max(avg_len, 1e-9)
                    )
                    scores[did] += idf * tf * (BM25_K1 + 1) / denom
            hits = scores.items()
            if mode == "phrase":
                hits = [
                    (did, sc) for did, sc in scores.items()
                    if self._phrase_match_unlocked(did, terms)
                ]
            elif mode == "and":
                need = len(set(terms))
                uniq_matched: Dict[int, set] = defaultdict(set)
                for term in set(terms):
                    for did in self._postings.get(term, {}):
                        uniq_matched[did].add(term)
                hits = [
                    (did, sc) for did, sc in scores.items()
                    if len(uniq_matched.get(did, ())) >= need
                ]
            if column_filter:
                hits = [
                    (did, sc) for did, sc in hits
                    if all(self._docs[did][0].get(k) == v
                           for k, v in column_filter.items())
                ]
            return sorted(hits, key=lambda t: -t[1])[:topk]

    def _phrase_match_unlocked(self, doc_id: int,
                               terms: List[str]) -> bool:
        """True when the terms occur at consecutive positions in order."""
        lists = []
        for term in terms:
            positions = self._postings.get(term, {}).get(doc_id)
            if not positions:
                return False
            lists.append(set(positions))
        return any(
            all(start + i in lists[i] for i in range(1, len(lists)))
            for start in lists[0]
        )

    def get(self, doc_id: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            entry = self._docs.get(doc_id)
            return entry[0] if entry else None

    def count(self) -> int:
        with self._lock:
            return len(self._docs)

    # ---------------- persistence ----------------
    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        with self._lock:
            blob = persist.dumps({
                "postings": dict(self._postings),
                "docs": self._docs,
                "total_tokens": self._total_tokens,
            })
        with open(os.path.join(path, "document.idx"), "wb") as f:
            f.write(blob)
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump({
                "text_fields": self.text_fields,
                "apply_log_id": self.apply_log_id,
            }, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        with open(os.path.join(path, "document.idx"), "rb") as f:
            state = persist.loads(f.read())
        with self._lock:
            self.text_fields = meta["text_fields"]
            self.apply_log_id = meta["apply_log_id"]
            postings = state["postings"]
            # migrate pre-positional snapshots ({doc: tf} ints): synthesize
            # positions so BM25 keeps working; phrase matches degrade to
            # position-0 runs until the doc is re-upserted
            for term, docs in postings.items():
                for did, val in list(docs.items()):
                    if isinstance(val, int):
                        docs[did] = list(range(val))
            self._postings = defaultdict(dict, postings)
            self._docs = state["docs"]
            self._total_tokens = state["total_tokens"]
