"""Failpoint: runtime fault injection.

Reference: src/common/failpoint.{h,cc} — named failpoints configured at
runtime (via DebugService) with actions panic/sleep/print/yield/delay
(failpoint.h:44-141), compiled in behind ENABLE_FAILPOINT. Here failpoints
are always available (no compile gate) and applied with `apply("name")` at
the instrumented site.

Config string format (reference-compatible spirit):
    "<percent>%<count>*<action>(<arg>)"
e.g. "100%10*sleep(50)" = always fire, first 10 times, sleep 50ms.
"""

from __future__ import annotations

import random
import re
import threading
import time
from typing import Dict, Optional


class FailPointError(RuntimeError):
    """Raised by the `panic` action."""


class _FailPoint:
    def __init__(self, name: str, percent: int, count: int, action: str,
                 arg: str):
        self.name = name
        self.percent = percent
        self.count = count          # -1 = unlimited
        self.action = action
        self.arg = arg
        self.hits = 0


_CFG_RE = re.compile(
    r"^(?:(?P<pct>\d+)%)?(?:(?P<cnt>\d+)\*)?(?P<act>\w+)(?:\((?P<arg>[^)]*)\))?$"
)


class FailPointManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._points: Dict[str, _FailPoint] = {}
        self._rng = random.Random(0xFA11)

    def configure(self, name: str, config: str) -> None:
        """e.g. configure("before_raft_commit", "50%3*sleep(100)")."""
        m = _CFG_RE.match(config.strip())
        if not m:
            raise ValueError(f"bad failpoint config {config!r}")
        point = _FailPoint(
            name,
            int(m.group("pct") or 100),
            int(m.group("cnt") or -1),
            m.group("act"),
            m.group("arg") or "",
        )
        with self._lock:
            self._points[name] = point

    def remove(self, name: str) -> None:
        with self._lock:
            self._points.pop(name, None)

    def list(self) -> Dict[str, str]:
        with self._lock:
            return {
                n: f"{p.percent}%{p.count}*{p.action}({p.arg})"
                for n, p in self._points.items()
            }

    def apply(self, name: str) -> None:
        """Call at the instrumented site; may sleep/raise per config."""
        with self._lock:
            point = self._points.get(name)
            if point is None:
                return
            if point.count == 0:
                return
            if self._rng.random() * 100 >= point.percent:
                return
            if point.count > 0:
                point.count -= 1
            point.hits += 1
            action, arg = point.action, point.arg
        if action == "panic":
            raise FailPointError(f"failpoint {name} panic")
        if action == "sleep" or action == "delay":
            time.sleep(float(arg or 0) / 1000.0)
        elif action == "print":
            print(f"[failpoint] {name}: {arg}")
        elif action == "yield":
            time.sleep(0)


#: process-global manager (the reference's singleton)
FAILPOINTS = FailPointManager()


def failpoint(name: str) -> None:
    FAILPOINTS.apply(name)
