"""Order-invariant incremental set digests for device-state integrity.

The state-integrity plane (obs/integrity.py) needs to answer "do two
replicas of a region — or a snapshot and its restore, or the incremental
ledger and the actual device arrays — hold the same data" without ever
hashing the whole index on the hot path. The construction here makes the
digest *maintainable*: a write batch folds in O(batch) host work, and the
digest of the full set is always available in O(1).

Per-row fingerprint (uint64):

    proj  = sum_i bytes[i] * coeff[i]    (mod 2^64; coeff = fixed seeded
                                          odd uint64 stream)
    fp    = splitmix64(proj ^ splitmix64(id) ^ tag_seed)

- coeff[i] is ODD, so a single flipped byte (delta in [-255, 255], != 0)
  always changes proj — no power of two <= 2^8 divides 2^64/coeff[i].
- the id mixes NONLINEARLY (through splitmix64), so swapping two rows'
  payloads changes both fingerprints: a linear id term would cancel in
  the aggregate sum.
- tag_seed separates artifacts: the same bytes digested as "rows" and as
  "blocked" produce unrelated fingerprints.

Aggregate (SetDigest): component-wise modular sums of (fp,
splitmix64(fp ^ LANE2)) plus the element count. Sums are add/remove-
homomorphic — put adds a term, tombstone subtracts it — and order-
invariant, so replicas that applied the same writes in different slot
orders agree, and an incrementally-maintained ledger can be checked
against a from-scratch recompute (the corruption scrub).

Collision notes: this is an integrity check against silent corruption
and bookkeeping bugs, not an adversarial MAC. A single-element change is
ALWAYS detected (the per-fp guarantees above); multi-element collisions
require two independent 64-bit lanes to cancel simultaneously.
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional

import numpy as np

_U64 = np.uint64
_GOLDEN = _U64(0x9E3779B97F4A7C15)
_MIX1 = _U64(0xBF58476D1CE4E5B9)
_MIX2 = _U64(0x94D049BB133111EB)
_LANE2 = _U64(0xD6E8FEB86659FD93)

#: projection coefficients are generated lazily per payload width and
#: cached (a fixed seed, so every process derives the same stream)
_COEFF_SEED = 0xD1E657
_coeff_cache: Dict[int, np.ndarray] = {}


def _coeffs(nbytes: int) -> np.ndarray:
    """[nbytes] uint64 odd projection coefficients (fixed seeded stream)."""
    have = _coeff_cache.get(0)
    if have is None or len(have) < nbytes:
        n = max(4096, 1 << int(nbytes - 1).bit_length() if nbytes else 4096)
        rng = np.random.default_rng(_COEFF_SEED)
        have = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
        have = (have << _U64(1)) | _U64(1)   # force odd
        _coeff_cache[0] = have
    return have[:nbytes]


def splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer over uint64 (wraps mod 2^64)."""
    z = x.astype(np.uint64, copy=True)
    z += _GOLDEN
    z = (z ^ (z >> _U64(30))) * _MIX1
    z = (z ^ (z >> _U64(27))) * _MIX2
    return z ^ (z >> _U64(31))


def tag_seed(tag: str) -> np.uint64:
    """Stable per-artifact domain-separation seed."""
    h = hashlib.blake2b(tag.encode("utf-8"), digest_size=8).digest()
    return _U64(int.from_bytes(h, "little"))


def _payload_bytes(payload: np.ndarray) -> np.ndarray:
    """[n, ...] fixed-width payload -> [n, L] uint8 canonical bytes."""
    arr = np.ascontiguousarray(payload)
    if arr.ndim == 1:
        arr = arr[:, None]
    elif arr.ndim > 2:
        arr = arr.reshape(arr.shape[0], -1)
    return arr.view(np.uint8).reshape(arr.shape[0], -1)


def row_fingerprints(tag: str, ids: np.ndarray,
                     payload: np.ndarray) -> np.ndarray:
    """[n] uint64 fingerprints binding (id, payload row) under `tag`.

    `payload` is any fixed-width array [n, ...]; rows are digested over
    their canonical C-order bytes, so the same VALUES in the same dtype
    always fingerprint identically regardless of the device layout they
    were read back from."""
    ids = np.asarray(ids)
    if len(ids) == 0:
        return np.empty(0, np.uint64)
    raw = _payload_bytes(payload)
    if len(raw) != len(ids):
        raise ValueError(f"ids/payload length mismatch "
                         f"({len(ids)} vs {len(raw)})")
    proj = _project(raw)
    h_id = splitmix64(ids.astype(np.int64).view(np.uint64))
    return splitmix64(proj ^ h_id ^ tag_seed(tag))


def _project(raw: np.ndarray) -> np.ndarray:
    """[n, L] uint8 -> [n] uint64 coefficient projection, accumulated
    over column blocks so the uint64 widening temporary stays a few MB
    instead of 8x the whole payload (a 64K-slot scrub chunk at d=512
    would otherwise allocate ~2 GB transiently on the serving host)."""
    n, L = raw.shape
    coeff = _coeffs(L)
    # bound the widened temporary to ~32 MB: block_cols * n * 8 bytes
    block = max(16, (1 << 22) // max(1, n))
    proj = np.zeros(n, np.uint64)
    for j in range(0, L, block):
        blk = raw[:, j:j + block].astype(np.uint64)
        proj += (blk * coeff[j:j + block][None, :]).sum(
            axis=1, dtype=np.uint64
        )
    return proj


class SetDigest:
    """Order-invariant multiset digest: element count + two modular-sum
    lanes over row fingerprints. add/remove are exact inverses."""

    __slots__ = ("count", "s0", "s1")

    def __init__(self, count: int = 0,
                 s0: np.uint64 = _U64(0), s1: np.uint64 = _U64(0)):
        self.count = int(count)
        self.s0 = _U64(s0)
        self.s1 = _U64(s1)

    def add(self, fps: np.ndarray) -> None:
        self._fold(fps, +1)

    def remove(self, fps: np.ndarray) -> None:
        self._fold(fps, -1)

    def _fold(self, fps: np.ndarray, sign: int) -> None:
        """Modular sums in Python ints — numpy warns on SCALAR uint64
        wraparound even though wraparound is exactly the semantics here."""
        if len(fps):
            mask = (1 << 64) - 1
            self.count += sign * len(fps)
            self.s0 = _U64(
                (int(self.s0) + sign * int(fps.sum(dtype=np.uint64)))
                & mask
            )
            lane2 = int(splitmix64(fps ^ _LANE2).sum(dtype=np.uint64))
            self.s1 = _U64((int(self.s1) + sign * lane2) & mask)

    @classmethod
    def of(cls, fps: np.ndarray) -> "SetDigest":
        d = cls()
        d.add(np.asarray(fps, np.uint64))
        return d

    def copy(self) -> "SetDigest":
        return SetDigest(self.count, self.s0, self.s1)

    def hex(self) -> str:
        """Stable wire form `count-s0-s1` (rides heartbeats / meta.json)."""
        return f"{self.count:x}-{int(self.s0):016x}-{int(self.s1):016x}"

    @classmethod
    def from_hex(cls, text: str) -> Optional["SetDigest"]:
        try:
            c, s0, s1 = text.split("-")
            return cls(int(c, 16), _U64(int(s0, 16)), _U64(int(s1, 16)))
        except (ValueError, AttributeError):
            return None

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, SetDigest)
            and self.count == other.count
            and self.s0 == other.s0
            and self.s1 == other.s1
        )

    def __hash__(self):  # noqa: D105 — dict/set member in tests
        return hash((self.count, int(self.s0), int(self.s1)))

    def __repr__(self):  # noqa: D105
        return f"SetDigest({self.hex()})"
