"""Raft consensus core: election, replication, commit, snapshot install.

Reference mapping:
  RaftNode (src/raft/raft_node.h; Commit at raft_node.cc:124)  -> RaftNode
  StoreStateMachine::on_apply (store_state_machine.cc:110)     -> apply_fn
  on_leader_start / on_start_following (raft_vote_handler.cc)  -> callbacks
  braft replication + snapshot install                         -> ticker
      thread + InstallSnapshot RPC (engine checkpoint blob)

Original implementation of the Raft algorithm (Ongaro & Ousterhout) — the
reference uses braft; we need no external consensus library.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional

from dingo_tpu.common.log import get_logger
from dingo_tpu.raft.log import RaftLog
from dingo_tpu.raft.transport import Transport

_log = get_logger("raft.core")

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class NotLeader(Exception):
    def __init__(self, leader_hint: Optional[str] = None):
        super().__init__(f"not leader (hint: {leader_hint})")
        self.leader_hint = leader_hint


class ProposalFailed(Exception):
    pass


class RaftNode:
    def __init__(
        self,
        node_id: str,
        peers: List[str],
        transport: Transport,
        log: Optional[RaftLog] = None,
        apply_fn: Optional[Callable[[int, bytes], None]] = None,
        snapshot_save_fn: Optional[Callable[[], bytes]] = None,
        snapshot_install_fn: Optional[Callable[[bytes], None]] = None,
        on_leader_start: Optional[Callable[[int], None]] = None,
        on_start_following: Optional[Callable[[str, int], None]] = None,
        election_timeout: tuple = (0.15, 0.3),
        heartbeat_interval: float = 0.05,
        snapshot_threshold: int = 10_000,
        seed: Optional[int] = None,
    ):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.transport = transport
        self.log = log or RaftLog()
        self.apply_fn = apply_fn or (lambda i, p: None)
        self.snapshot_save_fn = snapshot_save_fn
        self.snapshot_install_fn = snapshot_install_fn
        self.on_leader_start = on_leader_start
        self.on_start_following = on_start_following
        self.election_timeout = election_timeout
        self.heartbeat_interval = heartbeat_interval
        self.snapshot_threshold = snapshot_threshold

        self._lock = threading.RLock()
        self._applied_cv = threading.Condition(self._lock)
        #: serializes state-machine application: apply_fn must run in log
        #: order and last_applied only advances AFTER apply_fn returns.
        self._apply_mutex = threading.Lock()
        self.role = FOLLOWER
        self.current_term, self.voted_for = self.log.hard_state()
        self.leader_id: Optional[str] = None
        self.commit_index = self.log.snapshot_index
        self.last_applied = self.log.snapshot_index
        self.next_index: Dict[str, int] = {}
        self.match_index: Dict[str, int] = {}
        self._rng = random.Random(seed if seed is not None else hash(node_id))
        self._deadline = time.monotonic() + self._rand_timeout()
        #: last time we heard from a live leader — drives pre-vote
        #: stickiness; must NOT be conflated with _deadline, which the
        #: node's own candidacy resets (that conflation livelocked
        #: failover: survivors mutually refused pre-votes)
        self._last_leader_contact = 0.0
        #: leader-side: last time each peer answered an RPC (check-quorum)
        self._peer_last_ack: Dict[str, float] = {}
        self._stop = threading.Event()
        self._appliers_busy = False

        transport.register(node_id, self._handle_rpc)
        self._ticker = threading.Thread(
            target=self._tick_loop, name=f"raft-{node_id}", daemon=True
        )

    # ------------- lifecycle -------------
    def start(self) -> None:
        self._ticker.start()

    def stop(self) -> None:
        self._stop.set()
        self.transport.unregister(self.id)
        if self._ticker.is_alive():
            self._ticker.join(timeout=2)
        self.log.close()

    def is_leader(self) -> bool:
        with self._lock:
            return self.role == LEADER

    # ------------- public: membership -------------
    def update_peers(self, peer_ids) -> None:
        """Single-step membership change (braft ChangePeers analog; the
        coordinator changes one server at a time, which keeps single-step
        reconfiguration safe). New peers start from next_index=1 and catch
        up via normal replication / snapshot install."""
        with self._lock:
            new_peers = [p for p in peer_ids if p != self.id]
            now = time.monotonic()
            for p in new_peers:
                if p not in self.next_index:
                    self.next_index[p] = self.log.last_index() + 1
                    self.match_index[p] = 0
                    # full check-quorum grace window, like a fresh leader:
                    # an epoch ack would count the new peer as
                    # unreachable-forever and could depose a healthy
                    # leader on the very tick the membership change applies
                    self._peer_last_ack[p] = now
            for p in list(self.next_index):
                if p not in new_peers and p != self.id:
                    self.next_index.pop(p, None)
                    self.match_index.pop(p, None)
                    self._peer_last_ack.pop(p, None)
            self.peers = new_peers

    # ------------- public: leadership transfer -------------
    def transfer_leadership(self, target: str) -> bool:
        """Ask `target` to campaign now; we step down on its higher term
        (RaftNode transfer-leader, raft_node.h)."""
        with self._lock:
            if self.role != LEADER or target not in self.peers:
                return False
        resp = self.transport.send(target, "timeout_now", {"from": self.id})
        return resp is not None and resp.get("ok", False)

    # ------------- public: propose (RaftNode::Commit) -------------
    def propose(self, payload: bytes, timeout: float = 5.0) -> int:
        """Append to the replicated log; blocks until applied locally.
        Returns the log index. Raises NotLeader / ProposalFailed."""
        from dingo_tpu.common.failpoint import failpoint

        failpoint("before_raft_propose")
        with self._lock:
            if self.role != LEADER:
                raise NotLeader(self.leader_id)
            term = self.current_term
            index = self.log.append(term, payload)
            self.match_index[self.id] = index
        self._broadcast_append()
        deadline = time.monotonic() + timeout
        with self._applied_cv:
            while self.last_applied < index:
                if self.log.term_at(index) != term:
                    raise ProposalFailed(f"entry {index} overwritten")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ProposalFailed(f"timeout waiting for apply {index}")
                self._applied_cv.wait(remaining)
            if self.log.term_at(index) not in (term, None):
                raise ProposalFailed(f"entry {index} overwritten")
        return index

    # ------------- ticker -------------
    def _persist_hard_state(self) -> None:
        """Raft safety: term/vote must survive restart or a node can vote
        twice in one term (election safety violation). Must hold _lock."""
        self.log.set_hard_state(self.current_term, self.voted_for)

    def _rand_timeout(self) -> float:
        lo, hi = self.election_timeout
        return lo + (hi - lo) * self._rng.random()

    def _tick_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                role = self.role
            if role == LEADER:
                self._broadcast_append()
                self._check_quorum()
                self._stop.wait(self.heartbeat_interval)
            else:
                now = time.monotonic()
                with self._lock:
                    expired = now >= self._deadline
                if expired:
                    self._start_election()
                else:
                    self._stop.wait(0.01)

    # ------------- election -------------
    def _pre_vote(self) -> bool:
        """Pre-vote phase (braft parity): probe a majority's willingness to
        vote for term+1 WITHOUT bumping our term. A partitioned node that
        keeps timing out cannot inflate its term and depose a healthy
        leader on rejoin; peers with a live leader refuse."""
        with self._lock:
            proposed = self.current_term + 1
            last_idx = self.log.last_index()
            last_term = self.log.last_term()
            # reset the deadline so we do not spin pre-votes back to back
            self._deadline = time.monotonic() + self._rand_timeout()
        granted = 1
        for peer in self.peers:
            resp = self.transport.send(peer, "pre_vote", {
                "from": self.id, "term": proposed,
                "last_log_index": last_idx, "last_log_term": last_term,
            })
            if resp is None:
                continue
            if resp["term"] > proposed - 1:
                # a peer is ahead: adopt its term so we can participate in
                # the real election instead of probing a stale term forever
                self._step_down(resp["term"])
                return False
            if resp.get("granted"):
                granted += 1
        quorum = (len(self.peers) + 1) // 2 + 1
        ok = granted >= quorum
        if not ok:
            # retry sooner than a full election timeout: pre-vote probes
            # disturb nobody, and a refused round usually means peers'
            # deadlines have not expired yet
            with self._lock:
                self._deadline = time.monotonic() + 0.5 * self._rand_timeout()
        return ok

    def _on_pre_vote(self, msg: dict) -> dict:
        with self._lock:
            # refuse while we believe a leader is alive: if WE are the
            # leader that is trivially true (a leader's own deadline is not
            # refreshed, so the time check below would wrongly lapse), and
            # for followers the deadline tracks recent leader contact —
            # leader stickiness is the whole point of pre-vote
            leader_alive = self.role == LEADER or (
                self.leader_id is not None
                and time.monotonic() - self._last_leader_contact
                < self.election_timeout[1]
            )
            up_to_date = (
                msg["last_log_term"], msg["last_log_index"]
            ) >= (self.log.last_term(), self.log.last_index())
            granted = (
                not leader_alive
                and msg["term"] > self.current_term
                and up_to_date
            )
            return {"term": self.current_term, "granted": granted}

    def _start_election(self, skip_pre_vote: bool = False) -> None:
        if not skip_pre_vote and self.peers and not self._pre_vote():
            return
        with self._lock:
            self.role = CANDIDATE
            self.current_term += 1
            term = self.current_term
            self.voted_for = self.id
            self._persist_hard_state()
            self.leader_id = None
            self._deadline = time.monotonic() + self._rand_timeout()
            last_idx = self.log.last_index()
            last_term = self.log.last_term()
        votes = 1
        for peer in self.peers:
            resp = self.transport.send(peer, "request_vote", {
                "from": self.id, "term": term, "last_log_index": last_idx,
                "last_log_term": last_term,
            })
            if resp is None:
                continue
            if resp["term"] > term:
                self._step_down(resp["term"])
                return
            if resp.get("granted"):
                votes += 1
        quorum = (len(self.peers) + 1) // 2 + 1
        with self._lock:
            if self.role != CANDIDATE or self.current_term != term:
                return
            if votes >= quorum:
                self.role = LEADER
                self.leader_id = self.id
                last = self.log.last_index()
                self.next_index = {p: last + 1 for p in self.peers}
                self.match_index = {p: 0 for p in self.peers}
                self.match_index[self.id] = last
                # fresh check-quorum clock: the new leader gets a full
                # window before reachability is judged
                now = time.monotonic()
                self._peer_last_ack = {p: now for p in self.peers}
                cb = self.on_leader_start
            else:
                return
        _log.info("%s became leader (term %d, last_index %d)",
                  self.id, term, last)
        if cb:
            cb(term)
        self._broadcast_append()

    def _step_down(self, term: int, leader: Optional[str] = None) -> None:
        cb = None
        with self._lock:
            if term > self.current_term:
                self.current_term = term
                self.voted_for = None
                self._persist_hard_state()
            was = self.role
            self.role = FOLLOWER
            if leader is not None and leader != self.leader_id:
                self.leader_id = leader
                cb = self.on_start_following
            self._deadline = time.monotonic() + self._rand_timeout()
        if cb and leader is not None:
            cb(leader, term)

    # ------------- replication (leader side) -------------
    def _check_quorum(self) -> None:
        """Check-quorum (braft parity): a leader that cannot reach a
        majority within ~2 election timeouts steps down. Without this, a
        partitioned-away leader keeps role=LEADER until it SEES a higher
        term — which the partition prevents — and the leader-gated read
        paths would serve reads missing the new leader's commits
        indefinitely. With it, the stale-read window is bounded by the
        check window."""
        window = 2.0 * self.election_timeout[1]
        with self._lock:
            if self.role != LEADER or not self.peers:
                return
            now = time.monotonic()
            reachable = 1 + sum(
                1 for p in self.peers
                if now - self._peer_last_ack.get(p, 0.0) <= window
            )
            quorum = (len(self.peers) + 1) // 2 + 1
            if reachable >= quorum:
                return
            self.role = FOLLOWER
            self.leader_id = None
            self._deadline = now + self._rand_timeout()
        _log.warning(
            "%s stepping down (check-quorum): %d/%d peers reachable in "
            "%.2fs window", self.id, reachable - 1, len(self.peers), window,
        )

    def _broadcast_append(self) -> None:
        for peer in self.peers:
            self._replicate_to(peer)
        self._advance_commit()

    def _replicate_to(self, peer: str) -> None:
        # Decide snapshot-vs-append under _lock, but CALL _send_snapshot
        # outside it: _send_snapshot takes _apply_mutex, and
        # _apply_committed takes _apply_mutex then _lock — calling it
        # while holding _lock inverts the lock order (deadlock).
        need_snapshot = False
        with self._lock:
            if self.role != LEADER:
                return
            term = self.current_term
            nxt = self.next_index.get(peer, self.log.last_index() + 1)
            # Follower too far behind the compacted log -> snapshot install
            if nxt <= self.log.snapshot_index:
                need_snapshot = True
            else:
                prev_index = nxt - 1
                prev_term = self.log.term_at(prev_index)
                if prev_term is None:
                    need_snapshot = True
                else:
                    entries = self.log.entries_from(nxt)
                    commit = self.commit_index
        if need_snapshot:
            self._send_snapshot(peer, term)
            return
        resp = self.transport.send(peer, "append_entries", {
            "from": self.id, "term": term, "prev_index": prev_index,
            "prev_term": prev_term, "entries": entries, "commit": commit,
        })
        if resp is None:
            return
        with self._lock:
            # any response proves reachability (check-quorum input)
            self._peer_last_ack[peer] = time.monotonic()
        if resp["term"] > term:
            self._step_down(resp["term"])
            return
        with self._lock:
            if self.role != LEADER or self.current_term != term:
                return
            if resp.get("ok"):
                if entries:
                    self.match_index[peer] = entries[-1][0]
                    self.next_index[peer] = entries[-1][0] + 1
                else:
                    self.match_index[peer] = max(
                        self.match_index.get(peer, 0), prev_index
                    )
            else:
                hint = resp.get("conflict_index")
                self.next_index[peer] = max(
                    1, hint if hint else self.next_index.get(peer, 2) - 1
                )

    def _send_snapshot(self, peer: str, term: int) -> None:
        if self.snapshot_save_fn is None:
            return
        # Hold the apply mutex so the blob reflects EXACTLY last_applied —
        # labeling it with a commit_index ahead of apply would make the
        # follower skip the gap entries forever (replica divergence).
        with self._apply_mutex:
            with self._lock:
                snap_index = self.last_applied
                snap_term = self.log.term_at(snap_index) or self.current_term
            blob = self.snapshot_save_fn()
        resp = self.transport.send(peer, "install_snapshot", {
            "from": self.id, "term": term, "snap_index": snap_index,
            "snap_term": snap_term, "blob": blob,
        })
        if resp is None:
            return
        with self._lock:
            self._peer_last_ack[peer] = time.monotonic()
        if resp["term"] > term:
            self._step_down(resp["term"])
            return
        with self._lock:
            if self.role == LEADER and resp.get("ok"):
                self.match_index[peer] = snap_index
                self.next_index[peer] = snap_index + 1

    def _advance_commit(self) -> None:
        with self._lock:
            if self.role != LEADER:
                return
            matches = sorted(self.match_index.values(), reverse=True)
            quorum = (len(self.peers) + 1) // 2 + 1
            candidate = matches[quorum - 1] if len(matches) >= quorum else 0
            # Raft safety: only commit entries from the current term directly
            if (
                candidate > self.commit_index
                and self.log.term_at(candidate) == self.current_term
            ):
                self.commit_index = candidate
        self._apply_committed()

    # ------------- RPC handlers (follower side) -------------
    def _handle_rpc(self, method: str, msg: dict) -> dict:
        if method == "request_vote":
            return self._on_request_vote(msg)
        if method == "pre_vote":
            return self._on_pre_vote(msg)
        if method == "timeout_now":
            # leadership transfer: start an election immediately, skipping
            # pre-vote (the current leader explicitly asked us to take
            # over; braft TransferLeadership analog)
            threading.Thread(
                target=self._start_election, kwargs={"skip_pre_vote": True},
                daemon=True,
            ).start()
            return {"term": self.current_term, "ok": True}
        if method == "append_entries":
            return self._on_append_entries(msg)
        if method == "install_snapshot":
            return self._on_install_snapshot(msg)
        return {"term": 0, "ok": False}

    def _on_request_vote(self, msg: dict) -> dict:
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "granted": False}
            if term > self.current_term:
                self.current_term = term
                self.voted_for = None
                self.role = FOLLOWER
                self._persist_hard_state()
            up_to_date = (
                msg["last_log_term"], msg["last_log_index"]
            ) >= (self.log.last_term(), self.log.last_index())
            if up_to_date and self.voted_for in (None, msg["from"]):
                self.voted_for = msg["from"]
                self._persist_hard_state()
                self._deadline = time.monotonic() + self._rand_timeout()
                return {"term": self.current_term, "granted": True}
            return {"term": self.current_term, "granted": False}

    def _on_append_entries(self, msg: dict) -> dict:
        to_apply = []
        cb = None
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "ok": False}
            if term > self.current_term:
                self.current_term = term
                self.voted_for = None
                self._persist_hard_state()
            self.role = FOLLOWER
            if msg["from"] != self.leader_id:
                self.leader_id = msg["from"]
                cb = self.on_start_following
            self._deadline = time.monotonic() + self._rand_timeout()
            self._last_leader_contact = time.monotonic()
            prev_index, prev_term = msg["prev_index"], msg["prev_term"]
            my_prev_term = self.log.term_at(prev_index)
            if my_prev_term is None or my_prev_term != prev_term:
                conflict = min(prev_index, self.log.last_index() + 1)
                # skip back over the conflicting term cheaply
                while (
                    conflict > self.log.first_index
                    and self.log.term_at(conflict - 1) == my_prev_term
                    and my_prev_term is not None
                ):
                    conflict -= 1
                return {
                    "term": self.current_term, "ok": False,
                    "conflict_index": max(conflict, 1),
                }
            for index, eterm, payload in msg["entries"]:
                existing = self.log.term_at(index)
                if existing != eterm:
                    self.log.put_at(index, eterm, payload)
            if msg["commit"] > self.commit_index:
                self.commit_index = min(msg["commit"], self.log.last_index())
            out = {"term": self.current_term, "ok": True}
        if cb:
            cb(msg["from"], msg["term"])
        self._apply_committed()
        return out

    def _on_install_snapshot(self, msg: dict) -> dict:
        with self._lock:
            term = msg["term"]
            if term < self.current_term:
                return {"term": self.current_term, "ok": False}
            if term > self.current_term:
                self.current_term = term
                self.voted_for = None
                self._persist_hard_state()
            self.role = FOLLOWER
            self.leader_id = msg["from"]
            self._deadline = time.monotonic() + self._rand_timeout()
            self._last_leader_contact = time.monotonic()
            if msg["snap_index"] <= self.log.snapshot_index:
                return {"term": self.current_term, "ok": True}
        _log.info("%s installing snapshot @%d (term %d) from %s",
                  self.id, msg["snap_index"], msg["snap_term"], msg["from"])
        with self._apply_mutex:  # no concurrent apply during state install
            if self.snapshot_install_fn:
                self.snapshot_install_fn(msg["blob"])
            with self._lock:
                self.log.install_snapshot_mark(
                    msg["snap_index"], msg["snap_term"]
                )
                self.commit_index = max(self.commit_index, msg["snap_index"])
                self.last_applied = max(self.last_applied, msg["snap_index"])
                self._applied_cv.notify_all()
        return {"term": self.current_term, "ok": True}

    # ------------- apply -------------
    def _apply_committed(self) -> None:
        """Apply committed entries IN ORDER; last_applied only advances
        after apply_fn returns, and a mutex serializes appliers across
        threads (ticker + RPC handlers) so the state machine never sees
        out-of-order or premature-visible applies."""
        applied_any = False
        with self._apply_mutex:
            while True:
                with self._lock:
                    nxt = self.last_applied + 1
                    if nxt > self.commit_index:
                        break
                    entry = self.log.entry_at(nxt)
                    if entry is None:
                        break
                    payload = entry[1]
                self.apply_fn(nxt, payload)
                applied_any = True
                with self._applied_cv:
                    self.last_applied = nxt
                    self._applied_cv.notify_all()
        if applied_any:
            self._maybe_snapshot()

    def _maybe_snapshot(self) -> None:
        """Log compaction once the retained tail exceeds the threshold
        (braft snapshot trigger analog)."""
        if self.snapshot_save_fn is None:
            return
        with self._apply_mutex:
            with self._lock:
                retained = self.last_applied - self.log.snapshot_index
                if retained < self.snapshot_threshold:
                    return
                upto = self.last_applied
            # blob reflects exactly last_applied (apply mutex held)
            self.snapshot_save_fn()
            with self._lock:
                self.log.compact(upto)
