"""context-handoff: thread handoffs must carry trace + budget context.

Trace context (PR 1) and the request budget (PR 10) both live in
contextvars, and contextvars do not cross threads. The repo's handoff
discipline: capture at the submission site (the coalescer opens the
``coalesce.wait`` span and reads ``current_budget()`` at submit, storing
both ON the entry), re-attach on the worker (``run_span.attach()``, the
flush thread consults ``entry.budget``). A ``threading.Thread`` or
``executor.submit`` that skips this silently orphans everything
downstream: device spans mint root traces instead of nesting under the
request, deadline checks read "no budget" and admit doomed work, and the
qos per-stage accounting loses the request it was accounting.

A handoff site passes when evidence of the discipline is visible to
static analysis — the spawned target (resolved through the call graph)
or the enclosing function references the capture/attach surface
(``current_span`` / ``attach`` / ``current_budget`` / ``wait_span`` /
``budget`` / ``copy_context``). Background loops that never carry a
request (crontab scheduler, metrics HTTP sidecar, heartbeat/raft
tickers) legitimately fail this test; they are adjudicated in the
baseline, each with its rationale, so a NEW thread spawn starts life
flagged and somebody has to say why it's exempt.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from tools.dingolint.callgraph import dotted_name
from tools.dingolint.core import Checker, Finding, Module, Repo

#: evidence that trace/budget context is being captured or re-attached
_EVIDENCE_RE = re.compile(
    r"\b(current_span|start_span|attach|attach_budget|current_budget|"
    r"copy_context|wait_span|budget)\b"
)


def _has_evidence(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    try:
        return bool(_EVIDENCE_RE.search(ast.unparse(node)))
    except Exception:  # pragma: no cover — unparse is total on parsed asts
        return False


class ContextHandoffChecker(Checker):
    name = "context-handoff"
    description = ("threading.Thread / executor submits must capture "
                   "trace + budget context (or be baselined as "
                   "context-free background loops)")

    def check_module(self, module: Module, repo: Repo) -> List[Finding]:
        cg = repo.callgraph()
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            parts = dotted_name(node.func)
            if not parts:
                continue
            kind = None
            if parts[-1] == "Thread" and (len(parts) == 1
                                          or parts[-2] == "threading"):
                if any(kw.arg == "target" for kw in node.keywords):
                    kind = "threading.Thread"
            elif parts[-1] == "submit" and len(parts) >= 2:
                kind = "submit"
            if kind is None:
                continue
            if self._handoff_ok(module, cg, node, kind):
                continue
            f = module.finding(
                self.name, node,
                f"{kind} handoff without visible trace/budget capture — "
                f"contextvars do not cross threads; capture "
                f"current_span()/current_budget() at the submit site and "
                f"re-attach on the worker (the PR 1/PR 10 coalescer "
                f"discipline), or baseline this site as a context-free "
                f"background loop",
            )
            if f:
                out.append(f)
        return out

    def _handoff_ok(self, module: Module, cg, node: ast.Call,
                    kind: str) -> bool:
        # the enclosing function already shows capture/attach work
        fn = module.enclosing_function(node)
        if _has_evidence(fn):
            return True
        # resolve the spawned target and inspect its body
        targets: List[ast.AST] = []
        if kind == "threading.Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    targets.append(kw.value)
        else:
            # receiver.submit(fn, ...) — the receiver's submit() AND the
            # submitted callable both count (the coalescer captures
            # inside submit(); a raw executor relies on the callable)
            exact, fuzzy = cg.resolve_call(module, node, None)
            for qual in sorted(exact | fuzzy):
                info = cg.funcs.get(qual)
                if info is not None and _has_evidence(info.node):
                    return True
            if node.args:
                targets.append(node.args[0])
        for tgt in targets:
            tparts = dotted_name(tgt)
            if tparts is None:
                # lambda / partial: inspect the expression itself
                if _has_evidence(tgt):
                    return True
                continue
            qual = self._resolve_target(module, cg, tgt, tparts)
            info = cg.funcs.get(qual) if qual else None
            if info is None:
                continue
            if _has_evidence(info.node):
                return True
            # one delegation hop: a dispatcher loop (the coalescer's
            # timer thread) may hand each batch to the function that
            # actually re-attaches context
            for callee in sorted(cg.callees(qual, fuzzy=False)):
                ci = cg.funcs.get(callee)
                if ci is not None and _has_evidence(ci.node):
                    return True
        return False

    @staticmethod
    def _resolve_target(module: Module, cg, tgt: ast.AST,
                        parts: List[str]) -> Optional[str]:
        # self.method / local function / imported function
        fake_call = ast.Call(func=tgt, args=[], keywords=[])
        ast.copy_location(fake_call, tgt)
        fake_call._dl_parent = getattr(  # type: ignore[attr-defined]
            tgt, "_dl_parent", None)
        cls = None
        cnode = module.enclosing_class(tgt)
        if cnode is not None:
            cls = getattr(cnode, "_dl_qual", cnode.name)
        exact, fuzzy = cg.resolve_call(module, fake_call, cls)
        for qual in sorted(exact) + sorted(fuzzy):
            if qual in cg.funcs:
                return qual
        # local nested def (target=work)
        if len(parts) == 1:
            for q in module.funcs:
                if q.rsplit(".", 1)[-1] == parts[0]:
                    return f"{module.name}.{q}"
        return None
