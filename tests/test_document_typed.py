"""Document subsystem depth: typed columns, range queries, query parser,
persisted schema (reference src/document/document_index.h over tantivy —
typed schema fields + query-language search)."""

import time

import pytest

from dingo_tpu.document.index import DocumentIndex, SchemaError
from dingo_tpu.document.query import (
    ColumnPredicate,
    QueryParseError,
    parse_query,
)

SCHEMA = {"title": "text", "body": "text", "price": "i64",
          "rating": "f64", "sku": "bytes", "in_stock": "bool"}


def make_index():
    idx = DocumentIndex(1, text_fields=("title", "body"), schema=SCHEMA)
    docs = [
        (1, {"title": "red shoes", "body": "comfortable running shoes",
             "price": 50, "rating": 4.5, "sku": b"A1", "in_stock": True}),
        (2, {"title": "blue shoes", "body": "stylish walking shoes",
             "price": 80, "rating": 3.9, "sku": b"B2", "in_stock": False}),
        (3, {"title": "red hat", "body": "warm winter hat",
             "price": 20, "rating": 4.9, "sku": b"C3", "in_stock": True}),
        (4, {"title": "green coat", "body": "waterproof hiking coat",
             "price": 150, "rating": 4.1, "sku": b"D4", "in_stock": True}),
    ]
    for did, doc in docs:
        idx.add(did, doc)
    return idx


def test_schema_validation():
    idx = DocumentIndex(1, schema={"price": "i64", "flag": "bool"})
    with pytest.raises(SchemaError):
        idx.add(1, {"text": "x", "price": "not a number"})
    with pytest.raises(SchemaError):
        idx.add(1, {"text": "x", "flag": 1})     # int is not bool
    with pytest.raises(SchemaError):
        idx.add(1, {"text": "x", "price": True})  # bool is not i64
    with pytest.raises(SchemaError):
        DocumentIndex(2, schema={"c": "decimal"})
    idx.add(1, {"text": "ok", "price": 5, "flag": True})
    assert idx.count() == 1


def test_range_select_typed_columns():
    idx = make_index()
    assert idx.range_select("price", lo=20, hi=80) == [1, 2, 3]
    assert idx.range_select("price", lo=20, hi=80, incl_lo=False) == [1, 2]
    assert idx.range_select("price", lo=None, hi=50) == [1, 3]
    assert idx.range_select("rating", lo=4.2) == [1, 3]
    assert idx.range_select("sku", lo=b"B", hi=b"D") == [2, 3]
    with pytest.raises(SchemaError):
        idx.range_select("in_stock")   # bool is not range-indexable
    # mutation invalidates the sorted column index
    idx.add(5, {"title": "socks", "body": "wool socks", "price": 10,
                "rating": 2.0, "sku": b"E5", "in_stock": True})
    assert idx.range_select("price", hi=15) == [5]
    idx.delete([5])
    assert idx.range_select("price", hi=15) == []


def test_query_parser():
    pq = parse_query('red +shoes -hat "running shoes" title:blue '
                     'price:[20 TO 80] rating:{4.0 TO *] in_stock:true',
                     SCHEMA)
    assert "red" in pq.terms and "shoes" in pq.terms
    assert pq.required == ["shoes"]
    assert pq.excluded == ["hat"]
    assert ["running", "shoes"] in pq.phrases
    assert ("title", "blue") in pq.field_terms
    ops = {(p.field, p.op) for p in pq.predicates}
    assert ("price", "range") in ops and ("rating", "range") in ops
    assert ("in_stock", "eq") in ops
    price = next(p for p in pq.predicates if p.field == "price")
    assert price.lo == 20 and price.hi == 80 and price.incl_lo
    rating = next(p for p in pq.predicates if p.field == "rating")
    assert rating.lo == 4.0 and not rating.incl_lo and rating.hi is None
    with pytest.raises(QueryParseError):
        parse_query("price:[x TO 9]", SCHEMA)
    assert parse_query("a b AND c").mode == "and"


def test_query_mode_search():
    idx = make_index()
    # text + typed range: red things under 60
    hits = idx.search("red price:[* TO 60]", mode="query")
    assert {d for d, _ in hits} == {1, 3}
    # required/excluded
    hits = idx.search("+shoes -blue", mode="query")
    assert {d for d, _ in hits} == {1}
    # phrase
    hits = idx.search('"running shoes"', mode="query")
    assert {d for d, _ in hits} == {1}
    # field-restricted term: 'red' in title only
    hits = idx.search("title:red", mode="query")
    assert {d for d, _ in hits} == {1, 3}
    hits = idx.search("title:running", mode="query")   # body-only word
    assert hits == []
    # pure column query (no text terms): range + bool eq
    hits = idx.search("price:[20 TO 100] in_stock:true", mode="query")
    assert {d for d, _ in hits} == {1, 3}
    # exclusive range bound
    hits = idx.search("price:{20 TO 100]", mode="query")
    assert {d for d, _ in hits} == {1, 2}
    # AND mode over text terms
    hits = idx.search("red shoes AND", mode="query")
    assert {d for d, _ in hits} == {1}


def test_schema_survives_save_load(tmp_path):
    idx = make_index()
    idx.apply_log_id = 77
    idx.save(str(tmp_path))
    idx2 = DocumentIndex(1)
    idx2.load(str(tmp_path))
    assert idx2.schema == SCHEMA
    assert idx2.apply_log_id == 77
    # typed queries work on the reloaded index (spans + columns derived)
    hits = idx2.search("title:red price:[* TO 60]", mode="query")
    assert {d for d, _ in hits} == {1, 3}
    assert idx2.range_select("price", lo=100) == [4]
    # validation still enforced after reload
    with pytest.raises(SchemaError):
        idx2.add(9, {"title": "x", "price": "bad"})


def test_typed_document_region_over_grpc():
    """Schema travels through CreateRegion; query-mode search over the
    wire (DocumentService) with typed predicates."""
    from dingo_tpu.client.client import DingoClient
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.raft import LocalTransport
    from dingo_tpu.server import pb
    from dingo_tpu.server.rpc import DingoServer
    from dingo_tpu.store.node import StoreNode
    from dingo_tpu.raft import wire

    transport = LocalTransport()
    me = MemEngine()
    control = CoordinatorControl(me, replication=3)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    nodes, servers, addrs = {}, [], {}
    for i, sid in enumerate(["s0", "s1", "s2"]):
        n = StoreNode(sid, transport, control, raft_kw={"seed": i})
        srv = DingoServer()
        srv.host_store_role(n)
        port = srv.start()
        n.start_heartbeat(0.1)
        nodes[sid] = n
        servers.append(srv)
        addrs[sid] = f"127.0.0.1:{port}"
    client = DingoClient(f"127.0.0.1:{cport}", addrs)
    try:
        d = client.create_document_region(
            0, 0, 1 << 40, schema={"text": "text", "price": "i64"})
        time.sleep(1.2)
        req = pb.DocumentAddRequest()
        req.context.region_id = d.region_id
        for did, text, price in ((1, "cheap red shirt", 10),
                                 (2, "expensive red coat", 200),
                                 (3, "cheap blue shirt", 12)):
            e = req.documents.add()
            e.id = did
            for k, v in (("text", text), ("price", price)):
                f = e.fields.add()
                f.key = k
                f.value = wire.encode_obj(v)
        resp = client._call_leader(d, "DocumentService", "DocumentAdd", req)
        assert resp.error.errcode == 0, resp.error.errmsg

        sreq = pb.DocumentSearchRequest()
        sreq.context.region_id = d.region_id
        sreq.query = "red price:[* TO 100]"
        sreq.mode = "query"
        sreq.top_n = 10
        sresp = client._call_leader(
            d, "DocumentService", "DocumentSearch", sreq)
        assert sresp.error.errcode == 0, sresp.error.errmsg
        assert [doc.id for doc in sresp.documents] == [1]
        # the leader rejects schema-invalid docs BEFORE the raft propose
        from dingo_tpu.client.client import ClientError

        breq = pb.DocumentAddRequest()
        breq.context.region_id = d.region_id
        e = breq.documents.add()
        e.id = 9
        f = e.fields.add()
        f.key = "price"
        f.value = wire.encode_obj("not a number")
        with pytest.raises(ClientError, match="expected i64"):
            client._call_leader(d, "DocumentService", "DocumentAdd", breq)
        # the bad doc never entered the log: count unchanged everywhere
        creq = pb.DocumentCountRequest()
        creq.context.region_id = d.region_id
        cresp = client._call_leader(
            d, "DocumentService", "DocumentCount", creq)
        assert cresp.count == 3
    finally:
        client.close()
        for s in servers:
            s.stop()
        cs.stop()
        for n in nodes.values():
            n.stop()


def test_negated_predicates_and_phrases():
    idx = make_index()
    # -range excludes the matching docs
    hits = idx.search("shoes -price:[60 TO 100]", mode="query")
    assert {d for d, _ in hits} == {1}
    # negated bool eq
    hits = idx.search("shoes -in_stock:true", mode="query")
    assert {d for d, _ in hits} == {2}
    # negated phrase
    hits = idx.search('shoes -"running shoes"', mode="query")
    assert {d for d, _ in hits} == {2}
    # all-negative column query evaluates against every doc
    hits = idx.search("-price:[40 TO 200]", mode="query")
    assert {d for d, _ in hits} == {3}


def test_schemaless_range_and_mixed_types():
    """Schemaless columns: range queries scan safely (mixed types cannot
    sort) and never serve a stale cache."""
    idx = DocumentIndex(1)
    idx.add(1, {"text": "a", "price": 10})
    idx.add(2, {"text": "b", "price": "cheap"})   # nothing rejects this
    idx.add(3, {"text": "c", "price": 30})
    hits = idx.search("price:[5 TO 20]", mode="query")
    assert {d for d, _ in hits} == {1}
    # mutations visible immediately (no stale sorted-column cache)
    idx.add(4, {"text": "d", "price": 7})
    hits = idx.search("price:[5 TO 20]", mode="query")
    assert {d for d, _ in hits} == {1, 4}
    idx.delete([1])
    hits = idx.search("price:[5 TO 20]", mode="query")
    assert {d for d, _ in hits} == {4}


def test_split_preserves_document_schema():
    """A split DOCUMENT region's child keeps the typed schema (a
    schemaless child would silently stop validating and mis-coerce
    query literals)."""
    from dingo_tpu.store.region import (
        Region,
        RegionDefinition,
        RegionType,
    )
    from dingo_tpu.index import codec as vcodec

    parent_def = RegionDefinition(
        region_id=50,
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1000),
        region_type=RegionType.DOCUMENT,
        document_schema={"price": "i64"},
    )
    parent = Region(parent_def)
    assert parent.document_index.schema == {"price": "i64"}
    # the split handler builds the child from the parent's definition
    import dataclasses as _dc

    child_def = _dc.replace(
        parent_def, region_id=51,
        start_key=vcodec.encode_vector_key(0, 500),
    )
    child = Region(child_def)
    assert child.document_index.schema == {"price": "i64"}
    with pytest.raises(SchemaError):
        child.document_index.add(1, {"text": "x", "price": "bad"})


def test_unknown_schema_type_rejected_at_coordinator():
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.store.region import RegionType

    control = CoordinatorControl(MemEngine(), replication=1)
    control.register_store("s0")
    with pytest.raises(RuntimeError, match="unknown document column"):
        control.create_region(
            b"a", b"z", region_type=RegionType.DOCUMENT,
            document_schema={"c": "decimal"},
        )


def test_cli_document_verbs(capsys):
    """Operator CLI: document create-region/add/search/count with a typed
    schema and query-language search."""
    import json as _json
    import time as _time

    from dingo_tpu.client.cli import main
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.raft import LocalTransport
    from dingo_tpu.server.rpc import DingoServer
    from dingo_tpu.store.node import StoreNode

    transport = LocalTransport()
    me = MemEngine()
    control = CoordinatorControl(me, replication=1)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    n = StoreNode("s0", transport, control, raft_kw={"seed": 0})
    srv = DingoServer()
    srv.host_store_role(n)
    port = srv.start()
    n.start_heartbeat(0.1)
    base = ["--coordinator", f"127.0.0.1:{cport}",
            "--store", f"s0=127.0.0.1:{port}"]
    try:
        assert main(base + ["document", "create-region",
                            "--schema", "text:text,price:i64"]) == 0
        rid = _json.loads(capsys.readouterr().out)["region_id"]
        _time.sleep(0.8)
        assert main(base + ["document", "add", "--region", str(rid),
                            "--id", "1", "text=cheap red shirt",
                            "price=10"]) == 0
        capsys.readouterr()
        assert main(base + ["document", "add", "--region", str(rid),
                            "--id", "2", "text=pricey red coat",
                            "price=200"]) == 0
        capsys.readouterr()
        assert main(base + ["document", "count", "--region",
                            str(rid)]) == 0
        assert _json.loads(capsys.readouterr().out)["count"] == 2
        assert main(base + ["document", "search", "--region", str(rid),
                            "red price:[* TO 100]"]) == 0
        hits = _json.loads(capsys.readouterr().out)
        assert [h[0] for h in hits] == [1]
    finally:
        srv.stop()
        cs.stop()
        n.stop()
