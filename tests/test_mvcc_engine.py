"""MVCC codec/reader + raw engine tests (mirrors reference test/unit_test/
mvcc/ and engine/ suites: codec roundtrips, version visibility, TTL,
delete-range, WAL recovery, checkpoints)."""

import os
import time

import numpy as np
import pytest

from dingo_tpu.engine.raw_engine import (
    CF_DEFAULT,
    MemEngine,
    SortedKv,
    WalEngine,
    WriteBatch,
)
from dingo_tpu.mvcc.codec import Codec, ValueFlag
from dingo_tpu.mvcc.reader import Reader, Writer
from dingo_tpu.mvcc.ts_provider import LocalTsOracle, TsProvider, decompose_ts


# ---------------- codec ----------------


def test_encode_bytes_roundtrip():
    for data in (b"", b"a", b"12345678", b"123456789", b"\x00\xff" * 9):
        enc = Codec.encode_bytes(data)
        dec, consumed = Codec.decode_bytes(enc)
        assert dec == data and consumed == len(enc)


def test_encode_bytes_order_preserving():
    keys = [b"", b"a", b"aa", b"ab", b"b", b"abcdefgh", b"abcdefgh\x00", b"abcdefghi"]
    encs = [Codec.encode_bytes(k) for k in keys]
    assert sorted(encs) == [Codec.encode_bytes(k) for k in sorted(keys)]


def test_key_ts_ordering():
    """Newer versions of the same key sort FIRST (inverted ts suffix)."""
    k10 = Codec.encode_key(b"k", 10)
    k20 = Codec.encode_key(b"k", 20)
    assert k20 < k10
    uk, ts = Codec.decode_key(k20)
    assert uk == b"k" and ts == 20


def test_value_flags():
    v = Codec.package_value(b"hello")
    assert Codec.unpackage_value(v) == (ValueFlag.PUT, b"hello", 0)
    v = Codec.package_value(b"x", ValueFlag.PUT_TTL, ttl_ms=12345)
    assert Codec.unpackage_value(v) == (ValueFlag.PUT_TTL, b"x", 12345)
    v = Codec.package_value(b"", ValueFlag.DELETE)
    assert Codec.unpackage_value(v)[0] is ValueFlag.DELETE


# ---------------- ts provider ----------------


def test_ts_monotonic():
    tp = TsProvider(batch_size=4)
    seen = [tp.get_ts() for _ in range(100)]
    assert all(b > a for a, b in zip(seen, seen[1:]))


def test_tso_format():
    oracle = LocalTsOracle()
    first, count = oracle.generate(10)
    phys, logical = decompose_ts(first)
    assert abs(phys - time.time() * 1000) < 5000
    assert count == 10


# ---------------- sorted kv / engines ----------------


def test_sorted_kv_scan():
    kv = SortedKv()
    for i in (3, 1, 2, 9, 5):
        kv.put(f"k{i}".encode(), f"v{i}".encode())
    assert [k for k, _ in kv.scan(b"k2", b"k5")] == [b"k2", b"k3"]
    assert [k for k, _ in kv.scan_reverse(b"k2", b"k9")] == [b"k5", b"k3", b"k2"]
    assert kv.delete_range(b"k1", b"k3") == 2
    assert len(kv) == 3


def test_mem_engine_batch_atomicity():
    eng = MemEngine()
    batch = (
        WriteBatch()
        .put(CF_DEFAULT, b"a", b"1")
        .put("lock", b"a", b"L")
        .delete(CF_DEFAULT, b"missing")
    )
    eng.write(batch)
    assert eng.get(CF_DEFAULT, b"a") == b"1"
    assert eng.get("lock", b"a") == b"L"


def test_wal_engine_recovery(tmp_path):
    path = str(tmp_path / "eng")
    eng = WalEngine(path)
    eng.put(CF_DEFAULT, b"k1", b"v1")
    eng.put(CF_DEFAULT, b"k2", b"v2")
    eng.delete(CF_DEFAULT, b"k1")
    eng.close()
    eng2 = WalEngine(path)
    assert eng2.get(CF_DEFAULT, b"k1") is None
    assert eng2.get(CF_DEFAULT, b"k2") == b"v2"
    eng2.close()


def test_wal_engine_checkpoint_truncates(tmp_path):
    path = str(tmp_path / "eng")
    eng = WalEngine(path)
    for i in range(100):
        eng.put(CF_DEFAULT, f"k{i}".encode(), b"v")
    eng.checkpoint()
    assert os.path.getsize(os.path.join(path, "wal.log")) == 0
    eng.put(CF_DEFAULT, b"post", b"1")
    eng.close()
    eng2 = WalEngine(path)
    assert eng2.get(CF_DEFAULT, b"k50") == b"v"
    assert eng2.get(CF_DEFAULT, b"post") == b"1"
    eng2.close()


def test_wal_engine_torn_tail(tmp_path):
    path = str(tmp_path / "eng")
    eng = WalEngine(path)
    eng.put(CF_DEFAULT, b"good", b"1")
    eng.close()
    with open(os.path.join(path, "wal.log"), "ab") as f:
        f.write(b"\xde\xad\xbe\xef garbage")
    eng2 = WalEngine(path)
    assert eng2.get(CF_DEFAULT, b"good") == b"1"
    eng2.close()


# ---------------- mvcc reader/writer ----------------


def test_mvcc_visibility():
    eng = MemEngine()
    w = Writer(eng, CF_DEFAULT)
    r = Reader(eng, CF_DEFAULT)
    w.kv_put(b"k", b"v1", ts=10)
    w.kv_put(b"k", b"v2", ts=20)
    assert r.kv_get(b"k", 15) == b"v1"
    assert r.kv_get(b"k", 25) == b"v2"
    assert r.kv_get(b"k", 5) is None
    w.kv_delete(b"k", ts=30)
    assert r.kv_get(b"k", 35) is None
    assert r.kv_get(b"k", 25) == b"v2"  # old snapshot still sees it


def test_mvcc_ttl():
    eng = MemEngine()
    w = Writer(eng, CF_DEFAULT)
    r = Reader(eng, CF_DEFAULT)
    past = int(time.time() * 1000) - 1000
    future = int(time.time() * 1000) + 60_000
    w.kv_put(b"dead", b"x", ts=1, ttl_ms=past)
    w.kv_put(b"alive", b"y", ts=1, ttl_ms=future)
    assert r.kv_get(b"dead", 10) is None
    assert r.kv_get(b"alive", 10) == b"y"


def test_mvcc_scan_skips_versions_and_deletes():
    eng = MemEngine()
    w = Writer(eng, CF_DEFAULT)
    r = Reader(eng, CF_DEFAULT)
    for i in range(5):
        key = f"k{i}".encode()
        w.kv_put(key, b"old", ts=10)
        w.kv_put(key, f"new{i}".encode(), ts=20)
    w.kv_delete(b"k2", ts=25)
    got = r.kv_scan(b"k0", b"k9", ts=30)
    assert [k for k, _ in got] == [b"k0", b"k1", b"k3", b"k4"]
    assert dict(got)[b"k3"] == b"new3"
    got15 = r.kv_scan(b"k0", b"k9", ts=15)
    assert all(v == b"old" for _, v in got15) and len(got15) == 5


def test_mvcc_scan_limit():
    eng = MemEngine()
    w = Writer(eng, CF_DEFAULT)
    r = Reader(eng, CF_DEFAULT)
    for i in range(10):
        w.kv_put(f"k{i}".encode(), b"v", ts=1)
    assert len(r.kv_scan(b"k0", b"k9", ts=5, limit=3)) == 3
    assert r.kv_count(b"k0", b"k99", ts=5) == 10
