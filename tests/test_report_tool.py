"""Report generator (reference src/report/ role): junitxml -> JSON + HTML."""

import json
import subprocess
import sys


JUNIT = """<?xml version="1.0"?>
<testsuites>
 <testsuite name="pytest" time="1.5">
  <testcase classname="tests.test_a" name="test_ok" time="0.5"/>
  <testcase classname="tests.test_a" name="test_bad" time="0.2">
    <failure message="assert 1 == 2">trace</failure>
  </testcase>
  <testcase classname="tests.test_b" name="test_skip" time="0.0">
    <skipped message="no tpu"/>
  </testcase>
 </testsuite>
</testsuites>
"""


def test_report_generation(tmp_path):
    junit = tmp_path / "junit.xml"
    junit.write_text(JUNIT)
    out = tmp_path / "out"
    proc = subprocess.run(
        [sys.executable, "tools/report.py", str(junit), str(out)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1  # failures present -> nonzero
    data = json.loads((out / "report.json").read_text())
    assert data["total"] == 3 and data["passed"] == 1
    assert data["failed"] == 1 and data["skipped"] == 1
    names = {s["name"] for s in data["suites"]}
    assert names == {"tests.test_a", "tests.test_b"}
    page = (out / "report.html").read_text()
    assert "test_bad" in page and "assert 1 == 2" in page
    # failing suites render auto-expanded; passing ones collapsed
    assert "<details open>" in page
    assert "('', '')" not in page
