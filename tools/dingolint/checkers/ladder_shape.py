"""ladder-shape: static args of sentinel kernels come off the ladders.

The "steady state never recompiles" invariant (PR 3, monitored since
PR 5) holds because every integer that becomes a jitted program's static
argument or padded dimension is drawn from a small closed set: the pow2
ladders (``_next_pow2`` / ``_pad_batch`` / ``pad_query_batch`` and the
{1,1.25,1.5,1.75}x bucket-alloc ladder), conf-pinned constants, and
tuner knobs that only ever take ladder values. Mint one static arg
directly from data (``k=len(queries)``, ``bucket=rows.shape[0]``) and
every novel workload size compiles a novel program: the jit cache grows
without bound and each growth step is a 100ms-40s serving stall that no
unit test sees, because unit tests run one shape.

The checker finds every sentinel-wrapped kernel in the repo (decorator
form ``@sentinel_jit(name, static_argnames=...)`` and call form
``x = sentinel_jit(name, fn, static_argnames=...)``), maps its static
argnames through the wrapped function's signature, and at every call
site checks the expression feeding each static arg: an expression that
visibly derives from data size — contains ``len(...)`` or a ``.shape``
access — must also contain a ladder call. One hop of local dataflow is
followed (``n = len(q); kernel(..., k=n)`` is still flagged). Params,
attributes, literals, and conf reads pass: their mint sites are checked
where they mint.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.dingolint.callgraph import dotted_name
from tools.dingolint.core import Checker, Finding, Module, Repo

#: the sanctioned shape-ladder helpers (grep-verified defs): an
#: expression containing a call to one of these is ladder-derived by
#: construction. Extend the set when a new ladder helper lands — that's
#: explicit on purpose, like FAMILY_NAMES in metric-names.
LADDER_FUNCS = {
    "_next_pow2",       # index/slot_store.py, ops/scatter.py
    "_prev_pow2",       # common/coalescer.py (flush threshold)
    "_pad_batch",       # index/flat.py (pow2 batch pad)
    "pad_query_batch",  # parallel/sharded_store.py (batch-axis ladder)
    "shape_bucket",     # index/ivf_layout.py ({1,1.25,1.5,1.75}x-pow2)
    "_shape_buckets",   # index/ivf_flat.py ((topk, nprobe) bucketing)
    "_beam_width",      # index/hnsw.py (ef -> beam {1,1.5}x-pow2)
    "resolve_dim_block",  # ops/blocked.py (conf-pinned dim tiling)
    "ladder_values",    # obs/tuner.py (warm knob ladder)
    "ladder_step",
}


class _KernelSig:
    __slots__ = ("kernel", "static", "params", "posmap", "module")

    def __init__(self, kernel: str, static: Set[str],
                 params: List[str], module: str):
        self.kernel = kernel          #: sentinel name, for messages
        self.static = static          #: static_argnames
        self.params = params          #: positional parameter names
        self.posmap = {i: p for i, p in enumerate(params)}
        self.module = module          #: defining module (disambiguation)


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names: Set[str] = set()
            for sub in ast.walk(kw.value):
                if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str):
                    names.add(sub.value)
            return names
    return set()


def _kernel_name(call: ast.Call) -> str:
    if call.args and isinstance(call.args[0], ast.Constant):
        return str(call.args[0].value)
    return "?"


def collect_kernels(repo: Repo) -> Dict[str, List[_KernelSig]]:
    """callable-basename -> signatures, for every sentinel wrapper with
    static argnames. Call-form wrappers assigned to ``self._x_jit`` are
    keyed by the attribute basename. A basename may map to SEVERAL sigs
    (same-named wrappers in different modules) — the call-site check
    disambiguates by defining module and skips when it can't, rather
    than checking against the wrong posmap."""
    out: Dict[str, List[_KernelSig]] = {}
    for module in repo.modules:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call):
                        continue
                    parts = dotted_name(dec.func)
                    if not parts or parts[-1] != "sentinel_jit":
                        continue
                    static = _static_argnames(dec)
                    if static:
                        params = [a.arg for a in node.args.args]
                        out.setdefault(node.name, []).append(_KernelSig(
                            _kernel_name(dec), static, params,
                            module.name))
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                parts = dotted_name(node.value.func)
                if not parts or parts[-1] != "sentinel_jit":
                    continue
                static = _static_argnames(node.value)
                if not static:
                    continue
                # resolve the wrapped fn's params when it is a local name
                params: List[str] = []
                if len(node.value.args) >= 2 and isinstance(
                        node.value.args[1], ast.Name):
                    fnode = module.funcs.get(node.value.args[1].id) or \
                        next((n for q, n in module.funcs.items()
                              if q.rsplit(".", 1)[-1]
                              == node.value.args[1].id), None)
                    if fnode is not None:
                        params = [a.arg for a in fnode.args.args]
                for tgt in node.targets:
                    tparts = dotted_name(tgt)
                    if tparts:
                        out.setdefault(tparts[-1], []).append(_KernelSig(
                            _kernel_name(node.value), static, params,
                            module.name))
    return out


def _pick_sig(sigs: List[_KernelSig], module: Module,
              repo: Repo, call: ast.Call) -> Optional[_KernelSig]:
    """Disambiguate same-basename wrappers: unique sig wins; otherwise
    prefer the one whose defining module the call resolves into (exact
    call-graph edge), then the caller's own module; ambiguous -> None."""
    if len(sigs) == 1:
        return sigs[0]
    cg = repo.callgraph()
    cnode = module.enclosing_class(call)
    cls = getattr(cnode, "_dl_qual", cnode.name) if cnode else None
    exact, _fuzzy = cg.resolve_call(module, call, cls)
    mods = {q.rsplit(".", 1)[0] for q in exact}
    hits = [s for s in sigs if s.module in mods]
    if len(hits) == 1:
        return hits[0]
    local = [s for s in sigs if s.module == module.name]
    if len(local) == 1:
        return local[0]
    return None


def _contains_ladder(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            parts = dotted_name(sub.func)
            if parts and parts[-1] in LADDER_FUNCS:
                return True
    return False


def _derives_from_data(expr: ast.AST) -> bool:
    """True when the expression visibly mints a value from data size:
    a len() call or a .shape access anywhere inside it."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "len":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "shape":
            return True
    return False


def _local_assignment(module: Module, fn: ast.AST, qual: str,
                      name: str) -> Optional[ast.AST]:
    """The value expression of the (last) simple local assignment to
    `name` inside `fn` — one dataflow hop."""
    found: Optional[ast.AST] = None
    for node in ast.walk(fn):
        if module.qualname_of(node) != qual:
            continue
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    found = node.value
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.target, ast.Name) and node.target.id == name:
            found = node
    return found


class LadderShapeChecker(Checker):
    name = "ladder-shape"
    description = ("static args of sentinel kernels must not mint "
                   "data-derived shapes without a ladder helper")

    def check_repo(self, repo: Repo) -> List[Finding]:
        kernels = collect_kernels(repo)
        out: List[Finding] = []
        for module in repo.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    continue
                parts = dotted_name(node.func)
                if not parts:
                    continue
                sigs = kernels.get(parts[-1])
                if not sigs:
                    continue
                sig = _pick_sig(sigs, module, repo, node)
                if sig is None:
                    continue
                fn = module.enclosing_function(node)
                qual = module.qualname_of(node)
                for pname, expr in self._static_args(node, sig):
                    bad = self._off_ladder(module, fn, qual, expr)
                    if bad is None:
                        continue
                    f = module.finding(
                        self.name, node,
                        f"static arg {pname!r} of sentinel kernel "
                        f"{sig.kernel!r} is minted from data size "
                        f"({bad}) without a ladder helper — every novel "
                        f"workload size will compile a novel program; "
                        f"route it through _next_pow2/_pad_batch or a "
                        f"declared ladder",
                    )
                    if f:
                        out.append(f)
        return out

    @staticmethod
    def _static_args(call: ast.Call, sig: _KernelSig
                     ) -> List[Tuple[str, ast.AST]]:
        pairs: List[Tuple[str, ast.AST]] = []
        for kw in call.keywords:
            if kw.arg in sig.static:
                pairs.append((kw.arg, kw.value))
        for i, arg in enumerate(call.args):
            pname = sig.posmap.get(i)
            if pname in sig.static:
                pairs.append((pname, arg))
        return pairs

    def _off_ladder(self, module: Module, fn: Optional[ast.AST],
                    qual: str, expr: ast.AST) -> Optional[str]:
        """Why the expression is off-ladder, or None when it's fine."""
        if _contains_ladder(expr):
            return None
        if _derives_from_data(expr):
            return ast.unparse(expr)
        # one hop: a bare local name assigned from a data-derived expr
        if isinstance(expr, ast.Name) and fn is not None:
            src = _local_assignment(module, fn, qual, expr.id)
            if src is not None and not _contains_ladder(src) \
                    and _derives_from_data(src):
                return f"{expr.id} = {ast.unparse(src)}"
        return None
