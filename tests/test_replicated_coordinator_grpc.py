"""Replicated coordinator over REAL grpc sockets + RemoteHeartbeat failover.

Three coordinator processes-worth of RaftMetaCoordinator, each behind its
own DingoServer with a GrpcRaftTransport (the --coor-peers deployment shape
from server/main.py), plus a store heartbeating through RemoteHeartbeat
with the full endpoint list. Verifies: NotLeader rotation, ack-based queue
pruning, and command delivery surviving a coordinator leader kill.
"""

import time

import pytest

from dingo_tpu.coordinator.raft_meta import RaftMetaCoordinator
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.raft.grpc_transport import GrpcRaftTransport
from dingo_tpu.raft.transport import LocalTransport
from dingo_tpu.server.remote_heartbeat import RemoteHeartbeat
from dingo_tpu.server.rpc import DingoServer
from dingo_tpu.store.node import StoreNode

COORS = ["coor0", "coor1", "coor2"]
FAST = dict(election_timeout=(0.1, 0.25), heartbeat_interval=0.04)


@pytest.fixture()
def coor_group():
    coords, servers, transports, addrs = [], [], [], {}
    for i, cid in enumerate(COORS):
        t = GrpcRaftTransport(cid)
        c = RaftMetaCoordinator(cid, COORS, t, MemEngine(),
                                **FAST, seed=i)
        srv = DingoServer()
        srv.host_coordinator_role(c.control, c.tso, c.kv, meta=c.meta,
                                  raft_transport=t)
        port = srv.start()
        addrs[cid] = f"127.0.0.1:{port}"
        coords.append(c)
        servers.append(srv)
        transports.append(t)
    for t in transports:
        for cid, addr in addrs.items():
            t.set_peer(cid, addr)
    for c in coords:
        c.start()
    yield coords, servers, addrs
    for c in coords:
        try:
            c.stop()
        except Exception:
            pass
    for s in servers:
        s.stop()
    for t in transports:
        t.close()


def wait_leader(coords, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for c in coords:
            if c.is_leader():
                return c
        time.sleep(0.02)
    raise AssertionError("no coordinator leader")


def test_remote_heartbeat_rotates_to_leader_and_acks(coor_group):
    coords, _servers, addrs = coor_group
    leader = wait_leader(coords)
    follower = next(c for c in coords if c is not leader)
    follower_first = [addrs[follower.node.id]] + [
        a for cid, a in addrs.items() if cid != follower.node.id
    ]
    # store's endpoint list deliberately starts at a FOLLOWER
    store = StoreNode("s1", LocalTransport(), coordinator=None)
    hb = RemoteHeartbeat(store, ",".join(follower_first))
    hb.beat()   # must rotate to the leader instead of silently no-oping
    assert "s1" in leader.sm.control.stores

    # queue a region create; next beat executes + acks; the beat after
    # that must show a pruned queue on the coordinator
    definition = leader.control.create_region(b"a", b"z", replication=1)
    executed = 0
    deadline = time.monotonic() + 5
    while executed == 0 and time.monotonic() < deadline:
        executed = hb.beat()
        time.sleep(0.05)
    assert executed == 1
    assert store.get_region(definition.region_id) is not None
    hb.beat()   # carries the ack
    assert leader.sm.control.store_ops.get("s1") == []


def test_command_delivery_survives_coordinator_leader_kill(coor_group):
    coords, servers, addrs = coor_group
    leader = wait_leader(coords)
    store = StoreNode("s1", LocalTransport(), coordinator=None)
    hb = RemoteHeartbeat(store, ",".join(addrs.values()))
    hb.beat()
    definition = leader.control.create_region(b"a", b"z", replication=1)
    # deliver once ('sent') but DON'T let the store ack or execute: simulate
    # by asking the coordinator directly, bypassing hb
    leader.control.store_heartbeat("s1")
    # kill the leader PROCESS (raft node + its grpc server)
    servers[coords.index(leader)].stop()
    leader.stop()
    survivors = [c for c in coords if c is not leader]
    new_leader = wait_leader(survivors)
    # new leader re-arms 'sent' cmds; the store's next beats (rotating to
    # the new leader) must execute the create exactly once
    executed, deadline = 0, time.monotonic() + 8
    while executed == 0 and time.monotonic() < deadline:
        try:
            executed += hb.beat()
        except Exception:
            pass
        time.sleep(0.05)
    assert executed == 1
    assert store.get_region(definition.region_id) is not None
    # and once more: no duplicate execution on further beats
    assert hb.beat() == 0
    assert new_leader.sm.control.store_ops.get("s1") == []


def test_sdk_rotates_on_coordinator_leader_kill(coor_group):
    """SDK coordinator-group failover (reference SDK + br take coordinator
    LISTS): the client gets all three endpoints, the leader's server is
    killed mid-workload, and the client finishes against the new leader."""
    from dingo_tpu.client.client import ClientError, DingoClient

    coords, servers, addrs = coor_group
    leader = wait_leader(coords)
    # endpoint list deliberately starts at the CURRENT leader so the kill
    # strands the active channel, not a follower
    ordered = [addrs[leader.node.id]] + [
        a for cid, a in addrs.items() if cid != leader.node.id
    ]
    client = DingoClient(",".join(ordered), {})
    try:
        ts1 = client.tso()
        client.create_schema("failover_schema")

        idx = coords.index(leader)
        servers[idx].stop()
        leader.stop()

        # workload continues once a new leader is up; the client must
        # rotate there on its own
        ts2 = None
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            try:
                ts2 = client.tso()
                break
            except ClientError:
                time.sleep(0.3)
        assert ts2 is not None and ts2 > ts1, "client never recovered"
        # the pre-kill mutation survived the failover (raft-replicated)
        assert "failover_schema" in client.get_schemas()
        # and new mutations land on the new leader
        client.create_schema("post_failover_schema")
        assert "post_failover_schema" in client.get_schemas()
    finally:
        client.close()
