"""Raft consensus layer.

Mirrors reference src/raft/ (RaftNode over braft, raft_node.h;
StoreStateMachine, store_state_machine.h) + src/log/ (RocksLogStorage /
SegmentLogStorage). This is an original Raft implementation (leader election,
log replication, commit, snapshot/compaction) with a pluggable transport:
in-process LocalTransport for the reference-style single-process multi-peer
tests (test_raft_node.cc:125-199), grpc for real deployments.
"""

from dingo_tpu.raft.core import RaftNode, NotLeader  # noqa: F401
from dingo_tpu.raft.log import RaftLog  # noqa: F401
from dingo_tpu.raft.transport import LocalTransport  # noqa: F401
