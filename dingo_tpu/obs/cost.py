"""Per-(kernel, padded-shape) dispatch cost model.

The coalescer's admission control used to price every queued row with
ONE scalar per-row EWMA — a global average over every kernel family and
batch shape the process ever ran. That is wrong in both directions: a
small-k FLAT dispatch and a wide-beam HNSW dispatch can differ by an
order of magnitude per row, and padded execution means cost steps at
the pad-ladder points rather than scaling linearly. This module learns
the real surface from the timings the completion lane already records:

- **Key.** (kernel id, padded-rows ladder point). The kernel id is
  derived from the coalescer key — (region, topn, params) IS one
  compiled-program family — and the rows axis uses the serving shape
  ladder (index/ivf_layout.shape_bucket), so the model's support is
  exactly the set of programs XLA actually compiled.
- **Learning.** Every dispatch completion feeds ``note(kernel, rows,
  run_ms)``: an EWMA per ladder point (alpha 0.3, the coalescer's own
  smoothing) plus a per-kernel per-row rate for interpolation between
  points, and a per-region run-time/row-rate aggregate for the SLO
  tuner and heartbeats.
- **Estimating.** ``estimate_run_ms(kernel, rows)`` answers from the
  exact ladder point when it has one, interpolates/extrapolates from
  the nearest measured point otherwise, and falls back to the
  ``cost.prior_row_ms`` conservative prior when the kernel has never
  been measured — so the FIRST overload burst sheds on a pessimistic
  estimate instead of riding in on the old ``return 0.0`` cold-start
  hole (coalescer satellite fix).
- **Shape.** ``cost.*`` curated family; per-region row-rate rides
  heartbeats (RegionMetricsSnapshot.cost_row_us) into the coordinator's
  capacity rollups and flight bundles.

Everything here is host-side dict math under one lock — safe to call
from the completion lane's resolve and the admission path.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, Optional, Tuple

from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS

_log = get_logger("obs.cost")

#: EWMA smoothing for per-point run times (the coalescer's own alpha)
_ALPHA = 0.3

#: per-kernel ladder points kept (pad ladders are short; this is a
#: runaway bound, not a working limit)
_MAX_POINTS = 64

#: publication throttle: gauges update on note() at most this often
#: per kernel (completion-lane rate can be thousands/s)
_PUBLISH_EVERY = 16


def cost_enabled() -> bool:
    from dingo_tpu.common.config import FLAGS

    try:
        return bool(FLAGS.get("cost_enabled"))
    except KeyError:
        return True


def prior_row_ms() -> float:
    from dingo_tpu.common.config import FLAGS

    try:
        return max(0.0, float(FLAGS.get("cost_prior_row_ms")))
    except KeyError:
        return 0.5


def _ladder(rows: int) -> int:
    """Rows -> the serving pad-ladder point ({1,1.5}x-pow2, the shape
    discipline every dispatch actually compiles at)."""
    try:
        from dingo_tpu.index.ivf_layout import shape_bucket

        return int(shape_bucket(max(1, int(rows))))
    except Exception:  # noqa: BLE001 — unit contexts without the index
        r, p = max(1, int(rows)), 1                       # package
        while p < r:
            p *= 2
        return p


def kernel_id(key: Any) -> str:
    """Stable kernel-family id from a coalescer key. The canonical key
    is (region_id, topn, params-tuple); params collapse to a short hash
    so metric labels stay bounded. Any other hashable (test fakes)
    falls back to its repr."""
    if isinstance(key, tuple) and len(key) >= 2 \
            and isinstance(key[0], int):
        tail = ""
        if len(key) > 2 and key[2]:
            h = hashlib.blake2s(repr(key[2:]).encode(),
                                digest_size=4).hexdigest()
            tail = f":{h}"
        return f"r{key[0]}:k{key[1]}{tail}"
    return repr(key)[:48]


def kernel_region(key: Any) -> Optional[int]:
    if isinstance(key, tuple) and key and isinstance(key[0], int):
        return key[0]
    return None


class _KernelModel:
    __slots__ = ("points", "row_ms", "samples")

    def __init__(self):
        #: ladder rows -> EWMA total run ms at that point
        self.points: Dict[int, float] = {}
        #: per-row rate EWMA across points (interpolation fallback)
        self.row_ms = 0.0
        self.samples = 0


class CostModel:
    """Process-global dispatch cost model (``COST``)."""

    def __init__(self, registry=METRICS):
        self.registry = registry
        self._lock = threading.Lock()
        self._kernels: Dict[str, _KernelModel] = {}
        #: region -> (EWMA run ms of its typical dispatch, EWMA row ms)
        self._regions: Dict[int, Tuple[float, float]] = {}

    # -- learning -----------------------------------------------------------
    def note(self, kernel: str, rows: int, run_ms: float,
             region_id: Optional[int] = None) -> None:
        """Feed one completed dispatch (completion lane / serial run
        path). ``rows`` is the UNPADDED row count; the ladder point it
        compiled at is recomputed here so caller and model can never
        disagree about the axis."""
        if not cost_enabled():
            return
        rows = int(rows)
        if rows <= 0 or run_ms <= 0.0:
            return
        point = _ladder(rows)
        per_row = run_ms / point
        with self._lock:
            km = self._kernels.get(kernel)
            if km is None:
                km = self._kernels[kernel] = _KernelModel()
            cur = km.points.get(point)
            km.points[point] = run_ms if cur is None else (
                (1.0 - _ALPHA) * cur + _ALPHA * run_ms)
            km.row_ms = per_row if km.samples == 0 else (
                (1.0 - _ALPHA) * km.row_ms + _ALPHA * per_row)
            km.samples += 1
            samples = km.samples
            if len(km.points) > _MAX_POINTS:
                km.points.pop(min(km.points))
            if region_id is not None:
                r_run, r_row = self._regions.get(region_id, (0.0, 0.0))
                first = r_run == 0.0 and r_row == 0.0
                self._regions[region_id] = (
                    run_ms if first else
                    (1.0 - _ALPHA) * r_run + _ALPHA * run_ms,
                    per_row if first else
                    (1.0 - _ALPHA) * r_row + _ALPHA * per_row,
                )
            point_ms = km.points[point]
            row_ms = km.row_ms
        if samples == 1 or samples % _PUBLISH_EVERY == 0:
            labels = {"kernel": kernel, "rows": str(point)}
            self.registry.gauge("cost.run_ms", region_id,
                                labels).set(round(point_ms, 4))
            self.registry.gauge(
                "cost.row_us", region_id,
                {"kernel": kernel}).set(round(row_ms * 1000.0, 3))
            self.registry.counter("cost.samples", region_id).add(
                1 if samples == 1 else _PUBLISH_EVERY)

    # -- estimating ---------------------------------------------------------
    def estimate_run_ms(self, kernel: Optional[str], rows: int) -> float:
        """Predicted run time of a ``rows``-row dispatch of ``kernel``.
        Exact ladder point -> its EWMA; otherwise scale the nearest
        measured point by the per-row rate; never measured -> the
        conservative prior (rows x cost.prior_row_ms)."""
        rows = int(rows)
        if rows <= 0:
            return 0.0
        point = _ladder(rows)
        with self._lock:
            km = self._kernels.get(kernel) if kernel is not None \
                else None
            if km is None or not km.points:
                return rows * prior_row_ms()
            exact = km.points.get(point)
            if exact is not None:
                return exact
            # nearest measured point in log-rows distance; beyond the
            # support extrapolate by the per-row rate, between points
            # scale the nearer one's per-row cost
            near = min(km.points,
                       key=lambda p: abs(_log2(p) - _log2(point)))
            near_ms = km.points[near]
            est = near_ms * (point / near)
            # a smaller dispatch never costs MORE than the measured
            # larger one; a larger one never costs less than measured
            if point < near:
                return min(near_ms, max(est, km.row_ms * point))
            return max(est, near_ms)

    def has_model(self, kernel: Optional[str]) -> bool:
        if kernel is None:
            return False
        with self._lock:
            km = self._kernels.get(kernel)
            return km is not None and bool(km.points)

    def row_ms(self, kernel: Optional[str]) -> Optional[float]:
        """Measured per-row rate for the kernel (None = unmeasured)."""
        if kernel is None:
            return None
        with self._lock:
            km = self._kernels.get(kernel)
            if km is None or km.samples == 0:
                return None
            return km.row_ms

    # -- region aggregates (tuner, heartbeats) ------------------------------
    def region_typical_ms(self, region_id: int) -> Optional[float]:
        """EWMA run time of the region's typical dispatch — the latency
        floor the SLO tuner treats as evidence before (and alongside)
        measured p99s."""
        with self._lock:
            st = self._regions.get(region_id)
            return st[0] if st is not None else None

    def region_row_us(self, region_id: int) -> float:
        """Per-row cost in µs for heartbeat rollups (0.0 = unmeasured)."""
        with self._lock:
            st = self._regions.get(region_id)
            return st[1] * 1000.0 if st is not None else 0.0

    # -- lifecycle ----------------------------------------------------------
    def forget_region(self, region_id: int) -> None:
        with self._lock:
            self._regions.pop(region_id, None)
            prefix = f"r{region_id}:"
            for k in [k for k in self._kernels if k.startswith(prefix)]:
                del self._kernels[k]

    def reset(self) -> None:
        with self._lock:
            self._kernels.clear()
            self._regions.clear()


def _log2(x: int) -> float:
    import math

    return math.log2(max(1, x))


COST = CostModel()
