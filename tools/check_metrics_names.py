"""Thin shim over the dingolint metric-names checker.

The metric/span-name lint started here (PR 2) and was folded into the
dingolint framework (PR 12) as its sixth checker — the logic now lives
in ``tools/dingolint/checkers/metric_names.py`` and also runs as part of
``python tools/lint.py``. This module keeps the standalone CLI and its
import surface (``check_file``, ``FAMILY_NAMES``, ``main``) so existing
wiring (tests/test_metrics_names.py, docs, muscle memory) keeps working.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from tools.dingolint.checkers.metric_names import (  # noqa: E402,F401
    FAMILY_NAMES,
    NAME_RE,
    PREFIX_RE,
    SPAN_NAME_RE,
    check_file,
    check_tree,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC_DIRS = ("dingo_tpu",)


def main(argv=None) -> int:
    bad = 0
    checked = 0
    for src in SRC_DIRS:
        for dirpath, _dirnames, filenames in os.walk(os.path.join(REPO, src)):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                checked += 1
                for lineno, msg in check_file(path):
                    rel = os.path.relpath(path, REPO)
                    print(f"{rel}:{lineno}: {msg}", file=sys.stderr)
                    bad += 1
    if bad:
        print(f"{bad} bad metric name(s)", file=sys.stderr)
        return 1
    print(f"metric names OK ({checked} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
