"""TpuShardedFlat: a FLAT region index sharded over a jax.sharding.Mesh.

VERDICT round-1 gap: ShardedFlatStore was load-once and unreachable from
the serving stack. This class is the full VectorIndex contract
(upsert/delete/search/save/load, filters) over the mesh, selectable from
the factory behind FLAGS.use_mesh_sharded_flat — so a region served
through IndexService can live distributed across devices while the rest of
the stack (wrapper, manager, reader, services) stays unchanged.

Layout: global slot space [S * cap_per_shard]; shard s owns slots
[s*cap, (s+1)*cap). Rows shard over the mesh "data" axis, the feature
dimension over "dim" (TP): one jit'd shard_map search does psum partial
dots over "dim", per-shard top-k, and an all_gather merge over "data" —
the ICI replacement for the reference's cross-node scatter-gather
(SURVEY §7 step 8).

Mutations: slots allocate host-side balanced across shards; row writes are
one donated scatter per batch (XLA routes rows to their owning devices).
Capacity grows by doubling cap_per_shard with an on-device reshape —
global slot ids are remapped (slot -> shard*2cap + offset) on the host.
"""

from __future__ import annotations

import functools
import json
import os
import threading
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    InvalidParameter,
    SearchResult,
    VectorIndex,
    resolve_precision,
    strip_invalid,
)
from dingo_tpu.ops.distance import Metric, np_normalize
from dingo_tpu.parallel.sharded_store import (
    ShardedFlatStore,
    account_merge,
    batch_spec,
    make_mesh,
    pad_query_batch,
)
from dingo_tpu.obs.sentinel import sentinel_jit


def mesh_from_flags() -> "Mesh":
    """Mesh shaped by the serving flags: 'dim' (TP) x optional 'batch'
    (query DP) axes, 'data' takes the rest of the devices."""
    from dingo_tpu.common.config import FLAGS

    dim_axis = int(FLAGS.get("mesh_dim_axis") or 1)
    batch_axis = int(FLAGS.get("mesh_batch_axis") or 1)
    return make_mesh(dim=dim_axis, batch=batch_axis)

MIN_CAP_PER_SHARD = 64


@sentinel_jit("parallel.flat.scatter_rows", donate_argnums=(0, 1, 2))
def _scatter_rows(vecs, sqnorm, valid, slots, rows, row_sq, row_valid):
    """Donated batch update; XLA routes each row to its owning shard."""
    vecs = vecs.at[slots].set(rows)
    sqnorm = sqnorm.at[slots].set(row_sq)
    valid = valid.at[slots].set(row_valid)
    return vecs, sqnorm, valid


class TpuShardedFlat(VectorIndex):
    """Mesh-sharded exact search index (FLAT semantics)."""

    def __init__(self, index_id: int, parameter: IndexParameter,
                 mesh: Optional[Mesh] = None):
        super().__init__(index_id, parameter)
        if parameter.dimension <= 0:
            raise InvalidParameter(f"dimension {parameter.dimension}")
        if parameter.metric not in (
            Metric.L2, Metric.INNER_PRODUCT, Metric.COSINE
        ):
            raise InvalidParameter(
                f"sharded flat does not support {parameter.metric}"
            )
        if mesh is None:
            mesh = mesh_from_flags()
        self.mesh = mesh
        self.n_shards = mesh.shape["data"]
        if parameter.dimension % mesh.shape["dim"]:
            raise InvalidParameter(
                f"dimension {parameter.dimension} not divisible by mesh "
                f"dim axis {mesh.shape['dim']}"
            )
        # precision tier over the mesh: bf16 shards the rows at half the
        # HBM; sq8 stays single-device (code scatter over 'data' + per-dim
        # affine replication is future work, not silently approximated)
        self._precision = resolve_precision(parameter)
        if self._precision == "sq8":
            raise InvalidParameter(
                "sq8 tier is not supported on mesh-sharded FLAT "
                "(use bf16, or a single-device FLAT region)"
            )
        self._dtype = (
            jnp.bfloat16 if self._precision == "bf16" else jnp.float32
        )
        self._store = ShardedFlatStore(
            mesh, dim=parameter.dimension, metric=parameter.metric,
            dtype=self._dtype,
        )
        self.cap_per_shard = 0
        self.ids_by_gslot = np.empty(0, np.int64)
        self._id_to_gslot: dict = {}
        self._free_per_shard: List[List[int]] = []
        # serializes donated scatters/growth against search dispatch (the
        # donated buffers invalidate the old array references)
        self._device_lock = threading.RLock()
        self._alloc(MIN_CAP_PER_SHARD)

    # -- slot management -----------------------------------------------------
    @property
    def total_slots(self) -> int:
        return self.cap_per_shard * self.n_shards

    def _alloc(self, cap: int) -> None:
        """(Re)allocate device arrays at cap rows per shard, preserving
        current rows via an on-device reshape when growing."""
        old_cap = self.cap_per_shard
        S, d = self.n_shards, self.dimension
        sharding2d = NamedSharding(self.mesh, P("data", "dim"))
        sharding1d = NamedSharding(self.mesh, P("data"))
        if old_cap == 0:
            z = jnp.zeros((S * cap, d), self._dtype)
            self._store.vecs = jax.device_put(z, sharding2d)
            self._store.sqnorm = jax.device_put(
                jnp.zeros((S * cap,), jnp.float32), sharding1d
            )
            self._store.valid = jax.device_put(
                jnp.zeros((S * cap,), bool), sharding1d
            )
            self.ids_by_gslot = np.full(S * cap, -1, np.int64)
            self._free_per_shard = [
                list(range(s * cap + cap - 1, s * cap - 1, -1))
                for s in range(S)
            ]
        else:
            pad = cap - old_cap
            # [S*old, d] -> [S, old, d] -> pad -> [S*cap, d]; the reshape
            # stays shard-local because the leading axis is the shard axis
            def grow2d(v):
                v = v.reshape(S, old_cap, d)
                v = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
                return v.reshape(S * cap, d)

            def grow1d(v, fill):
                v = v.reshape(S, old_cap)
                v = jnp.pad(v, ((0, 0), (0, pad)), constant_values=fill)
                return v.reshape(S * cap)

            # growth cannot donate: the output is LARGER than the input,
            # so XLA can never alias the buffers (donating only produced
            # "donated buffers were not usable" warnings); the old arrays
            # free when the references drop below. Growth compiles per
            # (old_cap, cap) pair by construction — sentinel_jit keeps
            # those traces in the xla.recompiles accounting (bare-jit
            # lint) instead of invisible.
            self._store.vecs = sentinel_jit(
                "parallel.flat.grow_vecs", grow2d,
                out_shardings=sharding2d,
            )(self._store.vecs)  # under _device_lock via callers
            self._store.sqnorm = sentinel_jit(
                "parallel.flat.grow_sqnorm",
                functools.partial(grow1d, fill=0.0),
                out_shardings=sharding1d,
            )(self._store.sqnorm)
            self._store.valid = sentinel_jit(
                "parallel.flat.grow_valid",
                functools.partial(grow1d, fill=False),
                out_shardings=sharding1d,
            )(self._store.valid)
            # host remap: old gslot s*old+o -> s*cap+o. Vectorized — the
            # per-slot Python loops here were O(S*cap) per growth and
            # dominated ingest at 1M+ rows per region (VERDICT r2 weak #6)
            new_ids = np.full(S * cap, -1, np.int64)
            old = self.ids_by_gslot.reshape(S, old_cap)
            new_ids.reshape(S, cap)[:, :old_cap] = old
            self.ids_by_gslot = new_ids
            live = np.flatnonzero(new_ids >= 0)
            self._id_to_gslot = dict(
                zip(new_ids[live].tolist(), live.tolist())
            )
            grid = new_ids.reshape(S, cap)
            for s in range(S):
                free = np.flatnonzero(grid[s] < 0)[::-1] + s * cap
                self._free_per_shard[s] = free.tolist()
        self.cap_per_shard = cap
        self._store.cap_per_shard = cap
        self._store.ids_by_gslot = self.ids_by_gslot

    def _update_mesh_gauges(self) -> None:
        """Per-shard liveness for the mesh metrics plane: row counts per
        shard plus the max/mean skew ratio. Flight bundles inherit these
        through the metric tick ring, so a slow-query bundle shows whether
        one shard was carrying the region."""
        from dingo_tpu.common.metrics import METRICS

        cap = self.cap_per_shard
        live = [cap - len(f) for f in self._free_per_shard]
        mean = sum(live) / max(1, len(live))
        for s, rows in enumerate(live):
            METRICS.gauge("mesh.shard_rows", region_id=self.id,
                          labels={"shard": str(s)}).set(float(rows))
        METRICS.gauge("mesh.shard_skew", region_id=self.id).set(
            (max(live) / mean) if mean > 0 else 0.0
        )

    def _take_slots(self, n: int) -> np.ndarray:
        """Balanced BULK allocation of n slots: waterfill so the shards'
        remaining free counts stay as equal as possible, popping each
        shard's share as one slice (the per-id pop + max-over-shards loop
        this replaces was O(n*S) on the ingest path)."""
        counts = np.array([len(f) for f in self._free_per_shard], np.int64)
        if int(counts.sum()) < n:
            raise RuntimeError("no free slots (grow first)")
        # largest level L with sum(max(counts-L, 0)) >= n (binary search)
        lo, hi = 0, int(counts.max())
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if int(np.maximum(counts - mid, 0).sum()) >= n:
                lo = mid
            else:
                hi = mid - 1
        take = np.maximum(counts - lo, 0)
        excess = int(take.sum()) - n
        if excess:
            cand = np.flatnonzero(take > 0)
            cand = cand[np.argsort(counts[cand])][:excess]
            take[cand] -= 1
        out = np.empty(n, np.int64)
        pos = 0
        for s in range(self.n_shards):
            t = int(take[s])
            if not t:
                continue
            fl = self._free_per_shard[s]
            out[pos:pos + t] = fl[-t:][::-1]
            del fl[-t:]
            pos += t
        return out

    # -- mutation ------------------------------------------------------------
    def _prep(self, vectors: np.ndarray) -> np.ndarray:
        vectors = np.asarray(vectors, np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dimension:
            raise InvalidParameter(f"vector dim {vectors.shape}")
        if self.metric is Metric.COSINE:
            vectors = np_normalize(vectors)
        return vectors

    def reserve(self, n: int) -> None:
        need = -(-n // self.n_shards)
        cap = self.cap_per_shard
        while cap < need:
            cap *= 2
        if cap != self.cap_per_shard:
            with self._device_lock:
                self._alloc(cap)

    def upsert(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        vectors = self._prep(vectors)
        ids = np.asarray(ids, np.int64)
        if len(ids) != len(vectors):
            raise InvalidParameter("ids/vectors length mismatch")
        if len(ids) != len(np.unique(ids)):
            # duplicate ids map to one slot; an XLA scatter with repeated
            # indices has an undefined winner, so keep only the LAST
            # occurrence (upsert last-write-wins, matching TpuFlat)
            last = {int(v): i for i, v in enumerate(ids)}
            keep = sorted(last.values())
            ids, vectors = ids[keep], vectors[keep]
        lookup = self._id_to_gslot
        slots = np.fromiter(
            (lookup.get(v, -1) for v in ids.tolist()), np.int64, len(ids)
        )
        new_mask = slots < 0
        new = int(new_mask.sum())
        free = sum(len(f) for f in self._free_per_shard)
        if new > free:
            need = -(-(len(self._id_to_gslot) + new) // self.n_shards)
            cap = self.cap_per_shard
            while cap < need:
                cap *= 2
            with self._device_lock:
                self._alloc(cap)
            # growth REMAPPED the gslot space: refresh existing ids' slots
            lookup = self._id_to_gslot
            slots = np.fromiter(
                (lookup.get(v, -1) for v in ids.tolist()), np.int64,
                len(ids)
            )
            new_mask = slots < 0
        if new:
            fresh = self._take_slots(new)
            slots[new_mask] = fresh
            new_ids = ids[new_mask]
            self.ids_by_gslot[fresh] = new_ids
            lookup.update(zip(new_ids.tolist(), fresh.tolist()))
        row_sq = (vectors.astype(np.float64) ** 2).sum(1).astype(np.float32)
        with self._device_lock:
            self._store.vecs, self._store.sqnorm, self._store.valid = (
                _scatter_rows(
                    self._store.vecs, self._store.sqnorm, self._store.valid,
                    jnp.asarray(slots, jnp.int32),
                    jnp.asarray(vectors, dtype=self._dtype),
                    jnp.asarray(row_sq), jnp.ones(len(ids), bool),
                )
            )
        self.write_count_since_save += len(ids)
        self._update_mesh_gauges()

    def add(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        uniq, counts = np.unique(ids, return_counts=True)
        if (counts > 1).any():
            raise InvalidParameter(
                f"duplicate ids within batch: {uniq[counts > 1][:5].tolist()}"
            )
        dup = [int(i) for i in ids if int(i) in self._id_to_gslot]
        if dup:
            raise InvalidParameter(f"duplicate ids {dup[:5]} (use upsert)")
        self.upsert(ids, vectors)

    def delete(self, ids: np.ndarray) -> int:
        doomed = []
        for vid in np.asarray(ids, np.int64):
            s = self._id_to_gslot.pop(int(vid), None)
            if s is not None:
                doomed.append(s)
                self.ids_by_gslot[s] = -1
                self._free_per_shard[s // self.cap_per_shard].append(s)
        if doomed:
            slots = jnp.asarray(np.asarray(doomed, np.int64), jnp.int32)
            zrows = jnp.zeros((len(doomed), self.dimension), self._dtype)
            with self._device_lock:
                self._store.vecs, self._store.sqnorm, self._store.valid = (
                    _scatter_rows(
                        self._store.vecs, self._store.sqnorm,
                        self._store.valid,
                        slots, zrows, jnp.zeros(len(doomed), jnp.float32),
                        jnp.zeros(len(doomed), bool),
                    )
                )
            self.write_count_since_save += len(doomed)
            self._update_mesh_gauges()
        return len(doomed)

    # -- search --------------------------------------------------------------
    def search(self, queries, topk, filter_spec=None, **kw):
        return self.search_async(queries, topk, filter_spec, **kw)()

    def search_async(self, queries, topk, filter_spec: Optional[FilterSpec] = None,
                     **kw):
        from dingo_tpu.common.config import FLAGS
        from dingo_tpu.parallel.tracing import shard_search_span

        with shard_search_span("parallel.flat.search", self.mesh) as span:
            queries = self._prep(np.atleast_2d(np.asarray(queries, np.float32)))
            b = queries.shape[0]
            qpad = pad_query_batch(queries, self.mesh)
            collective = bool(FLAGS.get("mesh_collective_merge"))
            q = jax.device_put(
                jnp.asarray(qpad),
                NamedSharding(self.mesh, batch_spec(self.mesh, "dim")),
            )
            with self._device_lock:
                # capture valid/vecs AND the gslot translation table inside
                # the lock: a concurrent donated scatter invalidates the
                # arrays and a growth remaps the gslot space
                if filter_spec is None or filter_spec.is_empty():
                    valid = self._store.valid
                else:
                    mask = filter_spec.slot_mask(self.ids_by_gslot)
                    valid = jax.device_put(
                        jnp.asarray(mask) & self._store.valid,
                        NamedSharding(self.mesh, P("data")),
                    )
                if collective:
                    vals, gslots = self._store._search_jit(
                        self._store.vecs, self._store.sqnorm, valid, q,
                        int(topk),
                    )
                else:
                    # capped fallback arm: per-shard [b, k] shortlists only
                    # cross to the host, merged in resolve()
                    vals, gslots = self._store._local_topk_jit(
                        self._store.vecs, self._store.sqnorm, valid, q,
                        int(topk),
                    )
                ids_by_gslot = self.ids_by_gslot.copy()
            if collective:
                account_merge(self.mesh, qpad.shape[0], int(topk),
                              region_id=self.id)
            else:
                from dingo_tpu.common.metrics import METRICS

                METRICS.counter("mesh.fallback_searches").add(1)
            vals.copy_to_host_async()
            gslots.copy_to_host_async()
            if span.sampled:
                # sampled requests trade pipelining for a true kernel span
                span.set_attr("batch", b)
                jax.block_until_ready((vals, gslots))
        ascending = self.metric is Metric.L2

        def resolve() -> List[SearchResult]:
            vals_h, gslots_h = jax.device_get((vals, gslots))
            if not collective:
                from dingo_tpu.parallel.sharded_store import merge_host_topk

                vals_h, gslots_h = merge_host_topk(
                    vals_h, gslots_h, int(topk)
                )
            vals_h, gslots_h = vals_h[:b], gslots_h[:b]
            safe = np.where(gslots_h >= 0, gslots_h, 0)
            ids = np.where(gslots_h >= 0, ids_by_gslot[safe], -1)
            dists = -vals_h if ascending else vals_h
            return [strip_invalid(i, d) for i, d in zip(ids, dists)]

        return resolve

    # -- misc contract -------------------------------------------------------
    def need_train(self) -> bool:
        return False

    def is_trained(self) -> bool:
        return True

    def get_count(self) -> int:
        return len(self._id_to_gslot)

    def get_memory_size(self) -> int:
        return int(
            self.total_slots * self.dimension * jnp.dtype(self._dtype).itemsize
        )

    def save(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        # f32 on disk regardless of tier (savez can't take ml_dtypes bf16)
        vecs = np.asarray(jax.device_get(self._store.vecs), np.float32)
        live = np.flatnonzero(self.ids_by_gslot >= 0)
        np.savez(
            os.path.join(path, "sharded_flat.npz"),
            ids=self.ids_by_gslot[live],
            vectors=vecs[live],
        )
        meta = {
            "index_type": self.index_type.value,
            "dimension": self.dimension,
            "metric": self.metric.value,
            "apply_log_id": self.apply_log_id,
            "count": self.get_count(),
        }
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if meta["dimension"] != self.dimension:
            raise InvalidParameter("snapshot dimension mismatch")
        if meta["metric"] != self.metric.value:
            raise InvalidParameter(
                f"snapshot metric {meta['metric']} != {self.metric.value}"
            )
        data = np.load(os.path.join(path, "sharded_flat.npz"))
        self.cap_per_shard = 0
        self._id_to_gslot.clear()
        self._alloc(MIN_CAP_PER_SHARD)
        if len(data["ids"]):
            self.reserve(len(data["ids"]) + 1)
            # rows were normalized before save for cosine; re-normalizing
            # in _prep is idempotent
            self.upsert(
                np.asarray(data["ids"], np.int64),
                np.asarray(data["vectors"], np.float32),
            )
        self.apply_log_id = meta["apply_log_id"]
        self.write_count_since_save = 0
