"""Skew-proof bucketed IVF layout shared by TpuIvfFlat / TpuIvfPq.

Round-1 layout padded every coarse list to the LARGEST list's pow2 size
([nlist, cap_max, d]); with realistic k-means skew that multiplies HBM by
the skew factor (a 10x-hot list inflates every other list 10x). This layout
fixes the bucket width near the MEAN list size and lets a long list spill
into several fixed-width buckets instead:

  data        [B, cap_list, d]   B = sum_l ceil(count_l / cap_list)  (>= nlist)
  bucket_slot [B, cap_list]      slot per row, -1 pad
  probe_table [nlist, max_spill] bucket ids per coarse list, -1 pad

Memory is bounded by n*d + nlist*cap_list*d regardless of skew, and the
probe expansion (coarse list -> its spill buckets) happens ON DEVICE so no
D2H round-trip enters the search path. Construction is fully vectorized —
the round-1 per-row Python loop was itself a 1M-scale ingest bug.

Reference contract: faiss IndexIVF inverted lists are exact-size per list
(vector_index_ivf_flat.cc:60-62); the fixed-width spill encoding is the
static-shape equivalent XLA needs.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dingo_tpu.index.slot_store import _next_pow2

#: bucket width bounds: small enough to bound padding waste (<= nlist*cap*d),
#: large enough to keep per-bucket matmuls MXU-friendly
MIN_CAP = 8
MAX_CAP = 2048


@dataclasses.dataclass
class BucketLayout:
    """Host-side layout description + device probe/slot arrays."""

    cap_list: int
    max_spill: int
    nbuckets: int
    bucket_slot_h: np.ndarray      # [B, cap_list] int32, -1 pad
    bucket_slot: jax.Array         # device copy
    bucket_valid: jax.Array        # [B, cap_list] bool
    probe_table: jax.Array         # [nlist, max_spill] int32, -1 pad
    gather_idx: jax.Array          # [B * cap_list] int32 (slot or 0)
    bucket_coarse: jax.Array       # [B] int32: coarse list of each bucket

    def gather_rows(self, source: jax.Array) -> jax.Array:
        """[B, cap_list, *source.shape[1:]] rows grouped by bucket."""
        out = jnp.take(source, self.gather_idx, axis=0)
        return out.reshape(
            (self.nbuckets, self.cap_list) + source.shape[1:]
        )


def build_layout(
    assign_h: np.ndarray,
    valid_h: np.ndarray,
    nlist: int,
    cap_hint: Optional[int] = None,
) -> BucketLayout:
    """Group live slots by coarse assignment into fixed-width spill buckets.

    assign_h: [capacity] int32 coarse list per slot (-1 unassigned)
    valid_h:  [capacity] bool liveness
    """
    live = np.flatnonzero(valid_h)
    assign = assign_h[live]
    keep = assign >= 0
    live, assign = live[keep], assign[keep]

    counts = np.bincount(assign, minlength=nlist).astype(np.int64)
    mean = max(1, int(np.ceil(len(live) / max(1, nlist))))
    cap_list = cap_hint or min(MAX_CAP, max(MIN_CAP, _next_pow2(mean)))

    # buckets per list (every list gets >= 1 so probe_table[:, 0] is valid)
    nb = np.maximum(1, -(-counts // cap_list))           # ceil div
    max_spill = int(nb.max()) if len(nb) else 1
    offsets = np.zeros(nlist + 1, np.int64)
    np.cumsum(nb, out=offsets[1:])
    nbuckets = int(offsets[-1])

    # stable sort by list; position within list -> (bucket, row) coordinates
    order = np.argsort(assign, kind="stable")
    live_s, assign_s = live[order], assign[order]
    starts = np.zeros(nlist, np.int64)
    np.cumsum(counts, out=starts)
    starts = np.concatenate([[0], starts[:-1]])
    pos = np.arange(len(live_s), dtype=np.int64) - starts[assign_s]
    bucket_id = offsets[assign_s] + pos // cap_list
    row = pos % cap_list

    bucket_slot = np.full((nbuckets, cap_list), -1, np.int32)
    bucket_slot[bucket_id, row] = live_s

    probe = offsets[:nlist, None] + np.arange(max_spill)[None, :]
    probe = np.where(
        np.arange(max_spill)[None, :] < nb[:, None], probe, -1
    ).astype(np.int32)

    safe = np.where(bucket_slot >= 0, bucket_slot, 0)
    coarse = np.repeat(np.arange(nlist, dtype=np.int32), nb)
    return BucketLayout(
        cap_list=cap_list,
        max_spill=max_spill,
        nbuckets=nbuckets,
        bucket_slot_h=bucket_slot,
        bucket_slot=jnp.asarray(bucket_slot),
        bucket_valid=jnp.asarray(bucket_slot >= 0),
        probe_table=jnp.asarray(probe),
        gather_idx=jnp.asarray(safe.reshape(-1), jnp.int32),
        bucket_coarse=jnp.asarray(coarse),
    )


def alloc_buckets(n: int) -> int:
    """Physical bucket allocation for n logical buckets: the smallest
    {1, 1.25, 1.5, 1.75} x pow2 ladder value >= n. The bucket count is a
    traced dimension of the scan kernels, so every distinct allocation is
    a compile — the ladder bounds the cache at 4 entries per octave while
    capping padding waste at 25% (plain pow2 doubling would waste up to
    2x HBM on the [B, cap_list, d] data array)."""
    n = max(1, int(n))
    if n <= 8:
        return _next_pow2(n)
    p = _next_pow2(n)
    for num in (5, 6, 7):
        cand = (p // 8) * num       # 1.25/1.5/1.75 x (p/2)
        if cand >= n:
            return cand
    return p


def shape_bucket(n: int) -> int:
    """Round a request shape (topk, nprobe) up to the {1, 1.5} x pow2
    ladder (..., 8, 12, 16, 24, 32, 48, 64, ...). Kernel k/nprobe are
    static arguments, so serving raw request values compiles one program
    per distinct (batch, k, nprobe) triple; the ladder keeps steady-state
    traffic on a handful of cached executables. Searching a slightly
    larger k/nprobe is strictly recall-neutral-or-better; callers slice
    results back to the requested k."""
    n = int(n)
    if n <= 4:
        return max(1, n)
    p = _next_pow2(n)
    mid = 3 * (p // 4)               # 1.5 x p/2
    return mid if mid >= n else p


class MutableIvfView:
    """Incrementally-maintained bucketed IVF view.

    Wraps the dense layout from build_layout() with the host bookkeeping
    needed to mutate it in place: slot -> (bucket, row) positions,
    per-bucket fill cursors, per-list bucket chains. Upserts append into
    free rows of a list's tail bucket (allocating a new spill bucket when
    the chain is full), deletes flip the row invalid — both become O(batch)
    device scatters (ops/scatter.py) instead of the O(N) gather+re-upload
    that build_layout costs. A deferred compaction (the owning index's
    compact()) restores the dense layout off the hot path.

    Ownership split: this class owns the INDEX-AGNOSTIC device arrays
    (bucket_slot / bucket_valid / probe_table / bucket_coarse); the data
    arrays grouped by the same coordinates ([B, cap, d] vectors, [B, cap]
    sqnorm, [B, cap, m] codes) belong to the owning index, which applies
    the scatter coordinates staged here to its own arrays. All device
    writes are donated — stage_*() is host-only; apply_device() and the
    index's data scatters must run under the store's device_lock.

    Invariant: a row is live iff bucket_slot[b, r] >= 0 (tombstones reset
    the slot to -1 so the filtered path can never resurrect a reassigned
    slot through a stale id).
    """

    def __init__(self, lay: BucketLayout, nlist: int, slot_capacity: int):
        self.cap_list = lay.cap_list
        self.nlist = nlist
        self.nbuckets = lay.nbuckets
        self.alloc = alloc_buckets(lay.nbuckets)
        self.max_spill = lay.max_spill

        cap = self.cap_list
        self.bucket_slot_h = np.full((self.alloc, cap), -1, np.int32)
        self.bucket_slot_h[: lay.nbuckets] = lay.bucket_slot_h
        self.bucket_coarse_h = np.full((self.alloc,), -1, np.int32)
        self.bucket_coarse_h[: lay.nbuckets] = np.asarray(lay.bucket_coarse)
        # dense layout packs each bucket's rows from 0 -> fill = live count
        self.bucket_fill = (self.bucket_slot_h >= 0).sum(axis=1).astype(
            np.int32
        )
        self.probe_table_h = np.full(
            (nlist, self.max_spill), -1, np.int32
        )
        self.probe_table_h[:] = np.asarray(lay.probe_table)
        self.list_nb = (self.probe_table_h >= 0).sum(axis=1).astype(np.int32)

        self.slot_pos = np.full((slot_capacity,), -1, np.int32)
        flat = self.bucket_slot_h.reshape(-1)
        live = np.flatnonzero(flat >= 0)
        self.slot_pos[flat[live]] = live

        # mutation accounting (since the last dense build)
        self.version = 0
        self.tombstones = 0
        self.inplace_appends = 0
        self.buckets_added = 0
        self.base_buckets = lay.nbuckets
        self.base_rows = int(len(live))
        self.live_rows = int(len(live))

        # device mirrors
        self.bucket_slot = jnp.asarray(self.bucket_slot_h)
        self.bucket_valid = jnp.asarray(self.bucket_slot_h >= 0)
        self.probe_table = jnp.asarray(self.probe_table_h)
        self.bucket_coarse = jnp.asarray(
            np.where(self.bucket_coarse_h >= 0, self.bucket_coarse_h, 0)
        )

    @classmethod
    def build(cls, assign_h: np.ndarray, valid_h: np.ndarray, nlist: int,
              slot_capacity: int,
              cap_hint: Optional[int] = None) -> "MutableIvfView":
        lay = build_layout(assign_h, valid_h, nlist, cap_hint)
        return cls(lay, nlist, slot_capacity)

    # -- derived -----------------------------------------------------------
    @property
    def gather_idx(self) -> jax.Array:
        """[alloc * cap_list] int32 slot-or-0 gather map (rebuild path of
        the owning index's data arrays)."""
        flat = self.bucket_slot_h.reshape(-1)
        return jnp.asarray(np.where(flat >= 0, flat, 0), jnp.int32)

    def gather_rows(self, source: jax.Array) -> jax.Array:
        """[alloc, cap_list, *source.shape[1:]] rows grouped by bucket."""
        out = jnp.take(source, self.gather_idx, axis=0)
        return out.reshape(
            (self.alloc, self.cap_list) + source.shape[1:]
        )

    def tombstone_ratio(self) -> float:
        return self.tombstones / max(1, self.live_rows + self.tombstones)

    def spill_ratio(self) -> float:
        return self.buckets_added / max(1, self.base_buckets)

    def stats(self) -> dict:
        return {
            "nbuckets": self.nbuckets,
            "alloc_buckets": self.alloc,
            "cap_list": self.cap_list,
            "live_rows": self.live_rows,
            "tombstones": self.tombstones,
            "tombstone_ratio": self.tombstone_ratio(),
            "inplace_appends": self.inplace_appends,
            "buckets_added": self.buckets_added,
            "spill_ratio": self.spill_ratio(),
            "version": self.version,
        }

    # -- staging (host bookkeeping; no device work) ------------------------
    def ensure_slot_capacity(self, capacity: int) -> None:
        if capacity > len(self.slot_pos):
            grown = np.full((capacity,), -1, np.int32)
            grown[: len(self.slot_pos)] = self.slot_pos
            self.slot_pos = grown

    def _alloc_bucket(self, coarse: int) -> int:
        """Allocate a fresh spill bucket for `coarse`; returns bucket id.
        Grows the physical allocation / probe-table width when needed
        (both already reflected host-side; _ViewUpdate carries the device
        growth directives)."""
        if self.nbuckets == self.alloc:
            new_alloc = alloc_buckets(self.nbuckets + 1)
            grown = np.full((new_alloc, self.cap_list), -1, np.int32)
            grown[: self.alloc] = self.bucket_slot_h
            self.bucket_slot_h = grown
            gc = np.full((new_alloc,), -1, np.int32)
            gc[: self.alloc] = self.bucket_coarse_h
            self.bucket_coarse_h = gc
            gf = np.zeros((new_alloc,), np.int32)
            gf[: self.alloc] = self.bucket_fill
            self.bucket_fill = gf
            self.alloc = new_alloc
        s = int(self.list_nb[coarse])
        if s == self.max_spill:
            new_spill = max(self.max_spill + 1,
                            self.max_spill + self.max_spill // 2)
            grown = np.full((self.nlist, new_spill), -1, np.int32)
            grown[:, : self.max_spill] = self.probe_table_h
            self.probe_table_h = grown
            self.max_spill = new_spill
        b = self.nbuckets
        self.nbuckets += 1
        self.buckets_added += 1
        self.bucket_coarse_h[b] = coarse
        self.probe_table_h[coarse, s] = b
        self.list_nb[coarse] = s + 1
        return b

    def stage_delete(self, slots: np.ndarray) -> Optional["_ViewUpdate"]:
        """Tombstone the given slots' rows. Host arrays are updated here;
        returns the device scatter batch (None when nothing changed).
        Unlike stage_upsert there is no size cutoff: a delete-only batch
        never allocates buckets, and the scatter payload is one int32 per
        row — far cheaper than invalidating the whole view."""
        upd = _ViewUpdate(self.alloc, self.nbuckets)
        for s in np.asarray(slots, np.int64):
            self._tombstone(int(s), upd)
        return self._finish(upd)

    def stage_upsert(
        self, slots: np.ndarray, assigns: np.ndarray
    ) -> Optional["_ViewUpdate"]:
        """Place upserted slots: tombstone any previous position, append
        into the assigned list's tail bucket. Returns None when the batch
        was a no-op (callers must NOT treat that as a rebuild request —
        oversize batches are the CALLER's cutoff, ops/scatter.py
        MAX_SCATTER_BATCH, checked before staging)."""
        slots = np.asarray(slots, np.int64)
        upd = _ViewUpdate(self.alloc, self.nbuckets)
        placed: dict = {}            # slot -> batch index of surviving row
        for i, (s, lst) in enumerate(zip(slots, np.asarray(assigns))):
            s, lst = int(s), int(lst)
            self._tombstone(s, upd)
            if lst < 0:
                continue
            # find a free row: tail bucket of the list's chain, else a
            # fresh spill bucket
            tail = int(self.probe_table_h[lst, self.list_nb[lst] - 1]) \
                if self.list_nb[lst] else -1
            if tail < 0 or self.bucket_fill[tail] >= self.cap_list:
                tail = self._alloc_bucket(lst)
            r = int(self.bucket_fill[tail])
            self.bucket_fill[tail] = r + 1
            self.bucket_slot_h[tail, r] = s
            self.slot_pos[s] = tail * self.cap_list + r
            self.live_rows += 1
            self.inplace_appends += 1
            placed[s] = i
            upd.touched.append(tail * self.cap_list + r)
        upd.appended = [(int(self.slot_pos[s]), i) for s, i in placed.items()]
        return self._finish(upd)

    def _tombstone(self, slot: int, upd: "_ViewUpdate") -> None:
        if slot < 0 or slot >= len(self.slot_pos):
            return
        pos = int(self.slot_pos[slot])
        if pos < 0:
            return
        self.slot_pos[slot] = -1
        self.bucket_slot_h[pos // self.cap_list, pos % self.cap_list] = -1
        self.tombstones += 1
        self.live_rows -= 1
        upd.touched.append(pos)

    def _finish(self, upd: "_ViewUpdate") -> Optional["_ViewUpdate"]:
        if not upd.touched and upd.nbuckets_before == self.nbuckets:
            return None
        self.version += 1
        # final value per touched position comes from the HOST truth, so
        # a slot upserted twice in one batch (tombstone of its own fresh
        # row) cannot race inside one scatter
        pos = np.unique(np.asarray(upd.touched, np.int64))
        upd.b_idx = (pos // self.cap_list).astype(np.int32)
        upd.r_idx = (pos % self.cap_list).astype(np.int32)
        upd.slot_vals = self.bucket_slot_h[upd.b_idx, upd.r_idx]
        upd.grew_alloc = self.alloc if upd.alloc_before != self.alloc else None
        upd.new_probe = upd.nbuckets_before != self.nbuckets
        return upd

    # -- device apply (caller holds the store's device_lock) ---------------
    def apply_device(self, upd: "_ViewUpdate") -> None:
        from dingo_tpu.ops.scatter import (
            pad_buckets,
            scatter_bucket_update,
        )

        if upd.grew_alloc is not None:
            self.bucket_slot = pad_buckets(self.bucket_slot, upd.grew_alloc,
                                           fill=-1)
            self.bucket_valid = pad_buckets(self.bucket_valid, upd.grew_alloc,
                                            fill=False)
        if len(upd.b_idx):
            self.bucket_slot = scatter_bucket_update(
                self.bucket_slot, upd.b_idx, upd.r_idx, upd.slot_vals
            )
            self.bucket_valid = scatter_bucket_update(
                self.bucket_valid, upd.b_idx, upd.r_idx, upd.slot_vals >= 0
            )
        if upd.new_probe:
            # probe table / coarse map are tiny ([nlist, spill] + [alloc])
            # — re-upload beats tracking their deltas
            self.probe_table = jnp.asarray(self.probe_table_h)
            self.bucket_coarse = jnp.asarray(
                np.where(self.bucket_coarse_h >= 0, self.bucket_coarse_h, 0)
            )


class _ViewUpdate:
    """Scatter batch staged by MutableIvfView: touched (bucket, row)
    coordinates with their final slot values, data-append mapping
    (position -> input-batch index), and growth directives."""

    __slots__ = ("alloc_before", "nbuckets_before", "touched", "appended",
                 "b_idx", "r_idx", "slot_vals", "grew_alloc", "new_probe")

    def __init__(self, alloc_before: int, nbuckets_before: int):
        self.alloc_before = alloc_before
        self.nbuckets_before = nbuckets_before
        self.touched: list = []
        self.appended: list = []
        self.b_idx = np.empty(0, np.int32)
        self.r_idx = np.empty(0, np.int32)
        self.slot_vals = np.empty(0, np.int32)
        self.grew_alloc: Optional[int] = None
        self.new_probe = False


def expand_probes(
    probes: jax.Array, probe_table: jax.Array, nprobe: int, max_spill: int
) -> jax.Array:
    """Coarse probes [b, nprobe] -> virtual bucket probes [b, budget].

    Valid buckets come first in original rank order; when the expansion
    exceeds the budget, the LOWEST-ranked coarse lists' spill buckets are
    dropped (they contribute least to recall). budget == nprobe when there
    is no spill, so the common case is a plain table lookup.
    """
    virt, _ = expand_probes_ranked(probes, probe_table, nprobe, max_spill)
    return virt


def expand_probes_ranked(
    probes: jax.Array, probe_table: jax.Array, nprobe: int, max_spill: int
):
    """expand_probes plus, per virtual probe, the POSITION of its coarse
    list within the query's probe ranking ([b, budget] int32). Lets callers
    that precompute per-(query, coarse-list) state (the IVF-PQ residual
    LUT) share it across a list's spill buckets instead of recomputing."""
    b = probes.shape[0]
    virt = jnp.take(probe_table, probes, axis=0)        # [b, nprobe, spill]
    virt = virt.reshape(b, nprobe * max_spill)
    if max_spill == 1:
        pos = jnp.broadcast_to(
            jnp.arange(nprobe, dtype=jnp.int32)[None, :], (b, nprobe)
        )
        return virt, pos
    width = nprobe * max_spill
    # rank-preserving compaction: valid entries keep their column index as
    # sort key, invalid ones sink to the end
    cols = jnp.arange(width, dtype=jnp.int32)[None, :]
    key = jnp.where(virt >= 0, cols, jnp.int32(width))
    order = jnp.argsort(key, axis=1)
    virt = jnp.take_along_axis(virt, order, axis=1)
    budget = min(width, nprobe + max(8, nprobe // 2) + max_spill - 1)
    pos = (order // max_spill).astype(jnp.int32)
    return virt[:, :budget], pos[:, :budget]
