"""State-integrity plane: incremental device-state digests + corruption
scrub.

The stack observes latency (trace), resources (metrics/hbm), quality
(obs/quality.py) and pressure (obs/pressure.py) — this module observes
*state*: whether the bytes an index actually serves from still match what
was written. One region's data lives simultaneously as SlotStore rows,
sq8 codes, a dimension-blocked scan mirror, an HNSW adjacency mirror and
an IVF bucket arrangement; silent drift between any of them (a scatter
bug, a bad restore, flipped HBM) is the failure mode nothing else
catches.

Mechanics (ops/digest.py): every artifact keeps an order-invariant
multiset digest over (id, canonical payload bytes) — write paths fold
batches in with O(batch) host work (put adds a term, tombstone subtracts
it; no device work, no recompiles), so the digest is always current and
O(1) to read. Digests are tagged with the raft applied index and ride
heartbeats (RegionMetrics.integrity_* pb fields); CoordinatorControl
compares replicas at EQUAL applied indices and raises the
``consistency.*`` family + a DIVERGED flag + a rate-limited flight
bundle carrying both replicas' digest vectors.

The ``consistency_scrub`` crontab recomputes full digests FROM DEVICE
STATE off the hot path (chunked reads under ``store.device_lock`` so
p99 stays bounded) and checks them against the incremental ledger —
catching both bookkeeping bugs (ledger wrong) and silent HBM/restore
corruption (device wrong). Snapshot save persists the digest vector in
meta.json; load recomputes from the restored state and refuses to serve
a mismatch (index/base.py SnapshotCorruption -> the manager falls back
to a rebuild from the engine, which is the source of truth).

Ledgers are keyed by INDEX OBJECT (weakly), not by region id: a rebuild
builds a fresh index while the old one still serves writes, and the two
must not share a ledger. Reporting resolves through the region's live
wrapper, so heartbeats always describe the serving index.
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.ops.digest import SetDigest, row_fingerprints

_log = get_logger("obs.integrity")

#: slots read back per device_lock hold during a scrub / restore rebuild
#: (bounds how long a scrub chunk can stall a concurrent search dispatch)
SCRUB_CHUNK = 65536

#: artifacts that survive a snapshot save/load round-trip and are
#: therefore persisted in meta.json ("blocked" is a runtime arrangement
#: rebuilt from conf at load; its digest is checked by the scrub instead)
SNAPSHOT_ARTIFACTS = ("rows", "adjacency", "ivf_buckets", "pq_codes")

#: artifacts EXCLUDED from the heartbeat digest vector the coordinator
#: compares across replicas: the adjacency ledger is rewritten by the
#: LAZY device-mirror re-export (search-timing-driven, not raft-ordered),
#: so two healthy replicas at the same applied index can legitimately
#: hold different adjacency digests — comparing them would read pure
#: staleness as divergence. The scrub (adjacency_in_sync-gated) and the
#: snapshot meta still cover the artifact.
HEARTBEAT_EXCLUDED = frozenset({"adjacency"})


class ArtifactLedger:
    """Incrementally-maintained digest of one artifact's (id -> payload)
    map. Callers hold the owning RegionIntegrity's lock."""

    __slots__ = ("tag", "digest", "version", "_fp")

    def __init__(self, tag: str):
        self.tag = tag
        self.digest = SetDigest()
        #: bumped on every mutation — the scrub uses it to detect a write
        #: racing the chunked recompute (raced pass = retry, not mismatch)
        self.version = 0
        self._fp: Dict[int, int] = {}

    def update(self, ids: np.ndarray, payload: np.ndarray) -> None:
        fps = row_fingerprints(self.tag, ids, payload)
        self._fold(np.asarray(ids, np.int64), fps)

    def update_fps(self, ids: np.ndarray, fps: np.ndarray) -> None:
        self._fold(np.asarray(ids, np.int64), fps)

    def _fold(self, ids: np.ndarray, fps: np.ndarray) -> None:
        olds: List[int] = []
        for i, fp in zip(ids.tolist(), fps.tolist()):
            prev = self._fp.get(i)
            if prev is not None:
                olds.append(prev)
            self._fp[i] = fp
        if olds:
            self.digest.remove(np.asarray(olds, np.uint64))
        self.digest.add(fps)
        self.version += 1

    def remove(self, ids: np.ndarray) -> None:
        olds = []
        for i in np.asarray(ids, np.int64).tolist():
            prev = self._fp.pop(i, None)
            if prev is not None:
                olds.append(prev)
        if olds:
            self.digest.remove(np.asarray(olds, np.uint64))
            self.version += 1

    def reset(self) -> None:
        self._fp.clear()
        self.digest = SetDigest()
        self.version += 1

    def count(self) -> int:
        return self.digest.count


class RegionIntegrity:
    """Per-index ledger set: one ArtifactLedger per artifact plus the
    raft applied index the digests correspond to."""

    def __init__(self, region_id: int):
        self.region_id = region_id
        self.lock = threading.Lock()
        self.artifacts: Dict[str, ArtifactLedger] = {}
        self.applied_index = 0
        #: bumped BEFORE each write path touches device state (the ledger
        #: folds after the device mutation, so per-artifact versions alone
        #: cannot see a write whose fold hasn't landed yet — the scrub
        #: checks this counter too and marks such passes raced)
        self.mutations = 0
        #: write paths IN FLIGHT right now (begin/end bracketed): while
        #: nonzero, device state may be ahead of the ledger and the
        #: applied-index tag may be pending — the scrub classifies
        #: overlapping passes as raced, and the heartbeat withholds the
        #: digest vector for the beat (no evidence beats torn evidence)
        self.pending = 0

    def begin_mutation(self) -> None:
        with self.lock:
            self.mutations += 1
            self.pending += 1

    def end_mutation(self) -> None:
        with self.lock:
            self.pending = max(0, self.pending - 1)

    def heartbeat_view(self) -> Tuple[int, str]:
        """(applied_index, digests_json) read ATOMICALLY: while any write
        is in flight the digest vector is withheld — between a ledger
        fold and its applied-index tag the pair would be torn, and the
        coordinator would read a healthy replica as DIVERGED."""
        with self.lock:
            applied = self.applied_index
            if self.pending:
                return applied, ""
            arts = {
                name: led.digest.hex()
                for name, led in sorted(self.artifacts.items())
                if name not in HEARTBEAT_EXCLUDED
            }
        if not arts:
            return applied, ""
        return applied, json.dumps(arts, sort_keys=True,
                                   separators=(",", ":"))

    def ledger(self, artifact: str) -> ArtifactLedger:
        led = self.artifacts.get(artifact)
        if led is None:
            led = self.artifacts[artifact] = ArtifactLedger(artifact)
        return led

    def update(self, artifact: str, ids: np.ndarray,
               payload: np.ndarray) -> None:
        with self.lock:
            self.ledger(artifact).update(ids, payload)

    def remove(self, artifact: str, ids: np.ndarray) -> None:
        with self.lock:
            led = self.artifacts.get(artifact)
            if led is not None:
                led.remove(ids)

    def drop(self, artifact: str) -> None:
        with self.lock:
            self.artifacts.pop(artifact, None)

    def report(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "applied_index": self.applied_index,
                "artifacts": {
                    name: led.digest.hex()
                    for name, led in sorted(self.artifacts.items())
                },
            }



def diverged_artifacts(a_json: str, b_json: str) -> List[str]:
    """Artifact names present in BOTH digest vectors with different
    digests (the coordinator's replica-compare primitive; artifacts only
    one side reports — e.g. a mirror not built yet — are not divergence)."""
    try:
        a, b = json.loads(a_json or "{}"), json.loads(b_json or "{}")
    except ValueError:
        return []
    return sorted(k for k in set(a) & set(b) if a[k] != b[k])


# ---------------------------------------------------------------------------
# device-state readers: (ids, payload) chunks per artifact, read back from
# the arrays the kernels actually serve from. Shared by the scrub (compare)
# and the restore/primer paths (rebuild the ledger from state).
# ---------------------------------------------------------------------------

def _iter_rows(index, chunk: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    store = index.store
    for lo in range(0, store.capacity, chunk):
        hi = min(store.capacity, lo + chunk)
        ids = store.ids_by_slot[lo:hi]
        live = ids >= 0
        if not live.any():
            continue
        with store.device_lock:
            vals = np.asarray(store.vecs[lo:hi])
        yield ids[live], vals[live]


def _iter_blocked(index, chunk: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    store = index.store
    for lo in range(0, store.capacity, chunk):
        hi = min(store.capacity, lo + chunk)
        ids = store.ids_by_slot[lo:hi]
        live = ids >= 0
        if not live.any():
            continue
        with store.device_lock:
            blk = np.asarray(store.vecs_blk[:, lo:hi, :])
        # [nblk, n, dblk] -> per-slot canonical row bytes (the blocked
        # transform is a per-row reshape, so values re-concatenate to the
        # original row exactly)
        rows = np.transpose(blk, (1, 0, 2)).reshape(hi - lo, -1)
        yield ids[live], rows[live]


def _iter_adjacency(index, chunk: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    store = index.store
    for lo in range(0, store.capacity, chunk):
        hi = min(store.capacity, lo + chunk)
        ids = store.ids_by_slot[lo:hi]
        live = ids >= 0
        if not live.any():
            continue
        with store.device_lock:
            adj = np.asarray(store.adj[lo:hi])
        # slot-space neighbors translate to EXTERNAL ids so the digest is
        # invariant under slot renumbering (snapshot load reassigns slots)
        yield ids[live], store.ids_of_slots(adj[live])


def _iter_ivf_buckets(index, chunk: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Per-row coarse-list assignment as arranged on DEVICE: reads the
    view's bucket_slot array back (in bucket-axis chunks so each
    device_lock hold stays bounded like the other readers) and pairs
    each placed slot with its bucket's coarse list."""
    view = index._view
    store = index.store
    nbuckets = int(view.bucket_slot.shape[0])
    cap = max(1, int(view.cap_list))
    step = max(1, chunk // cap)
    for lo in range(0, nbuckets, step):
        hi = min(nbuckets, lo + step)
        with store.device_lock:
            bucket_slot = np.asarray(view.bucket_slot[lo:hi])
        valid = bucket_slot >= 0
        if not valid.any():
            continue
        coarse = np.broadcast_to(
            view.bucket_coarse_h[lo:hi, None], bucket_slot.shape
        )
        ids = store.ids_of_slots(bucket_slot[valid])
        yield ids, np.ascontiguousarray(coarse[valid], np.int32)


def _iter_assign(index, chunk: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Host assignment truth (_assign_h) — the ledger/restore source for
    ivf_buckets; the scrub compares it against _iter_ivf_buckets."""
    store = index.store
    ids_all = store.ids_by_slot
    live = np.flatnonzero(ids_all >= 0)
    if len(live):
        assign = index._assign_h[live].astype(np.int32)
        placed = assign >= 0
        yield ids_all[live][placed], assign[placed]


def _iter_pq_codes(index, chunk: int) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    store = index.store
    for lo in range(0, store.capacity, chunk):
        hi = min(store.capacity, lo + chunk)
        ids = store.ids_by_slot[lo:hi]
        live = ids >= 0
        if not live.any():
            continue
        with store.device_lock:
            codes = np.asarray(index._codes[lo:hi])
        yield ids[live], codes[live]


def _digest_chunks(tag: str, chunks) -> Tuple[SetDigest, Dict[int, int], int]:
    """(digest, id->fp map, slots) over a chunk stream."""
    dig = SetDigest()
    fp_map: Dict[int, int] = {}
    n = 0
    for ids, payload in chunks:
        fps = row_fingerprints(tag, ids, payload)
        dig.add(fps)
        fp_map.update(zip(np.asarray(ids, np.int64).tolist(), fps.tolist()))
        n += len(ids)
    return dig, fp_map, n


class IntegrityPlane:
    def __init__(self, registry=METRICS):
        self.registry = registry
        self._lock = threading.Lock()
        #: index object -> RegionIntegrity (weak: a swapped-out index takes
        #: its ledger with it; the fresh index starts clean)
        self._ledgers: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )
        #: region id -> scrub status (verdicts survive index swaps so the
        #: heartbeat keeps reporting a mismatch until a clean pass clears it)
        self._status: Dict[int, Dict[str, Any]] = {}

    # ---- gating ------------------------------------------------------------
    @staticmethod
    def enabled() -> bool:
        try:
            return bool(FLAGS.get("integrity_enabled"))
        except KeyError:  # registry not populated (unit contexts)
            return False

    # ---- ledger access -----------------------------------------------------
    def ledger(self, index) -> RegionIntegrity:
        with self._lock:
            led = self._ledgers.get(index)
            if led is None:
                led = self._ledgers[index] = RegionIntegrity(index.id)
            return led

    def peek(self, index) -> Optional[RegionIntegrity]:
        if index is None:
            return None
        with self._lock:
            return self._ledgers.get(index)

    def tracking(self, index) -> bool:
        """True while writes must keep folding into this index's ledger.
        Only ledger CREATION is gated on integrity.enabled — an existing
        ledger keeps tracking through a momentary flag toggle, because a
        ledger frozen across untracked writes would read as corruption
        forever after (the PR 9 quality-mirror toggle discipline)."""
        return self.enabled() or self.peek(index) is not None

    def tag_applied(self, index, log_id: int) -> None:
        """Stamp the ledger with the raft applied index its digests now
        correspond to (wrapper.add/delete call this right after advancing
        apply_log_id, still under the wrapper lock — so a heartbeat never
        reads a digest tagged with an index it doesn't describe)."""
        led = self.peek(index)
        if led is not None:
            led.applied_index = int(log_id)

    # ---- write-path hooks (called from the index classes) ------------------
    def note_mutation_begin(self, index) -> None:
        """Called at the TOP of every index write path, BEFORE any device
        state mutates: the ledger fold lands after the device write, so a
        scrub overlapping that window would otherwise read fresh bytes
        against a stale ledger and report phantom corruption — this
        counter lets it classify the pass as raced instead."""
        if not self.tracking(index):
            return
        self.ledger(index).begin_mutation()

    def note_mutation_end(self, index) -> None:
        led = self.peek(index)
        if led is not None:
            led.end_mutation()

    def note_write(self, index, artifact: str, ids: np.ndarray,
                   payload: np.ndarray) -> None:
        if len(ids) == 0 or not self.tracking(index):
            return
        self.ledger(index).update(artifact, ids, payload)
        self.registry.counter(
            "consistency.digest_updates", region_id=index.id
        ).add(1)

    def note_delete(self, index, ids: np.ndarray) -> None:
        if len(ids) == 0:
            return
        led = self.peek(index)
        if led is None:
            return
        with led.lock:
            for art in list(led.artifacts):
                led.artifacts[art].remove(ids)

    def reset_artifact(self, index, artifact: str) -> None:
        """Clear one artifact's ledger IN PLACE (full-swap paths like the
        adjacency install): ArtifactLedger.reset() bumps the version
        counter, so a scrub pass that captured the pre-swap digest
        classifies as raced — dropping the ledger object instead would
        recreate it at version 1 and make the swap invisible."""
        led = self.peek(index)
        if led is not None:
            with led.lock:
                art = led.artifacts.get(artifact)
                if art is not None:
                    art.reset()

    # ---- reporting ---------------------------------------------------------
    def region_report(self, index,
                      region_id: Optional[int] = None
                      ) -> Tuple[int, str, bool]:
        """(applied_index, digests_json, scrub_mismatch) for the heartbeat
        snapshot; empty digests when the plane is off or unprimed."""
        led = self.peek(index)
        applied, digests = 0, ""
        if led is not None:
            applied, digests = led.heartbeat_view()
        if region_id is None:
            region_id = getattr(index, "id", 0) if index is not None else 0
        st = self._status.get(region_id)
        return applied, digests, bool(st and st.get("mismatch"))

    def last_verified_ms(self, region_id: int) -> int:
        st = self._status.get(region_id)
        return int(st.get("last_verified_ms", 0)) if st else 0

    def forget_region(self, region_id: int) -> None:
        with self._lock:
            self._status.pop(region_id, None)

    # ---- artifact discovery ------------------------------------------------
    def _state_arms(self, index) -> Dict[str, Any]:
        """Artifact -> chunk-iterator factory for everything the index's
        CURRENT device/host state materializes. Adjacency and bucket arms
        only appear while their mirror/view is in sync with the store —
        a pending lazy re-export is staleness, not corruption."""
        arms: Dict[str, Any] = {}
        store = getattr(index, "store", None)
        if store is None or getattr(store, "ids_by_slot", None) is None:
            return arms
        arms["rows"] = _iter_rows
        if getattr(store, "vecs_blk", None) is not None:
            arms["blocked"] = _iter_blocked
        if getattr(store, "adj", None) is not None:
            fresh = getattr(index, "adjacency_in_sync", None)
            if fresh is None or fresh():
                arms["adjacency"] = _iter_adjacency
        if getattr(index, "_view", None) is not None \
                and not getattr(index, "_view_dirty", True):
            arms["ivf_buckets"] = _iter_ivf_buckets
        if getattr(index, "_codes", None) is not None:
            arms["pq_codes"] = _iter_pq_codes
        return arms

    # ---- restore / primer --------------------------------------------------
    def rebuild_from_index(self, index) -> Dict[str, str]:
        """Recompute every artifact ledger from the index's live state
        (snapshot load, scrub priming, pre-save reconciliation). Returns
        {artifact: digest hex}."""
        led = self.ledger(index)
        out: Dict[str, str] = {}
        arms = self._state_arms(index)
        # ivf bucket ledger rebuilds from the assignment TRUTH (_assign_h)
        # so a restore can verify before any view exists
        if getattr(index, "_assign_h", None) is not None \
                and getattr(index, "is_trained", lambda: False)():
            arms["ivf_buckets"] = _iter_assign
        for artifact, it in arms.items():
            dig, fp_map, _ = _digest_chunks(
                artifact, it(index, SCRUB_CHUNK)
            )
            with led.lock:
                art = led.ledger(artifact)
                art.reset()
                art._fp = fp_map
                art.digest = dig
            out[artifact] = dig.hex()
        # drop ledger entries whose backing state vanished (e.g. a load
        # into an untrained index: no codes, no buckets)
        with led.lock:
            for name in list(led.artifacts):
                if name not in arms:
                    del led.artifacts[name]
        return out

    def snapshot_artifacts(self, index) -> Dict[str, str]:
        """Digest vector persisted in snapshot meta.json. Reconciles the
        ledger against live state first when it is missing or stale (e.g.
        the index was populated while the plane was disabled), so the
        persisted vector always describes the bytes being saved."""
        if not self.enabled():
            return {}
        led = self.peek(index)
        store = getattr(index, "store", None)
        live = len(store) if store is not None else 0
        rows = None
        if led is not None:
            with led.lock:
                art = led.artifacts.get("rows")
                rows = art.count() if art is not None else None
        if rows is None or rows != live:
            self.rebuild_from_index(index)
            led = self.ledger(index)
        rep = led.report()["artifacts"]
        # only artifacts whose backing state is CURRENT may persist: a
        # stale adjacency ledger (mirror pending re-export) must not gate
        # the restore against bytes the snapshot never carried
        valid = set(self._state_arms(index))
        if getattr(index, "_assign_h", None) is not None \
                and getattr(index, "is_trained", lambda: False)():
            valid.add("ivf_buckets")
        return {k: v for k, v in rep.items()
                if k in SNAPSHOT_ARTIFACTS and k in valid}

    def verify_restore(self, index, meta_integrity) -> None:
        """Recompute digests from the just-restored state and compare with
        the snapshot's persisted vector; raises SnapshotCorruption on any
        mismatch (the manager then falls back to an engine rebuild)."""
        if not self.enabled():
            return
        actual = self.rebuild_from_index(index)
        if not meta_integrity:
            return
        bad = {}
        for artifact, expected in meta_integrity.items():
            got = actual.get(artifact)
            if got is not None and got != expected:
                bad[artifact] = {"expected": expected, "actual": got}
        if bad:
            self.registry.counter(
                "consistency.restore_mismatches", region_id=index.id
            ).add(len(bad))
            from dingo_tpu.index.base import SnapshotCorruption

            raise SnapshotCorruption(
                f"restored index {index.id} digests diverge from "
                f"snapshot meta: {bad}"
            )

    # ---- scrub -------------------------------------------------------------
    def scrub_index(self, index, chunk: int = SCRUB_CHUNK
                    ) -> Dict[str, Dict[str, Any]]:
        """Full-state digest recompute vs the incremental ledger for one
        index. Chunked device reads under store.device_lock (never one
        long hold); a ledger mutation racing the pass marks the artifact
        'raced' instead of mismatched. Returns per-artifact verdicts and
        feeds the consistency.* metrics family + flight recorder."""
        rid = index.id
        results: Dict[str, Dict[str, Any]] = {}
        led = self.ledger(index)
        t0 = time.perf_counter()
        arms = self._state_arms(index)
        checked_slots = 0
        for artifact, it in arms.items():
            with led.lock:
                art = led.artifacts.get(artifact)
                before = (art.version, art.digest.copy()) if art else None
                muts_before = led.mutations
                pending_before = led.pending
            actual, fp_map, n = _digest_chunks(artifact, it(index, chunk))
            checked_slots += n
            with led.lock:
                art2 = led.artifacts.get(artifact)
                # raced on ANY signal: a folded ledger mutation (artifact
                # version), a write that touched device state but hasn't
                # folded yet (region mutation counter, bumped before any
                # device write begins), or a write IN FLIGHT at either
                # endpoint of the pass (pending bracket — covers a write
                # that began before the capture and folds after the check)
                raced = (
                    pending_before > 0
                    or led.pending > 0
                    or led.mutations != muts_before
                    or (before is not None and (
                        art2 is None or art2.version != before[0]))
                )
                expected = (art2.digest.copy() if art2
                            else (before[1] if before else None))
                if before is None and not raced:
                    # state exists but was never ledgered (plane enabled
                    # mid-life): prime the ledger from this clean pass
                    art = led.ledger(artifact)
                    art._fp = fp_map
                    art.digest = actual
            if before is None and not raced:
                results[artifact] = {"status": "primed", "slots": n,
                                     "digest": actual.hex()}
                continue
            if raced:
                results[artifact] = {"status": "raced", "slots": n}
                continue
            if actual == expected:
                results[artifact] = {"status": "ok", "slots": n,
                                     "digest": actual.hex()}
            else:
                results[artifact] = {
                    "status": "mismatch", "slots": n,
                    "expected": expected.hex(), "actual": actual.hex(),
                }
        self._finish_scrub(rid, results, time.perf_counter() - t0)
        return results

    def _finish_scrub(self, rid: int, results, dur_s: float) -> None:
        reg = self.registry
        reg.counter("consistency.scrub_runs", region_id=rid).add(1)
        reg.counter("consistency.scrub_slots", region_id=rid).add(
            sum(r.get("slots", 0) for r in results.values())
        )
        reg.latency("consistency.scrub_ms", region_id=rid).observe_us(
            dur_s * 1e6
        )
        bad = {a: r for a, r in results.items()
               if r["status"] == "mismatch"}
        clean = bool(results) and all(
            r["status"] in ("ok", "primed") for r in results.values()
        )
        now_ms = int(time.time() * 1000)
        with self._lock:
            st = self._status.setdefault(rid, {})
            if bad:
                st["mismatch"] = True
                st["artifacts"] = sorted(bad)
            elif clean:
                st["mismatch"] = False
                st["artifacts"] = []
                st["last_verified_ms"] = now_ms
        if bad or clean:
            # only DECISIVE passes move the gauge: a raced/empty pass
            # after a confirmed mismatch must not flip a dashboard back
            # to healthy while the heartbeat still says CORRUPT
            reg.gauge("consistency.scrub_ok", region_id=rid).set(
                0.0 if bad else 1.0
            )
        if bad:
            for artifact, r in bad.items():
                reg.counter(
                    "consistency.scrub_mismatches", region_id=rid,
                    labels={"artifact": artifact},
                ).add(1)
                _log.error(
                    "integrity scrub MISMATCH region=%d artifact=%s "
                    "expected=%s actual=%s", rid, artifact,
                    r["expected"], r["actual"],
                )
            if bool(FLAGS.get("integrity_flight_on_divergence")):
                from dingo_tpu.obs.flight import FLIGHT

                FLIGHT.trigger(
                    "corruption",
                    name=f"scrub:{','.join(sorted(bad))}",
                    region_id=rid,
                    extra={"artifacts": bad},
                )

    def scrub_node(self, node) -> Dict[int, Dict[str, Dict[str, Any]]]:
        """One scrub sweep over every region's serving index (the
        consistency_scrub crontab body; best-effort per region)."""
        out: Dict[int, Dict[str, Dict[str, Any]]] = {}
        for region in node.meta.get_all_regions():
            wrapper = region.vector_index_wrapper
            idx = wrapper.own_index if wrapper is not None else None
            if idx is None:
                continue
            try:
                out[region.id] = self.scrub_index(idx)
            except Exception:  # noqa: BLE001 — index mid-swap/build
                _log.exception("scrub failed for region %d", region.id)
        now_ms = int(time.time() * 1000)
        for rid in out:
            last = self.last_verified_ms(rid)
            self.registry.gauge(
                "consistency.digest_age_s", region_id=rid
            ).set((now_ms - last) / 1000.0 if last else -1.0)
        return out

    # ---- flight capture ----------------------------------------------------
    def state(self) -> Dict[str, Any]:
        """Per-region digest vectors + scrub verdicts for flight bundles
        (resolved through live ledgers; weakly-held indexes may be gone)."""
        regions: Dict[int, Any] = {}
        with self._lock:
            items = list(self._ledgers.items())
            status = {r: dict(s) for r, s in self._status.items()}
        for index, led in items:
            rep = led.report()
            if rep["artifacts"]:
                regions[led.region_id] = rep
        return {"regions": regions, "scrub_status": status,
                "sampled_at": time.time()}

    def clear(self) -> None:
        with self._lock:
            self._ledgers = weakref.WeakKeyDictionary()
            self._status.clear()


INTEGRITY = IntegrityPlane()


class IntegrityScrubRunner:
    """consistency_scrub crontab body: hot-gated on integrity.enabled,
    re-applies a hot-changed integrity.scrub_interval_s per tick (the
    QualityTunerRunner pattern), and runs the sweep on its own worker so
    a long chunked scrub never stalls the shared crontab thread."""

    def __init__(self, node, crontab=None):
        self.node = node
        self._crontab = crontab
        self._worker: Optional[threading.Thread] = None
        self.sweeps = 0

    def tick(self) -> None:
        if self._crontab is not None:
            self._crontab.set_interval(
                "consistency_scrub",
                float(FLAGS.get("integrity_scrub_interval_s")),
            )
        if not INTEGRITY.enabled():
            return
        t = self._worker
        if t is not None and t.is_alive():
            return

        def work():
            INTEGRITY.scrub_node(self.node)
            self.sweeps += 1
            # recovery actions ride the same maintenance lane: rebuild
            # scrub-confirmed corrupt indexes from the engine, and
            # re-materialize device-degraded regions at lower precision
            # (index/recovery.py — fault-domain hardening)
            from dingo_tpu.index.recovery import RECOVERY

            try:
                RECOVERY.run_rematerializations(self.node)
            except Exception:  # noqa: BLE001 — next tick retries
                _log.exception("device recovery sweep failed")

        t = threading.Thread(target=work, name="consistency_scrub",
                             daemon=True)
        self._worker = t
        t.start()
