"""Native C++ LSM raw engine (native/lsm/lsm.cc via LsmRawEngine) —
RocksRawEngine's role: durability, compaction, checkpoints (reference
test/unit_test/engine/ suites)."""

import os

import numpy as np
import pytest

from dingo_tpu.engine.lsm_engine import LsmRawEngine
from dingo_tpu.engine.raw_engine import CF_DEFAULT, WriteBatch


@pytest.fixture()
def eng(tmp_path):
    e = LsmRawEngine(str(tmp_path / "db"), memtable_bytes=1 << 20)
    yield e
    e.close()


def test_crud_and_scan(eng):
    for i in range(100):
        eng.put(CF_DEFAULT, f"k{i:03d}".encode(), f"v{i}".encode())
    assert eng.get(CF_DEFAULT, b"k050") == b"v50"
    assert eng.get(CF_DEFAULT, b"missing") is None
    rows = eng.scan(CF_DEFAULT, b"k010", b"k020")
    assert [k for k, _ in rows] == [f"k{i:03d}".encode() for i in range(10, 20)]
    rrows = eng.scan_reverse(CF_DEFAULT, b"k010", b"k020")
    assert rrows == rows[::-1]
    assert eng.count(CF_DEFAULT, b"k010", b"k020") == 10
    eng.delete(CF_DEFAULT, b"k050")
    assert eng.get(CF_DEFAULT, b"k050") is None
    assert eng.count(CF_DEFAULT, b"", None) == 99


def test_batch_atomic_and_delete_range(eng):
    b = WriteBatch()
    for i in range(10):
        b.put(CF_DEFAULT, f"x{i}".encode(), b"v")
    eng.write(b)
    assert eng.count(CF_DEFAULT, b"x", b"y") == 10
    eng.delete_range(CF_DEFAULT, b"x2", b"x6")
    assert [k for k, _ in eng.scan(CF_DEFAULT, b"x", b"y")] == [
        b"x0", b"x1", b"x6", b"x7", b"x8", b"x9"
    ]


def test_restart_recovery(tmp_path):
    path = str(tmp_path / "db")
    e = LsmRawEngine(path, memtable_bytes=1 << 20)
    for i in range(50):
        e.put(CF_DEFAULT, f"k{i:02d}".encode(), b"v" * 10)
    e.delete(CF_DEFAULT, b"k10")
    e.close()
    e2 = LsmRawEngine(path, memtable_bytes=1 << 20)
    assert e2.get(CF_DEFAULT, b"k42") == b"v" * 10
    assert e2.get(CF_DEFAULT, b"k10") is None
    assert e2.count(CF_DEFAULT, b"", None) == 49
    e2.close()


def test_flush_tombstones_and_compaction(tmp_path):
    path = str(tmp_path / "db")
    e = LsmRawEngine(path, memtable_bytes=1 << 20)
    for i in range(20):
        e.put(CF_DEFAULT, f"k{i:02d}".encode(), b"v")
    e.flush()
    e.delete(CF_DEFAULT, b"k05")
    e.flush()                      # tombstone persisted in its own SST
    assert e.sst_counts()[CF_DEFAULT] >= 2
    assert e.get(CF_DEFAULT, b"k05") is None
    e.compact()                    # merge drops the dead row
    assert e.sst_counts()[CF_DEFAULT] == 1
    assert e.get(CF_DEFAULT, b"k05") is None
    assert e.count(CF_DEFAULT, b"", None) == 19
    e.close()
    e2 = LsmRawEngine(path)
    assert e2.get(CF_DEFAULT, b"k05") is None
    assert e2.get(CF_DEFAULT, b"k06") == b"v"
    e2.close()


def test_memtable_flush_trigger(tmp_path):
    e = LsmRawEngine(str(tmp_path / "db"), memtable_bytes=4096)
    payload = b"x" * 256
    for i in range(64):
        e.put(CF_DEFAULT, f"k{i:03d}".encode(), payload)
    assert e.sst_counts()[CF_DEFAULT] >= 1  # size trigger fired
    for i in range(64):
        assert e.get(CF_DEFAULT, f"k{i:03d}".encode()) == payload
    e.close()


def test_torn_wal_tail(tmp_path):
    path = str(tmp_path / "db")
    e = LsmRawEngine(path, memtable_bytes=1 << 20)
    for i in range(10):
        e.put(CF_DEFAULT, f"k{i}".encode(), b"v")
    e.close()
    wal = os.path.join(path, f"cf_{CF_DEFAULT}", "wal.log")
    data = open(wal, "rb").read()
    open(wal, "wb").write(data[:-5])
    e2 = LsmRawEngine(path)
    assert e2.get(CF_DEFAULT, b"k8") == b"v"
    assert e2.get(CF_DEFAULT, b"k9") is None       # torn record dropped
    e2.put(CF_DEFAULT, b"k9", b"v2")               # writable after recovery
    e2.close()
    e3 = LsmRawEngine(path)
    assert e3.get(CF_DEFAULT, b"k9") == b"v2"      # survives restart #2
    e3.close()


def test_checkpoint_restore(tmp_path):
    e = LsmRawEngine(str(tmp_path / "db"))
    for i in range(30):
        e.put(CF_DEFAULT, f"k{i:02d}".encode(), f"v{i}".encode())
    e.checkpoint(str(tmp_path / "ckpt"))
    e.put(CF_DEFAULT, b"k99", b"after")            # not in the checkpoint
    e.restore_checkpoint(str(tmp_path / "ckpt"))
    assert e.get(CF_DEFAULT, b"k15") == b"v15"
    assert e.get(CF_DEFAULT, b"k99") is None
    e.close()


def test_store_node_on_lsm(tmp_path):
    """Full store-node restart recovery on the native engine (same drive as
    the WalEngine durability test)."""
    import time

    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.index import codec as vcodec
    from dingo_tpu.index.base import IndexParameter, IndexType
    from dingo_tpu.raft.transport import LocalTransport
    from dingo_tpu.store.node import StoreNode
    from dingo_tpu.store.region import RegionType

    control = CoordinatorControl(MemEngine(), replication=1)
    raw = LsmRawEngine(str(tmp_path / "store"), memtable_bytes=32768)
    node = StoreNode("s0", LocalTransport(), control, raw_engine=raw,
                     raft_kw={"seed": 0})
    node.start_heartbeat(0.1)
    d = control.create_region(
        vcodec.encode_vector_key(1, 0), vcodec.encode_vector_key(1, 1 << 30),
        partition_id=1, region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=IndexType.FLAT,
                                       dimension=16),
    )
    time.sleep(1.0)
    region = node.get_region(d.region_id)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((300, 16)).astype(np.float32)
    node.storage.vector_add(region, np.arange(300, dtype=np.int64), x)
    node.stop()
    raw.close()

    raw2 = LsmRawEngine(str(tmp_path / "store"), memtable_bytes=32768)
    node2 = StoreNode("s0", LocalTransport(), None, raw_engine=raw2,
                      raft_kw={"seed": 0})
    assert node2.recover() == 1
    time.sleep(0.6)
    region2 = node2.get_region(d.region_id)
    res = node2.storage.vector_batch_search(region2, x[:2], 3)
    assert res[0][0].id == 0 and res[1][0].id == 1
    node2.stop()
    raw2.close()
