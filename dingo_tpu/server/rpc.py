"""grpc server plumbing: hand-written method handler registration.

One DingoServer can host store-role services (Index/Store/Node/Debug/Util —
the reference's dingodb_server --role=index|store) and/or coordinator-role
services (Coordinator/Version) in one process, like the reference binary.
"""

from __future__ import annotations

from concurrent import futures
from typing import Dict, Optional, Tuple

import grpc

from dingo_tpu.common.config import FLAGS
from dingo_tpu.obs.pressure import (
    attach_budget,
    detach_budget,
    extract_budget_metadata,
    inject_budget_metadata,
)
from dingo_tpu.raft.core import NotLeader
from dingo_tpu.server import pb
from dingo_tpu.trace import (
    TRACE_METADATA_KEY,
    TRACER,
    UNSAMPLED_HEADER,
    current_span,
    extract_metadata,
    inject_metadata,
)
from dingo_tpu.server.services import (
    CoordinatorService,
    DebugService,
    DocumentService,
    FileService,
    IndexService,
    NodeService,
    StoreService,
    UtilService,
    VersionService,
)

#: service -> method -> (request type, response type)
SERVICE_SCHEMA: Dict[str, Dict[str, Tuple[type, type]]] = {
    "IndexService": {
        "VectorSearch": (pb.VectorSearchRequest, pb.VectorSearchResponse),
        "VectorSearchDebug": (
            pb.VectorSearchDebugRequest, pb.VectorSearchDebugResponse,
        ),
        "VectorAdd": (pb.VectorAddRequest, pb.VectorAddResponse),
        "VectorImport": (pb.VectorImportRequest, pb.VectorImportResponse),
        "VectorDelete": (pb.VectorDeleteRequest, pb.VectorDeleteResponse),
        "VectorBatchQuery": (pb.VectorBatchQueryRequest, pb.VectorBatchQueryResponse),
        "VectorGetBorderId": (pb.VectorGetBorderIdRequest, pb.VectorGetBorderIdResponse),
        "VectorScanQuery": (pb.VectorScanQueryRequest, pb.VectorScanQueryResponse),
        "VectorCount": (pb.VectorCountRequest, pb.VectorCountResponse),
        "VectorBuild": (pb.VectorBuildRequest, pb.VectorBuildResponse),
        "VectorLoad": (pb.VectorLoadRequest, pb.VectorLoadResponse),
        "VectorStatus": (pb.VectorStatusRequest, pb.VectorStatusResponse),
        "VectorReset": (pb.VectorResetRequest, pb.VectorResetResponse),
        "VectorDump": (pb.VectorDumpRequest, pb.VectorDumpResponse),
        "VectorCountMemory": (
            pb.VectorCountMemoryRequest, pb.VectorCountMemoryResponse,
        ),
        "VectorGetRegionMetrics": (
            pb.VectorGetRegionMetricsRequest,
            pb.VectorGetRegionMetricsResponse,
        ),
    },
    "StoreService": {
        "KvGet": (pb.KvGetRequest, pb.KvGetResponse),
        "KvBatchGet": (pb.KvBatchGetRequest, pb.KvBatchGetResponse),
        "KvDeleteRange": (
            pb.KvDeleteRangeRequest, pb.KvDeleteRangeResponse,
        ),
        "KvBatchPut": (pb.KvBatchPutRequest, pb.KvBatchPutResponse),
        "KvPutIfAbsent": (pb.KvPutIfAbsentRequest, pb.KvPutIfAbsentResponse),
        "KvCompareAndSet": (
            pb.KvCompareAndSetRequest, pb.KvCompareAndSetResponse,
        ),
        "KvBatchDelete": (pb.KvBatchDeleteRequest, pb.KvBatchDeleteResponse),
        "KvScan": (pb.KvScanRequest, pb.KvScanResponse),
        "TxnPrewrite": (pb.TxnPrewriteRequest, pb.TxnPrewriteResponse),
        "TxnCommit": (pb.TxnCommitRequest, pb.TxnCommitResponse),
        "TxnGet": (pb.TxnGetRequest, pb.TxnGetResponse),
        "TxnScan": (pb.TxnScanRequest, pb.TxnScanResponse),
        "TxnBatchRollback": (pb.TxnBatchRollbackRequest, pb.TxnBatchRollbackResponse),
        "TxnCheckStatus": (pb.TxnCheckStatusRequest, pb.TxnCheckStatusResponse),
        "TxnPessimisticLock": (
            pb.TxnPessimisticLockRequest, pb.TxnPessimisticLockResponse,
        ),
        "TxnPessimisticRollback": (
            pb.TxnPessimisticRollbackRequest, pb.TxnPessimisticRollbackResponse,
        ),
        "TxnResolveLock": (pb.TxnResolveLockRequest, pb.TxnResolveLockResponse),
        "TxnHeartBeat": (pb.TxnHeartBeatRequest, pb.TxnHeartBeatResponse),
        "TxnGc": (pb.TxnGcRequest, pb.TxnGcResponse),
        "TxnScanLock": (pb.TxnScanLockRequest, pb.TxnScanLockResponse),
        "TxnBatchGet": (pb.TxnBatchGetRequest, pb.TxnBatchGetResponse),
        "TxnCheckSecondaryLocks": (
            pb.TxnCheckSecondaryLocksRequest, pb.TxnCheckSecondaryLocksResponse,
        ),
        "TxnDeleteRange": (pb.TxnDeleteRangeRequest, pb.TxnDeleteRangeResponse),
        "TxnDump": (pb.TxnDumpRequest, pb.TxnDumpResponse),
        "KvScanBegin": (pb.KvScanBeginRequest, pb.KvScanBeginResponse),
        "KvScanContinue": (pb.KvScanContinueRequest, pb.KvScanContinueResponse),
        "KvScanRelease": (pb.KvScanReleaseRequest, pb.KvScanReleaseResponse),
    },
    "DiskAnnService": {
        "DiskAnnNew": (pb.DiskAnnNewRequest, pb.DiskAnnNewResponse),
        "DiskAnnPushData": (
            pb.DiskAnnPushDataRequest, pb.DiskAnnPushDataResponse,
        ),
        "DiskAnnBuild": (pb.DiskAnnBuildRequest, pb.DiskAnnBuildResponse),
        "DiskAnnLoad": (pb.DiskAnnLoadRequest, pb.DiskAnnLoadResponse),
        "DiskAnnSearch": (pb.DiskAnnSearchRequest, pb.DiskAnnSearchResponse),
        "DiskAnnStatus": (pb.DiskAnnStatusRequest, pb.DiskAnnStatusResponse),
        "DiskAnnCount": (pb.DiskAnnCountRequest, pb.DiskAnnCountResponse),
        "DiskAnnReset": (pb.DiskAnnResetRequest, pb.DiskAnnResetResponse),
        "DiskAnnClose": (pb.DiskAnnCloseRequest, pb.DiskAnnCloseResponse),
        "DiskAnnDestroy": (
            pb.DiskAnnDestroyRequest, pb.DiskAnnDestroyResponse,
        ),
    },
    "MetaService": {
        "CreateSchema": (pb.CreateSchemaRequest, pb.CreateSchemaResponse),
        "DropSchema": (pb.DropSchemaRequest, pb.DropSchemaResponse),
        "GetSchemas": (pb.GetSchemasRequest, pb.GetSchemasResponse),
        "CreateTable": (pb.CreateTableRequest, pb.CreateTableResponse),
        "ImportTable": (pb.ImportTableRequest, pb.ImportTableResponse),
        "DropTable": (pb.DropTableRequest, pb.DropTableResponse),
        "GetTable": (pb.GetTableRequest, pb.GetTableResponse),
        "GetTables": (pb.GetTablesRequest, pb.GetTablesResponse),
        "MetaWatch": (pb.MetaWatchRequest, pb.MetaWatchResponse),
    },
    "UtilService": {
        "VectorCalcDistance": (pb.VectorCalcDistanceRequest, pb.VectorCalcDistanceResponse),
    },
    "DocumentService": {
        "DocumentAdd": (pb.DocumentAddRequest, pb.DocumentAddResponse),
        "DocumentDelete": (pb.DocumentDeleteRequest, pb.DocumentDeleteResponse),
        "DocumentSearch": (pb.DocumentSearchRequest, pb.DocumentSearchResponse),
        "DocumentCount": (pb.DocumentCountRequest, pb.DocumentCountResponse),
    },
    "NodeService": {
        "NodeInfo": (pb.NodeInfoRequest, pb.NodeInfoResponse),
        "GetVectorIndexSnapshotMeta": (
            pb.VectorIndexSnapshotMetaRequest,
            pb.VectorIndexSnapshotMetaResponse,
        ),
        "SetLogLevel": (pb.SetLogLevelRequest, pb.SetLogLevelResponse),
        "GetLogLevel": (pb.GetLogLevelRequest, pb.GetLogLevelResponse),
    },
    "FileService": {
        "ReadFileChunk": (pb.FileChunkRequest, pb.FileChunkResponse),
    },
    "DebugService": {
        "MetricsDump": (pb.MetricsDumpRequest, pb.MetricsDumpResponse),
        # trace exports reuse the MetricsDump message pair (json payload);
        # the method name alone routes — no proto regen needed
        "TraceDump": (pb.MetricsDumpRequest, pb.MetricsDumpResponse),
        "TraceChromeDump": (pb.MetricsDumpRequest, pb.MetricsDumpResponse),
        "FailPoint": (pb.FailPointRequest, pb.FailPointResponse),
        "FlightDump": (pb.FlightDumpRequest, pb.FlightDumpResponse),
        # process-local control-plane event ring (obs/events.py)
        "EventDump": (pb.EventDumpRequest, pb.EventDumpResponse),
    },
    "CoordinatorService": {
        "Hello": (pb.HelloRequest, pb.HelloResponse),
        "StoreHeartbeat": (pb.StoreHeartbeatRequest, pb.StoreHeartbeatResponse),
        "CreateRegion": (pb.CreateRegionRequest, pb.CreateRegionResponse),
        "SplitRegion": (pb.SplitRegionRequest, pb.SplitRegionResponse),
        "MergeRegion": (pb.MergeRegionRequest, pb.MergeRegionResponse),
        "ChangePeerRegion": (
            pb.ChangePeerRegionRequest, pb.ChangePeerRegionResponse,
        ),
        "TransferLeaderRegion": (
            pb.TransferLeaderRegionRequest, pb.TransferLeaderRegionResponse,
        ),
        "GetRegionMap": (pb.GetRegionMapRequest, pb.GetRegionMapResponse),
        "Tso": (pb.TsoRequest, pb.TsoResponse),
        "TsoAdvance": (pb.TsoAdvanceRequest, pb.TsoAdvanceResponse),
        "RequeueRegionCmd": (pb.RequeueRegionCmdRequest, pb.RequeueRegionCmdResponse),
        "GetGCSafePoint": (pb.GetGCSafePointRequest, pb.GetGCSafePointResponse),
    },
    "JobService": {
        "ListJobs": (pb.ListJobsRequest, pb.ListJobsResponse),
    },
    "ClusterStatService": {
        "GetClusterStat": (
            pb.GetClusterStatRequest, pb.GetClusterStatResponse,
        ),
        "GetStoreMetrics": (
            pb.GetStoreMetricsRequest, pb.GetStoreMetricsResponse,
        ),
        "GetRegionMetrics": (
            pb.GetRegionMetricsRequest, pb.GetRegionMetricsResponse,
        ),
        # merged cross-node control-plane timeline (obs/events.py) —
        # same message pair as the store-local DebugService.EventDump
        "EventDump": (pb.EventDumpRequest, pb.EventDumpResponse),
    },
    "RegionControlService": {
        "RegionSnapshot": (
            pb.RegionSnapshotRequest, pb.RegionSnapshotResponse,
        ),
        "RegionRebuildIndex": (
            pb.RegionRebuildIndexRequest, pb.RegionRebuildIndexResponse,
        ),
        "RegionDetail": (pb.RegionDetailRequest, pb.RegionDetailResponse),
        "RegionExport": (pb.RegionExportRequest, pb.RegionExportResponse),
        "RegionImport": (pb.RegionImportRequest, pb.RegionImportResponse),
    },
    "RaftService": {
        "RaftMessage": (pb.RaftMessageRequest, pb.RaftMessageResponse),
    },
    "PushService": {
        "PushStoreOperation": (
            pb.PushStoreOperationRequest, pb.PushStoreOperationResponse,
        ),
    },
    "VersionService": {
        "VKvPut": (pb.VKvPutRequest, pb.VKvPutResponse),
        "VKvRange": (pb.VKvRangeRequest, pb.VKvRangeResponse),
        "VKvDeleteRange": (
            pb.VKvDeleteRangeRequest, pb.VKvDeleteRangeResponse,
        ),
        "VKvCompaction": (pb.VKvCompactionRequest, pb.VKvCompactionResponse),
        "VKvWatch": (pb.VKvWatchRequest, pb.VKvWatchResponse),
        "LeaseGrant": (pb.LeaseGrantRequest, pb.LeaseGrantResponse),
        "LeaseRenew": (pb.LeaseRenewRequest, pb.LeaseRenewResponse),
        "LeaseRevoke": (pb.LeaseRevokeRequest, pb.LeaseRevokeResponse),
    },
}


def _register(server: grpc.Server, service_name: str, impl) -> None:
    schema = SERVICE_SCHEMA[service_name]
    handlers = {}
    for method, (req_t, resp_t) in schema.items():
        fn = getattr(impl, method)

        def make(fn, req_t, resp_t, method):
            span_name = f"rpc.{service_name}.{method}"

            def handler(request, context):
                # trace ingress: adopt the caller's context from metadata
                # (one distributed trace across client -> server -> raft
                # hops) or mint a root here; attaching makes every deeper
                # span — coalescer, reader, kernels — a descendant
                metadata = context.invocation_metadata()
                parent = extract_metadata(metadata)
                span = TRACER.start_span(span_name, parent=parent)
                # qos ingress: adopt the caller's time budget (remaining-
                # ms header -> host-monotonic deadline) or grant the
                # configured default while qos.enabled; None otherwise —
                # the budget rides the same contextvar plumbing as the
                # span, so the coalescer handoff and nested egress calls
                # see it without any per-layer threading
                budget = extract_budget_metadata(metadata)
                btoken = attach_budget(budget) if budget is not None \
                    else None
                # always-sample-slow: an unsampled request still gets a
                # two-clock-read watch so outlier latency is never lost
                slow_t0 = 0 if span.sampled else TRACER.slow_watch_start()
                # attach only when a sampling DECISION exists (sampled,
                # an upstream header, or a local rate roll). A rate-0
                # ingress with no header must leave the context clean —
                # otherwise nested outbound calls would propagate '0-0-0'
                # for a decision nobody made and permanently suppress
                # sampling on downstream servers that have tracing on
                decided = (
                    span.sampled or parent is not None
                    or FLAGS.get("trace_sampling_rate") > 0
                )
                token = span.attach() if decided else None
                try:
                    resp = fn(request)
                    if span.sampled and getattr(
                        getattr(resp, "error", None), "errcode", 0
                    ):
                        span.set_attr("errcode", resp.error.errcode)
                    return resp
                except NotLeader as e:
                    # replicated-coordinator followers (raft_meta proxies)
                    # surface the hint so clients re-route, same contract
                    # as store-side region writes
                    span.set_attr("errcode", 20001)
                    resp = resp_t()
                    if hasattr(resp, "error"):
                        resp.error.errcode = 20001
                        resp.error.errmsg = f"not leader: {e.leader_hint}"
                    return resp
                except Exception as e:  # noqa: BLE001
                    # unexpected failures (incl. injected failpoints) become
                    # in-band errors instead of opaque grpc UNKNOWNs
                    from dingo_tpu.common.log import get_logger

                    get_logger("rpc").exception(
                        "%s.%s failed", service_name, method)
                    span.set_error(e)
                    # black-box the failure: spans + metric deltas + kernel
                    # cache + hbm ledger at the moment it happened (device
                    # OOMs get their own reason and bump hbm.alloc_failures)
                    from dingo_tpu.obs.flight import black_box_error

                    black_box_error(span_name, e, span)
                    resp = resp_t()
                    if hasattr(resp, "error"):
                        resp.error.errcode = 99999
                        resp.error.errmsg = f"{type(e).__name__}: {e}"
                    return resp
                finally:
                    if btoken is not None:
                        detach_budget(btoken)
                    if token is not None:
                        span.detach(token)
                    span.end()
                    TRACER.slow_watch_end(span_name, slow_t0)

            return handler

        handlers[method] = grpc.unary_unary_rpc_method_handler(
            make(fn, req_t, resp_t, method),
            request_deserializer=req_t.FromString,
            response_serializer=resp_t.SerializeToString,
        )
    server.add_generic_rpc_handlers((
        grpc.method_handlers_generic_handler(
            f"dingo_tpu.{service_name}", handlers
        ),
    ))


class DingoServer:
    def __init__(self, port: int = 0, max_workers: int = 16):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")

    def host_store_role(self, node) -> None:
        """--role=store|index service set (main.cc:681+)."""
        from dingo_tpu.raft.grpc_transport import GrpcRaftTransport, RaftService
        from dingo_tpu.server.services import PushService

        if isinstance(node.engine.transport, GrpcRaftTransport):
            _register(self._server, "RaftService",
                      RaftService(node.engine.transport))
        _register(self._server, "PushService", PushService(node))
        self._index_service = IndexService(node)
        _register(self._server, "IndexService", self._index_service)
        _register(self._server, "StoreService", StoreService(node))
        _register(self._server, "DocumentService", DocumentService(node))
        _register(self._server, "FileService", FileService(node))
        _register(self._server, "NodeService", NodeService(node))
        _register(self._server, "DebugService", DebugService())
        _register(self._server, "UtilService", UtilService())
        from dingo_tpu.server.services import RegionControlService

        _register(self._server, "RegionControlService",
                  RegionControlService(node))

    def host_diskann_role(self, manager) -> None:
        """--role=diskann service set (main.cc:1340)."""
        from dingo_tpu.diskann.service import DiskAnnService

        _register(self._server, "DiskAnnService", DiskAnnService(manager))
        _register(self._server, "DebugService", DebugService())

    def host_coordinator_role(self, control, tso, kv_control,
                              meta=None, raft_transport=None) -> None:
        """--role=coordinator service set. `raft_transport` (a
        GrpcRaftTransport) is set for replicated-coordinator deployments so
        the meta raft group's RPCs land here."""
        from dingo_tpu.server.services import MetaService

        if raft_transport is not None:
            from dingo_tpu.raft.grpc_transport import RaftService

            _register(self._server, "RaftService",
                      RaftService(raft_transport))

        _register(self._server, "CoordinatorService",
                  CoordinatorService(control, tso))
        _register(self._server, "VersionService", VersionService(kv_control))
        _register(self._server, "DebugService", DebugService())
        if meta is None:
            from dingo_tpu.coordinator.meta import MetaControl

            meta = MetaControl(control.engine, control)
        _register(self._server, "MetaService", MetaService(meta))
        from dingo_tpu.server.services import ClusterStatService, JobService

        _register(self._server, "JobService", JobService(control))
        _register(self._server, "ClusterStatService",
                  ClusterStatService(control))

    def start(self) -> int:
        self._server.start()
        return self.port

    def stop(self, grace: float = 0.5) -> None:
        svc = getattr(self, "_index_service", None)
        if svc is not None:
            svc.close()
        self._server.stop(grace)


class _TracedCall:
    """Wraps a unary-unary multicallable: egress span + trace metadata
    injection so server-side spans join the caller's trace. Unsampled
    calls pass metadata through untouched (one sampled-check)."""

    __slots__ = ("_call", "_name")

    def __init__(self, call, name: str):
        self._call = call
        self._name = name

    def __call__(self, request, timeout=None, metadata=None, **kwargs):
        with TRACER.start_span(self._name) as span:
            # qos egress: the current budget (if any) crosses the wire as
            # remaining-ms + tenant + priority, next to the trace context
            metadata = inject_budget_metadata(metadata)
            if span.sampled:
                metadata = inject_metadata(metadata)
            elif current_span() is not None \
                    or FLAGS.get("trace_sampling_rate") > 0:
                # a decision WAS made — locally (rate > 0) or upstream
                # (an attached noop from an adopted '0-0-0' header):
                # propagate it so downstream servers don't re-roll and
                # mint fragment roots mid-request. With tracing fully off
                # and no inherited decision we send nothing — that path
                # stays allocation-free
                metadata = [
                    *(metadata or ()),
                    (TRACE_METADATA_KEY, UNSAMPLED_HEADER),
                ]
            return self._call(
                request, timeout=timeout, metadata=metadata, **kwargs
            )


class ServiceStub:
    """Minimal client-side stub (the grpc codegen plugin is absent)."""

    def __init__(self, channel: grpc.Channel, service_name: str):
        self._channel = channel
        self._service = service_name
        for method, (req_t, resp_t) in SERVICE_SCHEMA[service_name].items():
            setattr(self, method, _TracedCall(channel.unary_unary(
                f"/dingo_tpu.{service_name}/{method}",
                request_serializer=req_t.SerializeToString,
                response_deserializer=resp_t.FromString,
            ), f"client.{service_name}.{method}"))
