"""Serving-pressure observability plane + QoS budget propagation.

The rest of the observability stack can see latency (trace), recompiles
(sentinel), HBM (ledger), and recall (quality) — but nothing measures
*pressure*: how long requests wait relative to what they can afford, who
is demanding the capacity, and what fraction of the work served actually
arrived in time to matter. Under overload those are the only questions;
KBest (PAPERS.md) ties sustained QPS to a kernel path that is fed but
bounded, and Faiss frames ANN serving as optimization under a budget —
here the budget is per-request *time*, and this module makes it a
first-class, propagated, observed quantity.

Three cooperating pieces:

- **Budget** — the per-request deadline/tenant/priority triple. It rides
  the same plumbing as the trace context: a contextvar inside a process
  (surviving the coalescer's thread handoff via capture-at-submit), gRPC
  metadata between processes (``x-dingo-deadline-ms`` carries REMAINING
  milliseconds, never absolute wall time — clocks differ across hosts;
  the gRPC deadline-propagation convention). Extraction never fails the
  request it rode in on, and with ``qos.enabled = false`` and no headers
  present the path allocates nothing (the tracing discipline).

- **PressurePlane** (``PRESSURE``) — the sensor: the curated ``qos.*``
  metrics family. Per-(region, tenant, priority) demand and queue-depth
  gauges, queue-wait recorders and short-window watermarks, per-stage
  time-budget accounting (queue-wait / batch-form / kernel / rerank as
  percentages of the request's deadline), goodput-vs-throughput and
  shed/expired counters, and deadline-exceeded flight-bundle triggers.
  Region rollups ride heartbeats into the coordinator's ``cluster top``
  QDEPTH/PRESS/SHED columns (metrics/collector.py harvests them).

- **ShedController** — the actuator: graduated degrade under sustained
  queue pressure, built as an EXTENSION of the SLO tuner's knob ladder
  (obs/tuner.py), not a parallel controller: level 1 drops the exact
  rerank stage (``rerank_factor`` -> 1), level 2 walks nprobe/ef DOWN
  the same {1,1.5}x-pow2 shape ladder one step per tick (every value it
  can choose is an already-warm program — degrading never recompiles),
  level 3 publishes an ADVISORY sq8 precision target (a tier flip
  re-encodes the store; ROADMAP item 4's migration is the actor).
  Overrides land in ``VectorIndex.tuning`` — the same override path the
  tuner uses — and the ORIGINAL values are saved and restored as
  pressure clears, one level per tick in each direction (hysteresis).
  While a region is degraded the SLO tuner holds (it would tighten the
  very knobs pressure just relaxed).

The admission/expiry mechanics that FEED this plane live in
common/coalescer.py (the QoS layer grown out of the batching window);
the error types both layers speak are defined here.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS

_log = get_logger("obs.pressure")

#: gRPC metadata keys. The deadline carries REMAINING milliseconds at
#: injection time (clock-skew safe); tenant key is configurable via
#: ``qos.tenant_header`` so deployments can reuse an existing auth header.
DEADLINE_METADATA_KEY = "x-dingo-deadline-ms"
PRIORITY_METADATA_KEY = "x-dingo-priority"
DEFAULT_TENANT_HEADER = "x-dingo-tenant"

#: priority semantics: higher = more important. 0 = batch/background
#: (shed first), 1 = default, >= 2 = interactive (never pressure-shed,
#: only hopeless-deadline shed applies).
DEFAULT_PRIORITY = 1

#: watermark bucket rotation: recent_watermark() = max queue wait over
#: the current + previous bucket (a 2-bucket rolling window needs no
#: reader-side reset, so the collector and the shed controller can both
#: read it without racing each other)
WATERMARK_BUCKET_S = 5.0


class QosRejected(RuntimeError):
    """Base for QoS admission rejections. NOT retried as a direct search
    by the service layer — a rejection under pressure that falls back to
    an unbatched search would defeat the whole admission decision."""


class DeadlineExceeded(QosRejected):
    """The request's budget was already spent (at admission or in queue)."""


class RequestShed(QosRejected):
    """Dropped by admission control under pressure (policy-dependent)."""


def qos_enabled() -> bool:
    try:
        return bool(FLAGS.get("qos_enabled"))
    except KeyError:
        return False


def shed_policy() -> str:
    """`qos.shed_policy`: 'off' (observe only), 'degrade' (knob ladder
    only), 'drop' (admission shed only), 'degrade_drop' (both)."""
    try:
        return str(FLAGS.get("qos_shed_policy"))
    except KeyError:
        return "degrade_drop"


def _policy_drops() -> bool:
    return shed_policy() in ("drop", "degrade_drop")


# ---------------------------------------------------------------------------
# Budget: the propagated deadline/tenant/priority triple
# ---------------------------------------------------------------------------

class Budget:
    """Per-request time budget. ``deadline`` is a host-local monotonic
    instant (never propagated raw — remaining ms is what crosses the
    wire). ``deadline_ms`` keeps the ORIGINAL grant so stage accounting
    can express spent time as a fraction of it."""

    __slots__ = ("deadline", "deadline_ms", "tenant", "priority", "t0")

    def __init__(self, deadline_ms: float, tenant: str = "default",
                 priority: int = DEFAULT_PRIORITY,
                 t0: Optional[float] = None):
        self.t0 = time.monotonic() if t0 is None else t0
        self.deadline_ms = float(deadline_ms)
        self.deadline = self.t0 + self.deadline_ms / 1000.0
        self.tenant = tenant or "default"
        self.priority = int(priority)

    def remaining_ms(self, now: Optional[float] = None) -> float:
        return (self.deadline - (now if now is not None
                                 else time.monotonic())) * 1000.0

    def expired(self, now: Optional[float] = None) -> bool:
        return self.remaining_ms(now) <= 0.0

    def elapsed_ms(self, now: Optional[float] = None) -> float:
        return ((now if now is not None else time.monotonic())
                - self.t0) * 1000.0

    def fraction_spent(self, ms: float) -> float:
        """`ms` as a percentage of the original grant (stage accounting)."""
        if self.deadline_ms <= 0:
            return 0.0
        return 100.0 * ms / self.deadline_ms

    def __repr__(self) -> str:
        return (f"Budget(remaining={self.remaining_ms():.1f}ms, "
                f"tenant={self.tenant!r}, priority={self.priority})")


_BUDGET: contextvars.ContextVar[Optional[Budget]] = contextvars.ContextVar(
    "dingo_qos_budget", default=None
)


def current_budget() -> Optional[Budget]:
    return _BUDGET.get()


def attach_budget(budget: Optional[Budget]):
    """Make `budget` current; returns the token for detach_budget()."""
    return _BUDGET.set(budget)


def detach_budget(token) -> None:
    try:
        _BUDGET.reset(token)
    except ValueError:
        pass    # token minted in another thread/context (handoff)


@contextlib.contextmanager
def budget_scope(deadline_ms: float, tenant: str = "default",
                 priority: int = DEFAULT_PRIORITY):
    """Client-side scope: calls made inside carry this budget (the stub's
    egress injection reads the contextvar, mirroring trace injection)."""
    token = attach_budget(Budget(deadline_ms, tenant, priority))
    try:
        yield
    finally:
        detach_budget(token)


def tenant_header() -> str:
    try:
        return str(FLAGS.get("qos_tenant_header")) or DEFAULT_TENANT_HEADER
    except KeyError:
        return DEFAULT_TENANT_HEADER


def inject_budget_metadata(
    metadata: Optional[Sequence[Tuple[str, str]]] = None,
) -> Optional[List[Tuple[str, str]]]:
    """Append the current budget to outbound gRPC metadata (remaining-ms
    form). Returns the input unchanged (possibly None) when no budget is
    attached — the no-QoS path must not allocate."""
    cur = _BUDGET.get()
    if cur is None:
        return list(metadata) if metadata is not None else None
    entries = [(DEADLINE_METADATA_KEY, f"{cur.remaining_ms():.3f}")]
    if cur.tenant != "default":
        entries.append((tenant_header(), cur.tenant))
    if cur.priority != DEFAULT_PRIORITY:
        entries.append((PRIORITY_METADATA_KEY, str(cur.priority)))
    return [*(metadata or ()), *entries]


def extract_budget_metadata(
    metadata: Optional[Iterable[Tuple[str, str]]],
) -> Optional[Budget]:
    """Parse the QoS headers out of invocation metadata into a Budget.
    Malformed values never fail the RPC (the trace-extract contract).
    With no deadline header: ``qos.enabled`` servers grant the configured
    ``qos.default_deadline_ms`` (0 = unbounded -> no budget); disabled
    servers return None unless a deadline header is present (pure
    propagation still works so a mid-upgrade fleet keeps the chain)."""
    deadline_ms: Optional[float] = None
    tenant = "default"
    priority = DEFAULT_PRIORITY
    thdr = tenant_header()
    for key, value in metadata or ():
        try:
            if key == DEADLINE_METADATA_KEY:
                deadline_ms = float(value)
            elif key == thdr:
                tenant = str(value) or "default"
            elif key == PRIORITY_METADATA_KEY:
                priority = int(value)
        except (TypeError, ValueError):
            continue
    if deadline_ms is None:
        if not qos_enabled():
            return None
        try:
            default_ms = float(FLAGS.get("qos_default_deadline_ms"))
        except KeyError:
            default_ms = 0.0
        if default_ms <= 0:
            return None
        deadline_ms = default_ms
    return Budget(deadline_ms, tenant, priority)


# ---------------------------------------------------------------------------
# PressurePlane: the qos.* sensor
# ---------------------------------------------------------------------------

class _RegionPressure:
    """Per-region aggregate the heartbeat rollup harvests. Counters are
    cumulative (the snapshot ships totals; the coordinator sees rates via
    successive beats); the queue-wait watermark is a 2-bucket rolling max
    so concurrent readers never need a reset."""

    __slots__ = ("queued_rows", "shed", "expired", "served",
                 "served_in_deadline", "deadline_exceeded",
                 "_wm_bucket", "_wm_cur", "_wm_prev")

    def __init__(self):
        self.queued_rows = 0
        self.shed = 0
        self.expired = 0
        self.served = 0
        self.served_in_deadline = 0
        self.deadline_exceeded = 0
        self._wm_bucket = 0
        self._wm_cur = 0.0
        self._wm_prev = 0.0

    def note_wait(self, wait_ms: float, now: float) -> None:
        b = int(now / WATERMARK_BUCKET_S)
        if b != self._wm_bucket:
            self._wm_prev = self._wm_cur if b == self._wm_bucket + 1 else 0.0
            self._wm_cur = 0.0
            self._wm_bucket = b
        if wait_ms > self._wm_cur:
            self._wm_cur = wait_ms

    def recent_watermark(self, now: float) -> float:
        b = int(now / WATERMARK_BUCKET_S)
        if b == self._wm_bucket:
            return max(self._wm_cur, self._wm_prev)
        if b == self._wm_bucket + 1:
            return self._wm_cur
        return 0.0


class PressurePlane:
    """Process-global pressure sensor (one per store, like METRICS)."""

    def __init__(self, registry=METRICS):
        self.registry = registry
        self._lock = threading.Lock()
        self._regions: Dict[int, _RegionPressure] = {}

    def _region(self, region_id: int) -> _RegionPressure:
        """Caller must hold self._lock — every _RegionPressure mutation
        happens under the plane lock (read-modify-write counters shared
        across request + flush threads)."""
        rp = self._regions.get(region_id)
        if rp is None:
            rp = self._regions[region_id] = _RegionPressure()
        return rp

    @staticmethod
    def _labels(budget: Optional[Budget]) -> Dict[str, str]:
        if budget is None:
            return {"tenant": "default", "priority": str(DEFAULT_PRIORITY)}
        return {"tenant": budget.tenant, "priority": str(budget.priority)}

    # -- queue lifecycle -----------------------------------------------------
    def on_admit(self, region_id: int, rows: int,
                 budget: Optional[Budget]) -> None:
        lab = self._labels(budget)
        self.registry.counter("qos.admitted", region_id=region_id).add(1)
        self.registry.counter("qos.demand_rows", labels=lab).add(rows)
        self.registry.gauge("qos.queue_depth", region_id=region_id,
                            labels=lab).add(rows)
        with self._lock:
            self._region(region_id).queued_rows += rows

    def on_dequeue(self, region_id: int, rows: int,
                   budget: Optional[Budget]) -> None:
        self.registry.gauge("qos.queue_depth", region_id=region_id,
                            labels=self._labels(budget)).add(-rows)
        with self._lock:
            rp = self._region(region_id)
            rp.queued_rows = max(0, rp.queued_rows - rows)

    def observe_wait(self, region_id: int, wait_ms: float,
                     budget: Optional[Budget]) -> None:
        self.registry.latency("qos.queue_wait", region_id=region_id
                              ).observe_us(wait_ms * 1000.0)
        with self._lock:
            self._region(region_id).note_wait(wait_ms, time.monotonic())

    # -- outcomes ------------------------------------------------------------
    def on_expired(self, where: str, region_id: int,
                   budget: Optional[Budget], n: int = 1) -> None:
        """`where` is 'admission' (rejected before any queueing) or
        'queue' (died waiting; dropped before dispatch)."""
        self.registry.counter(
            "qos.expired", region_id=region_id,
            labels={**self._labels(budget), "where": where},
        ).add(n)
        with self._lock:
            self._region(region_id).expired += n

    def on_shed(self, reason: str, region_id: int,
                budget: Optional[Budget], n: int = 1) -> None:
        """`reason`: 'pressure' (queue-wait bound), 'hopeless' (could not
        finish inside its own deadline), 'tenant_limit' (per-tenant
        queue-row cap)."""
        self.registry.counter(
            "qos.shed", region_id=region_id,
            labels={**self._labels(budget), "reason": reason},
        ).add(n)
        with self._lock:
            self._region(region_id).shed += n

    def on_served(self, region_id: int, budget: Optional[Budget],
                  elapsed_ms: Optional[float] = None) -> None:
        """Throughput vs goodput: every reply counts served; only replies
        inside their deadline count toward goodput. A reply that missed
        its deadline additionally black-boxes the moment (rate-limited)."""
        self.registry.counter("qos.served", region_id=region_id).add(1)
        if budget is not None and elapsed_ms is None:
            elapsed_ms = budget.elapsed_ms()
        in_deadline = budget is None or elapsed_ms <= budget.deadline_ms
        with self._lock:
            rp = self._region(region_id)
            rp.served += 1
            if in_deadline:
                rp.served_in_deadline += 1
            else:
                rp.deadline_exceeded += 1
        if in_deadline:
            self.registry.counter("qos.served_in_deadline",
                                  region_id=region_id).add(1)
        else:
            self.registry.counter("qos.deadline_exceeded",
                                  region_id=region_id).add(1)
            self._flight_deadline_exceeded(region_id, budget, elapsed_ms)

    def observe_stages(self, budget: Optional[Budget],
                       stages_ms: Dict[str, float]) -> None:
        """Per-stage time-budget accounting: each stage's share of the
        request's deadline, observed in PERCENT (the recorder's p50/p99
        then read as 'the kernel stage typically eats N% of the grant').
        Stages: queue / batch_form / kernel / rerank, plus dispatch on
        the pipelined path (the kernel-enqueue + staging cost the
        overlapped flush pays per batch — booked separately so it never
        inflates the kernel fraction the SLO tuner reads)."""
        if budget is None or budget.deadline_ms <= 0:
            return
        for stage, ms in stages_ms.items():
            if ms <= 0:
                continue
            self.registry.latency(
                "qos.stage_budget_pct", labels={"stage": stage}
            ).observe_us(budget.fraction_spent(ms))

    def _flight_deadline_exceeded(self, region_id: int, budget: Budget,
                                  elapsed_ms: float) -> None:
        """Deadline-exceeded flight bundle: carries the absolute qos.*
        family state (the recorder snapshots it like mesh/hnsw/quality).
        Rate-limited per reason by the recorder itself; never raises."""
        try:
            from dingo_tpu.obs.flight import FLIGHT

            FLIGHT.trigger(
                "deadline_exceeded", region_id=region_id,
                extra={
                    "elapsed_ms": round(elapsed_ms, 1),
                    "deadline_ms": round(budget.deadline_ms, 1),
                    "tenant": budget.tenant,
                    "priority": budget.priority,
                },
            )
        except Exception:  # noqa: BLE001 — observability never fails serving
            pass

    # -- rollups -------------------------------------------------------------
    def region_stats(self, region_id: int) -> Dict[str, float]:
        """Heartbeat harvest (metrics/collector.py): queue depth, recent
        queue-wait watermark, cumulative shed+expired, goodput counters.
        Read-only — the watermark window rotates by itself."""
        with self._lock:
            rp = self._regions.get(region_id)
            if rp is None:
                return {"queue_depth": 0, "queue_wait_ms": 0.0,
                        "shed_total": 0, "served": 0,
                        "served_in_deadline": 0}
            return {
                "queue_depth": rp.queued_rows,
                "queue_wait_ms": rp.recent_watermark(time.monotonic()),
                "shed_total": rp.shed + rp.expired,
                "served": rp.served,
                "served_in_deadline": rp.served_in_deadline,
            }

    def queue_pressure_ms(self, region_id: int) -> float:
        """The shed controller's input: recent queue-wait watermark."""
        with self._lock:
            rp = self._regions.get(region_id)
            return rp.recent_watermark(time.monotonic()) if rp else 0.0

    def forget_region(self, region_id: int) -> None:
        with self._lock:
            self._regions.pop(region_id, None)

    def reset(self) -> None:
        """Test/bench isolation only."""
        with self._lock:
            self._regions.clear()


PRESSURE = PressurePlane()


def degrade_level(region_id: int) -> int:
    """Current shed-ladder degrade level for a region, read from the
    published ``qos.degrade_level`` gauge (the same value heartbeats and
    the SLO tuner consume) — 0 when no ShedController has run. Lets
    consumers outside the qos plane (e.g. the serving-edge cache's
    stale-rung policy) observe pressure without holding a ShedController
    reference."""
    from dingo_tpu.common.metrics import METRICS

    try:
        return int(METRICS.gauge("qos.degrade_level", region_id).get())
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------------------
# ShedController: graduated degrade on the tuner's ladder
# ---------------------------------------------------------------------------

#: degrade ladder levels (cheap -> drastic); one level per tick each way
DEGRADE_NONE = 0
DEGRADE_DROP_RERANK = 1      # rerank_factor -> 1 (skip the exact stage)
DEGRADE_LOWER_PROBE = 2      # nprobe/ef one ladder step down per tick
DEGRADE_SQ8_ADVISORY = 3     # publish the precision target (never flips)

MAX_DEGRADE_LEVEL = DEGRADE_SQ8_ADVISORY


class ShedController:
    """Pressure actuator: walks each over-pressure region one degrade
    level per tick and restores one level per calm tick. Escalation uses
    the SAME knob model and shape ladder as the SLO tuner (every value a
    warm program), and every change goes through ``index.tuning`` so a
    request-pinned parameter still wins."""

    def __init__(self, node, plane: Optional[PressurePlane] = None,
                 tuner=None, crontab=None, tab_name: str = "qos_shed"):
        from dingo_tpu.obs.tuner import SloTuner

        self.node = node
        self.plane = plane or PRESSURE
        self.tuner = tuner or SloTuner()
        #: owning CrontabManager (when crontab-wired): qos.shed_interval_s
        #: is hot-changeable, so each tick re-applies it to the tab (the
        #: QualityTunerRunner pattern)
        self._crontab = crontab
        self._tab_name = tab_name
        #: region -> degrade level
        self._level: Dict[int, int] = {}
        #: region -> {knob: original tuning value (None = was unset)}
        self._saved: Dict[int, Dict[str, Optional[int]]] = {}

    def degrade_level(self, region_id: int) -> int:
        return self._level.get(region_id, 0)

    # -- knob mechanics (the tuner's ladder, walked downward) ---------------
    def _save(self, region_id: int, knob: str, index) -> None:
        self._saved.setdefault(region_id, {}).setdefault(
            knob, index.tuning.get(knob)
        )

    def _apply_level(self, index, level: int) -> Optional[str]:
        """Apply ONE escalation step for `level`; returns a description
        (for the log/counter) or None when the level has no effect on
        this index kind (still counts as escalated — the next tick moves
        on)."""
        from dingo_tpu.obs.tuner import ladder_step

        rid = index.id
        knobs = {k: (ladder, cur) for k, ladder, cur
                 in self.tuner._knobs(index)}
        if level == DEGRADE_DROP_RERANK:
            if "rerank_factor" not in knobs:
                return None
            _, cur = knobs["rerank_factor"]
            if cur <= 1:
                return None
            self._save(rid, "rerank_factor", index)
            index.tuning["rerank_factor"] = 1
            return f"rerank_factor {cur} -> 1"
        if level == DEGRADE_LOWER_PROBE:
            for knob in ("nprobe", "ef"):
                if knob not in knobs:
                    continue
                ladder, cur = knobs[knob]
                prev = ladder_step(ladder, cur, up=False)
                if prev is None:
                    return None
                self._save(rid, knob, index)
                index.tuning[knob] = int(prev)
                return f"{knob} {cur} -> {prev}"
            return None
        if level == DEGRADE_SQ8_ADVISORY:
            precision = getattr(index, "_precision", "fp32")
            if precision == "sq8":
                return None
            self.registry_gauge_advisory(rid, 1.0)
            return f"advisory precision {precision} -> sq8"
        return None

    def registry_gauge_advisory(self, region_id: int, v: float) -> None:
        self.plane.registry.gauge(
            "qos.precision_advisory", region_id=region_id
        ).set(v)

    def _restore(self, index) -> None:
        """Put every saved knob back (pressure cleared)."""
        saved = self._saved.pop(index.id, {})
        for knob, orig in saved.items():
            if orig is None:
                index.tuning.pop(knob, None)
            else:
                index.tuning[knob] = orig
        self.registry_gauge_advisory(index.id, 0.0)

    # -- the control step ----------------------------------------------------
    def step_region(self, region_id: int, index,
                    pressure_ms: float, max_queue_ms: float) -> int:
        """One tick for one region: escalate one level while the recent
        queue-wait watermark exceeds ``qos.max_queue_ms``, de-escalate
        one level once it falls below half of it (hysteresis band), hold
        in between. Returns the new degrade level."""
        from dingo_tpu.obs.events import EVENTS

        level = self._level.get(region_id, 0)
        g = self.plane.registry.gauge
        if max_queue_ms > 0 and pressure_ms > max_queue_ms:
            if level < MAX_DEGRADE_LEVEL:
                level += 1
                desc = self._apply_level(index, level)
                self._level[region_id] = level
                EVENTS.emit(
                    "shed", region_id, "degrade_level", level - 1, level,
                    trigger="escalate",
                    evidence={"pressure_ms": round(pressure_ms, 2),
                              "max_queue_ms": max_queue_ms,
                              "step": desc or ""},
                )
                self.plane.registry.counter(
                    "qos.degrade_steps", region_id=region_id,
                    labels={"direction": "down"},
                ).add(1)
                if desc or level == DEGRADE_LOWER_PROBE:
                    # quality evidence gathered before the knob moved must
                    # not judge the degraded setting (the tuner's reset
                    # discipline)
                    self._reset_quality(region_id)
                _log.warning(
                    "shed region %d: pressure %.0fms > %.0fms, degrade "
                    "level %d (%s)", region_id, pressure_ms, max_queue_ms,
                    level, desc or "no-op for this index",
                )
            else:
                # at the ladder top but pressure persists: the graduated
                # walk continues — nprobe/ef keeps stepping down one warm
                # ladder rung per tick until its floor ("one step per
                # tick" outlives the level count; the floor ends it)
                desc = self._apply_level(index, DEGRADE_LOWER_PROBE)
                if desc:
                    EVENTS.emit(
                        "shed", region_id, "degrade_level", level, level,
                        trigger="escalate",
                        evidence={"pressure_ms": round(pressure_ms, 2),
                                  "max_queue_ms": max_queue_ms,
                                  "step": desc},
                    )
                    self.plane.registry.counter(
                        "qos.degrade_steps", region_id=region_id,
                        labels={"direction": "down"},
                    ).add(1)
                    self._reset_quality(region_id)
                    _log.warning(
                        "shed region %d: pressure %.0fms > %.0fms still, "
                        "degrade level %d (%s)", region_id, pressure_ms,
                        max_queue_ms, level, desc,
                    )
        elif level > 0 and pressure_ms < 0.5 * max_queue_ms:
            level -= 1
            if level == 0:
                self._restore(index)
                self._reset_quality(region_id)
            self._level[region_id] = level
            EVENTS.emit(
                "shed", region_id, "degrade_level", level + 1, level,
                trigger="restore" if level == 0 else "relax",
                evidence={"pressure_ms": round(pressure_ms, 2),
                          "max_queue_ms": max_queue_ms},
            )
            self.plane.registry.counter(
                "qos.degrade_steps", region_id=region_id,
                labels={"direction": "up"},
            ).add(1)
            _log.info("shed region %d: pressure cleared, degrade level %d",
                      region_id, level)
        g("qos.degrade_level", region_id=region_id).set(float(level))
        return level

    @staticmethod
    def _reset_quality(region_id: int) -> None:
        try:
            from dingo_tpu.obs.quality import QUALITY

            QUALITY.reset_region(region_id)
        except Exception:  # noqa: BLE001
            pass

    def _restore_all(self) -> None:
        """The flags no longer permit degrading: a disabled actuator must
        not pin its overrides — put every degraded region back to its
        saved settings NOW (and zero the gauges, which the SLO tuner
        reads as 'hold while > 0')."""
        if not self._level and not self._saved:
            return
        by_id = {}
        if self.node is not None:
            for region in self.node.meta.get_all_regions():
                wrapper = region.vector_index_wrapper
                if wrapper is not None and wrapper.own_index is not None:
                    by_id[region.id] = wrapper.own_index
        from dingo_tpu.obs.events import EVENTS

        for rid in set(self._level) | set(self._saved):
            index = by_id.get(rid)
            if index is not None:
                self._restore(index)        # pops _saved, zeroes advisory
            else:
                self._saved.pop(rid, None)  # region departed: just drop
                self.registry_gauge_advisory(rid, 0.0)
            EVENTS.emit(
                "shed", rid, "degrade_level",
                self._level.get(rid, 0), 0, trigger="disable",
                evidence={"reason": "shed policy flipped off"},
            )
            self._level.pop(rid, None)
            self.plane.registry.gauge(
                "qos.degrade_level", region_id=rid).set(0.0)
            self._reset_quality(rid)
            _log.info("shed region %d: degrading disabled, settings "
                      "restored", rid)

    def tick(self) -> int:
        """Crontab body (server/main.py ``qos_shed`` tab): hot-reads the
        flags per tick so operators can flip policy live; no-ops entirely
        unless ``qos.enabled`` and the policy includes 'degrade' — but a
        flip-to-off mid-incident still restores any degraded region
        first (overrides must never outlive the actuator)."""
        if self._crontab is not None:
            self._crontab.set_interval(
                self._tab_name, float(FLAGS.get("qos_shed_interval_s"))
            )
        try:
            max_queue_ms = float(FLAGS.get("qos_max_queue_ms"))
        except KeyError:
            max_queue_ms = 0.0
        if not qos_enabled() or max_queue_ms <= 0 \
                or shed_policy() not in ("degrade", "degrade_drop"):
            self._restore_all()
            return 0
        degraded = 0
        for region in self.node.meta.get_all_regions():
            wrapper = region.vector_index_wrapper
            if wrapper is None or not wrapper.is_ready():
                continue
            index = wrapper.own_index
            if index is None:
                continue
            pressure_ms = self.plane.queue_pressure_ms(region.id)
            if self.step_region(region.id, index, pressure_ms,
                                max_queue_ms) > 0:
                degraded += 1
        return degraded
