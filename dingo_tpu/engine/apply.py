"""Apply handlers: committed write payloads -> raw engine + vector index.

Reference: src/handler/raft_apply_handler.{h,cc} — per-command-type handlers
dispatched from StoreStateMachine::on_apply (store_state_machine.cc:110-216).
The same handlers serve both the raft path (every replica applies the
committed entry) and the mono path (single-replica direct apply), which is
exactly how MonoStoreEngine reuses them in the reference.

Key invariant (§3.2): the raw engine write happens FIRST (source of truth),
then the vector index is updated iff log_id > wrapper.apply_log_id — the
in-memory ANN index is an apply-log-tracked materialized view that can always
be rebuilt from the engine.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dingo_tpu.engine.raw_engine import (
    CF_DEFAULT,
    CF_VECTOR_SCALAR,
    CF_VECTOR_SCALAR_SPEEDUP,
    CF_VECTOR_TABLE,
    RawEngine,
    WriteBatch,
)
from dingo_tpu.engine import write_data as wd
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.vector_reader import serialize_scalar, serialize_vector
from dingo_tpu.mvcc.codec import MAX_TS, Codec, ValueFlag
from dingo_tpu.store.region import Region
from dingo_tpu.raft import wire


def apply_write(
    engine: RawEngine, region: Region, data: wd.WriteData, log_id: int = 0,
    context=None, want_result: bool = True,
) -> Optional[dict]:
    """Dispatch one committed payload (RaftApplyHandlerFactory equivalent).

    `context` (optional) is the hosting StoreNode for handlers that touch
    region topology (SplitHandler needs to create the child region and its
    raft member on EVERY replica applying the entry).

    Returns an optional handler result (e.g. {"deleted": n} for range
    deletes) that the replication engines surface to the proposer — the
    applied state, not a pre-propose scan, is what response counts must
    reflect (they can diverge under concurrent writes)."""
    from dingo_tpu.common.failpoint import failpoint

    failpoint("before_apply")
    if isinstance(data, wd.SplitRegionData):
        if context is None:
            raise NotImplementedError(
                "region split needs a StoreNode context (mono engines do "
                "not host split topology)"
            )
        context.handle_split(region, data, log_id)
        return None
    if isinstance(data, wd.MergeRegionData):
        if context is None:
            raise NotImplementedError("region merge needs a StoreNode context")
        context.handle_merge(region, data, log_id)
        return None
    if isinstance(data, wd.RegionInstallData):
        _apply_region_install(engine, region, data)
        # rebuild derived in-memory indexes on THIS replica (each replica's
        # apply runs with its own node context)
        if context is not None and hasattr(context, "after_region_install"):
            context.after_region_install(region)
        return None
    if isinstance(data, wd.KvPutData):
        _apply_kv_put(engine, data)
    elif isinstance(data, wd.KvDeleteData):
        _apply_kv_delete(engine, data)
    elif isinstance(data, wd.KvDeleteRangeData):
        return _apply_kv_delete_range(engine, data, want_result)
    elif isinstance(data, wd.VectorAddData):
        _apply_vector_add(engine, region, data, log_id)
    elif isinstance(data, wd.VectorDeleteData):
        _apply_vector_delete(engine, region, data, log_id)
    elif isinstance(data, wd.DocumentAddData):
        _apply_document_add(engine, region, data, log_id)
    elif isinstance(data, wd.DocumentDeleteData):
        _apply_document_delete(engine, region, data, log_id)
    elif isinstance(data, wd.TxnRaftData):
        _apply_txn(engine, data)
    else:
        raise TypeError(f"unknown write payload {type(data)}")
    return None


def _apply_region_install(
    engine: RawEngine, region: Region, data: wd.RegionInstallData
) -> None:
    """Wipe + restore the region's range — delegates to the one
    region_install implementation (function-level import: raft_engine
    imports this module at top level)."""
    from dingo_tpu.engine.raft_engine import region_install

    region_install(engine, region, dict(data.cfs))


def _apply_kv_put(engine: RawEngine, data: wd.KvPutData) -> None:
    batch = WriteBatch()
    flag = ValueFlag.PUT_TTL if data.ttl_ms else ValueFlag.PUT
    for key, value in data.kvs:
        batch.put(
            data.cf,
            Codec.encode_key(key, data.ts),
            Codec.package_value(value, flag, data.ttl_ms),
        )
    engine.write(batch)


def _apply_kv_delete(engine: RawEngine, data: wd.KvDeleteData) -> None:
    batch = WriteBatch()
    for key in data.keys:
        batch.put(
            data.cf,
            Codec.encode_key(key, data.ts),
            Codec.package_value(b"", ValueFlag.DELETE),
        )
    engine.write(batch)


def _apply_kv_delete_range(
    engine: RawEngine, data: wd.KvDeleteRangeData, want_result: bool
) -> Optional[dict]:
    """Range deletes drop whole encoded ranges (the reference issues RocksDB
    DeleteRange on the raw engine rather than writing per-key tombstones).

    The live-key count at apply time is what delete_count responses must
    report (a pre-propose scan races concurrent writes) — but it is NOT
    consensus state, so only a node with a waiting proposer pays for the
    scan (want_result); followers and log replay skip it. The scan runs
    inside the (per-region) apply loop, so it delays only this region's
    later applies — same serialization the reference's raft apply has.

    An empty end key means "to the end" (region with unbounded end_key):
    it must become an unbounded engine range, NOT an encoded b"" (which
    sorts below every real key and would delete nothing)."""
    deleted = 0
    if want_result:
        from dingo_tpu.mvcc.reader import Reader as MvccReader

        reader = MvccReader(engine, data.cf)
        for start, end in data.ranges:
            deleted += reader.kv_count(start, end, MAX_TS)
    batch = WriteBatch()
    for start, end in data.ranges:
        batch.delete_range(
            data.cf, Codec.encode_bytes(start),
            Codec.encode_bytes(end) if end else None,
        )
    engine.write(batch)
    return {"deleted": deleted} if want_result else None


def _apply_vector_add(
    engine: RawEngine, region: Region, data: wd.VectorAddData, log_id: int
) -> None:
    """VectorAddHandler (raft_apply_handler.cc:1115): write data CF + scalar
    CF (+ speed-up/table CFs when schemas exist), then update the index."""
    part = region.definition.partition_id
    param = region.definition.index_parameter
    speedup_keys = tuple(
        getattr(param, "scalar_speedup_keys", ()) or ()) if param else ()
    batch = WriteBatch()
    flag = ValueFlag.PUT_TTL if data.ttl_ms else ValueFlag.PUT
    for i, vid in enumerate(data.ids):
        key = vcodec.encode_vector_key(part, int(vid))
        ekey = Codec.encode_key(key, data.ts)
        batch.put(
            CF_DEFAULT,
            ekey,
            Codec.package_value(
                serialize_vector(data.vectors[i]), flag, data.ttl_ms
            ),
        )
        if data.scalars is not None:
            batch.put(
                CF_VECTOR_SCALAR,
                ekey,
                Codec.package_value(
                    serialize_scalar(data.scalars[i]), flag, data.ttl_ms
                ),
            )
            if speedup_keys:
                # SplitVectorScalarData (vector_index_utils.h, written at
                # raft_apply_handler.cc:1115): the flagged subset lands in
                # a narrow CF so covered pre-filter scans skip the wide
                # one. The narrow CF is a DERIVED view of the wide row, so
                # every wide write gets a narrow twin — a tombstone when
                # the upsert dropped all flagged fields, or the previous
                # narrow version would stay visible and covered filters
                # would diverge from the wide path.
                subset = {
                    k: data.scalars[i][k]
                    for k in speedup_keys if k in data.scalars[i]
                }
                if subset:
                    batch.put(
                        CF_VECTOR_SCALAR_SPEEDUP,
                        ekey,
                        Codec.package_value(
                            serialize_scalar(subset), flag, data.ttl_ms
                        ),
                    )
                else:
                    batch.put(
                        CF_VECTOR_SCALAR_SPEEDUP, ekey,
                        Codec.package_value(b"", ValueFlag.DELETE),
                    )
        if data.table_values is not None:
            # table rows are an independent attribute, per entry:
            # None = leave this vector's row untouched, b"" = clear it,
            # bytes = replace it
            tv = data.table_values[i]
            if tv:
                batch.put(
                    CF_VECTOR_TABLE,
                    ekey,
                    Codec.package_value(tv, flag, data.ttl_ms),
                )
            elif tv is not None:
                batch.put(
                    CF_VECTOR_TABLE, ekey,
                    Codec.package_value(b"", ValueFlag.DELETE),
                )
    engine.write(batch)

    wrapper = region.vector_index_wrapper
    if wrapper is not None and wrapper.is_ready():
        if data.is_update:
            wrapper.add(data.ids, data.vectors, log_id, is_upsert=True)
        else:
            wrapper.add(data.ids, data.vectors, log_id, is_upsert=False)


def _apply_vector_delete(
    engine: RawEngine, region: Region, data: wd.VectorDeleteData, log_id: int
) -> None:
    part = region.definition.partition_id
    batch = WriteBatch()
    for vid in data.ids:
        key = vcodec.encode_vector_key(part, int(vid))
        ekey = Codec.encode_key(key, data.ts)
        batch.put(CF_DEFAULT, ekey, Codec.package_value(b"", ValueFlag.DELETE))
        batch.put(
            CF_VECTOR_SCALAR, ekey, Codec.package_value(b"", ValueFlag.DELETE)
        )
        batch.put(
            CF_VECTOR_SCALAR_SPEEDUP, ekey,
            Codec.package_value(b"", ValueFlag.DELETE),
        )
        batch.put(
            CF_VECTOR_TABLE, ekey, Codec.package_value(b"", ValueFlag.DELETE)
        )
    engine.write(batch)
    wrapper = region.vector_index_wrapper
    if wrapper is not None and wrapper.is_ready():
        wrapper.delete(np.asarray(data.ids, np.int64), log_id)


def _apply_document_add(
    engine: RawEngine, region: Region, data: wd.DocumentAddData, log_id: int
) -> None:
    """DocumentAdd handler: persist docs (source of truth) then update the
    in-memory full-text index — same dual-write contract as vectors."""
    part = region.definition.partition_id
    batch = WriteBatch()
    for did, doc in zip(data.ids, data.documents):
        key = vcodec.encode_vector_key(part, int(did))
        batch.put(
            CF_DEFAULT,
            Codec.encode_key(key, data.ts),
            Codec.package_value(wire.encode_obj(doc)),
        )
    engine.write(batch)
    if region.document_index is not None and (
        log_id == 0 or log_id > region.document_index.apply_log_id
    ):
        for did, doc in zip(data.ids, data.documents):
            region.document_index.upsert(int(did), doc)
        if log_id:
            region.document_index.apply_log_id = log_id


def _apply_document_delete(
    engine: RawEngine, region: Region, data: wd.DocumentDeleteData, log_id: int
) -> None:
    part = region.definition.partition_id
    batch = WriteBatch()
    for did in data.ids:
        key = vcodec.encode_vector_key(part, int(did))
        batch.put(
            CF_DEFAULT,
            Codec.encode_key(key, data.ts),
            Codec.package_value(b"", ValueFlag.DELETE),
        )
    engine.write(batch)
    if region.document_index is not None and (
        log_id == 0 or log_id > region.document_index.apply_log_id
    ):
        region.document_index.delete([int(d) for d in data.ids])
        if log_id:
            region.document_index.apply_log_id = log_id


def _apply_txn(engine: RawEngine, data: wd.TxnRaftData) -> None:
    batch = WriteBatch()
    for cf, key, value in data.puts:
        batch.put(cf, key, value)
    for cf, key in data.deletes:
        batch.delete(cf, key)
    engine.write(batch)
