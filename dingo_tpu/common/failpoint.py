"""Failpoint: runtime fault injection.

Reference: src/common/failpoint.{h,cc} — named failpoints configured at
runtime (via DebugService) with actions panic/sleep/print/yield/delay
(failpoint.h:44-141), compiled in behind ENABLE_FAILPOINT. Here failpoints
are always available (no compile gate) and applied with `apply("name")` at
the instrumented site.

Config string format (reference-compatible spirit):
    "<percent>%<count>*<action>(<arg>)"
e.g. "100%10*sleep(50)" = always fire, first 10 times, sleep 50ms;
     "50%error(30001)"  = half the passes raise errcode 30001;
     "3*panic"          = panic the first 3 times, then off.

Actions:
    panic        — raise FailPointError
    error(code)  — raise FailPointInjectedError carrying an errcode (the
                   rpc layer converts it in-band like any application
                   error, so clients exercise their retry classification)
    sleep/delay(ms) — stall the instrumented site
    print(msg)   — log the pass
    yield        — yield the GIL (scheduling perturbation)

Determinism: the probabilistic roll uses one process-global seeded rng;
``FAILPOINTS.set_seed(s)`` re-arms it so a chaos scenario replays the
exact same fault schedule. ``scoped()`` installs a point for the dynamic
extent of a with-block (tests can't leak configured faults).

Every pass that FIRES bumps the curated ``fault.injected`` counter
(labels={"point": name}) so chaos gates can assert the fault actually
happened rather than trusting the schedule.
"""

from __future__ import annotations

import contextlib
import random
import re
import threading
import time
from typing import Dict, Optional


class FailPointError(RuntimeError):
    """Raised by the `panic` action."""


class FailPointInjectedError(FailPointError):
    """Raised by the `error(code)` action; carries an in-band errcode so
    the rpc layer and client retry classification see a typed failure."""

    def __init__(self, name: str, errcode: int):
        super().__init__(f"failpoint {name} injected error {errcode}")
        self.point = name
        self.errcode = errcode


class _FailPoint:
    def __init__(self, name: str, percent: int, count: int, action: str,
                 arg: str):
        self.name = name
        self.percent = percent
        self.count = count          # -1 = unlimited
        self.action = action
        self.arg = arg
        self.hits = 0


_CFG_RE = re.compile(
    r"^(?:(?P<pct>\d+)%)?(?:(?P<cnt>\d+)\*)?(?P<act>\w+)(?:\((?P<arg>[^)]*)\))?$"
)

_ACTIONS = ("panic", "error", "sleep", "delay", "print", "yield")


class FailPointManager:
    def __init__(self, seed: int = 0xFA11):
        self._lock = threading.Lock()
        self._points: Dict[str, _FailPoint] = {}
        self._rng = random.Random(seed)

    def set_seed(self, seed: int) -> None:
        """Re-arm the probabilistic roll for a deterministic replay."""
        with self._lock:
            self._rng = random.Random(seed)

    def configure(self, name: str, config: str) -> None:
        """e.g. configure("before_raft_commit", "50%3*sleep(100)")."""
        m = _CFG_RE.match(config.strip())
        if not m:
            raise ValueError(f"bad failpoint config {config!r}")
        if m.group("act") not in _ACTIONS:
            raise ValueError(
                f"unknown failpoint action {m.group('act')!r} "
                f"(want one of {_ACTIONS})"
            )
        point = _FailPoint(
            name,
            int(m.group("pct") or 100),
            int(m.group("cnt") or -1),
            m.group("act"),
            m.group("arg") or "",
        )
        with self._lock:
            self._points[name] = point

    def remove(self, name: str) -> None:
        with self._lock:
            self._points.pop(name, None)

    def clear(self) -> None:
        with self._lock:
            self._points.clear()

    def list(self) -> Dict[str, str]:
        with self._lock:
            return {
                n: f"{p.percent}%{p.count}*{p.action}({p.arg})"
                for n, p in self._points.items()
            }

    def hits(self, name: str) -> int:
        """Times the point FIRED (post-roll) — chaos gates assert on it."""
        with self._lock:
            p = self._points.get(name)
            return p.hits if p is not None else 0

    @contextlib.contextmanager
    def scoped(self, name: str, config: str):
        """Install a point for the extent of a with-block, restoring any
        previous config on exit (tests / chaos scenarios can't leak)."""
        with self._lock:
            prev = self._points.get(name)
        self.configure(name, config)
        try:
            yield self
        finally:
            with self._lock:
                if prev is not None:
                    self._points[name] = prev
                else:
                    self._points.pop(name, None)

    def apply(self, name: str) -> None:
        """Call at the instrumented site; may sleep/raise per config."""
        with self._lock:
            point = self._points.get(name)
            if point is None:
                return
            if point.count == 0:
                return
            if self._rng.random() * 100 >= point.percent:
                return
            if point.count > 0:
                point.count -= 1
            point.hits += 1
            action, arg = point.action, point.arg
        # lazy import: failpoint is reachable from early-import modules
        # (engine/storage) and must not force the metrics registry up
        from dingo_tpu.common.metrics import METRICS

        METRICS.counter("fault.injected", labels={"point": name}).add(1)
        if action == "panic":
            raise FailPointError(f"failpoint {name} panic")
        if action == "error":
            raise FailPointInjectedError(name, int(arg or 99999))
        if action == "sleep" or action == "delay":
            time.sleep(float(arg or 0) / 1000.0)
        elif action == "print":
            print(f"[failpoint] {name}: {arg}")
        elif action == "yield":
            time.sleep(0)


#: process-global manager (the reference's singleton)
FAILPOINTS = FailPointManager()


def failpoint(name: str) -> None:
    FAILPOINTS.apply(name)


def failpoint_scope(name: str, config: str):
    """Module-level sugar for ``FAILPOINTS.scoped`` (test idiom)."""
    return FAILPOINTS.scoped(name, config)
