"""Balance schedulers: leader-count and region-count balancing.

Reference: src/coordinator/balance_leader.{h,cc} + balance_region.{h,cc}
(~2.6K LoC) — periodic crontab schedulers that inspect the store/region maps
and emit transfer-leader / change-peer jobs. Filters (balance_leader.h:98-
123) skip unhealthy stores/regions; an inspection time window gates when
balancing may run (config_helper.h:46-48).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from dingo_tpu.coordinator.control import CoordinatorControl, StoreState


@dataclasses.dataclass
class TransferLeaderOp:
    region_id: int
    from_store: str
    to_store: str


@dataclasses.dataclass
class MoveRegionOp:
    region_id: int
    from_store: str
    to_store: str


@dataclasses.dataclass
class ScaleReplicaOp:
    """Grow/shrink a region's replica set for read scaling (the mesh
    serving tier's coordinator arm): peers to add ride first, drops later —
    raft membership changes stay one server at a time."""

    region_id: int
    current: int
    target: int
    add_stores: List[str]
    drop_stores: List[str]
    #: heartbeat evidence the sizing read (event-ledger snapshot): the
    #: region's leader QPS and the per-replica QPS target in force
    qps: float = 0.0
    target_qps: float = 0.0
    floor: int = 0


#: load-aware weight: one load unit per this many index bytes (memory is a
#: capacity signal alongside QPS — a cold 4GB leader still costs HBM)
LOAD_BYTES_PER_UNIT = 64 * 1024 * 1024
#: hysteresis floor: gaps under one load unit (1 QPS / 64MB) are noise —
#: acting on them would churn leadership for nothing (count mode's
#: `n_most <= n_least + 1` dead band, translated to load units)
MIN_LOAD_GAP = 1.0


def fresh_store_metrics(control: CoordinatorControl):
    """store_id -> snapshot for every ALIVE store with non-stale metrics
    (the one trust filter both the load balancer and the replica planner
    apply — staleness semantics must not diverge between schedulers)."""
    alive = {s.store_id for s in control.alive_stores()}
    rows = control.get_store_metrics()
    return {
        sid: snap for sid, snap, _at, stale in rows
        if not stale and sid in alive
    }


class BalanceLeaderScheduler:
    """Move leaders from the most-loaded store to the least-loaded one when
    the imbalance exceeds the ratio gate (BalanceLeaderScheduler).

    mode="count": load = leader tally (reference behavior).
    mode="load":  load = measured leader QPS + memory units from the
    store-metrics plane — two stores with EQUAL leader counts but skewed
    traffic rebalance under this mode where count mode sees no work.
    Falls back to count while metrics are missing or stale (a balancing
    decision on dead figures is worse than none)."""

    def __init__(self, control: CoordinatorControl, ratio_gate: float = 1.2,
                 mode: str = "count"):
        self.control = control
        self.ratio_gate = ratio_gate
        self.mode = mode

    # ---------------- load-aware helpers ----------------
    def _region_weights(self) -> Optional[Dict[str, Dict[int, float]]]:
        """store_id -> {led region_id -> weight}; None when any alive
        store lacks fresh metrics (fall back to count mode)."""
        alive = {s.store_id for s in self.control.alive_stores()}
        fresh = fresh_store_metrics(self.control)
        if alive - set(fresh):
            return None
        out: Dict[str, Dict[int, float]] = {}
        for sid, snap in fresh.items():
            out[sid] = {
                rm.region_id:
                    rm.search_qps
                    + (rm.vector_memory_bytes + rm.device_memory_bytes)
                    / LOAD_BYTES_PER_UNIT
                for rm in snap.regions if rm.is_leader
            }
        return out

    def plan(self) -> List[TransferLeaderOp]:
        if self.mode == "load":
            weights = self._region_weights()
            if weights is not None:
                return self._plan_load(weights)
        return self._plan_count()

    def _plan_count(self) -> List[TransferLeaderOp]:
        stores = self.control.alive_stores()
        if len(stores) < 2:
            return []
        by_leaders = sorted(stores, key=lambda s: len(s.leader_region_ids))
        least, most = by_leaders[0], by_leaders[-1]
        n_least = len(least.leader_region_ids)
        n_most = len(most.leader_region_ids)
        if n_most <= n_least + 1:
            return []
        if n_least > 0 and n_most / max(n_least, 1) < self.ratio_gate:
            return []
        ops = []
        movable = [
            rid for rid in most.leader_region_ids
            # target must already host a replica to receive leadership
            if least.store_id in
            (self.control.regions.get(rid).peers
             if self.control.regions.get(rid) else [])
        ]
        to_move = (n_most - n_least) // 2
        for rid in movable[:to_move]:
            ops.append(TransferLeaderOp(rid, most.store_id, least.store_id))
        return ops

    def _plan_load(self, weights: Dict[str, Dict[int, float]]
                   ) -> List[TransferLeaderOp]:
        stores = self.control.alive_stores()
        if len(stores) < 2:
            return []
        load = {
            s.store_id: sum(weights.get(s.store_id, {}).values())
            for s in stores
        }
        by_load = sorted(stores, key=lambda s: load[s.store_id])
        least, most = by_load[0], by_load[-1]
        l_least, l_most = load[least.store_id], load[most.store_id]
        gap = l_most - l_least
        if gap < MIN_LOAD_GAP:
            return []
        if l_least > 0 and l_most / l_least < self.ratio_gate:
            return []
        # move the heaviest movable leaders first, stopping once half the
        # gap shifts. Each move must STRICTLY shrink the gap (w < remaining
        # gap): with a single dominant leader, w == gap would mirror the
        # skew exactly and the next tick would move it straight back —
        # perpetual leadership ping-pong
        movable = sorted(
            (
                (w, rid) for rid, w in weights[most.store_id].items()
                if least.store_id in
                (self.control.regions.get(rid).peers
                 if self.control.regions.get(rid) else [])
            ),
            reverse=True,
        )
        ops: List[TransferLeaderOp] = []
        moved = 0.0
        for w, rid in movable:
            if moved >= gap / 2 or w >= gap - moved:
                continue
            ops.append(TransferLeaderOp(rid, most.store_id, least.store_id))
            moved += w
        return ops

    def dispatch(self) -> int:
        ops = self.plan()
        for op in ops:
            self.control.transfer_leader(op.region_id, op.to_store)
        return len(ops)


class ReplicaPlanScheduler:
    """Scale a region's read-replica count from its measured QPS
    (`balance.replica_mode = auto`): regions hotter than
    `balance.replica_qps_target` per replica gain replicas on the
    least-loaded stores; regions that cooled back down drop follower
    replicas from the most-loaded stores — never below the cluster's
    configured raft replication (the base peers are quorum, not elastic
    read capacity). The store-side mechanism is
    parallel/replica_group.py (device slices) or extra raft followers
    serving follower reads — this tier only decides COUNT and PLACEMENT
    from the heartbeat metrics plane, like the reference's region
    scheduler family."""

    def __init__(self, control: CoordinatorControl,
                 mode: Optional[str] = None,
                 qps_target: Optional[float] = None,
                 max_replicas: int = 5):
        self.control = control
        self._mode = mode
        self._qps_target = qps_target
        self.max_replicas = max_replicas

    def _flag(self, name: str, override):
        if override is not None:
            return override
        from dingo_tpu.common.config import FLAGS

        return FLAGS.get(name)

    def plan(self) -> List[ScaleReplicaOp]:
        mode = self._flag("balance_replica_mode", self._mode)
        if mode != "auto":
            return []
        target_qps = float(
            self._flag("balance_replica_qps_target", self._qps_target)
        )
        fresh = fresh_store_metrics(self.control)
        if not fresh:
            return []    # planning replicas on dead figures is worse than none
        # store load (for placement) + per-region leader QPS (for sizing)
        store_load = {
            sid: sum(
                rm.search_qps
                + (rm.vector_memory_bytes + rm.device_memory_bytes)
                / LOAD_BYTES_PER_UNIT
                for rm in snap.regions
            )
            for sid, snap in fresh.items()
        }
        region_qps = {}
        for sid, snap in fresh.items():
            for rm in snap.regions:
                if rm.is_leader:
                    region_qps[rm.region_id] = rm.search_qps
        # NEVER shrink below the cluster's configured raft replication:
        # the base peers are write durability / quorum, only replicas the
        # planner ADDED beyond that are elastic read capacity. (Without
        # this floor every quiet region would erode to a single peer.)
        floor = max(1, int(getattr(self.control, "replication", 1) or 1))
        ops: List[ScaleReplicaOp] = []
        for rid, qps in sorted(region_qps.items()):
            definition = self.control.regions.get(rid)
            if definition is None:
                continue
            peers = list(definition.peers)
            current = len(peers)
            want = max(1, -(-int(qps) // max(1, int(target_qps))))
            target = min(max(want, floor), max(self.max_replicas, floor))
            # hysteresis: one-step moves only, and never below the raft
            # quorum floor the region was created with is the control
            # plane's concern — this planner only adds/removes ONE peer
            # per tick so a QPS spike can't thrash membership
            if target > current:
                cand = sorted(
                    (s for s in store_load if s not in peers),
                    key=lambda s: store_load[s],
                )
                if not cand:
                    continue
                ops.append(ScaleReplicaOp(
                    rid, current, current + 1, [cand[0]], [],
                    qps=float(qps), target_qps=target_qps, floor=floor,
                ))
            elif target < current and current > floor:
                leader = next(
                    (s.store_id for s in self.control.alive_stores()
                     if rid in s.leader_region_ids), None
                )
                followers = [s for s in peers if s != leader]
                if not followers:
                    continue
                drop = max(
                    followers, key=lambda s: store_load.get(s, 0.0)
                )
                ops.append(ScaleReplicaOp(
                    rid, current, current - 1, [], [drop],
                    qps=float(qps), target_qps=target_qps, floor=floor,
                ))
        return ops

    def dispatch(self) -> int:
        from dingo_tpu.obs.events import EVENTS

        ops = self.plan()
        for op in ops:
            peers = list(self.control.regions[op.region_id].peers)
            for s in op.add_stores:
                peers = peers + [s]
                self.control.change_peer(op.region_id, peers)
            for s in op.drop_stores:
                peers = [p for p in peers if p != s]
                self.control.change_peer(op.region_id, peers)
            EVENTS.emit(
                "planner", op.region_id, "replicas", op.current, op.target,
                trigger="scale",
                evidence={
                    "qps": round(op.qps, 3),
                    "target_qps": op.target_qps,
                    "floor": op.floor,
                    "add": list(op.add_stores),
                    "drop": list(op.drop_stores),
                },
            )
        return len(ops)


class BalanceRegionScheduler:
    """Move replicas from crowded stores to empty ones (BalanceRegion)."""

    def __init__(self, control: CoordinatorControl, ratio_gate: float = 1.3):
        self.control = control
        self.ratio_gate = ratio_gate

    def plan(self) -> List[MoveRegionOp]:
        stores = self.control.alive_stores()
        if len(stores) < 2:
            return []
        by_regions = sorted(stores, key=lambda s: len(s.region_ids))
        least, most = by_regions[0], by_regions[-1]
        n_least, n_most = len(least.region_ids), len(most.region_ids)
        if n_most <= n_least + 1:
            return []
        if n_least > 0 and n_most / max(n_least, 1) < self.ratio_gate:
            return []
        ops = []
        for rid in most.region_ids:
            definition = self.control.regions.get(rid)
            if definition is None or least.store_id in definition.peers:
                continue
            ops.append(MoveRegionOp(rid, most.store_id, least.store_id))
            if len(ops) >= (n_most - n_least) // 2:
                break
        return ops

    def dispatch(self) -> int:
        ops = self.plan()
        for op in ops:
            definition = self.control.regions[op.region_id]
            # Two-phase: add the new peer, then remove the old one — raft
            # single-step membership changes stay safe only one server at a
            # time (simultaneous add+remove can elect two leaders).
            self.control.change_peer(
                op.region_id, definition.peers + [op.to_store]
            )
            self.control.change_peer(
                op.region_id,
                [p for p in self.control.regions[op.region_id].peers
                 if p != op.from_store],
            )
        return len(ops)
