"""Module-tagged logging + runtime log-level RPC (reference
src/common/logging.h glog wrappers; NodeService log-level RPC)."""

import json
import logging
import time

import pytest

from dingo_tpu.common import log as dlog


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)

    def lines(self):
        fmt = dlog._TagFormatter()
        return [fmt.format(r) for r in self.records]


@pytest.fixture()
def capture():
    h = _Capture()
    root = logging.getLogger("dingo")
    prior = root.level
    root.addHandler(h)
    yield h
    root.removeHandler(h)
    root.setLevel(prior)


def test_module_and_region_tags(capture):
    dlog.set_level("DEBUG")
    log = dlog.get_logger("raft.apply")
    log.info("plain event")
    dlog.region_log(log, 42).warning("regional event %d", 7)
    lines = capture.lines()
    assert any("[raft.apply] plain event" in ln for ln in lines)
    assert any("[raft.apply][region(42)] regional event 7" in ln
               for ln in lines)


def test_subtree_level_control(capture):
    dlog.set_level("WARNING")               # whole tree quiet
    dlog.set_level("DEBUG", module="raft")  # one subtree loud
    dlog.get_logger("raft.core").debug("raft debug")
    dlog.get_logger("index.manager").debug("index debug")
    dlog.get_logger("index.manager").error("index error")
    lines = capture.lines()
    assert any("raft debug" in ln for ln in lines)
    assert not any("index debug" in ln for ln in lines)
    assert any("index error" in ln for ln in lines)
    with pytest.raises(ValueError):
        dlog.set_level("LOUD")


def test_cluster_emits_tagged_logs_and_rpc_flips_level(capture):
    """A live cluster emits module-tagged logs during region lifecycle,
    and the NodeService RPC flips verbosity at runtime."""
    from dingo_tpu.client.client import DingoClient
    from dingo_tpu.coordinator.control import CoordinatorControl
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.raft import LocalTransport
    from dingo_tpu.server import pb
    from dingo_tpu.server.rpc import DingoServer
    from dingo_tpu.store.node import StoreNode

    dlog.set_level("INFO")
    transport = LocalTransport()
    me = MemEngine()
    control = CoordinatorControl(me, replication=3)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    nodes, servers, addrs = {}, [], {}
    for i, sid in enumerate(["s0", "s1", "s2"]):
        n = StoreNode(sid, transport, control, raft_kw={"seed": i})
        srv = DingoServer()
        srv.host_store_role(n)
        port = srv.start()
        n.start_heartbeat(0.1)
        nodes[sid] = n
        servers.append(srv)
        addrs[sid] = f"127.0.0.1:{port}"
    client = DingoClient(f"127.0.0.1:{cport}", addrs)
    try:
        param = pb.VectorIndexParameter(
            index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=8,
            metric_type=pb.METRIC_TYPE_L2,
        )
        client.create_index_region(0, 0, 1 << 40, param)
        time.sleep(1.2)
        lines = capture.lines()
        # coordinator logged the create; raft logged an election
        assert any("[coordinator.control][region(" in ln and "create" in ln
                   for ln in lines), lines[:10]
        assert any("[raft.core]" in ln and "became leader" in ln
                   for ln in lines)

        # runtime flip over the RPC: DEBUG exposes store cmd dispatch
        stub = client._stub("s0", "NodeService")
        r = stub.SetLogLevel(pb.SetLogLevelRequest(level="DEBUG"))
        assert r.error.errcode == 0
        levels = stub.GetLogLevel(pb.GetLogLevelRequest())
        got = {e.module: e.level for e in levels.levels}
        assert got["dingo"] == "DEBUG"
        # bad level is rejected in-band
        r = stub.SetLogLevel(pb.SetLogLevelRequest(level="LOUD"))
        assert r.error.errcode == 90003

        capture.records.clear()
        client.create_index_region(1, 0, 1 << 40, param)
        time.sleep(1.2)
        assert any("executing cmd" in ln for ln in capture.lines()), (
            "DEBUG level did not expose store cmd dispatch")
    finally:
        client.close()
        for s in servers:
            s.stop()
        cs.stop()
        for n in nodes.values():
            n.stop()
        dlog.set_level("WARNING")
