"""Region replica groups: R full index replicas on disjoint device slices.

The reference scales reads by placing multi-Raft region replicas across
Store/Index nodes and routing follower reads at them (PAPER.md layer map).
On one mesh host the analog is a ReplicaGroup: the factory carves the
device set into R disjoint slices, builds one complete mesh-sharded index
per slice, and routes each search at exactly one replica — writes fan out
to every member so replicas stay bit-identical. Two knobs compose:

  FLAGS.mesh_batch_axis — SPMD read scaling: ONE program whose query
      batch splits over a "batch" mesh axis (collectives stitch the
      result). Best when requests arrive pre-coalesced into big batches.
  FLAGS.mesh_replicas  — MPMD read scaling (this module): independent
      programs on disjoint devices, routed per request. Best when many
      small batches arrive concurrently — no cross-replica collective,
      no shared program, a wedged replica only hurts its slice.

The coordinator's replica planner (coordinator/balance.py,
`balance.replica_mode = auto`) chooses R per region from measured QPS via
the heartbeat metrics plane; this module is the store-side mechanism.

Observability: per-replica search counters / in-flight gauges / latency
series under `mesh.replica.*` (the latency series carries the windowed
per-replica QPS the planner and `cluster top` read).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    IndexType,
    InvalidParameter,
    SearchResult,
    VectorIndex,
)


def _default_member_builder(index_id: int, parameter: IndexParameter,
                            devices: Sequence) -> VectorIndex:
    """One mesh-sharded replica on an explicit device slice. The batch
    (and, for FLAT, dim) mesh axes COMPOSE with replication: each member
    carves its slice into batch x data (x dim) per the serving flags —
    indivisible combinations fail loudly instead of silently dropping an
    axis the operator configured."""
    from dingo_tpu.common.config import FLAGS
    from dingo_tpu.parallel.sharded_store import make_mesh

    n = len(devices)
    batch = int(FLAGS.get("mesh_batch_axis") or 1)
    dim = (int(FLAGS.get("mesh_dim_axis") or 1)
           if parameter.index_type is IndexType.FLAT else 1)
    if n % (batch * dim):
        raise InvalidParameter(
            f"replica slice of {n} devices does not divide by "
            f"mesh_batch_axis={batch} x mesh_dim_axis={dim}"
        )
    mesh = make_mesh(
        devices=devices, batch=batch, dim=dim, data=n // (batch * dim)
    )
    if parameter.index_type is IndexType.FLAT:
        from dingo_tpu.parallel.sharded_flat import TpuShardedFlat

        return TpuShardedFlat(index_id, parameter, mesh=mesh)
    if parameter.index_type is IndexType.IVF_FLAT:
        from dingo_tpu.parallel.sharded_ivf import TpuShardedIvfFlat

        return TpuShardedIvfFlat(index_id, parameter, mesh=mesh)
    if parameter.index_type is IndexType.IVF_PQ:
        from dingo_tpu.parallel.sharded_pq import TpuShardedIvfPq

        return TpuShardedIvfPq(index_id, parameter, mesh=mesh)
    raise InvalidParameter(
        f"replica groups support mesh-sharded FLAT/IVF_FLAT/IVF_PQ, "
        f"not {parameter.index_type}"
    )


#: full resident-id-set digest comparison runs every Nth write fan-out
#: (the O(1) count comparison runs on EVERY fan-out and forces a full
#: check on disagreement) — bounds the O(live ids) scan off the per-write
#: path at scale while keeping detection latency a handful of batches
REPLICA_CHECK_EVERY = 16


def _member_live_ids(member) -> Optional[np.ndarray]:
    """Resident external ids of one replica member (mesh-sharded indexes
    keep ids_by_gslot; slot-store indexes keep store.ids_by_slot); None
    for members with no inspectable id surface."""
    ids = getattr(member, "ids_by_gslot", None)
    if ids is None:
        store = getattr(member, "store", None)
        ids = getattr(store, "ids_by_slot", None)
    if ids is None:
        return None
    ids = np.asarray(ids, np.int64)
    return ids[ids >= 0]


class ReplicaGroup(VectorIndex):
    """R replicas of one region's index; reads route, writes fan out."""

    def __init__(self, index_id: int, parameter: IndexParameter,
                 replicas: int = 2,
                 devices: Optional[Sequence] = None,
                 member_builder: Optional[Callable] = None):
        super().__init__(index_id, parameter)
        if replicas < 1:
            raise InvalidParameter(f"replicas {replicas} < 1")
        if devices is None:
            import jax

            devices = jax.devices()
        if len(devices) % replicas:
            raise InvalidParameter(
                f"{len(devices)} devices not divisible by "
                f"{replicas} replicas"
            )
        per = len(devices) // replicas
        build = member_builder or _default_member_builder
        self.members: List[VectorIndex] = [
            build(index_id, parameter, devices[r * per:(r + 1) * per])
            for r in range(replicas)
        ]
        self._rr = 0
        self._inflight = [0] * replicas
        self._lock = threading.Lock()
        self._writes_since_check = 0
        from dingo_tpu.common.metrics import METRICS

        METRICS.gauge("mesh.replicas", region_id=index_id).set(
            float(replicas)
        )

    @property
    def replicas(self) -> int:
        return len(self.members)

    # -- routing -------------------------------------------------------------
    def _route(self) -> int:
        """Pick a replica: 'rr' round-robin, or 'load' = fewest searches
        currently in flight (a replica stuck on a slow scan stops
        receiving until it drains)."""
        from dingo_tpu.common.config import FLAGS

        with self._lock:
            if FLAGS.get("mesh_replica_route") == "load":
                r = int(np.argmin(self._inflight))
            else:
                r = self._rr % len(self.members)
                self._rr += 1
            self._inflight[r] += 1
            return r

    def _begin(self, r: int):
        from dingo_tpu.common.metrics import METRICS

        METRICS.counter("mesh.replica.searches", region_id=self.id,
                        labels={"replica": str(r)}).add(1)
        METRICS.gauge("mesh.replica.inflight", region_id=self.id,
                      labels={"replica": str(r)}).set(
            float(self._inflight[r])
        )
        return time.perf_counter()

    def _finish(self, r: int, t0: float) -> None:
        from dingo_tpu.common.metrics import METRICS

        with self._lock:
            self._inflight[r] -= 1
            inflight = self._inflight[r]
        METRICS.latency("mesh.replica.search_ms", region_id=self.id,
                        labels={"replica": str(r)}).observe_us(
            (time.perf_counter() - t0) * 1e6
        )
        METRICS.gauge("mesh.replica.inflight", region_id=self.id,
                      labels={"replica": str(r)}).set(float(inflight))

    # -- queries -------------------------------------------------------------
    def search_async(self, queries, topk,
                     filter_spec: Optional[FilterSpec] = None, **kw):
        r = self._route()
        t0 = self._begin(r)
        member = self.members[r]
        try:
            if hasattr(member, "search_async"):
                inner = member.search_async(
                    queries, topk, filter_spec, **kw
                )
            else:
                res = member.search(queries, topk, filter_spec, **kw)
                inner = lambda: res  # noqa: E731
        except BaseException:
            self._finish(r, t0)
            raise

        def resolve() -> List[SearchResult]:
            try:
                return inner()
            finally:
                self._finish(r, t0)

        return resolve

    def search(self, queries, topk,
               filter_spec: Optional[FilterSpec] = None, **kw):
        return self.search_async(queries, topk, filter_spec, **kw)()

    # -- mutation: fan out so replicas stay identical ------------------------
    def add(self, ids, vectors) -> None:
        for m in self.members:
            m.add(ids, vectors)
        self.verify_fanout()

    def upsert(self, ids, vectors) -> None:
        for m in self.members:
            m.upsert(ids, vectors)
        self.verify_fanout()

    def delete(self, ids):
        out = [m.delete(ids) for m in self.members][0]
        self.verify_fanout()
        return out

    # -- post-fanout bit-identity monitor (state-integrity plane) ------------
    def verify_fanout(self, force: bool = False) -> bool:
        """The write fan-out's replicas-stay-identical claim, MONITORED:
        compare member counts after every fan-out (O(1)) and the full
        resident-id-set digests every REPLICA_CHECK_EVERY batches (or on
        any count disagreement / force). A mismatch raises
        consistency.replica_mismatch and captures a flight bundle with
        every member's digest — a member that dropped a write (partial
        failure, a donation bug) surfaces within a handful of batches
        instead of as silently route-dependent results."""
        from dingo_tpu.obs.integrity import INTEGRITY

        if len(self.members) < 2 or not INTEGRITY.enabled():
            return True
        counts = [m.get_count() for m in self.members]
        count_mismatch = len(set(counts)) > 1
        with self._lock:
            self._writes_since_check += 1
            due = (force or count_mismatch
                   or self._writes_since_check >= REPLICA_CHECK_EVERY)
            if due:
                self._writes_since_check = 0
        if not due:
            return True
        from dingo_tpu.ops.digest import SetDigest, row_fingerprints

        digs = []
        for m in self.members:
            ids = _member_live_ids(m)
            if ids is None:
                return True       # opaque member: nothing comparable
            digs.append(
                SetDigest.of(
                    row_fingerprints("replica_ids", ids, ids)
                ).hex()
            )
        if len(set(digs)) <= 1 and not count_mismatch:
            return True
        from dingo_tpu.common.metrics import METRICS

        METRICS.counter(
            "consistency.replica_mismatch", region_id=self.id
        ).add(1)
        from dingo_tpu.obs.flight import FLIGHT

        FLIGHT.trigger(
            "divergence",
            name=f"replica_group_{self.id}",
            region_id=self.id,
            extra={"counts": counts,
                   "digests": {str(r): d for r, d in enumerate(digs)}},
        )
        return False

    def need_train(self) -> bool:
        return self.members[0].need_train()

    def is_trained(self) -> bool:
        return all(m.is_trained() for m in self.members)

    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        """Fan out; members train deterministically (seed = index id over
        identical rows), so replicas end with the same model state and
        answer identically."""
        for m in self.members:
            m.train(vectors) if vectors is not None else m.train()

    def reserve(self, n: int) -> None:
        for m in self.members:
            if hasattr(m, "reserve"):
                m.reserve(n)

    # -- lifecycle -----------------------------------------------------------
    def save(self, path: str) -> None:
        # replicas are write-identical; one copy on disk is the snapshot
        self.members[0].save(path)

    def load(self, path: str) -> None:
        for m in self.members:
            m.load(path)

    def get_count(self) -> int:
        return self.members[0].get_count()

    def get_memory_size(self) -> int:
        # the real footprint: every replica holds a full copy
        return sum(m.get_memory_size() for m in self.members)

    def replica_stats(self) -> List[dict]:
        from dingo_tpu.common.metrics import METRICS

        out = []
        for r in range(len(self.members)):
            lat = METRICS.latency(
                "mesh.replica.search_ms", region_id=self.id,
                labels={"replica": str(r)},
            ).stats()
            out.append({
                "replica": r,
                "searches": METRICS.counter(
                    "mesh.replica.searches", region_id=self.id,
                    labels={"replica": str(r)},
                ).get(),
                "inflight": self._inflight[r],
                "qps": lat.get("qps", 0.0),
            })
        return out
