"""TPU kernel layer: pure JAX/XLA/Pallas numerics with no framework deps.

Replaces the reference's SIMD hook surface (src/simd/hook.h:23-31 —
fvec_L2sqr / fvec_inner_product / fvec_norm_L2sqr / ... with runtime
AVX512/AVX2/SSE dispatch) and the faiss compute kernels behind the
VectorIndex hierarchy. Everything here is batched and jit-friendly:
distance computation is an MXU matmul, k-selection is lax.top_k, binary
(hamming) distance is a ±1 matmul, IVF/PQ training is on-device k-means.
"""

from dingo_tpu.ops.distance import (  # noqa: F401
    Metric,
    pairwise_l2sqr,
    pairwise_inner_product,
    pairwise_cosine,
    pairwise_hamming,
    score_matrix,
    scores_to_distances,
    squared_norms,
)
from dingo_tpu.ops.topk import topk_scores, merge_topk  # noqa: F401
