"""Storage facade: role-agnostic entry points over the engines.

Reference: src/engine/storage.{h,cc} (storage.h:33) — stateless dispatch that
picks the engine (raft vs mono, GetStoreEngine storage.cc:65), stamps TSO
timestamps (ts_provider_->GetTs(), storage.cc:460), validates requests, and
exposes KvGet/KvPut/VectorAdd (storage.cc:458)/VectorBatchSearch
(storage.cc:577)/Txn* to the RPC services.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dingo_tpu.engine import write_data as wd
from dingo_tpu.engine.raw_engine import CF_DEFAULT
from dingo_tpu.index.base import InvalidParameter
from dingo_tpu.index.vector_reader import VectorWithData
from dingo_tpu.mvcc.codec import MAX_TS
from dingo_tpu.mvcc.reader import Reader as MvccReader
from dingo_tpu.mvcc.ts_provider import TsProvider
from dingo_tpu.store.region import Region

#: FLAGS_vector_max_batch_count (index_service.cc:50)
VECTOR_MAX_BATCH_COUNT = 4096
#: FLAGS_vector_max_request_size (index_service.cc:51)
VECTOR_MAX_REQUEST_SIZE = 32 * 1024 * 1024
#: topN * batch guard (index_service.cc:206)
MAX_TOPN_BATCH_PRODUCT = 10 * VECTOR_MAX_BATCH_COUNT


class Storage:
    def __init__(self, engine, ts_provider: Optional[TsProvider] = None):
        """engine: MonoStoreEngine or RaftStoreEngine (same surface)."""
        import threading

        self.engine = engine
        self.ts_provider = ts_provider or TsProvider()
        self._locks_guard = threading.Lock()
        self._region_locks: Dict[int, Any] = {}

    def _region_lock(self, region: Region):
        """Serializes read-check-write primitives per region (the reference
        uses Latches/ConcurrencyManager for the same job, latch.h:27-95)."""
        import threading

        with self._locks_guard:
            lock = self._region_locks.get(region.id)
            if lock is None:
                lock = self._region_locks[region.id] = threading.Lock()
            return lock

    # ---------------- KV ----------------------------------------------------

    def kv_get(self, region: Region, key: bytes,
               read_ts: int = MAX_TS) -> Optional[bytes]:
        return MvccReader(self.engine.raw, CF_DEFAULT).kv_get(key, read_ts)

    def kv_batch_get(self, region: Region, keys: Sequence[bytes],
                     read_ts: int = MAX_TS) -> List[Optional[bytes]]:
        reader = MvccReader(self.engine.raw, CF_DEFAULT)
        return [reader.kv_get(k, read_ts) for k in keys]

    def kv_put(self, region: Region, kvs: Sequence[Tuple[bytes, bytes]],
               ttl_ms: int = 0) -> int:
        ts = self.ts_provider.get_ts()
        self.engine.write(
            region, wd.KvPutData(cf=CF_DEFAULT, ts=ts, kvs=list(kvs),
                                 ttl_ms=ttl_ms)
        )
        return ts

    def kv_put_if_absent(
        self, region: Region, kvs: Sequence[Tuple[bytes, bytes]],
        is_atomic: bool = False,
    ) -> List[bool]:
        """KvPutIfAbsent semantics: per-key success flags. is_atomic: all
        keys must be absent or nothing is written (store_service.cc
        KvBatchPutIfAbsent atomic arm)."""
        reader = MvccReader(self.engine.raw, CF_DEFAULT)
        with self._region_lock(region):
            ts = self.ts_provider.get_ts()
            wins, results = [], []
            for k, v in kvs:
                if reader.kv_get(k, MAX_TS) is None:
                    wins.append((k, v))
                    results.append(True)
                else:
                    results.append(False)
            if is_atomic and not all(results):
                return [False] * len(results)
            if wins:
                self.engine.write(
                    region, wd.KvPutData(cf=CF_DEFAULT, ts=ts, kvs=wins)
                )
            return results

    def kv_compare_and_set(
        self, region: Region, key: bytes, expect: Optional[bytes], value: bytes
    ) -> bool:
        reader = MvccReader(self.engine.raw, CF_DEFAULT)
        with self._region_lock(region):
            cur = reader.kv_get(key, MAX_TS)
            if cur != expect:
                return False
            ts = self.ts_provider.get_ts()
            self.engine.write(
                region, wd.KvPutData(cf=CF_DEFAULT, ts=ts, kvs=[(key, value)])
            )
            return True

    def kv_batch_delete(self, region: Region, keys: Sequence[bytes]) -> int:
        ts = self.ts_provider.get_ts()
        self.engine.write(
            region, wd.KvDeleteData(cf=CF_DEFAULT, ts=ts, keys=list(keys))
        )
        return ts

    def kv_delete_range(
        self, region: Region, ranges: Sequence[Tuple[bytes, bytes]]
    ) -> int:
        """Returns the number of live keys the APPLIED write removed (the
        apply handler counts them; a pre-propose scan would race concurrent
        writes)."""
        ts = self.ts_provider.get_ts()
        log_id = self.engine.write(
            region,
            wd.KvDeleteRangeData(cf=CF_DEFAULT, ts=ts, ranges=list(ranges)),
        )
        result = self.engine.take_apply_result(region.id, log_id)
        return int(result["deleted"]) if result else 0

    def kv_scan(
        self,
        region: Region,
        start: bytes,
        end: bytes,
        limit: int = 0,
        read_ts: int = MAX_TS,
        keys_only: bool = False,
    ) -> List[Tuple[bytes, bytes]]:
        return MvccReader(self.engine.raw, CF_DEFAULT).kv_scan(
            start, end, read_ts, limit, keys_only
        )

    # ---------------- vector -------------------------------------------------

    def _validate_vector_batch(self, region: Region, ids, vectors) -> None:
        if len(ids) != len(vectors):
            raise InvalidParameter("ids/vectors length mismatch")
        if len(ids) > VECTOR_MAX_BATCH_COUNT:
            raise InvalidParameter(
                f"batch {len(ids)} > {VECTOR_MAX_BATCH_COUNT}"
            )
        if vectors.nbytes > VECTOR_MAX_REQUEST_SIZE:
            raise InvalidParameter("request exceeds 32MiB")
        param = region.definition.index_parameter
        from dingo_tpu.index.vector_reader import is_binary_dim_param

        want = None
        if param:
            want = param.dimension // 8 if is_binary_dim_param(param)                 else param.dimension
        if want is not None and vectors.shape[1] != want:
            raise InvalidParameter(
                f"row width {vectors.shape[1]} != {want}"
            )
        lo, hi = region.id_window()
        ids = np.asarray(ids, np.int64)
        if ((ids < lo) | (ids >= hi)).any():
            raise InvalidParameter("vector id out of region range")

    def vector_add(
        self,
        region: Region,
        ids: np.ndarray,
        vectors: np.ndarray,
        scalars: Optional[List[Dict[str, Any]]] = None,
        is_update: bool = True,
        ttl_ms: int = 0,
        table_values: Optional[List[bytes]] = None,
    ) -> int:
        """Storage::VectorAdd (storage.cc:458-482): stamp TSO ts, build write
        payload, hand to the engine (raft propose or mono apply)."""
        from dingo_tpu.common.failpoint import failpoint

        failpoint("before_vector_add")
        from dingo_tpu.index.vector_reader import is_binary_dim_param

        if is_binary_dim_param(region.definition.index_parameter):
            vectors = np.asarray(vectors, np.uint8)
        else:
            vectors = np.asarray(vectors, np.float32)
        ids = np.asarray(ids, np.int64)
        self._validate_vector_batch(region, ids, vectors)
        ts = self.ts_provider.get_ts()
        self.engine.write(
            region,
            wd.VectorAddData(
                ts=ts, ids=ids, vectors=vectors, scalars=scalars,
                is_update=is_update, ttl_ms=ttl_ms,
                table_values=table_values,
            ),
        )
        return ts

    def vector_delete(self, region: Region, ids: Sequence[int]) -> int:
        ts = self.ts_provider.get_ts()
        self.engine.write(
            region,
            wd.VectorDeleteData(ts=ts, ids=np.asarray(ids, np.int64)),
        )
        return ts

    def vector_batch_search(
        self, region: Region, queries: np.ndarray, topk: int, **kw
    ) -> List[List[VectorWithData]]:
        """Storage::VectorBatchSearch (storage.cc:577)."""
        from dingo_tpu.index.vector_reader import is_binary_dim_param

        qdtype = (
            np.uint8
            if is_binary_dim_param(region.definition.index_parameter)
            else np.float32
        )
        queries = np.asarray(queries, qdtype)
        if queries.ndim == 1:
            queries = queries[None, :]
        if len(queries) > VECTOR_MAX_BATCH_COUNT:
            raise InvalidParameter("too many queries")
        if topk * len(queries) > MAX_TOPN_BATCH_PRODUCT:
            raise InvalidParameter(
                "topN * batch exceeds guard (index_service.cc:206)"
            )
        reader = self.engine.new_vector_reader(region)
        return reader.vector_batch_search(queries, topk, **kw)

    def vector_batch_search_async(
        self, region: Region, queries: np.ndarray, topk: int, **kw
    ):
        """Dispatch-now/resolve-later arm of vector_batch_search (serving
        pipeline): same guards, returns the reader's resolve thunk."""
        from dingo_tpu.index.vector_reader import is_binary_dim_param

        qdtype = (
            np.uint8
            if is_binary_dim_param(region.definition.index_parameter)
            else np.float32
        )
        queries = np.asarray(queries, qdtype)
        if queries.ndim == 1:
            queries = queries[None, :]
        if len(queries) > VECTOR_MAX_BATCH_COUNT:
            raise InvalidParameter("too many queries")
        if topk * len(queries) > MAX_TOPN_BATCH_PRODUCT:
            raise InvalidParameter(
                "topN * batch exceeds guard (index_service.cc:206)"
            )
        reader = self.engine.new_vector_reader(region)
        return reader.vector_batch_search_async(queries, topk, **kw)

    def vector_batch_query(self, region: Region, ids: Sequence[int], **kw):
        return self.engine.new_vector_reader(region).vector_batch_query(ids, **kw)

    def vector_get_border_id(self, region: Region, get_min: bool):
        return self.engine.new_vector_reader(region).vector_get_border_id(get_min)

    def vector_scan_query(self, region: Region, **kw):
        return self.engine.new_vector_reader(region).vector_scan_query(**kw)

    def vector_count(self, region: Region) -> int:
        return self.engine.new_vector_reader(region).vector_count()
