"""Mesh sharding / collectives: the TPU-native distribution layer.

The reference scales one logical dataset beyond a node via region split +
client-side scatter-gather (SURVEY.md §5 'long-context' note) and
parallelizes within a node via ThreadPools (vector_index.h:157-196
*ByParallel). The TPU equivalents here:

  sharded_store.py — one region's vectors sharded across a jax Mesh
                     (row-sharded data parallel), per-device top-k +
                     all-gather + merge in one shard_map program; optional
                     "batch" mesh axis splits the query batch across
                     replicas of the row shards (SPMD read scaling).
  sharded_train.py — k-means training over the mesh (psum-reduced
                     assignment statistics).
  replica_group.py — R full index replicas on disjoint device slices with
                     per-request routing (MPMD read scaling), the
                     store-side mechanism behind the coordinator's
                     replica planner.
"""

from dingo_tpu.parallel.sharded_store import ShardedFlatStore  # noqa: F401
