"""End-to-end grpc: coordinator server + 3 store servers + client SDK —
the full reference topology (client -> brpc -> services -> storage) in one
process over real sockets."""

import json
import time

import numpy as np
import pytest

from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coordinator.kv_control import KvControl
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.client import DingoClient
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server import pb
from dingo_tpu.server.rpc import DingoServer
from dingo_tpu.store.node import StoreNode


@pytest.fixture(scope="module")
def cluster():
    transport = LocalTransport()
    meta_engine = MemEngine()
    control = CoordinatorControl(meta_engine, replication=3)
    tso = TsoControl(meta_engine)
    kv_control = KvControl(meta_engine)

    coord_server = DingoServer()
    coord_server.host_coordinator_role(control, tso, kv_control)
    coord_port = coord_server.start()

    nodes, servers, addrs = {}, [], {}
    for i, sid in enumerate(["s0", "s1", "s2"]):
        node = StoreNode(sid, transport, control, raft_kw={"seed": i})
        server = DingoServer()
        server.host_store_role(node)
        port = server.start()
        node.start_heartbeat(0.1)
        nodes[sid] = node
        servers.append(server)
        addrs[sid] = f"127.0.0.1:{port}"

    client = DingoClient(f"127.0.0.1:{coord_port}", addrs)
    yield client, control, nodes
    client.close()
    for s in servers:
        s.stop()
    coord_server.stop()
    for n in nodes.values():
        n.stop()


def test_hello_and_region_lifecycle(cluster):
    client, control, nodes = cluster
    resp = client.coordinator.Hello(pb.HelloRequest())
    assert resp.store_count == 3

    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=16,
        metric_type=pb.METRIC_TYPE_L2,
    )
    definition = client.create_index_region(0, 0, 1 << 40, param)
    time.sleep(1.0)  # heartbeats create + elect

    rng = np.random.default_rng(0)
    x = rng.standard_normal((200, 16)).astype(np.float32)
    client.vector_add(0, list(range(200)), x,
                      [{"tag": i % 3} for i in range(200)])
    assert client.vector_count(0) == 200

    res = client.vector_search(0, x[:4], topk=5)
    assert [row[0][0] for row in res] == [0, 1, 2, 3]
    assert res[0][0][1] == pytest.approx(0.0, abs=1e-3)


def test_search_across_split_regions(cluster):
    client, control, nodes = cluster
    # split the partition's region; scatter-gather must still find everything
    client.refresh_region_map()
    region = next(d for d in client._regions if d.index_parameter is not None)
    client.split_region(region.region_id, 100)
    time.sleep(1.2)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((200, 16)).astype(np.float32)
    res = client.vector_search(0, x[[50, 150]], topk=3)
    assert res[0][0][0] == 50
    assert res[1][0][0] == 150
    assert client.vector_count(0) == 200


def test_kv_and_tso_and_version(cluster):
    client, control, nodes = cluster
    # KV region over raw byte keyspace
    req = pb.CreateRegionRequest()
    req.range.start_key = b"a"
    req.range.end_key = b"z"
    resp = client.coordinator.CreateRegion(req)
    assert resp.error.errcode == 0
    time.sleep(1.0)
    client.kv_put(b"hello", b"world")
    assert client.kv_get(b"hello") == b"world"
    assert client.kv_get(b"missing") is None

    ts1, ts2 = client.tso(), client.tso()
    assert ts2 > ts1

    r = client.version.VKvPut(pb.VKvPutRequest(key=b"/cfg", value=b"1"))
    assert r.revision > 0
    rng_resp = client.version.VKvRange(pb.VKvRangeRequest(start=b"/cfg"))
    assert rng_resp.items[0].value == b"1"


def test_node_and_debug_services(cluster):
    client, control, nodes = cluster
    stub = client._stub("s0", "NodeService")
    info = stub.NodeInfo(pb.NodeInfoRequest())
    assert info.store_id == "s0" and len(info.region_ids) >= 1

    dbg = client._stub("s0", "DebugService")
    dump = dbg.MetricsDump(pb.MetricsDumpRequest())
    assert "vector_add" in dump.json
    fp = dbg.FailPoint(pb.FailPointRequest(name="x", config="panic"))
    assert fp.error.errcode == 0
    fp2 = dbg.FailPoint(pb.FailPointRequest(name="x", remove=True))
    assert fp2.error.errcode == 0


def test_txn_over_grpc(cluster):
    client, control, nodes = cluster
    client.refresh_region_map()
    kv_region = next(d for d in client._regions
                     if d.start_key == b"a" and d.index_parameter is None)
    stub_owner = None
    start_ts = client.tso()
    req = pb.TxnPrewriteRequest()
    req.context.region_id = kv_region.region_id
    m = req.mutations.add()
    m.op = "put"
    m.key = b"txnkey"
    m.value = b"txnval"
    req.primary_lock = b"txnkey"
    req.start_ts = start_ts
    resp = client._call_leader(kv_region, "StoreService", "TxnPrewrite", req)
    assert resp.error.errcode == 0

    commit = pb.TxnCommitRequest()
    commit.context.region_id = kv_region.region_id
    commit.keys.append(b"txnkey")
    commit.start_ts = start_ts
    commit.commit_ts = client.tso()
    resp = client._call_leader(kv_region, "StoreService", "TxnCommit", commit)
    assert resp.error.errcode == 0

    get = pb.TxnGetRequest()
    get.context.region_id = kv_region.region_id
    get.key = b"txnkey"
    get.start_ts = client.tso()
    resp = client._call_leader(kv_region, "StoreService", "TxnGet", get)
    assert resp.found and resp.value == b"txnval"


def test_calc_distance_util(cluster):
    client, control, nodes = cluster
    stub = client._stub("s0", "UtilService")
    req = pb.VectorCalcDistanceRequest(metric_type=pb.METRIC_TYPE_L2)
    a = req.op_left_vectors.add()
    a.values.extend([1.0, 0.0])
    b = req.op_right_vectors.add()
    b.values.extend([0.0, 1.0])
    resp = stub.VectorCalcDistance(req)
    assert resp.distances[0].values[0] == pytest.approx(2.0, abs=1e-4)


def test_range_search_over_grpc(cluster):
    client, control, nodes = cluster
    client.refresh_region_map()
    region = next(d for d in client._regions if d.index_parameter is not None)
    rng = np.random.default_rng(3)
    q = rng.standard_normal((1, 16)).astype(np.float32)
    # exact distances to pick a radius including exactly a few neighbors
    res_all = client.vector_search(0, q, topk=20)
    radius = res_all[0][4][1]  # include the 5 nearest
    req = pb.VectorSearchRequest()
    req.context.region_id = region.region_id
    v = req.vectors.add()
    v.values.extend(q[0].tolist())
    req.parameter.top_n = 10
    req.parameter.radius = float(radius)
    leader = control.region_leaders.get(region.region_id, "s0")
    resp = client._stub(leader, "IndexService").VectorSearch(req)
    got = [(r.vector.id, r.distance) for r in resp.batch_results[0].results]
    assert 0 < len(got) <= 10
    assert all(d <= radius + 1e-4 for _, d in got)


def test_failpoint_injects_into_write_path(cluster):
    client, control, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=16,
        metric_type=pb.METRIC_TYPE_L2,
    )
    region = client.create_index_region(7, 0, 1 << 30, param)
    time.sleep(1.0)
    deadline = time.monotonic() + 5
    leader_sid = None
    while leader_sid is None and time.monotonic() < deadline:
        leader_sid = next(
            (sid for sid, n in nodes.items()
             if (rn := n.engine.get_node(region.region_id)) and rn.is_leader()),
            None,
        )
        time.sleep(0.05)
    dbg = client._stub(leader_sid, "DebugService")
    dbg.FailPoint(pb.FailPointRequest(name="before_vector_add",
                                      config="100%1*panic"))
    req = pb.VectorAddRequest()
    req.context.region_id = region.region_id
    v = req.vectors.add()
    v.vector.id = 123
    v.vector.values.extend([0.0] * 16)
    resp = client._stub(leader_sid, "IndexService").VectorAdd(req)
    # injected fault surfaces as an in-band error, then auto-disarms
    assert resp.error.errcode == 99999
    assert "failpoint" in resp.error.errmsg
    resp2 = client._stub(leader_sid, "IndexService").VectorAdd(req)
    assert resp2.error.errcode == 0
    dbg.FailPoint(pb.FailPointRequest(name="before_vector_add", remove=True))


def test_kv_put_if_absent_and_compare_and_set(cluster):
    """StoreService KV parity: KvPutIfAbsent / KvCompareAndSet
    (store_service.cc KV RPC set)."""
    client, control, nodes = cluster
    client.kv_put(b"cas-key", b"v1")
    d = client._region_for_key(b"cas-key")

    req = pb.KvPutIfAbsentRequest()
    req.context.region_id = d.region_id
    for key, val in [(b"cas-key", b"loser"), (b"pia-new", b"winner")]:
        kv = req.kvs.add()
        kv.key = key
        kv.value = val
    resp = client._call_leader(d, "StoreService", "KvPutIfAbsent", req)
    assert list(resp.key_states) == [False, True]
    assert client.kv_get(b"cas-key") == b"v1"
    assert client.kv_get(b"pia-new") == b"winner"

    # atomic batch: one existing key poisons the whole batch
    areq = pb.KvPutIfAbsentRequest(is_atomic=True)
    areq.context.region_id = d.region_id
    for key in (b"pia-new", b"pia-never"):
        kv = areq.kvs.add()
        kv.key = key
        kv.value = b"x"
    aresp = client._call_leader(d, "StoreService", "KvPutIfAbsent", areq)
    assert list(aresp.key_states) == [False, False]
    assert client.kv_get(b"pia-never") is None

    creq = pb.KvCompareAndSetRequest(expect_value=b"v1")
    creq.context.region_id = d.region_id
    creq.kv.key = b"cas-key"
    creq.kv.value = b"v2"
    cresp = client._call_leader(d, "StoreService", "KvCompareAndSet", creq)
    assert cresp.key_state is True
    assert client.kv_get(b"cas-key") == b"v2"
    # stale expect fails
    cresp = client._call_leader(d, "StoreService", "KvCompareAndSet", creq)
    assert cresp.key_state is False


def test_vector_search_debug_stage_timings(cluster):
    """VectorSearchDebug returns results + stage timings
    (vector_reader.h:85-88)."""
    client, control, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=16,
        metric_type=pb.METRIC_TYPE_L2,
    )
    d = client.create_index_region(9, 0, 1 << 30, param)
    time.sleep(1.0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((50, 16)).astype(np.float32)
    client.vector_add(9, list(range(50)), x)
    req = pb.VectorSearchDebugRequest()
    req.context.region_id = d.region_id
    v = req.vectors.add()
    v.values.extend([0.1] * 16)
    req.parameter.top_n = 3
    resp = client._call_leader(d, "IndexService", "VectorSearchDebug", req)
    assert resp.error.errcode == 0
    assert len(resp.batch_results) == 1
    assert len(resp.batch_results[0].results) == 3
    assert resp.total_us > 0
    assert resp.search_us > 0
    assert resp.total_us >= (
        resp.prefilter_us + resp.search_us + resp.postfilter_us
        + resp.backfill_us
    )


def test_index_lifecycle_rpcs(cluster):
    """VectorBuild/Status/Reset/Dump/CountMemory/GetRegionMetrics
    (index_service.h lifecycle set)."""
    client, control, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=16,
        metric_type=pb.METRIC_TYPE_L2,
    )
    d = client.create_index_region(13, 0, 1 << 30, param)
    time.sleep(1.0)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((40, 16)).astype(np.float32)
    client.vector_add(13, list(range(40)), x)

    def leader_call(method, req):
        req.context.region_id = d.region_id
        return client._call_leader(d, "IndexService", method, req)

    st = leader_call("VectorStatus", pb.VectorStatusRequest())
    assert st.error.errcode == 0 and st.ready and st.count == 40
    assert st.index_type == "flat" and st.apply_log_id > 0

    cm = leader_call("VectorCountMemory", pb.VectorCountMemoryRequest())
    assert cm.bytes > 0

    rm = leader_call("VectorGetRegionMetrics",
                     pb.VectorGetRegionMetricsRequest())
    assert rm.vector_count == 40
    assert rm.min_id == 0 and rm.max_id == 39
    assert rm.region_state == "normal"

    dump = leader_call("VectorDump", pb.VectorDumpRequest())
    parsed = json.loads(dump.json)
    assert parsed["count"] == 40 and parsed["ready"] is True

    # reset drops the view and rebuilds it from the engine
    assert leader_call("VectorReset", pb.VectorResetRequest()).error.errcode == 0
    st = leader_call("VectorStatus", pb.VectorStatusRequest())
    assert st.ready and st.count == 40
    assert leader_call("VectorBuild", pb.VectorBuildRequest()).error.errcode == 0
    res = client.vector_search(13, x[:2], topk=3)
    assert [r[0][0] for r in res] == [0, 1]


def test_kv_batch_get_and_delete_range(cluster):
    """KvBatchGet / KvDeleteRange (store_service.cc KV RPC parity), with
    region-bounds validation (ServiceHelper::ValidateRange)."""
    client, control, nodes = cluster
    # ensure SOME region covers the dr* keys: create [dq, ds) unless an
    # earlier test's wider region already does (the coordinator rejects
    # overlapping same-type ranges)
    req0 = pb.CreateRegionRequest()
    req0.range.start_key = b"dq"
    req0.range.end_key = b"ds"
    created = client.coordinator.CreateRegion(req0)
    assert created.error.errcode in (0, 60001)
    time.sleep(1.0)
    client.refresh_region_map()
    for i in range(5):
        client.kv_put(f"dr{i}".encode(), f"v{i}".encode())
    d = client._region_for_key(b"dr0")
    req = pb.KvBatchGetRequest()
    req.context.region_id = d.region_id
    req.keys.extend([b"dr1", b"drMISSING", b"dr3"])  # absent key in-range
    resp = client._call_leader(d, "StoreService", "KvBatchGet", req)
    assert list(resp.found) == [True, False, True]
    assert resp.kvs[0].value == b"v1" and resp.kvs[2].value == b"v3"

    dreq = pb.KvDeleteRangeRequest()
    dreq.context.region_id = d.region_id
    dreq.range.start_key = b"dr1"
    dreq.range.end_key = b"dr4"
    first = client._call_leader(d, "StoreService", "KvDeleteRange", dreq)
    assert first.error.errcode == 0
    # count reflects the APPLIED write (dr1, dr2, dr3 were live)
    assert first.delete_count == 3
    assert client.kv_get(b"dr0") == b"v0"
    assert client.kv_get(b"dr2") is None
    assert client.kv_get(b"dr4") == b"v4"

    # the response reports how many keys the range actually covered
    dresp = client._call_leader(d, "StoreService", "KvDeleteRange", dreq)
    assert dresp.delete_count == 0      # already deleted

    # a range reaching outside the region is rejected, not clamped-silent
    from dingo_tpu.client.client import ClientError

    bad = pb.KvDeleteRangeRequest()
    bad.context.region_id = d.region_id
    bad.range.start_key = b"dq"
    bad.range.end_key = b"zz"           # beyond region end b"ds"
    with pytest.raises(ClientError, match="outside region"):
        client._call_leader(d, "StoreService", "KvDeleteRange", bad)
    # out-of-region key in a put is rejected too
    preq = pb.KvBatchPutRequest()
    preq.context.region_id = d.region_id
    kv = preq.kvs.add()
    kv.key = b"zz-outside"
    kv.value = b"x"
    with pytest.raises(ClientError, match="outside region"):
        client._call_leader(d, "StoreService", "KvBatchPut", preq)

    # every KV entry point validates bounds the same way: a stale-routed
    # client must not read or write through the wrong region's raft group
    # (reference ValidateKv*Request, store_service.cc:154,471)
    greq = pb.KvBatchGetRequest()
    greq.context.region_id = d.region_id
    greq.keys.append(b"zz-outside")
    with pytest.raises(ClientError, match="outside region"):
        client._call_leader(d, "StoreService", "KvBatchGet", greq)

    pareq = pb.KvPutIfAbsentRequest()
    pareq.context.region_id = d.region_id
    pkv = pareq.kvs.add()
    pkv.key = b"zz-outside"
    pkv.value = b"x"
    with pytest.raises(ClientError, match="outside region"):
        client._call_leader(d, "StoreService", "KvPutIfAbsent", pareq)

    creq = pb.KvCompareAndSetRequest()
    creq.context.region_id = d.region_id
    creq.kv.key = b"zz-outside"
    creq.kv.value = b"x"
    with pytest.raises(ClientError, match="outside region"):
        client._call_leader(d, "StoreService", "KvCompareAndSet", creq)


def test_table_filter_over_grpc(cluster):
    """TABLE coprocessor filter end-to-end over the wire: table rows ride
    VectorAdd (VectorWithScalar.table_data), the search parameter carries
    a pb.Coprocessor, and the reader dispatches it (reference
    vector_reader.cc:169-232)."""
    from dingo_tpu.coprocessor.coprocessor_v2 import encode_row
    from dingo_tpu.raft import wire

    client, control, nodes = cluster
    param = pb.VectorIndexParameter(
        index_type=pb.VECTOR_INDEX_TYPE_FLAT, dimension=16,
        metric_type=pb.METRIC_TYPE_L2,
    )
    client.create_index_region(3, 0, 1 << 40, param)
    time.sleep(1.0)

    rng = np.random.default_rng(5)
    x = rng.standard_normal((120, 16)).astype(np.float32)
    rows = [["eng" if i % 4 == 0 else "ops", float(i)] for i in range(120)]
    client.vector_add(3, list(range(120)), x,
                      table_values=[encode_row(r) for r in rows])

    cop = pb.Coprocessor()
    for i, (name, t) in enumerate((("dept", "VARCHAR"), ("rank", "DOUBLE"))):
        col = cop.original_schema.add()
        col.name, col.sql_type, col.index = name, t, i
    cop.filter_expr = wire.encode(
        ["eq", ["field", "dept"], ["const", "eng"]])

    res = client.vector_search(
        3, x[:4], topk=8, filter=pb.TABLE_FILTER,
        filter_type=pb.QUERY_PRE, coprocessor=cop,
    )
    for row in res:
        assert row, "TABLE pre-filter returned nothing over grpc"
        assert all(vid % 4 == 0 for vid, _ in row), row
    assert res[0][0][0] == 0   # query 0 is vector 0 (dept=eng)

    # post variant
    res_post = client.vector_search(
        3, x[4:6], topk=5, filter=pb.TABLE_FILTER,
        filter_type=pb.QUERY_POST, coprocessor=cop,
    )
    for row in res_post:
        assert all(vid % 4 == 0 for vid, _ in row), row


def test_kv_reads_leader_gated(cluster):
    """A follower must not serve KV reads (its apply can lag committed
    writes); it answers 20001 with the leader hint so clients re-route —
    same contract as the txn surface."""
    client, control, nodes = cluster
    # reuse the module's KV region over [a, z) (module-scoped cluster)
    client.refresh_region_map()
    d = client._region_for_key(b"gate-k")
    rid = d.region_id
    client.kv_put(b"gate-k", b"v")

    follower = next(
        sid for sid, n in nodes.items()
        if (r := n.engine.get_node(rid)) is not None and not r.is_leader()
    )
    stub = client._stub(follower, "StoreService")
    kreq = pb.KvGetRequest()
    kreq.context.region_id = rid
    kreq.key = b"gate-k"
    resp = stub.KvGet(kreq)
    assert resp.error.errcode == 20001, resp
    assert "not leader" in resp.error.errmsg
    # leader-routed read still works (SDK rotation)
    assert client.kv_get(b"gate-k") == b"v"
