"""Checker registry. Order is report order, not priority."""

from __future__ import annotations

from typing import List

from tools.dingolint.core import Checker


def all_checkers() -> List[Checker]:
    from tools.dingolint.checkers.bare_jit import BareJitChecker
    from tools.dingolint.checkers.context_handoff import (
        ContextHandoffChecker,
    )
    from tools.dingolint.checkers.host_sync import HostSyncChecker
    from tools.dingolint.checkers.knob_audit import KnobAuditChecker
    from tools.dingolint.checkers.ladder_shape import LadderShapeChecker
    from tools.dingolint.checkers.lock_order import LockOrderChecker
    from tools.dingolint.checkers.metric_names import MetricNamesChecker
    from tools.dingolint.checkers.resolve_sync import ResolveSyncChecker
    from tools.dingolint.checkers.retry_policy import RetryPolicyChecker

    return [
        LockOrderChecker(),
        HostSyncChecker(),
        ResolveSyncChecker(),
        BareJitChecker(),
        LadderShapeChecker(),
        ContextHandoffChecker(),
        MetricNamesChecker(),
        RetryPolicyChecker(),
        KnobAuditChecker(),
    ]


def by_name(names) -> List[Checker]:
    wanted = set(names)
    out = [c for c in all_checkers() if c.name in wanted]
    missing = wanted - {c.name for c in out}
    if missing:
        raise SystemExit(f"unknown checker(s): {sorted(missing)} "
                         f"(have: {[c.name for c in all_checkers()]})")
    return out
