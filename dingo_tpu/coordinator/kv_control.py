"""KvControl: etcd-compatible revisioned KV with leases and one-time watches.

Reference: src/coordinator/kv_control.{h,cc} + _fsm/_kv/_lease/_watch.cc
(~6K LoC) — KvRange/KvPut/KvDeleteRange/KvCompaction (kv_control.h:252-291),
revision model, LeaseGrant/LeaseRevoke (:221-225) with TTL-attached keys,
and one-time watches with a KvWatchNode closure queue (:47-113).

Round-2 VERDICT item 5: the store now keeps PER-KEY REVISION CHAINS (every
put appends a version, every delete appends a tombstone), so

  - KvRange can read as-of a past revision,
  - watches can start from a past revision and replay history,
  - KvCompaction(revision) is real: it drops versions superseded at or
    below the compaction floor (keeping each key's live base version,
    etcd semantics) and reads/watches below the floor fail Compacted.

Persistence: every version is a typed-codec blob under an 8-byte
big-endian revision key (naturally scan-ordered for recovery); the latest
live version is additionally indexed by key for O(1) point reads after
recovery. Compaction deletes the superseded version blobs.
"""

from __future__ import annotations

import dataclasses
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from dingo_tpu.common import persist
from dingo_tpu.engine.raw_engine import CF_META, RawEngine

_PREFIX_KV = b"VKV_"          # latest live version per key
_PREFIX_VER = b"VKVV_"        # every version, keyed by revision (8B BE)
_PREFIX_LEASE = b"VLEASE_"
_KEY_REVISION = b"VKVREV__"   # NOT under VKV_: user keys cannot collide
_KEY_COMPACT = b"VKVCOMPACT__"


class CompactedError(KeyError):
    """Requested revision is below the compaction floor (etcd
    ErrCompacted)."""


class FutureRevError(KeyError):
    """Requested revision is ahead of the store (etcd ErrFutureRev) — a
    pinned read served from the future would return different data once
    the store catches up."""


@persist.register
@dataclasses.dataclass
class KvItem:
    key: bytes
    value: bytes
    create_revision: int
    mod_revision: int
    version: int            # 0 = tombstone (delete event)
    lease_id: int = 0

    @property
    def is_tombstone(self) -> bool:
        return self.version == 0


@persist.register
@dataclasses.dataclass
class Lease:
    lease_id: int
    ttl_s: int
    granted_ms: int
    keys: List[bytes] = dataclasses.field(default_factory=list)

    def expired(self, now_ms: Optional[int] = None) -> bool:
        now_ms = now_ms if now_ms is not None else int(time.time() * 1000)
        return now_ms > self.granted_ms + self.ttl_s * 1000


class KvControl:
    def __init__(self, engine: RawEngine):
        self.engine = engine
        self._lock = threading.RLock()
        self._revision = 1
        self._compact_revision = 0
        self._kv: Dict[bytes, KvItem] = {}            # latest live version
        self._history: Dict[bytes, List[KvItem]] = {}  # revision chains
        self._leases: Dict[int, Lease] = {}
        self._next_lease = 1
        #: one-time watches: key -> [(watch_revision, callback)]
        self._watches: Dict[bytes, List[Tuple[int, Callable]]] = {}
        self._recover()

    # ---------------- persistence -------------------------------------------
    def _recover(self) -> None:
        blob = self.engine.get(CF_META, _KEY_REVISION)
        if blob:
            self._revision = persist.loads(blob)
        blob = self.engine.get(CF_META, _KEY_COMPACT)
        if blob:
            self._compact_revision = persist.loads(blob)
        # version log first (revision-ordered by key layout)
        for k, v in self.engine.scan(CF_META, _PREFIX_VER,
                                     _PREFIX_VER + b"\xff"):
            item: KvItem = persist.loads(v)
            self._history.setdefault(item.key, []).append(item)
            self._revision = max(self._revision, item.mod_revision)
        for chain in self._history.values():
            chain.sort(key=lambda i: i.mod_revision)
        # latest-live index; also seeds chains for pre-history state
        # (a round-2 snapshot has _PREFIX_KV entries but no version log)
        # materialized: the loop writes version blobs into the SAME CF,
        # and mutating under a live scan generator double-yields keys
        for k, v in list(
            self.engine.scan(CF_META, _PREFIX_KV, _PREFIX_KV + b"\xff")
        ):
            if k == _KEY_REVISION:
                continue
            item = persist.loads(v)
            self._kv[item.key] = item
            self._revision = max(self._revision, item.mod_revision)
            chain = self._history.setdefault(item.key, [])
            if not any(c.mod_revision == item.mod_revision for c in chain):
                chain.append(item)
                chain.sort(key=lambda i: i.mod_revision)
                # write-through so the seeded version survives the NEXT
                # restart even after _PREFIX_KV is overwritten (and so
                # compaction's per-blob delete accounting stays exact)
                self.engine.put(CF_META, self._ver_key(item.mod_revision),
                                persist.dumps(item))
        for k, v in self.engine.scan(CF_META, _PREFIX_LEASE,
                                     _PREFIX_LEASE + b"\xff"):
            lease: Lease = persist.loads(v)
            self._leases[lease.lease_id] = lease
            self._next_lease = max(self._next_lease, lease.lease_id + 1)

    def _bump_revision(self) -> int:
        """Monotonic across restarts: deletes advance it too, so issued
        revisions are never reused (etcd contract)."""
        self._revision += 1
        self.engine.put(CF_META, _KEY_REVISION, persist.dumps(self._revision))
        return self._revision

    def _ver_key(self, revision: int) -> bytes:
        return _PREFIX_VER + struct.pack(">Q", revision)

    def _append_version(self, item: KvItem) -> None:
        self._history.setdefault(item.key, []).append(item)
        self.engine.put(CF_META, self._ver_key(item.mod_revision),
                        persist.dumps(item))

    def _persist_kv(self, item: KvItem) -> None:
        self.engine.put(CF_META, _PREFIX_KV + item.key, persist.dumps(item))

    def _persist_lease(self, lease: Lease) -> None:
        self.engine.put(
            CF_META, _PREFIX_LEASE + str(lease.lease_id).encode(),
            persist.dumps(lease),
        )

    # ---------------- KV ------------------------------------------------------
    def kv_put(self, key: bytes, value: bytes, lease_id: int = 0, *,
               now_ms: Optional[int] = None) -> int:
        """Returns the new revision (KvPut, kv_control.h:263)."""
        with self._lock:
            if lease_id:
                lease = self._leases.get(lease_id)
                if lease is None or lease.expired(now_ms):
                    raise KeyError(f"lease {lease_id} not found/expired")
                if key not in lease.keys:
                    lease.keys.append(key)
                    self._persist_lease(lease)
            self._bump_revision()
            old = self._kv.get(key)
            item = KvItem(
                key=key,
                value=value,
                create_revision=old.create_revision if old else self._revision,
                mod_revision=self._revision,
                version=(old.version + 1) if old else 1,
                lease_id=lease_id,
            )
            self._kv[key] = item
            self._persist_kv(item)
            self._append_version(item)
            self._fire_watches(key, "put", item)
            return self._revision

    def _as_of(self, key: bytes, revision: int) -> Optional[KvItem]:
        """Newest live version of key with mod_revision <= revision."""
        chain = self._history.get(key)
        if not chain:
            return None
        best = None
        for item in chain:
            if item.mod_revision > revision:
                break
            best = item
        if best is None or best.is_tombstone:
            return None
        return best

    def kv_range(self, start: bytes, end: Optional[bytes] = None,
                 limit: int = 0, revision: int = 0) -> Tuple[List[KvItem], int]:
        """KvRange: [start, end) or exact key when end is None. With
        revision > 0, reads as of that PAST revision (etcd range
        revision); below the compaction floor raises CompactedError."""
        with self._lock:
            # NOTE deliberately no lease expiry here: a read must not mutate
            # state (in raft-meta mode a follower read would fork replica
            # state off-log). The lease_gc crontab — replicated through the
            # log on the leader — is the only expiry path.
            if revision and revision < self._compact_revision:
                raise CompactedError(
                    f"revision {revision} compacted "
                    f"(floor {self._compact_revision})"
                )
            if revision > self._revision:
                raise FutureRevError(
                    f"revision {revision} > current {self._revision}"
                )
            if revision == 0 or revision == self._revision:
                if end is None:
                    item = self._kv.get(start)
                    return ([item] if item else [], self._revision)
                out = [
                    item for k, item in sorted(self._kv.items())
                    if start <= k < end
                ]
            else:
                keys = (
                    [start] if end is None
                    else sorted(k for k in self._history if start <= k < end)
                )
                out = [i for i in (self._as_of(k, revision) for k in keys)
                       if i is not None]
            if limit:
                out = out[:limit]
            return out, self._revision

    def kv_delete_range(self, start: bytes, end: Optional[bytes] = None) -> int:
        """Returns number deleted."""
        with self._lock:
            doomed = (
                [start] if end is None
                else [k for k in list(self._kv) if start <= k < end]
            )
            n = 0
            for k in doomed:
                item = self._kv.pop(k, None)
                if item is None:
                    continue
                rev = self._bump_revision()
                n += 1
                self.engine.delete(CF_META, _PREFIX_KV + k)
                tomb = KvItem(key=k, value=b"", create_revision=0,
                              mod_revision=rev, version=0)
                self._append_version(tomb)
                self._fire_watches(k, "delete", tomb)
            return n

    def kv_compaction(self, revision: int) -> int:
        """KvCompaction (kv_control.h:287): drop versions superseded at or
        below `revision`. Each key keeps its newest version <= revision iff
        live (the base state readers at `revision` still need); tombstones
        at/below the floor and everything they superseded are dropped.
        Returns the number of versions removed."""
        with self._lock:
            revision = min(revision, self._revision)
            if revision <= self._compact_revision:
                return 0
            removed = 0
            for key in list(self._history):
                chain = self._history[key]
                below = [i for i in chain if i.mod_revision <= revision]
                above = [i for i in chain if i.mod_revision > revision]
                keep_base = (
                    [below[-1]] if below and not below[-1].is_tombstone
                    else []
                )
                for item in below:
                    if keep_base and item is keep_base[0]:
                        continue
                    self.engine.delete(
                        CF_META, self._ver_key(item.mod_revision)
                    )
                    removed += 1
                new_chain = keep_base + above
                if new_chain:
                    self._history[key] = new_chain
                else:
                    del self._history[key]
            self._compact_revision = revision
            self.engine.put(CF_META, _KEY_COMPACT, persist.dumps(revision))
            return removed

    # ---------------- leases --------------------------------------------------
    def lease_grant(self, ttl_s: int, lease_id: int = 0, *,
                    now_ms: Optional[int] = None) -> Lease:
        """`now_ms` comes from the raft-meta harness in replicated mode so
        lease clocks are identical on every coordinator replica."""
        with self._lock:
            lid = lease_id or self._next_lease
            self._next_lease = max(self._next_lease, lid + 1)
            lease = Lease(lease_id=lid, ttl_s=ttl_s,
                          granted_ms=now_ms if now_ms is not None else int(time.time() * 1000))
            self._leases[lid] = lease
            self._persist_lease(lease)
            return lease

    def lease_renew(self, lease_id: int, *,
                    now_ms: Optional[int] = None) -> Lease:
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease.expired(now_ms):
                raise KeyError(f"lease {lease_id} not found/expired")
            lease.granted_ms = now_ms if now_ms is not None else int(time.time() * 1000)
            self._persist_lease(lease)
            return lease

    def lease_revoke(self, lease_id: int) -> int:
        """Revoke + delete attached keys; returns deleted count."""
        with self._lock:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return 0
            self.engine.delete(CF_META, _PREFIX_LEASE + str(lease_id).encode())
            n = 0
            for key in lease.keys:
                n += self.kv_delete_range(key)
            return n

    def _expire_leases(self, now_ms: Optional[int] = None) -> None:
        for lid, lease in list(self._leases.items()):
            if lease.expired(now_ms):
                self.lease_revoke(lid)

    def lease_gc(self, *, now_ms: Optional[int] = None) -> None:
        """Crontab entry point (lease expiry sweep)."""
        with self._lock:
            self._expire_leases(now_ms)

    # ---------------- watches -------------------------------------------------
    def watch(self, key: bytes, start_revision: int,
              callback: Callable[[str, KvItem], None]) -> None:
        """One-time watch (kv_control.h:47-113): fires once with the OLDEST
        event for `key` at/after start_revision — replayed from the
        revision chain when it already happened — then unregisters.
        start_revision at/below the compaction floor raises
        CompactedError when the needed history is gone."""
        with self._lock:
            if start_revision <= self._compact_revision:
                # etcd-strict (<=, not <): compaction drops tombstone
                # events at exactly the floor, so a watch from the floor
                # could silently miss a delete — cancel with Compacted
                raise CompactedError(
                    f"watch from {start_revision} compacted "
                    f"(floor {self._compact_revision})"
                )
            chain = self._history.get(key, [])
            for item in chain:
                if item.mod_revision >= start_revision:
                    callback("delete" if item.is_tombstone else "put", item)
                    return
            self._watches.setdefault(key, []).append((start_revision, callback))

    def cancel_watch(self, key: bytes, callback: Callable) -> bool:
        with self._lock:
            entries = self._watches.get(key, [])
            for pair in entries:
                if pair[1] is callback:
                    entries.remove(pair)
                    if not entries:
                        self._watches.pop(key, None)
                    return True
            return False

    def _fire_watches(self, key: bytes, event: str, item: KvItem) -> None:
        keep = []
        for rev, cb in self._watches.pop(key, []):
            if item.mod_revision < rev:
                keep.append((rev, cb))   # event predates the watch window
                continue
            try:
                cb(event, item)
            except Exception:
                pass
        if keep:
            self._watches[key] = keep
