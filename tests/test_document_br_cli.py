"""Document index, BR backup/restore, CLI tests."""

import json
import time

import numpy as np
import pytest

from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.br import backup_cluster, restore_cluster
from dingo_tpu.document import DocumentIndex
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.raft import LocalTransport
from dingo_tpu.store.node import StoreNode
from dingo_tpu.store.region import RegionType


# ---------------- document index ----------------


def test_document_bm25_ranking():
    idx = DocumentIndex(1, text_fields=("title", "body"))
    idx.add(1, {"title": "tpu vector search",
                "body": "fast distance kernels on the mxu"})
    idx.add(2, {"title": "cooking pasta",
                "body": "boil water add salt add pasta"})
    idx.add(3, {"title": "vector databases",
                "body": "vector indexes ivf hnsw vector"})
    hits = idx.search("vector")
    assert [h[0] for h in hits][:2] == [3, 1]   # 3 has more matches
    assert idx.search("pasta")[0][0] == 2
    assert idx.search("nonexistentterm") == []


def test_document_and_mode_and_filters():
    idx = DocumentIndex(1)
    idx.add(1, {"text": "red fast car", "year": 2020})
    idx.add(2, {"text": "red slow truck", "year": 2021})
    idx.add(3, {"text": "blue fast car", "year": 2021})
    both = idx.search("red fast", mode="and")
    assert [h[0] for h in both] == [1]
    filtered = idx.search("fast", column_filter={"year": 2021})
    assert [h[0] for h in filtered] == [3]


def test_document_delete_upsert_save_load(tmp_path):
    idx = DocumentIndex(1)
    idx.add(1, {"text": "hello world"})
    idx.add(2, {"text": "hello there"})
    idx.delete([1])
    assert idx.count() == 1
    assert [h[0] for h in idx.search("hello")] == [2]
    idx.upsert(2, {"text": "goodbye"})
    assert idx.search("hello") == []
    assert idx.search("goodbye")[0][0] == 2
    idx.apply_log_id = 42
    idx.save(str(tmp_path))
    idx2 = DocumentIndex(1)
    idx2.load(str(tmp_path))
    assert idx2.apply_log_id == 42
    assert idx2.search("goodbye")[0][0] == 2


# ---------------- BR ----------------


def test_backup_restore_roundtrip(tmp_path):
    transport = LocalTransport()
    coord = CoordinatorControl(MemEngine(), replication=2)
    nodes = {
        sid: StoreNode(sid, transport, coord, raft_kw={"seed": i})
        for i, sid in enumerate(["s0", "s1"])
    }
    d = coord.create_region(
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1 << 30),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=IndexType.FLAT, dimension=8),
    )
    for _ in range(3):
        for n in nodes.values():
            n.heartbeat_once()
        time.sleep(0.05)
    leader = None
    deadline = time.monotonic() + 5
    while leader is None and time.monotonic() < deadline:
        leader = next((n for n in nodes.values()
                       if (rn := n.engine.get_node(d.region_id)) and
                       rn.is_leader()), None)
        time.sleep(0.02)
    region = leader.get_region(d.region_id)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((50, 8)).astype(np.float32)
    leader.storage.vector_add(region, np.arange(50, dtype=np.int64), x,
                              [{"i": int(i)} for i in range(50)])
    time.sleep(0.3)
    manifest = backup_cluster(coord, nodes, str(tmp_path / "bak"))
    assert len(manifest["regions"]) == 1

    # fresh cluster
    transport2 = LocalTransport()
    coord2 = CoordinatorControl(MemEngine(), replication=2)
    nodes2 = {
        sid: StoreNode(sid, transport2, coord2, raft_kw={"seed": i})
        for i, sid in enumerate(["s0", "s1"])
    }
    n_restored = restore_cluster(coord2, nodes2, str(tmp_path / "bak"))
    assert n_restored == 1
    rid2 = next(iter(coord2.regions))
    deadline = time.monotonic() + 5
    leader2 = None
    while leader2 is None and time.monotonic() < deadline:
        leader2 = next((n for n in nodes2.values()
                        if (rn := n.engine.get_node(rid2)) and rn.is_leader()),
                       None)
        time.sleep(0.02)
    region2 = leader2.get_region(rid2)
    assert leader2.storage.vector_count(region2) == 50
    res = leader2.storage.vector_batch_search(region2, x[:2], 1)
    assert [r[0].id for r in res] == [0, 1]
    got = leader2.storage.vector_batch_query(region2, [7],
                                             with_scalar_data=True)
    assert got[0].scalar == {"i": 7}
    for n in list(nodes.values()) + list(nodes2.values()):
        n.stop()


# ---------------- CLI ----------------


@pytest.fixture(scope="module")
def grpc_cluster():
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.server.rpc import DingoServer

    transport = LocalTransport()
    me = MemEngine()
    control = CoordinatorControl(me, replication=2)
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    nodes, servers, flags = {}, [], []
    for i, sid in enumerate(["s0", "s1"]):
        n = StoreNode(sid, transport, control, raft_kw={"seed": i})
        srv = DingoServer()
        srv.host_store_role(n)
        port = srv.start()
        n.start_heartbeat(0.1)
        nodes[sid] = n
        servers.append(srv)
        flags.append(f"--store")
        flags.append(f"{sid}=127.0.0.1:{port}")
    base = ["--coordinator", f"127.0.0.1:{cport}"] + flags
    yield base
    for s in servers:
        s.stop()
    cs.stop()
    for n in nodes.values():
        n.stop()


def test_cli_end_to_end(grpc_cluster, capsys):
    from dingo_tpu.client.cli import main

    base = grpc_cluster
    assert main(base + ["coordinator", "hello"]) == 0
    assert main(base + ["region", "create-index", "--dim", "8"]) == 0
    rid = json.loads(capsys.readouterr().out.strip().splitlines()[-1])["region_id"]
    time.sleep(1.0)
    assert main(base + ["vector", "add-random", "--dim", "8",
                        "--count", "50"]) == 0
    # count may route to a follower that hasn't applied yet (reads are
    # eventually consistent off-leader); poll briefly
    deadline = time.monotonic() + 3
    out = [""]
    while time.monotonic() < deadline:
        assert main(base + ["vector", "count"]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        if out[-1] == "50":
            break
        time.sleep(0.1)
    assert out[-1] == "50"
    assert main(base + ["vector", "search-random", "--dim", "8"]) == 0
    hits = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert len(hits) == 5
    assert main(base + ["node", "info", "--store", "s0"]) == 0
    assert main(base + ["coordinator", "region-map"]) == 0
    assert main(base + ["debug", "metrics", "--store", "s0"]) == 0
    # kv flow needs a kv region over byte keys
    from dingo_tpu.client.client import DingoClient
    from dingo_tpu.server import pb as _pb
    assert main(base + ["coordinator", "tso"]) == 0


def test_cli_meta_cluster_groups(grpc_cluster, capsys):
    """New CLI groups: meta (schema/table ops), cluster (stat/jobs/
    region-detail), search-debug."""
    from dingo_tpu.client.cli import main

    base = grpc_cluster
    assert main(base + ["meta", "schemas"]) == 0
    assert "dingo" in json.loads(capsys.readouterr().out.strip())
    assert main(base + ["meta", "create-schema", "cliapp"]) == 0
    capsys.readouterr()
    assert main(base + ["meta", "create-table", "--schema", "cliapp",
                        "clitab", "--dim", "8"]) == 0
    created = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert created["table_id"] > 0 and created["regions"]
    time.sleep(1.0)
    assert main(base + ["meta", "tables", "--schema", "cliapp"]) == 0
    rows = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert any(r["name"] == "clitab" for r in rows)
    assert main(base + ["meta", "table", "--schema", "cliapp", "clitab"]) == 0
    t = json.loads(capsys.readouterr().out.strip())
    region_id = t["partitions"][0]["region_id"]
    pid = t["partitions"][0]["partition_id"]

    assert main(base + ["vector", "add-random", "--dim", "8",
                        "--count", "20", "--partition", str(pid)]) == 0
    capsys.readouterr()
    assert main(base + ["cluster", "stat"]) == 0
    stat = json.loads(capsys.readouterr().out.strip())
    assert stat["stores"] == 2 and stat["regions"] >= 1
    assert main(base + ["cluster", "jobs", "--include-done"]) == 0
    capsys.readouterr()
    # region-detail on whichever store leads it
    ok = False
    for sid in ("s0", "s1"):
        if main(base + ["cluster", "region-detail", "--store", sid,
                        "--region", str(region_id)]) == 0:
            detail = json.loads(capsys.readouterr().out.strip())
            ok = ok or detail["region_id"] == region_id
        else:
            capsys.readouterr()
    assert ok
    assert main(base + ["search-debug", "--dim", "8",
                        "--partition", str(pid)]) == 0
    dbg = json.loads(capsys.readouterr().out.strip())
    assert dbg["stage_us"]["total"] > 0
    assert main(base + ["meta", "drop-table", "--schema", "cliapp",
                        "clitab"]) == 0


def test_backup_restore_with_table_meta(tmp_path):
    """Backup carries schema/table meta + TSO/auto-increment state; restore
    remaps table partitions onto the recreated region ids (reference br
    sdk/sql meta groups)."""
    import numpy as np

    from dingo_tpu.coordinator.auto_increment import AutoIncrementControl
    from dingo_tpu.coordinator.meta import MetaControl, PartitionDefinition
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.index.base import IndexParameter, IndexType

    transport = LocalTransport()
    me = MemEngine()
    coord = CoordinatorControl(me, replication=1)
    meta = MetaControl(me, coord)
    tso = TsoControl(me)
    auto = AutoIncrementControl(me)
    node = StoreNode("s0", transport, coord, raft_kw={"seed": 0})
    node.start_heartbeat(0.1)
    t = meta.create_table(
        "dingo", "bk",
        [PartitionDefinition(partition_id=61, id_lo=0, id_hi=1000)],
        index_parameter=IndexParameter(index_type=IndexType.FLAT,
                                       dimension=8),
    )
    time.sleep(1.0)
    region = node.get_region(t.partitions[0].region_id)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((50, 8)).astype(np.float32)
    node.storage.vector_add(region, np.arange(50, dtype=np.int64), x)
    ts_before = tso.gen_ts()[0]
    auto.update(t.table_id, 500, force=True)
    manifest = backup_cluster(coord, {"s0": node}, str(tmp_path / "bk"),
                              meta=meta, tso=tso, auto_increment=auto)
    assert manifest["tables"] and manifest["schemas"]
    node.stop()

    # fresh cluster
    me2 = MemEngine()
    coord2 = CoordinatorControl(me2, replication=1)
    meta2 = MetaControl(me2, coord2)
    tso2 = TsoControl(me2)
    auto2 = AutoIncrementControl(me2)
    node2 = StoreNode("s0", LocalTransport(), coord2, raft_kw={"seed": 0})
    node2.start_heartbeat(0.1)
    n = restore_cluster(coord2, {"s0": node2}, str(tmp_path / "bk"),
                        meta=meta2, tso=tso2, auto_increment=auto2)
    assert n == 1
    t2 = meta2.get_table("dingo", "bk")
    assert t2 is not None
    rid = t2.partitions[0].region_id
    assert rid in coord2.regions           # remapped to the NEW region
    region2 = node2.get_region(rid)
    res = node2.storage.vector_batch_search(region2, x[:2], 3)
    assert res[0][0].id == 0 and res[1][0].id == 1
    assert tso2.gen_ts()[0] > ts_before    # watermark advanced
    assert auto2.get(t2.table_id) == 500
    node2.stop()


def test_document_phrase_queries():
    """Phrase mode: terms must appear consecutively (tantivy phrase-query
    parity over the positional postings)."""
    from dingo_tpu.document.index import DocumentIndex

    idx = DocumentIndex(1)
    idx.add(1, {"text": "distributed vector search on tpu"})
    idx.add(2, {"text": "search for distributed systems with vector math"})
    idx.add(3, {"text": "vector search is fast"})
    # both docs contain the words; only 1 and 3 contain the phrase
    hits = idx.search("vector search", mode="phrase")
    assert sorted(d for d, _ in hits) == [1, 3]
    assert idx.search("search vector", mode="phrase") == []
    # OR mode still matches all three
    assert len(idx.search("vector search", mode="or")) == 3
    # delete updates positional postings
    idx.delete([3])
    assert sorted(d for d, _ in idx.search("vector search", mode="phrase")) == [1]
    # save/load keeps positions
    import tempfile

    d = tempfile.mkdtemp()
    idx.save(d)
    idx2 = DocumentIndex(1)
    idx2.load(d)
    assert sorted(x for x, _ in idx2.search("vector search", mode="phrase")) == [1]


# ---------------- remote BR (fan-out over RPC) ----------------


def _mk_grpc_cluster(seed: int, snapdir: str, stores=("s0", "s1")):
    from dingo_tpu.coordinator.kv_control import KvControl
    from dingo_tpu.coordinator.tso import TsoControl
    from dingo_tpu.server.rpc import DingoServer

    transport = LocalTransport()
    me = MemEngine()
    control = CoordinatorControl(me, replication=len(stores))
    cs = DingoServer()
    cs.host_coordinator_role(control, TsoControl(me), KvControl(me))
    cport = cs.start()
    nodes, servers, flags = {}, [cs], []
    for i, sid in enumerate(stores):
        n = StoreNode(sid, transport, control, raft_kw={"seed": seed + i},
                      snapshot_root=f"{snapdir}/{sid}")
        srv = DingoServer()
        srv.host_store_role(n)
        port = srv.start()
        n.start_heartbeat(0.1)
        nodes[sid] = n
        servers.append(srv)
        flags += ["--store", f"{sid}=127.0.0.1:{port}"]
    base = ["--coordinator", f"127.0.0.1:{cport}"] + flags
    return base, nodes, servers


def test_remote_br_backup_restore_and_dump(tmp_path, capsys):
    """br backup fans RegionExport over the cluster, restore re-creates
    the regions in a FRESH cluster and pushes data to every peer; dump
    region/inspect give operators artifact visibility (reference src/br/
    + client_v2 dump tools)."""
    import os

    from dingo_tpu.client.cli import main

    base, nodes, servers = _mk_grpc_cluster(seed=0, snapdir=str(tmp_path / "snapA"))
    bdir = str(tmp_path / "bk")
    try:
        assert main(base + ["region", "create-index", "--dim", "8"]) == 0
        rid = json.loads(
            capsys.readouterr().out.strip().splitlines()[-1])["region_id"]
        time.sleep(1.0)
        assert main(base + ["vector", "add-random", "--dim", "8",
                            "--count", "60"]) == 0
        capsys.readouterr()

        # dump region -> inspect
        dumpf = str(tmp_path / "r.data")
        assert main(base + ["dump", "region", "--region", str(rid),
                            "--out", dumpf]) == 0
        capsys.readouterr()
        assert main(base + ["dump", "inspect", "--file", dumpf,
                            "--keys", "2"]) == 0
        info = json.loads(capsys.readouterr().out)
        assert any(cf["keys"] > 0 for cf in info.values())

        # index snapshot inspection
        assert main(base + ["dump", "index-snapshot", "--store", "s0",
                            "--region", str(rid)]) == 0
        snap = json.loads(capsys.readouterr().out)
        assert snap["files"], snap

        # a table whose meta must survive the restore
        assert main(base + ["meta", "create-table", "--dim", "8",
                            "tbl_br"]) == 0
        capsys.readouterr()

        # backup (writes progress.json + per-region artifacts)
        assert main(base + ["br", "backup", "--dir", bdir]) == 0
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["regions"] >= 1
        progress = json.load(open(os.path.join(bdir, "progress.json")))
        assert all(e["status"] == "done" for e in progress.values())

        # resumability: corrupt ONE artifact; a resumed backup re-pulls
        # only it (other artifacts untouched by mtime)
        files = sorted(f for f in os.listdir(bdir)
                       if f.startswith("region_"))
        victim = os.path.join(bdir, files[0])
        open(victim, "wb").write(b"garbage")
        mtimes = {f: os.path.getmtime(os.path.join(bdir, f))
                  for f in files[1:]}
        time.sleep(0.05)
        assert main(base + ["br", "backup", "--dir", bdir]) == 0
        capsys.readouterr()
        assert open(victim, "rb").read() != b"garbage"   # re-pulled
        for f, mt in mtimes.items():
            assert os.path.getmtime(os.path.join(bdir, f)) == mt  # skipped
    finally:
        for s in servers:
            s.stop()
        for n in nodes.values():
            n.stop()

    # restore into a FRESH cluster
    base2, nodes2, servers2 = _mk_grpc_cluster(seed=10, snapdir=str(tmp_path / "snapB"))
    try:
        assert main(base2 + ["br", "restore", "--dir", bdir]) == 0
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["restored_regions"] >= 1
        deadline = time.monotonic() + 3
        count = None
        while time.monotonic() < deadline:
            assert main(base2 + ["vector", "count"]) == 0
            count = capsys.readouterr().out.strip().splitlines()[-1]
            if count == "60":
                break
            time.sleep(0.1)
        assert count == "60"
        assert main(base2 + ["vector", "search-random", "--dim", "8"]) == 0
        hits = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert len(hits) == 5
        # table meta came back with partitions remapped to live regions
        assert main(base2 + ["meta", "table", "tbl_br"]) == 0
        t = json.loads(capsys.readouterr().out)
        assert t["name"] == "tbl_br" and t["partitions"]
        from dingo_tpu.client.client import DingoClient as _DC
        import re as _re
        coord = base2[base2.index("--coordinator") + 1]
        stores = dict(s.split("=", 1) for s in base2[3::2] if "=" in s)
        c2 = _DC(coord, stores)
        try:
            c2.refresh_region_map()
            live_ids = {d.region_id for d in c2._regions}
            assert all(p["region_id"] in live_ids for p in t["partitions"])
        finally:
            c2.close()
    finally:
        for s in servers2:
            s.stop()
        for n in nodes2.values():
            n.stop()


def test_cli_repl_smoke(tmp_path, capsys, monkeypatch):
    """REPL parses group commands, survives bad input, and exits cleanly
    (client_v2 interactive mode analog)."""
    from dingo_tpu.client.cli import main

    base, nodes, servers = _mk_grpc_cluster(
        seed=21, snapdir=str(tmp_path / "snap"), stores=("s0",))
    try:
        lines = iter([
            "coordinator hello",
            "bogus nonsense here",     # parse error must not kill the loop
            "coordinator tso",
            "exit",
        ])
        monkeypatch.setattr("builtins.input", lambda *_: next(lines))
        assert main(base + ["repl"]) == 0
        out = capsys.readouterr().out
        assert '"stores": 1' in out       # hello answered
        assert "error:" not in out        # tso answered too (the REPL's
        # blanket handler would swallow a failure into an 'error:' line)
        assert out.count("dingo>") == 0   # prompt goes through input()
    finally:
        for s in servers:
            s.stop()
        for n in nodes.values():
            n.stop()
