"""Expression VM for pushdown predicates (coprocessor v2).

Reference: src/coprocessor/coprocessor_v2.{h,cc} runs rel-expression
bytecode from the dingo-libexpr submodule (rel::RelRunner,
coprocessor_v2.cc:209-216). This is an original expression evaluator over
the same role: a wire-encodable expression tree evaluated against a row's
field map, with comparison, boolean, arithmetic, and membership operators.

Wire form: nested lists (JSON/pickle friendly) —
    ["and", ["ge", ["field", "age"], ["const", 21]],
            ["in", ["field", "color"], ["const", ["red", "blue"]]]]
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

_BINOPS = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "div": lambda a, b: a / b,
    "mod": lambda a, b: a % b,
    "in": lambda a, b: a in b,
}


class ExprError(ValueError):
    pass


class Expr:
    """Compiled expression (validates shape once; eval per row)."""

    def __init__(self, tree: Sequence):
        self._tree = self._validate(tree)

    @classmethod
    def _validate(cls, node) -> List:
        if not isinstance(node, (list, tuple)) or not node:
            raise ExprError(f"bad expr node {node!r}")
        op = node[0]
        if op == "const":
            if len(node) != 2:
                raise ExprError("const takes 1 arg")
            return ["const", node[1]]
        if op == "field":
            if len(node) != 2 or not isinstance(node[1], str):
                raise ExprError("field takes a name")
            return ["field", node[1]]
        if op == "not":
            if len(node) != 2:
                raise ExprError("not takes 1 arg")
            return ["not", cls._validate(node[1])]
        if op in ("and", "or"):
            if len(node) < 3:
                raise ExprError(f"{op} takes >=2 args")
            return [op] + [cls._validate(a) for a in node[1:]]
        if op == "is_null":
            if len(node) != 2:
                raise ExprError("is_null takes 1 arg")
            return ["is_null", cls._validate(node[1])]
        if op in _BINOPS:
            if len(node) != 3:
                raise ExprError(f"{op} takes 2 args")
            return [op, cls._validate(node[1]), cls._validate(node[2])]
        raise ExprError(f"unknown op {op!r}")

    def eval(self, row: Dict[str, Any]) -> Any:
        return self._eval(self._tree, row)

    def matches(self, row: Dict[str, Any]) -> bool:
        try:
            return bool(self.eval(row))
        except TypeError:
            return False   # type-mismatched comparisons filter the row out

    @classmethod
    def _eval(cls, node: List, row: Dict[str, Any]) -> Any:
        op = node[0]
        if op == "const":
            return node[1]
        if op == "field":
            return row.get(node[1])
        if op == "not":
            return not cls._eval(node[1], row)
        if op == "and":
            return all(cls._eval(a, row) for a in node[1:])
        if op == "or":
            return any(cls._eval(a, row) for a in node[1:])
        if op == "is_null":
            return cls._eval(node[1], row) is None
        a = cls._eval(node[1], row)
        b = cls._eval(node[2], row)
        if a is None or b is None:
            raise TypeError("null operand")
        return _BINOPS[op](a, b)


class ExprFilter:
    """ScalarFilter-compatible adapter so the VectorReader's TABLE filter
    mode and scans can take full expressions."""

    def __init__(self, tree: Sequence):
        self.expr = Expr(tree)

    def matches(self, scalar: Dict[str, Any]) -> bool:
        return self.expr.matches(scalar)

    def is_empty(self) -> bool:
        return False
