"""Serving-edge result cache + in-flight query dedupe.

Skewed (power-law) traffic re-asks the same and near-same queries; every
repeat bought a full kernel dispatch. This package closes that gap with
three rungs, each reusing machinery earlier PRs built:

- **dedupe.py** — identical query rows inside one coalescer flush
  collapse to a single kernel row fanned out to every waiter (PR 11 row
  fingerprints; the batch shrinks BEFORE padding, so the pow2 ladder and
  staging rings are untouched).
- **store.py / keys.py** — a bounded per-region result cache keyed
  ``(query fingerprint, SlotStore.mutation_version, resolved params,
  filter fingerprint)``: the version key makes invalidation structural
  (every put/remove/growth bumps it), entries hold final post-rerank
  rows so hits are byte-identical to fresh dispatch, LRU bounded by
  ``cache.max_bytes`` with per-tenant fairness.
- **policy.py / edge.py** — tier gates and the services.py glue: hits
  are consulted at admission (before QoS queuing — a hit costs no queue
  slot), a "serve-slightly-stale" rung opens only while the shed ladder
  is degraded, and optional sq8-semantic hits (PR 4 codec) serve only
  while the PR 9 shadow-quality estimator attests the recall SLO.

Everything is host-side: a cache lookup can never introduce a device
sync on the admission path (dingolint's host-sync checker roots this
package to enforce exactly that).

Off by default (``cache.enabled``); one flag read when off.
"""

from dingo_tpu.cache.dedupe import DedupePlan, build_plan, deduped_rows
from dingo_tpu.cache.edge import (
    CACHE,
    CODECS,
    EdgeLookup,
    active,
    fill,
    index_version,
    lookup,
    region_version,
)
from dingo_tpu.cache.keys import (
    SemanticCodec,
    params_seed,
    query_fingerprints,
    semantic_fingerprints,
)
from dingo_tpu.cache.policy import (
    cache_enabled,
    dedupe_enabled,
    semantic_allowed,
    stale_versions_allowed,
)
from dingo_tpu.cache.store import ResultCache

__all__ = [
    "CACHE",
    "CODECS",
    "DedupePlan",
    "EdgeLookup",
    "ResultCache",
    "SemanticCodec",
    "active",
    "build_plan",
    "cache_enabled",
    "dedupe_enabled",
    "deduped_rows",
    "fill",
    "index_version",
    "lookup",
    "params_seed",
    "query_fingerprints",
    "region_version",
    "semantic_allowed",
    "semantic_fingerprints",
    "stale_versions_allowed",
]
