"""TpuShardedIvfPq: an IVF_PQ region sharded over a jax.sharding.Mesh.

Closes the round-2 VERDICT gap chain (next #3): with FLAT and IVF_FLAT
already mesh-sharded, this carries the last BASELINE config-5 index type
so a multi-region hybrid IVF_PQ deployment (10M x 768, scalar
post-filter) can span devices end-to-end.

Design (reference analog: region scatter-gather, SURVEY §7 step 8; PQ
contract src/vector/vector_index_ivf_pq.cc):

  rows/coarse — inherited from TpuShardedIvfFlat: global slot space,
            distributed Lloyd k-means, replicated centroids, per-shard
            skew-proof spill buckets.
  codes   — [S*cap, m] uint8 DEVICE-resident, sharded over "data" like
            the rows; encoding (residual argmin over codebooks) runs as
            one shard_map program so no vector ever crosses shards.
  search  — ONE jit'd shard_map program per shard: coarse-probe the
            replicated centroids, ADC-scan the shard's probed code
            buckets (reusing the single-device `_ivfpq_scan_kernel`),
            take the ADC top-k' candidates, then EXACT-rerank them
            shard-locally — the candidate rows live in this shard's HBM,
            so the rerank is a [b, k', d] einsum with no host round-trip
            (the single-device index must rerank on the host because its
            10M rows only fit in host memory; sharded over the mesh the
            rows fit in device HBM, which is the point) — and finally
            all_gather + merge exact-scored candidates over "data".

The ADC prune + local exact rerank means recall matches the exact
rerank quality of the host-vectors path while keeping the whole search
on-device.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from dingo_tpu.parallel.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from dingo_tpu.common.config import FLAGS
from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    InvalidParameter,
    NotTrained,
)
from dingo_tpu.index.ivf_flat import coarse_probes
from dingo_tpu.index.ivf_pq import MAX_POINTS_PER_CENTROID, _ivfpq_scan_kernel
from dingo_tpu.index.ivf_layout import expand_probes_ranked
from dingo_tpu.ops.distance import Metric
from dingo_tpu.ops.kmeans import kmeans_assign
from dingo_tpu.ops.pq import pairwise_l2sqr, pq_train, split_subvectors
from dingo_tpu.obs.sentinel import sentinel_jit
from dingo_tpu.ops.topk import merge_sharded_topk
from dingo_tpu.parallel.sharded_ivf import TpuShardedIvfFlat
from dingo_tpu.parallel.sharded_store import (
    account_merge,
    batch_spec,
    pad_query_batch,
)


def _encode_codes(vecs, assign, centroids, codebooks, m):
    """Residual PQ encode -> [n, m] uint8 (rows with assign -1 get 0).
    The ONE encoding pipeline — train-time re-encode and incremental
    upsert must quantize identically or post-train rows silently lose
    recall."""
    safe = jnp.maximum(assign, 0)
    resid = vecs - jnp.take(centroids, safe, axis=0)
    subs = split_subvectors(resid, m)               # [m, n, dsub]

    def enc_one(sub, cb):
        return jnp.argmin(pairwise_l2sqr(sub, cb), axis=1)

    codes = jax.vmap(enc_one)(subs, codebooks).T.astype(jnp.uint8)
    return jnp.where((assign >= 0)[:, None], codes, 0)


@dataclasses.dataclass
class _PqShardedView:
    """Stacked per-shard code-bucket layout, device-resident."""

    cap_list: int
    max_spill: int
    nbuckets: int
    code_buckets: jax.Array       # [S, B, cap_list, m] uint8  P("data")
    bucket_valid: jax.Array       # [S, B, cap_list] bool
    bucket_slot: jax.Array        # [S, B, cap_list] int32 (shard-LOCAL slot)
    bucket_slot_h: np.ndarray     # host copy for filter masking
    probe_table: jax.Array        # [S, nlist, max_spill] int32
    bucket_coarse: jax.Array      # [S, B] int32


class TpuShardedIvfPq(TpuShardedIvfFlat):
    """Mesh-sharded IVF_PQ (reference VectorIndexIvfPq contract)."""

    def __init__(self, index_id: int, parameter: IndexParameter,
                 mesh=None):
        p = parameter
        if p.nsubvector <= 0 or p.dimension % p.nsubvector:
            raise InvalidParameter(
                f"dimension {p.dimension} not divisible by m={p.nsubvector}"
            )
        if p.nbits_per_idx != 8:
            raise InvalidParameter("only nbits=8 supported (uint8 codes)")
        self.m = p.nsubvector
        self.ksub = 1 << p.nbits_per_idx
        self.codebooks: Optional[jax.Array] = None     # [m, ksub, dsub]
        self._codes: Optional[jax.Array] = None        # [S*cap, m] uint8
        self._pq_view: Optional[_PqShardedView] = None
        #: cached per-instance programs (built lazily: their out_shardings
        #: capture self.mesh) — a fresh jax.jit per call would re-trace
        #: every invocation and hide the compiles from the sentinel
        self._code_update_jit = None
        self._gather_rows_jit = None
        super().__init__(index_id, parameter, mesh)
        self._build_pq_programs()

    # -- allocation: codes grow with the gslot space -------------------------
    def _alloc(self, cap: int) -> None:
        old_cap = self.cap_per_shard
        super()._alloc(cap)
        if self._codes is None:
            return   # codes exist only after _encode_all/load (cap > 0)
        S, m = self.n_shards, self.m
        sh = NamedSharding(self.mesh, P("data", None))
        pad = cap - old_cap

        def grow(c):
            c = c.reshape(S, old_cap, m)
            c = jnp.pad(c, ((0, 0), (0, pad), (0, 0)))
            return c.reshape(S * cap, m)

        # growth cannot donate (output larger than input — no aliasing);
        # sentinel-wrapped so the per-(old_cap, cap) compile is accounted
        self._codes = sentinel_jit(
            "parallel.pq.grow_codes", grow, out_shardings=sh
        )(self._codes)

    # -- programs ------------------------------------------------------------
    def _build_pq_programs(self) -> None:
        mesh = self.mesh
        m = self.m
        metric = self.metric

        def encode_local(vecs, assign, centroids, codebooks):
            # vecs [cap, d], assign [cap] int32 (-1 unassigned)
            return _encode_codes(vecs, assign, centroids, codebooks, m)

        self._encode_all_jit = sentinel_jit("parallel.pq.encode_all", shard_map(
            encode_local, mesh=mesh,
            in_specs=(P("data", None), P("data"), P(None, None),
                      P(None, None, None)),
            out_specs=P("data", None),
            check_vma=False,
        ))

        def gather_codes_local(codes, gidx):
            return jnp.take(codes, gidx[0], axis=0)[None]

        def gather_codes_fn(codes, gidx, B, cap_list):
            f = shard_map(
                gather_codes_local, mesh=mesh,
                in_specs=(P("data", None), P("data", None)),
                out_specs=P("data", None, None),
                check_vma=False,
            )
            out = f(codes, gidx)
            S = mesh.shape["data"]
            return out.reshape(S, B, cap_list, m)

        self._gather_codes_jit = sentinel_jit(
            "parallel.pq.gather_codes",
            gather_codes_fn, static_argnames=("B", "cap_list")
        )

        def local_search(codebkts, bval, bslot, bcoarse, ptable, vecs,
                         sqnorm, centroids, c_sq, codebooks, queries, cap,
                         *, k, kprime, nprobe, max_spill, precompute_lut):
            codebkts, bval, bslot, bcoarse, ptable = (
                a[0] for a in (codebkts, bval, bslot, bcoarse, ptable)
            )
            probes = coarse_probes(queries, centroids, c_sq, nprobe)
            vprobes, cpos = expand_probes_ranked(
                probes, ptable, nprobe, max_spill
            )
            _, slots = _ivfpq_scan_kernel(
                codebkts, bval, bslot, bcoarse, probes, vprobes, cpos,
                queries, centroids, codebooks,
                k=kprime, precompute_lut=precompute_lut,
            )                                          # slots [b, kprime]
            # exact rerank: the candidate rows are THIS shard's — one take
            safe = jnp.maximum(slots, 0)
            rows = jnp.take(vecs, safe, axis=0)        # [b, kprime, d]
            rsq = jnp.take(sqnorm, safe)               # [b, kprime]
            dots = jnp.einsum(
                "bkd,bd->bk", rows, queries,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            if metric is Metric.L2:
                qsq = jnp.einsum(
                    "bd,bd->b", queries, queries,
                    precision=jax.lax.Precision.HIGHEST,
                )
                score = -(qsq[:, None] - 2.0 * dots + rsq)
            else:   # IP / cosine (rows+queries normalized at ingest)
                score = dots
            score = jnp.where(slots >= 0, score, -jnp.inf)
            vals, idx = jax.lax.top_k(score, min(k, score.shape[1]))
            sel = jnp.take_along_axis(slots, idx, axis=1)
            sel = jnp.where(jnp.isneginf(vals), -1, sel)
            shard = jax.lax.axis_index("data")
            gsl = jnp.where(sel >= 0, sel + shard * cap, -1)
            all_vals = jax.lax.all_gather(vals, "data")
            all_gsl = jax.lax.all_gather(gsl, "data")
            return merge_sharded_topk(all_vals, all_gsl, k)

        def search_fn(codebkts, bval, bslot, bcoarse, ptable, vecs, sqnorm,
                      centroids, c_sq, codebooks, queries, cap,
                      k, kprime, nprobe, max_spill, precompute_lut):
            out2 = batch_spec(mesh, None)
            f = shard_map(
                functools.partial(
                    local_search, k=k, kprime=kprime, nprobe=nprobe,
                    max_spill=max_spill, precompute_lut=precompute_lut,
                ),
                mesh=mesh,
                in_specs=(
                    P("data", None, None, None),   # code buckets
                    P("data", None, None),         # bucket_valid
                    P("data", None, None),         # bucket_slot
                    P("data", None),               # bucket_coarse
                    P("data", None, None),         # probe_table
                    P("data", None),               # vecs (rows)
                    P("data"),                     # sqnorm
                    P(None, None),                 # centroids
                    P(None),                       # c_sqnorm
                    P(None, None, None),           # codebooks
                    batch_spec(mesh, None),        # queries (batch-split)
                    P(),                           # cap scalar
                ),
                out_specs=(out2, out2),
                check_vma=False,
            )
            return f(codebkts, bval, bslot, bcoarse, ptable, vecs, sqnorm,
                     centroids, c_sq, codebooks, queries, cap)

        self._pq_search_jit = sentinel_jit(
            "parallel.pq.search",
            search_fn,
            static_argnames=(
                "k", "kprime", "nprobe", "max_spill", "precompute_lut"
            ),
        )

    # -- training ------------------------------------------------------------
    def is_trained(self) -> bool:
        return self.codebooks is not None

    def _rows_at_gslots(self, gslots: np.ndarray) -> np.ndarray:
        """Bounded replicated gather of sample rows from the sharded store
        (XLA inserts the cross-shard collective)."""
        if self._gather_rows_jit is None:
            self._gather_rows_jit = sentinel_jit(
                "parallel.pq.gather_rows",
                lambda v, i: jnp.take(v, i, axis=0),
                out_shardings=NamedSharding(self.mesh, P(None, None)),
            )
        with self._device_lock:
            out = self._gather_rows_jit(
                self._store.vecs, jnp.asarray(gslots, jnp.int32))
        return np.asarray(jax.device_get(out), np.float32)

    def train(self, vectors: Optional[np.ndarray] = None) -> None:
        if vectors is not None:
            vectors = self._prep(np.asarray(vectors, np.float32))
            if len(vectors) < max(self.nlist, self.ksub):
                raise NotTrained(
                    f"need >= {max(self.nlist, self.ksub)} train vectors, "
                    f"have {len(vectors)}"
                )
        else:
            live = int((self.ids_by_gslot >= 0).sum())
            if live < max(self.nlist, self.ksub):
                raise NotTrained(
                    f"need >= {max(self.nlist, self.ksub)} stored vectors, "
                    f"have {live}"
                )
        self.codebooks = None     # parent search must not run mid-train
        super().train(vectors)    # centroids (distributed) + _assign_h
        rng = np.random.default_rng(self.id)
        cap = MAX_POINTS_PER_CENTROID * self.nlist
        if vectors is None:
            live_slots = np.flatnonzero(self.ids_by_gslot >= 0)
            sel = live_slots if len(live_slots) <= cap else np.sort(
                rng.choice(live_slots, cap, replace=False)
            )
            sample = self._rows_at_gslots(sel)
            assign = self._assign_h[sel]
        else:
            sample = vectors if len(vectors) <= cap else vectors[
                rng.choice(len(vectors), cap, replace=False)
            ]
            assign = np.asarray(kmeans_assign(
                jnp.asarray(sample), self.centroids
            ))
        cent_h = np.asarray(jax.device_get(self.centroids))
        resid = sample - cent_h[np.maximum(assign, 0)]
        cb = pq_train(jnp.asarray(resid), m=self.m, ksub=self.ksub,
                      iters=10, seed=self.id)
        self.codebooks = jax.device_put(
            cb, NamedSharding(self.mesh, P(None, None, None))
        )
        self._encode_all()
        self._view_dirty = True

    def _encode_all(self) -> None:
        """(Re)encode every stored row, one shard_map pass, codes sharded."""
        assign_dev = jax.device_put(
            jnp.asarray(self._assign_h, jnp.int32),
            NamedSharding(self.mesh, P("data")),
        )
        with self._device_lock:
            self._codes = self._encode_all_jit(
                self._store.vecs, assign_dev, self.centroids, self.codebooks
            )

    # -- mutation ------------------------------------------------------------
    def upsert(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        vectors = self._prep(vectors)
        ids = np.asarray(ids, np.int64)
        if len(ids) != len(np.unique(ids)):
            last = {int(v): i for i, v in enumerate(ids)}
            keep = sorted(last.values())
            ids, vectors = ids[keep], vectors[keep]
        super().upsert(ids, vectors)
        if self.is_trained() and len(ids):
            slots = np.fromiter(
                (self._id_to_gslot[int(v)] for v in ids), np.int64, len(ids)
            )
            dv = jnp.asarray(vectors)
            assign = jnp.asarray(self._assign_h[slots], jnp.int32)
            codes = _encode_codes(
                dv, assign, self.centroids, self.codebooks, self.m
            )
            if self._code_update_jit is None:
                # cached per-instance: the old inline jax.jit(lambda...)
                # minted a FRESH wrapper per upsert, re-tracing the code
                # scatter on every trained write batch — invisibly,
                # because nothing sentinel-counted it (bare-jit lint)
                self._code_update_jit = sentinel_jit(
                    "parallel.pq.code_update",
                    lambda c, s, v: c.at[s].set(v),
                    out_shardings=NamedSharding(self.mesh,
                                                P("data", None)),
                    donate_argnums=0,
                )
            with self._device_lock:
                self._codes = self._code_update_jit(
                    self._codes, jnp.asarray(slots, jnp.int32), codes)
        self._view_dirty = True

    # -- bucketed view -------------------------------------------------------
    def _rebuild_view(self) -> None:
        (cap_list, spill, B, bucket_slot, bucket_valid, probe_table,
         gather_idx, bucket_coarse) = self._build_shard_layouts()
        sh3 = NamedSharding(self.mesh, P("data", None, None))
        sh2 = NamedSharding(self.mesh, P("data", None))
        gidx_dev = jax.device_put(gather_idx, sh2)
        with self._device_lock:
            code_buckets = self._gather_codes_jit(
                self._codes, gidx_dev, B=B, cap_list=cap_list
            )
        self._pq_view = _PqShardedView(
            cap_list=cap_list,
            max_spill=spill,
            nbuckets=B,
            code_buckets=code_buckets,
            bucket_valid=jax.device_put(bucket_valid, sh3),
            bucket_slot=jax.device_put(bucket_slot, sh3),
            bucket_slot_h=bucket_slot,
            probe_table=jax.device_put(probe_table, sh3),
            bucket_coarse=jax.device_put(bucket_coarse, sh2),
        )
        self._view_dirty = False

    def _pq_bucket_valid_for_filter(
        self, filter_spec: Optional[FilterSpec]
    ):
        return self._filtered_bucket_valid(
            filter_spec, self._pq_view.bucket_valid,
            self._pq_view.bucket_slot_h,
        )

    # -- search --------------------------------------------------------------
    def search_async(self, queries, topk,
                     filter_spec: Optional[FilterSpec] = None,
                     nprobe: Optional[int] = None, **kw):
        if not self.is_trained():
            raise NotTrained("sharded IVF_PQ not trained")
        from dingo_tpu.parallel.tracing import shard_search_span

        with shard_search_span("parallel.pq.search", self.mesh) as span:
            queries = self._prep(np.atleast_2d(np.asarray(queries, np.float32)))
            b = queries.shape[0]
            nprobe = min(nprobe or self.parameter.default_nprobe, self.nlist)
            qpad = jnp.asarray(pad_query_batch(queries, self.mesh))
            k = int(topk)
            kprime = max(
                k, min(self.get_count() or k,
                       k * int(FLAGS.get("ivfpq_rerank_factor") or 1))
            )
            with self._device_lock:
                if self._view_dirty:
                    self._rebuild_view()
                view = self._pq_view
                bval = self._pq_bucket_valid_for_filter(filter_spec)
                q = jax.device_put(
                    qpad,
                    NamedSharding(self.mesh, batch_spec(self.mesh, None)),
                )
                # per-(query, coarse-list) LUT sharing is worthwhile only
                # while the [b, nprobe, m, ksub] table stays comfortably
                # in HBM
                lut_bytes = (
                    qpad.shape[0] * nprobe * self.m * self.ksub * 4
                )
                vals, gslots = self._pq_search_jit(
                    view.code_buckets, bval, view.bucket_slot,
                    view.bucket_coarse, view.probe_table,
                    self._store.vecs, self._store.sqnorm,
                    self.centroids, self._c_sqnorm, self.codebooks, q,
                    jnp.int32(self.cap_per_shard),
                    k=k, kprime=int(kprime), nprobe=int(nprobe),
                    max_spill=int(view.max_spill),
                    precompute_lut=lut_bytes <= 256 * 1024 * 1024,
                )
                ids_by_gslot = self.ids_by_gslot.copy()
            account_merge(self.mesh, int(qpad.shape[0]), k,
                          region_id=self.id)
            if span.sampled:
                span.set_attr("batch", b)
                span.set_attr("nprobe", int(nprobe))
                jax.block_until_ready((vals, gslots))
        return self._make_resolve(vals, gslots, b, ids_by_gslot)

    # -- lifecycle -----------------------------------------------------------
    def get_memory_size(self) -> int:
        return int(
            self.total_slots * (self.dimension * 4 + self.m)
            + self.m * self.ksub * (self.dimension // self.m) * 4
        )

    def save(self, path: str) -> None:
        super().save(path)       # rows + centroids + assignments + meta
        if self.is_trained():
            live = np.flatnonzero(self.ids_by_gslot >= 0)
            codes_h = np.asarray(jax.device_get(self._codes))
            np.savez(
                os.path.join(path, "sharded_pq.npz"),
                codebooks=np.asarray(jax.device_get(self.codebooks)),
                ids=self.ids_by_gslot[live],
                codes=codes_h[live],
            )
        meta_path = os.path.join(path, "meta.json")
        with open(meta_path) as f:
            meta = json.load(f)
        meta["m"] = self.m
        meta["pq_trained"] = self.is_trained()
        with open(meta_path, "w") as f:
            json.dump(meta, f)

    def load(self, path: str) -> None:
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("m") != self.m:
            raise InvalidParameter(f"snapshot m {meta.get('m')} != {self.m}")
        self.codebooks = None
        self._codes = None
        super().load(path)       # rows + centroids + assignments
        if meta.get("pq_trained"):
            data = np.load(os.path.join(path, "sharded_pq.npz"))
            self.codebooks = jax.device_put(
                jnp.asarray(data["codebooks"]),
                NamedSharding(self.mesh, P(None, None, None)),
            )
            S, cap = self.n_shards, self.cap_per_shard
            codes_h = np.zeros((S * cap, self.m), np.uint8)
            slots = np.fromiter(
                (self._id_to_gslot[int(v)] for v in data["ids"]),
                np.int64, len(data["ids"]),
            )
            codes_h[slots] = data["codes"]
            self._codes = jax.device_put(
                jnp.asarray(codes_h),
                NamedSharding(self.mesh, P("data", None)),
            )
        self._view_dirty = True
