"""Vector key codec.

Reference: src/vector/codec.{h,cc} (codec.h:28-66) — vector keys are
`prefix + partition_id + vector_id [+ scalar_key]` in big-endian so ranges
sort correctly, with encoded (memcomparable + ts) variants for the MVCC CFs;
DecodeRangeToVectorId (:75) recovers the id window from a region range.
"""

from __future__ import annotations

import struct
from typing import Optional, Tuple

VECTOR_PREFIX = b"r"
MAX_VECTOR_ID = (1 << 63) - 1


def encode_vector_key(partition_id: int, vector_id: Optional[int] = None,
                      scalar_key: bytes = b"") -> bytes:
    out = VECTOR_PREFIX + struct.pack(">q", partition_id)
    if vector_id is not None:
        out += struct.pack(">q", vector_id)
    return out + scalar_key


def decode_vector_key(key: bytes) -> Tuple[int, Optional[int], bytes]:
    """Returns (partition_id, vector_id|None, scalar_key)."""
    if not key.startswith(VECTOR_PREFIX):
        raise ValueError(f"bad vector key prefix {key[:1]!r}")
    body = key[1:]
    (partition_id,) = struct.unpack(">q", body[:8])
    if len(body) == 8:
        return partition_id, None, b""
    (vector_id,) = struct.unpack(">q", body[8:16])
    return partition_id, vector_id, body[16:]


def partition_range(partition_id: int) -> Tuple[bytes, bytes]:
    """Full key range of one partition."""
    return (
        encode_vector_key(partition_id),
        encode_vector_key(partition_id + 1),
    )


def range_to_vector_ids(start_key: bytes, end_key: bytes) -> Tuple[int, int]:
    """Region range -> [start_vector_id, end_vector_id) window
    (DecodeRangeToVectorId, codec.h:75)."""
    sp, sv, _ = decode_vector_key(start_key)
    start_id = sv if sv is not None else 0
    try:
        ep, ev, _ = decode_vector_key(end_key)
        if ev is None:
            end_id = MAX_VECTOR_ID
        else:
            end_id = ev
    except (ValueError, struct.error):
        end_id = MAX_VECTOR_ID
    return start_id, end_id
