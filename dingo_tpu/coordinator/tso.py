"""TsoControl: the cluster timestamp oracle.

Reference: src/coordinator/tso_control.{h,cc} — TsoTimestamp is physical
milliseconds + an 18-bit logical counter (tso_control.h:92,173-175),
raft-replicated; it leases BatchTs blocks to stores' TsProviders. The safety
invariant: after failover the new oracle must never re-issue timestamps, so
the high-water physical mark persists ahead of issuance (save_interval
semantics).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from dingo_tpu.common import persist
from dingo_tpu.engine.raw_engine import CF_META, RawEngine
from dingo_tpu.mvcc.ts_provider import TSO_LOGICAL_BITS, compose_ts

_KEY = b"TSO_HIGH_WATER"
#: persist the physical watermark this far ahead (ms)
SAVE_AHEAD_MS = 3000


class TsoControl:
    def __init__(self, engine: RawEngine, clock_init: bool = True):
        """clock_init=False (raft-meta mode) initializes the physical mark
        from PERSISTED state only: seeding from the local wall clock would
        let a clock-skewed leader issue timestamps above anything recorded
        in the replicated log, which a failover successor (whose state is
        exactly the applied log) could then re-issue. Deterministic mode
        takes time exclusively from the now_ms the leader stamps into each
        replicated gen_ts op."""
        self.engine = engine
        self._lock = threading.Lock()
        blob = engine.get(CF_META, _KEY)
        persisted = persist.loads(blob) if blob else 0
        # never go below the persisted watermark (failover safety)
        self._physical = max(
            persisted, int(time.time() * 1000) if clock_init else 0
        )
        self._logical = 0
        self._persisted_until = persisted
        if clock_init:
            self._save_ahead()

    def _save_ahead(self) -> None:
        target = self._physical + SAVE_AHEAD_MS
        if target > self._persisted_until:
            self.engine.put(CF_META, _KEY, persist.dumps(target))
            self._persisted_until = target

    def gen_ts(self, count: int = 1, *,
               now_ms: Optional[int] = None) -> Tuple[int, int]:
        """GenerateTso: a contiguous block [first, first+count). In
        raft-meta mode now_ms is the leader's stamp so the op applies
        identically on every replica."""
        with self._lock:
            now = now_ms if now_ms is not None else int(time.time() * 1000)
            if now > self._physical:
                self._physical = now
                self._logical = 0
            first = compose_ts(self._physical, self._logical)
            self._logical += count
            while self._logical >= (1 << TSO_LOGICAL_BITS):
                self._physical += 1
                self._logical -= 1 << TSO_LOGICAL_BITS
            self._save_ahead()
            return first, count

    def current(self) -> int:
        with self._lock:
            return compose_ts(self._physical, self._logical)

    def advance_to(self, ts: int) -> None:
        """Never hand out timestamps at or below `ts` again (restore path:
        a restored cluster must stay ahead of every ts the backed-up
        cluster issued)."""
        with self._lock:
            physical = ts >> TSO_LOGICAL_BITS
            if physical >= self._physical:
                self._physical = physical + 1
                self._logical = 0
                self._save_ahead()
