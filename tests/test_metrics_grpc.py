"""Heartbeat metrics over REAL grpc: a store beating through
RemoteHeartbeat delivers region metrics that become visible in
GetClusterStat / GetStoreMetrics on the coordinator server, including
staleness once the store stops beating (satellite: gRPC transport leg of
the metrics pipeline)."""

import time

import grpc
import numpy as np
import pytest

from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coordinator.kv_control import KvControl
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server import pb
from dingo_tpu.server.remote_heartbeat import RemoteHeartbeat
from dingo_tpu.server.rpc import DingoServer, ServiceStub
from dingo_tpu.store.node import StoreNode


@pytest.fixture()
def remote_cluster():
    meta_engine = MemEngine()
    control = CoordinatorControl(meta_engine, replication=1)
    coord_server = DingoServer()
    coord_server.host_coordinator_role(
        control, TsoControl(meta_engine), KvControl(meta_engine))
    coord_port = coord_server.start()
    addr = f"127.0.0.1:{coord_port}"

    # a store with NO in-process coordinator: it only talks grpc
    node = StoreNode("s0", LocalTransport(), coordinator=None,
                     raft_kw={"seed": 0})
    hb = RemoteHeartbeat(node, addr)
    channel = grpc.insecure_channel(addr)
    yield control, node, hb, channel
    channel.close()
    coord_server.stop()
    node.stop()


def test_remote_heartbeat_delivers_metrics(remote_cluster):
    control, node, hb, channel = remote_cluster
    hb.beat()
    definition = control.create_region(b"", b"", replication=1)
    rid = definition.region_id
    deadline = time.monotonic() + 5
    while node.get_region(rid) is None and time.monotonic() < deadline:
        hb.beat()
        time.sleep(0.05)
    region = node.get_region(rid)
    assert region is not None
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        rn = node.engine.get_node(rid)
        if rn is not None and rn.is_leader():
            break
        time.sleep(0.03)
    node.storage.kv_put(region, [(b"k1", b"v1"), (b"k2", b"v2")])

    node.metrics._latest_mono = 0.0    # next beat must collect fresh
    hb.beat()

    # metrics landed on the coordinator via the pb leg
    rows = control.get_store_metrics("s0")
    assert len(rows) == 1
    _sid, snap, _at, stale = rows[0]
    assert not stale
    assert snap.region(rid).key_count == 2
    assert snap.region(rid).is_leader

    # and are queryable over the grpc service surface
    stub = ServiceStub(channel, "ClusterStatService")
    resp = stub.GetStoreMetrics(pb.GetStoreMetricsRequest())
    assert resp.stores[0].store_id == "s0"
    assert resp.stores[0].metrics.regions[0].key_count == 2
    stat = stub.GetClusterStat(pb.GetClusterStatRequest())
    assert stat.total_key_count == 2
    srow = next(s for s in stat.stores if s.store_id == "s0")
    assert srow.key_count == 2 and not srow.metrics_stale

    # staleness: no beats for METRICS_STALE_MS -> flagged, rollups drop
    future = int(time.time() * 1000) + control.METRICS_STALE_MS + 1
    assert control.get_store_metrics("s0", now_ms=future)[0][3] is True
    assert control.cluster_metrics_rollup(now_ms=future)["key_count"] == 0


def test_debug_metrics_dump_prometheus_over_grpc(remote_cluster):
    control, node, hb, channel = remote_cluster
    # store-side DebugService is registered on the store's own server in
    # production; here exercise the coordinator-side one over the wire
    stub = ServiceStub(channel, "DebugService")
    resp = stub.MetricsDump(pb.MetricsDumpRequest(format="prometheus"))
    assert not resp.error.errcode
    from tests.test_store_metrics import parse_prometheus

    parse_prometheus(resp.json)   # every line must obey the text format
    resp = stub.MetricsDump(pb.MetricsDumpRequest())
    import json

    json.loads(resp.json)         # default stays the /vars JSON dump
