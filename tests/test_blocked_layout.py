"""Dimension-blocked (PDX vertical) layout: flat <-> blocked round-trips,
the SlotStore scan mirror under in-place writes/tombstones/growth, and
snapshot round-trips carrying layout metadata."""

import numpy as np
import jax.numpy as jnp
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.index.slot_store import SlotStore, SqSlotStore
from dingo_tpu.ops.blocked import (
    block_sqnorms,
    bucket_block_sqnorms,
    from_blocked,
    query_prefix_sqnorms,
    resolve_dim_block,
    to_blocked,
)


@pytest.fixture
def small_dim_block():
    FLAGS.set("ivf_dim_block", 8)
    yield
    FLAGS.set("ivf_dim_block", 128)


def test_round_trip_bit_exact():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((37, 48)).astype(np.float32)
    for dblk in (8, 16, 48):
        blk = to_blocked(x, dblk)
        assert blk.shape == (48 // dblk if 48 % dblk == 0 else -(-48 // dblk),
                             37, dblk)
        np.testing.assert_array_equal(from_blocked(blk, 48), x)
    # non-divisible dimension: zero-padded trailing block, still bit-exact
    blk = to_blocked(x[:, :42], 16)
    assert blk.shape == (3, 37, 16)
    np.testing.assert_array_equal(from_blocked(blk, 42), x[:, :42])
    # device arrays round-trip too
    xd = jnp.asarray(x)
    np.testing.assert_array_equal(
        np.asarray(from_blocked(to_blocked(xd, 16), 48)), x
    )


def test_block_norm_helpers_consistent():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((20, 32)).astype(np.float32)
    bsq = block_sqnorms(x, 8)                       # [4, 20]
    np.testing.assert_allclose(bsq.sum(axis=0), (x ** 2).sum(axis=1),
                               rtol=1e-5)
    pref = np.asarray(query_prefix_sqnorms(jnp.asarray(x), 8))  # [20, 4]
    np.testing.assert_allclose(pref[:, -1], (x ** 2).sum(axis=1), rtol=1e-5)
    np.testing.assert_allclose(pref.T, np.cumsum(bsq, axis=0), rtol=1e-5)
    buckets = x.reshape(2, 10, 32)
    bb = np.asarray(bucket_block_sqnorms(jnp.asarray(buckets), 8))
    np.testing.assert_allclose(bb.sum(axis=1), (buckets ** 2).sum(axis=2),
                               rtol=1e-5)


def test_resolve_dim_block_gates():
    assert resolve_dim_block(768, 128) == 128
    assert resolve_dim_block(128, 128) is None      # single block: no prune
    assert resolve_dim_block(100, 32) is None       # doesn't tile
    assert resolve_dim_block(64, 0) is None         # disabled


def test_blocked_store_mirror_append_and_tombstone(small_dim_block):
    rng = np.random.default_rng(2)
    store = SlotStore(32, capacity=4096, blocked=True)
    assert store.dim_block == 8 and store.nblk == 4
    v = rng.standard_normal((300, 32)).astype(np.float32)
    store.put(np.arange(300, dtype=np.int64), v)
    # mirror matches the flat ground truth bit-for-bit
    np.testing.assert_array_equal(
        np.asarray(from_blocked(store.vecs_blk, 32))[:300],
        np.asarray(store.vecs[:300]),
    )
    # overwrite + scattered update keeps the mirror in sync
    sel = np.array([5, 17, 250], np.int64)
    v2 = rng.standard_normal((3, 32)).astype(np.float32)
    store.put(sel, v2)
    slots = store.slots_of(sel)
    got = np.asarray(from_blocked(store.vecs_blk, 32))[slots]
    np.testing.assert_array_equal(got, v2)
    # per-block norms track the stored rows
    np.testing.assert_allclose(
        np.asarray(store.bsq_blk)[:, slots], block_sqnorms(v2, 8), rtol=1e-5
    )
    # tombstone: host bitmap only — mirror rows go stale but masked
    store.remove(np.array([5], np.int64))
    assert not store.valid_h[slots[0]]
    # growth preserves mirror content
    store.put(np.arange(300, 5000, dtype=np.int64),
              rng.standard_normal((4700, 32)).astype(np.float32))
    assert store.vecs_blk.shape[1] == store.capacity
    np.testing.assert_array_equal(
        np.asarray(from_blocked(store.vecs_blk, 32))[slots[1]], v2[1]
    )


def test_blocked_sq_store_codes_and_decoded_norms(small_dim_block):
    rng = np.random.default_rng(3)
    store = SqSlotStore(32, capacity=4096, blocked=True)
    v = rng.standard_normal((200, 32)).astype(np.float32)
    store.put(np.arange(200, dtype=np.int64), v)
    codes = np.asarray(store.vecs[:200])
    np.testing.assert_array_equal(
        np.asarray(from_blocked(store.vecs_blk, 32))[:200], codes
    )
    deq = store.decode(codes)
    np.testing.assert_allclose(
        np.asarray(store.bsq_blk)[:, :200], block_sqnorms(deq, 8), rtol=1e-5
    )


def test_binary_and_host_stores_skip_mirror(small_dim_block):
    from dingo_tpu.index.slot_store import HostSlotStore

    assert SlotStore(32, jnp.int8, blocked=True).vecs_blk is None
    assert HostSlotStore(32, blocked=True).vecs_blk is None


def test_snapshot_round_trip_with_layout_metadata(tmp_path,
                                                  small_dim_block):
    import json
    import os

    from dingo_tpu.index.flat import TpuFlat

    rng = np.random.default_rng(4)
    x = rng.standard_normal((500, 32)).astype(np.float32)
    ids = np.arange(500, dtype=np.int64)
    FLAGS.set("vector_blocked_layout", True)
    try:
        idx = TpuFlat(1, IndexParameter(index_type=IndexType.FLAT,
                                        dimension=32))
        idx.upsert(ids, x)
        assert idx.store.vecs_blk is not None
        want = [list(r.ids) for r in idx.search(x[:4], 5)]
        idx.save(str(tmp_path))
        with open(os.path.join(str(tmp_path), "meta.json")) as f:
            meta = json.load(f)
        assert meta["blocked_layout"] is True and meta["dim_block"] == 8
        idx2 = TpuFlat(1, IndexParameter(index_type=IndexType.FLAT,
                                         dimension=32))
        idx2.load(str(tmp_path))
        # the mirror rebuilds at load time and rows restore bit-exactly
        assert idx2.store.vecs_blk is not None
        np.testing.assert_array_equal(
            np.asarray(from_blocked(idx2.store.vecs_blk, 32))[:500],
            np.asarray(idx2.store.vecs[:500]),
        )
        assert [list(r.ids) for r in idx2.search(x[:4], 5)] == want
    finally:
        FLAGS.set("vector_blocked_layout", "auto")
