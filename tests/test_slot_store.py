"""SlotStore regression tests, including code-review findings:
capacity-boundary write windows and in-flight slot reclamation."""

import numpy as np
import jax.numpy as jnp
import pytest

from dingo_tpu.index import IndexParameter, IndexType, new_index
from dingo_tpu.index.base import InvalidParameter
from dingo_tpu.index.slot_store import SlotStore


def test_capacity_boundary_write_no_corruption():
    """Regression: a pow2 write bucket reaching past capacity used to get its
    start clamped by dynamic_update_slice, shifting the write one slot off."""
    store = SlotStore(4, capacity=4096)
    ids1 = np.arange(4093, dtype=np.int64)
    v1 = np.arange(4093 * 4, dtype=np.float32).reshape(4093, 4)
    store.put(ids1, v1)
    ids2 = np.arange(4093, 4096, dtype=np.int64)
    v2 = -np.arange(1, 13, dtype=np.float32).reshape(3, 4)
    store.put(ids2, v2)
    # boundary rows and their neighbor are all intact
    found, got = store.gather(np.array([4091, 4092, 4093, 4094, 4095]))
    assert found.all()
    np.testing.assert_array_equal(got[0], v1[4091])
    np.testing.assert_array_equal(got[1], v1[4092])
    np.testing.assert_array_equal(got[2:], v2)
    # sqnorm consistent too
    sq = np.asarray(store.sqnorm)
    np.testing.assert_allclose(
        sq[4092], (v1[4092] ** 2).sum(), rtol=1e-5
    )
    np.testing.assert_allclose(sq[4095], (v2[2] ** 2).sum(), rtol=1e-5)


def test_growth_preserves_content():
    store = SlotStore(8, capacity=4096)
    rng = np.random.default_rng(0)
    v = rng.standard_normal((10000, 8)).astype(np.float32)
    store.put(np.arange(10000, dtype=np.int64), v)
    assert store.capacity >= 10000
    found, got = store.gather(np.array([0, 4095, 4096, 9999]))
    assert found.all()
    np.testing.assert_array_equal(got, v[[0, 4095, 4096, 9999]])


def test_inflight_slot_not_reused():
    """Regression: slots freed while a search is in flight must not be handed
    to new ids before the search resolves (id misattribution)."""
    idx = new_index(
        1, IndexParameter(index_type=IndexType.FLAT, dimension=4)
    )
    v = np.eye(4, dtype=np.float32)
    idx.add(np.arange(4, dtype=np.int64), v)
    thunk = idx.search_async(v[[0]], 1)
    # free slot of id 0, then insert id 99 (would reuse the slot eagerly)
    idx.delete(np.array([0], np.int64))
    idx.add(np.array([99], np.int64), v[[0]])
    slot_of_99 = idx.store.slots_of(np.array([99]))[0]
    res = thunk()
    # the in-flight search must NOT report id 99 for old slot contents
    assert 99 not in res[0].ids or slot_of_99 not in idx.store.slots_of(np.array([0]))
    # after resolve, limbo drains back to the free list
    assert idx.store._inflight == 0 and not idx.store._limbo


def test_intra_batch_duplicate_rejected():
    idx = new_index(
        1, IndexParameter(index_type=IndexType.FLAT, dimension=4)
    )
    with pytest.raises(InvalidParameter):
        idx.add(
            np.array([7, 7], np.int64), np.zeros((2, 4), np.float32)
        )


def test_metric_mismatch_on_load(tmp_path):
    from dingo_tpu.ops.distance import Metric

    idx = new_index(
        1, IndexParameter(index_type=IndexType.FLAT, dimension=4)
    )
    idx.add(np.arange(3, dtype=np.int64), np.eye(4, dtype=np.float32)[:3])
    idx.save(str(tmp_path))
    idx2 = new_index(
        1,
        IndexParameter(
            index_type=IndexType.FLAT, dimension=4, metric=Metric.COSINE
        ),
    )
    with pytest.raises(InvalidParameter):
        idx2.load(str(tmp_path))
