"""Role-based server binary (the `dingodb_server --role=...` analog,
reference src/server/main.cc:526-541).

    python -m dingo_tpu.server.main --role coordinator --port 20001 \
        --data-dir /tmp/dingo/coord
    python -m dingo_tpu.server.main --role store --id s0 --port 20011 \
        --coordinator 127.0.0.1:20001 --data-dir /tmp/dingo/s0

Startup order mirrors §3.3: config -> engine -> (coordinator: controls |
store: meta recovery -> index manager -> storage -> controllers) ->
services -> crontab schedule.

Raft traffic between processes rides the grpc raft transport
(raft/grpc_transport.py, wired below for --coor-peers deployments); the
in-process LocalTransport serves single-process multi-role runs.
"""

from __future__ import annotations

import argparse
import signal
import threading
import sys
import time

from dingo_tpu.common.config import FLAGS, Config
from dingo_tpu.common.crontab import CrontabManager
from dingo_tpu.common.stream import StreamManager
from dingo_tpu.coordinator.balance import (
    BalanceLeaderScheduler,
    BalanceRegionScheduler,
    ReplicaPlanScheduler,
)
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.coordinator.kv_control import KvControl
from dingo_tpu.coordinator.tso import TsoControl
from dingo_tpu.engine.gc import GCSafePointManager
from dingo_tpu.engine.raw_engine import MemEngine, WalEngine
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server.rpc import DingoServer
from dingo_tpu.store.checker import PreMergeChecker, PreSplitChecker
from dingo_tpu.store.node import StoreNode

_TRANSPORT = LocalTransport()   # in-process multi-role transport


def _make_engine(args):
    """Raw engine per --engine/--data-dir (an explicit durable engine
    without --data-dir is rejected in main() before reaching here)."""
    engine = getattr(args, "engine", "wal")
    if not args.data_dir:
        return MemEngine()
    if engine == "lsm":
        from dingo_tpu.engine.lsm_engine import LsmRawEngine

        return LsmRawEngine(args.data_dir)
    if engine == "mem":
        return MemEngine()
    return WalEngine(args.data_dir)


def serve_coordinator(args) -> None:
    engine = _make_engine(args)
    raft_coordinator = None
    if args.coor_peers:
        # replicated coordinator: every control mutation rides a raft group
        # (coordinator_control.h:218 SubmitMetaIncrementSync analog)
        import os

        from dingo_tpu.coordinator.raft_meta import RaftMetaCoordinator
        from dingo_tpu.raft.grpc_transport import GrpcRaftTransport
        from dingo_tpu.raft.log import RaftLog

        transport = GrpcRaftTransport(args.id,
                                      cluster_token=args.cluster_token)
        peer_ids = []
        for spec in args.coor_peers.split(","):
            cid, eq, addr = spec.strip().partition("=")
            if not eq or not cid or not addr:
                raise SystemExit(
                    f"--coor-peers: malformed entry {spec!r} "
                    "(want coor_id=host:port)"
                )
            transport.set_peer(cid.strip(), addr.strip())
            peer_ids.append(cid.strip())
        log = RaftLog(os.path.join(args.data_dir, "meta_raft.log")) \
            if args.data_dir else None
        raft_coordinator = RaftMetaCoordinator(
            args.id, peer_ids, transport, engine,
            replication=args.replication, log=log,
        )
        raft_coordinator.start()
        control = raft_coordinator.control
        tso = raft_coordinator.tso
        kv_control = raft_coordinator.kv
        meta = raft_coordinator.meta
        is_leader = raft_coordinator.is_leader
    else:
        control = CoordinatorControl(engine, replication=args.replication)
        tso = TsoControl(engine)
        kv_control = KvControl(engine)
        meta = None
        is_leader = lambda: True  # noqa: E731 — single coordinator

    server = DingoServer(args.port)
    server.host_coordinator_role(
        control, tso, kv_control, meta=meta,
        raft_transport=(raft_coordinator and transport) or None,
    )
    port = server.start()

    def when_leader(fn):
        """Crontab mutations run only on the raft leader — a follower
        proposing would just bounce with NotLeader."""
        return lambda: fn() if is_leader() else None

    crontab = CrontabManager()
    crontab.add("update_store_state", 5.0,
                when_leader(control.update_store_states))
    crontab.add("lease_gc", 10.0, when_leader(kv_control.lease_gc))
    balance_leader = BalanceLeaderScheduler(control)

    def dispatch_balance_leader():
        # balance_mode is hot-changeable — re-read per tick so an operator
        # can flip count <-> load without a restart
        balance_leader.mode = str(FLAGS.get("balance_mode"))
        return balance_leader.dispatch()

    crontab.add(
        "balance_leader", 30.0,
        when_leader(dispatch_balance_leader),
    )
    crontab.add(
        "balance_region", 60.0,
        when_leader(BalanceRegionScheduler(control).dispatch),
    )
    # replica planner reads balance_replica_mode/qps_target from FLAGS on
    # every tick (hot-changeable, no-ops while mode != auto or metrics
    # are stale), so it can always ride the crontab
    crontab.add(
        "replica_plan", 30.0,
        when_leader(ReplicaPlanScheduler(control).dispatch),
    )
    metrics_http = _maybe_metrics_http()
    crontab.start()
    print(f"coordinator {args.id} listening on 127.0.0.1:{port}"
          + (" (raft group)" if raft_coordinator else ""), flush=True)
    try:
        _wait(server, crontab)
    finally:
        if metrics_http is not None:
            metrics_http.stop()
        if raft_coordinator is not None:
            raft_coordinator.stop()


def serve_store(args) -> None:
    engine = _make_engine(args)
    if args.raft_peers:
        # multi-process replication: raft RPCs ride grpc between stores
        from dingo_tpu.raft.grpc_transport import GrpcRaftTransport

        transport = GrpcRaftTransport(args.id,
                                      cluster_token=args.cluster_token)
        for spec in args.raft_peers.split(","):
            sid, eq, addr = spec.strip().partition("=")
            if not eq or not sid or not addr:
                raise SystemExit(
                    f"--raft-peers: malformed entry {spec!r} "
                    "(want store_id=host:port)"
                )
            transport.set_peer(sid.strip(), addr.strip())
    else:
        transport = _TRANSPORT
    # single-process deployments reach the coordinator object directly; a
    # remote coordinator is reached through the grpc heartbeat below
    node = StoreNode(
        args.id, transport, coordinator=None, raw_engine=engine,
        snapshot_root=args.data_dir,
    )
    node.recover()
    gc = GCSafePointManager()
    streams = StreamManager()

    server = DingoServer(args.port)
    server.host_store_role(node)
    port = server.start()
    if args.raft_peers:
        transport.set_peer(args.id, f"127.0.0.1:{port}")

    crontab = CrontabManager()
    hb_interval = FLAGS.get("server_heartbeat_interval_s")
    if args.coordinator:
        from dingo_tpu.server.remote_heartbeat import RemoteHeartbeat

        hb = RemoteHeartbeat(node, args.coordinator)
        crontab.add("heartbeat", float(hb_interval), hb.beat, immediately=True)
    def scan_gc():
        from dingo_tpu.server.services import _SCAN_SESSIONS

        return streams.recycle_idle() + _SCAN_SESSIONS.streams.recycle_idle()

    crontab.add("scan_gc", 30.0, scan_gc)

    def run_gc():
        # advance the safe point (coordinator pull when configured, local
        # now-minus-retention otherwise), then prune MVCC versions below it
        from dingo_tpu.mvcc.ts_provider import compose_ts

        if args.coordinator:
            try:
                resp = hb._stub.GetGCSafePoint(pb_mod.GetGCSafePointRequest())
                gc.update(resp.safe_ts)
            except Exception:
                pass
        else:
            gc.update(compose_ts(
                int(time.time() * 1000) - FLAGS.get("gc_retention_ms"), 0
            ))
        return gc.gc_non_txn(node.raw)

    from dingo_tpu.server import pb as pb_mod

    crontab.add("mvcc_gc", 60.0, run_gc)
    crontab.add("split_check", 60.0,
                lambda: PreSplitChecker(node).run() if node.coordinator else None)
    scrub_worker = {"thread": None}

    def scrub_all():
        # rebuilds/saves can take minutes; run them OFF the shared crontab
        # thread so mvcc_gc/split_check keep ticking, one worker at a time
        t = scrub_worker["thread"]
        if t is not None and t.is_alive():
            return

        def work():
            for r in node.meta.get_all_regions():
                raft = node.engine.get_node(r.id)
                actions = node.index_manager.scrub(
                    r, act=True, raft_log=raft.log if raft else None
                )
                if actions.get("error"):
                    print(
                        f"scrub region {r.id}: {actions['error']}",
                        file=sys.stderr, flush=True,
                    )

        t = threading.Thread(target=work, name="scrub", daemon=True)
        scrub_worker["thread"] = t
        t.start()

    crontab.add("scrub_vector_index", 60.0, scrub_all)
    # IVF view compaction: restores the dense bucket layout once the
    # incrementally-maintained view accumulates tombstone/spill garbage —
    # off the search path (index/manager.py compact_views)
    crontab.add(
        "ivf_compact",
        float(FLAGS.get("ivf_compact_interval_s")),
        lambda: node.index_manager.compact_views(
            node.meta.get_all_regions()
        ),
    )
    # metrics collection rides its own crontab so heartbeats reuse the
    # cached snapshot instead of paying a full region sweep per beat
    crontab.add(
        "store_metrics",
        float(FLAGS.get("metrics_collect_interval_s")),
        node.metrics.collect,
        immediately=True,
    )
    # closed-loop SLO parameter controller (obs/tuner.py): one
    # cheap-to-expensive ladder step per region per tick against the live
    # recall CI from the quality plane. Hot-gated on tuner.enabled per
    # tick (the replica-planner wiring pattern), so it always rides the
    # crontab and no-ops while disabled or while estimates are stale
    from dingo_tpu.obs import QualityTunerRunner

    crontab.add(
        "quality_tuner",
        float(FLAGS.get("tuner_interval_s")),
        QualityTunerRunner(node, crontab=crontab).tick,
    )
    # graduated load shedding (obs/pressure.py): one degrade-ladder level
    # per tick per over-pressure region (drop rerank -> lower nprobe/ef ->
    # advisory sq8), one level back per calm tick. Hot-gated per tick on
    # qos.enabled + a 'degrade' shed policy (the tuner/replica-planner
    # wiring pattern), so it always rides the crontab and no-ops off
    from dingo_tpu.obs import ShedController

    crontab.add(
        "qos_shed",
        float(FLAGS.get("qos_shed_interval_s")),
        ShedController(node, crontab=crontab).tick,
    )
    # state-integrity corruption scrub (obs/integrity.py): recompute full
    # per-artifact digests from device state (chunked under the store
    # device lock) and check them against the incremental write-path
    # ledger. Hot-gated on integrity.enabled per tick; runs on its own
    # worker (the scrub_vector_index pattern) so a long chunked pass
    # never stalls the shared crontab thread
    from dingo_tpu.obs import IntegrityScrubRunner

    crontab.add(
        "consistency_scrub",
        float(FLAGS.get("integrity_scrub_interval_s")),
        IntegrityScrubRunner(node, crontab=crontab).tick,
    )
    # memory-tier ladder (index/tiering.py): one policy pass per tick —
    # demote the coldest region under HBM pressure / coordinator
    # advisory, promote a sustained-hot demoted one. Hot-gated on
    # tier.enabled per tick; transitions are full-region copies, so the
    # tick body runs on its own worker (the consistency_scrub pattern)
    # and never stalls the shared crontab thread
    from dingo_tpu.index.tiering import TierRunner

    crontab.add(
        "memory_tier",
        float(FLAGS.get("tier_interval_s")),
        TierRunner(node, crontab=crontab).tick,
    )
    # device-runtime observability: process HBM watermark poll (per-region
    # owner ledgers refresh with each store_metrics pass) + region/index
    # config snapshots for flight-recorder bundles
    from dingo_tpu.obs import FLIGHT, HBM

    crontab.add(
        "hbm_watermark",
        float(FLAGS.get("hbm_watermark_interval_s")),
        HBM.poll_process,
        immediately=True,
    )

    def _flight_node_config():
        return {
            "store_id": node.store_id,
            "regions": {
                r.id: {
                    "type": r.definition.region_type.name,
                    "index": (
                        r.definition.index_parameter.index_type.name
                        if r.definition.index_parameter else None
                    ),
                    "leader": (
                        node.engine.get_node(r.id).is_leader()
                        if node.engine.get_node(r.id) else False
                    ),
                }
                for r in node.meta.get_all_regions()
            },
        }

    FLIGHT.config_provider = _flight_node_config
    metrics_http = _maybe_metrics_http()
    crontab.start()
    print(f"store {args.id} listening on 127.0.0.1:{port}", flush=True)
    try:
        _wait(server, crontab, node)
    finally:
        if metrics_http is not None:
            metrics_http.stop()


def _maybe_metrics_http():
    """Bind the plain-HTTP /metrics sidecar when metrics.http_port is set
    (Prometheus scrapers can't speak the grpc DebugService)."""
    port = int(FLAGS.get("metrics_http_port"))
    if not port:
        return None
    from dingo_tpu.metrics.http import MetricsHttpServer

    srv = MetricsHttpServer(port)
    bound = srv.start()
    print(f"metrics http on 127.0.0.1:{bound}/metrics", flush=True)
    return srv


def _wait(server, crontab, node=None) -> None:
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        if crontab is not None:
            crontab.stop()
        server.stop()
        if node is not None:
            node.stop()


def serve_diskann(args) -> None:
    """--role=diskann: the separate build/search server (main.cc:1340)."""
    import tempfile

    from dingo_tpu.diskann.item import DiskAnnItemManager

    root = args.data_dir or tempfile.mkdtemp(prefix="dingo-diskann-")
    manager = DiskAnnItemManager(root)
    server = DingoServer(args.port)
    server.host_diskann_role(manager)
    port = server.start()
    print(f"diskann server on 127.0.0.1:{port} data={root}", flush=True)
    try:
        _wait(server, None)
    finally:
        manager.stop()
        server.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dingo-server")
    p.add_argument("--role",
                   choices=["coordinator", "store", "index", "diskann"],
                   required=True)
    p.add_argument("--id", default="s0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--coordinator", default="")
    p.add_argument("--data-dir", default="")
    p.add_argument("--engine", choices=["mem", "wal", "lsm"], default=None,
                   help="raw KV engine when --data-dir is set (default wal; "
                        "lsm = native C++ LSM, the RocksRawEngine analog)")
    p.add_argument("--replication", type=int, default=3)
    p.add_argument("--config", default="")
    p.add_argument("--cluster-token", default="",
                   help="shared secret gating the raft transport")
    p.add_argument("--raft-peers", default="",
                   help="store raft endpoints: s0=host:port,s1=host:port,...")
    p.add_argument("--coor-peers", default="",
                   help="coordinator raft group endpoints: "
                        "coor0=host:port,... (replicated coordinator; this "
                        "process's --id must be one of the ids)")
    args = p.parse_args(argv)
    if args.engine in ("lsm", "wal") and not args.data_dir \
            and args.role != "diskann":
        # an explicitly requested durable engine must not silently
        # downgrade to memory (None = flag not passed, default applies)
        p.error(f"--engine {args.engine} requires --data-dir")
    if args.engine is None:
        args.engine = "wal"
    if args.config:
        Config.load(args.config).apply_flag_overrides(FLAGS)
    if args.role == "coordinator":
        serve_coordinator(args)
    elif args.role == "diskann":
        serve_diskann(args)
    else:
        serve_store(args)   # store and index are one binary role-wise here
    return 0


if __name__ == "__main__":
    sys.exit(main())
