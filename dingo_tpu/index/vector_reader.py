"""VectorReader: region-local search orchestration (the query planner).

Reference: src/vector/vector_reader.{h,cc} (2,429 LoC) — VectorBatchSearch
(vector_reader.cc:439) -> SearchVector (:104) dispatches on filter mode:
  SCALAR post-filter  — over-fetch topk*10, then compare scalar data (:120-215)
  VECTOR_ID pre-filter — explicit candidate ids (:216-222, impl :830)
  SCALAR pre-filter   — scan scalar CF for candidates -> id filter (:853);
                        reads the narrow speed-up CF when it covers the
                        filter's fields (SplitVectorScalarData contract)
  TABLE filter        — coprocessor over the vector_table CF (:169-232),
                        pre (scan -> candidate ids) and post (over-fetch
                        then filter rows) variants
plus SearchAndRangeSearchWrapper (:1781) choosing index search vs
BruteForceSearch (:1873: scan region KVs in 2,048-vector batches —
FLAGS_vector_index_bruteforce_batch_count :61 — build temp flat index,
search, merge per-query top-k), and the VectorBatchQuery / GetBorderId /
ScanQuery / Count entry points (vector_reader.h:44-88).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dingo_tpu.coprocessor.scalar_filter import ScalarFilter
from dingo_tpu.engine.raw_engine import (
    CF_DEFAULT,
    CF_VECTOR_SCALAR,
    CF_VECTOR_SCALAR_SPEEDUP,
    CF_VECTOR_TABLE,
    RawEngine,
)
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    IndexType,
    NotSupported,
    NotTrained,
    SearchResult,
    VectorIndexError,
)
from dingo_tpu.index.flat import TpuFlat
from dingo_tpu.index.wrapper import VectorIndexWrapper
from dingo_tpu.mvcc.codec import MAX_TS
from dingo_tpu.mvcc.reader import Reader as MvccReader
from dingo_tpu.raft import wire
from dingo_tpu.trace import TRACER

#: FLAGS_vector_index_bruteforce_batch_count (vector_reader.cc:61)
BRUTEFORCE_BATCH = 2048
#: scalar post-filter over-fetch multiplier (vector_reader.cc:137,182)
POST_FILTER_OVERFETCH = 10
#: FLAGS_vector_max_range_search_result_count (vector_reader.cc:60)
RANGE_SEARCH_CAP = 1024


class VectorFilterMode(enum.Enum):
    """pb::common::VectorFilter."""

    NONE = "none"
    SCALAR = "scalar"          # scalar key/values must match
    VECTOR_ID = "vector_id"    # explicit candidate list
    TABLE = "table"            # coprocessor over table data


class VectorFilterType(enum.Enum):
    """pb::common::VectorFilterType."""

    QUERY_POST = "post"
    QUERY_PRE = "pre"


@dataclasses.dataclass
class VectorWithData:
    id: int
    distance: float = 0.0
    vector: Optional[np.ndarray] = None
    scalar: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class ReaderContext:
    """Engine::VectorReader::Context (engine.h:124-156)."""

    region_id: int
    partition_id: int
    start_key: bytes
    end_key: bytes
    index_wrapper: Optional[VectorIndexWrapper]
    engine: RawEngine
    read_ts: int = MAX_TS
    parameter: Optional[IndexParameter] = None

    def id_window(self) -> Tuple[int, int]:
        return vcodec.range_to_vector_ids(self.start_key, self.end_key)


def is_binary_dim_param(param) -> bool:
    """True when param describes a binary (bit-packed) index: dimension is
    in bits, rows on the wire/data-CF are dimension//8 uint8 bytes."""
    from dingo_tpu.index.base import IndexType as _IT

    return param is not None and param.index_type in (
        _IT.BINARY_FLAT, _IT.BINARY_IVF_FLAT
    )


def serialize_vector(v: np.ndarray) -> bytes:
    """Data-CF row bytes: uint8 rows (binary indexes) stay raw bit-packed
    bytes; everything else is little-endian f32."""
    v = np.asarray(v)
    if v.dtype == np.uint8:
        return v.tobytes()
    return np.asarray(v, np.float32).tobytes()


def deserialize_vector(b: bytes, dim: int, binary: bool = False) -> np.ndarray:
    if binary:
        return np.frombuffer(b, np.uint8, count=dim // 8)
    return np.frombuffer(b, np.float32, count=dim)


def serialize_scalar(scalar: Dict[str, Any]) -> bytes:
    return wire.encode_obj(scalar)


def deserialize_scalar(b: bytes) -> Dict[str, Any]:
    return wire.decode_obj(b)


class VectorReader:
    def __init__(self, ctx: ReaderContext):
        self.ctx = ctx
        self._data = MvccReader(ctx.engine, CF_DEFAULT)
        self._scalar = MvccReader(ctx.engine, CF_VECTOR_SCALAR)
        self._speedup = MvccReader(ctx.engine, CF_VECTOR_SCALAR_SPEEDUP)
        self._table = MvccReader(ctx.engine, CF_VECTOR_TABLE)
        self._binary = is_binary_dim_param(ctx.parameter)

    def _scalar_source(
        self, scalar_filter: Optional[ScalarFilter]
    ) -> MvccReader:
        """The narrow speed-up CF when it covers every field the filter
        reads (apply writes the flagged subset there —
        raft_apply_handler.cc:1115 via SplitVectorScalarData); the wide
        scalar CF otherwise. Match semantics are identical: a vector
        without any flagged field has no narrow row, and a filter on a
        missing field never matches."""
        keys = tuple(
            getattr(self.ctx.parameter, "scalar_speedup_keys", ()) or ()
        ) if self.ctx.parameter else ()
        if (
            keys
            and scalar_filter is not None
            and not scalar_filter.is_empty()
            and scalar_filter.fields() <= set(keys)
        ):
            return self._speedup
        return self._scalar

    def _deser(self, blob: bytes) -> np.ndarray:
        return deserialize_vector(
            blob, self.ctx.parameter.dimension, binary=self._binary
        )

    # ---------------- public entry points (vector_reader.h:44-88) ----------

    def vector_batch_search(
        self,
        queries: np.ndarray,
        topk: int,
        filter_mode: VectorFilterMode = VectorFilterMode.NONE,
        filter_type: VectorFilterType = VectorFilterType.QUERY_POST,
        **kw,
    ) -> List[List[VectorWithData]]:
        """Batch search. When `stage_us` (kw) is a dict it receives
        per-stage wall times in microseconds (prefilter/search/postfilter/
        backfill/total) — the VectorSearchDebug contract
        (vector_reader.h:85-88)."""
        with TRACER.start_span("index.search") as span:
            if span.sampled:
                span.set_attr("region_id", self.ctx.region_id)
                span.set_attr("batch", int(np.atleast_2d(queries).shape[0]))
                span.set_attr("topk", int(topk))
                span.set_attr("filter_mode", filter_mode.value)
            return self._batch_search_impl(
                queries, topk, filter_mode, filter_type, **kw
            )

    def vector_batch_search_async(
        self,
        queries: np.ndarray,
        topk: int,
        staged=None,
        stage_us: Optional[dict] = None,
        **search_kw,
    ):
        """Dispatch-now/resolve-later arm of vector_batch_search for the
        serving pipeline's coalescer: kernels enqueue here (flush
        thread), the returned thunk performs the reply's single host
        sync (completion lane). PLAIN searches only — the coalescer's
        plain-path conditions (no filters, no radius, no data backfill)
        are exactly the shapes whose whole post-kernel work is the one
        fetch. Anything that cannot stay async — degraded region,
        wrapper not ready/supported, a dispatch-time error — falls back
        to a thunk around the full sync path, which keeps its brute-
        force and OOM-recovery ladders. ``stage_us`` is filled at
        RESOLVE time: search_us there is the device wait, which the
        coalescer books as kernel time (the dispatch stage is accounted
        separately)."""
        import time as _time

        queries = np.asarray(queries,
                             np.uint8 if self._binary else np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]

        def sync_thunk():
            return self.vector_batch_search(
                queries, topk, stage_us=stage_us, **search_kw
            )

        from dingo_tpu.index.recovery import RECOVERY

        wrapper = self.ctx.index_wrapper
        if (wrapper is None or not wrapper.is_ready()
                or RECOVERY.is_degraded(self.ctx.region_id)):
            return sync_thunk
        base = FilterSpec(ranges=[self.ctx.id_window()])
        with TRACER.start_span("index.search") as span:
            if span.sampled:
                span.set_attr("region_id", self.ctx.region_id)
                span.set_attr("batch", int(queries.shape[0]))
                span.set_attr("topk", int(topk))
                span.set_attr("pipelined", True)
            try:
                thunk = wrapper.search_async(
                    queries, topk, base, staged=staged, **search_kw
                )
            except Exception:  # noqa: BLE001 — sync path re-raises real
                # errors through its own fallback/recovery ladders
                return sync_thunk

        def resolve() -> List[List[VectorWithData]]:
            t0 = _time.perf_counter_ns()
            results = thunk()
            out = [
                [VectorWithData(int(i), float(d))
                 for i, d in zip(r.ids, r.distances)]
                for r in results
            ]
            if stage_us is not None:
                total_ns = _time.perf_counter_ns() - t0
                stage_us["prefilter_us"] = 0
                stage_us["postfilter_us"] = 0
                stage_us["backfill_us"] = 0
                stage_us["search_us"] = total_ns // 1000
                stage_us["total_us"] = total_ns // 1000
            return out

        return resolve

    def _batch_search_impl(
        self,
        queries: np.ndarray,
        topk: int,
        filter_mode: VectorFilterMode = VectorFilterMode.NONE,
        filter_type: VectorFilterType = VectorFilterType.QUERY_POST,
        scalar_filter: Optional[ScalarFilter] = None,
        vector_ids: Optional[Sequence[int]] = None,
        coprocessor=None,
        with_vector_data: bool = False,
        with_scalar_data: bool = False,
        stage_us: Optional[dict] = None,
        **search_kw,
    ) -> List[List[VectorWithData]]:
        import time as _time

        t_start = _time.perf_counter_ns()
        prefilter_ns = postfilter_ns = backfill_ns = 0
        queries = np.asarray(queries, np.uint8 if self._binary else np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        base = FilterSpec(ranges=[self.ctx.id_window()])

        radius = search_kw.pop("radius", 0.0)
        if filter_mode is VectorFilterMode.VECTOR_ID:
            # pre-filter on explicit ids (vector_reader.cc:216-222, :830)
            t0 = _time.perf_counter_ns()
            ids = np.asarray(sorted(set(map(int, vector_ids or []))), np.int64)
            spec = FilterSpec(ranges=base.ranges, include_ids=ids)
            prefilter_ns = _time.perf_counter_ns() - t0
            results = self._search_with_fallback(queries, topk, spec, **search_kw)
        elif filter_mode is VectorFilterMode.SCALAR and (
            filter_type is VectorFilterType.QUERY_PRE
        ):
            # scan scalar CF for candidates (vector_reader.cc:853)
            t0 = _time.perf_counter_ns()
            cand = self._scan_scalar_candidates(scalar_filter)
            spec = FilterSpec(ranges=base.ranges, include_ids=cand)
            prefilter_ns = _time.perf_counter_ns() - t0
            results = self._search_with_fallback(queries, topk, spec, **search_kw)
        elif filter_mode is VectorFilterMode.SCALAR:
            # post-filter with x10 over-fetch (vector_reader.cc:120-215)
            over = self._search_with_fallback(
                queries, topk * POST_FILTER_OVERFETCH, base, **search_kw
            )
            t0 = _time.perf_counter_ns()
            results = [
                self._post_filter_scalar(r, scalar_filter, topk) for r in over
            ]
            postfilter_ns = _time.perf_counter_ns() - t0
        elif filter_mode is VectorFilterMode.TABLE and (
            filter_type is VectorFilterType.QUERY_PRE
        ):
            # coprocessor over the table CF -> candidate ids
            # (vector_reader.cc:169-232 TABLE dispatch, pre variant)
            t0 = _time.perf_counter_ns()
            cand = self._scan_table_candidates(coprocessor)
            spec = FilterSpec(ranges=base.ranges, include_ids=cand)
            prefilter_ns = _time.perf_counter_ns() - t0
            results = self._search_with_fallback(queries, topk, spec, **search_kw)
        elif filter_mode is VectorFilterMode.TABLE:
            # post variant: over-fetch then coprocessor-filter each
            # candidate's table row (same x10 contract as SCALAR post)
            over = self._search_with_fallback(
                queries, topk * POST_FILTER_OVERFETCH, base, **search_kw
            )
            t0 = _time.perf_counter_ns()
            results = [
                self._post_filter_table(r, coprocessor, topk) for r in over
            ]
            postfilter_ns = _time.perf_counter_ns() - t0
        else:
            results = self._search_with_fallback(queries, topk, base, **search_kw)

        if radius:
            # range-search semantics: keep hits within radius, capped at
            # RANGE_SEARCH_CAP (vector_reader.cc:60)
            results = [self._radius_cut(r, radius) for r in results]
        out: List[List[VectorWithData]] = []
        for r in results:
            row = [
                VectorWithData(int(i), float(d))
                for i, d in zip(r.ids, r.distances)
            ]
            out.append(row)
        if with_vector_data or with_scalar_data:
            t0 = _time.perf_counter_ns()
            self._backfill_many(out, with_vector_data, with_scalar_data)
            backfill_ns = _time.perf_counter_ns() - t0
        if stage_us is not None:
            total_ns = _time.perf_counter_ns() - t_start
            stage_us["prefilter_us"] = prefilter_ns // 1000
            stage_us["postfilter_us"] = postfilter_ns // 1000
            stage_us["backfill_us"] = backfill_ns // 1000
            stage_us["total_us"] = total_ns // 1000
            stage_us["search_us"] = (
                total_ns - prefilter_ns - postfilter_ns - backfill_ns
            ) // 1000
        return out

    def _radius_cut(self, r: SearchResult, radius: float) -> SearchResult:
        from dingo_tpu.ops.distance import Metric, metric_ascending

        metric = self.ctx.parameter.metric if self.ctx.parameter else Metric.L2
        keep = (r.distances <= radius) if metric_ascending(metric) \
            else (r.distances >= radius)
        return SearchResult(r.ids[keep][:RANGE_SEARCH_CAP],
                            r.distances[keep][:RANGE_SEARCH_CAP])

    def vector_batch_query(
        self,
        vector_ids: Sequence[int],
        with_vector_data: bool = True,
        with_scalar_data: bool = False,
    ) -> List[Optional[VectorWithData]]:
        keys = {
            int(vid): vcodec.encode_vector_key(self.ctx.partition_id, int(vid))
            for vid in vector_ids
        }
        data_map = self._data.kv_batch_get(keys.values(), self.ctx.read_ts)
        scalar_map = (
            self._scalar.kv_batch_get(keys.values(), self.ctx.read_ts)
            if with_scalar_data else {}
        )
        out: List[Optional[VectorWithData]] = []
        for vid in vector_ids:
            key = keys[int(vid)]
            blob = data_map.get(key)
            if blob is None:
                out.append(None)
                continue
            v = VectorWithData(int(vid))
            if with_vector_data and self.ctx.parameter:
                v.vector = self._deser(blob)
            if with_scalar_data:
                sb = scalar_map.get(key)
                v.scalar = deserialize_scalar(sb) if sb else {}
            out.append(v)
        return out

    def vector_get_border_id(self, get_min: bool) -> Optional[int]:
        """Min/max visible vector id in the region (VectorGetBorderId)."""
        mn, mx = self.vector_border_ids()
        return mn if get_min else mx

    def vector_border_ids(self):
        """(min_id, max_id) in ONE visibility scan ((None, None) when
        empty) — metrics endpoints poll this, so don't scan twice."""
        ids = self._visible_ids()
        if not ids:
            return None, None
        return min(ids), max(ids)

    def vector_scan_query(
        self,
        start_id: int,
        end_id: Optional[int] = None,
        limit: int = 1000,
        is_reverse: bool = False,
        with_vector_data: bool = True,
        with_scalar_data: bool = False,
        scalar_filter: Optional[ScalarFilter] = None,
    ) -> List[VectorWithData]:
        lo, hi = self.ctx.id_window()
        lo = max(lo, int(start_id)) if not is_reverse else lo
        if end_id is not None:
            hi = min(hi, int(end_id) + 1)
        out: List[VectorWithData] = []
        items = self._scan_data(lo, hi)
        if is_reverse:
            items = list(items)[::-1]
            items = [x for x in items if x[0] <= start_id]
        for vid, blob in items:
            v = VectorWithData(vid)
            if with_scalar_data or (scalar_filter and not scalar_filter.is_empty()):
                key = vcodec.encode_vector_key(self.ctx.partition_id, vid)
                sb = self._scalar.kv_get(key, self.ctx.read_ts)
                scalar = deserialize_scalar(sb) if sb else {}
                if scalar_filter and not scalar_filter.matches(scalar):
                    continue
                if with_scalar_data:
                    v.scalar = scalar
            if with_vector_data and self.ctx.parameter:
                v.vector = self._deser(blob)
            out.append(v)
            if len(out) >= limit:
                break
        return out

    def vector_count(self) -> int:
        return sum(1 for _ in self._scan_data(*self.ctx.id_window()))

    # ---------------- internals --------------------------------------------

    def _search_with_fallback(
        self, queries: np.ndarray, topk: int, spec: FilterSpec, **kw
    ) -> List[SearchResult]:
        """SearchAndRangeSearchWrapper (:1781): index search when the wrapper
        is ready and supports it, else brute-force scan (:1873). A
        device-degraded region (index/recovery.py) serves the exact host
        path instead; a device OOM mid-search walks the recovery ladder
        and falls back to the host path if the region degrades."""
        from dingo_tpu.index.recovery import RECOVERY, DeviceDegraded

        wrapper = self.ctx.index_wrapper
        if wrapper is not None and RECOVERY.is_degraded(self.ctx.region_id):
            return self._host_exact_search(queries, topk, spec)
        if wrapper is not None and wrapper.is_ready():
            try:
                return wrapper.search(queries, topk, spec, **kw)
            except (NotSupported, NotTrained):
                pass  # EVECTOR_NOT_SUPPORT contract -> brute force
            except Exception as e:  # noqa: BLE001 — OOM-classified below
                from dingo_tpu.obs.hbm import looks_like_oom

                if not (looks_like_oom(e) and RECOVERY.enabled()):
                    raise
                try:
                    return RECOVERY.attempt(
                        wrapper, self.ctx.region_id,
                        lambda: wrapper.search(queries, topk, spec, **kw),
                        kind="search", cause=e)
                except DeviceDegraded:
                    return self._host_exact_search(queries, topk, spec)
        return self._brute_force_search(queries, topk, spec)

    def _host_exact_search(
        self, queries: np.ndarray, topk: int, spec: FilterSpec
    ) -> List[SearchResult]:
        """Degraded-mode serving: exact scan over ENGINE rows in pure
        numpy — no device arrays at all (the brute-force path builds a
        temp DEVICE flat index, which is exactly what just OOMed). Slower,
        but full search parity: the engine is the source of truth and
        holds every acknowledged write, including those applied while the
        device index was degraded."""
        from dingo_tpu.ops.distance import Metric, metric_ascending

        param = self.ctx.parameter
        if param is None:
            raise VectorIndexError("host exact search needs index parameter")
        with TRACER.start_span("index.host_exact") as span:
            span.set_attr("region_id", self.ctx.region_id)
            lo, hi = self.ctx.id_window()
            ids_l: List[int] = []
            rows: List[np.ndarray] = []
            for vid, blob in self._scan_data(lo, hi):
                ids_l.append(vid)
                rows.append(self._deser(blob))
            span.set_attr("rows", len(ids_l))
            nq = len(queries)
            empty = SearchResult(np.empty(0, np.int64),
                                 np.empty(0, np.float32))
            if not ids_l:
                return [empty for _ in range(nq)]
            ids = np.asarray(ids_l, np.int64)
            valid = self._spec_mask(ids, spec)
            metric = param.metric
            if self._binary:
                db = np.unpackbits(np.stack(rows).astype(np.uint8), axis=1)
                qb = np.unpackbits(
                    np.asarray(queries, np.uint8).reshape(nq, -1), axis=1)
                # hamming distance via dot products over {0,1} planes
                scores = -(
                    qb @ (1 - db).T.astype(np.float32)
                    + (1 - qb) @ db.T.astype(np.float32)
                )
            else:
                vecs = np.stack(rows).astype(np.float32)
                q = np.asarray(queries, np.float32)
                if metric is Metric.L2:
                    scores = -(
                        (q ** 2).sum(1)[:, None]
                        - 2.0 * q @ vecs.T
                        + (vecs ** 2).sum(1)[None, :]
                    )
                else:
                    # COSINE rows are stored normalized (write-side prep):
                    # inner product IS the cosine similarity
                    scores = q @ vecs.T
            scores = np.where(valid[None, :], scores, -np.inf)
            kk = min(int(topk), scores.shape[1])
            part = np.argpartition(-scores, kk - 1, axis=1)[:, :kk]
            vals = np.take_along_axis(scores, part, axis=1)
            order = np.argsort(-vals, axis=1)
            part = np.take_along_axis(part, order, axis=1)
            vals = np.take_along_axis(vals, order, axis=1)
            out: List[SearchResult] = []
            for qi in range(nq):
                keep = ~np.isneginf(vals[qi])
                d = vals[qi][keep]
                d = -d if metric_ascending(metric) else d
                out.append(SearchResult(ids[part[qi][keep]],
                                        np.asarray(d, np.float32)))
            return out

    @staticmethod
    def _spec_mask(ids: np.ndarray, spec: Optional[FilterSpec]) -> np.ndarray:
        """FilterSpec evaluated against external ids (the host path has no
        slot space)."""
        mask = np.ones(len(ids), np.bool_)
        if spec is None or spec.is_empty():
            return mask
        if spec.ranges:
            rm = np.zeros(len(ids), np.bool_)
            for lo, hi in spec.ranges:
                rm |= (ids >= lo) & (ids < hi)
            mask &= rm
        if spec.include_ids is not None:
            mask &= np.isin(ids, np.asarray(spec.include_ids, np.int64))
        if spec.exclude_ids is not None:
            mask &= ~np.isin(ids, np.asarray(spec.exclude_ids, np.int64))
        return mask

    def _brute_force_search(
        self, queries: np.ndarray, topk: int, spec: FilterSpec
    ) -> List[SearchResult]:
        """Scan region data in BRUTEFORCE_BATCH chunks into a temp flat index
        (the reference builds a temp faiss flat per 2,048-vector batch and
        merges per-query top-k heaps; one TPU flat over the scan is the same
        result with fewer kernel launches)."""
        with TRACER.start_span("index.bruteforce") as span:
            out = self._brute_force_search_impl(queries, topk, spec)
            span.set_attr("batch", len(queries))
            return out

    def _brute_force_search_impl(
        self, queries: np.ndarray, topk: int, spec: FilterSpec
    ) -> List[SearchResult]:
        if self.ctx.parameter is None:
            raise VectorIndexError("brute force needs index parameter (dim)")
        dim = self.ctx.parameter.dimension
        if self._binary:
            # binary regions brute-force over a temp binary flat index
            from dingo_tpu.index.flat import TpuBinaryFlat

            param = IndexParameter(
                index_type=IndexType.BINARY_FLAT,
                dimension=dim,
                metric=self.ctx.parameter.metric,
            )
            temp = TpuBinaryFlat(self.ctx.region_id, param)
        else:
            param = IndexParameter(
                index_type=IndexType.FLAT,
                dimension=dim,
                metric=self.ctx.parameter.metric,
            )
            temp = TpuFlat(self.ctx.region_id, param)
        lo, hi = self.ctx.id_window()
        batch_ids: List[int] = []
        batch_vecs: List[np.ndarray] = []
        for vid, blob in self._scan_data(lo, hi):
            batch_ids.append(vid)
            batch_vecs.append(self._deser(blob))
            if len(batch_ids) >= BRUTEFORCE_BATCH:
                temp.upsert(np.asarray(batch_ids, np.int64), np.stack(batch_vecs))
                batch_ids, batch_vecs = [], []
        if batch_ids:
            temp.upsert(np.asarray(batch_ids, np.int64), np.stack(batch_vecs))
        if temp.get_count() == 0:
            return [SearchResult(np.empty(0, np.int64), np.empty(0, np.float32))
                    for _ in range(len(queries))]
        return temp.search(queries, topk, spec)

    def _scan_data(self, lo: int, hi: int):
        start = vcodec.encode_vector_key(self.ctx.partition_id, lo)
        end = vcodec.encode_vector_key(self.ctx.partition_id, hi)
        for key, blob in self._data.iter_visible(start, end, self.ctx.read_ts):
            _, vid, _ = vcodec.decode_vector_key(key)
            if vid is None:
                continue
            yield vid, blob

    def _visible_ids(self) -> List[int]:
        return [vid for vid, _ in self._scan_data(*self.ctx.id_window())]

    # shared skeletons for the SCALAR and TABLE filter paths: pre-filter =
    # scan a CF into a candidate id set, post-filter = keep over-fetched
    # hits whose CF row matches, stopping at topk
    def _scan_candidates(self, src: MvccReader, match) -> np.ndarray:
        lo, hi = self.ctx.id_window()
        start = vcodec.encode_vector_key(self.ctx.partition_id, lo)
        end = vcodec.encode_vector_key(self.ctx.partition_id, hi)
        out = []
        for key, blob in src.iter_visible(start, end, self.ctx.read_ts):
            _, vid, _ = vcodec.decode_vector_key(key)
            if vid is None:
                continue
            if match(blob):
                out.append(vid)
        return np.asarray(out, np.int64)

    def _post_filter(
        self, result: SearchResult, topk: int, src: MvccReader, match
    ) -> SearchResult:
        keep_ids, keep_d = [], []
        for vid, dist in zip(result.ids, result.distances):
            key = vcodec.encode_vector_key(self.ctx.partition_id, int(vid))
            blob = src.kv_get(key, self.ctx.read_ts)
            if match(blob):
                keep_ids.append(vid)
                keep_d.append(dist)
                if len(keep_ids) >= topk:
                    break
        return SearchResult(
            np.asarray(keep_ids, np.int64), np.asarray(keep_d, np.float32)
        )

    def _scan_scalar_candidates(
        self, scalar_filter: Optional[ScalarFilter]
    ) -> np.ndarray:
        src = self._scalar_source(scalar_filter)
        if scalar_filter is None:
            return self._scan_candidates(src, lambda blob: True)
        return self._scan_candidates(
            src, lambda blob: scalar_filter.matches(deserialize_scalar(blob))
        )

    def _scan_table_candidates(self, coprocessor) -> np.ndarray:
        """TABLE pre-filter: run the coprocessor's filter over every table
        row in the region (vector_reader.cc TABLE dispatch). A vector
        without a table row is never a candidate — same contract as the
        scalar pre-filter on a missing field."""
        if coprocessor is None:
            raise ValueError("TABLE filter requires a coprocessor")
        return self._scan_candidates(
            self._table,
            lambda blob: coprocessor.filter_row(coprocessor.decode(blob)),
        )

    def _post_filter_table(
        self, result: SearchResult, coprocessor, topk: int
    ) -> SearchResult:
        if coprocessor is None:
            raise ValueError("TABLE filter requires a coprocessor")
        return self._post_filter(
            result, topk, self._table,
            lambda blob: blob is not None
            and coprocessor.filter_row(coprocessor.decode(blob)),
        )

    def _post_filter_scalar(
        self,
        result: SearchResult,
        scalar_filter: Optional[ScalarFilter],
        topk: int,
    ) -> SearchResult:
        if scalar_filter is None or scalar_filter.is_empty():
            return SearchResult(result.ids[:topk], result.distances[:topk])
        return self._post_filter(
            result, topk, self._scalar_source(scalar_filter),
            lambda blob: scalar_filter.matches(
                deserialize_scalar(blob) if blob else {}
            ),
        )

    def _backfill(
        self, row: List[VectorWithData], with_vector: bool, with_scalar: bool
    ) -> None:
        """Backfill vectors/scalars from the engine by id
        (vector_reader.cc:243-266)."""
        self._backfill_many([row], with_vector, with_scalar)

    def _backfill_many(
        self,
        rows: List[List[VectorWithData]],
        with_vector: bool,
        with_scalar: bool,
    ) -> None:
        """Batched backfill over every result row at once: ONE multi-get
        per column source (data / scalar) for the whole batch instead of
        the per-id kv_get N+1 loop — batch*topk ids used to cost up to
        2*batch*topk engine point lookups per search response."""
        hits = [v for row in rows for v in row]
        if not hits:
            return
        keys = {
            v.id: vcodec.encode_vector_key(self.ctx.partition_id, v.id)
            for v in hits
        }
        data_map = (
            self._data.kv_batch_get(keys.values(), self.ctx.read_ts)
            if with_vector and self.ctx.parameter else {}
        )
        scalar_map = (
            self._scalar.kv_batch_get(keys.values(), self.ctx.read_ts)
            if with_scalar else {}
        )
        for v in hits:
            key = keys[v.id]
            if with_vector and self.ctx.parameter:
                blob = data_map.get(key)
                if blob is not None:
                    v.vector = self._deser(blob)
            if with_scalar:
                sb = scalar_map.get(key)
                v.scalar = deserialize_scalar(sb) if sb else {}
