"""Donated scatter/pad helpers for incrementally-maintained device views.

The bucketed IVF view (index/ivf_layout.py MutableIvfView) turns
upserts/deletes into O(batch) point updates of the device-resident
[B, cap_list, ...] arrays instead of an O(N) host gather + re-upload.
TPU scatter is the slow path for BULK writes (SURVEY.md measurements led
slot_store.py to contiguous dynamic_update_slice), but a serving-path
write batch touches a handful of scattered (bucket, row) coordinates —
one small scatter program beats rebuilding the whole view by ~N/batch.

Conventions shared by every helper here:
  * the destination is DONATED — callers must hold the owning store's
    device_lock across the call so a concurrent search cannot dispatch
    with the invalidated reference (same contract as slot_store._write_run);
  * update batches are padded to pow2 sizes with out-of-range indices
    (mode="drop") so the jit cache stays bounded per destination shape.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from dingo_tpu.obs.sentinel import sentinel_jit


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


#: scatter batches larger than this fall back to the caller's full-rebuild
#: path (a write that big amortizes a dense rebuild anyway)
MAX_SCATTER_BATCH = 8192


@sentinel_jit("ops.scatter.bucket_rows", donate_argnums=(0,))
def _scatter_bucket_rows(dst, b_idx, r_idx, vals):
    """dst[b_idx[i], r_idx[i]] = vals[i]; out-of-range indices dropped.

    Works for [B, cap] masks/slots (vals [n]) and [B, cap, d] data
    (vals [n, d]) alike; vals are cast to the destination dtype."""
    return dst.at[b_idx, r_idx].set(vals.astype(dst.dtype), mode="drop")


@sentinel_jit("ops.scatter.axis0", donate_argnums=(0,))
def _scatter_axis0(dst, idx, vals):
    return dst.at[idx].set(vals.astype(dst.dtype), mode="drop")


def _pad_pow2(arr, n_pad, fill):
    if isinstance(arr, jax.Array):
        pad_width = ((0, n_pad),) + ((0, 0),) * (arr.ndim - 1)
        return jnp.pad(arr, pad_width, constant_values=fill)
    arr = np.asarray(arr)
    pad = np.full((n_pad,) + arr.shape[1:], fill, arr.dtype)
    return np.concatenate([arr, pad])


def scatter_bucket_update(dst, b_idx, r_idx, vals):
    """Point-update a donated [B, cap, ...] view array at (bucket, row)
    coordinates. Batch is padded to pow2 with dropped indices; returns the
    new array (caller must rebind under its device lock)."""
    n = len(b_idx)
    if n == 0:
        return dst
    m = _next_pow2(n)
    if m != n:
        drop = dst.shape[0]          # out of range -> mode="drop"
        b_idx = _pad_pow2(np.asarray(b_idx, np.int32), m - n, drop)
        r_idx = _pad_pow2(np.asarray(r_idx, np.int32), m - n, 0)
        vals = _pad_pow2(vals, m - n, 0)
    return _scatter_bucket_rows(
        dst, jnp.asarray(b_idx, jnp.int32), jnp.asarray(r_idx, jnp.int32),
        jnp.asarray(vals),
    )


@sentinel_jit("ops.scatter.bucket_dim_rows", donate_argnums=(0,))
def _scatter_bucket_dim_rows(dst, b_idx, r_idx, vals):
    """dst[b_idx[i], :, r_idx[i]] = vals[i] for a dimension-blocked view
    array [A, n_blocks, cap, ...] (vals [n, n_blocks, ...]); out-of-range
    bucket indices dropped (the pow2 pad)."""
    blk = jnp.arange(dst.shape[1], dtype=jnp.int32)
    return dst.at[b_idx[:, None], blk[None, :], r_idx[:, None]].set(
        vals.astype(dst.dtype), mode="drop"
    )


def scatter_bucket_dim_update(dst, b_idx, r_idx, vals):
    """Point-update a donated dimension-blocked [A, n_blocks, cap, ...]
    view array at (bucket, row) coordinates — one row touches every
    dimension block. Same pow2-pad/donation contract as
    scatter_bucket_update."""
    n = len(b_idx)
    if n == 0:
        return dst
    m = _next_pow2(n)
    if m != n:
        drop = dst.shape[0]
        b_idx = _pad_pow2(np.asarray(b_idx, np.int32), m - n, drop)
        r_idx = _pad_pow2(np.asarray(r_idx, np.int32), m - n, 0)
        vals = _pad_pow2(vals, m - n, 0)
    return _scatter_bucket_dim_rows(
        dst, jnp.asarray(b_idx, jnp.int32), jnp.asarray(r_idx, jnp.int32),
        jnp.asarray(vals),
    )


def scatter_axis0_update(dst, idx, vals):
    """Point-update a donated [B, ...] array along axis 0 (bucket_coarse)."""
    n = len(idx)
    if n == 0:
        return dst
    m = _next_pow2(n)
    if m != n:
        idx = _pad_pow2(np.asarray(idx, np.int32), m - n, dst.shape[0])
        vals = _pad_pow2(vals, m - n, 0)
    return _scatter_axis0(
        dst, jnp.asarray(idx, jnp.int32), jnp.asarray(vals)
    )


def pad_buckets(arr, new_b, fill=0):
    """Grow a [B, ...] device array to [new_b, ...] (spill-bucket
    allocation outran the physical allocation). Plain concatenate: growth
    is rare (pow2-ladder alloc sizes) and stays device-side."""
    b = arr.shape[0]
    if new_b <= b:
        return arr
    pad = jnp.full((new_b - b,) + arr.shape[1:], fill, arr.dtype)
    return jnp.concatenate([arr, pad])
