"""Region runtime object + store meta manager.

Reference: store::Region (src/meta/store_meta_manager.h:57 — definition,
epoch, range, state, vector/document index wrappers) and StoreRegionMeta
persisted via TransformKvAble into the meta CF (:428). RegionChangeRecorder
(:259) keeps an audit trail of state transitions.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
import time
from typing import Dict, List, Optional, Tuple

from dingo_tpu.common import persist
from dingo_tpu.engine.raw_engine import CF_META, RawEngine
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.index.wrapper import VectorIndexWrapper
from dingo_tpu.ops.distance import Metric

# typed persistence: only registered types deserialize (common/persist.py)
persist.register(IndexParameter)
persist.register(IndexType)
persist.register(Metric)


@persist.register
class RegionState(enum.Enum):
    """pb::common::StoreRegionState."""

    NEW = "new"
    NORMAL = "normal"
    STANDBY = "standby"     # split child before switch
    SPLITTING = "splitting"
    MERGING = "merging"
    DELETING = "deleting"
    DELETED = "deleted"
    ORPHAN = "orphan"
    TOMBSTONE = "tombstone"


@persist.register
class RegionType(enum.Enum):
    STORE = "store"
    INDEX = "index"
    DOCUMENT = "document"


@persist.register
@dataclasses.dataclass
class RegionEpoch:
    """pb::common::RegionEpoch: conf_version bumps on peer changes,
    version bumps on range changes (split/merge)."""

    conf_version: int = 1
    version: int = 1

    def as_tuple(self) -> Tuple[int, int]:
        return (self.conf_version, self.version)


@persist.register
@dataclasses.dataclass
class RegionDefinition:
    """pb::common::RegionDefinition subset."""

    region_id: int
    start_key: bytes
    end_key: bytes
    partition_id: int = 0
    peers: List[int] = dataclasses.field(default_factory=list)  # store ids
    epoch: RegionEpoch = dataclasses.field(default_factory=RegionEpoch)
    region_type: RegionType = RegionType.STORE
    index_parameter: Optional[IndexParameter] = None
    #: DOCUMENT regions: column name -> type ("text"/"i64"/"f64"/"bytes"/
    #: "bool") — validated on add, backs range/eq predicates
    document_schema: Optional[Dict[str, str]] = None


class Region:
    """store::Region (store_meta_manager.h:57)."""

    def __init__(self, definition: RegionDefinition):
        self._lock = threading.RLock()
        self.definition = definition
        self.state = RegionState.NEW
        self.leader_store_id = 0
        self.vector_index_wrapper: Optional[VectorIndexWrapper] = None
        self.document_index = None   # DocumentIndex for DOCUMENT regions
        if definition.region_type is RegionType.INDEX:
            assert definition.index_parameter is not None
            self.vector_index_wrapper = VectorIndexWrapper(
                definition.region_id, definition.index_parameter
            )
        elif definition.region_type is RegionType.DOCUMENT:
            from dingo_tpu.document import DocumentIndex

            self.document_index = DocumentIndex(
                definition.region_id, schema=definition.document_schema)
        self.change_log: List[Tuple[float, str]] = []  # RegionChangeRecorder

    @property
    def id(self) -> int:
        return self.definition.region_id

    @property
    def range(self) -> Tuple[bytes, bytes]:
        return (self.definition.start_key, self.definition.end_key)

    @property
    def epoch(self) -> RegionEpoch:
        return self.definition.epoch

    def set_state(self, state: RegionState, reason: str = "") -> None:
        with self._lock:
            self.state = state
            self.change_log.append(
                (time.time(), f"{state.value}: {reason}")
            )

    def contains_key(self, key: bytes) -> bool:
        s, e = self.range
        return s <= key and (not e or key < e)

    def id_window(self) -> Tuple[int, int]:
        return vcodec.range_to_vector_ids(*self.range)

    def serialize(self) -> bytes:
        return persist.dumps(
            {"definition": self.definition, "state": self.state}
        )

    @classmethod
    def deserialize(cls, blob: bytes) -> "Region":
        d = persist.loads(blob)
        region = cls(d["definition"])
        region.state = d["state"]
        return region


_META_REGION_PREFIX = b"META_REGION_"


class StoreMetaManager:
    """Region registry persisted in the meta CF (StoreRegionMeta).

    Recovery order note: the reference initializes VectorIndexManager before
    StoreMetaManager because region recovery may trigger index loads
    (main.cc:1074-1076); our recover() takes the index manager callback for
    the same reason."""

    def __init__(self, engine: RawEngine):
        self._engine = engine
        self._lock = threading.RLock()
        self._regions: Dict[int, Region] = {}

    def add_region(self, region: Region) -> None:
        with self._lock:
            self._regions[region.id] = region
            self._persist(region)

    def update_region(self, region: Region) -> None:
        with self._lock:
            self._persist(region)

    def delete_region(self, region_id: int) -> None:
        with self._lock:
            self._regions.pop(region_id, None)
            self._engine.delete(
                CF_META, _META_REGION_PREFIX + str(region_id).encode()
            )

    def get_region(self, region_id: int) -> Optional[Region]:
        with self._lock:
            return self._regions.get(region_id)

    def get_all_regions(self) -> List[Region]:
        with self._lock:
            return list(self._regions.values())

    def _persist(self, region: Region) -> None:
        self._engine.put(
            CF_META,
            _META_REGION_PREFIX + str(region.id).encode(),
            region.serialize(),
        )

    def recover(self) -> int:
        """Reload regions from the meta CF after restart."""
        n = 0
        for key, blob in self._engine.scan(
            CF_META, _META_REGION_PREFIX, _META_REGION_PREFIX + b"\xff"
        ):
            region = Region.deserialize(blob)
            with self._lock:
                self._regions[region.id] = region
            n += 1
        return n
