"""On-chip smoke suite: the TPU-sensitive checks the CPU test suite cannot
cover (round-1 VERDICT weak #9).

    python tpu_smoke.py          # exits 0 = all good, 2 = no TPU, 1 = fail

Covers, on the real chip:
  1. flat search exactness (recall@10 == 1.0 vs numpy) + pipelined ms/batch
  2. IVF_FLAT recall + the spill-bucket layout under skew
  3. Mosaic COMPILATION of both Pallas kernels (fused flat + IVF list-DMA)
     and parity vs the XLA paths — interpret-mode tests cannot catch
     Mosaic rejections (round-1 finding: the fused kernel had never
     compiled)
  4. PQ ADC recall parity with the CPU value (precision pinning check)

Run it once per session before trusting any flag default that routes
traffic to a Pallas kernel. Keep workloads bounded; NEVER SIGKILL a
process holding the TPU (the axon lease wedges).
"""

from __future__ import annotations

import subprocess
import sys
import time


def probe_tpu(timeout_s: int = 0) -> bool:
    import os

    timeout_s = timeout_s or int(os.environ.get("DINGO_SMOKE_PROBE_S", 420))
    code = (
        "import jax; d = jax.devices(); import jax.numpy as jnp; "
        "(jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready(); "
        "print('PLATFORM=' + d[0].platform)"
    )
    try:
        p = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        print(f"no TPU: probe timed out after {timeout_s}s", file=sys.stderr)
        return False
    ok = p.returncode == 0 and (
        "PLATFORM=tpu" in p.stdout or "PLATFORM=axon" in p.stdout
    )
    if not ok:
        print(f"no TPU: rc={p.returncode} {p.stderr[-200:]!r}", file=sys.stderr)
    return ok


def main() -> int:
    if not probe_tpu():
        return 2
    from dingo_tpu.common.config import enable_compile_cache

    enable_compile_cache(lambda m: print(m, file=sys.stderr))
    import numpy as np

    from dingo_tpu.common.config import FLAGS
    from dingo_tpu.index.base import IndexParameter, IndexType
    from dingo_tpu.index.factory import new_index

    rng = np.random.default_rng(0)
    failures = []

    def check(name, fn):
        t0 = time.perf_counter()
        try:
            fn()
            print(f"PASS {name} ({time.perf_counter()-t0:.1f}s)")
        except Exception as e:  # noqa: BLE001
            failures.append((name, e))
            print(f"FAIL {name}: {type(e).__name__}: {e}")

    # ---- 1. flat exactness + speed --------------------------------------
    n, d, b, k = 100_000, 128, 64, 10
    x = rng.standard_normal((n, d), dtype=np.float32)
    ids = np.arange(n, dtype=np.int64)
    q = x[rng.choice(n, b, replace=False)]
    flat = new_index(1, IndexParameter(index_type=IndexType.FLAT, dimension=d))
    flat.store.reserve(n)
    flat.upsert(ids, x)

    def flat_exact():
        res = flat.search(q, k)
        gt_d = (
            (q ** 2).sum(1)[:, None] - 2.0 * q @ x.T + (x ** 2).sum(1)[None, :]
        )
        gt = np.argsort(gt_d, axis=1)[:, :k]
        rec = np.mean([len(set(r.ids) & set(ids[g])) / k
                       for r, g in zip(res, gt)])
        assert rec == 1.0, f"flat recall {rec} != 1.0 (precision regression?)"
        flat.search(q, k)  # warm
        t0 = time.perf_counter()
        thunks = [flat.search_async(q, k) for _ in range(50)]
        for t in thunks:
            t()
        ms = (time.perf_counter() - t0) / 50 * 1e3
        print(f"  flat 100Kx128 b{b}: {ms:.2f} ms/batch pipelined")
        assert ms < 100, f"flat pipelined {ms} ms/batch (expected ~4-5)"

    check("flat_exact_and_speed", flat_exact)

    # ---- 2+3. fused Pallas kernel compiles + parity ----------------------
    def fused_parity():
        want = [(list(r.ids), np.asarray(r.distances))
                for r in flat.search(q[:16], k)]
        FLAGS.set("use_pallas_fused_search", True)
        try:
            got = [(list(r.ids), np.asarray(r.distances))
                   for r in flat.search(q[:16], k)]
        finally:
            FLAGS.set("use_pallas_fused_search", False)
        for (ai, ad), (bi, bd) in zip(want, got):
            # set comparison: float accumulation-order ulps can swap ranks
            # of near-tied candidates between kernels — not a regression
            assert set(ai) == set(bi), f"fused ids diverge: {ai[:3]} vs {bi[:3]}"
            np.testing.assert_allclose(
                np.sort(ad), np.sort(bd), rtol=1e-3, atol=1e-2
            )

    check("pallas_fused_compiles_and_matches", fused_parity)

    # ---- IVF + list-DMA kernel ------------------------------------------
    ivf = new_index(2, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=d, ncentroids=64,
        default_nprobe=16,
    ))
    ivf.store.reserve(n)
    ivf.upsert(ids, x)
    ivf.train()

    def ivf_paths():
        base = [(list(r.ids), np.asarray(r.distances))
                for r in ivf.search(q[:16], k, nprobe=16)]
        FLAGS.set("use_pallas_ivf_search", True)
        try:
            got = [(list(r.ids), np.asarray(r.distances))
                   for r in ivf.search(q[:16], k, nprobe=16)]
        finally:
            FLAGS.set("use_pallas_ivf_search", False)
        for (ai, ad), (bi, bd) in zip(base, got):
            assert set(ai) == set(bi), \
                f"ivf list-DMA ids diverge: {ai[:3]} vs {bi[:3]}"
            np.testing.assert_allclose(
                np.sort(ad), np.sort(bd), rtol=1e-3, atol=1e-2
            )

    check("pallas_ivf_list_dma_compiles_and_matches", ivf_paths)

    # ---- 4. PQ ADC precision parity -------------------------------------
    def pq_parity():
        xs = rng.standard_normal((20_000, 128), dtype=np.float32)
        pq = new_index(3, IndexParameter(
            index_type=IndexType.IVF_PQ, dimension=128, ncentroids=64,
            nsubvector=16, default_nprobe=64,
        ))
        pq.upsert(np.arange(20_000, dtype=np.int64), xs)
        pq.train()
        qs = xs[:16] + 0.01
        res = pq.search(qs, 10, nprobe=64)
        gt_d = ((qs ** 2).sum(1)[:, None] - 2.0 * qs @ xs.T
                + (xs ** 2).sum(1)[None, :])
        gt = np.argsort(gt_d, axis=1)[:, :10]
        rec = np.mean([len(set(r.ids) & set(g)) / 10
                       for r, g in zip(res, gt)])
        # CPU-measured value for this exact setup is ~0.33; a big drop
        # means the TPU matmul precision pin regressed
        assert rec > 0.25, f"PQ recall {rec} (CPU parity ~0.33)"
        print(f"  PQ ADC recall@10 = {rec:.3f} (CPU ~0.33)")

    check("pq_adc_precision_parity", pq_parity)

    if failures:
        print(f"\n{len(failures)} smoke check(s) FAILED")
        return 1
    print("\nall TPU smoke checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
