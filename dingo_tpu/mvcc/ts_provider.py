"""Timestamp provisioning.

Reference: mvcc::TsProvider (src/mvcc/ts_provider.h:40) leases BatchTs blocks
from the coordinator's TSO oracle (src/coordinator/tso_control.h:92-175:
TsoTimestamp = physical milliseconds + 18-bit logical counter) and hands out
timestamps from the lease with a local atomic, refreshing in the background
when the block runs low.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple

TSO_LOGICAL_BITS = 18


def compose_ts(physical_ms: int, logical: int) -> int:
    return (physical_ms << TSO_LOGICAL_BITS) | logical


def decompose_ts(ts: int) -> Tuple[int, int]:
    return ts >> TSO_LOGICAL_BITS, ts & ((1 << TSO_LOGICAL_BITS) - 1)


class LocalTsOracle:
    """Standalone TSO for single-node / test deployments (the coordinator's
    TsoControl serves this role in a cluster — coordinator/tso.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last_physical = 0
        self._logical = 0

    def generate(self, count: int) -> Tuple[int, int]:
        """Returns (first_ts, count): a contiguous block."""
        with self._lock:
            now = int(time.time() * 1000)
            if now > self._last_physical:
                self._last_physical = now
                self._logical = 0
            first = compose_ts(self._last_physical, self._logical)
            self._logical += count
            # logical overflow rolls physical forward (tso_control semantics)
            while self._logical >= (1 << TSO_LOGICAL_BITS):
                self._last_physical += 1
                self._logical -= 1 << TSO_LOGICAL_BITS
            return first, count


class TsProvider:
    """Batched ts allocation with lease refill (ts_provider.h:40)."""

    def __init__(
        self,
        source: Optional[Callable[[int], Tuple[int, int]]] = None,
        batch_size: int = 8192,
    ):
        self._source = source or LocalTsOracle().generate
        self._batch = batch_size
        self._lock = threading.Lock()
        self._next = 0
        self._limit = 0

    def get_ts(self) -> int:
        with self._lock:
            if self._next >= self._limit:
                first, count = self._source(self._batch)
                self._next, self._limit = first, first + count
            ts = self._next
            self._next += 1
            return ts
