"""Failover-aware client channel to the replicated coordinator group.

One rotation protocol shared by the SDK (client/client.py) and the store's
remote heartbeat (server/remote_heartbeat.py): hold the raft group's
endpoint list and route every call through the shared RetryPolicy
(client/retry.py) — rotate on NotLeader (errcode 20001) or
connection-level grpc failure, back off with equal jitter between full
rotations (the thundering-herd fix: the old loop slept a fixed 0.2s, so
every client in the fleet re-hit a recovering leader in lockstep), skip
endpoints whose circuit breaker is open, and never outlive the request's
deadline budget.

Retry semantics: UNAVAILABLE / CANCELLED (request never served) and
DEADLINE_EXCEEDED (hung endpoint — rotating is the whole point of the
group) rotate and re-send; every other RpcError and every in-band
application error surfaces to the caller. Caveat a client cannot remove:
a re-sent call whose first attempt committed before the deadline makes
mutations at-least-once — idempotent coordinator ops (create returns
"exists", acks dedupe by cmd_id) absorb this; callers doing
non-idempotent mutations should treat an "exists" answer after a retry
as success.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Type

import grpc

from dingo_tpu.common.log import get_logger
from dingo_tpu.server.rpc import ServiceStub

_log = get_logger("coord_channel")

_ERR_NOT_LEADER = 20001


class RotatingCoordinatorChannel:
    """Thread-safe; one instance backs every coordinator-side service stub
    so a failover discovered by one call benefits the rest."""

    def __init__(self, addrs: str, error_cls: Type[Exception],
                 timeout_s: float = 10.0, rounds: int = 3,
                 policy=None):
        # deferred: client.retry lives under the client package whose
        # __init__ imports the SDK, which imports THIS module
        from dingo_tpu.client.retry import RetryPolicy

        self._addrs = [a.strip() for a in addrs.split(",") if a.strip()]
        if not self._addrs:
            raise error_cls("empty coordinator address list")
        self._error_cls = error_cls
        self._timeout_s = timeout_s
        self._active = 0
        self._lock = threading.Lock()
        self._channel: Optional[grpc.Channel] = None
        self._stubs: Dict[str, ServiceStub] = {}
        self._policy = policy if policy is not None else \
            RetryPolicy.from_flags(rounds=rounds)
        self._connect(0)

    @property
    def addrs(self):
        return list(self._addrs)

    def _connect(self, idx: int) -> None:
        if self._channel is not None:
            self._channel.close()
        self._active = idx % len(self._addrs)
        self._channel = grpc.insecure_channel(self._addrs[self._active])
        self._stubs = {}

    def _stub_for(self, service: str):
        stub = self._stubs.get(service)
        if stub is None:
            stub = self._stubs[service] = ServiceStub(self._channel, service)
        return stub

    def call(self, service: str, method: str, req,
             timeout_s: Optional[float] = None):
        """Invoke over the group via the RetryPolicy, starting from the
        last-known-good endpoint, with a per-attempt deadline (a hung
        leader must not disable rotation). Application errors other than
        NotLeader return in-band for the caller to interpret; exhaustion
        raises error_cls. The lock guards only channel state — a
        long-poll must not serialize other calls."""
        deadline = timeout_s if timeout_s is not None else self._timeout_s
        with self._lock:
            start = self._active
        n = len(self._addrs)
        # rotation order starts at the shared active endpoint: a failover
        # discovered by one thread re-points every caller
        order = [self._addrs[(start + i) % n] for i in range(n)]

        from dingo_tpu.client.retry import OK, ROTATE, attempt_metadata

        def _attempt(addr, attempt):
            idx = self._addrs.index(addr)
            with self._lock:
                if self._active != idx:
                    self._connect(idx)
                    _log.info("rotating coordinator endpoint -> %s", addr)
                stub = self._stub_for(service)
            return getattr(stub, method)(
                req, timeout=deadline,
                metadata=attempt_metadata(attempt))

        def _classify(resp):
            err = getattr(resp, "error", None)
            if err is not None and err.errcode == _ERR_NOT_LEADER:
                return (ROTATE, err.errmsg)
            return OK

        return self._policy.call(
            order, _attempt, classify=_classify,
            op=f"coordinator group: {method}",
            error_cls=self._error_cls, idempotent=True)

    def close(self) -> None:
        with self._lock:
            if self._channel is not None:
                self._channel.close()
                self._channel = None
                self._stubs = {}
