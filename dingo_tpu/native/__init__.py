"""ctypes bindings to the native C++ runtime pieces (built by native/Makefile).

The shared libraries are built on demand at import time if missing — the
environment guarantees g++ but no pip installs, so we ship sources and
compile lazily (cached .so next to this file).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

_HERE = os.path.dirname(os.path.abspath(__file__))
_NATIVE_SRC = os.path.join(os.path.dirname(os.path.dirname(_HERE)), "native")


def _build(lib: str, src: str) -> str:
    path = os.path.join(_HERE, lib)
    srcpath = os.path.join(_NATIVE_SRC, src)
    if not os.path.exists(path) or (
        os.path.exists(srcpath)
        and os.path.getmtime(srcpath) > os.path.getmtime(path)
    ):
        subprocess.run(
            [
                "g++", "-O3", "-std=c++17", "-fPIC", "-shared",
                "-march=native", srcpath, "-o", path,
            ],
            check=True,
            capture_output=True,
        )
    return path


def load_hnsw() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build("libdingohnsw.so", "hnsw/hnsw.cc"))
    c = ctypes
    lib.hnsw_new.restype = c.c_void_p
    lib.hnsw_new.argtypes = [c.c_int, c.c_int, c.c_int, c.c_int, c.c_uint64]
    lib.hnsw_free.argtypes = [c.c_void_p]
    lib.hnsw_add.argtypes = [
        c.c_void_p, c.c_int, c.POINTER(c.c_int64), c.POINTER(c.c_float),
    ]
    lib.hnsw_delete.restype = c.c_int
    lib.hnsw_delete.argtypes = [c.c_void_p, c.c_int, c.POINTER(c.c_int64)]
    lib.hnsw_search.argtypes = [
        c.c_void_p, c.c_int, c.POINTER(c.c_float), c.c_int, c.c_int,
        c.POINTER(c.c_int64), c.POINTER(c.c_float),
    ]
    lib.hnsw_count.restype = c.c_int64
    lib.hnsw_count.argtypes = [c.c_void_p]
    lib.hnsw_deleted_count.restype = c.c_int64
    lib.hnsw_deleted_count.argtypes = [c.c_void_p]
    lib.hnsw_memory.restype = c.c_int64
    lib.hnsw_memory.argtypes = [c.c_void_p]
    lib.hnsw_save_size.restype = c.c_int64
    lib.hnsw_save_size.argtypes = [c.c_void_p]
    lib.hnsw_save.restype = c.c_int64
    lib.hnsw_save.argtypes = [c.c_void_p, c.POINTER(c.c_uint8)]
    lib.hnsw_load.restype = c.c_void_p
    lib.hnsw_load.argtypes = [c.POINTER(c.c_uint8), c.c_int64]
    return lib
