"""Spill-bucket IVF layout: skew-bounded memory + probe expansion parity.

Round-1 regression: the bucketed view padded every list to the largest
list's pow2 size, so one hot list multiplied total HBM by the skew factor.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.index.ivf_flat import TpuIvfFlat
from dingo_tpu.index.ivf_layout import build_layout, expand_probes


def test_layout_memory_bounded_under_skew():
    """One list holding 50% of rows must not inflate the other 255 lists."""
    nlist, n = 64, 20_000
    assign = np.random.default_rng(0).integers(0, nlist, n).astype(np.int32)
    assign[: n // 2] = 7  # hot list
    valid = np.ones(n + 100, bool)
    valid[n:] = False
    assign = np.concatenate([assign, np.full(100, -1, np.int32)])
    lay = build_layout(assign, valid, nlist)
    total_rows = lay.nbuckets * lay.cap_list
    # bounded: data + <=1 partial bucket per list (+pow2 rounding of cap)
    assert total_rows <= n + (nlist + 1) * lay.cap_list
    # the round-1 layout would be nlist * pow2(n/2) = 64 * 16384 rows
    assert total_rows < nlist * 16384 / 4
    assert lay.max_spill > 1
    # every live slot appears exactly once
    slots = lay.bucket_slot_h[lay.bucket_slot_h >= 0]
    assert sorted(slots) == sorted(np.flatnonzero(valid & (assign >= 0)))
    # probe_table covers exactly each list's buckets
    probe = np.asarray(lay.probe_table)
    coarse = np.asarray(lay.bucket_coarse)
    for lst in (7, 0, nlist - 1):
        buckets = probe[lst][probe[lst] >= 0]
        assert (coarse[buckets] == lst).all()
        got_slots = lay.bucket_slot_h[buckets]
        got_slots = got_slots[got_slots >= 0]
        want = np.flatnonzero(valid & (assign == lst))
        assert sorted(got_slots) == sorted(want)


def test_expand_probes_rank_order_and_budget():
    nlist = 8
    assign = np.repeat(np.arange(nlist), 40).astype(np.int32)
    assign[:120] = 0  # list 0 spills
    valid = np.ones(len(assign), bool)
    lay = build_layout(assign, valid, nlist, cap_hint=32)
    assert lay.max_spill >= 2
    probes = jnp.asarray([[0, 3, 5], [5, 3, 0]], jnp.int32)
    virt = np.asarray(expand_probes(probes, lay.probe_table, 3, lay.max_spill))
    coarse = np.asarray(lay.bucket_coarse)
    for row, order in zip(virt, ([0, 3, 5], [5, 3, 0])):
        lists_seen = [coarse[v] for v in row if v >= 0]
        # rank order preserved: first occurrences follow the probe order
        firsts = [lists_seen.index(l) for l in order]
        assert firsts == sorted(firsts)
        # all probed lists' buckets present (budget not exceeded here)
        assert set(lists_seen) == set(order)


def test_ivf_flat_search_exact_under_skew():
    """Skewed corpus: searching with nprobe=nlist must equal exact search."""
    rng = np.random.default_rng(1)
    d, nlist = 24, 16
    hot = rng.standard_normal((1, d)).astype(np.float32)
    x = np.concatenate([
        hot + 0.01 * rng.standard_normal((3000, d)).astype(np.float32),
        rng.standard_normal((1000, d)).astype(np.float32) * 5,
    ])
    ids = np.arange(len(x), dtype=np.int64)
    idx = TpuIvfFlat(1, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=d, ncentroids=nlist,
    ))
    idx.upsert(ids, x)
    idx.train()
    q = x[[5, 3500]] + 0.001
    res = idx.search(q, 10, nprobe=nlist)
    d2 = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = np.argsort(d2, 1)[:, :10]
    for qi, (r, w) in enumerate(zip(res, want)):
        # near-duplicate corpus -> f32 ties at the tail; any symmetric-
        # difference member must be within tie tolerance of the 10th best
        cutoff = d2[qi, w[-1]]
        for got in set(r.ids) - set(ids[w]):
            assert d2[qi, got] <= cutoff + 1e-3, (got, d2[qi, got], cutoff)
        assert len(set(r.ids) & set(ids[w])) >= 8
