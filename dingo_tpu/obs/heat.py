"""Workload-heat plane: WHERE traffic lands, not just how much of it.

The metrics plane knows each region's QPS; nothing in the system knows
which IVF buckets, graph neighborhoods, or slot ranges that traffic
actually touches — the signal ROADMAP items 1–2 (memory tiering,
device-aware split) need before they can demote a cold region or split a
hot one on evidence instead of guesses. This module is that sensor:

- **Access sketches.** Per region, an exponential-decay sketch over
  *heat units* — IVF bucket ids on the IVF paths, fixed slot blocks
  (``SLOT_BLOCK`` rows) on FLAT/HNSW. Every unit carries a decayed touch
  mass with e-folding time ``heat.decay_s``: a unit untouched for one
  decay constant keeps 1/e of its mass. Entries are bounded at
  ``heat.max_entries`` per region; past it the coldest are evicted.
- **Zero new device syncs.** The sketches are fed ENTIRELY from arrays
  the resolve paths already hold on host: IVF appends its probed-bucket
  ids to the batch's EXISTING ``begin_host_fetch`` group (one D2H copy
  either way — dingolint's resolve-sync contract stays intact), FLAT and
  HNSW reuse the result-slot array they already fetched. The serving
  thread only appends to a bounded queue; folding, decay, eviction, and
  all derived math run on a dedicated worker (the quality-plane async
  lane). ``heat.enabled`` off = one flag read and an early return,
  nothing allocated (the sampling-off discipline).
- **Working-set estimator.** Sorting units by decayed mass and walking
  the cumulative traffic curve yields bytes-to-serve-{50,90,99}%-of-
  traffic, priced per precision tier (fp32/bf16/sq8 bytes per row) from
  a layout provider each index registers (rows per unit + its own
  tier). That curve IS the tiering decision input: a region whose p99
  working set is a sliver of its resident bytes is a demote candidate.
- **Shape.** ``heat.*`` curated family (bucket_gini, hot_fraction,
  working_set_bytes{pct,tier}, touches, entries, dropped); region
  rollups ride heartbeats (RegionMetricsSnapshot.heat_*) to the
  coordinator's capacity plane (coordinator/capacity.py) and surface in
  ``cluster top`` (HEAT/WSET), ``cluster capacity``, and flight bundles.

Sketch math: masses are stored in a *time-warped* basis — a touch at
time t adds ``exp((t - t0)/tau)`` where t0 is the region's reference
time — so a fold is O(touched units) with no rescan, and the true
decayed mass is recovered at read time by one multiply. When the warp
factor grows past ``_REBASE_WARP`` the sketch rebases (one O(n) sweep)
to keep the floats in range. See ARCHITECTURE.md "Workload heat &
capacity".
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dingo_tpu.common.log import get_logger
from dingo_tpu.common.metrics import METRICS

_log = get_logger("obs.heat")

#: FLAT/HNSW heat-unit granule: one unit = this many consecutive slots.
#: Coarse enough that a region's sketch stays small (1M rows -> 512
#: units), fine enough that a hot shard of the slot space stands out.
SLOT_BLOCK = 2048

#: pending touch batches; overflow drops (and counts) — the async lane
#: must never apply backpressure to the serving path
QUEUE_MAX = 256

#: percentiles of the traffic curve the working-set estimator prices
WS_PCTS = (50, 90, 99)

#: bytes per coordinate, per precision tier (the working-set price list)
TIER_BYTES = {"fp32": 4.0, "bf16": 2.0, "sq8": 1.0}

#: rebase the time-warped masses when the warp factor exceeds e^16
#: (~53 decay constants of uptime between O(n) sweeps at default tau)
_REBASE_WARP = 16.0

#: derived stats (gini/hot-fraction/working set) are recomputed and
#: published at most this often per region — folds are much hotter
_PUBLISH_MIN_S = 1.0

#: layout providers are polled at most this often (rows-per-bucket via
#: bincount over the host assignment array is cheap, but not per-fold)
_LAYOUT_TTL_S = 10.0


def heat_enabled() -> bool:
    from dingo_tpu.common.config import FLAGS

    try:
        return bool(FLAGS.get("heat_enabled"))
    except KeyError:     # registry not populated (unit contexts)
        return False


def _decay_s() -> float:
    from dingo_tpu.common.config import FLAGS

    try:
        return max(1.0, float(FLAGS.get("heat_decay_s")))
    except KeyError:
        return 300.0


def _max_entries() -> int:
    from dingo_tpu.common.config import FLAGS

    try:
        return max(16, int(FLAGS.get("heat_max_entries")))
    except KeyError:
        return 4096


# ---------------------------------------------------------------------------
# pure sketch math (unit-testable)
# ---------------------------------------------------------------------------

def gini(masses: np.ndarray) -> float:
    """Gini coefficient of the mass distribution in [0, 1): 0 = every
    unit equally hot, ->1 = all traffic on one unit. The single-number
    skew signal `cluster top` and the split advisory read."""
    x = np.sort(np.asarray(masses, np.float64))
    n = x.size
    total = float(x.sum())
    if n <= 1 or total <= 0.0:
        return 0.0
    idx = np.arange(1, n + 1, dtype=np.float64)
    return float((2.0 * idx - n - 1.0) @ x) / (n * total)


def hot_fraction(masses: np.ndarray, top: float = 0.1) -> float:
    """Traffic mass carried by the hottest ``top`` fraction of units
    (>=1 unit). Uniform traffic reads ~``top``; a Zipf hotspot reads
    near 1.0 — the separation test_heat.py pins down."""
    x = np.sort(np.asarray(masses, np.float64))[::-1]
    total = float(x.sum())
    if x.size == 0 or total <= 0.0:
        return 0.0
    k = max(1, int(math.ceil(top * x.size)))
    return float(x[:k].sum()) / total


def working_set_rows(masses: np.ndarray, rows: np.ndarray,
                     pcts: Tuple[int, ...] = WS_PCTS) -> Dict[int, int]:
    """Rows needed to serve each pct of traffic: walk units hottest
    first, accumulate traffic mass, stop when the cumulative share
    reaches pct/100. The byte figure is rows x the tier's row price."""
    m = np.asarray(masses, np.float64)
    r = np.asarray(rows, np.float64)
    total = float(m.sum())
    if m.size == 0 or total <= 0.0:
        return {p: 0 for p in pcts}
    order = np.argsort(m)[::-1]
    cum_mass = np.cumsum(m[order]) / total
    cum_rows = np.cumsum(r[order])
    out: Dict[int, int] = {}
    for p in pcts:
        i = int(np.searchsorted(cum_mass, p / 100.0))
        i = min(i, m.size - 1)
        out[p] = int(cum_rows[i])
    return out


# ---------------------------------------------------------------------------
# per-region sketch
# ---------------------------------------------------------------------------

class _RegionHeat:
    """One region's decayed-touch sketch + cached layout + derived
    stats. All mutation happens on the plane's worker thread; reads
    (region_stats, unit view) take the plane lock for the brief copy."""

    __slots__ = ("mass", "t0", "touches", "layouts", "layout_cache",
                 "layout_ts", "last_publish", "stats")

    def __init__(self, now: float):
        #: (kind, unit_id) -> time-warped touch mass
        self.mass: Dict[Tuple[str, int], float] = {}
        #: reference time of the warp basis (exp((t - t0)/tau))
        self.t0 = now
        self.touches = 0
        #: kind -> layout provider ( -> dict(unit_rows, row_bytes, tier,
        #: dim)); refreshed from the worker at most every _LAYOUT_TTL_S
        self.layouts: Dict[str, Callable[[], Optional[dict]]] = {}
        self.layout_cache: Dict[str, dict] = {}
        self.layout_ts = 0.0
        self.last_publish = 0.0
        #: last derived stats (the heartbeat read)
        self.stats: Dict[str, Any] = {}

    # -- decay basis --------------------------------------------------------
    def warp(self, now: float, tau: float) -> float:
        return math.exp((now - self.t0) / tau)

    def rebase(self, now: float, tau: float) -> None:
        """Renormalize the warped masses to reference time ``now`` (the
        O(n) sweep that keeps exp() in float range over long uptimes)."""
        scale = math.exp((self.t0 - now) / tau)
        for k in self.mass:
            self.mass[k] *= scale
        self.t0 = now

    def fold(self, kind: str, units: np.ndarray, weight: float,
             now: float, tau: float, cap: int) -> int:
        """Add one touch batch. Returns the number of raw touches."""
        if (now - self.t0) / tau > _REBASE_WARP:
            self.rebase(now, tau)
        w = weight * self.warp(now, tau)
        uniq, counts = np.unique(units, return_counts=True)
        m = self.mass
        for u, c in zip(uniq.tolist(), counts.tolist()):
            key = (kind, int(u))
            m[key] = m.get(key, 0.0) + w * c
        n = int(counts.sum())
        self.touches += n
        if len(m) > cap:
            self.evict(cap)
        return n

    def evict(self, cap: int) -> None:
        """Drop the coldest entries down to ``cap`` (their mass is the
        least informative; the working-set tail they represent is the
        part already safe to leave cold)."""
        items = sorted(self.mass.items(), key=lambda kv: kv[1],
                       reverse=True)
        self.mass = dict(items[:cap])

    # -- layout -------------------------------------------------------------
    def refresh_layouts(self, now: float) -> None:
        if now - self.layout_ts < _LAYOUT_TTL_S and self.layout_cache:
            return
        self.layout_ts = now
        for kind, fn in list(self.layouts.items()):
            try:
                lay = fn()
            except Exception:  # noqa: BLE001 — providers ride on live
                _log.exception("heat layout provider failed")  # indexes
                lay = None
            if lay is not None:
                self.layout_cache[kind] = lay

    def rows_of(self, kind: str, unit: int) -> float:
        lay = self.layout_cache.get(kind)
        if lay is None:
            return float(SLOT_BLOCK)
        unit_rows = lay.get("unit_rows")
        if unit_rows is None:
            return float(lay.get("rows_per_unit", SLOT_BLOCK))
        if 0 <= unit < len(unit_rows):
            return float(unit_rows[unit])
        return 0.0

    # -- derived ------------------------------------------------------------
    def derive(self, now: float, tau: float) -> Dict[str, Any]:
        """Recompute gini / hot fraction / working set from the live
        sketch (worker thread; the O(n log n) sort is over <= cap
        entries). Bytes are priced at the region's OWN tier; the
        per-tier what-if curve is published as labeled gauges."""
        self.refresh_layouts(now)
        keys = list(self.mass.keys())
        masses = np.fromiter(self.mass.values(), np.float64, len(keys))
        rows = np.fromiter(
            (self.rows_of(k[0], k[1]) for k in keys), np.float64,
            len(keys))
        ws_rows = working_set_rows(masses, rows)
        # the region's own tier prices the headline bytes figure
        dim = 0.0
        own_row_bytes = 0.0
        tier = "fp32"
        for lay in self.layout_cache.values():
            dim = max(dim, float(lay.get("dim", 0)))
            own_row_bytes = max(own_row_bytes,
                                float(lay.get("row_bytes", 0.0)))
            tier = lay.get("tier", tier)
        if own_row_bytes <= 0.0:
            own_row_bytes = dim * TIER_BYTES.get(tier, 4.0)
        st: Dict[str, Any] = {
            "gini": gini(masses),
            "hot_fraction": hot_fraction(masses),
            "entries": len(keys),
            "touches": self.touches,
            "tier": tier,
            "ws_rows": ws_rows,
            "ws_bytes": {p: int(r * own_row_bytes)
                         for p, r in ws_rows.items()},
            # what-if: the same traffic served from each precision tier
            "ws_bytes_tier": {
                t: {p: int(r * dim * tb) for p, r in ws_rows.items()}
                for t, tb in TIER_BYTES.items()
            } if dim > 0 else {},
        }
        self.stats = st
        return st


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------

class HeatPlane:
    """Process-global heat sketch aggregator (``HEAT``).

    Serving-thread surface: ``observe`` (bounded enqueue, overflow drops
    and counts) and ``register_layout`` (dict set). Everything else —
    folding, decay, eviction, working-set math, metric publication —
    runs on the single worker thread."""

    def __init__(self, registry=METRICS):
        self.registry = registry
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._worker: Optional[threading.Thread] = None
        self._busy = 0
        self._regions: Dict[int, _RegionHeat] = {}

    # -- serving-thread surface ---------------------------------------------
    def observe(self, region_id: int, kind: str, units,
                weight: float = 1.0) -> None:
        """Record one resolve's touches. ``units`` is a host array of
        unit ids (IVF bucket ids for kind="ivf"; raw result slots for
        kind="slot" — mapped to SLOT_BLOCK units and -1-filtered on the
        worker, not here). Call sites gate on heat_enabled() so the
        off path never reaches this function."""
        try:
            arr = np.asarray(units)
            if arr.size == 0:
                return
            item = (int(region_id), kind, arr.reshape(-1).copy(),
                    float(weight), time.time())
        except Exception:  # noqa: BLE001 — observability never breaks
            _log.exception("heat observe failed")          # the reply
            return
        with self._cond:
            if len(self._queue) >= QUEUE_MAX:
                self.registry.counter(
                    "heat.dropped", region_id=region_id).add(1)
                return
            self._queue.append(item)
            self._ensure_worker()
            self._cond.notify()

    def register_layout(self, region_id: int, kind: str,
                        provider: Callable[[], Optional[dict]]) -> None:
        """Attach a layout provider for (region, kind). The provider is
        invoked on the WORKER thread (<= once per _LAYOUT_TTL_S) and
        returns ``{"unit_rows": array-or-None, "rows_per_unit": int,
        "row_bytes": float, "tier": str, "dim": int}`` or None."""
        with self._lock:
            rh = self._regions.get(region_id)
            if rh is None:
                rh = self._regions[region_id] = _RegionHeat(time.time())
            rh.layouts[kind] = provider

    # -- async lane ---------------------------------------------------------
    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        # context-free background fold loop (the quality-plane pattern):
        # touch batches carry their own timestamps; no trace or budget
        # crosses into the worker.
        # dingolint: ok[context-handoff] context-free background loop
        self._worker = threading.Thread(
            target=self._run, name="heat-fold", daemon=True
        )
        self._worker.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._queue:
                    self._cond.wait()
                item = self._queue.popleft()
                self._busy += 1
            try:
                self._fold(item)
            except Exception:  # noqa: BLE001 — the lane must survive
                _log.exception("heat fold failed")
            finally:
                with self._cond:
                    self._busy -= 1
                    self._cond.notify_all()

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until every queued touch batch is folded (tests,
        bench, the collector's deterministic reads)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._busy:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return False
                self._cond.wait(timeout=remain)
        return True

    # -- folding (worker thread) --------------------------------------------
    def _fold(self, item) -> None:
        region_id, kind, units, weight, ts = item
        if kind == "slot":
            units = units[units >= 0] // SLOT_BLOCK
            if units.size == 0:
                return
        tau = _decay_s()
        cap = _max_entries()
        with self._lock:
            rh = self._regions.get(region_id)
            if rh is None:
                rh = self._regions[region_id] = _RegionHeat(ts)
            n = rh.fold(kind, units, weight, ts, tau, cap)
            publish = ts - rh.last_publish >= _PUBLISH_MIN_S
            if publish:
                rh.last_publish = ts
        self.registry.counter("heat.touches", region_id=region_id).add(n)
        if publish:
            with self._lock:
                st = rh.derive(ts, tau)
            self._publish(region_id, st)

    def _publish(self, region_id: int, st: Dict[str, Any]) -> None:
        g = self.registry.gauge
        g("heat.bucket_gini", region_id).set(round(st["gini"], 6))
        g("heat.hot_fraction", region_id).set(
            round(st["hot_fraction"], 6))
        g("heat.entries", region_id).set(st["entries"])
        for p, b in st["ws_bytes"].items():
            g("heat.working_set_bytes", region_id,
              {"pct": str(p), "tier": st["tier"]}).set(b)
        for tier, per_pct in st["ws_bytes_tier"].items():
            if tier == st["tier"]:
                continue
            for p, b in per_pct.items():
                g("heat.working_set_bytes", region_id,
                  {"pct": str(p), "tier": tier}).set(b)

    # -- read side ----------------------------------------------------------
    def region_stats(self, region_id: int) -> Optional[Dict[str, Any]]:
        """Latest derived stats for the heartbeat harvest (collector
        thread). Recomputes when folds landed since the last publish so
        a freshly-flushed test/bench read is never a beat stale."""
        with self._lock:
            rh = self._regions.get(region_id)
            if rh is None or rh.touches == 0:
                return None
            return rh.derive(time.time(), _decay_s())

    def unit_masses(self, region_id: int,
                    kind: Optional[str] = None) -> Dict[Tuple[str, int],
                                                        float]:
        """Decayed per-unit masses (bench heat_skew, tests). True mass
        basis (warp undone)."""
        now = time.time()
        tau = _decay_s()
        with self._lock:
            rh = self._regions.get(region_id)
            if rh is None:
                return {}
            scale = math.exp((rh.t0 - now) / tau)
            return {k: v * scale for k, v in rh.mass.items()
                    if kind is None or k[0] == kind}

    def forget_region(self, region_id: int) -> None:
        """Drop the region's sketch when the store no longer hosts it
        (the collector's retire loop)."""
        with self._lock:
            self._regions.pop(region_id, None)

    def reset(self) -> None:
        """Forget everything (tests, bench arms)."""
        with self._cond:
            self._queue.clear()
            self._regions.clear()


HEAT = HeatPlane()
