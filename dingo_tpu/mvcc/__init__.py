"""MVCC layer: memcomparable key codec, versioned reads, TSO timestamps.

Mirrors reference src/mvcc/ (codec.h, reader.h, ts_provider.h)."""

from dingo_tpu.mvcc.codec import Codec, ValueFlag  # noqa: F401
from dingo_tpu.mvcc.ts_provider import TsProvider  # noqa: F401
