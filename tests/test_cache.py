"""Serving-edge result cache + in-flight dedupe (ISSUE 16).

Covers the three serving tiers and the coalescer integration:

- in-flight dedupe: N submitters of identical rows share ONE kernel row
  (sentinel spy proves a single dispatch) and every future resolves with
  the rows a solo dispatch would have produced;
- exact hits are byte-identical to a fresh dispatch across index family
  x precision tier, and invalidate on upsert / delete / retrain via
  SlotStore.mutation_version;
- the stale rung serves only while the shed ladder is degraded and never
  beyond cache.stale_versions;
- per-tenant fairness: one tenant's inserts evict its OWN tail first and
  can never push another tenant out;
- the semantic tier closes when the shadow-quality estimator's recall CI
  dips below quality.slo_recall (and stays closed while cold);
- eviction accounting: bytes/entries track the LRU exactly;
- budget/priority across dedupe: an admission-expired member fails its
  own future without killing its fan-out siblings, and the collapsed
  row rides its highest-priority member's dispatch position.
"""

import time

import numpy as np
import pytest

from dingo_tpu.cache import edge as cache_edge
from dingo_tpu.cache import keys as cache_keys
from dingo_tpu.cache import policy
from dingo_tpu.cache.dedupe import build_plan, deduped_rows
from dingo_tpu.cache.store import ResultCache
from dingo_tpu.common.coalescer import SearchCoalescer
from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.index import IndexParameter, IndexType, new_index
from dingo_tpu.obs.pressure import (
    PRESSURE,
    Budget,
    DeadlineExceeded,
    attach_budget,
    detach_budget,
)


@pytest.fixture
def cache_on():
    FLAGS.set("cache_enabled", True)
    cache_edge.CACHE.reset()
    cache_edge.CODECS.reset()
    yield
    FLAGS.set("cache_enabled", False)
    FLAGS.set("cache_semantic", False)
    FLAGS.set("cache_max_bytes", 64 * 1024 * 1024)
    FLAGS.set("cache_stale_versions", 1)
    FLAGS.set("cache_tenant_share", 0.5)
    cache_edge.CACHE.reset()
    cache_edge.CODECS.reset()


def rows_of(results):
    """Per-row reply as the plain (id, distance) item list services
    caches — python scalars, so equality compares are exact."""
    return [list(zip(r.ids.tolist(), r.distances.tolist()))
            for r in results]


# -- in-flight dedupe ---------------------------------------------------------


def test_dedupe_collapses_to_one_kernel_row(cache_on):
    calls = []

    def run(key, stacked):
        calls.append(np.array(stacked, copy=True))
        return [("reply", float(q.sum())) for q in stacked]

    co = SearchCoalescer(run, window_ms=40.0)
    try:
        dup = np.full((1, 4), 7.0, np.float32)
        solo = np.full((1, 4), 9.0, np.float32)
        futs = [co.submit("k", dup) for _ in range(4)]
        futs.append(co.submit("k", solo))
        got = [f.result(timeout=5) for f in futs]
    finally:
        co.stop()
    # one kernel call, duplicates collapsed before padding
    assert len(calls) == 1
    assert len(calls[0]) == 2
    # every duplicate submitter got the rows a solo dispatch produces
    for rows in got[:4]:
        assert rows == [("reply", 28.0)]
    assert got[4] == [("reply", 36.0)]
    # the collapse is accounted to the region's cache.* family
    assert cache_edge.CACHE.region_stats(0)["dedup_collapsed"] == 3


def test_dedupe_off_without_subsystem():
    calls = []

    def run(key, stacked):
        calls.append(len(stacked))
        return list(range(len(stacked)))

    co = SearchCoalescer(run, window_ms=30.0)
    try:
        dup = np.full((1, 4), 7.0, np.float32)
        for f in [co.submit("k", dup) for _ in range(3)]:
            f.result(timeout=5)
    finally:
        co.stop()
    assert calls == [3]     # no plan: the kernel sees every row


def test_build_plan_none_when_nothing_collapses():
    class E:
        def __init__(self, q):
            self.queries = q

    a = E(np.arange(4, dtype=np.float32).reshape(1, 4))
    b = E(np.arange(4, 8, dtype=np.float32).reshape(1, 4))
    assert build_plan([a, b]) is None
    assert deduped_rows([a, b]) == 2
    dup = E(np.arange(4, dtype=np.float32).reshape(1, 4))
    plan = build_plan([a, b, dup])
    assert plan is not None
    assert plan.collapsed == 1
    assert len(plan.stacked) == 2


# -- exact hits: byte-identity + invalidation --------------------------------

FAMILIES = [
    (IndexType.FLAT, "fp32"),
    (IndexType.FLAT, "sq8"),
    (IndexType.IVF_FLAT, "fp32"),
    (IndexType.IVF_FLAT, "sq8"),
    (IndexType.HNSW, "fp32"),
    (IndexType.HNSW, "sq8"),
]


def _mk_index(rid, index_type, precision, d=16, n=96):
    kw = {}
    if index_type == IndexType.IVF_FLAT:
        kw = {"ncentroids": 4, "default_nprobe": 4}
    elif index_type == IndexType.HNSW:
        kw = {"nlinks": 8, "efconstruction": 40}
    idx = new_index(rid, IndexParameter(
        index_type=index_type, dimension=d, precision=precision, **kw))
    rng = np.random.default_rng(rid)
    x = rng.standard_normal((n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    idx.upsert(ids, x)
    if index_type == IndexType.IVF_FLAT:
        idx.train()
    search_kw = ({"nprobe": 4} if index_type == IndexType.IVF_FLAT
                 else {})
    return idx, x, search_kw


@pytest.mark.parametrize(
    "index_type,precision", FAMILIES,
    ids=[f"{t.value}-{p}" for t, p in FAMILIES])
def test_hit_byte_identical_to_fresh_dispatch(cache_on, index_type,
                                              precision):
    rid = 4000 + FAMILIES.index((index_type, precision))
    idx, x, kw = _mk_index(rid, index_type, precision)
    kw_items = tuple(sorted(kw.items()))
    q = x[:3] + np.float32(0.01)
    ver = cache_edge.index_version(idx)
    assert ver is not None

    looked = cache_edge.lookup(rid, q, 5, kw_items, ver, index=idx)
    assert looked is not None and not looked.any_hit
    fresh = rows_of(idx.search(q, 5, **kw))
    cache_edge.fill(rid, looked, fresh, cache_edge.index_version(idx), q)

    again = cache_edge.lookup(rid, q, 5, kw_items, ver, index=idx)
    assert again is not None and again.complete
    # the hit is byte-identical to a SECOND uncached dispatch, not just
    # to the rows that populated it — determinism is part of the claim
    assert again.rows == rows_of(idx.search(q, 5, **kw))
    assert again.rows == fresh
    st = cache_edge.CACHE.region_stats(rid)
    assert st["hits"] == 3 and st["misses"] == 3


def test_params_change_is_a_different_key(cache_on):
    rid = 4100
    idx, x, kw = _mk_index(rid, IndexType.FLAT, "fp32")
    q = x[:2]
    ver = cache_edge.index_version(idx)
    looked = cache_edge.lookup(rid, q, 5, (), ver, index=idx)
    cache_edge.fill(rid, looked, rows_of(idx.search(q, 5)), ver, q)
    # same rows, different topn -> different params seed -> miss
    other = cache_edge.lookup(rid, q, 7, (), ver, index=idx)
    assert other is not None and not other.any_hit


def test_partial_hit_submits_only_miss_rows(cache_on):
    rid = 4200
    idx, x, kw = _mk_index(rid, IndexType.FLAT, "fp32")
    ver = cache_edge.index_version(idx)
    q0 = x[:1]
    looked = cache_edge.lookup(rid, q0, 5, (), ver, index=idx)
    cache_edge.fill(rid, looked, rows_of(idx.search(q0, 5)), ver, q0)

    q = np.concatenate([x[:1], x[10:11]], axis=0)
    part = cache_edge.lookup(rid, q, 5, (), ver, index=idx)
    assert part is not None and part.any_hit and not part.complete
    assert part.miss_idx.tolist() == [1]
    miss_rows = rows_of(idx.search(q[part.miss_idx], 5))
    merged = part.merge(miss_rows)
    # stitching: the hit row is byte-identical to the dispatch that
    # populated it, the miss row to the dispatch that just ran (pad
    # buckets differ between a 1-row and a 2-row dispatch, so low float
    # bits may differ ACROSS shapes — the per-row identity is the claim)
    assert merged[0] == rows_of(idx.search(q0, 5))[0]
    assert merged[1] == miss_rows[0]
    full = rows_of(idx.search(q, 5))
    for got, want in zip(merged, full):
        assert [i for i, _ in got] == [i for i, _ in want]
        assert np.allclose([s for _, s in got], [s for _, s in want],
                           atol=1e-4)


@pytest.mark.parametrize("mutate", ["upsert", "delete", "train"])
def test_invalidation_on_mutation(cache_on, mutate):
    rid = 4300
    idx, x, kw = _mk_index(rid, IndexType.IVF_FLAT, "fp32")
    kw_items = tuple(sorted(kw.items()))
    q = x[:2]
    v0 = cache_edge.index_version(idx)
    looked = cache_edge.lookup(rid, q, 5, kw_items, v0, index=idx)
    cache_edge.fill(rid, looked, rows_of(idx.search(q, 5, **kw)), v0, q)
    assert cache_edge.lookup(rid, q, 5, kw_items, v0, index=idx).complete

    if mutate == "upsert":
        idx.upsert(np.array([500], np.int64), x[:1] + np.float32(1.0))
    elif mutate == "delete":
        idx.delete(np.array([3], np.int64))
    else:
        idx.train()
    v1 = cache_edge.index_version(idx)
    assert v1 > v0      # every mutation kind bumps the serving version
    # the old entry keys at v0; a live lookup (degrade_level 0 -> no
    # stale allowance) must MISS
    after = cache_edge.lookup(rid, q, 5, kw_items, v1, index=idx)
    assert not after.any_hit


def test_fill_skipped_when_version_moved_mid_flight(cache_on):
    rid = 4400
    idx, x, kw = _mk_index(rid, IndexType.FLAT, "fp32")
    q = x[:1]
    v0 = cache_edge.index_version(idx)
    looked = cache_edge.lookup(rid, q, 5, (), v0, index=idx)
    fresh = rows_of(idx.search(q, 5))
    idx.upsert(np.array([700], np.int64), x[5:6])   # write lands mid-flight
    cache_edge.fill(rid, looked, fresh, cache_edge.index_version(idx), q)
    assert cache_edge.CACHE.stats()["entries"] == 0


# -- stale rung ---------------------------------------------------------------


def test_stale_rung_only_under_degrade_and_never_beyond_bound(cache_on):
    rid = 4500
    FLAGS.set("cache_stale_versions", 2)
    rc = cache_edge.CACHE
    rows = [[(1, 0.5)]]
    rc.put(rid, 99, version=5, rows=rows)

    # not degraded: the policy grants no stale allowance at all
    METRICS.gauge("qos.degrade_level", rid).set(0.0)
    assert policy.stale_versions_allowed(rid) == 0
    assert rc.lookup(rid, 99, version=6, stale_versions=0) is None

    # degraded: up to cache.stale_versions behind serves...
    METRICS.gauge("qos.degrade_level", rid).set(1.0)
    allowed = policy.stale_versions_allowed(rid)
    assert allowed == 2
    got = rc.lookup(rid, 99, version=7, stale_versions=allowed)
    assert got == rows
    assert rc.region_stats(rid)["stale_served"] == 1
    # ...but NEVER beyond the bound, degraded or not
    assert rc.lookup(rid, 99, version=8, stale_versions=allowed) is None
    METRICS.gauge("qos.degrade_level", rid).set(0.0)


# -- per-tenant fairness + eviction accounting -------------------------------


def test_tenant_evicts_own_tail_never_neighbors(cache_on):
    FLAGS.set("cache_max_bytes", 2000)
    FLAGS.set("cache_tenant_share", 0.5)    # 1000 bytes per tenant
    rc = ResultCache()
    rows = [(i, float(i)) for i in range(5)]    # 160 + 5*56 = 440 bytes
    assert rc.put(1, 1, 1, rows, tenant="b")
    for fp in (10, 11, 12):                     # 3rd insert busts a's share
        assert rc.put(1, fp, 1, rows, tenant="a")
    assert rc.tenant_bytes("a") <= 1000
    assert rc.tenant_bytes("b") == 440          # b untouched
    assert rc.lookup(1, 10, 1) is None          # a's own LRU tail paid
    assert rc.lookup(1, 12, 1) == rows
    # a single entry larger than the tenant share is refused outright
    big = [(i, float(i)) for i in range(20)]    # 160 + 20*56 = 1280
    assert not rc.put(1, 77, 1, big, tenant="a")


def test_eviction_accounting_tracks_lru(cache_on):
    FLAGS.set("cache_max_bytes", 1000)
    FLAGS.set("cache_tenant_share", 0.0)        # no per-tenant carve-out
    rc = ResultCache()
    rows = [(i, float(i)) for i in range(5)]    # 440 bytes each
    rc.put(7, 1, 1, rows)
    rc.put(7, 2, 1, rows)
    assert rc.stats() == {"bytes": 880, "entries": 2, "tenants": 1}
    rc.put(7, 3, 1, rows)                       # evicts fp=1 (oldest)
    st = rc.stats()
    assert st["bytes"] == 880 and st["entries"] == 2
    assert rc.lookup(7, 1, 1) is None
    assert rc.lookup(7, 2, 1) == rows
    assert rc.region_stats(7)["entries"] == 2
    # a hit refreshes recency: inserting again now evicts fp=3, not fp=2
    rc.put(7, 4, 1, rows)
    assert rc.lookup(7, 3, 1) is None
    assert rc.lookup(7, 2, 1) == rows


# -- semantic tier ------------------------------------------------------------


def test_semantic_gate_fails_closed_and_closes_on_dip(cache_on,
                                                      monkeypatch):
    from dingo_tpu.obs import quality as quality_mod

    rid = 4600
    FLAGS.set("cache_semantic", True)
    # cold estimator: no evidence -> no semantic serving
    monkeypatch.setattr(quality_mod.QUALITY, "region_estimate",
                        lambda _rid: None)
    assert not policy.semantic_allowed(rid)
    # healthy CI above the SLO -> open
    FLAGS.set("quality_slo_recall", 0.95)
    monkeypatch.setattr(quality_mod.QUALITY, "region_estimate",
                        lambda _rid: {"ci_low": 0.97})
    assert policy.semantic_allowed(rid)
    # recall dip below the SLO -> the gate closes
    monkeypatch.setattr(quality_mod.QUALITY, "region_estimate",
                        lambda _rid: {"ci_low": 0.90})
    assert not policy.semantic_allowed(rid)


def test_semantic_hit_serves_rounded_query_and_respects_gate(
        cache_on, monkeypatch):
    from dingo_tpu.obs import quality as quality_mod

    rid = 4700
    idx, x, kw = _mk_index(rid, IndexType.FLAT, "fp32", d=8, n=300)
    FLAGS.set("cache_semantic", True)
    FLAGS.set("quality_slo_recall", 0.95)
    monkeypatch.setattr(quality_mod.QUALITY, "region_estimate",
                        lambda _rid: {"ci_low": 0.99})
    # train the per-region sq8 fingerprint codec from real traffic
    cache_edge.CODECS.observe(rid, x[:cache_keys.SEMANTIC_TRAIN_ROWS])
    assert cache_edge.CODECS.trained(rid)

    q = x[:1]
    ver = cache_edge.index_version(idx)
    looked = cache_edge.lookup(rid, q, 5, (), ver, index=idx)
    cache_edge.fill(rid, looked, rows_of(idx.search(q, 5)), ver, q)

    # a near-identical query (same sq8 rounding) misses exact, hits
    # semantic while the SLO gate holds
    near = q + np.float32(1e-6)
    got = cache_edge.lookup(rid, near, 5, (), ver, index=idx)
    assert got is not None and got.complete
    assert cache_edge.CACHE.region_stats(rid)["semantic_served"] == 1

    # the same lookup after a recall dip falls through to a miss
    monkeypatch.setattr(quality_mod.QUALITY, "region_estimate",
                        lambda _rid: {"ci_low": 0.50})
    got = cache_edge.lookup(rid, near, 5, (), ver, index=idx)
    assert not got.any_hit


# -- budget/priority across dedupe (satellite 4 regression) ------------------


def test_expired_member_fails_alone_dedupe_siblings_served(cache_on):
    FLAGS.set("qos_enabled", True)
    PRESSURE.reset()
    calls = []

    def run(key, stacked):
        calls.append(np.array(stacked, copy=True))
        return [("reply", float(q.sum())) for q in stacked]

    co = SearchCoalescer(run, window_ms=80.0)
    try:
        dup = np.full((1, 4), 3.0, np.float32)
        now = time.monotonic()
        # alive member: generous deadline
        token = attach_budget(Budget(60_000.0, priority=2, t0=now))
        try:
            f_alive = co.submit("k", dup, region_id=77)
        finally:
            detach_budget(token)
        # doomed member of the SAME fan-out set: alive at admission,
        # dead by the time the 80ms window flushes
        token = attach_budget(Budget(20.0, priority=0, t0=now))
        try:
            f_dead = co.submit("k", dup, region_id=77)
        finally:
            detach_budget(token)
        assert f_alive.result(timeout=5) == [("reply", 12.0)]
        with pytest.raises(DeadlineExceeded):
            f_dead.result(timeout=5)
    finally:
        co.stop()
        FLAGS.set("qos_enabled", False)
    # the survivor still dispatched its row — once
    assert len(calls) == 1 and len(calls[0]) == 1


def test_collapsed_row_rides_highest_priority_position(cache_on):
    FLAGS.set("qos_enabled", True)
    PRESSURE.reset()
    calls = []

    def run(key, stacked):
        calls.append(np.array(stacked, copy=True))
        return [("reply", float(q.sum())) for q in stacked]

    co = SearchCoalescer(run, window_ms=80.0)
    try:
        row_a = np.full((1, 4), 1.0, np.float32)
        row_b = np.full((1, 4), 2.0, np.float32)
        futs = []
        # background submits rows A then B; an interactive submitter
        # duplicates row B — the collapsed B row must ride the
        # interactive member's position, ahead of A
        for q, prio in ((row_a, 0), (row_b, 0), (row_b, 2)):
            token = attach_budget(Budget(60_000.0, priority=prio))
            try:
                futs.append(co.submit("k", q, region_id=78))
            finally:
                detach_budget(token)
        got = [f.result(timeout=5) for f in futs]
    finally:
        co.stop()
        FLAGS.set("qos_enabled", False)
    assert len(calls) == 1
    assert len(calls[0]) == 2                    # B collapsed
    assert float(calls[0][0].sum()) == 8.0       # B dispatched first
    assert got[1] == got[2] == [("reply", 8.0)]
    assert got[0] == [("reply", 4.0)]


# -- key derivation -----------------------------------------------------------


def test_query_fingerprints_bind_params_and_bytes():
    q = np.arange(8, dtype=np.float32).reshape(2, 4)
    s1 = cache_keys.params_seed(5, (("nprobe", 4),))
    s2 = cache_keys.params_seed(5, (("nprobe", 8),))
    s3 = cache_keys.params_seed(5, (("nprobe", 4),), filter_fp=b"\x01")
    f1 = cache_keys.query_fingerprints(q, s1)
    assert f1.shape == (2,)
    # identical rows, different resolved params -> disjoint keys
    assert not np.any(f1 == cache_keys.query_fingerprints(q, s2))
    assert not np.any(f1 == cache_keys.query_fingerprints(q, s3))
    # a single flipped mantissa bit is a different key
    q2 = q.copy()
    q2[0, 0] = np.nextafter(q2[0, 0], np.float32(1e9))
    f2 = cache_keys.query_fingerprints(q2, s1)
    assert f2[0] != f1[0] and f2[1] == f1[1]
