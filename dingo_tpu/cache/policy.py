"""Cache policy gates: when each serving tier is allowed to answer.

Three tiers, strictly ordered by how much they're allowed to assume:

- **dedupe** (always safe): collapsing identical in-flight rows changes
  nothing observable — every fan-out member receives the same rows a solo
  dispatch would have produced. Enabled whenever the subsystem is.
- **exact hits** (safe at the live version): keyed on
  ``mutation_version``, so correctness is structural. Enabled whenever
  the subsystem is and ``cache.max_bytes`` > 0.
- **stale hits**: bounded ``cache.stale_versions`` behind, and ONLY
  while the region's shed ladder is degraded (qos.degrade_level > 0) —
  a pressure valve on the QoS degrade ladder, never steady state.
- **semantic hits**: sq8-rounded fingerprints, off by default, and gated
  live by the shadow-quality estimator: they serve only while the
  windowed recall CI lower bound holds ``quality.slo_recall``. No
  estimate for the region (cold estimator) means NO semantic serving —
  the gate fails closed.

Every gate is a cheap host-side read (flag + gauge/dict); nothing here
may touch a device value — the dingolint host-sync checker roots these
functions to enforce that.
"""

from __future__ import annotations


def cache_enabled() -> bool:
    """Whole-subsystem gate (``cache.enabled``)."""
    from dingo_tpu.common.config import result_cache_enabled

    return result_cache_enabled()


def dedupe_enabled() -> bool:
    """In-flight dedupe rides the subsystem gate; it needs no byte
    budget (``cache.max_bytes = 0`` keeps dedupe while disabling the
    result store)."""
    return cache_enabled()


#: region_id -> stale bound currently engaged. Transition memo so the
#: event ledger records WHEN stale serving engaged/disengaged, not every
#: per-query gate read (stale_versions_allowed is hot-path).
_stale_engaged: dict = {}


def stale_versions_allowed(region_id: int) -> int:
    """How many mutation_versions behind a hit may serve for this region
    RIGHT NOW: ``cache.stale_versions`` while the shed ladder is degraded,
    else 0 (exact-version only)."""
    from dingo_tpu.common.config import FLAGS
    from dingo_tpu.obs.pressure import degrade_level

    try:
        bound = int(FLAGS.get("cache_stale_versions"))
    except (TypeError, ValueError):
        bound = 0
    level = degrade_level(region_id) if bound > 0 else 0
    allowed = bound if (bound > 0 and level > 0) else 0
    prev = _stale_engaged.get(region_id, 0)
    if allowed != prev:
        _stale_engaged[region_id] = allowed
        from dingo_tpu.obs.events import EVENTS

        EVENTS.emit(
            "cache", region_id, "stale_rung", prev, allowed,
            trigger="engage" if allowed else "disengage",
            evidence={"degrade_level": level, "bound": bound},
        )
    return allowed


def forget_region(region_id: int) -> None:
    """Drop the stale-serving transition memo for a retired region (called
    from the collector's retire sweep alongside the other planes)."""
    _stale_engaged.pop(region_id, None)


def semantic_allowed(region_id: int) -> bool:
    """Live SLO gate for approximate hits: ``cache.semantic`` is on AND
    the shadow-quality estimator currently attests the region's windowed
    recall CI lower bound >= ``quality.slo_recall``. Fails closed when
    the estimator has no evidence."""
    from dingo_tpu.common.config import FLAGS
    from dingo_tpu.obs.quality import QUALITY

    v = FLAGS.get("cache_semantic")
    if isinstance(v, str):
        on = v.strip().lower() in ("true", "1", "on", "yes")
    else:
        on = bool(v)
    if not on:
        return False
    est = QUALITY.region_estimate(region_id)
    if not est:
        return False
    try:
        slo = float(FLAGS.get("quality_slo_recall"))
        ci_low = float(est.get("ci_low", 0.0))
    except (TypeError, ValueError):
        return False
    return ci_low >= slo
