"""Batched device-side HNSW construction (ISSUE 18 tentpole).

Host construction inserts one row at a time through native ``hnsw_add``
— pointer-chasing work the reference (vector_index_hnsw.cc) parallelizes
with a thread pool and CS-PQ (PAPERS.md) identifies as THE bottleneck of
large-scale ANNS. This module builds the level-0 graph the device graph
tier serves (``SlotStore.adj``) directly on the accelerator, one pow2
insert batch at a time:

  candidate discovery   the PR 8 lockstep beam walk (ops/beam.py, raw
                        body inlined — this kernel is already
                        sentineled) runs the BATCH ROWS as queries
                        against the partially-built adjacency; an
                        intra-batch all-pairs top-k adds same-batch
                        neighbors the partial graph cannot see yet, and
                        bootstraps the first batch, whose graph is empty

  neighbor selection    RNG*-style occlusion pruning as ``deg`` rounds
                        of masked argmax over the candidate score
                        matrix: each round keeps the best surviving
                        candidate and occludes every candidate scoring
                        closer to the kept one than to the inserted
                        point — ``alpha^2 * s(c, kept) > s(c, p)`` in
                        the shared larger-is-better score space of
                        ops/rerank._scores_from_rows (for L2's negated
                        squared distances this is exactly DiskANN's
                        ``alpha * d(kept, c) <= d(p, c)`` prune)

  reverse edges         the selected edges flatten to (dst, src) pairs
                        and sort by dst; each run head re-prunes its
                        destination row ONCE against old neighbors plus
                        up to REVERSE_WINDOW same-batch incomers,
                        degree-clamped by plain top-deg, and the rows
                        install with the PR 3 donated scatter idiom
                        (out-of-range targets drop). Incomers past the
                        window drop and are counted
                        (``build.reverse_dropped``) — the next batch's
                        walk rediscovers those neighborhoods.

Shape discipline: the batch is pow2-padded with -1 slots and the caller
reserves store capacity up front, so a full build ladder compiles a
handful of programs and steady state (batch 2..N) compiles ZERO — the
monitored PR 3/5 invariant extended to construction.

Sync discipline: nothing here reads device values back per batch; the
entry slot and drop counter live on device across the whole build and
``BulkGraphBuilder.finish()`` performs the single host sync. Bulk build
is off the serving path — dingolint's host-sync checker covers this
module and that one sync is adjudicated in the baseline.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dingo_tpu.common.metrics import METRICS
from dingo_tpu.obs.sentinel import sentinel_jit
from dingo_tpu.ops.distance import Metric

#: same-batch incomers one destination row can absorb per flushed batch
#: (the reverse re-prune's static window); overflow drops and counts
REVERSE_WINDOW = 8

#: edge-list chunk of the reverse re-prune: bounds the resident
#: [chunk, deg + REVERSE_WINDOW, d] candidate-row gather
REVERSE_CHUNK = 1024


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _decoded_rows(vecs, slots, sq, vmin, scale):
    """Gather rows at ``slots`` in the compute representation the scoring
    kernels expect: sq8 codes decode to the bf16 surrogate (the store's
    sqnorm convention), float tiers gather as stored."""
    rows = jnp.take(vecs, slots, axis=0)
    if sq:
        from dingo_tpu.ops.sq import sq_decode_device

        rows = sq_decode_device(rows, vmin, scale)
    return rows


def _pair_scores(rows, sqn, metric):
    """[B, B] larger-is-better scores among the batch rows — the same
    formulas as ops/rerank._scores_from_rows, computed as one [B, B]
    matmul instead of a broadcast [B, B, d] gather. These only PROPOSE
    candidates; every survivor is re-scored through _scores_from_rows
    itself in the selection stage, so no cross-path drift can leak into
    the installed adjacency."""
    dots = jnp.einsum(
        "id,jd->ij", rows, rows,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    if metric is Metric.L2:
        return -(sqn[:, None] - 2.0 * dots + sqn[None, :])
    if metric is Metric.COSINE:
        return dots * jax.lax.rsqrt(jnp.maximum(sqn, 1e-30))[None, :]
    return dots


@sentinel_jit(
    "ops.build.insert",
    static_argnames=("beam", "max_iters", "metric", "sq", "alpha_sq"),
    donate_argnums=(0,),
)
def insert_batch(adj, vecs, sqnorm, valid, batch_slots, entry, vmin,
                 scale, beam, max_iters, metric, sq, alpha_sq):
    """Insert one pow2 batch of store rows into the partial adjacency.

    adj [cap, deg] int32 (-1 padded) is DONATED — the caller (a
    BulkGraphBuilder holding store.device_lock) rebinds its reference to
    the returned array, the ops/scatter.py discipline. batch_slots [B]
    int32; -1 pads the final partial batch (padded lanes select nothing
    and install nothing). entry [] int32 is the walk entry (-1 while the
    graph is empty).

    Returns (adj' [cap, deg], entry' [] int32, reverse_dropped []
    int32 — same-batch reverse edges past REVERSE_WINDOW).
    """
    from dingo_tpu.ops.beam import beam_search
    from dingo_tpu.ops.rerank import _scores_from_rows

    cap, deg = adj.shape
    b = batch_slots.shape[0]
    bvalid = batch_slots >= 0
    safe_b = jnp.where(bvalid, batch_slots, 0)
    rows = _decoded_rows(vecs, safe_b, sq, vmin, scale)
    qd = rows.astype(jnp.float32)
    bsq = jnp.take(sqnorm, safe_b)

    # -- candidate discovery -------------------------------------------------
    res_slots, _, _, _ = beam_search.__wrapped__(
        adj, vecs, sqnorm, valid, valid, qd, entry, vmin, scale,
        beam, max_iters, metric, sq,
    )
    ib = min(b, beam)
    pair = _pair_scores(qd, bsq, metric)
    pair = jnp.where(
        jnp.eye(b, dtype=bool) | ~bvalid[None, :] | ~bvalid[:, None],
        -jnp.inf, pair,
    )
    pv, pi = lax.top_k(pair, ib)
    intra = jnp.where(jnp.isneginf(pv), -1, jnp.take(safe_b, pi))

    # merge + self-mask + dedup (the beam.py sort trick: holes sort last)
    cand = jnp.concatenate([res_slots, intra], axis=1)        # [b, C]
    cand = jnp.where(cand == batch_slots[:, None], -1, cand)
    cs = jnp.where(cand >= 0, cand, cap)
    cs = jnp.sort(cs, axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((b, 1), bool), cs[:, 1:] == cs[:, :-1]], axis=1
    )
    cand = jnp.where((cs < cap) & ~dup, cs, -1).astype(jnp.int32)

    # -- occlusion selection -------------------------------------------------
    nc = cand.shape[1]
    csafe = jnp.where(cand >= 0, cand, 0)
    crows = _decoded_rows(vecs, csafe, sq, vmin, scale)       # [b, C, d]
    csq = jnp.take(sqnorm, csafe)
    s_pc = _scores_from_rows(crows, csq, qd, metric)
    s_pc = jnp.where(cand >= 0, s_pc, -jnp.inf)

    def select(i, st):
        selected, alive = st
        masked = jnp.where(alive, s_pc, -jnp.inf)
        j = jnp.argmax(masked, axis=1)[:, None]               # [b, 1]
        ok = jnp.take_along_axis(masked, j, axis=1)[:, 0] > -jnp.inf
        pick = jnp.take_along_axis(cand, j, axis=1)[:, 0]
        selected = selected.at[:, i].set(jnp.where(ok, pick, -1))
        alive = alive & (jnp.arange(nc)[None, :] != j)
        kept = jnp.take_along_axis(crows, j[:, :, None], axis=1)[:, 0, :]
        s_ck = _scores_from_rows(
            crows, csq, kept.astype(jnp.float32), metric
        )
        # RNG* occlusion: c is dominated once the kept neighbor explains
        # it better than the inserted point does
        alive = alive & ~(ok[:, None] & (alpha_sq * s_ck > s_pc))
        return selected, alive

    selected, _ = lax.fori_loop(
        0, deg, select,
        (jnp.full((b, deg), -1, jnp.int32), cand >= 0),
    )

    # -- forward install (donated scatter; padded lanes drop) ---------------
    adj = adj.at[jnp.where(bvalid, batch_slots, cap)].set(
        selected, mode="drop"
    )

    # -- reverse edges with degree-clamped re-pruning -----------------------
    ne = b * deg
    w = REVERSE_WINDOW
    dst = selected.reshape(-1)
    src = jnp.repeat(batch_slots, deg)
    ok_e = (dst >= 0) & (src >= 0)
    key = jnp.where(ok_e, dst, cap).astype(jnp.int32)
    order = jnp.argsort(key)                                  # stable
    dsts = jnp.take(key, order)
    srcs = jnp.take(jnp.where(ok_e, src, -1), order)
    idx = jnp.arange(ne)
    head = (dsts < cap) & jnp.concatenate(
        [jnp.ones((1,), bool), dsts[1:] != dsts[:-1]]
    )
    # run position via cummax over head indices: edges past the window
    # drop (counted; the next batch's walk rediscovers them)
    run_start = lax.associative_scan(
        jnp.maximum, jnp.where(head, idx, -1)
    )
    dropped = jnp.sum(
        ((dsts < cap) & (idx - run_start >= w)).astype(jnp.int32)
    )

    rc = min(REVERSE_CHUNK, _next_pow2(ne))
    pad = (-ne) % rc
    if pad:
        dsts = jnp.concatenate([dsts, jnp.full((pad,), cap, jnp.int32)])
        srcs = jnp.concatenate([srcs, jnp.full((pad,), -1, jnp.int32)])
        head = jnp.concatenate([head, jnp.zeros((pad,), bool)])
    nep = ne + pad

    def reprune(s):
        ii = s + jnp.arange(rc)
        d_e = lax.dynamic_slice(dsts, (s,), (rc,))
        h_e = lax.dynamic_slice(head, (s,), (rc,))
        dsafe = jnp.where(d_e < cap, d_e, 0)
        old = jnp.take(adj, dsafe, axis=0)                    # [rc, deg]
        # same-dst incomers in the static window after each head; a
        # destination inserted THIS batch already carries its incomers
        # in the just-installed forward row, so old ∩ incomers can be
        # non-empty — dedup with the same sort trick as discovery
        win = ii[:, None] + jnp.arange(w)[None, :]
        wclip = jnp.clip(win, 0, nep - 1)
        inc = jnp.where(
            (jnp.take(dsts, wclip) == d_e[:, None]) & (win < nep),
            jnp.take(srcs, wclip), -1,
        )
        cand2 = jnp.concatenate([old, inc], axis=1)           # [rc, deg+w]
        cand2 = jnp.where(cand2 == d_e[:, None], -1, cand2)
        c2 = jnp.where(cand2 >= 0, cand2, cap)
        c2 = jnp.sort(c2, axis=1)
        dup2 = jnp.concatenate(
            [jnp.zeros((rc, 1), bool), c2[:, 1:] == c2[:, :-1]], axis=1
        )
        cand2 = jnp.where((c2 < cap) & ~dup2, c2, -1).astype(jnp.int32)
        c2safe = jnp.where(cand2 >= 0, cand2, 0)
        c2rows = _decoded_rows(vecs, c2safe, sq, vmin, scale)
        c2sq = jnp.take(sqnorm, c2safe)
        drow = _decoded_rows(vecs, dsafe, sq, vmin, scale)
        s2 = _scores_from_rows(
            c2rows, c2sq, drow.astype(jnp.float32), metric
        )
        s2 = jnp.where(cand2 >= 0, s2, -jnp.inf)
        v2, i2 = lax.top_k(s2, deg)
        new_row = jnp.where(
            jnp.isneginf(v2), -1, jnp.take_along_axis(cand2, i2, axis=1)
        )
        return jnp.where(h_e & (d_e < cap), d_e, cap), new_row

    tgt2, new_rows = lax.map(reprune, jnp.arange(nep // rc) * rc)
    adj = adj.at[tgt2.reshape(-1)].set(
        new_rows.reshape(-1, deg), mode="drop"
    )

    # -- entry: the first inserted row anchors all later walks ---------------
    entry = jnp.where(
        entry >= 0, entry,
        jnp.where(jnp.any(bvalid),
                  jnp.take(batch_slots, jnp.argmax(bvalid)), -1),
    ).astype(jnp.int32)
    return adj, entry, dropped


class BulkGraphBuilder:
    """Accumulates store slots into pow2 insert batches and maintains the
    under-construction adjacency as a device array. Pure slot/store
    level: index-level concerns (row puts, integrity ledgers, native
    back-fill) live in index/hnsw.py's bulk session.

    Not thread-safe; one builder per build. Flushes take
    store.device_lock (the vecs/sqnorm references are donatable by
    writers) and donate the adjacency back into ``insert_batch``.
    """

    def __init__(self, store, deg: int, metric, *, sq: bool = False,
                 batch_rows: int = 256, beam: int = 64,
                 max_iters: int = 48, alpha: float = 1.0,
                 region_id: int = 0):
        self.store = store
        self.deg = max(1, int(deg))
        self.metric = metric
        self.sq = bool(sq)
        self.batch_rows = _next_pow2(max(8, int(batch_rows)))
        self.beam = max(8, int(beam))
        self.max_iters = max(1, int(max_iters))
        self.alpha_sq = float(alpha) * float(alpha)
        self.region_id = region_id
        self.rows = 0
        self.batches = 0
        self._pend = np.empty((0,), np.int32)
        self._adj = None
        self._entry_d = jnp.asarray(-1, jnp.int32)
        self._dropped_d = jnp.asarray(0, jnp.int32)
        self._done = False

    def _ensure_adj(self) -> None:
        cap = self.store.capacity
        if self._adj is None:
            self._adj = jnp.full((cap, self.deg), -1, jnp.int32)
        elif self._adj.shape[0] != cap:
            # the store grew under us (pow2 ladder): pad the building
            # adjacency to match — callers that reserve() capacity up
            # front never hit this and stay on one compiled program
            self._adj = jnp.concatenate([
                self._adj,
                jnp.full((cap - self._adj.shape[0], self.deg), -1,
                         jnp.int32),
            ])

    def add_slots(self, slots: np.ndarray) -> None:
        """Queue freshly-put store slots; full batches flush immediately."""
        assert not self._done, "builder already finished"
        self._pend = np.concatenate(
            [self._pend, np.asarray(slots, np.int32)]
        )
        while len(self._pend) >= self.batch_rows:
            self._flush(self._pend[:self.batch_rows])
            self._pend = self._pend[self.batch_rows:]

    def _flush(self, slots: np.ndarray) -> None:
        bb = self.batch_rows
        if len(slots) < bb:
            slots = np.concatenate(
                [slots, np.full(bb - len(slots), -1, np.int32)]
            )
        store = self.store
        with store.device_lock:
            self._ensure_adj()
            sq_on = self.sq and getattr(store, "sq_params", None) is not None
            if sq_on:
                vmin, scale = store.sq_vmin_d, store.sq_scale_d
            else:
                d = store.vecs.shape[1]
                vmin = jnp.zeros((d,), jnp.float32)
                scale = jnp.ones((d,), jnp.float32)
            self._adj, self._entry_d, dropped = insert_batch(
                self._adj, store.vecs, store.sqnorm, store.device_mask(),
                jnp.asarray(slots), self._entry_d, vmin, scale,
                beam=self.beam, max_iters=self.max_iters,
                metric=self.metric, sq=sq_on, alpha_sq=self.alpha_sq,
            )
            self._dropped_d = self._dropped_d + dropped
        n = int((slots >= 0).sum())
        self.rows += n
        self.batches += 1
        METRICS.counter("build.rows", region_id=self.region_id).add(n)
        METRICS.counter("build.batches", region_id=self.region_id).add(1)

    def finish(self) -> Tuple[jax.Array, int, dict]:
        """Flush the remainder and return (adj [cap, deg] int32 device,
        entry_slot, stats). The device_get here is the build's ONE host
        sync — per-batch state (entry, drop counter) stays device-side."""
        assert not self._done, "builder already finished"
        self._done = True
        if len(self._pend):
            self._flush(self._pend)
            self._pend = np.empty((0,), np.int32)
        self._ensure_adj()    # a zero-row build still yields a mirror
        entry, dropped = jax.device_get((self._entry_d, self._dropped_d))
        METRICS.counter(
            "build.reverse_dropped", region_id=self.region_id
        ).add(int(dropped))
        return self._adj, int(entry), {
            "rows": self.rows,
            "batches": self.batches,
            "reverse_dropped": int(dropped),
        }
