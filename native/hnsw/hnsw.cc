// hnsw.cc — from-scratch hierarchical NSW graph for the dingo-tpu HNSW
// index family (reference: src/vector/vector_index_hnsw.{h,cc} wraps the
// vendored hnswlib fork; this is an original implementation, NOT a copy).
//
// Division of labor (BASELINE config 4): graph construction and beam search
// are irregular pointer-chasing -> they stay on CPU in C++; the TPU re-ranks
// the candidate set with exact batched distances (Python side, index/hnsw.py).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 hnsw.cc -o libdingohnsw.so
// (auto-vectorized scalar loops; no hand intrinsics — the hot exact-distance
// work happens on the TPU, the graph only needs "good enough" CPU distances.)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <unordered_map>
#include <vector>

namespace {

struct Hnsw {
  int dim = 0;
  int metric = 0;  // 0 = L2, 1 = IP (cosine handled by normalized input)
  int M = 16;
  int M0 = 32;  // layer-0 degree cap (2*M, hnsw convention)
  int ef_construction = 200;
  double level_mult = 1.0;
  std::mt19937_64 rng{0x5eed};

  // node storage
  std::vector<float> vecs;                    // [n, dim]
  std::vector<int64_t> labels;                // external ids
  std::vector<uint8_t> deleted;               // tombstones
  std::vector<int> levels;                    // top layer per node
  // links[l] is a flat array: node i's neighbors at slot i*cap .. with count
  std::vector<std::vector<int>> links;        // per layer: [n * cap_l]
  std::vector<std::vector<int>> link_count;   // per layer: [n]
  std::unordered_map<int64_t, int> label_to_node;
  int entry = -1;
  int max_level = -1;
  // Bumped whenever the adjacency STRUCTURE can have changed (new node
  // inserted, snapshot loaded). In-place vector replacement and tombstone
  // deletes keep the links untouched and do NOT bump it — the Python side
  // keys its device adjacency mirror on (graph_version, store version).
  int64_t graph_version = 0;
  std::mutex mu;

  int cap(int level) const { return level == 0 ? M0 : M; }

  float dist(const float* a, const float* b) const {
    float acc = 0.f;
    if (metric == 0) {
      for (int i = 0; i < dim; ++i) {
        float t = a[i] - b[i];
        acc += t * t;
      }
      return acc;
    }
    for (int i = 0; i < dim; ++i) acc += a[i] * b[i];
    return -acc;  // smaller-is-better internally
  }

  const float* vec(int node) const { return vecs.data() + (size_t)node * dim; }

  int random_level() {
    std::uniform_real_distribution<double> u(0.0, 1.0);
    double r = u(rng);
    int lvl = (int)(-std::log(std::max(r, 1e-12)) * level_mult);
    return std::min(lvl, 32);
  }

  void ensure_layer(int level) {
    while ((int)links.size() <= level) {
      links.emplace_back();
      link_count.emplace_back();
    }
    // Every layer indexes by node id, so all layers grow with node count.
    size_t n = labels.size();
    for (size_t l = 0; l < links.size(); ++l) {
      links[l].resize(n * (size_t)cap((int)l), -1);
      link_count[l].resize(n, 0);
    }
  }

  // Greedy single-entry descent at `level`.
  int greedy(int start, const float* q, int level) const {
    int cur = start;
    float cd = dist(q, vec(cur));
    bool improved = true;
    while (improved) {
      improved = false;
      const int c = cap(level);
      const int* nb = links[level].data() + (size_t)cur * c;
      int cnt = link_count[level][cur];
      for (int j = 0; j < cnt; ++j) {
        int nx = nb[j];
        if (nx < 0) continue;
        float nd = dist(q, vec(nx));
        if (nd < cd) {
          cd = nd;
          cur = nx;
          improved = true;
        }
      }
    }
    return cur;
  }

  // Beam search at one layer; returns up to ef (dist, node) pairs sorted asc.
  std::vector<std::pair<float, int>> beam(int start, const float* q, int ef,
                                          int level,
                                          bool skip_deleted) const {
    // max-heap for results (worst on top), min-heap for frontier
    std::priority_queue<std::pair<float, int>> results;
    std::priority_queue<std::pair<float, int>,
                        std::vector<std::pair<float, int>>,
                        std::greater<>> frontier;
    std::vector<uint8_t> visited(labels.size(), 0);
    float sd = dist(q, vec(start));
    frontier.emplace(sd, start);
    visited[start] = 1;
    if (!skip_deleted || !deleted[start]) results.emplace(sd, start);
    while (!frontier.empty()) {
      auto [cd, cur] = frontier.top();
      if (!results.empty() && (int)results.size() >= ef &&
          cd > results.top().first)
        break;
      frontier.pop();
      const int c = cap(level);
      const int* nb = links[level].data() + (size_t)cur * c;
      int cnt = link_count[level][cur];
      for (int j = 0; j < cnt; ++j) {
        int nx = nb[j];
        if (nx < 0 || visited[nx]) continue;
        visited[nx] = 1;
        float nd = dist(q, vec(nx));
        if ((int)results.size() < ef ||
            nd < results.top().first) {
          frontier.emplace(nd, nx);
          if (!skip_deleted || !deleted[nx]) {
            results.emplace(nd, nx);
            if ((int)results.size() > ef) results.pop();
          }
        }
      }
    }
    std::vector<std::pair<float, int>> out(results.size());
    for (int i = (int)results.size() - 1; i >= 0; --i) {
      out[i] = results.top();
      results.pop();
    }
    return out;
  }

  // Heuristic neighbor selection (keep candidates not dominated by chosen).
  std::vector<int> select(const std::vector<std::pair<float, int>>& cand,
                          int maxn) const {
    std::vector<int> chosen;
    for (const auto& [cd, node] : cand) {
      if ((int)chosen.size() >= maxn) break;
      bool ok = true;
      for (int s : chosen) {
        if (dist(vec(node), vec(s)) < cd) {
          ok = false;
          break;
        }
      }
      if (ok) chosen.push_back(node);
    }
    // backfill with nearest remaining if pruning was too aggressive
    if ((int)chosen.size() < maxn) {
      for (const auto& [cd, node] : cand) {
        if ((int)chosen.size() >= maxn) break;
        if (std::find(chosen.begin(), chosen.end(), node) == chosen.end())
          chosen.push_back(node);
      }
    }
    return chosen;
  }

  void connect(int a, int b, int level) {
    const int c = cap(level);
    int* nb = links[level].data() + (size_t)a * c;
    int& cnt = link_count[level][a];
    if (cnt < c) {
      nb[cnt++] = b;
      return;
    }
    // full: re-select among existing + new
    std::vector<std::pair<float, int>> cand;
    cand.reserve(c + 1);
    cand.emplace_back(dist(vec(a), vec(b)), b);
    for (int j = 0; j < cnt; ++j)
      cand.emplace_back(dist(vec(a), vec(nb[j])), nb[j]);
    std::sort(cand.begin(), cand.end());
    auto chosen = select(cand, c);
    cnt = (int)chosen.size();
    for (int j = 0; j < cnt; ++j) nb[j] = chosen[j];
    for (int j = cnt; j < c; ++j) nb[j] = -1;
  }

  int add_one(int64_t label, const float* v) {
    auto it = label_to_node.find(label);
    if (it != label_to_node.end()) {
      // upsert: replace vector in place (links stay; graph quality degrades
      // slightly, matching hnswlib's updatePoint approximation)
      std::memcpy(vecs.data() + (size_t)it->second * dim, v,
                  sizeof(float) * dim);
      deleted[it->second] = 0;
      return it->second;
    }
    int node = (int)labels.size();
    ++graph_version;
    labels.push_back(label);
    deleted.push_back(0);
    vecs.insert(vecs.end(), v, v + dim);
    int lvl = random_level();
    levels.push_back(lvl);
    ensure_layer(std::max(lvl, std::max(max_level, 0)));
    label_to_node.emplace(label, node);

    if (entry < 0) {
      entry = node;
      max_level = lvl;
      return node;
    }
    int cur = entry;
    for (int l = max_level; l > lvl; --l) cur = greedy(cur, v, l);
    for (int l = std::min(lvl, max_level); l >= 0; --l) {
      auto cand = beam(cur, v, ef_construction, l, /*skip_deleted=*/false);
      auto neighbors = select(cand, cap(l));
      for (int nb : neighbors) {
        connect(node, nb, l);
        connect(nb, node, l);
      }
      if (!cand.empty()) cur = cand.front().second;
    }
    if (lvl > max_level) {
      max_level = lvl;
      entry = node;
    }
    return node;
  }

  void search_one(const float* q, int k, int ef, int64_t* out_labels,
                  float* out_d) const {
    if (entry < 0) {
      for (int i = 0; i < k; ++i) {
        out_labels[i] = -1;
        out_d[i] = INFINITY;
      }
      return;
    }
    int cur = entry;
    for (int l = max_level; l > 0; --l) cur = greedy(cur, q, l);
    auto cand = beam(cur, q, std::max(ef, k), 0, /*skip_deleted=*/true);
    int i = 0;
    for (; i < k && i < (int)cand.size(); ++i) {
      out_labels[i] = labels[cand[i].second];
      out_d[i] = metric == 0 ? cand[i].first : -cand[i].first;
    }
    for (; i < k; ++i) {
      out_labels[i] = -1;
      out_d[i] = INFINITY;
    }
  }
};

}  // namespace

extern "C" {

void* hnsw_new(int dim, int metric, int M, int ef_construction,
               uint64_t seed) {
  auto* h = new Hnsw();
  h->dim = dim;
  h->metric = metric;
  h->M = M;
  h->M0 = 2 * M;
  h->ef_construction = ef_construction;
  h->level_mult = 1.0 / std::log(std::max(2.0, (double)M));
  h->rng.seed(seed);
  return h;
}

void hnsw_free(void* p) { delete (Hnsw*)p; }

void hnsw_add(void* p, int n, const int64_t* labels, const float* vecs) {
  auto* h = (Hnsw*)p;
  std::lock_guard<std::mutex> g(h->mu);
  for (int i = 0; i < n; ++i)
    h->add_one(labels[i], vecs + (size_t)i * h->dim);
}

int hnsw_delete(void* p, int n, const int64_t* labels) {
  auto* h = (Hnsw*)p;
  std::lock_guard<std::mutex> g(h->mu);
  int removed = 0;
  for (int i = 0; i < n; ++i) {
    auto it = h->label_to_node.find(labels[i]);
    if (it != h->label_to_node.end() && !h->deleted[it->second]) {
      h->deleted[it->second] = 1;
      ++removed;
    }
  }
  return removed;
}

void hnsw_search(void* p, int nq, const float* queries, int k, int ef,
                 int64_t* out_labels, float* out_d) {
  auto* h = (Hnsw*)p;
  for (int i = 0; i < nq; ++i)
    h->search_one(queries + (size_t)i * h->dim, k, ef,
                  out_labels + (size_t)i * k, out_d + (size_t)i * k);
}

int64_t hnsw_count(void* p) {
  auto* h = (Hnsw*)p;
  int64_t live = 0;
  for (size_t i = 0; i < h->labels.size(); ++i)
    if (!h->deleted[i]) ++live;
  return live;
}

int64_t hnsw_deleted_count(void* p) {
  auto* h = (Hnsw*)p;
  return (int64_t)h->labels.size() - hnsw_count(p);
}

int64_t hnsw_memory(void* p) {
  auto* h = (Hnsw*)p;
  int64_t m = (int64_t)h->vecs.capacity() * 4 + h->labels.capacity() * 8;
  for (auto& l : h->links) m += (int64_t)l.capacity() * 4;
  return m;
}

// Serialization: simple versioned binary blob.
int64_t hnsw_save_size(void* p) {
  auto* h = (Hnsw*)p;
  int64_t sz = 8 * 8;  // header
  size_t n = h->labels.size();
  sz += (int64_t)n * (h->dim * 4 + 8 + 1 + 4);
  for (size_t l = 0; l < h->links.size(); ++l)
    sz += 8 + (int64_t)h->links[l].size() * 4 + (int64_t)n * 4;
  return sz;
}

int64_t hnsw_save(void* p, uint8_t* buf) {
  auto* h = (Hnsw*)p;
  uint8_t* w = buf;
  auto w64 = [&](int64_t v) { std::memcpy(w, &v, 8); w += 8; };
  w64(1);  // version
  w64(h->dim);
  w64(h->metric);
  w64(h->M);
  w64(h->ef_construction);
  w64((int64_t)h->labels.size());
  w64(h->entry);
  w64(h->max_level);
  size_t n = h->labels.size();
  std::memcpy(w, h->vecs.data(), n * h->dim * 4);
  w += n * h->dim * 4;
  std::memcpy(w, h->labels.data(), n * 8);
  w += n * 8;
  std::memcpy(w, h->deleted.data(), n);
  w += n;
  std::memcpy(w, h->levels.data(), n * 4);
  w += n * 4;
  for (size_t l = 0; l < h->links.size(); ++l) {
    w64((int64_t)h->links[l].size());
    std::memcpy(w, h->links[l].data(), h->links[l].size() * 4);
    w += h->links[l].size() * 4;
    std::memcpy(w, h->link_count[l].data(), n * 4);
    w += n * 4;
  }
  return w - buf;
}

void* hnsw_load(const uint8_t* buf, int64_t len) {
  const uint8_t* r = buf;
  auto r64 = [&]() { int64_t v; std::memcpy(&v, r, 8); r += 8; return v; };
  int64_t version = r64();
  if (version != 1) return nullptr;
  auto* h = new Hnsw();
  h->dim = (int)r64();
  h->metric = (int)r64();
  h->M = (int)r64();
  h->M0 = 2 * h->M;
  h->ef_construction = (int)r64();
  h->level_mult = 1.0 / std::log(std::max(2.0, (double)h->M));
  size_t n = (size_t)r64();
  h->entry = (int)r64();
  h->max_level = (int)r64();
  h->vecs.resize(n * h->dim);
  std::memcpy(h->vecs.data(), r, n * h->dim * 4);
  r += n * h->dim * 4;
  h->labels.resize(n);
  std::memcpy(h->labels.data(), r, n * 8);
  r += n * 8;
  h->deleted.resize(n);
  std::memcpy(h->deleted.data(), r, n);
  r += n;
  h->levels.resize(n);
  std::memcpy(h->levels.data(), r, n * 4);
  r += n * 4;
  while (r < buf + len) {
    int64_t sz = r64();
    h->links.emplace_back(sz);
    std::memcpy(h->links.back().data(), r, sz * 4);
    r += sz * 4;
    h->link_count.emplace_back(n);
    std::memcpy(h->link_count.back().data(), r, n * 4);
    r += n * 4;
  }
  for (size_t i = 0; i < n; ++i)
    h->label_to_node.emplace(h->labels[i], (int)i);
  h->graph_version = (int64_t)n;
  return h;
}

// ---- device-graph export: flattened level-0 adjacency ----------------------
// The TPU beam kernel walks a dense fixed-degree [n, deg] int array; these
// hooks hand the Python side the level-0 neighbor lists (node indices,
// -1 padded) plus the labels needed to remap node space -> slot space.

int64_t hnsw_total_count(void* p) {
  // total nodes INCLUDING tombstones (adjacency indexes by node id)
  auto* h = (Hnsw*)p;
  return (int64_t)h->labels.size();
}

int64_t hnsw_graph_version(void* p) {
  auto* h = (Hnsw*)p;
  return h->graph_version;
}

int64_t hnsw_entry_label(void* p) {
  auto* h = (Hnsw*)p;
  return h->entry >= 0 ? h->labels[h->entry] : -1;
}

void hnsw_export_level0(void* p, int64_t max_nodes, int deg_cap,
                        int64_t* out_labels, int32_t* out_adj) {
  auto* h = (Hnsw*)p;
  std::lock_guard<std::mutex> g(h->mu);
  // Clamp to the CALLER'S buffer capacity: the caller sized its arrays
  // from an earlier hnsw_total_count() read, and a concurrent insert may
  // have grown labels since — writing labels.size() entries would
  // overflow the caller's heap. A clamped (stale) export is fine: the
  // caller keys its mirror on graph_version and re-exports next search.
  size_t n = std::min(h->labels.size(), (size_t)std::max<int64_t>(0, max_nodes));
  if (n == 0) return;
  std::memcpy(out_labels, h->labels.data(), n * sizeof(int64_t));
  std::fill(out_adj, out_adj + n * (size_t)deg_cap, -1);
  if (h->links.empty()) return;
  const int c = h->cap(0);
  const int take = std::min(deg_cap, c);
  for (size_t i = 0; i < n; ++i) {
    int cnt = std::min(h->link_count[0][i], take);
    const int* nb = h->links[0].data() + i * (size_t)c;
    for (int j = 0; j < cnt; ++j)
      // neighbors past the clamp (concurrently inserted nodes wired
      // into existing lists) have no label in the caller's view: pad
      out_adj[i * (size_t)deg_cap + j] = nb[j] < (int64_t)n ? nb[j] : -1;
  }
}

}  // extern "C"
