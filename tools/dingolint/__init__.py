"""dingolint — repo-native static invariant analyzer.

Eleven PRs accreted load-bearing conventions: every persistent jit goes
through ``sentinel_jit`` (PR 5), device mutations happen under
``store.device_lock`` (PR 3), static shapes come off the pow2 ladders so
steady state never recompiles (PR 3/6), and trace + budget contextvars
must be captured across thread handoffs (PR 1/10). Each was enforced
only by convention plus a handful of runtime tests — which means a new
call site that syncs the host mid-resolve or mints an off-ladder shape
compiles, passes unit tests, and silently kills the serving properties
(sustained QPS needs a stall-free kernel path; a single retrace is a
100ms-40s p99 outlier) until the bench regresses.

dingolint encodes those invariants as static checkers over the package
AST plus a module-level call graph:

- per-file checkers get each parsed module (``check_module``);
- inter-procedural checkers additionally get the whole repo and a call
  graph (``check_repo``) for reachability questions ("is this host sync
  reachable from a search dispatch path?") and lock-acquisition nesting.

Adjudicated pre-existing findings live in ``baseline.json`` next to this
package — every entry carries a one-line rationale, and the lint fails
if one doesn't. New code suppresses a deliberate exception inline with
``# dingolint: ok[<checker>] <reason>``.

Entry point: ``tools/lint.py`` (wired into tier-1 via
tests/test_dingolint.py — a violation fails CI, not the bench).
"""

from tools.dingolint.core import (  # noqa: F401
    Checker,
    Finding,
    Module,
    Repo,
    lint_paths,
    lint_repo,
    load_repo,
)
from tools.dingolint.checkers import all_checkers  # noqa: F401
