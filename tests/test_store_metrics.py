"""Store-metrics pipeline: collection -> heartbeat -> coordinator
aggregation -> exposition (tentpole acceptance: a two-store cluster shows
per-region key counts, vector counts, memory bytes, and device-memory
gauges flowing store -> heartbeat -> coordinator, queryable via
GetStoreMetrics, rendered by `cluster top`, scrapeable as valid Prometheus
text, and load-aware balancing acts on injected skew that count-based
balancing ignores)."""

import re
import time

import numpy as np
import pytest

from dingo_tpu.client.cli import format_cluster_top
from dingo_tpu.common.metrics import (
    Gauge,
    LatencyRecorder,
    MetricsRegistry,
)
from dingo_tpu.coordinator.balance import BalanceLeaderScheduler
from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.metrics.snapshot import (
    RegionMetricsSnapshot,
    StoreMetricsSnapshot,
)
from dingo_tpu.raft import LocalTransport
from dingo_tpu.server.services import ClusterStatService, DebugService
from dingo_tpu.server import pb
from dingo_tpu.store.node import StoreNode
from dingo_tpu.store.region import RegionType


# ---------------------------------------------------------------------------
# metric primitives (satellites)
# ---------------------------------------------------------------------------

def test_gauge_add_is_atomic_delta():
    g = Gauge()
    g.set(100.0)
    assert g.add(28.0) == 128.0
    assert g.add(-128.0) == 0.0
    import threading

    def worker():
        for _ in range(1000):
            g.add(1)
            g.add(-1)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # racing read-modify-write via set() would lose deltas; add() must not
    assert g.get() == 0.0


def test_latency_qps_is_windowed_not_lifetime():
    lr = LatencyRecorder()
    # simulate a long-lived process: constructed 1000s ago, traffic NOW
    lr._t0 -= 1000.0
    for _ in range(32):
        lr.observe_us(50.0)
    st = lr.stats()
    assert st["count"] == 32            # count stays lifetime
    # lifetime-based estimate would be 32/1000 = 0.032; windowed must see
    # the current burst (32 samples within the 16s window -> >= 2/s)
    assert st["qps"] >= 1.0
    # and an idle recorder's rate decays to zero once the window passes
    lr2 = LatencyRecorder()
    lr2.observe_us(10.0)
    now = time.monotonic() + 60       # pretend a minute passed
    assert lr2.windowed_qps(now=now) == 0.0


def test_prometheus_rendering_parses_back():
    m = MetricsRegistry()
    m.counter("rpc.requests", labels={"service": "index"}).add(5)
    m.gauge("store.region.key_count", region_id=3).set(42)
    lat = m.latency("vector_search", region_id=3)
    for v in (100.0, 200.0):
        lat.observe_us(v)
    text = m.render_prometheus()
    assert parse_prometheus(text)  # strict line grammar
    series = parse_prometheus(text)
    assert series[("rpc_requests", (("service", "index"),))] == 5.0
    assert series[("store_region_key_count", (("region", "3"),))] == 42.0
    assert series[
        ("vector_search_count", (("region", "3"),))
    ] == 2.0
    assert ("vector_search", (("quantile", "0.5"), ("region", "3"))) in series


_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? (-?[0-9.eE+\-]+|NaN)"
    # optional OpenMetrics exemplar suffix (trace-id attachments on
    # latency outliers — dingo_tpu/obs; an OpenMetrics-aware scraper
    # links the p99 series to its flight-recorder bundle)
    r"(?: # \{[^{}]*\} -?[0-9.eE+\-]+(?: -?[0-9.eE+\-]+)?)?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text):
    """Minimal strict parser: every exposition line must match the text
    format grammar; returns {(name, sorted-label-tuple): value}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        name, labelstr, value = m.groups()
        labels = tuple(sorted(_LABEL_RE.findall(labelstr or "")))
        out[(name, labels)] = float(value)
    return out


# ---------------------------------------------------------------------------
# two-store pipeline (tentpole acceptance)
# ---------------------------------------------------------------------------

@pytest.fixture()
def two_store_cluster():
    transport = LocalTransport()
    coord = CoordinatorControl(MemEngine(), replication=2)
    nodes = {
        sid: StoreNode(sid, transport, coord, raft_kw={"seed": i})
        for i, sid in enumerate(["s0", "s1"])
    }
    yield coord, nodes
    for n in nodes.values():
        n.stop()


def drive_until_leader(coord, nodes, region_id, timeout=6.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for n in nodes.values():
            n.heartbeat_once()
        leaders = [
            n for n in nodes.values()
            if (rn := n.engine.get_node(region_id)) is not None
            and rn.is_leader()
        ]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.03)
    raise AssertionError(f"no leader for region {region_id}")


def force_fresh_beats(nodes):
    for n in nodes.values():
        n.metrics._latest_mono = 0.0   # invalidate the snapshot cache
        n.heartbeat_once()


def test_metrics_flow_two_store_cluster(two_store_cluster):
    coord, nodes = two_store_cluster
    definition = coord.create_region(
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1 << 40),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(
            index_type=IndexType.FLAT, dimension=8),
    )
    rid = definition.region_id
    leader = drive_until_leader(coord, nodes, rid)
    region = leader.get_region(rid)
    n_vec = 12
    leader.storage.vector_add(
        region, np.arange(n_vec, dtype=np.int64),
        np.random.default_rng(0).standard_normal((n_vec, 8))
        .astype(np.float32),
    )
    # propose() only blocks until the LEADER applied; the follower applies
    # asynchronously — wait for both replicas to converge before snapshot
    # assertions (the race only lost when warm earlier tests made the
    # beat path fast enough to collect before the follower's apply)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        force_fresh_beats(nodes)
        rows = coord.get_store_metrics()
        if len(rows) == 2 and all(
            any(r.region_id == rid and r.key_count == n_vec
                for r in snap.regions)
            for _sid, snap, _at, _stale in rows
        ):
            break
        time.sleep(0.05)

    # --- coordinator holds both stores' snapshots, fresh
    rows = coord.get_store_metrics()
    assert [r[0] for r in rows] == ["s0", "s1"]
    for sid, snap, _at, stale in rows:
        assert not stale
        rm = snap.region(rid)
        assert rm.key_count == n_vec          # raft-replicated to both
        assert rm.vector_count == n_vec
        assert rm.vector_memory_bytes > 0
        assert rm.device_memory_bytes > 0     # live jax arrays (HBM analog)
        assert rm.approximate_bytes > 0
    leaders = [r for r in coord.get_region_metrics(rid) if r[2].is_leader]
    assert len(leaders) == 1 and leaders[0][0] == leader.store_id

    # --- queryable over the service surface (GetStoreMetrics RPC impl)
    stat = ClusterStatService(coord)
    resp = stat.GetStoreMetrics(pb.GetStoreMetricsRequest())
    assert {e.store_id for e in resp.stores} == {"s0", "s1"}
    entry = next(e for e in resp.stores if e.store_id == leader.store_id)
    pb_rm = next(r for r in entry.metrics.regions if r.region_id == rid)
    assert pb_rm.vector_count == n_vec and pb_rm.device_memory_bytes > 0
    region_resp = stat.GetRegionMetrics(
        pb.GetRegionMetricsRequest(region_id=rid))
    assert len(region_resp.regions) == 2

    # --- GetClusterStat rollups (leader-only logical counts)
    cs = stat.GetClusterStat(pb.GetClusterStatRequest())
    assert cs.total_vector_count == n_vec
    assert cs.total_key_count == n_vec
    assert cs.total_device_memory_bytes > 0
    lead_stat = next(
        s for s in cs.stores if s.store_id == leader.store_id)
    assert lead_stat.vector_count == n_vec and not lead_stat.metrics_stale

    # --- cluster top renders both tables
    table = format_cluster_top(resp)
    assert "STORE" in table and "REGION" in table
    assert str(rid) in table and "s0" in table and "s1" in table
    assert "L" in table  # a leader row

    # --- scrapeable as VALID prometheus text (parse-back)
    from dingo_tpu.common.metrics import METRICS

    series = parse_prometheus(METRICS.render_prometheus())
    key = ("store_region_vector_count", (("region", str(rid)),))
    assert series[key] == float(n_vec)
    assert series[
        ("store_region_device_memory_bytes", (("region", str(rid)),))
    ] > 0

    # --- DebugService format switch serves the same payload in-band
    dump = DebugService().MetricsDump(
        pb.MetricsDumpRequest(format="prometheus"))
    assert parse_prometheus(dump.json)[key] == float(n_vec)
    bad = DebugService().MetricsDump(pb.MetricsDumpRequest(format="xml"))
    assert bad.error.errcode


def test_metrics_staleness_after_store_stops_beating(two_store_cluster):
    coord, nodes = two_store_cluster
    for n in nodes.values():
        n.heartbeat_once()
    rows = coord.get_store_metrics()
    assert rows and all(not stale for *_x, stale in rows)
    # the store stops beating; judged from the coordinator's receive clock
    future = int(time.time() * 1000) + coord.METRICS_STALE_MS + 1
    rows = coord.get_store_metrics(now_ms=future)
    assert rows and all(stale for *_x, stale in rows)
    # stale snapshots drop out of cluster rollups
    assert coord.cluster_metrics_rollup(now_ms=future) == {
        "key_count": 0, "vector_count": 0,
        "memory_bytes": 0, "device_memory_bytes": 0,
    }


# ---------------------------------------------------------------------------
# load-aware balancing (tentpole acceptance: plans on skew count mode misses)
# ---------------------------------------------------------------------------

def _inject_cluster(hot_qps=100.0, warm_qps=10.0, cold_qps=1.0):
    """Two stores, three regions. s0 leads {1 (hot), 2 (warm)}, s1 leads
    {3 (cold)} — a 2-vs-1 leader split is inside count mode's
    `n_most <= n_least + 1` dead band, but the measured load is skewed."""
    from dingo_tpu.store.region import RegionDefinition

    coord = CoordinatorControl(MemEngine(), replication=2)
    coord.register_store("s0")
    coord.register_store("s1")
    for rid in (1, 2, 3):
        coord.regions[rid] = RegionDefinition(
            region_id=rid, start_key=b"", end_key=b"",
            peers=["s0", "s1"],
        )
    qps = {1: hot_qps, 2: warm_qps, 3: cold_qps}

    def snap(store_id, led):
        return StoreMetricsSnapshot(
            store_id=store_id,
            regions=[
                RegionMetricsSnapshot(
                    region_id=r, is_leader=(r in led),
                    search_qps=qps[r] if r in led else 0.0,
                    vector_memory_bytes=1 << 20,
                )
                for r in (1, 2, 3)
            ],
        )

    coord.store_heartbeat(
        "s0", region_ids=[1, 2, 3], leader_region_ids=[1, 2],
        metrics=snap("s0", {1, 2}))
    coord.store_heartbeat(
        "s1", region_ids=[1, 2, 3], leader_region_ids=[3],
        metrics=snap("s1", {3}))
    return coord


def test_load_aware_balance_plans_where_count_mode_does_not():
    coord = _inject_cluster()
    count_plan = BalanceLeaderScheduler(coord, mode="count").plan()
    assert count_plan == []       # 2-vs-1 leaders: count's dead band
    load_plan = BalanceLeaderScheduler(coord, mode="load").plan()
    assert len(load_plan) == 1
    op = load_plan[0]
    # the HOT region moves (heaviest-first), not the warm one
    assert (op.region_id, op.from_store, op.to_store) == (1, "s0", "s1")


def test_load_aware_balance_falls_back_on_stale_metrics():
    coord = _inject_cluster()
    # age the metrics past the staleness gate: load mode must fall back to
    # count (which sees balance) instead of acting on dead figures
    for sid in list(coord.store_metrics):
        snap, _at = coord.store_metrics[sid]
        coord.store_metrics[sid] = (
            snap, _at - coord.METRICS_STALE_MS - 1000)
    assert BalanceLeaderScheduler(coord, mode="load").plan() == []


def test_load_aware_balance_does_not_ping_pong_single_hot_leader():
    """One dominant leader, zero-load peer: moving it would mirror the
    skew exactly and the next tick would move it back — the strict
    gap-shrink guard must refuse (review fix)."""
    from dingo_tpu.store.region import RegionDefinition

    coord = CoordinatorControl(MemEngine(), replication=2)
    coord.register_store("s0")
    coord.register_store("s1")
    coord.regions[1] = RegionDefinition(
        region_id=1, start_key=b"", end_key=b"", peers=["s0", "s1"])
    coord.store_heartbeat(
        "s0", region_ids=[1], leader_region_ids=[1],
        metrics=StoreMetricsSnapshot("s0", regions=[
            RegionMetricsSnapshot(region_id=1, is_leader=True,
                                  search_qps=500.0)]))
    coord.store_heartbeat(
        "s1", region_ids=[1], leader_region_ids=[],
        metrics=StoreMetricsSnapshot("s1", regions=[
            RegionMetricsSnapshot(region_id=1)]))
    assert BalanceLeaderScheduler(coord, mode="load").plan() == []


def test_load_aware_balance_ignores_noise_gaps():
    # sub-unit load gap (hysteresis floor): no churn over 0.2 QPS skew
    coord = _inject_cluster(hot_qps=0.2, warm_qps=0.0, cold_qps=0.0)
    assert BalanceLeaderScheduler(coord, mode="load").plan() == []


def test_load_aware_balance_no_op_when_load_is_even():
    # s0: 5 + 5, s1: 10 — equal measured load, no transfer despite 2-vs-1
    coord = _inject_cluster(hot_qps=5.0, warm_qps=5.0, cold_qps=10.0)
    assert BalanceLeaderScheduler(coord, mode="load").plan() == []


# ---------------------------------------------------------------------------
# collector resilience (review fixes)
# ---------------------------------------------------------------------------

def test_failed_collection_keeps_last_good_snapshot(two_store_cluster):
    coord, nodes = two_store_cluster
    node = nodes["s0"]
    node.heartbeat_once()
    good = node.metrics.collect()
    assert good.engine_key_count >= 0
    # break the engine count: the pass fails, but the last GOOD snapshot
    # must keep shipping (an empty one would zero the coordinator's view
    # and bait load-aware balancing toward the malfunctioning store)
    orig = node.raw.count
    node.raw.count = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("compaction"))
    errors_before = node.metrics.collect_errors
    node.metrics._latest_mono = 0.0
    got = node.metrics.collect()
    node.raw.count = orig
    assert node.metrics.collect_errors > errors_before
    assert got is good                       # not the broken partial snap
    assert node.metrics.latest is good


def test_dropped_region_series_leave_the_registry(two_store_cluster):
    coord, nodes = two_store_cluster
    definition = coord.create_region(
        start_key=b"", end_key=b"", region_type=RegionType.STORE)
    rid = definition.region_id
    leader = drive_until_leader(coord, nodes, rid)
    from dingo_tpu.common.metrics import METRICS

    leader.metrics.collect()
    key = f"store.region.key_count{{region={rid}}}"
    assert key in METRICS.dump()
    leader.delete_region(rid)
    leader.metrics.collect()
    # the region's gauges must not report last values forever
    assert key not in METRICS.dump()


# ---------------------------------------------------------------------------
# plain-HTTP exposition (scrapers can't speak grpc)
# ---------------------------------------------------------------------------

def test_metrics_http_server_scrape():
    import json
    import urllib.request

    from dingo_tpu.metrics.http import MetricsHttpServer

    m = MetricsRegistry()
    m.gauge("store.engine.key_count").set(77)
    m.counter("rpc.requests").add(3)
    srv = MetricsHttpServer(port=0, registry=m)
    port = srv.start()
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            series = parse_prometheus(r.read().decode())
        assert series[("store_engine_key_count", ())] == 77.0
        assert series[("rpc_requests", ())] == 3.0
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/vars", timeout=5
        ) as r:
            assert json.load(r)["store.engine.key_count"] == 77.0
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ) as r:
            assert r.read() == b"ok\n"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# metrics_report tool
# ---------------------------------------------------------------------------

def test_metrics_report_rates():
    import importlib

    mr = importlib.import_module("tools.metrics_report")
    before = {
        "vector_add{region=1}": 100,
        "store.region.key_count{region=1}": 500,
        "vector_search{region=1}": {"count": 10, "qps": 1.0,
                                    "avg_us": 100.0, "p50_us": 90.0,
                                    "p99_us": 200.0},
    }
    after = {
        "vector_add{region=1}": 400,
        "store.region.key_count{region=1}": 800,
        "vector_search{region=1}": {"count": 110, "qps": 10.0,
                                    "avg_us": 100.0, "p50_us": 95.0,
                                    "p99_us": 210.0},
        "new.series": 7,
    }
    text = mr.report(before, after, seconds=10.0)
    assert "vector_add{region=1}" in text
    assert "+30.00/s" in text             # (400-100)/10
    assert "rate=10.00/s" in text         # (110-10)/10 search calls
    assert "added" in text                # new.series
