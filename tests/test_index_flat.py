"""TpuFlat functional + filter + save/load + recall tests.

Mirrors reference suites test/unit_test/vector/test_vector_index_flat.cc,
test_vector_index_flat_search_param.cc, test_vector_index_recall_flat.cc
(recall harness at :103-170), test_vector_index_snapshot.cc."""

import numpy as np
import pytest

from dingo_tpu.index import (
    FilterSpec,
    IndexParameter,
    IndexType,
    VectorIndex,
    new_index,
)
from dingo_tpu.index.base import InvalidParameter, NotSupported
from dingo_tpu.ops.distance import Metric


def make_index(metric=Metric.L2, dim=32) -> VectorIndex:
    return new_index(
        1001, IndexParameter(index_type=IndexType.FLAT, dimension=dim, metric=metric)
    )


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(7)
    x = rng.standard_normal((1000, 32)).astype(np.float32)
    ids = np.arange(100, 1100, dtype=np.int64)
    return ids, x


def test_add_search_exact(corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    assert idx.get_count() == 1000
    q = x[[3, 500]]
    res = idx.search(q, 5)
    assert res[0].ids[0] == ids[3] and res[1].ids[0] == ids[500]
    assert res[0].distances[0] == pytest.approx(0.0, abs=1e-3)
    # full exactness vs numpy
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = ids[np.argsort(d, 1)[:, :5]]
    got = np.stack([r.ids for r in res])
    np.testing.assert_array_equal(got, want)


def test_duplicate_add_rejected(corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids[:10], x[:10])
    with pytest.raises(InvalidParameter):
        idx.add(ids[5:15], x[5:15])


def test_upsert_replaces(corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids[:10], x[:10])
    new_vec = x[999][None, :]
    idx.upsert(ids[:1], new_vec)
    res = idx.search(new_vec, 1)
    assert res[0].ids[0] == ids[0]
    assert idx.get_count() == 10


def test_delete_tombstones(corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids[:100], x[:100])
    idx.delete(ids[:50])
    assert idx.get_count() == 50
    res = idx.search(x[10][None, :], 3)
    assert all(i >= ids[50] for i in res[0].ids)
    # deleting unknown ids is a no-op (reference ignores missing ids)
    idx.delete(np.array([999999], np.int64))


def test_search_more_than_count(corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids[:3], x[:3])
    res = idx.search(x[0][None, :], 10)
    assert len(res[0].ids) == 3  # fewer results than topk, no -1s


def test_ip_and_cosine_metrics(corpus):
    ids, x = corpus
    for metric in (Metric.INNER_PRODUCT, Metric.COSINE):
        idx = make_index(metric)
        idx.add(ids, x)
        q = x[[42]]
        res = idx.search(q, 5)
        if metric is Metric.INNER_PRODUCT:
            want = ids[np.argsort(-(q @ x.T), 1)[:, :5]]
        else:
            qn = q / np.linalg.norm(q, axis=1, keepdims=True)
            xn = x / np.linalg.norm(x, axis=1, keepdims=True)
            want = ids[np.argsort(-(qn @ xn.T), 1)[:, :5]]
        np.testing.assert_array_equal(res[0].ids, want[0])
        # descending similarity
        assert (np.diff(res[0].distances) <= 1e-5).all()


def test_range_filter(corpus):
    """RangeFilterFunctor parity (vector_index.h:75-84): region split child
    serves [lo, hi) of the parent's id space."""
    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    f = FilterSpec(ranges=[(100, 200), (300, 400)])
    res = idx.search(x[:4], 20, filter_spec=f)
    for r in res:
        assert (((r.ids >= 100) & (r.ids < 200)) | ((r.ids >= 300) & (r.ids < 400))).all()
        assert len(r.ids) == 20


def test_include_ids_filter(corpus):
    """SortFilterFunctor / scalar pre-filter parity (vector_reader.cc:853)."""
    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    allow = ids[::7]
    res = idx.search(x[:2], 10, filter_spec=FilterSpec(include_ids=allow))
    allow_set = set(allow.tolist())
    for r in res:
        assert set(r.ids.tolist()) <= allow_set
    # numpy reference: best allowed neighbors
    d = ((x[:2][:, None, :] - x[None, :, :]) ** 2).sum(-1)
    mask = np.isin(ids, allow)
    d[:, ~mask] = np.inf
    want = ids[np.argsort(d, 1)[:, :10]]
    np.testing.assert_array_equal(np.stack([r.ids for r in res]), want)


def test_exclude_ids_filter(corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    res = idx.search(x[[3]], 5, filter_spec=FilterSpec(exclude_ids=ids[[3]]))
    assert ids[3] not in res[0].ids


def test_range_search(corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    q = x[[0]]
    d = ((q - x) ** 2).sum(-1)
    radius = float(np.sort(d)[20])
    res = idx.range_search(q, radius)
    want = set(ids[d <= radius].tolist())
    assert set(res[0].ids.tolist()) == want


def test_save_load_roundtrip(tmp_path, corpus):
    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    idx.delete(ids[:10])
    idx.apply_log_id = 777
    idx.save(str(tmp_path))
    idx2 = make_index()
    idx2.load(str(tmp_path))
    assert idx2.get_count() == 990
    assert idx2.apply_log_id == 777
    r1 = idx.search(x[[500]], 5)[0]
    r2 = idx2.search(x[[500]], 5)[0]
    np.testing.assert_array_equal(r1.ids, r2.ids)


def test_capacity_growth():
    rng = np.random.default_rng(0)
    idx = make_index(dim=8)
    for batch in range(5):
        ids = np.arange(batch * 2000, (batch + 1) * 2000, dtype=np.int64)
        idx.add(ids, rng.standard_normal((2000, 8)).astype(np.float32))
    assert idx.get_count() == 10000
    assert idx.store.capacity >= 10000
    res = idx.search(rng.standard_normal((1, 8)).astype(np.float32), 3)
    assert len(res[0].ids) == 3


def test_recall_harness(corpus):
    """Recall@k == 1.0 for exact flat (reference
    test_vector_index_recall_flat.cc:103-170 computes the same)."""
    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    rng = np.random.default_rng(11)
    q = x[rng.choice(1000, 32, replace=False)] + 0.01 * rng.standard_normal(
        (32, 32)
    ).astype(np.float32)
    res = idx.search(q, 10)
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    want = ids[np.argsort(d, 1)[:, :10]]
    recall = np.mean(
        [len(set(r.ids) & set(w)) / 10 for r, w in zip(res, want)]
    )
    assert recall == 1.0


def test_bruteforce_type_not_supported():
    idx = new_index(
        1, IndexParameter(index_type=IndexType.BRUTEFORCE, dimension=8)
    )
    with pytest.raises(NotSupported):
        idx.search(np.zeros((1, 8), np.float32), 1)


def test_binary_flat_hamming():
    rng = np.random.default_rng(1)
    dim_bits = 64
    x = rng.integers(0, 256, (200, dim_bits // 8), dtype=np.uint8)
    ids = np.arange(200, dtype=np.int64)
    idx = new_index(
        2,
        IndexParameter(
            index_type=IndexType.BINARY_FLAT,
            dimension=dim_bits,
            metric=Metric.HAMMING,
        ),
    )
    idx.add(ids, x)
    res = idx.search(x[[5]], 3)
    assert res[0].ids[0] == 5 and res[0].distances[0] == 0.0


def test_dimension_mismatch_rejected(corpus):
    ids, x = corpus
    idx = make_index()
    with pytest.raises(InvalidParameter):
        idx.add(ids[:2], np.zeros((2, 16), np.float32))
    idx.add(ids[:2], x[:2])
    with pytest.raises(InvalidParameter):
        idx.search(np.zeros((1, 16), np.float32), 1)


def test_fused_pallas_path_matches_xla(corpus):
    """FLAGS.use_pallas_fused_search routes flat search through the fused
    streaming kernel with identical results (interpret mode off-TPU)."""
    from dingo_tpu.common.config import FLAGS

    ids, x = corpus
    idx = make_index()
    idx.add(ids, x)
    want = idx.search(x[:4], 7)
    FLAGS.set("use_pallas_fused_search", True)
    try:
        got = idx.search(x[:4], 7)
    finally:
        FLAGS.set("use_pallas_fused_search", "auto")
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a.ids, b.ids)
        np.testing.assert_allclose(a.distances, b.distances, rtol=5e-3,
                                   atol=5e-2)
