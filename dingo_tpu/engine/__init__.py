"""Engines: raw KV storage, replication engines, storage facade, txn.

Mirrors reference src/engine/ (raw_engine.h, engine.h, storage.{h,cc},
txn_engine_helper.{h,cc})."""
