"""Two-tier configuration: per-role config file + flag registry.

Reference: src/config/ — YamlConfig (yaml-cpp) loaded per role at boot,
ConfigManager singleton, ConfigHelper typed accessors with defaults
(config_helper.h:25-53), plus gflags for every tunable; yaml values override
gflag defaults at boot (server.cc:500-512).

No yaml parser is baked into this image, so config files are TOML-like
`section.key = value` lines (plus JSON support); the Flag registry plays the
gflags role with runtime mutability for the hot-changeable set.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Dict, Optional

_UNSET = object()


class Flag:
    def __init__(self, name: str, default: Any, help_: str = "",
                 mutable: bool = False):
        self.name = name
        self.default = default
        self.help = help_
        self.mutable = mutable
        self.value = default


class FlagRegistry:
    """DEFINE_*/FLAGS_* analog with optional hot changes
    (BRPC_VALIDATE_GFLAG pattern, vector_reader.cc:72)."""

    def __init__(self):
        self._flags: Dict[str, Flag] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, help_: str = "",
               mutable: bool = False) -> None:
        with self._lock:
            if name not in self._flags:
                self._flags[name] = Flag(name, default, help_, mutable)

    def get(self, name: str) -> Any:
        return self._flags[name].value

    def set(self, name: str, value: Any, boot: bool = False) -> None:
        with self._lock:
            flag = self._flags[name]
            if not boot and not flag.mutable:
                raise PermissionError(f"flag {name} is not hot-changeable")
            flag.value = type(flag.default)(value) if flag.default is not None \
                else value

    def all(self) -> Dict[str, Any]:
        return {k: f.value for k, f in self._flags.items()}


FLAGS = FlagRegistry()

# reference limits (index_service.cc:50-51,206; vector_reader.cc:60-61)
FLAGS.define("vector_max_batch_count", 4096)
FLAGS.define("vector_max_request_size", 32 * 1024 * 1024)
FLAGS.define("vector_index_bruteforce_batch_count", 2048, mutable=True)
FLAGS.define("vector_max_range_search_result_count", 1024, mutable=True)
FLAGS.define("enable_async_vector_search", True, mutable=True)
FLAGS.define("search_coalescing_window_ms", 0.0, mutable=True,
             help_="merge concurrent same-shaped VectorSearch RPCs into one "
                   "device batch within this window (0 disables); fills the "
                   "MXU batch dimension instead of spending threads")
FLAGS.define("server_heartbeat_interval_s", 10, mutable=True)
FLAGS.define("raft_snapshot_threshold", 10000, mutable=True)
FLAGS.define("region_max_size_bytes", 256 * 1024 * 1024, mutable=True)
FLAGS.define("split_check_approximate_keys", 1_000_000, mutable=True)
FLAGS.define("gc_retention_ms", 3_600_000, mutable=True)
FLAGS.define("use_pallas_fused_search", "auto", mutable=True,
             help_="route flat L2/IP searches through the fused Pallas "
                   "streaming kernel (no [b,n] HBM materialization). "
                   "'auto' (default) enables it on TPU once the store is "
                   "large enough to amortize the streaming grid "
                   "(capacity >= 2048 — below that one XLA matmul wins). "
                   "True/False force; same tri-state crossover discipline "
                   "as use_pallas_ivf_search")
FLAGS.define("ivfpq_rerank_factor", 8, mutable=True,
             help_="host-vectors IVF_PQ reranks topk*factor ADC candidates "
                   "exactly from host rows (1 disables); same prune+rerank "
                   "recipe as the diskann role")
FLAGS.define("lsm_sync_writes", False, mutable=True,
             help_="fsync the native LSM WAL on every commit: power-loss "
                   "durability instead of process-crash durability. Off by "
                   "default — raft replication is the availability story "
                   "and per-commit fsync costs ~ms (rocksdb's "
                   "WriteOptions.sync analog)")
FLAGS.define("wal_checkpoint_bytes", 64 * 1024 * 1024, mutable=True,
             help_="WalEngine folds the WAL into a checkpoint once it "
                   "exceeds this size, bounding restart replay time")
FLAGS.define("diskann_server_addr", "", mutable=True,
             help_="endpoint of the --role=diskann server; required to "
                   "create VECTOR_INDEX_TYPE_DISKANN indexes")
FLAGS.define("diskann_rerank_io_rows", 8192, mutable=True,
             help_="exact-rerank disk gathers read at most this many "
                   "(sorted, deduplicated) rows per memmap access — an IO "
                   "budget so a big batch*k*rerank_factor fan-out cannot "
                   "issue one unbounded random-read burst on spinning "
                   "or network storage")
FLAGS.define("use_mesh_sharded_flat", False, mutable=True,
             help_="serve FLAT regions from a mesh-sharded index "
                   "(TpuShardedFlat): rows over the 'data' axis, feature "
                   "dim over 'dim', search fan-out/merge via XLA "
                   "collectives over ICI")
FLAGS.define("use_mesh_sharded_ivf", False, mutable=True,
             help_="serve IVF_FLAT regions from a mesh-sharded index "
                   "(TpuShardedIvfFlat): rows shard over 'data', "
                   "distributed k-means train, per-shard bucket scan + "
                   "all_gather top-k merge over ICI")
FLAGS.define("use_mesh_sharded_ivfpq", False, mutable=True,
             help_="serve IVF_PQ regions from a mesh-sharded index "
                   "(TpuShardedIvfPq): codes shard over 'data', per-shard "
                   "ADC prune + shard-local exact rerank + all_gather "
                   "top-k merge over ICI")
FLAGS.define("mesh_dim_axis", 1, mutable=True,
             help_="size of the mesh 'dim' (tensor-parallel) axis used by "
                   "mesh-sharded indexes; 'data' axis = n_devices // dim")
FLAGS.define("mesh_batch_axis", 1, mutable=True,
             help_="size of the mesh 'batch' (query data-parallel) axis: "
                   "coalesced query batches split across batch replicas "
                   "while every replica scans the full set of row shards; "
                   "vector state replicates over this axis (read scaling). "
                   "Must be a power of two so the shape-bucket ladder's "
                   "pow2 batch padding stays divisible; 1 disables")
FLAGS.define("mesh_replicas", 1, mutable=True,
             help_="replica-group fan-out for mesh-sharded regions: the "
                   "factory builds this many full index replicas on "
                   "disjoint device slices and routes searches across "
                   "them (parallel/replica_group.py); writes fan out to "
                   "every member; 1 disables")
FLAGS.define("mesh_replica_route", "rr", mutable=True,
             help_="replica-group routing policy: 'rr' (round robin) or "
                   "'load' (fewest in-flight searches)")
FLAGS.define("mesh_collective_merge", True, mutable=True,
             help_="merge per-shard shortlists ON DEVICE with an in-jit "
                   "all_gather + top_k (the ICI path). Off = the capped "
                   "fallback: each shard ships only its local [b, k] "
                   "shortlist to the host, merged there (debug/A-B arm; "
                   "never transfers full score matrices either way)")
FLAGS.define("balance_replica_mode", "off", mutable=True,
             help_="coordinator replica planning: 'off' or 'auto' (scale "
                   "a region's read-replica count from its measured QPS "
                   "via the store-metrics plane; placement picks the "
                   "least-loaded stores)")
FLAGS.define("balance_replica_qps_target", 50.0, mutable=True,
             help_="replica planning aims for at most this many QPS per "
                   "replica before adding another (auto mode)")
FLAGS.define("ivf_prune_inbucket_bound", True, mutable=True,
             help_="pruned-scan kernels refresh the k-th-best bound "
                   "BETWEEN dimension blocks inside a bucket/row-block "
                   "from the candidates' own suffix-norm lower bounds "
                   "(PDX finer-grained threshold), not only from shortlist "
                   "merges at bucket boundaries; off = PR 6 behavior")
FLAGS.define("metrics_collect_interval_s", 5.0, mutable=True,
             help_="StoreMetricsCollector crontab period; heartbeats also "
                   "refresh snapshots older than this so beats never ship "
                   "stale figures even without the crontab")
FLAGS.define("metrics_http_port", 0, mutable=False,
             help_="bind a plain-HTTP sidecar on this port serving "
                   "/metrics (Prometheus text format) and /vars (JSON); "
                   "0 disables — scrapers can't speak the grpc "
                   "DebugService.MetricsDump")
FLAGS.define("balance_mode", "count", mutable=True,
             help_="leader balancing signal: 'count' (leader tallies) or "
                   "'load' (measured per-region QPS + memory bytes from "
                   "store metrics; falls back to count while metrics are "
                   "missing or stale)")
FLAGS.define("trace_sampling_rate", 0.0, mutable=True,
             help_="fraction of ingress requests recording a full span "
                   "tree into dingo_tpu/trace (0 disables; 1 records "
                   "everything). Decided once at the trace root; children "
                   "and remote hops inherit the decision")
FLAGS.define("slow_query_ms", 500.0, mutable=True,
             help_="a sampled root span slower than this lands in the "
                   "slow-query log (retained separately from the span "
                   "ring so fast-trace churn cannot evict slow evidence)")
FLAGS.define("ivf_compact_interval_s", 60.0, mutable=True,
             help_="period of the IVF view-compaction crontab: restores "
                   "the dense bucket layout (full rebuild) off the search "
                   "path once tombstone/spill garbage accumulates")
FLAGS.define("ivf_compact_tombstone_ratio", 0.25, mutable=True,
             help_="compact an IVF view once tombstoned rows exceed this "
                   "fraction of (live + tombstoned) — dead rows still burn "
                   "scan FLOPs until compaction reclaims them")
FLAGS.define("ivf_compact_spill_ratio", 0.5, mutable=True,
             help_="compact once incremental appends allocated this many "
                   "extra spill buckets relative to the dense build — "
                   "ragged chains cost probe-expansion budget")
FLAGS.define("ivf_shape_bucketing", True, mutable=True,
             help_="round (topk, nprobe) up to the {1,1.5}x-pow2 ladder so "
                   "steady-state serving reuses a handful of compiled "
                   "programs instead of recompiling per request shape; "
                   "results are sliced back to the requested topk")
FLAGS.define("vector_precision", "fp32", mutable=True,
             help_="default precision tier for float FLAT/IVF_FLAT region "
                   "indexes when VectorIndexParameter.precision is unset: "
                   "'fp32' (exact storage+compute), 'bf16' (bf16 storage, "
                   "bf16 MXU multiplies, fp32 accumulate — 2x HBM "
                   "capacity), 'sq8' (uint8 scalar-quantized storage, "
                   "decode-on-the-fly bf16 compute, fp32 accumulate — 4x "
                   "HBM capacity). Per-index override via the parameter")
FLAGS.define("rerank_cache_rows", 0, mutable=True,
             help_="device-resident exact-rerank row cache size (rows per "
                   "bf16/sq8 index; 0 disables the cache). Cached rows "
                   "rerank quantized shortlists ON DEVICE (no host "
                   "gather); uncached candidates keep their quantized "
                   "score, so a partial cache only improves ranking")
FLAGS.define("rerank_cache_dtype", "float32", mutable=True,
             help_="dtype of the rerank row cache: 'float32' (exact "
                   "rerank) or 'bfloat16' (half the cache HBM; rerank is "
                   "then bf16-exact, still above SQ8 fidelity)")
FLAGS.define("quantized_rerank_factor", 4, mutable=True,
             help_="bf16/sq8 searches with a non-empty rerank cache scan "
                   "topk*factor candidates and rerank them exactly on "
                   "device (1 disables the stage)")
FLAGS.define("obs_flight_buffer_s", 30.0, mutable=True,
             help_="flight-recorder metrics window: bundles carry metric "
                   "deltas over the last this-many seconds of ticks (the "
                   "store-metrics crontab drives the tick ring)")
FLAGS.define("obs_flight_max_bundles", 16, mutable=True,
             help_="flight-recorder retention: newest N compressed "
                   "bundles kept in memory (0 disables capturing)")
FLAGS.define("obs_exemplars", True, mutable=True,
             help_="attach trace-id exemplars to latency-series outliers "
                   "in the Prometheus exposition (OpenMetrics syntax) so "
                   "a scrape links a bad bucket to its trace/flight "
                   "bundle")
FLAGS.define("hbm_watermark_interval_s", 10.0, mutable=True,
             help_="period of the process HBM watermark poll (allocator "
                   "bytes-in-use/limit/peak -> hbm.* gauges); per-region "
                   "owner ledgers additionally refresh with every "
                   "store-metrics collection pass")
FLAGS.define("use_pallas_ivf_search", "auto", mutable=True,
             help_="route trained IVF_FLAT searches through the Pallas "
                   "list-DMA kernel (streams only probed buckets to VMEM; "
                   "no per-rank [b,cap,d] gather materialization). 'auto' "
                   "(default) enables it on TPU when dimension >= 256: "
                   "measured on-chip r3 at 1Mx768/nlist=1024/b=64 the "
                   "kernel is 4.9x the XLA path (33 vs 163 ms/batch), but "
                   "at 100Kx128/nlist=64 it LOSES 1.3x (18 vs 14) — thin "
                   "rows starve the per-bucket DMA. True/False force.")
FLAGS.define("ivf_dim_block", 128, mutable=True,
             help_="dimension-block width of the PDX-style vertical scan "
                   "layout (per-block partial distances let the pruning "
                   "kernels stop scanning candidates that cannot beat the "
                   "running k-th best). 128 = one TPU lane tile; an index "
                   "only builds blocked metadata when its (padded) "
                   "dimension is a multiple with >= 2 blocks")
FLAGS.define("ivf_prune_check_interval", 1, mutable=True,
             help_="pruned-scan kernels re-evaluate the partial-distance "
                   "bound every N dimension blocks (1 = every block). "
                   "Larger values trade pruning opportunity for less VPU "
                   "compare/mask overhead per block")
FLAGS.define("ivf_prune_scan", "auto", mutable=True,
             help_="use the early-pruning dimension-blocked scan kernels "
                   "wherever the Pallas path is active and the index has "
                   "blocked metadata. 'auto' (default) = on (the kernels "
                   "fall back to the plain fused scan when the dimension "
                   "doesn't block); False forces the non-pruning kernels")
FLAGS.define("hnsw_device_search", "auto", mutable=True,
             help_="route HNSW searches through the device-resident graph "
                   "tier: a batched lockstep beam search over the flattened "
                   "level-0 adjacency (ops/beam.py), quantized-tier compute "
                   "+ exact device rerank of the final beam. 'auto' "
                   "(default) enables it on TPU only — the XLA walk wins "
                   "when hundreds of queries amortize each gather/einsum "
                   "round; the host C++ beam stays the CPU arm and the "
                   "parity oracle. True/False force")
FLAGS.define("hnsw_device_beam", 0, mutable=True,
             help_="fixed candidate-beam width for the device HNSW walk; "
                   "0 (default) derives it from the request ef via the "
                   "{1,1.5}x-pow2 shape-bucket ladder so steady-state "
                   "serving reuses a handful of compiled programs")
FLAGS.define("hnsw_max_iters", 48, mutable=True,
             help_="hard cap on lockstep beam-expansion rounds of the "
                   "device HNSW walk (one round = expand every beam entry "
                   "one hop). The walk exits earlier once every query's "
                   "beam has converged; the cap bounds worst-case latency "
                   "on adversarial graphs")
FLAGS.define("hnsw_device_build", "auto", mutable=True,
             help_="build bulk HNSW graphs on the device "
                   "(ops/graph_build.py): pow2 insert batches walk the "
                   "partially-built adjacency with the lockstep beam "
                   "kernel, occlusion-prune neighbors as masked top-k "
                   "over the candidate score matrix, and install reverse "
                   "edges with degree-clamped re-pruning; the native "
                   "graph back-fills lazily on first host-path use. "
                   "'auto' (default) = TPU-only — MXU batch throughput "
                   "is the whole point; the host insert loop stays the "
                   "CPU arm and the parity oracle. True/False force")
FLAGS.define("hnsw_build_batch", 256, mutable=True,
             help_="rows per device bulk-build insert batch (rounded up "
                   "to a power of two; the final partial batch pads with "
                   "dropped lanes). Larger batches amortize more MXU "
                   "work per dispatch but discover neighbors against a "
                   "staler partial graph")
FLAGS.define("hnsw_build_alpha", 1.0, mutable=True,
             help_="occlusion-pruning diversification factor of the "
                   "device bulk build (DiskANN's alpha): a candidate is "
                   "pruned once it scores closer to an already-kept "
                   "neighbor than to the inserted point, with the kept "
                   "score scaled by alpha^2. >1 keeps longer edges "
                   "(denser graph, better recall on clustered data)")
FLAGS.define("train_sample_rows", 65536, mutable=True,
             help_="train-sample row cap shared by every k-means/PQ "
                   "train path (IVF coarse quantizer, PQ codebooks, the "
                   "sharded plane's seeding sample). Trainers gather at "
                   "most this many stored rows — on device when the rows "
                   "live there, so only the sample (or just centroids) "
                   "ever crosses to the host. 0 = full corpus: every "
                   "live row feeds training and derived caps "
                   "(max_points_per_centroid * nlist) are lifted too")
FLAGS.define("quality_sample_rate", 0.0, mutable=True,
             help_="fraction of live searches re-answered EXACTLY by the "
                   "shadow scan and scored for recall/RBO/score-gap "
                   "(obs/quality.py). Head-sampled like tracing: 0 "
                   "(default) is a zero-alloc noop — no shadow kernels, "
                   "no mirrors, no estimator state; 1 scores every batch "
                   "(bench/tests). Scoring runs on an async lane off the "
                   "request's critical path")
FLAGS.define("quality_slo_recall", 0.95, mutable=True,
             help_="recall@k service-level objective the quality plane "
                   "reports against and the SLO tuner steers toward: the "
                   "tuner tightens knobs while the live estimate's CI "
                   "upper bound sits below this, relaxes when the lower "
                   "bound clears it with margin")
FLAGS.define("quality_window_s", 60.0, mutable=True,
             help_="sliding window of the live quality estimators: "
                   "samples older than this age out of the recall "
                   "estimate/CI (longer = tighter CI, slower reaction)")
FLAGS.define("tuner_enabled", False, mutable=True,
             help_="run the closed-loop SLO parameter controller "
                   "(obs/tuner.py) on the store crontab: one "
                   "cheap-to-expensive ladder step per tick per region, "
                   "driven by the live recall CI vs quality.slo_recall. "
                   "Requires quality.sample_rate > 0 to have a sensor")
FLAGS.define("tuner_interval_s", 30.0, mutable=True,
             help_="period of the quality_tuner crontab (one knob step "
                   "at most per region per tick; the estimator window "
                   "reset after each step is the hysteresis)")
FLAGS.define("tuner_latency_budget_ms", 0.0, mutable=True,
             help_="vector_search p99 budget the tuner respects: it "
                   "never tightens past it, and relaxes while over it "
                   "(if recall allows). 0 = no latency constraint")
FLAGS.define("qos_enabled", False, mutable=True,
             help_="traffic-shaped serving (obs/pressure.py + the QoS "
                   "coalescer): deadline-aware admission, priority batch "
                   "forming, expiry of dead requests before dispatch, and "
                   "graduated shed/degrade under pressure. Off = observe "
                   "nothing, act on nothing (zero-alloc like tracing); "
                   "deadline METADATA still propagates either way so a "
                   "mid-upgrade fleet keeps the chain")
FLAGS.define("qos_default_deadline_ms", 0.0, mutable=True,
             help_="deadline granted to requests arriving WITHOUT an "
                   "x-dingo-deadline-ms header while qos.enabled (0 = no "
                   "implied deadline: headerless requests are never "
                   "expired or deadline-shed)")
FLAGS.define("qos_tenant_header", "x-dingo-tenant", mutable=True,
             help_="gRPC metadata key carrying the tenant id for "
                   "per-tenant demand accounting and admission "
                   "(deployments can point this at an existing auth "
                   "header)")
FLAGS.define("qos_max_queue_ms", 50.0, mutable=True,
             help_="queue-wait bound the QoS layer defends: admission "
                   "sheds low-priority work once the estimated wait "
                   "exceeds it (priority >= 2 is exempt) and the shed "
                   "controller escalates the degrade ladder while the "
                   "recent queue-wait watermark sits above it")
FLAGS.define("qos_shed_policy", "degrade_drop", mutable=True,
             help_="pressure response: 'off' (observe only), 'degrade' "
                   "(knob ladder only: drop rerank -> lower nprobe/ef -> "
                   "advisory sq8), 'drop' (admission shed only), "
                   "'degrade_drop' (both, default)")
FLAGS.define("qos_tenant_queue_rows", 0, mutable=True,
             help_="per-tenant cap on queued query rows inside the "
                   "coalescer (admission sheds the excess with "
                   "reason=tenant_limit); 0 = unlimited")
FLAGS.define("qos_shed_interval_s", 2.0, mutable=True,
             help_="period of the qos_shed crontab driving the graduated "
                   "degrade ladder (one level per tick each way)")
FLAGS.define("integrity_enabled", True, mutable=True,
             help_="maintain incremental per-artifact state digests "
                   "(obs/integrity.py): every index write folds its batch "
                   "into an order-invariant set digest per artifact (rows, "
                   "sq8 codes, blocked mirror, HNSW adjacency, IVF bucket "
                   "assignment) with O(batch) host work; digests ride "
                   "heartbeats for replica divergence detection and gate "
                   "snapshot restores. Off = no ledgers, no scrub, no "
                   "restore verification")
FLAGS.define("integrity_scrub_interval_s", 60.0, mutable=True,
             help_="period of the consistency_scrub crontab: recompute "
                   "full digests from device state (chunked under "
                   "store.device_lock) and check them against the "
                   "incremental ledger — catches silent HBM/restore "
                   "corruption AND ledger bookkeeping bugs")
FLAGS.define("integrity_flight_on_divergence", True, mutable=True,
             help_="capture a flight-recorder bundle (rate-limited per "
                   "reason) when the scrub finds a corrupted artifact or "
                   "the coordinator sees replicas diverge at equal "
                   "applied indices; the bundle carries the digest "
                   "vectors of both sides")
FLAGS.define("retry_rounds", 3, mutable=True,
             help_="full target-rotation rounds the client RetryPolicy "
                   "makes before giving up (each round tries every "
                   "non-breaker-open target once)")
FLAGS.define("retry_base_backoff_ms", 25.0, mutable=True,
             help_="base of the equal-jitter backoff between rotation "
                   "rounds: sleep ~ d/2 + U(0, d/2) where "
                   "d = min(cap, base*2^round) — the d/2 floor guarantees "
                   "an election-scale wait actually happens while the "
                   "jitter half spreads the herd; always clamped to the "
                   "request's remaining deadline budget")
FLAGS.define("retry_max_backoff_ms", 1000.0, mutable=True,
             help_="cap of the equal-jitter backoff between rounds")
FLAGS.define("retry_breaker_threshold", 5, mutable=True,
             help_="consecutive connection-level failures that open a "
                   "target's circuit breaker (in-band responses — even "
                   "NotLeader — count as success: the endpoint is alive)")
FLAGS.define("retry_breaker_cooldown_s", 5.0, mutable=True,
             help_="how long an open breaker skips its target before "
                   "admitting one half-open probe")
FLAGS.define("retry_hedge_enabled", False, mutable=True,
             help_="hedged reads: fire a second VectorSearch attempt at "
                   "the next replica when the primary hasn't answered "
                   "within its p99-derived delay; first success wins. "
                   "Idempotent reads only, budget-gated, attempts "
                   "stamped with x-dingo-attempt")
FLAGS.define("retry_hedge_min_delay_ms", 5.0, mutable=True,
             help_="floor of the hedge delay (covers the cold start "
                   "before enough latency samples exist for a p99)")
FLAGS.define("device_recovery_enabled", True, mutable=True,
             help_="graduated HBM OOM recovery ladder (index/recovery.py): "
                   "on an OOM during device write/search, drop rerank "
                   "caches, evict blocked/adjacency mirrors, retry once; "
                   "if still OOM, mark the region device-degraded (served "
                   "by the host exact path) and schedule background "
                   "re-materialization at lower precision. Off = OOMs "
                   "propagate raw")
FLAGS.define("device_recovery_remat_precision", "sq8", mutable=True,
             help_="precision tier the background re-materialization "
                   "rebuilds a device-degraded region at (advisory-lower "
                   "than the configured tier; the region definition keeps "
                   "its declared precision)")
FLAGS.define("pipeline_enabled", "auto", mutable=True,
             help_="stall-free serving pipeline: the coalescer flush "
                   "thread dispatches every due batch's kernels before any "
                   "resolve runs, resolves drain on a completion lane, and "
                   "query staging double-buffers H2D uploads. 'auto' = "
                   "TPU-only (on CPU the backend is synchronous so overlap "
                   "buys nothing and the extra thread hop costs latency). "
                   "True/False force; same tri-state crossover discipline "
                   "as hnsw_device_search")
FLAGS.define("pipeline_depth", 2, mutable=True,
             help_="staging-ring depth per coalescer key (pow2-ladder "
                   "shaped host buffers): batch N+1's query upload can "
                   "overlap batch N's compute up to this many batches in "
                   "flight. 1 degenerates to the serial path (staging "
                   "still used, no overlap); 2 is classic double "
                   "buffering")
FLAGS.define("cache_enabled", False, mutable=True,
             help_="serving-edge result cache + in-flight query dedupe "
                   "(dingo_tpu/cache/): identical query rows inside one "
                   "coalescer flush window collapse to a single kernel "
                   "row, and exact repeats of plain searches are answered "
                   "from a bounded per-region result cache keyed on "
                   "(query fingerprint, SlotStore.mutation_version, "
                   "resolved params) — a hit costs no queue slot and "
                   "dispatches no kernel")
FLAGS.define("cache_max_bytes", 64 * 1024 * 1024, mutable=True,
             help_="LRU bound on the result cache's host memory across "
                   "all regions (approximate accounting: cached rows are "
                   "(id, distance) pairs). 0 disables caching while "
                   "leaving in-flight dedupe active")
FLAGS.define("cache_stale_versions", 1, mutable=True,
             help_="serve-slightly-stale degrade rung: while a region's "
                   "shed ladder is degraded (qos.degrade_level > 0) a "
                   "lookup may fall back to entries at most this many "
                   "mutation_versions behind the live store. 0 = exact "
                   "version only, always")
FLAGS.define("cache_semantic", False, mutable=True,
             help_="semantic (approximate) cache hits via sq8-quantized "
                   "query fingerprints: near-identical queries that "
                   "quantize to the same codes share a cache entry. "
                   "Gated live by the shadow-quality estimator — "
                   "approximate hits serve only while the windowed "
                   "recall CI lower bound holds quality.slo_recall")
FLAGS.define("cache_tenant_share", 0.5, mutable=True,
             help_="per-tenant fairness bound: the fraction of "
                   "cache.max_bytes any single tenant's entries may "
                   "occupy (its own inserts evict its own LRU tail past "
                   "the share). <= 0 or >= 1 disables the bound")
FLAGS.define("heat_enabled", False, mutable=True,
             help_="workload-heat plane (obs/heat.py): per-region "
                   "exponential-decay access sketches fed from data the "
                   "resolve paths already hold on host (probed IVF "
                   "buckets, FLAT/HNSW result slot ranges) — zero new "
                   "device syncs — plus the derived working-set "
                   "estimator. Off = observe nothing, allocate nothing "
                   "(the quality-plane sampling discipline)")
FLAGS.define("heat_decay_s", 300.0, mutable=True,
             help_="e-folding time constant of the heat sketches: a "
                   "unit untouched for this long keeps 1/e of its mass. "
                   "~5 min tracks traffic shifts faster than the "
                   "coordinator acts on them while riding out "
                   "second-scale burstiness")
FLAGS.define("heat_max_entries", 4096, mutable=True,
             help_="bound on live sketch entries per region: past it the "
                   "coldest units are evicted (their mass is the least "
                   "informative). Memory per region stays O(max_entries)")
FLAGS.define("cost_enabled", True, mutable=True,
             help_="per-(kernel, padded-shape-ladder-point) dispatch "
                   "cost model (obs/cost.py) learned from the completion "
                   "lane's stage timings; consulted by QoS "
                   "estimated_wait_ms and the SLO tuner's latency "
                   "budget. Off = the coalescer falls back to its single "
                   "scalar per-row EWMA")
FLAGS.define("cost_prior_row_ms", 0.5, mutable=True,
             help_="conservative per-row service-time prior the wait "
                   "estimator sheds on before the first measured sample "
                   "lands — the first overload burst must not ride in on "
                   "a 0ms estimate (pessimistic on purpose: over-shedding "
                   "a cold store beats serving it into collapse)")
FLAGS.define("capacity_advise", True, mutable=True,
             help_="coordinator capacity plane: roll per-store HBM "
                   "headroom vs heartbeat working-set demand and emit "
                   "ADVISORY-ONLY tier/split recommendations "
                   "(capacity.* metrics, cluster capacity table). Never "
                   "actuates — tiering and split are roadmap items 1-2")
FLAGS.define("capacity_headroom_target", 0.2, mutable=True,
             help_="fraction of a store's HBM the capacity plane wants "
                   "free: below it the coldest region (most resident "
                   "bytes outside its working set) draws a demote "
                   "advisory")
FLAGS.define("tier_enabled", False, mutable=True,
             help_="memory-tier ladder (index/tiering.py): a store-local "
                   "policy loop demotes cold regions along HBM-fp32/bf16 "
                   "-> HBM-sq8 -> host-RAM sq8 -> mmap'd sq8 codes and "
                   "promotes them back on re-warm, every transition "
                   "digest-gated against the state-integrity ledger. "
                   "Policy inputs are the existing planes: capacity "
                   "demote advisories, heat working-set bytes vs HBM "
                   "headroom, windowed search QPS. Off = regions stay at "
                   "their declared tier (today's behavior)")
FLAGS.define("tier_demote_headroom", 0.15, mutable=True,
             help_="free-HBM fraction below which the tier loop demotes "
                   "the coldest resident region one rung (a tighter "
                   "store-local tripwire under the capacity plane's "
                   "capacity_headroom_target advisory threshold, so "
                   "actuation fires before the allocator does)")
FLAGS.define("tier_promote_qps", 5.0, mutable=True,
             help_="sustained windowed vector-search QPS above which a "
                   "demoted region promotes one rung back toward its "
                   "declared tier (given HBM headroom to fit it); the "
                   "same metrics-plane window the shed controller reads")
FLAGS.define("tier_mmap_dir", "", mutable=True,
             help_="directory for the mmap rung's code files (one "
                   "region_<id>.codes per demoted region); empty = a "
                   "per-process temp directory. Local SSD recommended — "
                   "the paged exact scan's latency is this device's "
                   "read bandwidth")
FLAGS.define("tier_interval_s", 30.0, mutable=True,
             help_="tier policy tick cadence (server crontab): each tick "
                   "applies at most one transition per store — demotions "
                   "and promotions are full-region copies, so pacing them "
                   "keeps the build/copy bandwidth bounded")
FLAGS.define("events_enabled", True, mutable=True,
             help_="control-plane flight recorder (obs/events.py): every "
                   "controller actuation — tuner step, shed ladder move, "
                   "tier transition, recovery rung, replica scale, "
                   "capacity advisory, cache stale rung — records a "
                   "structured event with the evidence it decided on. "
                   "Events ride heartbeats to the coordinator for the "
                   "cluster timeline and `cluster explain`. Off = emit "
                   "is one flag read, nothing is allocated or shipped")
FLAGS.define("events_max_entries", 1024, mutable=True,
             help_="bound on the per-node event ring AND the "
                   "coordinator's merged timeline: past it the oldest "
                   "events fall off (never-shipped ones count into "
                   "event.dropped). Controller decisions are crontab-"
                   "paced, so 1024 covers hours of history")
FLAGS.define("events_heartbeat_batch", 128, mutable=True,
             help_="max events one heartbeat carries to the coordinator "
                   "(each ships exactly once — the collector keeps a "
                   "harvest cursor). 0 keeps the ledger node-local "
                   "(EventDump/flight bundles still see it)")
FLAGS.define("vector_blocked_layout", "auto", mutable=True,
             help_="maintain a dimension-blocked ([n_blocks, capacity, "
                   "block_d]) scan mirror + per-block norms in float/sq8 "
                   "SlotStores so FLAT searches can run the pruned "
                   "streaming kernel. 'auto' = on-TPU only (the mirror "
                   "costs one extra copy of the rows in HBM; on CPU "
                   "nothing reads it unless forced). True/False force")


def bf16_compute_native() -> bool:
    """True where bf16 is native matmul currency (TPU MXU) and the bf16
    tier should SCAN bf16-resident data directly. XLA CPU converts bf16
    scalar-ly (~500M elt/s measured on this image — a [64,512,256] rank
    gather pays ~17 ms of convert alone), so the CPU arm keeps the bf16
    tier's SCAN arrays f32: rows still quantize to bf16 at the write
    boundary (identical recall semantics), only the resident compute copy
    widens. Same backend-crossover discipline as use_pallas_ivf_search."""
    import jax

    return jax.default_backend() in ("tpu", "axon")


def _parse_tri(flag) -> Optional[bool]:
    """Parse a tri-state backend-crossover flag: None = 'auto' (caller
    applies its measured crossover), True/False force. FLAGS.set coerces
    to the default's type (str), so boolean sets arrive as 'True'/'False'
    strings — parse, don't truth-test."""
    if isinstance(flag, str):
        low = flag.strip().lower()
        if low == "auto":
            return None
        return low in ("true", "1", "on", "yes")
    return bool(flag)


def _on_tpu() -> bool:
    import jax

    return jax.default_backend() in ("tpu", "axon")


def pallas_ivf_enabled(dimension: int) -> bool:
    """Resolve the tri-state use_pallas_ivf_search flag for an index."""
    v = _parse_tri(FLAGS.get("use_pallas_ivf_search"))
    if v is None:
        return _on_tpu() and dimension >= 256
    return v


def pallas_fused_enabled(capacity: int) -> bool:
    """Tri-state use_pallas_fused_search crossover for FLAT searches:
    'auto' routes to the streaming kernel on TPU once the store is big
    enough (capacity >= 2048) that avoiding the [b, capacity] HBM score
    matrix beats one fused XLA matmul+top_k; True/False force."""
    v = _parse_tri(FLAGS.get("use_pallas_fused_search"))
    if v is None:
        return _on_tpu() and capacity >= 2048
    return v


def prune_scan_enabled() -> bool:
    """Tri-state ivf_prune_scan: 'auto' = on (the pruned kernels are only
    reachable where the Pallas crossover already fired AND the index has
    blocked metadata, so there is no separate hardware condition)."""
    v = _parse_tri(FLAGS.get("ivf_prune_scan"))
    return True if v is None else v


def hnsw_device_enabled() -> bool:
    """Tri-state hnsw.device_search: 'auto' keeps the device graph walk
    TPU-only (the lockstep beam needs MXU batch throughput to beat the
    native C++ graph; on CPU the host path wins and doubles as the
    parity oracle). True/False force."""
    v = _parse_tri(FLAGS.get("hnsw_device_search"))
    if v is None:
        return _on_tpu()
    return v


def hnsw_device_build_enabled() -> bool:
    """Tri-state hnsw.device_build: 'auto' keeps bulk device construction
    TPU-only — the batched beam walks and masked top-k selection rounds
    need MXU throughput to beat the native C++ insert loop; the host
    build stays the CPU arm and the parity oracle. True/False force."""
    v = _parse_tri(FLAGS.get("hnsw_device_build"))
    if v is None:
        return _on_tpu()
    return v


def train_sample_rows() -> int:
    """Row cap shared by every train path (conf train.sample_rows,
    floor 0). 0 = full corpus: trainers feed every live row and lift
    their derived caps (an explicit opt-in — full-corpus Lloyd over a
    blocked device layout is exactly what the chunked kmeans_fit scan
    compiles to one program for)."""
    try:
        return max(0, int(FLAGS.get("train_sample_rows")))
    except (TypeError, ValueError):
        return 65536


def serving_pipeline_enabled() -> bool:
    """Tri-state pipeline.enabled: 'auto' keeps the overlapped-dispatch
    serving pipeline TPU-only (CPU XLA executes synchronously inside
    dispatch, so there is nothing to overlap — the completion-lane hop
    would only add latency). True/False force."""
    v = _parse_tri(FLAGS.get("pipeline_enabled"))
    if v is None:
        return _on_tpu()
    return v


def pipeline_depth() -> int:
    """Staging-ring depth for the serving pipeline (floor 1)."""
    try:
        return max(1, int(FLAGS.get("pipeline_depth")))
    except (TypeError, ValueError):
        return 2


def result_cache_enabled() -> bool:
    """Whole-subsystem gate for the serving-edge cache (dedupe + result
    cache). One boolean read — with the flag off every hook is a cheap
    early return, mirroring qos_enabled()."""
    v = FLAGS.get("cache_enabled")
    if isinstance(v, str):
        return v.strip().lower() in ("true", "1", "on", "yes")
    return bool(v)


def blocked_layout_enabled() -> bool:
    """Tri-state vector_blocked_layout: 'auto' keeps the blocked FLAT scan
    mirror TPU-only (it duplicates the rows in device memory; the CPU arm
    never routes to the kernel that reads it unless forced)."""
    v = _parse_tri(FLAGS.get("vector_blocked_layout"))
    if v is None:
        return _on_tpu()
    return v


class Config:
    """Per-role config (ConfigManager + YamlConfig analog)."""

    def __init__(self, values: Optional[Dict[str, Any]] = None):
        self._values = dict(values or {})

    @classmethod
    def load(cls, path: str) -> "Config":
        with open(path) as f:
            text = f.read()
        if path.endswith(".json"):
            return cls(_flatten(json.loads(text)))
        values: Dict[str, Any] = {}
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                continue
            key, _, raw = line.partition("=")
            values[key.strip()] = _parse_scalar(raw.strip())
        return cls(values)

    def get(self, key: str, default: Any = _UNSET) -> Any:
        if key in self._values:
            return self._values[key]
        if default is _UNSET:
            raise KeyError(key)
        return default

    def get_int(self, key: str, default: int = 0) -> int:
        return int(self.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)

    def apply_flag_overrides(self, flags: FlagRegistry = FLAGS) -> int:
        """Boot-time yaml-overrides-gflags behavior (server.cc:500-512)."""
        n = 0
        for key, value in self._values.items():
            name = key.replace(".", "_")
            if name in flags._flags:
                flags.set(name, value, boot=True)
                n += 1
        return n


def _parse_scalar(raw: str) -> Any:
    for caster in (int, float):
        try:
            return caster(raw)
        except ValueError:
            pass
    if raw.lower() in ("true", "false"):
        return raw.lower() == "true"
    return raw.strip("\"'")


def _flatten(obj: Dict, prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in obj.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def enable_compile_cache(log_fn=None) -> None:
    """Persistent XLA compilation cache (DINGO_COMPILE_CACHE overrides the
    default ~/.dingo-xla-cache): first compile on the chip is 20-40s per
    program, and bench/smoke re-run every round."""
    import jax

    cache_dir = os.environ.get(
        "DINGO_COMPILE_CACHE", os.path.expanduser("~/.dingo-xla-cache")
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # noqa: BLE001
        if log_fn:
            log_fn(f"compile cache unavailable: {e}")
