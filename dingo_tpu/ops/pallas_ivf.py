"""Pallas IVF list-DMA kernel: stream ONLY probed buckets through VMEM.

The XLA IVF path (`ivf_flat._ivf_scan_kernel`) gathers each probed bucket
into a fresh [b, cap_list, d] HBM array per probe rank and then reads it
again for the distance einsum — 3x the necessary HBM traffic, plus it
cannot skip padded ranks. This kernel uses scalar-prefetched probe ids as
the BlockSpec index_map, so the Pallas pipeline DMAs exactly one probed
bucket [cap_list, d] from HBM to VMEM per grid step (double-buffered), and
the distance + running top-k merge happen in VMEM with nothing written
back but the final [b, k].

Replaces the hot loop the reference runs through faiss's IVF scanners over
src/simd/hook.cc kernels (vector_index_ivf_flat.cc search path).

Grid: (b, budget) — query-major, so the output block for query q stays
resident in VMEM across its inner rank loop (accumulate-in-output pattern).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dingo_tpu.ops.pallas_topk import _select_topk
from dingo_tpu.obs.sentinel import sentinel_jit

NEG_INF = float("-inf")
#: output lane padding (TPU lane width; k slots live in the first k lanes)
OUT_PAD = 128
#: sublane-aligned row blocking for per-query arrays (batch padded to this)
ROW_BLOCK = 8


def _ivf_kernel(vp_ref, q_ref, qsq_ref, x_ref, xsq_ref, val_ref, slot_ref,
                outv_ref, outi_ref, *, k, ascending):
    # Mosaic's tiling rule rejects blocks with a size-1 sublane dim on a
    # larger array (observed on-chip round 3), so queries/qsq/outputs
    # arrive as 8-row sublane-aligned blocks (index q // 8) and the kernel
    # addresses its query's row within the block with a dynamic slice —
    # VMEM stays O(1) in the batch, unlike full-batch blocks. The grid is
    # query-major, so all 8 rows of an output block are initialized and
    # filled by their own queries before the block index advances.
    qi = pl.program_id(0)
    r = pl.program_id(1)
    row = pl.ds(jax.lax.rem(qi, ROW_BLOCK), 1)

    @pl.when(r == 0)
    def _init():
        outv_ref[row, :] = jnp.full(
            (1, outv_ref.shape[1]), NEG_INF, jnp.float32
        )
        outi_ref[row, :] = jnp.full(
            (1, outi_ref.shape[1]), -1, jnp.int32
        )

    @pl.when(vp_ref[qi, r] >= 0)
    def _scan_bucket():
        q = q_ref[row, :]                                # [1, d]
        x = x_ref[0].astype(jnp.float32)                 # [cap, d]
        dots = jax.lax.dot_general(
            q, x, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )                                                # [1, cap]
        if ascending:   # L2 score = -(||q||^2 - 2qx + ||x||^2)
            scores = -(qsq_ref[row, :] - 2.0 * dots + xsq_ref[0])
        else:           # IP
            scores = dots
        scores = jnp.where(val_ref[0] > 0.5, scores, NEG_INF)
        slot = slot_ref[0].astype(jnp.int32)             # [1, cap]
        blk_v, blk_i = _select_topk(scores, slot, k)
        cur_v = outv_ref[row, :]
        cur_i = outi_ref[row, :]
        cat_v = jnp.concatenate([cur_v[:, :k], blk_v], axis=1)
        cat_i = jnp.concatenate([cur_i[:, :k], blk_i], axis=1)
        new_v, new_i = _select_topk(cat_v, cat_i, k)
        pad = outv_ref.shape[1] - k
        outv_ref[row, :] = jnp.concatenate(
            [new_v, jnp.full((1, pad), NEG_INF, jnp.float32)], axis=1
        )
        outi_ref[row, :] = jnp.concatenate(
            [new_i, jnp.full((1, pad), -1, jnp.int32)], axis=1
        )

    @pl.when(r == pl.num_programs(1) - 1)
    def _finish():
        fv = outv_ref[row, :]
        # -inf picks carry arbitrary slots; normalize to -1 like the XLA path
        outi_ref[row, :] = jnp.where(jnp.isneginf(fv), -1, outi_ref[row, :])


@sentinel_jit("ops.pallas.ivf_list_topk",
              static_argnames=("k", "ascending", "interpret", "nq"))
def ivf_list_topk(
    vprobes: jax.Array,        # [b, budget] int32 virtual bucket ids (-1 pad)
    queries: jax.Array,        # [b, d] f32
    buckets: jax.Array,        # [B, cap, d]
    bucket_sqnorm: jax.Array,  # [B, cap] f32
    bucket_valid: jax.Array,   # [B, cap] bool/float
    bucket_slot: jax.Array,    # [B, cap] int32
    k: int,
    ascending: bool = True,
    interpret: bool = False,
    nq: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Fused probed-bucket scan -> (scores[b, k], slots[b, k]).

    Scores follow the 'larger is better' convention (negated L2 when
    ascending); slots are -1 where fewer than k valid rows were probed.
    `nq` clamps the query grid to the REAL batch: arrays stay padded to
    ROW_BLOCK rows (Mosaic tiling), but padded rows get no grid steps —
    without the clamp a b=1 batch paid 8x the grid (and each dead step
    still DMA'd bucket 0's [cap, d] tile through VMEM).
    """
    b, d = queries.shape
    nb, cap, _ = buckets.shape
    budget = vprobes.shape[1]
    nq = nq or b
    q32 = queries.astype(jnp.float32)
    qsq = jnp.einsum(
        "bd,bd->b", q32, q32, precision=jax.lax.Precision.HIGHEST
    )[:, None]
    # index_map reads the prefetched probes; clamp padded (-1) ranks to
    # bucket 0 — the kernel body skips them via pl.when
    def bucket_map(q, r, vp):
        return (jnp.maximum(vp[q, r], 0), 0, 0)

    # row metadata rides as [B, 1, cap] so each block is (1, 1, cap): the
    # last two dims equal the array's — Mosaic rejects (1, cap) blocks on
    # [B, cap] (size-1 sublane on a larger array). Per-query arrays ride
    # as ROW_BLOCK-row blocks so VMEM stays O(1) in the batch.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nq, budget),
        in_specs=[
            pl.BlockSpec(
                (ROW_BLOCK, d), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),                                                    # queries
            pl.BlockSpec(
                (ROW_BLOCK, 1), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),                                                    # qsq
            pl.BlockSpec((1, cap, d), bucket_map),                # bucket data
            pl.BlockSpec((1, 1, cap), bucket_map),                # sqnorm
            pl.BlockSpec((1, 1, cap), bucket_map),                # valid
            pl.BlockSpec((1, 1, cap), bucket_map),                # slots
        ],
        out_specs=[
            pl.BlockSpec(
                (ROW_BLOCK, OUT_PAD), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),
            pl.BlockSpec(
                (ROW_BLOCK, OUT_PAD), lambda q, r, vp: (q // ROW_BLOCK, 0)
            ),
        ],
    )
    out_v, out_i = pl.pallas_call(
        functools.partial(_ivf_kernel, k=k, ascending=ascending),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.float32),
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.int32),
        ],
        interpret=interpret,
    )(
        vprobes,
        q32,
        qsq,
        buckets,
        bucket_sqnorm[:, None, :],
        bucket_valid.astype(jnp.float32)[:, None, :],
        bucket_slot[:, None, :],
    )
    return out_v[:, :k], out_i[:, :k]


def ivf_list_search(
    vprobes, queries, buckets, bucket_sqnorm, bucket_valid, bucket_slot,
    k: int, ascending: bool = True,
):
    """Backend-aware wrapper: interpret mode off-TPU (Mosaic is TPU-only);
    pads the ARRAYS to ROW_BLOCK rows but clamps the grid to the real
    batch, so a b<8 request doesn't run (or DMA for) dead grid steps."""
    b = queries.shape[0]
    queries, vprobes = _pad_rows(queries, vprobes)
    interpret = jax.default_backend() not in ("tpu", "axon")
    vals, slots = ivf_list_topk(
        vprobes, queries, buckets, bucket_sqnorm, bucket_valid, bucket_slot,
        k=k, ascending=ascending, interpret=interpret, nq=b,
    )
    from dingo_tpu.ops.distance import device_wait_span

    vals, slots = device_wait_span("pallas_ivf_search", (vals, slots))
    return vals[:b], slots[:b]


def _pad_rows(queries, vprobes):
    """Pad the per-query arrays to the ROW_BLOCK sublane multiple (padded
    queries probe nothing: vprobes -1)."""
    pad = (-queries.shape[0]) % ROW_BLOCK
    if pad:
        queries = jnp.concatenate(
            [queries, jnp.zeros((pad, queries.shape[1]), queries.dtype)]
        )
        vprobes = jnp.concatenate(
            [vprobes, jnp.full((pad, vprobes.shape[1]), -1, vprobes.dtype)]
        )
    return queries, vprobes


def _ivf_pruned_kernel(vp_ref, q_ref, qsq_ref, qpsq_ref, x_ref, bsq_ref,
                       xsq_ref, val_ref, slot_ref, *rest,
                       k, ascending, nblk, check_every, sq, inbucket):
    """Dimension-blocked early-pruning list scan (PDX on TPU).

    Grid (q, r, jb) with the dimension block jb INNERMOST: for each probed
    bucket the kernel streams one [cap, dblk] tile per step, accumulates
    the partial dot in VMEM scratch, and after each block masks out
    candidates whose partial-distance bound already cannot beat the
    running k-th best (read from the resident output block). A bucket
    whose candidates are ALL dead skips the remaining blocks' compute
    entirely. Bounds:

      L2: partial dist through block j = qpsq[j] - 2*cum + xpsq[j] is a
          LOWER bound of the final distance (remaining blocks add >= 0),
          so -partial is an upper bound of the final score.
      IP: cum + sqrt(qtail[j] * xtail[j]) (Cauchy-Schwarz on the unseen
          dimension suffix) is an upper bound of the final dot.

    A candidate is pruned only when its upper bound is STRICTLY below the
    running k-th best, so results match the non-pruning kernels exactly
    (up to f32 partial-sum rounding on the reported distances).

    With `inbucket` (FLAGS.ivf_prune_inbucket_bound) the threshold also
    REFRESHES between dimension blocks inside a bucket: every alive
    candidate carries a suffix-norm LOWER bound of its final score
    (L2: dist <= partial + (|q_tail| + |x_tail|)^2 by the triangle
    inequality; IP: dot >= cum - |q_tail||x_tail| by Cauchy-Schwarz), and
    the k-th largest lower bound among them is a valid prune threshold
    even though none of these candidates has reached the shortlist merge
    yet. Early buckets — where the output block still reads -inf — start
    pruning from block 1 instead of scanning fully.

    Stats output lanes (accumulated per query): 0 = candidate-block pairs
    actually scanned, 1 = candidate-block pairs total, 2 = candidates
    scanned to the last block, 3 = candidates considered.
    """
    if sq:
        (vmin_ref, scale_ref, outv_ref, outi_ref, outs_ref,
         cum, alive, xpsq) = rest
    else:
        outv_ref, outi_ref, outs_ref, cum, alive, xpsq = rest
    qi = pl.program_id(0)
    r = pl.program_id(1)
    jb = pl.program_id(2)
    row = pl.ds(jax.lax.rem(qi, ROW_BLOCK), 1)
    lanes = jax.lax.broadcasted_iota(jnp.int32, (1, outs_ref.shape[1]), 1)

    @pl.when((r == 0) & (jb == 0))
    def _init_out():
        outv_ref[row, :] = jnp.full(
            (1, outv_ref.shape[1]), NEG_INF, jnp.float32
        )
        outi_ref[row, :] = jnp.full((1, outi_ref.shape[1]), -1, jnp.int32)
        outs_ref[row, :] = jnp.zeros((1, outs_ref.shape[1]), jnp.float32)

    @pl.when(vp_ref[qi, r] >= 0)
    def _scan_bucket():
        @pl.when(jb == 0)
        def _init_bucket():
            cum[:] = jnp.zeros_like(cum)
            xpsq[:] = jnp.zeros_like(xpsq)
            alive[:] = val_ref[0]
            nvalid = jnp.sum(val_ref[0])
            outs_ref[row, :] += jnp.where(
                lanes == 1, nvalid * nblk,
                jnp.where(lanes == 3, nvalid, 0.0),
            )

        nalive = jnp.sum(alive[:])
        outs_ref[row, :] += jnp.where(lanes == 0, nalive, 0.0)

        @pl.when((jb == nblk - 1))
        def _count_full():
            outs_ref[row, :] += jnp.where(lanes == 2, nalive, 0.0)

        @pl.when(nalive > 0.5)
        def _compute():
            q = q_ref[row, :]                          # [1, dblk]
            x = x_ref[0]                               # [cap, dblk]
            if sq:
                # decode in f32, multiply in bf16 with f32 accumulation —
                # the sq8 tier's compute contract (ops/sq.py): native
                # bf16 MXU matmul fed by 1-byte HBM reads
                x = (
                    x.astype(jnp.float32) * scale_ref[:] + vmin_ref[:]
                ).astype(jnp.bfloat16)
                q = q.astype(jnp.bfloat16)
            else:
                x = x.astype(jnp.float32)
            dots = jax.lax.dot_general(
                q, x, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=(None if sq else jax.lax.Precision.HIGHEST),
            )                                          # [1, cap]
            cum[:] += dots
            xpsq[:] += bsq_ref[0]
            bound = outv_ref[row, :][:, k - 1:k]       # running k-th best
            qpsq_j = qpsq_ref[row, :]                  # [1, 1] prefix
            qtail = jnp.maximum(qsq_ref[row, :] - qpsq_j, 0.0)
            xtail = jnp.maximum(xsq_ref[0] - xpsq[:], 0.0)
            if ascending:
                partial = qpsq_j - 2.0 * cum[:] + xpsq[:]
                ub = -partial
                final = ub
            else:
                ub = cum[:] + jnp.sqrt(qtail * xtail)
                final = cum[:]

            @pl.when((jb < nblk - 1)
                     & (jax.lax.rem(jb + 1, check_every) == 0))
            def _prune():
                bnd = bound
                if inbucket:
                    # within-bucket refresh (PDX finer threshold): each
                    # alive candidate's final score is >= its suffix-norm
                    # LOWER bound, so the k-th largest lower bound among
                    # this bucket's alive candidates is itself a valid
                    # prune threshold — usable blocks before any of them
                    # reaches the shortlist merge. A candidate can never
                    # prune itself: ub >= lb always, so ub < kth-lb
                    # implies its own lb is below the top-k lb set.
                    if ascending:
                        tail = jnp.sqrt(qtail) + jnp.sqrt(xtail)
                        lb = -(partial + tail * tail)
                    else:
                        lb = cum[:] - jnp.sqrt(qtail * xtail)
                    # f32 safety shave: the bound math is exact in real
                    # arithmetic; keep rounding on the conservative side
                    lb = lb - 1e-5 * jnp.abs(lb) - 1e-6
                    lb = jnp.where(alive[:] > 0.5, lb, NEG_INF)
                    lb_k, _ = _select_topk(
                        lb, slot_ref[0].astype(jnp.int32), k
                    )
                    bnd = jnp.maximum(bnd, lb_k[:, k - 1:k])
                alive[:] = jnp.where(ub < bnd, 0.0, alive[:])

            @pl.when(jb == nblk - 1)
            def _merge():
                scores = jnp.where(alive[:] > 0.5, final, NEG_INF)
                slot = slot_ref[0].astype(jnp.int32)
                blk_v, blk_i = _select_topk(scores, slot, k)
                cur_v = outv_ref[row, :]
                cur_i = outi_ref[row, :]
                cat_v = jnp.concatenate([cur_v[:, :k], blk_v], axis=1)
                cat_i = jnp.concatenate([cur_i[:, :k], blk_i], axis=1)
                new_v, new_i = _select_topk(cat_v, cat_i, k)
                pad = outv_ref.shape[1] - k
                outv_ref[row, :] = jnp.concatenate(
                    [new_v, jnp.full((1, pad), NEG_INF, jnp.float32)],
                    axis=1,
                )
                outi_ref[row, :] = jnp.concatenate(
                    [new_i, jnp.full((1, pad), -1, jnp.int32)], axis=1
                )

    @pl.when((r == pl.num_programs(1) - 1) & (jb == nblk - 1))
    def _finish():
        fv = outv_ref[row, :]
        outi_ref[row, :] = jnp.where(jnp.isneginf(fv), -1, outi_ref[row, :])


@sentinel_jit("ops.pallas.ivf_pruned_topk",
              static_argnames=("k", "ascending", "dim_block", "check_every",
                               "interpret", "nq", "sq", "inbucket"))
def ivf_pruned_topk(
    vprobes: jax.Array,        # [b, budget] int32 virtual bucket ids (-1 pad)
    queries: jax.Array,        # [b, d] f32
    qpsq: jax.Array,           # [b, nblk] f32 inclusive per-block prefixes
    buckets: jax.Array,        # [B, cap, d] rows (f32/bf16) or codes (uint8)
    bucket_bsq: jax.Array,     # [B, nblk, cap] f32 per-block (decoded) norms
    bucket_sqnorm: jax.Array,  # [B, cap] f32 total (decoded) norms
    bucket_valid: jax.Array,   # [B, cap] bool/float
    bucket_slot: jax.Array,    # [B, cap] int32
    sq_vmin,                   # [d] f32 codec params (None for float rows)
    sq_scale,
    k: int,
    dim_block: int,
    ascending: bool = True,
    check_every: int = 1,
    interpret: bool = False,
    nq: int = 0,
    sq: bool = False,
    inbucket: bool = True,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Early-pruning probed-bucket scan -> (scores, slots, stats).

    Same contract as ivf_list_topk plus a [b, OUT_PAD] stats output (see
    _ivf_pruned_kernel lanes) the caller turns into pruned-fraction
    metrics. The [B, cap, d] bucket array is NOT physically re-laid-out:
    the (1, cap, dim_block) BlockSpec tile IS the PDX vertical access
    pattern (one dimension block of every candidate per DMA)."""
    b, d = queries.shape
    nb, cap, _ = buckets.shape
    budget = vprobes.shape[1]
    nblk = d // dim_block
    nq = nq or b
    q32 = queries.astype(jnp.float32)
    qsq = jnp.einsum(
        "bd,bd->b", q32, q32, precision=jax.lax.Precision.HIGHEST
    )[:, None]

    def bucket_map(q, r, jb, vp):
        return (jnp.maximum(vp[q, r], 0), 0, 0)

    in_specs = [
        pl.BlockSpec(
            (ROW_BLOCK, dim_block),
            lambda q, r, jb, vp: (q // ROW_BLOCK, jb),
        ),                                                    # queries
        pl.BlockSpec(
            (ROW_BLOCK, 1), lambda q, r, jb, vp: (q // ROW_BLOCK, 0)
        ),                                                    # qsq
        pl.BlockSpec(
            (ROW_BLOCK, 1), lambda q, r, jb, vp: (q // ROW_BLOCK, jb)
        ),                                                    # qpsq
        pl.BlockSpec(
            (1, cap, dim_block),
            lambda q, r, jb, vp: (jnp.maximum(vp[q, r], 0), 0, jb),
        ),                                                    # bucket tile
        pl.BlockSpec(
            (1, 1, cap),
            lambda q, r, jb, vp: (jnp.maximum(vp[q, r], 0), jb, 0),
        ),                                                    # per-block norms
        pl.BlockSpec((1, 1, cap), bucket_map),                # total norms
        pl.BlockSpec((1, 1, cap), bucket_map),                # valid
        pl.BlockSpec((1, 1, cap), bucket_map),                # slots
    ]
    args = [
        q32,
        qsq,
        qpsq,
        buckets,
        bucket_bsq,
        bucket_sqnorm[:, None, :],
        bucket_valid.astype(jnp.float32)[:, None, :],
        bucket_slot[:, None, :],
    ]
    if sq:
        in_specs += [
            pl.BlockSpec((1, dim_block), lambda q, r, jb, vp: (0, jb)),
            pl.BlockSpec((1, dim_block), lambda q, r, jb, vp: (0, jb)),
        ]
        args += [sq_vmin[None, :], sq_scale[None, :]]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nq, budget, nblk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (ROW_BLOCK, OUT_PAD),
                lambda q, r, jb, vp: (q // ROW_BLOCK, 0),
            ),
        ] * 3,
        scratch_shapes=[
            pltpu.VMEM((1, cap), jnp.float32),    # cum dot
            pltpu.VMEM((1, cap), jnp.float32),    # alive mask
            pltpu.VMEM((1, cap), jnp.float32),    # x per-block prefix norms
        ],
    )
    out_v, out_i, out_s = pl.pallas_call(
        functools.partial(
            _ivf_pruned_kernel, k=k, ascending=ascending, nblk=nblk,
            check_every=check_every, sq=sq, inbucket=inbucket,
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.float32),
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.int32),
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.float32),
        ],
        interpret=interpret,
    )(vprobes, *args)
    return out_v[:, :k], out_i[:, :k], out_s[:, :4]


def ivf_pruned_search(
    vprobes, queries, buckets, bucket_bsq, bucket_sqnorm, bucket_valid,
    bucket_slot, k: int, dim_block: int, ascending: bool = True,
    sq_vmin=None, sq_scale=None,
):
    """Backend-aware wrapper for the pruning scan: pads per-query arrays
    to ROW_BLOCK, clamps the grid to the real batch, computes the query
    prefix norms, and returns (scores[b,k], slots[b,k], stats[b,4])."""
    from dingo_tpu.common.config import FLAGS
    from dingo_tpu.ops.blocked import query_prefix_sqnorms

    b = queries.shape[0]
    queries, vprobes = _pad_rows(queries, vprobes)
    qpsq = query_prefix_sqnorms(queries, dim_block)
    interpret = jax.default_backend() not in ("tpu", "axon")
    check = max(1, int(FLAGS.get("ivf_prune_check_interval")))
    vals, slots, stats = ivf_pruned_topk(
        vprobes, queries, qpsq, buckets, bucket_bsq, bucket_sqnorm,
        bucket_valid, bucket_slot, sq_vmin, sq_scale,
        k=k, dim_block=dim_block, ascending=ascending, check_every=check,
        interpret=interpret, nq=b, sq=sq_vmin is not None,
        inbucket=bool(FLAGS.get("ivf_prune_inbucket_bound")),
    )
    from dingo_tpu.ops.distance import device_wait_span

    vals, slots, stats = device_wait_span(
        "pruned_scan", (vals, slots, stats)
    )
    return vals[:b], slots[:b], stats[:b]
