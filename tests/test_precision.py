"""Precision tiers (ISSUE 4): bf16/sq8 recall parity vs fp32, SQ codec
persistence, device-resident rerank correctness vs the host rerank, and
the capacity win (device bytes/vector) the tiers exist for.

Scales are test-sized; the bench-operating-point numbers live in
bench.py's precision_sweep JSON. The pyproject filterwarnings gate
("Some donated buffers were not usable" -> error) rides along on every
device write these tests trigger.
"""

import tempfile

import numpy as np
import pytest

import jax.numpy as jnp

from dingo_tpu.common.config import FLAGS
from dingo_tpu.index.base import (
    FilterSpec,
    IndexParameter,
    IndexType,
    InvalidParameter,
    Metric,
    resolve_precision,
)
from dingo_tpu.index.flat import TpuFlat
from dingo_tpu.index.ivf_flat import TpuIvfFlat
from dingo_tpu.index.ivf_pq import TpuIvfPq, _exact_rerank_host
from dingo_tpu.index.rerank_cache import DeviceRerankCache
from dingo_tpu.index.slot_store import HostSlotStore, SlotStore, SqSlotStore
from dingo_tpu.ops.rerank import cached_rerank_device, exact_rerank_device
from dingo_tpu.ops.sq import SqParams, params_close, sq_decode, sq_encode, sq_train

N, D, K = 6000, 64, 10


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(42)
    centers = rng.standard_normal((64, D), dtype=np.float32)
    x = centers[rng.integers(0, 64, N)] + 0.3 * rng.standard_normal(
        (N, D)
    ).astype(np.float32)
    ids = np.arange(N, dtype=np.int64)
    q = x[:16] + 0.02 * rng.standard_normal((16, D)).astype(np.float32)
    gt = np.argsort(((q[:, None, :] - x[None, :, :]) ** 2).sum(-1), 1)[:, :K]
    return ids, x, q, gt


def _recall(res, gt):
    return float(np.mean(
        [len(set(r.ids) & set(g)) / K for r, g in zip(res, gt)]
    ))


@pytest.fixture
def no_cache():
    FLAGS.set("rerank_cache_rows", 0)
    yield
    FLAGS.set("rerank_cache_rows", 0)


@pytest.fixture
def with_cache():
    FLAGS.set("rerank_cache_rows", 8192)
    FLAGS.set("rerank_cache_dtype", "float32")
    yield
    FLAGS.set("rerank_cache_rows", 0)


def _flat(precision, idx_id=1, metric=Metric.L2):
    return TpuFlat(idx_id, IndexParameter(
        index_type=IndexType.FLAT, dimension=D, metric=metric,
        precision=precision,
    ))


def _ivf(precision, idx_id=1, nlist=32):
    return TpuIvfFlat(idx_id, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=D, ncentroids=nlist,
        default_nprobe=16, precision=precision,
    ))


# ---------------------------------------------------------------- codec --

def test_sq_codec_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2000, D)).astype(np.float32)
    params = sq_train(x)
    codes = sq_encode(x, params)
    assert codes.dtype == np.uint8
    err = np.abs(sq_decode(codes, params) - x)
    # per-dim error bound: half a quantization step
    assert (err <= params.scale[None, :] * 0.5 + 1e-6).all()


def test_sq_out_of_range_clips_not_wraps():
    params = SqParams(np.zeros(D, np.float32), np.full(D, 1 / 255, np.float32))
    hot = np.full((1, D), 9.0, np.float32)     # far above the range
    cold = np.full((1, D), -9.0, np.float32)
    assert (sq_encode(hot, params) == 255).all()
    assert (sq_encode(cold, params) == 0).all()


def test_resolve_precision_aliases_and_legacy_dtype():
    p = IndexParameter(index_type=IndexType.FLAT, dimension=D)
    assert resolve_precision(p) == "fp32"
    assert resolve_precision(
        IndexParameter(dimension=D, precision="bfloat16")) == "bf16"
    # legacy configs set dtype=bfloat16 directly (bench rounds 1-5)
    assert resolve_precision(
        IndexParameter(dimension=D, dtype="bfloat16")) == "bf16"
    with pytest.raises(InvalidParameter):
        resolve_precision(IndexParameter(dimension=D, precision="fp8"))


# ---------------------------------------------------- recall parity gates --

def test_flat_recall_parity(corpus, no_cache):
    ids, x, q, gt = corpus
    recalls = {}
    for tier in ("fp32", "bf16", "sq8"):
        idx = _flat(tier)
        idx.upsert(ids, x)
        recalls[tier] = _recall(idx.search(q, K), gt)
    assert recalls["fp32"] >= 0.999
    assert recalls["bf16"] >= recalls["fp32"] - 0.05
    assert recalls["sq8"] >= recalls["fp32"] - 0.05
    assert recalls["sq8"] >= 0.95 and recalls["bf16"] >= 0.95


def test_ivf_recall_parity(corpus, no_cache):
    ids, x, q, gt = corpus
    recalls = {}
    for tier in ("fp32", "bf16", "sq8"):
        idx = _ivf(tier)
        idx.upsert(ids, x)
        idx.train()
        recalls[tier] = _recall(idx.search(q, K), gt)
    assert recalls["bf16"] >= recalls["fp32"] - 0.05
    assert recalls["sq8"] >= recalls["fp32"] - 0.05


def test_sq8_rerank_restores_exact_recall(corpus, with_cache):
    ids, x, q, gt = corpus
    idx = _flat("sq8")
    idx.upsert(ids, x)
    assert len(idx._rerank_cache) == N      # cache covers every row
    # shortlist k*factor reranked exactly from fp32 rows -> exact top-k
    assert _recall(idx.search(q, K), gt) == 1.0


def test_cosine_tier_parity(corpus, no_cache):
    ids, x, q, gt_l2 = corpus
    res = {}
    for tier in ("fp32", "sq8"):
        idx = _flat(tier, metric=Metric.COSINE)
        idx.upsert(ids, x)
        res[tier] = idx.search(q, K)
    overlap = np.mean([
        len(set(a.ids) & set(b.ids)) / K
        for a, b in zip(res["fp32"], res["sq8"])
    ])
    assert overlap >= 0.9


# --------------------------------------------------- capacity (HBM) gates --

def test_sq8_device_bytes_at_least_3p5x_smaller(corpus, no_cache):
    ids, x, _, _ = corpus
    sizes = {}
    for tier in ("fp32", "sq8"):
        idx = _ivf(tier, idx_id=5)
        idx.upsert(ids, x)
        idx.train()
        idx.search(x[:4], K)     # materialize the bucketed view
        sizes[tier] = idx.get_device_memory_size()
    assert sizes["fp32"] / sizes["sq8"] >= 3.5, sizes


def test_bf16_device_bytes_about_half(corpus, no_cache):
    ids, x, _, _ = corpus
    sizes = {}
    for tier in ("fp32", "bf16"):
        idx = _flat(tier, idx_id=6)
        idx.upsert(ids, x)
        sizes[tier] = idx.get_device_memory_size()
    assert sizes["fp32"] / sizes["bf16"] >= 1.8, sizes


# ------------------------------------------------------------ persistence --

def test_sq_params_persist_flat(corpus, no_cache, tmp_path):
    ids, x, q, _ = corpus
    idx = _flat("sq8")
    idx.upsert(ids, x)
    idx.save(str(tmp_path))
    idx2 = _flat("sq8", idx_id=2)
    idx2.load(str(tmp_path))
    assert params_close(idx.store.sq_params, idx2.store.sq_params)
    a, b = idx.search(q, K), idx2.search(q, K)
    for ai, bi in zip(a, b):
        np.testing.assert_array_equal(ai.ids, bi.ids)
        np.testing.assert_allclose(ai.distances, bi.distances, rtol=1e-6)


def test_sq_params_persist_ivf_snapshot(corpus, no_cache, tmp_path):
    ids, x, q, _ = corpus
    idx = _ivf("sq8", idx_id=7)
    idx.upsert(ids, x)
    idx.train()
    before = idx.search(q, K)
    idx.save(str(tmp_path))
    idx2 = _ivf("sq8", idx_id=8)
    idx2.load(str(tmp_path))
    assert params_close(idx.store.sq_params, idx2.store.sq_params)
    after = idx2.search(q, K)
    for ai, bi in zip(before, after):
        np.testing.assert_array_equal(ai.ids, bi.ids)


def test_empty_untrained_sq8_saves_and_reloads(no_cache, tmp_path):
    """Snapshotting an sq8 region that never saw a write must not crash
    on the missing codec params (code-review finding: to_host decoded
    unconditionally)."""
    idx = _flat("sq8", idx_id=30)
    idx.save(str(tmp_path))
    idx2 = _flat("sq8", idx_id=31)
    idx2.load(str(tmp_path))
    assert idx2.get_count() == 0
    assert idx2.search(np.zeros((1, D), np.float32), K)[0].ids.size == 0


def test_legacy_snapshot_without_precision_key_loads(corpus, no_cache,
                                                     tmp_path):
    """Pre-tier snapshots carry no 'precision' meta; a legacy
    dtype=bfloat16 index (tier bf16) must still load them, and an
    fp32<->bf16 tier flip must load (shared f32-on-disk row format) while
    crossing into sq8 stays a hard error."""
    import json as _json
    import os as _os

    ids, x, q, _ = corpus
    idx = _flat("fp32", idx_id=32)
    idx.upsert(ids[:200], x[:200])
    idx.save(str(tmp_path))
    meta_path = _os.path.join(str(tmp_path), "meta.json")
    with open(meta_path) as f:
        meta = _json.load(f)
    del meta["precision"]                 # simulate a pre-upgrade snapshot
    with open(meta_path, "w") as f:
        _json.dump(meta, f)
    legacy = TpuFlat(33, IndexParameter(
        index_type=IndexType.FLAT, dimension=D, dtype="bfloat16",
    ))
    legacy.load(str(tmp_path))            # must not raise
    assert legacy.get_count() == 200
    # explicit fp32 meta + bf16 index: tier flip, same container — loads
    meta["precision"] = "fp32"
    with open(meta_path, "w") as f:
        _json.dump(meta, f)
    flip = _flat("bf16", idx_id=34)
    flip.load(str(tmp_path))
    assert flip.get_count() == 200
    # crossing into sq8 is a container change — still rejected
    with open(meta_path) as f:
        meta = _json.load(f)
    meta["precision"] = "sq8"
    with open(meta_path, "w") as f:
        _json.dump(meta, f)
    with pytest.raises(InvalidParameter):
        _flat("fp32", idx_id=35).load(str(tmp_path))


def test_precision_mismatch_rejected(corpus, no_cache, tmp_path):
    ids, x, _, _ = corpus
    idx = _flat("sq8")
    idx.upsert(ids[:100], x[:100])
    idx.save(str(tmp_path))
    with pytest.raises(InvalidParameter):
        _flat("fp32", idx_id=3).load(str(tmp_path))


# ----------------------------------------------------- rerank correctness --

def test_device_rerank_matches_host_rerank(corpus):
    """exact_rerank_device == _exact_rerank_host on identical rows and
    candidates (the satellite gate: the device stage may remove the host
    gather, not change the answer)."""
    ids, x, q, _ = corpus
    dev = SlotStore(D)
    host = HostSlotStore(D)
    dev.put(ids, x)
    host.put(ids, x)
    rng = np.random.default_rng(1)
    cand = rng.integers(0, N, size=(len(q), 40)).astype(np.int64)
    cand[:, -3:] = -1                      # padding must stay padding
    for metric in (Metric.L2, Metric.INNER_PRODUCT):
        d_dev, s_dev = exact_rerank_device(
            dev.vecs, dev.sqnorm, jnp.asarray(q), jnp.asarray(cand),
            k=K, metric=metric,
        )
        d_host, s_host = _exact_rerank_host(host, q, cand, K, metric)
        np.testing.assert_array_equal(
            np.asarray(s_dev), np.asarray(s_host))
        np.testing.assert_allclose(
            np.asarray(d_dev), np.asarray(d_host), rtol=1e-5, atol=1e-4)


def test_cached_rerank_full_cache_matches_exact(corpus):
    ids, x, q, _ = corpus
    store = SlotStore(D)
    slots = store.put(ids, x)
    cache = DeviceRerankCache(D, max_rows=N, device_lock=store.device_lock)
    cache.offer(slots, x)
    rng = np.random.default_rng(2)
    cand = rng.integers(0, N, size=(len(q), 40)).astype(np.int64)
    quant = rng.standard_normal((len(q), 40)).astype(np.float32)
    d_ref, s_ref = exact_rerank_device(
        store.vecs, store.sqnorm, jnp.asarray(q), jnp.asarray(cand),
        k=K, metric=Metric.L2,
    )
    d_c, s_c = cached_rerank_device(
        cache.vecs, cache.sqnorm, cache.device_map(store.capacity),
        jnp.asarray(quant), jnp.asarray(cand), jnp.asarray(q),
        k=K, metric=Metric.L2,
    )
    np.testing.assert_array_equal(np.asarray(s_ref), np.asarray(s_c))
    np.testing.assert_allclose(np.asarray(d_ref), np.asarray(d_c),
                               rtol=1e-5, atol=1e-4)


def test_cached_rerank_partial_cache_keeps_quantized_scores(corpus):
    """A candidate missing from the cache must keep its quantized score,
    never drop out of the shortlist."""
    ids, x, q, _ = corpus
    store = SlotStore(D)
    slots = store.put(ids, x)
    cache = DeviceRerankCache(D, max_rows=16, device_lock=store.device_lock)
    cache.offer(slots[:16], x[:16])
    cand = np.tile(np.arange(30, dtype=np.int64), (len(q), 1))
    # give uncached candidate #25 an unbeatable quantized (wire-L2) score
    quant = np.full((len(q), 30), 1e6, np.float32)
    quant[:, 25] = 0.0
    d_c, s_c = cached_rerank_device(
        cache.vecs, cache.sqnorm, cache.device_map(store.capacity),
        jnp.asarray(quant), jnp.asarray(cand), jnp.asarray(q),
        k=K, metric=Metric.L2,
    )
    assert (np.asarray(s_c)[:, 0] == 25).all()


def test_rerank_cache_eviction_and_overwrite(corpus):
    ids, x, _, _ = corpus
    store = SlotStore(D)
    slots = store.put(ids[:100], x[:100])
    cache = DeviceRerankCache(D, max_rows=32, device_lock=store.device_lock)
    assert cache.offer(slots, x[:100]) == 32          # bounded admit
    assert len(cache) == 32
    # overwrite of a cached slot always lands, even when full
    new_row = x[200:201]
    assert cache.offer(slots[:1], new_row) == 1
    found, row = cache.inner.gather(slots[:1])
    np.testing.assert_allclose(row[0], new_row[0], rtol=1e-6)
    # invalidation frees room
    cache.invalidate(slots[:8])
    assert len(cache) == 24
    assert cache.offer(slots[40:60], x[40:60]) > 0


def test_ivfpq_device_store_reranks_on_device(corpus, no_cache):
    """Device-resident IVF_PQ now reranks its ADC shortlist from
    store.vecs on device; recall must beat the ADC-only ranking."""
    ids, x, q, gt = corpus
    param = IndexParameter(
        index_type=IndexType.IVF_PQ, dimension=D, ncentroids=16,
        nsubvector=8, default_nprobe=16,
    )
    FLAGS.set("ivfpq_rerank_factor", 8)
    idx = TpuIvfPq(11, param)
    idx.upsert(ids, x)
    idx.train()
    r_rerank = _recall(idx.search(q, K), gt)
    FLAGS.set("ivfpq_rerank_factor", 1)
    try:
        r_adc = _recall(idx.search(q, K), gt)
    finally:
        FLAGS.set("ivfpq_rerank_factor", 8)
    assert r_rerank >= r_adc
    assert r_rerank >= 0.9


# --------------------------------------------------------------- plumbing --

def test_search_by_precision_counter(corpus, no_cache):
    from dingo_tpu.common.metrics import METRICS

    ids, x, q, _ = corpus
    idx = _flat("sq8", idx_id=77)
    idx.upsert(ids[:100], x[:100])
    c = METRICS.counter("vector.search_by_precision", region_id=77,
                        labels={"precision": "sq8"})
    before = c.get()
    idx.search(q, K)
    assert c.get() == before + 1


def test_sq8_rejected_for_ivfpq_and_sharded():
    with pytest.raises(InvalidParameter):
        TpuIvfPq(12, IndexParameter(
            index_type=IndexType.IVF_PQ, dimension=D, nsubvector=8,
            precision="sq8",
        ))


def test_conf_template_precision_keys_in_sync():
    """conf/store.template.conf carries the precision-tier keys, each maps
    to a defined flag, and the template's value equals the flag default
    (the satellite's 'kept in sync with common/config.py defaults')."""
    from dingo_tpu.common.config import Config

    cfg = Config.load("conf/store.template.conf")
    for key, want in (
        ("vector.precision", "fp32"),
        ("rerank.cache_rows", 0),
        ("rerank.cache_dtype", "float32"),
        ("quantized.rerank_factor", 4),
    ):
        assert cfg.get(key) == want, key
        flag = key.replace(".", "_")
        assert FLAGS._flags[flag].default == want, flag


def test_sharded_flat_bf16_parity(corpus):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    from dingo_tpu.parallel.sharded_flat import TpuShardedFlat

    ids, x, q, gt = corpus
    idx = TpuShardedFlat(21, IndexParameter(
        index_type=IndexType.FLAT, dimension=D, precision="bf16",
    ))
    idx.upsert(ids, x)
    assert idx._store.vecs.dtype == jnp.bfloat16
    assert _recall(idx.search(q, K), gt) >= 0.95
