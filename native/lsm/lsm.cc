// Native LSM raw-KV engine: memtable + WAL + sorted immutable SSTs with
// tombstones and compaction.
//
// Plays RocksRawEngine's role (reference src/engine/rocks_raw_engine.{h,cc}:
// the store's persistent KV under raft apply and MVCC) as an ORIGINAL
// implementation — this is not a RocksDB wrapper and shares no code with it.
// Scope matches what the dingo_tpu stack needs: atomic batch writes through
// a torn-tail-safe WAL (optionally fsync'd per commit), sorted range scans
// (both directions), tombstoned deletes, native range deletes, size-
// triggered flush to numbered SST files, and checkpoint-by-flush (the
// Python side copies the immutable files).
//
// Round-3 scale hardening (VERDICT r2 weak #4):
//   - SST payloads are NOT resident: each SST keeps an open handle plus a
//     sparse index (every kIndexEvery-th key -> file offset, persisted in a
//     side .idx file; rebuilt by one sequential scan for legacy/checkpoint
//     files, which carry only .sst). Point reads seek to the floor index
//     entry and scan <= kIndexEvery records; range scans stream from the
//     seek point.
//   - Compaction is size-tiered over AGE-CONTIGUOUS runs (newest-wins needs
//     age order; records carry no seqnums) and STREAMS a k-way merge from
//     the input files to the output — nothing is materialized. Tombstones
//     drop only when the run includes the oldest SST. Explicit
//     lsm_compact() still merges everything (tombstone GC).
//   - lsm_open takes a sync_writes flag: fsync the WAL on every commit
//     (power-loss durability) vs fflush only (process-crash durability).
//
// C ABI for ctypes (dingo_tpu/native/__init__.py builds it with g++).

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <fcntl.h>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kWalMagic = 0xD146157A;
// bumped from ..7B: the .idx format gained a trailing checksum; old files
// fail the magic check and are rebuilt by one sequential scan
constexpr uint32_t kIdxMagic = 0xD146157C;
constexpr uint32_t kTombstone = 0xFFFFFFFFu;
constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpDelete = 2;
constexpr uint32_t kIndexEvery = 32;   // records per sparse-index entry
constexpr int kTierFanout = 4;         // merge a run of >= this many SSTs
constexpr double kTierFactor = 4.0;    // ...whose sizes are within this ratio

struct Entry {
  std::string key;
  std::string value;
  bool tombstone = false;
};

// An immutable on-disk SST: open handle + sparse index, payload on demand.
struct Sst {
  uint64_t id = 0;
  FILE* f = nullptr;
  uint64_t data_bytes = 0;            // byte length of the record region
  uint64_t count = 0;
  std::vector<std::string> idx_keys;  // every kIndexEvery-th record's key
  std::vector<uint64_t> idx_offs;     // its file offset
  std::string max_key;

  ~Sst() {
    if (f) fclose(f);
  }
};

struct Db {
  std::string dir;
  uint64_t memtable_limit = 8ull << 20;
  uint64_t memtable_bytes = 0;
  std::map<std::string, std::optional<std::string>> memtable;
  std::vector<std::unique_ptr<Sst>> ssts;  // oldest..newest
  uint64_t next_sst_id = 1;
  FILE* wal = nullptr;
  bool sync_writes = false;
  std::recursive_mutex mu;
  int compact_trigger = 8;

  std::string wal_path() const { return dir + "/wal.log"; }
  std::string sst_path(uint64_t id) const {
    char buf[32];
    snprintf(buf, sizeof(buf), "/%012llu.sst", (unsigned long long)id);
    return dir + buf;
  }
  std::string idx_path(uint64_t id) const {
    char buf[32];
    snprintf(buf, sizeof(buf), "/%012llu.idx", (unsigned long long)id);
    return dir + buf;
  }
};

bool write_all(FILE* f, const void* p, size_t n) {
  return fwrite(p, 1, n, f) == n;
}

// Make a rename/unlink durable: fsync the containing directory. Without
// this, power loss can persist a later WAL truncation while losing the
// SST rename it depends on (the acknowledged writes would vanish).
// Returns false on open/fsync failure — callers that are about to
// truncate the WAL MUST treat that as a failed flush, not a no-op.
bool fsync_dir(const std::string& dir) {
  int fd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (fd < 0) return false;
  bool ok = fsync(fd) == 0;
  close(fd);
  return ok;
}

uint32_t fnv1a(const char* p, size_t n) {
  uint32_t h = 2166136261u;
  for (size_t i = 0; i < n; ++i) {
    h ^= (uint8_t)p[i];
    h *= 16777619u;
  }
  return h;
}

// ---- record IO -----------------------------------------------------------
// record: [u32 klen][u32 vlen | kTombstone][key][value?]

// Reads the record at *off (which the caller positioned via fseek or a
// previous read); advances *off past it. skip_value avoids materializing
// the payload (header-only walks: index build, count, range delete).
// Returns 1 = record read, 0 = clean EOF (off exactly at limit),
// -1 = I/O error or corruption — callers that destroy source files
// (compaction) MUST distinguish the last two: a mid-stream error that
// looked like EOF would silently truncate the merge output.
int read_rec(FILE* f, uint64_t limit, uint64_t* off, Entry* e,
             bool skip_value) {
  if (*off == limit) return 0;
  if (*off + 8 > limit) return -1;
  uint32_t kl, vl;
  if (fread(&kl, 1, 4, f) != 4 || fread(&vl, 1, 4, f) != 4) return -1;
  uint64_t vbytes = (vl == kTombstone) ? 0 : vl;
  if (*off + 8 + kl + vbytes > limit) return -1;
  e->key.resize(kl);
  if (kl && fread(&e->key[0], 1, kl, f) != kl) return -1;
  e->tombstone = (vl == kTombstone);
  e->value.clear();
  if (!e->tombstone && vbytes) {
    if (skip_value) {
      if (fseek(f, (long)vbytes, SEEK_CUR) != 0) return -1;
    } else {
      e->value.resize(vbytes);
      if (fread(&e->value[0], 1, vbytes, f) != vbytes) return -1;
    }
  }
  *off += 8 + kl + vbytes;
  return 1;
}

// Sequential cursor over one SST's records (all access under db->mu).
struct Cursor {
  Sst* sst = nullptr;
  uint64_t off = 0;
  Entry cur;
  bool ok = false;
  bool err = false;
  bool skip_values = false;

  void seek_to(uint64_t o) {
    off = o;
    if (fseek(sst->f, (long)off, SEEK_SET) != 0) {
      ok = false;
      err = true;
      return;
    }
    advance();
  }
  void advance() {
    int rc = read_rec(sst->f, sst->data_bytes, &off, &cur, skip_values);
    ok = rc == 1;
    err = rc < 0;
  }
};

// floor sparse-index offset for `key` (start of file when key precedes all)
uint64_t floor_offset(const Sst& sst, const std::string& key) {
  auto it = std::upper_bound(sst.idx_keys.begin(), sst.idx_keys.end(), key);
  if (it == sst.idx_keys.begin()) return 0;
  return sst.idx_offs[(it - sst.idx_keys.begin()) - 1];
}

// ---- sparse index persistence -------------------------------------------
// .idx: [u32 magic][u64 count][u64 data_bytes][u32 max_klen][max_key]
//       [u32 n][n x (u64 off, u32 klen, key)][u32 fnv1a of everything above]
//
// The side file is best-effort (rebuildable by one scan), so the read path
// must never TRUST it: the whole file is read into memory (bounded by its
// actual size), checksum-verified — a rename can survive power loss while
// its data blocks do not — and then parsed with per-field bounds so a
// corrupt length can neither over-allocate nor over-read.
void put_bytes(std::string* b, const void* p, size_t n) {
  b->append((const char*)p, n);
}

bool write_idx_file(const Db& db, const Sst& sst) {
  std::string buf;
  uint32_t magic = kIdxMagic;
  uint32_t mkl = (uint32_t)sst.max_key.size();
  uint32_t n = (uint32_t)sst.idx_keys.size();
  put_bytes(&buf, &magic, 4);
  put_bytes(&buf, &sst.count, 8);
  put_bytes(&buf, &sst.data_bytes, 8);
  put_bytes(&buf, &mkl, 4);
  buf.append(sst.max_key);
  put_bytes(&buf, &n, 4);
  for (uint32_t i = 0; i < n; ++i) {
    uint32_t kl = (uint32_t)sst.idx_keys[i].size();
    put_bytes(&buf, &sst.idx_offs[i], 8);
    put_bytes(&buf, &kl, 4);
    buf.append(sst.idx_keys[i]);
  }
  uint32_t sum = fnv1a(buf.data(), buf.size());
  put_bytes(&buf, &sum, 4);
  std::string tmp = db.idx_path(sst.id) + ".tmp";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return false;
  bool ok = write_all(f, buf.data(), buf.size());
  if (ok) {
    fflush(f);
    fsync(fileno(f));
  }
  fclose(f);
  if (!ok) {
    unlink(tmp.c_str());
    return false;
  }
  return rename(tmp.c_str(), db.idx_path(sst.id).c_str()) == 0;
}

bool read_idx_file(const Db& db, Sst* sst, uint64_t file_bytes) {
  std::string path = db.idx_path(sst->id);
  struct stat st;
  // cap: a sparse index is ~1/kIndexEvery of the SST; anything bigger than
  // the SST itself (+slack) is garbage, reject before allocating
  if (stat(path.c_str(), &st) != 0) return false;
  uint64_t sz = (uint64_t)st.st_size;
  if (sz < 32 || sz > file_bytes + (1u << 20)) return false;
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) return false;
  std::string buf(sz, '\0');
  bool ok = fread(&buf[0], 1, sz, f) == sz;
  fclose(f);
  if (!ok) return false;
  uint32_t want;
  memcpy(&want, buf.data() + sz - 4, 4);
  if (fnv1a(buf.data(), sz - 4) != want) return false;
  const char* p = buf.data();
  const char* lim = buf.data() + sz - 4;
  auto take = [&](void* dst, size_t n) {
    if ((size_t)(lim - p) < n) return false;
    memcpy(dst, p, n);
    p += n;
    return true;
  };
  uint32_t magic = 0, mkl = 0, n = 0;
  if (!take(&magic, 4) || magic != kIdxMagic || !take(&sst->count, 8) ||
      !take(&sst->data_bytes, 8) || !take(&mkl, 4)) {
    return false;
  }
  if ((size_t)(lim - p) < mkl) return false;
  sst->max_key.assign(p, mkl);
  p += mkl;
  if (!take(&n, 4)) return false;
  for (uint32_t i = 0; i < n; ++i) {
    uint64_t off;
    uint32_t kl;
    if (!take(&off, 8) || !take(&kl, 4) || (size_t)(lim - p) < kl) {
      return false;
    }
    sst->idx_offs.push_back(off);
    sst->idx_keys.emplace_back(p, kl);
    p += kl;
  }
  // stale side file (e.g. partial checkpoint restore): fall back to scan
  return sst->data_bytes <= file_bytes;
}

// one sequential header walk: offsets + sparse keys, payloads skipped
bool build_idx_by_scan(Sst* sst, uint64_t file_bytes) {
  if (fseek(sst->f, 0, SEEK_SET) != 0) return false;
  uint64_t off = 0;
  Entry e;
  while (true) {
    uint64_t rec_off = off;
    // a torn tail on a legacy/checkpoint file truncates to the clean
    // prefix (nothing is destroyed at open time)
    if (read_rec(sst->f, file_bytes, &off, &e, true) != 1) break;
    if (sst->count % kIndexEvery == 0) {
      sst->idx_keys.push_back(e.key);
      sst->idx_offs.push_back(rec_off);
    }
    sst->max_key = e.key;
    sst->count++;
  }
  sst->data_bytes = off;   // clean prefix; trailing garbage is unreachable
  return true;
}

bool open_sst(Db* db, uint64_t id) {
  std::string path = db->sst_path(id);
  struct stat st;
  if (stat(path.c_str(), &st) != 0) return false;
  auto sst = std::make_unique<Sst>();
  sst->id = id;
  sst->f = fopen(path.c_str(), "rb");
  if (!sst->f) return false;
  if (!read_idx_file(*db, sst.get(), (uint64_t)st.st_size)) {
    sst->idx_keys.clear();
    sst->idx_offs.clear();
    sst->count = 0;
    sst->max_key.clear();
    if (!build_idx_by_scan(sst.get(), (uint64_t)st.st_size)) return false;
    write_idx_file(*db, *sst);   // best-effort cache for the next open
  }
  if (sst->count == 0) {         // fully-empty file: nothing to serve
    unlink(path.c_str());
    unlink(db->idx_path(id).c_str());
    return true;
  }
  db->ssts.push_back(std::move(sst));
  return true;
}

// ---- framed op buffers (shared by WAL payloads and the batch ABI) --------
// op buffer: repeated [u8 op][u32 klen][u32 vlen][key][value]
bool apply_ops(Db* db, const char* buf, size_t len) {
  size_t off = 0;
  while (off < len) {
    if (off + 9 > len) return false;
    uint8_t op = (uint8_t)buf[off];
    uint32_t kl, vl;
    memcpy(&kl, buf + off + 1, 4);
    memcpy(&vl, buf + off + 5, 4);
    off += 9;
    if (off + kl > len) return false;
    std::string key(buf + off, kl);
    off += kl;
    std::string value;
    if (op == kOpPut) {
      if (off + vl > len) return false;
      value.assign(buf + off, vl);
      off += vl;
    }
    uint64_t delta = key.size() + value.size() + 48;
    auto it = db->memtable.find(key);
    if (it != db->memtable.end()) {
      db->memtable_bytes -=
          it->first.size() + (it->second ? it->second->size() : 0) + 48;
    }
    if (op == kOpPut) {
      db->memtable[key] = std::move(value);
    } else {
      db->memtable[key] = std::nullopt;  // tombstone (may mask SST rows)
    }
    db->memtable_bytes += delta;
  }
  return true;
}

// ---- SST writing ---------------------------------------------------------

// Streaming SST writer: records in, sparse index built on the fly.
struct SstWriter {
  FILE* f = nullptr;
  std::string tmp, final_path;
  uint64_t off = 0;
  uint64_t count = 0;
  std::vector<std::string> idx_keys;
  std::vector<uint64_t> idx_offs;
  std::string max_key;
  bool failed = false;

  ~SstWriter() {          // abort path: drop the half-written temp file
    if (f) {
      fclose(f);
      unlink(tmp.c_str());
    }
  }

  bool open(const std::string& path) {
    final_path = path;
    tmp = path + ".tmp";
    f = fopen(tmp.c_str(), "wb");
    return f != nullptr;
  }
  void add(const Entry& e) {
    if (failed) return;
    uint32_t kl = (uint32_t)e.key.size();
    uint32_t vl = e.tombstone ? kTombstone : (uint32_t)e.value.size();
    if (count % kIndexEvery == 0) {
      idx_keys.push_back(e.key);
      idx_offs.push_back(off);
    }
    if (!write_all(f, &kl, 4) || !write_all(f, &vl, 4) ||
        !write_all(f, e.key.data(), kl) ||
        (!e.tombstone && !write_all(f, e.value.data(), e.value.size()))) {
      failed = true;
      return;
    }
    off += 8 + kl + (e.tombstone ? 0 : e.value.size());
    max_key = e.key;
    count++;
  }
  // returns the opened Sst (handle on the renamed file) or nullptr
  std::unique_ptr<Sst> finish(Db* db, uint64_t id) {
    if (!f) return nullptr;
    fflush(f);
    fsync(fileno(f));
    fclose(f);
    f = nullptr;
    if (failed) {   // don't touch the live input's .idx on an aborted merge
      unlink(tmp.c_str());
      return nullptr;
    }
    // a leftover .idx from a previous file under this id (id reuse by
    // merge_run_locked) must die BEFORE the rename: a crash in between
    // would otherwise pair the new .sst with an index describing the old
    // one (the checksum can't catch that — the old idx is self-consistent)
    unlink(db->idx_path(id).c_str());
    if (rename(tmp.c_str(), final_path.c_str()) != 0) {
      unlink(tmp.c_str());
      return nullptr;
    }
    // sync_writes promises power-loss durability: the rename (and the idx
    // unlink) must hit disk before flush_locked truncates the WAL, or the
    // fsync'd commits could vanish with the lost rename. A failed dir
    // fsync therefore fails the whole flush — the WAL stays, replay
    // re-covers the data (the orphan SST is newest-wins-safe on reopen).
    if (db->sync_writes && !fsync_dir(db->dir)) return nullptr;
    auto sst = std::make_unique<Sst>();
    sst->id = id;
    sst->f = fopen(final_path.c_str(), "rb");
    if (!sst->f) return nullptr;
    sst->data_bytes = off;
    sst->count = count;
    sst->idx_keys = std::move(idx_keys);
    sst->idx_offs = std::move(idx_offs);
    sst->max_key = std::move(max_key);
    write_idx_file(*db, *sst);   // best-effort (rebuildable by scan)
    return sst;
  }
};

int flush_locked(Db* db);

// Streaming k-way merge of an age-contiguous run [lo, hi) of db->ssts into
// one new SST. Newest (highest vector position) wins ties; tombstones are
// dropped only when the run includes the oldest SST (lo == 0) — otherwise
// an older SST below the run could resurrect the deleted key.
int merge_run_locked(Db* db, size_t lo, size_t hi) {
  size_t n = hi - lo;
  if (n < 2) return 0;
  std::vector<Cursor> curs(n);
  for (size_t i = 0; i < n; ++i) {
    curs[i].sst = db->ssts[lo + i].get();
    curs[i].seek_to(0);
  }
  bool drop_tombstones = (lo == 0);
  // The output REUSES the oldest input's id. lsm_open rebuilds age order
  // by sorting ids, so the merged run must sort exactly where the run
  // lived; a fresh (highest) id would make the merged OLD data the newest
  // SST after reopen and resurrect stale/deleted keys. Inputs are deleted
  // below, and id order == age order held before the merge, so reusing
  // min(run ids) preserves the invariant. Crash safety: after the rename
  // clobbers input[lo] but before the other inputs are unlinked, the
  // leftovers carry newer ids and duplicate the merged content, so
  // newest-wins resolves identically on reopen.
  uint64_t id = db->ssts[lo]->id;
  SstWriter w;
  if (!w.open(db->sst_path(id))) return -1;
  auto any_err = [&] {
    for (size_t i = 0; i < n; ++i) {
      if (curs[i].err) return true;
    }
    return false;
  };
  while (true) {
    // a read error anywhere aborts the merge WITH the inputs intact — an
    // error mistaken for EOF would truncate the output and then the
    // unlinks below would destroy the only copy of the tail
    if (any_err()) return -1;   // ~SstWriter drops the temp file
    // smallest key among live cursors; on ties the NEWEST (largest i) wins
    int best = -1;
    for (size_t i = 0; i < n; ++i) {
      if (!curs[i].ok) continue;
      if (best < 0 || curs[i].cur.key < curs[best].cur.key ||
          (curs[i].cur.key == curs[best].cur.key && (int)i > best)) {
        best = (int)i;
      }
    }
    if (best < 0) break;
    // copy, not reference: advancing the winning cursor below mutates its
    // cur.key in place, and the pop comparisons must keep the OLD key
    const std::string k = curs[best].cur.key;
    if (!(drop_tombstones && curs[best].cur.tombstone)) {
      w.add(curs[best].cur);
    }
    for (size_t i = 0; i < n; ++i) {   // pop every cursor sitting on k
      while (curs[i].ok && curs[i].cur.key == k) curs[i].advance();
    }
    if (w.failed) return -1;
  }
  auto merged = w.finish(db, id);
  bool empty = (w.count == 0);
  if (!merged && !empty) return -1;
  for (size_t i = lo; i < hi; ++i) {
    uint64_t iid = db->ssts[i]->id;
    if (iid == id) continue;   // the output now lives under this id
    unlink(db->sst_path(iid).c_str());
    unlink(db->idx_path(iid).c_str());
  }
  db->ssts.erase(db->ssts.begin() + lo, db->ssts.begin() + hi);
  if (merged && !empty) {
    db->ssts.insert(db->ssts.begin() + lo, std::move(merged));
  } else {
    unlink(db->sst_path(id).c_str());
    unlink(db->idx_path(id).c_str());
  }
  // in-memory state already matches the directory contents; a failed dir
  // fsync only leaves durability unknown, so surface it to the caller
  if (db->sync_writes && !fsync_dir(db->dir)) return -1;
  return 0;
}

// full-merge compaction (explicit API): everything into one, tombstone GC
int compact_locked(Db* db) {
  if (flush_locked(db) != 0) return -1;
  if (db->ssts.size() < 2) return 0;
  return merge_run_locked(db, 0, db->ssts.size());
}

// size-tiered: merge the oldest age-contiguous run of >= kTierFanout SSTs
// whose sizes stay within kTierFactor of the run's smallest member
int maybe_compact_locked(Db* db) {
  size_t n = db->ssts.size();
  if ((int)n < db->compact_trigger) return 0;
  for (size_t i = 0; i < n; ++i) {
    uint64_t lo_bytes = UINT64_MAX, hi_bytes = 0;
    size_t j = i;
    for (; j < n; ++j) {
      uint64_t b = std::max<uint64_t>(db->ssts[j]->data_bytes, 1);
      uint64_t nlo = std::min(lo_bytes, b), nhi = std::max(hi_bytes, b);
      if ((double)nhi > kTierFactor * (double)nlo) break;
      lo_bytes = nlo;
      hi_bytes = nhi;
    }
    if (j - i >= (size_t)kTierFanout) return merge_run_locked(db, i, j);
  }
  // no similar-size run but far too many files: bound the count anyway
  if ((int)n >= 2 * db->compact_trigger) {
    return merge_run_locked(db, 0, n);
  }
  return 0;
}

int flush_locked(Db* db) {
  if (db->memtable.empty()) return 0;
  uint64_t id = db->next_sst_id++;
  SstWriter w;
  if (!w.open(db->sst_path(id))) return -1;
  for (const auto& [k, v] : db->memtable) {
    Entry e;
    e.key = k;
    e.tombstone = !v.has_value();
    if (v) e.value = *v;
    w.add(e);
  }
  auto sst = w.finish(db, id);
  if (!sst) return -1;
  db->ssts.push_back(std::move(sst));
  db->memtable.clear();
  db->memtable_bytes = 0;
  // truncate the WAL: its contents are now durable in the SST
  if (db->wal) fclose(db->wal);
  db->wal = fopen(db->wal_path().c_str(), "wb");
  if (!db->wal) return -1;
  return maybe_compact_locked(db);
}

int append_wal(Db* db, const char* ops, size_t len) {
  uint32_t magic = kWalMagic, l = (uint32_t)len;
  if (!db->wal) return -1;
  if (!write_all(db->wal, &magic, 4) || !write_all(db->wal, &l, 4) ||
      !write_all(db->wal, ops, len)) {
    return -1;
  }
  fflush(db->wal);
  // sync_writes: survive power loss, not just process death. Off by
  // default — raft replication is the availability story and fsync per
  // commit costs ~ms on commodity disks.
  if (db->sync_writes) fsync(fileno(db->wal));
  return 0;
}

void replay_wal(Db* db) {
  FILE* f = fopen(db->wal_path().c_str(), "rb");
  if (!f) return;
  long good = 0;
  std::vector<char> buf;
  for (;;) {
    uint32_t magic, len;
    if (fread(&magic, 1, 4, f) != 4) break;
    if (magic != kWalMagic) break;
    if (fread(&len, 1, 4, f) != 4) break;
    buf.resize(len);
    if (len && fread(buf.data(), 1, len, f) != len) break;
    if (!apply_ops(db, buf.data(), len)) break;
    good = ftell(f);
  }
  fclose(f);
  // torn-tail truncation: appends after garbage would be unreachable on
  // the next replay (same contract as the Python WalEngine)
  struct stat st;
  if (stat(db->wal_path().c_str(), &st) == 0 && st.st_size > good) {
    truncate(db->wal_path().c_str(), good);
  }
}

// merged newest-wins walk of [start, end): calls fn(key, Entry) for every
// LIVE (non-tombstone) key in ascending order. A streaming k-way merge
// over the SST cursors plus the memtable — O(#ssts) state regardless of
// range size (an unbounded count/delete over millions of keys must not
// materialize them; the same merge shape as merge_run_locked).
// Returns false if any cursor hit an I/O error — callers MUST distinguish
// that from a clean end: an error mistaken for exhaustion silently
// truncates scans, under-counts, and under-deletes ranges.
template <typename Fn>
bool merged_range_locked(Db* db, const std::string& start,
                         const std::string& end, bool has_end, bool want_values,
                         Fn&& fn) {
  size_t n = db->ssts.size();
  std::vector<Cursor> curs(n);   // index order == age order (older first)
  for (size_t i = 0; i < n; ++i) {
    Sst* sst = db->ssts[i].get();
    curs[i].sst = sst;
    curs[i].skip_values = !want_values;  // count/delete stay header-only
    if (start > sst->max_key) continue;  // whole SST precedes the range
    curs[i].seek_to(floor_offset(*sst, start));
    // skip records before start (floor entry may precede it)
    while (curs[i].ok && curs[i].cur.key < start) curs[i].advance();
  }
  auto mit = db->memtable.lower_bound(start);
  auto live = [&](size_t i) {
    return curs[i].ok && (!has_end || curs[i].cur.key < end);
  };
  while (true) {
    for (size_t i = 0; i < n; ++i) {
      if (curs[i].err) return false;
    }
    // smallest key among live cursors; ties go to the NEWEST (largest i)
    int best = -1;
    for (size_t i = 0; i < n; ++i) {
      if (!live(i)) continue;
      if (best < 0 || curs[i].cur.key <= curs[best].cur.key) best = (int)i;
    }
    bool mem_live = mit != db->memtable.end() &&
                    (!has_end || mit->first < end);
    // the memtable is newer than every SST, so it wins ties outright
    bool use_mem =
        mem_live && (best < 0 || mit->first <= curs[best].cur.key);
    if (!use_mem && best < 0) break;
    if (use_mem) {
      const std::string& k = mit->first;
      if (mit->second) {
        Entry e;
        e.key = k;
        if (want_values) e.value = *mit->second;
        fn(k, e);
      }
      for (size_t i = 0; i < n; ++i) {   // pop shadowed SST records
        while (curs[i].ok && curs[i].cur.key == k) curs[i].advance();
      }
      ++mit;
    } else {
      // copy, not reference: popping the winning cursor below mutates
      // its cur.key in place
      const std::string k = curs[best].cur.key;
      if (!curs[best].cur.tombstone) fn(k, curs[best].cur);
      for (size_t i = 0; i < n; ++i) {
        while (curs[i].ok && curs[i].cur.key == k) curs[i].advance();
      }
    }
  }
  return true;
}

struct Iter {
  std::vector<std::pair<std::string, std::string>> rows;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* lsm_open(const char* dir, uint64_t memtable_bytes, int sync_writes) {
  auto* db = new Db();
  db->dir = dir;
  if (memtable_bytes) db->memtable_limit = memtable_bytes;
  db->sync_writes = sync_writes != 0;
  mkdir(dir, 0755);
  // open SSTs in id order (sparse index only; payloads stay on disk)
  std::vector<uint64_t> ids;
  if (DIR* d = opendir(dir)) {
    while (dirent* e = readdir(d)) {
      std::string name = e->d_name;
      if (name.size() == 16 && name.substr(12) == ".sst") {
        ids.push_back(strtoull(name.c_str(), nullptr, 10));
      } else if (name.size() > 4 &&
                 name.compare(name.size() - 4, 4, ".tmp") == 0) {
        // half-written flush/merge/idx output from a crash
        unlink((db->dir + "/" + name).c_str());
      }
    }
    closedir(d);
  }
  std::sort(ids.begin(), ids.end());
  for (uint64_t id : ids) {
    // a failed open (fd exhaustion, I/O error during the index scan) must
    // fail the WHOLE open: proceeding without one SST would silently serve
    // not-found / stale values for every key that lived in it
    if (!open_sst(db, id)) {
      delete db;
      return nullptr;
    }
    db->next_sst_id = std::max(db->next_sst_id, id + 1);
  }
  replay_wal(db);
  db->wal = fopen(db->wal_path().c_str(), "ab");
  if (!db->wal) {
    delete db;
    return nullptr;
  }
  return db;
}

void lsm_close(void* h) {
  auto* db = (Db*)h;
  if (!db) return;
  if (db->wal) fclose(db->wal);
  delete db;
}

int lsm_write(void* h, const char* ops, uint64_t len) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  if (append_wal(db, ops, len) != 0) return -1;
  if (!apply_ops(db, ops, len)) return -2;
  if (db->memtable_bytes >= db->memtable_limit) return flush_locked(db);
  return 0;
}

int lsm_get(void* h, const char* k, uint64_t kl, char** out, uint64_t* outl) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  std::string key(k, kl);
  auto it = db->memtable.find(key);
  if (it != db->memtable.end()) {
    if (!it->second) return 1;  // tombstone
    *outl = it->second->size();
    *out = (char*)malloc(*outl);
    memcpy(*out, it->second->data(), *outl);
    return 0;
  }
  // newest SST first; <= kIndexEvery records read per miss
  for (auto r = db->ssts.rbegin(); r != db->ssts.rend(); ++r) {
    Sst* sst = r->get();
    if (sst->idx_keys.empty() || key < sst->idx_keys[0] ||
        key > sst->max_key) {
      continue;
    }
    Cursor c;
    c.sst = sst;
    c.seek_to(floor_offset(*sst, key));
    for (; c.ok && c.cur.key <= key; c.advance()) {
      if (c.cur.key == key) {
        if (c.cur.tombstone) return 1;
        *outl = c.cur.value.size();
        *out = (char*)malloc(*outl);
        memcpy(*out, c.cur.value.data(), *outl);
        return 0;
      }
    }
    // an I/O error is NOT "not found": the key may live past the failed
    // read, and falling through to older SSTs could serve a stale value
    if (c.err) return -1;
  }
  return 1;
}

void lsm_free_buf(char* p) { free(p); }

void* lsm_scan(void* h, const char* s, uint64_t sl, const char* e,
               uint64_t el, int has_end, int reverse) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  auto* it = new Iter();
  bool ok = merged_range_locked(
      db, std::string(s, sl), std::string(e, el), has_end != 0, true,
      [&](const std::string& k, const Entry& en) {
        it->rows.emplace_back(k, en.value);
      });
  if (!ok) {   // I/O error mid-merge: a truncated scan must not look clean
    delete it;
    return nullptr;
  }
  if (reverse) std::reverse(it->rows.begin(), it->rows.end());
  return it;
}

int lsm_iter_next(void* h, const char** k, uint64_t* kl, const char** v,
                  uint64_t* vl) {
  auto* it = (Iter*)h;
  if (it->pos >= it->rows.size()) return 1;
  const auto& row = it->rows[it->pos++];
  *k = row.first.data();
  *kl = row.first.size();
  *v = row.second.data();
  *vl = row.second.size();
  return 0;
}

void lsm_iter_close(void* h) { delete (Iter*)h; }

uint64_t lsm_count(void* h, const char* s, uint64_t sl, const char* e,
                   uint64_t el, int has_end) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  uint64_t n = 0;
  if (!merged_range_locked(db, std::string(s, sl), std::string(e, el),
                           has_end != 0, false,
                           [&](const std::string&, const Entry&) { ++n; })) {
    return UINT64_MAX;   // error sentinel (a real count can't reach this)
  }
  return n;
}

// Tombstone every live key in [start, end) — has_end=0 means unbounded,
// matching lsm_scan — as ONE atomic WAL record; returns the number of
// keys deleted (exact at apply time — the scan and the write happen
// under the same lock acquisition).
int64_t lsm_delete_range(void* h, const char* s, uint64_t sl, const char* e,
                         uint64_t el, int has_end) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  std::string ops;
  int64_t n = 0;
  bool ok = merged_range_locked(
      db, std::string(s, sl), std::string(e, el), has_end != 0, false,
      [&](const std::string& k, const Entry&) {
        uint8_t op = kOpDelete;
        uint32_t kl = (uint32_t)k.size(), vl = 0;
        ops.append((const char*)&op, 1);
        ops.append((const char*)&kl, 4);
        ops.append((const char*)&vl, 4);
        ops.append(k);
        ++n;
      });
  // a scan error must abort the whole delete: tombstoning only the prefix
  // we managed to read and reporting success would diverge raft replicas
  if (!ok) return -3;
  if (n == 0) return 0;
  if (append_wal(db, ops.data(), ops.size()) != 0) return -1;
  if (!apply_ops(db, ops.data(), ops.size())) return -2;
  if (db->memtable_bytes >= db->memtable_limit) {
    if (flush_locked(db) != 0) return -1;
  }
  return n;
}

int lsm_flush(void* h) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  return flush_locked(db);
}

int lsm_compact(void* h) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  return compact_locked(db);
}

uint64_t lsm_sst_count(void* h) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  return db->ssts.size();
}

// resident index memory (diagnostics): sparse keys + offsets only
uint64_t lsm_index_bytes(void* h) {
  auto* db = (Db*)h;
  std::lock_guard<std::recursive_mutex> g(db->mu);
  uint64_t n = 0;
  for (const auto& sst : db->ssts) {
    n += sst->idx_offs.size() * 8;
    for (const auto& k : sst->idx_keys) n += k.size() + 32;
  }
  return n;
}

}  // extern "C"
