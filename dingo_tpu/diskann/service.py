"""DiskAnnService: the --role=diskann server's RPC surface.

Reference: DiskAnnServiceHandle (diskann_service_handle.h:29-62) —
VectorNew/PushData/Build/Load/TryLoad/Search/Reset/Close/Destroy/Status/
Count over brpc, registered by main.cc:1340 for the diskann role.
"""

from __future__ import annotations

import numpy as np

from dingo_tpu.diskann.core import CoreState, DiskAnnError
from dingo_tpu.diskann.item import DiskAnnItemManager
from dingo_tpu.index.base import InvalidParameter
from dingo_tpu.server import convert, pb
from dingo_tpu.server.services import _err


class DiskAnnService:
    def __init__(self, manager: DiskAnnItemManager):
        self.manager = manager

    def _core_or_err(self, index_id, resp):
        core = self.manager.get(index_id)
        if core is None:
            _err(resp, 50001, f"diskann index {index_id} not found")
            return None
        return core

    def DiskAnnNew(self, req: pb.DiskAnnNewRequest):
        resp = pb.DiskAnnNewResponse()
        param = convert.index_parameter_from_pb(req.parameter)
        if param is None:
            return _err(resp, 50002, "missing index parameter")
        try:
            self.manager.create(req.vector_index_id, param)
        except (DiskAnnError, InvalidParameter) as e:
            return _err(resp, 50002, str(e))
        return resp

    def DiskAnnPushData(self, req: pb.DiskAnnPushDataRequest):
        resp = pb.DiskAnnPushDataResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        try:
            vectors = np.asarray(
                [list(v.values) for v in req.vectors], np.float32
            )
            resp.already_recv_vector_count = core.push_data(
                np.asarray(list(req.vector_ids), np.int64),
                vectors, req.has_more,
            )
        except (DiskAnnError, InvalidParameter, ValueError) as e:
            return _err(resp, 50003, str(e))
        return resp

    def DiskAnnBuild(self, req: pb.DiskAnnBuildRequest):
        resp = pb.DiskAnnBuildResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        try:
            if req.sync:
                core.build()
            else:
                self.manager.submit_build(req.vector_index_id)
        except (DiskAnnError, InvalidParameter) as e:
            return _err(resp, 50004, str(e))
        resp.state = core.status().value
        return resp

    def DiskAnnLoad(self, req: pb.DiskAnnLoadRequest):
        resp = pb.DiskAnnLoadResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        try:
            if req.try_load:
                core.try_load()
            else:
                core.load()
        except (DiskAnnError, InvalidParameter) as e:
            return _err(resp, 50005, str(e))
        resp.state = core.status().value
        return resp

    def DiskAnnSearch(self, req: pb.DiskAnnSearchRequest):
        resp = pb.DiskAnnSearchResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        try:
            queries = np.asarray(
                [list(v.values) for v in req.vectors], np.float32
            )
            rows = core.search(queries, int(req.top_n or 10),
                               nprobe=int(req.nprobe) or None)
        except (DiskAnnError, InvalidParameter, ValueError) as e:
            return _err(resp, 50006, str(e))
        for ids, dists in rows:
            r = resp.batch_results.add()
            for vid, dist in zip(ids, dists):
                item = r.results.add()
                item.vector.id = int(vid)
                item.distance = float(dist)
        return resp

    def DiskAnnStatus(self, req: pb.DiskAnnStatusRequest):
        resp = pb.DiskAnnStatusResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        resp.state = core.status().value
        resp.last_error = core.last_error
        resp.count = core.count
        return resp

    def DiskAnnCount(self, req: pb.DiskAnnCountRequest):
        resp = pb.DiskAnnCountResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        resp.count = core.count
        return resp

    def DiskAnnReset(self, req: pb.DiskAnnResetRequest):
        resp = pb.DiskAnnResetResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        core.reset(delete_data_file=req.delete_data_file)
        return resp

    def DiskAnnClose(self, req: pb.DiskAnnCloseRequest):
        resp = pb.DiskAnnCloseResponse()
        core = self._core_or_err(req.vector_index_id, resp)
        if core is None:
            return resp
        core.close()
        return resp

    def DiskAnnDestroy(self, req: pb.DiskAnnDestroyRequest):
        resp = pb.DiskAnnDestroyResponse()
        if self.manager.get(req.vector_index_id) is None:
            return _err(resp, 50001,
                        f"diskann index {req.vector_index_id} not found")
        self.manager.destroy(req.vector_index_id)
        return resp
