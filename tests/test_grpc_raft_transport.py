"""Multi-PROCESS-style raft over grpc: three store nodes each with their own
GrpcRaftTransport talking through real sockets (no shared in-proc bus), a
replicated INDEX region, failover, and the PushService path."""

import time

import numpy as np
import pytest

from dingo_tpu.coordinator.control import CoordinatorControl
from dingo_tpu.engine.raw_engine import MemEngine
from dingo_tpu.index import codec as vcodec
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.raft.grpc_transport import GrpcRaftTransport
from dingo_tpu.server import pb
from dingo_tpu.server.rpc import DingoServer, ServiceStub
from dingo_tpu.store.node import StoreNode
from dingo_tpu.store.region import RegionType

STORES = ["s0", "s1", "s2"]


@pytest.fixture()
def cluster():
    coord = CoordinatorControl(MemEngine(), replication=3)
    nodes, servers, addrs, transports = {}, [], {}, {}
    # create nodes first (ports unknown until servers start)
    for i, sid in enumerate(STORES):
        t = GrpcRaftTransport(sid)
        node = StoreNode(sid, t, coord, raft_kw={"seed": i})
        srv = DingoServer()
        srv.host_store_role(node)
        port = srv.start()
        nodes[sid] = node
        transports[sid] = t
        addrs[sid] = f"127.0.0.1:{port}"
        servers.append(srv)
    # wire peer addresses (the config/registry step of a real deployment)
    for t in transports.values():
        for sid, addr in addrs.items():
            t.set_peer(sid, addr)
    for n in nodes.values():
        n.start_heartbeat(0.1)
    yield coord, nodes, addrs, transports
    for s in servers:
        s.stop()
    for n in nodes.values():
        n.stop()
    for t in transports.values():
        t.close()


def wait_leader(nodes, region_id, timeout=8.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [
            n for n in nodes.values()
            if (rn := n.engine.get_node(region_id)) is not None
            and rn.is_leader()
        ]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.03)
    raise AssertionError("no unique leader over grpc transport")


def test_replication_over_sockets(cluster):
    coord, nodes, addrs, transports = cluster
    d = coord.create_region(
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1 << 30),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(index_type=IndexType.FLAT, dimension=8),
    )
    time.sleep(1.2)
    leader = wait_leader(nodes, d.region_id)
    region = leader.get_region(d.region_id)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((30, 8)).astype(np.float32)
    leader.storage.vector_add(region, np.arange(30, dtype=np.int64), x)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        counts = [n.storage.vector_count(n.get_region(d.region_id))
                  for n in nodes.values() if n.get_region(d.region_id)]
        if counts == [30, 30, 30]:
            break
        time.sleep(0.05)
    assert counts == [30, 30, 30]
    # every replica's index converged through socket replication
    for n in nodes.values():
        r = n.get_region(d.region_id)
        assert r.vector_index_wrapper.get_count() == 30


def test_failover_over_sockets(cluster):
    coord, nodes, addrs, transports = cluster
    d = coord.create_region(start_key=b"a", end_key=b"z")
    time.sleep(1.2)
    leader = wait_leader(nodes, d.region_id)
    region = leader.get_region(d.region_id)
    leader.storage.kv_put(region, [(b"k", b"v")])
    # drop the leader's transport links (its server keeps running, but its
    # outgoing messages fail -> followers elect a new leader)
    dead_sid = leader.store_id
    for t in transports.values():
        if t.store_id != dead_sid:
            t.set_peer(dead_sid, "127.0.0.1:1")   # unroutable
    for sid in STORES:
        if sid != dead_sid:
            transports[dead_sid].set_peer(sid, "127.0.0.1:1")
    survivors = {sid: n for sid, n in nodes.items() if sid != dead_sid}
    new_leader = wait_leader(survivors, d.region_id)
    r2 = new_leader.get_region(d.region_id)
    new_leader.storage.kv_put(r2, [(b"k2", b"v2")])
    assert new_leader.storage.kv_get(r2, b"k") == b"v"


def test_push_service(cluster):
    coord, nodes, addrs, transports = cluster
    d = coord.create_region(
        start_key=b"p", end_key=b"q", replication=2,
    )
    # deliver the CREATE commands by PUSH instead of waiting for heartbeat
    import grpc

    for sid in d.peers:
        pending = [c for c in coord.store_ops[sid] if c.status == "pending"]
        req = pb.PushStoreOperationRequest()
        for c in pending:
            out = req.commands.add()
            out.cmd_id = c.cmd_id
            out.region_id = c.region_id
            out.cmd_type = c.cmd_type.value
            if c.definition is not None:
                from dingo_tpu.server.convert import region_def_to_pb

                out.definition.CopyFrom(region_def_to_pb(c.definition))
        stub = ServiceStub(grpc.insecure_channel(addrs[sid]), "PushService")
        resp = stub.PushStoreOperation(req)
        assert list(resp.done_cmd_ids) == [c.cmd_id for c in pending]
        for c in pending:
            c.status = "done"
    for sid in d.peers:
        assert nodes[sid].get_region(d.region_id) is not None
