"""Device-side bulk index construction (ISSUE 18): batched MXU graph
build, streaming rebuild, shared train-sample conf.

The host insert loop stays the parity oracle: a device-built graph must
reach at least the host-built graph's recall at equal ef, build
byte-identically under a fixed seed, keep steady-state recompiles at
zero across the insert ladder, and hand over cleanly to the native
graph (back-fill) when the host path needs it. The manager build must
stream scan chunks — peak host memory O(chunk), not O(corpus).
"""

import numpy as np
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.common.metrics import METRICS
from dingo_tpu.index import IndexParameter, IndexType, new_index
from dingo_tpu.ops.distance import Metric


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    FLAGS.set("hnsw_device_build", "auto")
    FLAGS.set("hnsw_device_search", "auto")
    FLAGS.set("hnsw_build_batch", 256)
    FLAGS.set("hnsw_build_alpha", 1.0)
    FLAGS.set("train_sample_rows", 65536)


@pytest.fixture(scope="module")
def corpus():
    rng = np.random.default_rng(18)
    n, d = 1200, 32
    x = rng.standard_normal((n, d)).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    q = x[:10] + 0.01 * rng.standard_normal((10, d)).astype(np.float32)
    return ids, x, q


def hnsw_param(**kw):
    defaults = dict(
        index_type=IndexType.HNSW, dimension=32, nlinks=12,
        efconstruction=64,
    )
    defaults.update(kw)
    return IndexParameter(**defaults)


def exact_topk(x, ids, q, k, metric):
    if metric is Metric.L2:
        score = -(((q[:, None, :] - x[None, :, :]) ** 2).sum(-1))
    elif metric is Metric.COSINE:
        xn = x / np.linalg.norm(x, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        score = qn @ xn.T
    else:
        score = q @ x.T
    return ids[np.argsort(-score, axis=1)[:, :k]]


def recall(res, want, k=10):
    return float(np.mean(
        [len(set(r.ids) & set(w)) / k for r, w in zip(res, want)]
    ))


def bulk_build(rid, ids, x, chunk=500, **param_kw):
    """Build an index through the bulk device session in scan-sized
    chunks (the manager feed pattern)."""
    FLAGS.set("hnsw_device_build", True)
    idx = new_index(rid, hnsw_param(**param_kw))
    sess = idx.bulk_builder(expect_rows=len(ids))
    assert sess is not None
    for s in range(0, len(ids), chunk):
        sess.add(ids[s:s + chunk], x[s:s + chunk])
    sess.finish()
    return idx


@pytest.mark.parametrize("metric", [Metric.L2, Metric.INNER_PRODUCT,
                                    Metric.COSINE])
@pytest.mark.parametrize("tier", ["fp32", "sq8"])
def test_device_built_recall_at_least_host_built(corpus, metric, tier):
    """The acceptance gate: searching a DEVICE-built graph reaches at
    least the recall of searching a HOST-built graph at equal ef, per
    metric x precision tier (both arms use the device walk, so only the
    construction differs)."""
    ids, x, q = corpus
    dev = bulk_build(60, ids, x, metric=metric, precision=tier)
    FLAGS.set("hnsw_device_build", False)
    host = new_index(61, hnsw_param(metric=metric, precision=tier))
    host.add(ids, x)
    want = exact_topk(x, ids, q, 10, metric)
    FLAGS.set("hnsw_device_search", True)
    r_host = recall(host.search(q, 10, ef=128), want)
    r_dev = recall(dev.search(q, 10, ef=128), want)
    # sq8 arms quantize the candidate scores during construction, so the
    # two graphs see slightly different geometry — allow the noise floor
    tol = 1e-9 if tier == "fp32" else 0.05
    assert r_dev >= r_host - tol
    if metric is Metric.L2 and tier == "fp32":
        assert r_dev >= 0.9     # the built graph actually routes


def test_adjacency_byte_stable_under_fixed_seed(corpus):
    """Same rows, same order, same conf -> bit-identical adjacency and
    entry slot (no data-dependent nondeterminism in the build kernel)."""
    ids, x, q = corpus
    a = bulk_build(62, ids, x)
    b = bulk_build(63, ids, x)
    np.testing.assert_array_equal(
        np.asarray(a.store.adj), np.asarray(b.store.adj)
    )
    assert a._entry_slot == b._entry_slot


def test_incremental_insert_parity_after_bulk_build(corpus):
    """First host-path write back-fills the native graph from the store
    (O(chunk) replays), after which ordinary incremental upsert/delete
    and both search paths behave exactly as on a host-built index."""
    ids, x, q = corpus
    rng = np.random.default_rng(5)
    idx = bulk_build(64, ids, x)
    assert idx._native_pending
    # a second bulk session on a non-empty index must refuse
    assert idx.bulk_builder() is None
    bf = METRICS.counter("build.backfills", region_id=64)
    bf0 = bf.get()
    extra = rng.standard_normal((60, 32)).astype(np.float32)
    eids = np.arange(len(ids), len(ids) + 60, dtype=np.int64)
    idx.upsert(eids, extra)       # triggers the back-fill, then inserts
    assert bf.get() == bf0 + 1
    assert not idx._native_pending
    FLAGS.set("hnsw_device_search", True)
    res = idx.search(extra[:10], 1, ef=64)
    hit = np.mean([len(r.ids) and r.ids[0] == w
                   for r, w in zip(res, eids[:10])])
    assert hit >= 0.9
    # host path serves the same corpus post-back-fill
    FLAGS.set("hnsw_device_search", False)
    want = exact_topk(x, ids, q, 10, Metric.L2)
    assert recall(idx.search(q, 10, ef=128), want) >= 0.9
    # deletes flow through both representations
    idx.delete(eids)
    FLAGS.set("hnsw_device_search", True)
    for r in idx.search(extra[:5], 5, ef=64):
        assert (r.ids < len(ids)).all()


def test_zero_steady_state_recompiles_across_ladder(corpus):
    """The second bulk build at identical shapes (capacity, batch, beam,
    deg) reuses every compiled program — the monitored recompile
    invariant extended to construction."""
    ids, x, q = corpus
    bulk_build(65, ids, x)        # warm the (shape, static-args) cache
    rc = METRICS.counter("xla.recompiles")
    rc0 = rc.get()
    bulk_build(66, ids, x)
    assert rc.get() - rc0 == 0


def test_save_load_after_bulk_build(tmp_path, corpus):
    """save() back-fills first, so the snapshot carries a complete native
    blob and the restored index serves without knowing the build arm."""
    ids, x, q = corpus
    idx = bulk_build(67, ids[:600], x[:600])
    idx.save(str(tmp_path))
    assert not idx._native_pending
    idx2 = new_index(67, hnsw_param())
    idx2.load(str(tmp_path))
    FLAGS.set("hnsw_device_search", True)
    want = exact_topk(x[:600], ids[:600], q, 10, Metric.L2)
    assert recall(idx2.search(q, 10, ef=128), want) >= 0.9


def test_reverse_dropped_counted(corpus):
    """Degree-clamped reverse insertion drops overflow incomers and
    counts them (silent truncation would read as full coverage)."""
    ids, x, q = corpus
    rd = METRICS.counter("build.reverse_dropped", region_id=68)
    rd0 = rd.get()
    bulk_build(68, ids, x)
    assert rd.get() >= rd0      # non-negative fold; value is data-driven


# -- manager: streaming rebuild --------------------------------------------

def _make_stack(rid, index_type=IndexType.HNSW, **param_kw):
    from dingo_tpu.engine.mono_engine import MonoStoreEngine
    from dingo_tpu.engine.raw_engine import MemEngine
    from dingo_tpu.engine.storage import Storage
    from dingo_tpu.index import codec as vcodec
    from dingo_tpu.store.region import (
        Region,
        RegionDefinition,
        RegionType,
    )

    raw = MemEngine()
    engine = MonoStoreEngine(raw)
    storage = Storage(engine)
    defaults = dict(index_type=index_type, dimension=16, ncentroids=4,
                    default_nprobe=4, nlinks=8, efconstruction=48)
    defaults.update(param_kw)
    region = Region(RegionDefinition(
        region_id=rid,
        start_key=vcodec.encode_vector_key(0, 0),
        end_key=vcodec.encode_vector_key(0, 1 << 40),
        region_type=RegionType.INDEX,
        index_parameter=IndexParameter(**defaults),
    ))
    w = region.vector_index_wrapper
    w.build_own()
    w.set_own(w.own_index)
    return raw, engine, storage, region


def test_manager_build_streams_bounded_chunks(monkeypatch):
    """The rebuild scan pages in BUILD_BATCH-row chunks — no single call
    materializes the corpus (the old path asked for 1<<62 rows at once,
    then copied them AGAIN for the train sample)."""
    from dingo_tpu.index.manager import (
        BUILD_BATCH,
        VectorIndexManager,
    )
    from dingo_tpu.index.vector_reader import VectorReader

    raw, engine, storage, region = _make_stack(70)
    rng = np.random.default_rng(2)
    n = BUILD_BATCH + 500       # forces > 1 page
    x = rng.standard_normal((n, 16)).astype(np.float32)
    all_ids = np.arange(n, dtype=np.int64)
    for s in range(0, n, 4096):   # VECTOR_MAX_BATCH_COUNT per write
        storage.vector_add(region, all_ids[s:s + 4096], x[s:s + 4096])
    limits = []
    orig = VectorReader.vector_scan_query

    def spy(self, *args, **kwargs):
        limits.append(kwargs.get("limit", args[1] if len(args) > 1 else None))
        return orig(self, *args, **kwargs)

    monkeypatch.setattr(VectorReader, "vector_scan_query", spy)
    mgr = VectorIndexManager(raw)
    index = mgr.build_index(region)
    assert index.get_count() == n
    assert len(limits) >= 2                       # actually paged
    assert max(limits) <= BUILD_BATCH             # O(chunk), not O(corpus)
    res = index.search(x[:2], 1)
    assert [r.ids[0] for r in res] == [0, 1]


def test_manager_build_uses_bulk_device_arm():
    """With the crossover forced on, manager.build_index constructs the
    HNSW graph through the device bulk session (build.device_builds) and
    the result serves both paths."""
    from dingo_tpu.index.manager import VectorIndexManager

    raw, engine, storage, region = _make_stack(71)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((900, 16)).astype(np.float32)
    storage.vector_add(region, np.arange(900, dtype=np.int64), x)
    FLAGS.set("hnsw_device_build", True)
    db = METRICS.counter("build.device_builds", region_id=71)
    db0 = db.get()
    mgr = VectorIndexManager(raw)
    assert mgr.rebuild(region)
    assert db.get() == db0 + 1
    index = region.vector_index_wrapper.own_index
    assert index.get_count() == 900
    res = index.search(x[:2], 1)
    assert [r.ids[0] for r in res] == [0, 1]


def test_remat_override_goes_through_bulk_path():
    """PR 13 re-materialization is a rebuild with a narrowed parameter:
    the same streaming + bulk-build arm must carry it (repair time is
    degraded-serving time)."""
    from dingo_tpu.index.manager import VectorIndexManager
    from dingo_tpu.index.recovery import DeviceRecoveryPlane

    raw, engine, storage, region = _make_stack(72)
    rng = np.random.default_rng(4)
    x = rng.standard_normal((600, 16)).astype(np.float32)
    storage.vector_add(region, np.arange(600, dtype=np.int64), x)
    FLAGS.set("hnsw_device_build", True)
    db = METRICS.counter("build.device_builds", region_id=72)
    db0 = db.get()
    override = DeviceRecoveryPlane.remat_parameter(
        region.definition.index_parameter
    )
    mgr = VectorIndexManager(raw)
    assert mgr.rebuild(region, param_override=override)
    assert db.get() == db0 + 1
    index = region.vector_index_wrapper.own_index
    assert index._precision == "sq8"              # narrowed tier applied
    assert region.definition.index_parameter.precision == ""
    assert index.get_count() == 600


def test_manager_train_failure_counted_not_swallowed():
    """Too little data to train: the counter + log replace the old silent
    `except Exception: pass`; the index still installs (untrained exact
    fallback)."""
    from dingo_tpu.index.manager import VectorIndexManager

    raw, engine, storage, region = _make_stack(
        73, index_type=IndexType.IVF_FLAT)
    rng = np.random.default_rng(6)
    x = rng.standard_normal((3, 16)).astype(np.float32)   # < ncentroids
    storage.vector_add(region, np.arange(3, dtype=np.int64), x)
    tf = METRICS.counter("build.train_failures", region_id=73)
    t0 = tf.get()
    mgr = VectorIndexManager(raw)
    index = mgr.build_index(region)
    assert tf.get() == t0 + 1
    assert not index.is_trained()
    assert index.get_count() == 3   # rows held; reader-level exact
    # fallback serves them (untrained IVF search itself raises NotTrained)


def test_manager_build_trains_ivf_from_stream():
    """The streamed build trains IVF AFTER ingest from the device-held
    rows — no second host copy of the corpus — and assignments cover
    every row."""
    from dingo_tpu.index.manager import VectorIndexManager

    raw, engine, storage, region = _make_stack(
        74, index_type=IndexType.IVF_FLAT)
    rng = np.random.default_rng(7)
    x = rng.standard_normal((300, 16)).astype(np.float32)
    storage.vector_add(region, np.arange(300, dtype=np.int64), x)
    mgr = VectorIndexManager(raw)
    index = mgr.build_index(region)
    assert index.is_trained()
    res = index.search(x[:3], 1)
    assert [r.ids[0] for r in res] == [0, 1, 2]


# -- shared train-sample conf ----------------------------------------------

def test_train_sample_rows_conf_caps_device_sample():
    """conf train.sample_rows bounds every implicit train gather; 0 lifts
    both the conf cap and the caller's derived cap (full corpus)."""
    idx = new_index(75, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=8, ncentroids=4,
    ))
    rng = np.random.default_rng(8)
    n = 300
    idx.add(np.arange(n, dtype=np.int64),
            rng.standard_normal((n, 8)).astype(np.float32))
    FLAGS.set("train_sample_rows", 64)
    assert int(idx._train_rows_device(0).shape[0]) == 64
    # derived cap still binds when tighter than conf
    assert int(idx._train_rows_device(32).shape[0]) == 32
    FLAGS.set("train_sample_rows", 0)             # full corpus
    assert int(idx._train_rows_device(128).shape[0]) == n


def test_resolve_train_cap_semantics():
    from dingo_tpu.index.flat import _resolve_train_cap

    FLAGS.set("train_sample_rows", 1000)
    assert _resolve_train_cap(0) == 1000          # conf only
    assert _resolve_train_cap(500) == 500         # derived tighter
    assert _resolve_train_cap(5000) == 1000       # conf tighter
    FLAGS.set("train_sample_rows", 0)
    assert _resolve_train_cap(500) == 0           # 0 lifts BOTH caps
