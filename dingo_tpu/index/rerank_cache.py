"""Bounded device-resident row cache backing exact rerank of quantized
(bf16/SQ8) shortlists — the "compacted full-precision row cache" of the
precision tier (ISSUE 4 tentpole part 3).

Quantized tiers deliberately do NOT keep full-precision rows on device
(the whole point is the HBM saved), so an exact rerank needs a separate,
BOUNDED source of true rows. This cache reuses the SlotStore machinery
(donation-safe contiguous writes, cached norms, pow2 capacity) with the
OWNING store's slot numbers as keys:

  offer()       — write path hands over the rows it already has in hand
                  (no extra gather): rows for already-cached slots always
                  refresh (overwrite correctness), new slots fill until
                  max_rows.
  invalidate()  — deletes drop the row (a reused slot must never rerank
                  against a dead vector).
  device_map()  — [store_capacity] int32 slot->cache-row table, maintained
                  host-side and uploaded lazily exactly like SlotStore's
                  validity bitmap, so the rerank kernel
                  (ops/rerank.py cached_rerank_device) dispatches with
                  zero host synchronization or per-request H2D beyond one
                  int32 vector when the cache changed.

The cache shares the owning store's device_lock: its arrays are donated by
its own write programs, and the rerank kernel captures them at search
dispatch — one lock serializes both, the same contract SlotStore documents
for vecs/sqnorm.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dingo_tpu.index.slot_store import SlotStore, _next_pow2


class DeviceRerankCache:
    def __init__(self, dim: int, max_rows: int, dtype=jnp.float32,
                 device_lock: Optional[threading.RLock] = None):
        if max_rows <= 0:
            raise ValueError(f"max_rows {max_rows}")
        self.max_rows = int(max_rows)
        self.inner = SlotStore(dim, dtype, capacity=_next_pow2(max_rows))
        if device_lock is not None:
            self.inner.device_lock = device_lock
        self._dmap: Optional[jax.Array] = None
        self._map_capacity = 0

    def __len__(self) -> int:
        return len(self.inner)

    @property
    def vecs(self) -> jax.Array:
        return self.inner.vecs

    @property
    def sqnorm(self) -> jax.Array:
        return self.inner.sqnorm

    def offer(self, slots: np.ndarray, rows: np.ndarray) -> int:
        """Insert/refresh rows keyed by owning-store slots; returns how
        many landed. Already-cached slots ALWAYS update (an upsert that
        moved a vector must not leave the stale row serving reranks); new
        slots are admitted only while the cache has room."""
        slots = np.asarray(slots, np.int64)
        if not len(slots):
            return 0
        present = self.inner.slots_of(slots) >= 0
        take = present.copy()
        room = self.max_rows - len(self.inner)
        if room > 0:
            fresh = np.flatnonzero(~present)
            # admit at most `room` DISTINCT new slots (a slot may repeat
            # within one batch — every row of an admitted slot lands so
            # last-write-wins matches the store)
            uniq, first = np.unique(slots[fresh], return_index=True)
            admitted = uniq[np.argsort(first)][:room]
            take[fresh] = np.isin(slots[fresh], admitted)
        if not take.any():
            return 0
        self.inner.put(slots[take], np.asarray(rows)[take])
        self._dmap = None
        return int(take.sum())

    def invalidate(self, slots: np.ndarray) -> int:
        n = self.inner.remove(np.asarray(slots, np.int64))
        if n:
            self._dmap = None
        return n

    def device_map(self, store_capacity: int) -> jax.Array:
        """[store_capacity] int32: owning-store slot -> cache row, -1 when
        absent. Rebuilt host-side + uploaded only when the cache changed
        or the owning store grew."""
        if self._dmap is None or self._map_capacity != store_capacity:
            m = np.full((store_capacity,), -1, np.int32)
            cache_rows = np.flatnonzero(self.inner.ids_by_slot >= 0)
            if len(cache_rows):
                store_slots = self.inner.ids_by_slot[cache_rows]
                # drop entries pointing past a (shrunk/reloaded) store
                ok = store_slots < store_capacity
                m[store_slots[ok]] = cache_rows[ok].astype(np.int32)
            self._dmap = jnp.asarray(m)
            self._map_capacity = store_capacity
        return self._dmap

    def memory_size(self) -> int:
        return self.inner.memory_size()
