"""Metrics: counters, gauges, latency recorders with percentile windows.

Reference: bvar everywhere — multi-dimension per-region metrics
(store_bvar_metrics.h:86-89), task counters (vector_index_manager.h:177-199),
ad-hoc bvar::LatencyRecorder at each layer (vector_reader.cc:64-65,
raft_store_engine.cc:418,450), exposed via brpc /vars and the metrics
services. Here: a process-global registry the server layer dumps as JSON
(/vars analog) or Prometheus text exposition format (plain-HTTP /metrics).

Naming contract: metric names are lowercase dotted identifiers
(`store.region.key_count`); dimensions ride as labels (`region=`, plus
free-form key=value pairs). Prometheus rendering mangles dots to
underscores — tools/check_metrics_names.py lints registration sites so
the mangled names stay valid and no series is silently dropped.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

#: registration-time contract for metric names (see module docstring);
#: tools/check_metrics_names.py enforces it over literal call sites
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_.]*$")


def valid_metric_name(name: str) -> bool:
    return METRIC_NAME_RE.match(name) is not None


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def get(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, delta: float) -> float:
        """Atomic up/down delta. Concurrent accounting sites (live device
        bytes, in-flight builds) must not race a read-modify-write through
        get()+set() — two racing set()s would drop one side's delta."""
        with self._lock:
            self._value += delta
            return self._value

    def get(self) -> float:
        return self._value


#: windowed-QPS horizon: per-second hit buckets retained this many seconds
QPS_WINDOW_S = 16

#: exemplar retention: an outlier exemplar not beaten by a larger sample
#: is replaced by the next traced sample after this long, so the scrape
#: follows CURRENT outliers instead of the all-time worst
EXEMPLAR_WINDOW_S = 60.0


class LatencyRecorder:
    """bvar::LatencyRecorder analog: ring of recent samples with
    windowed qps estimation and percentile queries.

    `count` is the lifetime total; `qps` is measured over the last
    QPS_WINDOW_S seconds only (per-second hit buckets) — lifetime
    count / process uptime would decay toward zero on long-lived
    processes and never reflect current load."""

    def __init__(self, window: int = 4096):
        self._window = window
        self._samples: List[float] = []
        self._pos = 0
        self._count = 0
        self._sum_us = 0.0
        self._t0 = time.monotonic()
        # per-second hit buckets: slot i holds the count for absolute
        # second _sec_id[i]; stale slots (a different second hashed here
        # more than QPS_WINDOW_S ago) are excluded at read time
        self._sec_hits = [0] * QPS_WINDOW_S
        self._sec_id = [-1] * QPS_WINDOW_S
        # exemplar: (value_us, trace_id, unix_ts) of a recent outlier
        # sample that carried a trace id — the Prometheus exposition
        # attaches it to the p99 series (OpenMetrics exemplar syntax) so a
        # scrape links a bad bucket straight to its trace/flight bundle
        self._ex_us = 0.0
        self._ex_trace = ""
        self._ex_ts = 0.0
        # a pinned exemplar (sample the flight recorder bundled) is sticky:
        # merely-larger unbundled samples can't displace it inside the
        # window, so the scrape keeps linking to a bundle that exists
        self._ex_pinned = False
        self._lock = threading.Lock()

    def observe_us(self, us: float, trace_id: str = "") -> None:
        with self._lock:
            if len(self._samples) < self._window:
                self._samples.append(us)
            else:
                self._samples[self._pos] = us
                self._pos = (self._pos + 1) % self._window
            self._count += 1
            self._sum_us += us
            now_s = int(time.monotonic())
            i = now_s % QPS_WINDOW_S
            if self._sec_id[i] != now_s:
                self._sec_id[i] = now_s
                self._sec_hits[i] = 0
            self._sec_hits[i] += 1
            if trace_id:
                now = time.time()
                expired = now - self._ex_ts > EXEMPLAR_WINDOW_S
                if expired or (not self._ex_pinned and us >= self._ex_us):
                    self._ex_us = us
                    self._ex_trace = trace_id
                    self._ex_ts = now
                    self._ex_pinned = False

    def pin_exemplar(self, us: float, trace_id: str) -> None:
        """Force this sample to be the exemplar regardless of magnitude.
        The slow-query path pins the sample it just flight-recorded so the
        scrape's exemplar always links to a CAPTURED bundle's trace, not
        merely the window's largest sample (e.g. a warmup compile)."""
        if not trace_id:
            return
        with self._lock:
            self._ex_us = us
            self._ex_trace = trace_id
            self._ex_ts = time.time()
            self._ex_pinned = True

    def exemplar(self):
        """(value_us, trace_id, unix_ts) of the retained outlier, or None
        when no traced sample has been observed."""
        with self._lock:
            if not self._ex_trace:
                return None
            return (self._ex_us, self._ex_trace, self._ex_ts)

    class _Timer:
        __slots__ = ("rec", "t0")

        def __init__(self, rec):
            self.rec = rec

        def __enter__(self):
            self.t0 = time.perf_counter_ns()
            return self

        def __exit__(self, *exc):
            self.rec.observe_us((time.perf_counter_ns() - self.t0) / 1000.0)
            return False

    def time(self) -> "_Timer":
        return self._Timer(self)

    @staticmethod
    def _pick(ordered: List[float], p: float) -> float:
        """Percentile over a pre-sorted window; 0.0 on an empty window
        (metrics endpoints poll before the first sample — never raise)."""
        if not ordered:
            return 0.0
        i = min(len(ordered) - 1, int(p / 100.0 * len(ordered)))
        return ordered[i]

    def percentile(self, p: float) -> float:
        with self._lock:
            return self._pick(sorted(self._samples), p)

    def windowed_qps(self, now: Optional[float] = None) -> float:
        """Rate over the recent QPS_WINDOW_S-second window (young
        recorders divide by their actual age so early reads aren't
        deflated by the not-yet-elapsed window)."""
        if now is None:
            now = time.monotonic()
        now_s = int(now)
        with self._lock:
            recent = sum(
                hits for sid, hits in zip(self._sec_id, self._sec_hits)
                if sid >= 0 and now_s - sid < QPS_WINDOW_S
            )
            age = now - self._t0
        return recent / max(min(age, float(QPS_WINDOW_S)), 1e-9)

    def stats(self) -> Dict[str, float]:
        # one snapshot + one sort for every derived figure (p50 and p99
        # used to re-sort the window under separate lock acquisitions)
        now = time.monotonic()
        now_s = int(now)
        with self._lock:
            ordered = sorted(self._samples)
            count = self._count
            total_us = self._sum_us
            recent = sum(
                hits for sid, hits in zip(self._sec_id, self._sec_hits)
                if sid >= 0 and now_s - sid < QPS_WINDOW_S
            )
            age = now - self._t0
        n = len(ordered)
        return {
            "count": count,
            "sum_us": total_us,
            "qps": recent / max(min(age, float(QPS_WINDOW_S)), 1e-9),
            "avg_us": sum(ordered) / n if n else 0.0,
            "p50_us": self._pick(ordered, 50),
            "p99_us": self._pick(ordered, 99),
        }


def _series_key(name: str, region_id: Optional[int],
                labels: Optional[Dict[str, str]]) -> str:
    """`name{k=v,...}` series key. region_id stays the first label (and the
    only one for legacy call sites, so existing dump keys are unchanged);
    free-form labels follow sorted (StoreBvarMetrics multi-dimension
    pattern generalized)."""
    parts: List[Tuple[str, str]] = []
    if region_id:
        parts.append(("region", str(region_id)))
    if labels:
        parts.extend(
            (k, str(v)) for k, v in sorted(labels.items()) if k != "region"
        )
    if not parts:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in parts) + "}"


def split_series_key(key: str) -> Tuple[str, List[Tuple[str, str]]]:
    """Inverse of _series_key: `name{k=v,...}` -> (name, [(k, v), ...])."""
    if not key.endswith("}") or "{" not in key:
        return key, []
    name, _, rest = key.partition("{")
    pairs = []
    for item in rest[:-1].split(","):
        k, _, v = item.partition("=")
        pairs.append((k, v))
    return name, pairs


def mangle_prometheus_name(name: str) -> str:
    """Metric-name mangling for the exposition format: Prometheus names
    are [a-zA-Z_:][a-zA-Z0-9_:]*, so dots (and any other byte outside
    that set) become underscores."""
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_str(pairs: Iterable[Tuple[str, str]]) -> str:
    rendered = []
    for k, v in pairs:
        k = re.sub(r"[^a-zA-Z0-9_]", "_", k)
        if k and k[0].isdigit():
            k = "_" + k
        v = str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        rendered.append(f'{k}="{v}"')
    if not rendered:
        return ""
    return "{" + ",".join(rendered) + "}"


class MetricsRegistry:
    """Named metrics with a region dimension plus free-form labels
    (StoreBvarMetrics multi-dimension pattern)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._latencies: Dict[str, LatencyRecorder] = {}

    def counter(self, name: str, region_id: Optional[int] = None,
                labels: Optional[Dict[str, str]] = None) -> Counter:
        key = _series_key(name, region_id, labels)
        with self._lock:
            return self._counters.setdefault(key, Counter())

    def gauge(self, name: str, region_id: Optional[int] = None,
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        key = _series_key(name, region_id, labels)
        with self._lock:
            return self._gauges.setdefault(key, Gauge())

    def latency(self, name: str, region_id: Optional[int] = None,
                labels: Optional[Dict[str, str]] = None) -> LatencyRecorder:
        key = _series_key(name, region_id, labels)
        with self._lock:
            return self._latencies.setdefault(key, LatencyRecorder())

    def drop_region(self, region_id: int) -> int:
        """Forget every series labeled region=<id> (a deleted region's
        gauges must not report its last values forever)."""
        tag = f"region={region_id}"
        n = 0
        with self._lock:
            for d in (self._counters, self._gauges, self._latencies):
                dead = [
                    k for k in d
                    if any(f"{p[0]}={p[1]}" == tag
                           for p in split_series_key(k)[1])
                ]
                for k in dead:
                    del d[k]
                n += len(dead)
        return n

    def dump(self) -> Dict[str, object]:
        """/vars-style dump."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            lats = list(self._latencies.items())
        out: Dict[str, object] = {}
        for k, c in counters:
            out[k] = c.get()
        for k, g in gauges:
            out[k] = g.get()
        for k, lr in lats:
            out[k] = lr.stats()
        return out

    def render_prometheus(self, exemplars: Optional[bool] = None) -> str:
        """Prometheus text exposition format (v0.0.4): counters and gauges
        as-is, latency windows as summaries (quantile labels + lifetime
        _sum/_count). Dotted names mangle to underscores; series sharing a
        base name group under one # TYPE header.

        `exemplars` controls the OpenMetrics trace-id exemplar suffix on
        p99 series: None follows the obs.exemplars flag (the in-band
        DebugService dump and tools default), False strips them — the
        CLASSIC text format cannot carry exemplars, so the HTTP sidecar
        passes False unless the scraper negotiated OpenMetrics."""
        with self._lock:
            counters = list(self._counters.items())
            gauges = list(self._gauges.items())
            lats = list(self._latencies.items())

        lines: List[str] = []
        by_name: Dict[str, List[str]] = {}

        def emit(kind: str, key: str, render_fn) -> None:
            name, pairs = split_series_key(key)
            pname = mangle_prometheus_name(name)
            block = by_name.get(pname)
            if block is None:
                block = by_name[pname] = [f"# TYPE {pname} {kind}"]
            render_fn(pname, pairs, block)

        for key, c in counters:
            v = c.get()
            emit("counter", key,
                 lambda pn, pairs, b, v=v:
                 b.append(f"{pn}{_prom_label_str(pairs)} {v}"))
        for key, g in gauges:
            v = g.get()
            emit("gauge", key,
                 lambda pn, pairs, b, v=v:
                 b.append(f"{pn}{_prom_label_str(pairs)} {_fmt(v)}"))
        exemplars_on = _exemplars_enabled() if exemplars is None \
            else (exemplars and _exemplars_enabled())
        for key, lr in lats:
            st = lr.stats()
            ex = lr.exemplar() if exemplars_on else None

            def render(pn, pairs, b, st=st, ex=ex):
                for q, field in (("0.5", "p50_us"), ("0.99", "p99_us")):
                    line = (
                        f"{pn}{_prom_label_str(list(pairs) + [('quantile', q)])}"
                        f" {_fmt(st[field])}"
                    )
                    if q == "0.99" and ex is not None:
                        # OpenMetrics exemplar: trace id of a recent
                        # outlier sample rides the p99 series
                        val, trace_id, ts = ex
                        line += (
                            f' # {{trace_id="{trace_id}"}} '
                            f"{_fmt(round(val, 3))} {_fmt(round(ts, 3))}"
                        )
                    b.append(line)
                ls = _prom_label_str(pairs)
                b.append(f"{pn}_sum{ls} {_fmt(st['sum_us'])}")
                b.append(f"{pn}_count{ls} {int(st['count'])}")

            emit("summary", key, render)
            # windowed rate rides as a sibling gauge — a summary type may
            # only carry quantile/_sum/_count series, strict parsers reject
            # extra suffixes inside the block
            name, pairs = split_series_key(key)
            emit("gauge", f"{name}_qps",
                 lambda pn, _ignored, b, pairs=pairs, q=st["qps"]:
                 b.append(f"{pn}{_prom_label_str(pairs)} {_fmt(q)}"))

        for pname in sorted(by_name):
            lines.extend(by_name[pname])
        return "\n".join(lines) + "\n"


def _exemplars_enabled() -> bool:
    """obs.exemplars flag (lazy import: config must stay import-light and
    cycle-free from this module)."""
    try:
        from dingo_tpu.common.config import FLAGS

        return bool(FLAGS.get("obs_exemplars"))
    except Exception:  # noqa: BLE001 — registry usable standalone
        return False


def _fmt(v: float) -> str:
    """Render floats without exponent surprises; integers stay integral."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v)) if isinstance(v, float) else str(v)


METRICS = MetricsRegistry()
