"""Pallas IVF list-DMA kernel: parity vs the XLA scan path (interpret mode
on CPU; same program compiles for TPU via Mosaic)."""

import numpy as np
import jax.numpy as jnp
import pytest

from dingo_tpu.common.config import FLAGS
from dingo_tpu.index.base import IndexParameter, IndexType
from dingo_tpu.index.ivf_flat import TpuIvfFlat
from dingo_tpu.ops.distance import Metric


@pytest.fixture(scope="module")
def trained_index():
    rng = np.random.default_rng(3)
    n, d, nlist = 6000, 32, 16
    centers = rng.standard_normal((nlist, d)).astype(np.float32)
    x = centers[rng.integers(0, nlist, n)] + 0.2 * rng.standard_normal(
        (n, d)
    ).astype(np.float32)
    ids = np.arange(n, dtype=np.int64)
    idx = TpuIvfFlat(1, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=d, ncentroids=nlist,
    ))
    idx.upsert(ids, x)
    idx.train()
    q = x[rng.choice(n, 8, replace=False)] + 0.01
    return idx, x, q


def _results(idx, q, **kw):
    return [(list(r.ids), np.asarray(r.distances)) for r in idx.search(q, 10, **kw)]


def _assert_parity(base, fused):
    for (bi, bd), (fi, fd) in zip(base, fused):
        assert bi == fi
        np.testing.assert_allclose(bd, fd, rtol=1e-4, atol=1e-4)


def test_pallas_ivf_parity_with_xla_path(trained_index):
    idx, x, q = trained_index
    base = _results(idx, q, nprobe=8)
    FLAGS.set("use_pallas_ivf_search", True)
    try:
        fused = _results(idx, q, nprobe=8)
    finally:
        FLAGS.set("use_pallas_ivf_search", False)
    _assert_parity(base, fused)


def test_pallas_ivf_filter_and_full_probe(trained_index):
    idx, x, q = trained_index
    from dingo_tpu.index.base import FilterSpec

    spec = FilterSpec(ranges=[(100, 3000)])
    base = _results(idx, q, nprobe=idx.nlist, filter_spec=spec)
    FLAGS.set("use_pallas_ivf_search", True)
    try:
        fused = _results(idx, q, nprobe=idx.nlist, filter_spec=spec)
    finally:
        FLAGS.set("use_pallas_ivf_search", False)
    _assert_parity(base, fused)
    for ids, _ in fused:
        assert all(100 <= i < 3000 for i in ids)


def test_pallas_paths_accept_bf16_stores():
    """bench stores vectors in bf16; the Pallas kernels promote in VMEM so
    the flag-gated paths must route (and agree with XLA) for bf16 too."""
    import jax.numpy as jnp

    from dingo_tpu.index.flat import TpuFlat

    rng = np.random.default_rng(9)
    x = rng.standard_normal((3000, 32)).astype(np.float32)
    ids = np.arange(3000, dtype=np.int64)
    flat = TpuFlat(5, IndexParameter(index_type=IndexType.FLAT, dimension=32,
                                     dtype="bfloat16"))
    flat.upsert(ids, x)
    assert flat.store.vecs.dtype == jnp.bfloat16
    want = [list(r.ids) for r in flat.search(x[:4], 5)]
    FLAGS.set("use_pallas_fused_search", True)
    try:
        got = [list(r.ids) for r in flat.search(x[:4], 5)]
    finally:
        FLAGS.set("use_pallas_fused_search", "auto")
    assert want == got

    ivf = TpuIvfFlat(6, IndexParameter(
        index_type=IndexType.IVF_FLAT, dimension=32, ncentroids=8,
        dtype="bfloat16",
    ))
    ivf.upsert(ids, x)
    ivf.train()
    base = [list(r.ids) for r in ivf.search(x[:4], 5, nprobe=8)]
    FLAGS.set("use_pallas_ivf_search", True)
    try:
        fused = [list(r.ids) for r in ivf.search(x[:4], 5, nprobe=8)]
    finally:
        FLAGS.set("use_pallas_ivf_search", False)
    assert base == fused
