"""Quick-ADC-style fused PQ scan: LUTs resident in VMEM, codes streamed
per probed bucket.

Quick ADC (PAPERS.md) keeps the PQ distance tables in SIMD registers and
scans codes through them without ever leaving the register file. The TPU
analog: the per-(query, probed-list) residual LUT [m, ksub] stays resident
in VMEM for the whole bucket scan while the Pallas pipeline DMAs exactly
one probed code bucket [cap, m] per grid step (scalar-prefetched probe
ids, same scheme as ops/pallas_ivf.py), and the ADC sum + running top-k
merge happen in VMEM. The XLA path (`ivf_pq._ivfpq_scan_kernel`) instead
gathers a [b, cap, m] code bucket per rank into HBM and reads it back for
a take_along_axis — 3x the necessary HBM traffic, plus the gather itself
lowers badly on TPU.

The in-kernel table lookup is a one-hot contraction (the MXU-native
formulation ops/pq.py:adc_scan uses at the XLA layer), chunked over
subspace groups so the one-hot tile stays a few MB of VMEM:

    dist[c] = sum_g  onehot(codes[c, g*MG:(g+1)*MG]) . lut[g*MG:(g+1)*MG]

Output feeds the existing device-resident exact rerank (ops/rerank.py) —
the ADC scan is the prune, the rerank absorbs the quantization noise.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dingo_tpu.obs.sentinel import sentinel_jit
from dingo_tpu.ops.pallas_ivf import OUT_PAD, ROW_BLOCK
from dingo_tpu.ops.pallas_topk import NEG_INF, _select_topk

#: subspaces per one-hot group: bounds the [cap, MG * ksub] one-hot tile
#: (cap=2048, ksub=256 -> 16 MB f32 at MG=8; small caps use less)
MAX_GROUP = 8


def _adc_kernel(vp_ref, cp_ref, lut_ref, code_ref, val_ref, slot_ref,
                outv_ref, outi_ref, *, k, m, ksub):
    qi = pl.program_id(0)
    r = pl.program_id(1)
    row = pl.ds(jax.lax.rem(qi, ROW_BLOCK), 1)

    @pl.when(r == 0)
    def _init():
        outv_ref[row, :] = jnp.full(
            (1, outv_ref.shape[1]), NEG_INF, jnp.float32
        )
        outi_ref[row, :] = jnp.full((1, outi_ref.shape[1]), -1, jnp.int32)

    @pl.when(vp_ref[qi, r] >= 0)
    def _scan_bucket():
        lut = lut_ref[0, 0]                              # [m, ksub]
        codes = code_ref[0].astype(jnp.int32)            # [cap, m]
        cap = codes.shape[0]
        dist = jnp.zeros((1, cap), jnp.float32)
        kiota = jax.lax.broadcasted_iota(jnp.int32, (1, ksub), 1)
        # static unrolled group loop: one-hot contraction per MG subspaces
        for g in range(0, m, MAX_GROUP):
            w = min(MAX_GROUP, m - g)
            cg = codes[:, g:g + w]                       # [cap, w]
            oh = (cg[:, :, None] == kiota[None, :, :]).astype(jnp.float32)
            ohf = oh.reshape(cap, w * ksub)
            lutg = lut[g:g + w, :].reshape(1, w * ksub)
            dist += jax.lax.dot_general(
                lutg, ohf, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )                                            # [1, cap]
        scores = jnp.where(val_ref[0] > 0.5, -dist, NEG_INF)
        slot = slot_ref[0].astype(jnp.int32)
        blk_v, blk_i = _select_topk(scores, slot, k)
        cur_v = outv_ref[row, :]
        cur_i = outi_ref[row, :]
        cat_v = jnp.concatenate([cur_v[:, :k], blk_v], axis=1)
        cat_i = jnp.concatenate([cur_i[:, :k], blk_i], axis=1)
        new_v, new_i = _select_topk(cat_v, cat_i, k)
        pad = outv_ref.shape[1] - k
        outv_ref[row, :] = jnp.concatenate(
            [new_v, jnp.full((1, pad), NEG_INF, jnp.float32)], axis=1
        )
        outi_ref[row, :] = jnp.concatenate(
            [new_i, jnp.full((1, pad), -1, jnp.int32)], axis=1
        )

    @pl.when(r == pl.num_programs(1) - 1)
    def _finish():
        fv = outv_ref[row, :]
        outi_ref[row, :] = jnp.where(jnp.isneginf(fv), -1, outi_ref[row, :])


@sentinel_jit("ops.pallas.pq_adc_topk",
              static_argnames=("k", "interpret", "nq"))
def ivf_pq_adc_topk(
    vprobes: jax.Array,      # [b, budget] int32 virtual bucket ids (-1 pad)
    coarse_pos: jax.Array,   # [b, budget] int32 coarse rank of each probe
    lut_all: jax.Array,      # [b, nprobe, m, ksub] f32 residual ADC tables
    code_buckets: jax.Array,  # [B, cap, m] uint8
    bucket_valid: jax.Array,  # [B, cap] bool/float
    bucket_slot: jax.Array,   # [B, cap] int32
    k: int,
    interpret: bool = False,
    nq: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Fused ADC probed-bucket scan -> (scores[b, k], slots[b, k]).

    Scores are negated ADC distances ('larger is better'); a hot list's
    spill buckets share the coarse rank's LUT via coarse_pos, so the LUT
    block index_map re-reads the SAME VMEM-resident table instead of
    recomputing it per bucket (the Quick ADC property)."""
    b = vprobes.shape[0]
    budget = vprobes.shape[1]
    nb, cap, m = code_buckets.shape
    ksub = lut_all.shape[3]
    nq = nq or b

    def bucket_map(q, r, vp, cp):
        return (jnp.maximum(vp[q, r], 0), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nq, budget),
        in_specs=[
            pl.BlockSpec(
                (1, 1, m, ksub),
                lambda q, r, vp, cp: (q, cp[q, r], 0, 0),
            ),                                            # resident LUT
            pl.BlockSpec((1, cap, m), bucket_map),        # code bucket
            pl.BlockSpec((1, 1, cap), bucket_map),        # valid
            pl.BlockSpec((1, 1, cap), bucket_map),        # slots
        ],
        out_specs=[
            pl.BlockSpec(
                (ROW_BLOCK, OUT_PAD),
                lambda q, r, vp, cp: (q // ROW_BLOCK, 0),
            ),
        ] * 2,
    )
    out_v, out_i = pl.pallas_call(
        functools.partial(_adc_kernel, k=k, m=m, ksub=ksub),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.float32),
            jax.ShapeDtypeStruct((b, OUT_PAD), jnp.int32),
        ],
        interpret=interpret,
    )(
        vprobes,
        coarse_pos,
        lut_all,
        code_buckets,
        bucket_valid.astype(jnp.float32)[:, None, :],
        bucket_slot[:, None, :],
    )
    return out_v[:, :k], out_i[:, :k]


def ivf_pq_adc_search(
    vprobes, coarse_pos, lut_all, code_buckets, bucket_valid, bucket_slot,
    k: int,
):
    """Backend-aware wrapper: ROW_BLOCK-pads the per-query arrays, clamps
    the grid to the real batch, picks interpret mode off-TPU."""
    b = vprobes.shape[0]
    pad = (-b) % ROW_BLOCK
    if pad:
        vprobes = jnp.concatenate(
            [vprobes, jnp.full((pad, vprobes.shape[1]), -1, vprobes.dtype)]
        )
        coarse_pos = jnp.concatenate(
            [coarse_pos,
             jnp.zeros((pad, coarse_pos.shape[1]), coarse_pos.dtype)]
        )
        lut_all = jnp.concatenate(
            [lut_all, jnp.zeros((pad,) + lut_all.shape[1:], lut_all.dtype)]
        )
    interpret = jax.default_backend() not in ("tpu", "axon")
    vals, slots = ivf_pq_adc_topk(
        vprobes, coarse_pos, lut_all, code_buckets, bucket_valid,
        bucket_slot, k=k, interpret=interpret, nq=b,
    )
    from dingo_tpu.ops.distance import device_wait_span

    vals, slots = device_wait_span("pallas_pq_adc", (vals, slots))
    return vals[:b], slots[:b]
