"""Trace exporters: DebugService JSON and Chrome ``trace_event`` files.

The Chrome format (one ``X`` complete event per span, microsecond
timestamps) loads directly in chrome://tracing and Perfetto; pid groups a
process, tid lanes match the OS thread each span ran on, so the
coalescer's queue-wait (caller thread) and batch-run (timer thread) land
on different lanes of the same trace — exactly the handoff picture the
profiling workflow needs. tools/trace_report.py consumes the same file.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from dingo_tpu.trace.buffer import TRACE_BUFFER


def to_json(records: Optional[List[Dict]] = None,
            slow: Optional[List[Dict]] = None) -> Dict:
    """The DebugService TraceDump payload: spans grouped by trace id
    (oldest-first within a trace) plus the slow-query log and buffer
    health counters."""
    if records is None:
        records = TRACE_BUFFER.snapshot()
    if slow is None:
        slow = TRACE_BUFFER.slow_queries()
    traces: Dict[str, List[Dict]] = {}
    for rec in records:
        traces.setdefault(rec["trace_id"], []).append(rec)
    return {
        "traces": traces,
        "slow_queries": slow,
        "stats": TRACE_BUFFER.stats(),
    }


def to_chrome_trace(records: Optional[List[Dict]] = None) -> Dict:
    """Chrome trace_event JSON object (the documented object form with a
    ``traceEvents`` array, which Perfetto also accepts)."""
    if records is None:
        records = TRACE_BUFFER.snapshot()
    pid = os.getpid()
    events = []
    for rec in records:
        args = {
            "trace_id": rec["trace_id"],
            "span_id": rec["span_id"],
            "parent_id": rec["parent_id"],
            "status": rec["status"],
        }
        args.update(rec["attrs"])
        events.append({
            "name": rec["name"],
            "cat": "dingo",
            "ph": "X",
            "ts": rec["start_us"],
            "dur": max(rec["dur_us"], 1),   # 0-width events vanish in the UI
            "pid": pid,
            "tid": rec["thread"],
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_chrome_trace(path: str,
                      records: Optional[List[Dict]] = None) -> str:
    """Write the Chrome trace file; returns the path for convenience."""
    payload = to_chrome_trace(records)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1)
    return path
